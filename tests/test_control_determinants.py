"""Control-plane determinants on the live path: timer service wiring,
SOURCE_CHECKPOINT / IGNORE_CHECKPOINT emission, and config-driven runner
construction (reference StreamTask.performCheckpoint:833-840 /
ignoreCheckpoint:891-915 / SystemProcessingTimeService.java:50)."""

import numpy as np
import jax
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.causal import determinant as det
from clonos_tpu.causal import log as clog
from clonos_tpu.config import defaults as D
from clonos_tpu.config.options import Configuration
from clonos_tpu.runtime.cluster import ClusterRunner

VOCAB, BATCH, NKEYS = 23, 8, 23


def _job(parallelism=2):
    env = StreamEnvironment(name="wc", num_key_groups=16)
    (env.synthetic_source(vocab=VOCAB, batch_size=BATCH,
                          parallelism=parallelism)
        .key_by()
        .window_count(num_keys=NKEYS, window_size=50)
        .sink())
    return env.build()


def _runner(times, **kw):
    r = ClusterRunner(_job(), steps_per_epoch=3, seed=3, **kw)
    r.executor.time_source.now = lambda it=iter(times): next(it)
    return r


TIMES = list(range(0, 400, 20))


def _log_tags(runner, flat):
    one = jax.tree_util.tree_map(lambda x: x[flat],
                                 runner.executor.carry.logs)
    rows = np.asarray(one.rows)
    cap = rows.shape[0]
    tail, head = int(one.tail), int(one.head)
    pos = [(tail + i) & (cap - 1) for i in range(head - tail)]
    return rows[pos, det.LANE_TAG].tolist()


def test_source_checkpoint_determinant_logged_per_trigger():
    r = _runner(TIMES)
    r.run_epoch()
    r.run_epoch(complete_checkpoint=False)
    # Source subtasks (flats 0,1) log one SOURCE_CHECKPOINT per trigger.
    for flat in (0, 1):
        tags = _log_tags(r, flat)
        assert tags.count(det.SOURCE_CHECKPOINT) == 2
    # Non-source subtasks don't.
    assert _log_tags(r, 2).count(det.SOURCE_CHECKPOINT) == 0


def test_ignore_checkpoint_logged_on_recovery():
    r = _runner(TIMES)
    r.run_epoch()
    r.run_epoch(complete_checkpoint=False)   # pending, will be ignored
    r.inject_failure([3])
    report = r.recover()
    assert report.ignored_checkpoints == (1,)
    # Every healthy subtask logged the ignore decision.
    for flat in (0, 1, 2):
        assert _log_tags(r, flat).count(det.IGNORE_CHECKPOINT) == 1
    # The failed subtask (restored from replicas) did not.
    assert _log_tags(r, 3).count(det.IGNORE_CHECKPOINT) == 0


def test_timer_service_fires_and_replays_after_failure():
    fired_a, fired_b = [], []

    def build(sink_list):
        r = _runner(TIMES)
        svc = r.timer_service(3)             # window subtask 1
        cid = svc.register_callback(sink_list.append, callback_id=7)
        svc.register_timer(25, cid)          # fires in epoch 0 (t<=40)
        svc.register_timer(65, cid)          # fires in epoch 1 (lost range)
        return r

    a = build(fired_a)                       # golden
    b = build(fired_b)
    for r in (a, b):
        r.run_epoch()                        # epoch 0 completes (t=0,20,40)
        r.step()                             # t=60
        r.step()                             # t=80 -> timer 65 fires
    assert fired_a == fired_b == [25, 65]
    # Timer 25's row was truncated with checkpoint 0; 65's is live.
    assert _log_tags(b, 3).count(det.TIMER_TRIGGER) == 1

    b.inject_failure([3])
    b.recover()
    # Replay re-fired the lost-range timer effect (25 is checkpointed —
    # completed effects must NOT re-run) without duplicating rows.
    assert fired_b == [25, 65, 65]
    assert _log_tags(b, 3).count(det.TIMER_TRIGGER) == 1

    # And the carries stay bit-identical to the golden run.
    from clonos_tpu.runtime.executor import canonical_carry
    for xa, xb in zip(
            jax.tree_util.tree_leaves(canonical_carry(a.executor.carry)),
            jax.tree_util.tree_leaves(canonical_carry(b.executor.carry))):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def test_from_config_builds_runner():
    cfg = (Configuration()
           .set(D.CHECKPOINT_INTERVAL_STEPS, 4)
           .set(D.DETERMINANT_LOG_CAPACITY, 512)
           .set(D.DETERMINANT_MAX_EPOCHS, 8)
           .set(D.INFLIGHT_CAPACITY_BATCHES, 16)
           .set(D.NUM_STANDBY_TASKS, 2)
           .set(D.DETERMINANT_SHARING_DEPTH, 2)
           .set(D.HEARTBEAT_TIMEOUT_MS, 250))
    job = _job()
    r = ClusterRunner.from_config(job, cfg)
    assert r.executor.steps_per_epoch == 4
    assert r.executor.compiled.log_capacity == 512
    assert r.executor.compiled.max_epochs == 8
    assert r.executor.compiled.inflight_ring_steps == 16
    assert r.standbys.num_standby_per_vertex == 2
    assert r.heartbeats.timeout_s == 0.25
    assert job.sharing_depth == 2
    r.run_epoch()                            # functional end to end


def test_from_config_full_restart_strategy_disables_standby():
    cfg = Configuration().set(D.FAILOVER_STRATEGY, "full")
    r = ClusterRunner.from_config(_job(), cfg)
    assert r.standbys.num_standby_per_vertex == 0
    with pytest.raises(Exception):
        r.prewarm_recovery()
