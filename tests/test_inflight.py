"""In-flight log: device ring, epoch truncation, spill files, replay
iterator (reference inflightlogging package behaviors)."""

import os

import numpy as np
import jax
import jax.numpy as jnp

from clonos_tpu.api import records
from clonos_tpu.inflight import log as ifl


P, CAP = 2, 4


def _batch(step: int) -> records.RecordBatch:
    k = np.full((P, CAP), step, np.int32)
    v = np.arange(P * CAP, dtype=np.int32).reshape(P, CAP) + 100 * step
    valid = np.ones((P, CAP), bool)
    return records.RecordBatch(jnp.asarray(k), jnp.asarray(v),
                               jnp.zeros((P, CAP), jnp.int32),
                               jnp.asarray(valid))


def test_ring_append_slice_truncate():
    st = ifl.create(ring_steps=8, parallelism=P, capacity=CAP, max_epochs=8)
    st = ifl.start_epoch(st, 0)
    for i in range(3):
        st = ifl.append_step(st, _batch(i))
    st = ifl.start_epoch(st, 1)
    for i in range(3, 5):
        st = ifl.append_step(st, _batch(i))
    assert int(ifl.size(st)) == 5
    # Slice epoch 1's steps.
    batch, count, start = ifl.slice_steps(st, ifl.epoch_start_step(st, 1), 4)
    assert int(count) == 2 and int(start) == 3
    np.testing.assert_array_equal(np.asarray(batch.keys[0]),
                                  np.asarray(_batch(3).keys))
    # Padding slots are zeroed.
    assert int(jnp.sum(batch.valid[2:])) == 0
    # Truncate epoch 0.
    st = ifl.truncate(st, 0)
    assert int(ifl.size(st)) == 2 and int(st.tail) == 3
    assert not bool(ifl.overflowed(st))


def test_ring_wraparound_preserves_live_steps():
    st = ifl.create(ring_steps=4, parallelism=P, capacity=CAP, max_epochs=8)
    st = ifl.start_epoch(st, 0)
    for i in range(2):
        st = ifl.append_step(st, _batch(i))
    st = ifl.truncate(st, -1)  # no-op
    st = ifl.start_epoch(st, 1)
    st = ifl.truncate(st, 0)   # frees steps 0-1
    for i in range(2, 6):      # wraps the ring
        st = ifl.append_step(st, _batch(i))
    assert not bool(ifl.overflowed(st))
    batch, count, start = ifl.slice_steps(st, st.tail, 8)
    assert int(count) == 4
    np.testing.assert_array_equal(
        np.asarray(batch.keys[:, 0, 0]), [2, 3, 4, 5, 0, 0, 0, 0])


def test_spill_roundtrip_and_file_truncation(tmp_path):
    log = ifl.SpillingInFlightLog(str(tmp_path), edge_id=0)
    steps0 = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[_batch(i) for i in range(3)])
    steps1 = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[_batch(i) for i in range(3, 5)])
    log.spill_epoch(0, 0, steps0)
    log.spill_epoch(1, 3, steps1)
    log.drain()
    assert os.path.exists(log._path(0)) and os.path.exists(log._path(1))
    start, got = log.load_epoch(0)
    assert start == 0
    np.testing.assert_array_equal(np.asarray(got.keys),
                                  np.asarray(steps0.keys))
    log.truncate(0)
    assert log.retained_epochs() == [1]
    assert not os.path.exists(log._path(0))
    log.close()


def test_replay_iterator_order_and_skip(tmp_path):
    log = ifl.SpillingInFlightLog(str(tmp_path), edge_id=1)
    log.spill_epoch(0, 0, jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[_batch(i) for i in range(3)]))
    log.spill_epoch(1, 3, jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[_batch(i) for i in range(3, 5)]))
    log.drain()
    got = [(s, int(np.asarray(b.keys)[0, 0]))
           for s, b in ifl.ReplayIterator(log, 0, 1, skip_steps=1)]
    assert got == [(1, 1), (2, 2), (3, 3), (4, 4)]
    log.close()


def test_availability_policy_spills_before_wrap(tmp_path):
    """The AVAILABILITY-policy hole (round-2/3 advice): a skipped
    low-occupancy epoch must be retroactively spilled before a later ring
    wrap clobbers its only copy — recovery across the wrapped gap must
    still reconstruct every lost step."""
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import canonical_carry

    def build():
        env = StreamEnvironment(name="avail", num_key_groups=8)
        (env.synthetic_source(vocab=13, batch_size=4, parallelism=2)
            .key_by().window_count(num_keys=13, window_size=1 << 30)
            .sink())
        return env.build()

    def runner(d):
        r = ClusterRunner(
            build(), steps_per_epoch=4, log_capacity=1 << 9, max_epochs=16,
            inflight_ring_steps=8,           # 2 epochs fill the ring
            spool_dir=str(d), spill_policy=ifl.SpillPolicy.AVAILABILITY,
            seed=11)
        r.executor.time_source.now = lambda it=iter(range(0, 10000, 7)): \
            next(it)
        return r

    golden = runner(tmp_path / "g")
    r = runner(tmp_path / "r")
    for rr in (golden, r):
        rr.run_epoch(complete_checkpoint=True)    # restore point
        # Three un-truncated epochs = 12 steps > ring(8): wraps past the
        # first fill epoch, whose occupancy at close (4/8) was below the
        # default 0.3? no — 0.5 >= 0.3 spills. Tighten trigger to force
        # the skip.
        for sl in rr.executor.spill_logs:
            sl.availability_trigger = 0.9
        rr.run_epoch(complete_checkpoint=False)
        rr.run_epoch(complete_checkpoint=False)
        rr.run_epoch(complete_checkpoint=False)
    # The deferred epochs were spilled before the wrap destroyed them.
    assert any(sl.retained_epochs() for sl in r.executor.spill_logs)
    r.inject_failure([3])                         # window subtask 1
    report = r.recover()
    assert report.steps_replayed == 12
    # Compare the DATA-path state (op state, edge buffers, rings, record
    # counts). The causal logs legitimately differ: healthy subtasks
    # logged IGNORE_CHECKPOINT determinants for the three pending
    # checkpoints the dead task never acked — a never-failed run has no
    # such control history (reference StreamTask.ignoreCheckpoint).
    ca = canonical_carry(r.executor.carry)
    cb = canonical_carry(golden.executor.carry)
    for field in ("op_states", "edge_bufs", "rr_offsets",
                  "record_counts", "out_rings"):
        for xa, xb in zip(
                jax.tree_util.tree_leaves(getattr(ca, field)),
                jax.tree_util.tree_leaves(getattr(cb, field))):
            np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
