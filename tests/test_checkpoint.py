"""Checkpoint coordination: trigger/ack/complete ledger, truncation hooks,
standby dispatch, ignore-unacked, backoff, storage, and restore-equivalence
(reference CheckpointCoordinator behaviors, §3.3 of SURVEY.md)."""

import numpy as np
import jax
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.runtime import checkpoint as cp
from clonos_tpu.runtime.executor import LocalExecutor


def _job(parallelism=2):
    env = StreamEnvironment(num_key_groups=8)
    (env.synthetic_source(vocab=7, batch_size=4, parallelism=parallelism)
        .key_by().window_count(num_keys=7, window_size=10 ** 9).sink())
    return env.build()


def _coord(n=4, **kw):
    return cp.CheckpointCoordinator(cp.InMemoryCheckpointStorage(),
                                    num_subtasks=n, **kw)


def test_complete_requires_all_acks_and_write():
    c = _coord(n=2)
    done, dispatched = [], []
    c.subscribe_completion(done.append)
    c.subscribe_completed_state(lambda ck: dispatched.append(ck.checkpoint_id))
    c.trigger(0, {"x": np.arange(3)}, async_write=False)
    assert done == []
    c.ack(0, 0)
    assert done == []
    c.ack(0, 1)
    assert done == [0] and dispatched == [0]
    assert c.latest_completed_id == 0


def test_retention_deletes_old_checkpoints():
    c = _coord(n=1, max_retained=2)
    for cid in range(4):
        c.trigger(cid, {"v": np.asarray(cid)}, async_write=False)
        c.ack(cid, 0)
    assert c.storage.list_ids() == [2, 3]
    assert c.latest_completed().carry["v"] == 3


def test_ignore_unacked_for_failed_task():
    c = _coord(n=3)
    c.trigger(5, {}, async_write=False)
    c.ack(5, 0)
    ignored = c.ignore_unacked_for({2})
    assert ignored == [5]
    # Late acks for an ignored checkpoint never complete it.
    c.ack(5, 1)
    c.ack(5, 2)
    assert c.latest_completed_id is None
    # Re-trigger of an ignored id is a no-op.
    c.trigger(5, {}, async_write=False)
    c.ack_all(5)
    assert c.latest_completed_id is None


def test_backoff_and_reset():
    c = _coord(n=1, base_interval_steps=16, backoff_multiplier=2.0,
               max_backoff_steps=100)
    assert c.interval_steps == 16
    assert c.backoff() == 32
    assert c.backoff() == 64
    assert c.backoff() == 100
    assert c.backoff() == 100
    assert c.reset_interval() == 16


def test_file_storage_roundtrip(tmp_path):
    st = cp.FileCheckpointStorage(str(tmp_path))
    carry = {"a": np.arange(5, dtype=np.int32), "b": np.ones((2, 2))}
    st.write(cp.CompletedCheckpoint(3, carry, 0.0))
    got = st.read(3)
    np.testing.assert_array_equal(got.carry["a"], carry["a"])
    assert st.list_ids() == [3]
    st.delete(3)
    assert st.list_ids() == []


def test_restore_equivalence():
    """A standby restored from a checkpoint and fed the same step inputs
    reaches the bit-identical carry — the foundation of causal recovery."""
    job = _job()
    times = list(range(0, 100, 7))
    ex1 = LocalExecutor(job, steps_per_epoch=3, seed=1)
    ex1.time_source.now = lambda it=iter(times): next(it)
    ex1.run_epoch()                         # epoch 0
    coord = _coord(n=job.total_subtasks())
    coord.trigger(0, ex1.carry, async_write=False)
    coord.ack_all(0)
    ex1.notify_checkpoint_complete(0)       # truncation on the live side
    ex1.run_epoch()                         # epoch 1 (3 more steps)

    ex2 = LocalExecutor(job, steps_per_epoch=3, seed=99)
    ex2.restore(coord.latest_completed().carry, epoch_id=1)
    ex2.notify_checkpoint_complete(0)
    # Feed the standby the same post-checkpoint inputs the live run saw.
    ex2.time_source.now = lambda it=iter(times[3:]): next(it)
    # Match the live run's RNG stream position (3 draws pre-checkpoint).
    ex2._rng = np.random.RandomState(1)
    for _ in range(3):
        ex2._rng.randint(0, 2 ** 31, dtype=np.int64)
    ex2.run_epoch()

    a = jax.device_get(ex1.carry)
    b = jax.device_get(ex2.carry)
    fa, _ = jax.tree_util.tree_flatten(a)
    fb, _ = jax.tree_util.tree_flatten(b)
    assert len(fa) == len(fb)
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
