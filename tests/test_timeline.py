"""Causal timeline + gray-failure detection plane (clonos_tpu/obs/).

The HLC layers first: the clock's send/receive rules must order every
receive after its send no matter how badly the two processes' wall
clocks disagree, and the merged two-process record stream must show
zero causality inversions under seeded random interleavings. Then the
reader contract (torn tail dropped, mid-file junk refused with
file:line), the pure gray-failure detector (peer-relative scoring,
sustained-streak suspects, bit-identical replay from the pinned
snapshot log), and the ``clonos_tpu timeline`` CLI exit-0/1 contract.
The acceptance tests at the bottom run the real thing: a SIGKILLed
child process whose timeline file merges cleanly with the parent's,
and a gray soak where the suspect event lands BEFORE the first SLO
breach.
"""

import json
import os
import random
import signal
import subprocess
import sys
import time

import pytest

from clonos_tpu.obs.detect import (DetectorConfig, DetectorState,
                                   GrayFailureDetector, GraySnapshot,
                                   detect_gray, reset_detector,
                                   score_gray)
from clonos_tpu.obs.hlc import (HybridLogicalClock, reset_hlc,
                                stamp_key)
from clonos_tpu.obs.timeline import (TimelineStore, causality_inversions,
                                     configure_timeline, diff_timelines,
                                     merge_records, read_timeline,
                                     reset_timeline, timeline_self_check)
from clonos_tpu.soak import parse_schedule

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs_globals():
    yield
    reset_detector()
    reset_timeline()
    reset_hlc()


def _fake_clock(start: float, step: float = 0.001):
    """A deterministic wall clock: starts skewed, advances per read."""
    t = [start]

    def clock():
        t[0] += step
        return t[0]

    return clock


# --- hybrid logical clock ----------------------------------------------------


def test_hlc_tick_is_strictly_monotonic_under_frozen_clock():
    t = [100.0]
    h = HybridLogicalClock("a", clock=lambda: t[0])  # wall time frozen
    stamps = [h.tick() for _ in range(50)]
    for prev, nxt in zip(stamps, stamps[1:]):
        assert stamp_key(nxt) > stamp_key(prev)
    # frozen physical time advances the logical component only
    assert stamps[0][0] == stamps[-1][0]
    assert stamps[-1][1] > stamps[0][1]


def test_hlc_observe_orders_receive_after_send_despite_skew():
    # the receiver's wall clock is 30 SECONDS behind the sender's:
    # physical timestamps alone would order every receive before its
    # send; the observe rule must not.
    sender = HybridLogicalClock("fast", clock=_fake_clock(1000.0))
    receiver = HybridLogicalClock("slow", clock=_fake_clock(970.0))
    for _ in range(200):
        sent = sender.tick()
        recv = receiver.observe(sent)
        assert stamp_key(recv) > stamp_key(sent)


def test_hlc_merged_streams_show_no_inversions_seeded_interleavings():
    """The property the whole plane hangs on: two processes with badly
    skewed clocks exchange messages in seeded-random interleavings and
    the merged, HLC-ordered record stream NEVER shows a receive before
    its send."""
    rng = random.Random(7)
    for trial in range(20):
        skew = rng.uniform(-60.0, 60.0)
        clocks = {"a": HybridLogicalClock("a", clock=_fake_clock(500.0)),
                  "b": HybridLogicalClock(
                      "b", clock=_fake_clock(500.0 + skew))}
        records = []
        in_flight = []
        for _ in range(120):
            op = rng.random()
            src = rng.choice(["a", "b"])
            dst = "b" if src == "a" else "a"
            if op < 0.5:
                sent = clocks[src].tick()
                in_flight.append((dst, sent))
                records.append({"kind": "msg.send", "ts": 0.0,
                                "hlc": list(sent), "service": src,
                                "verb": "DEPLOY"})
            elif in_flight:
                dst, sent = in_flight.pop(
                    rng.randrange(len(in_flight)))
                got = clocks[dst].observe(sent)
                records.append({"kind": "msg.recv", "ts": 0.0,
                                "hlc": list(got), "service": dst,
                                "verb": "DEPLOY", "sent": list(sent)})
        merged = merge_records(records)
        assert causality_inversions(merged) == [], \
            f"trial {trial} (skew {skew:+.1f}s)"


def test_timeline_self_check_is_clean():
    # the conftest session gate, callable directly
    assert timeline_self_check() == []


def test_causality_inversions_catches_a_broken_receive_rule():
    # a receive stamped BELOW its send must be reported, not absorbed
    bad = [{"kind": "msg.send", "ts": 0.0, "hlc": [10, 0, "a"],
            "service": "a", "verb": "HEARTBEAT"},
           {"kind": "msg.recv", "ts": 0.0, "hlc": [9, 0, "b"],
            "service": "b", "verb": "HEARTBEAT",
            "sent": [10, 0, "a"]}]
    findings = causality_inversions(merge_records(bad))
    assert findings
    assert any(f["rule"] == "stamp" for f in findings)


# --- timeline store + reader -------------------------------------------------


def test_timeline_store_writes_and_reader_survives_torn_tail(tmp_path):
    path = str(tmp_path / "timeline-a.jsonl")
    tl = TimelineStore("a", path=path, clock=_fake_clock(10.0))
    tl.record("epoch.seal", epoch=3)
    tl.record("scale.decision", epoch=3, action="hold")
    tl.close()
    # a SIGKILL mid-append leaves a torn final line: dropped, not fatal
    with open(path, "a") as f:
        f.write('{"kind": "msg.send", "ts": 11.0, "hl')
    recs = read_timeline(path)
    assert [r["kind"] for r in recs] == ["epoch.seal", "scale.decision"]
    assert recs[0]["service"] == "a" and recs[0]["epoch"] == 3


def test_timeline_reader_refuses_mid_file_junk(tmp_path):
    path = str(tmp_path / "timeline-junk.jsonl")
    with open(path, "w") as f:
        f.write('{"kind": "epoch.seal", "ts": 1.0, "epoch": 1}\n')
        f.write("not json at all\n")
        f.write('{"kind": "epoch.seal", "ts": 2.0, "epoch": 2}\n')
    with pytest.raises(ValueError) as ei:
        read_timeline(path)
    assert "timeline-junk.jsonl" in str(ei.value)
    assert "2" in str(ei.value)   # the offending line number


def test_merge_and_diff_of_two_stores(tmp_path):
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    ta = TimelineStore("a", path=pa, clock=_fake_clock(5.0))
    tb = TimelineStore("b", path=pb, clock=_fake_clock(900.0))
    ta.record("epoch.seal", epoch=1)
    tb.record("epoch.seal", epoch=1)
    ta.record("chaos", chaos_kind="kill", at_s=1.0)
    ta.close(), tb.close()
    merged = merge_records(read_timeline(pa) + read_timeline(pb))
    assert len(merged) == 3
    # same logical content despite wildly different wall clocks
    assert diff_timelines(
        [r for r in read_timeline(pa) if r["kind"] == "epoch.seal"],
        read_timeline(pb)) == []
    # ...and the extra chaos record is attributed to the right side
    findings = diff_timelines(read_timeline(pa), read_timeline(pb))
    assert [f["only"] for f in findings] == ["a"]
    assert findings[0]["record"]["kind"] == "chaos"


# --- gray-failure detector (pure core) ---------------------------------------


def _snap(epoch=1, hb=None, ep=None, stal=None, stall=0.0):
    return GraySnapshot.build(
        epoch=epoch, hb_age_ms=hb or {}, epoch_ms=ep or {},
        staleness=stal or {}, fence_stall_ms=stall)


def test_snapshot_canonical_crc_roundtrip():
    s = _snap(epoch=7, hb={"w0": 0.0, "w3": 412.3},
              ep={"w0": 100.0, "w3": 950.0}, stal={"replica.0": 0.5},
              stall=133.7)
    d = json.loads(s.canonical())
    assert GraySnapshot.from_dict(d) == s
    assert GraySnapshot.from_dict(d).crc() == s.crc()


def test_detect_gray_is_deterministic():
    cfg = DetectorConfig()
    s = _snap(hb={"w0": 0.0, "w1": 0.0, "w3": 500.0})
    v1, st1 = detect_gray(s, cfg, DetectorState())
    v2, st2 = detect_gray(s, cfg, DetectorState())
    assert v1 == v2 and st1 == st2 and v1.snapshot_crc == s.crc()


def test_peer_relative_scoring_ignores_cluster_wide_slowdown():
    cfg = DetectorConfig()
    # everyone is equally slow: the median moves, nobody is an outlier
    uniform = _snap(ep={f"w{i}": 5000.0 for i in range(4)})
    assert score_gray(uniform, cfg) == {}
    # one worker 4x the median IS an outlier
    skewed = _snap(ep={"w0": 100.0, "w1": 100.0, "w2": 100.0,
                       "w3": 400.0})
    scores = score_gray(skewed, cfg)
    assert list(scores) == ["w3"]
    assert "epoch-outlier" in scores["w3"][1]


def test_fence_stall_corroborates_but_never_accuses():
    cfg = DetectorConfig()
    # a stalled fence with no per-worker evidence names nobody
    assert score_gray(_snap(stall=9000.0), cfg) == {}
    # with a lagging worker, the stall strengthens that evidence
    scores = score_gray(_snap(hb={"w0": 0.0, "w2": 400.0},
                              stall=9000.0), cfg)
    assert scores["w2"][0] == 2
    assert scores["w2"][1] == ("hb-lag", "fence-stall")


def test_suspicion_must_sustain_and_resets_on_recovery():
    cfg = DetectorConfig(sustain_fences=2)
    lagging = _snap(hb={"w0": 0.0, "w1": 300.0})
    healthy = _snap(hb={"w0": 0.0, "w1": 0.0})
    v, st = detect_gray(lagging, cfg, DetectorState())
    assert v.suspects == ()          # one fence is noise
    assert v.scores == (("w1", 1),)  # ...but the score is visible
    v, st = detect_gray(lagging, cfg, st)
    assert v.suspect_workers() == ["w1"]   # sustained: suspect
    v, st = detect_gray(healthy, cfg, st)
    assert v.suspects == ()          # recovered: streak resets
    v, st = detect_gray(lagging, cfg, st)
    assert v.suspects == ()          # must re-sustain from scratch


def test_detector_replays_bit_identically_and_catches_tampering(
        tmp_path):
    configure_timeline("jm", path=str(tmp_path / "t.jsonl"),
                       clock=_fake_clock(50.0))
    det = GrayFailureDetector(DetectorConfig(sustain_fences=1))
    det.on_fence(_snap(epoch=1, hb={"w0": 0.0, "w1": 300.0}))
    det.on_fence(_snap(epoch=2, hb={"w0": 0.0, "w1": 280.0}))
    det.on_fence(_snap(epoch=3, hb={"w0": 0.0, "w1": 0.0}))
    assert det.suspects() == []              # cleared at fence 3
    assert det.events_emitted >= 2           # suspect + cleared
    verdicts = det.replay()                  # bit-identical from log
    assert [v.epoch for v in verdicts] == [1, 2, 3]
    assert verdicts[0].suspect_workers() == ["w1"]
    # the timeline carries the suspect AND the clearance
    kinds = [r["kind"] for r in read_timeline(str(tmp_path / "t.jsonl"))]
    assert "health.gray-suspect" in kinds
    assert "health.gray-cleared" in kinds
    # tamper with a pinned snapshot: replay must refuse
    det.log[1]["snapshot"]["hb_age_ms"][1][1] = 0.0
    with pytest.raises(ValueError):
        det.replay()


def test_detector_gauges_ride_the_metric_rollup():
    from clonos_tpu.utils.metrics import MetricRegistry
    reg = MetricRegistry()
    det = GrayFailureDetector(DetectorConfig(sustain_fences=1))
    det.register_gauges(reg)
    det.on_fence(_snap(epoch=1, hb={"w0": 0.0, "w1": 300.0}))
    snap = reg.snapshot()
    assert snap["cluster.health.suspects"] == 1
    assert snap["cluster.health.gray-events"] == 1
    assert snap["cluster.health.fences-scored"] == 1


def test_top_renders_health_row_and_trace_drop_line():
    from clonos_tpu.cli import _top_table
    table = _top_table({"cluster.health.suspects": 1,
                        "cluster.health.gray-events": 3,
                        "trace.dropped-records": 42})
    health = next(l for l in table.splitlines()
                  if l.startswith("health:"))
    assert "suspects=1" in health and "gray-events=3" in health
    assert "dropped-records=42" in table
    # zero drops: no alarm line
    assert "dropped-records" not in _top_table(
        {"cluster.health.suspects": 0, "trace.dropped-records": 0})


def test_tracer_counts_ring_evictions():
    from clonos_tpu.obs.trace import Tracer
    tr = Tracer("t", clock=_fake_clock(1.0), buffer=4)
    for i in range(7):
        tr.event("e", i=i)
    assert tr.dropped == 3
    assert len(tr.records()) == 4
    tr.close()


# --- the CLI contract --------------------------------------------------------


def test_timeline_cli_report_json_and_filters(tmp_path, capsys):
    from clonos_tpu.cli import main
    pa = str(tmp_path / "timeline-jm.jsonl")
    tl = TimelineStore("jm", path=pa, clock=_fake_clock(5.0))
    tl.record("epoch.seal", epoch=1)
    tl.record("epoch.seal", epoch=2)
    tl.record("scale.decision", epoch=2, action="hold")
    tl.close()
    rc = main(["timeline", pa, "--report", "json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0 and rep["ok"] is True
    assert rep["records"] == 3 and rep["inversions"] == []
    assert rep["by_kind"]["epoch.seal"] == 2
    # filtered view: counts reflect the filter, inversions never do
    rc = main(["timeline", pa, "--kind", "scale", "--report", "json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0 and rep["shown"] == 1


def test_timeline_cli_self_check_and_diff_exit_codes(tmp_path, capsys):
    from clonos_tpu.cli import main
    assert main(["timeline", "--self-check"]) == 0
    capsys.readouterr()
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    ta = TimelineStore("a", path=pa, clock=_fake_clock(1.0))
    tb = TimelineStore("b", path=pb, clock=_fake_clock(2.0))
    ta.record("epoch.seal", epoch=1)
    tb.record("epoch.seal", epoch=1)
    ta.close(), tb.close()
    assert main(["timeline", pa, "--diff", pb]) == 0
    capsys.readouterr()
    tb2 = TimelineStore("b", path=pb, clock=_fake_clock(3.0))
    tb2.record("epoch.seal", epoch=2)   # b diverges
    tb2.close()
    assert main(["timeline", pa, "--diff", pb]) == 1
    capsys.readouterr()


def test_timeline_cli_reports_inversions_with_exit_1(tmp_path, capsys):
    from clonos_tpu.cli import main
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "msg.send", "ts": 1.0,
                            "hlc": [10, 0, "a"], "service": "a",
                            "verb": "DEPLOY"}) + "\n")
        f.write(json.dumps({"kind": "msg.recv", "ts": 2.0,
                            "hlc": [9, 0, "b"], "service": "b",
                            "verb": "DEPLOY",
                            "sent": [10, 0, "a"]}) + "\n")
    rc = main(["timeline", path, "--report", "json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 1 and rep["ok"] is False and rep["inversions"]


def test_timeline_cli_chrome_export(tmp_path, capsys):
    from clonos_tpu.cli import main
    pa = str(tmp_path / "t.jsonl")
    tl = TimelineStore("jm", path=pa, clock=_fake_clock(5.0))
    tl.record("epoch.seal", epoch=1)
    tl.close()
    out = str(tmp_path / "chrome.json")
    assert main(["timeline", pa, "--chrome", out]) == 0
    capsys.readouterr()
    doc = json.load(open(out))
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert instants and instants[0]["name"] == "epoch.seal"


# --- acceptance: 2-process SIGKILL, one merged timeline ----------------------


_CHILD = r"""
import sys, time
port, path = int(sys.argv[1]), sys.argv[2]
from clonos_tpu.obs import configure_hlc, configure_timeline
from clonos_tpu.parallel import transport as tp
# the child's wall clock reads 45 SECONDS AHEAD of the parent's: raw
# timestamps would order every parent-side receive far before its
# send; only the HLC receive rule keeps the merged timeline causal
import time as _t
configure_hlc(node="child", clock=lambda: _t.time() + 45.0)
configure_timeline("child", path=path)
c = tp.ControlClient(("127.0.0.1", port), timeout_s=10.0)
for i in range(100000):
    msg = tp.attach_hlc({"seq": i}, verb="HEARTBEAT")
    c.call_json(tp.HEARTBEAT, msg)
    if i == 0:
        print("ready", flush=True)
    time.sleep(0.002)
"""


def test_sigkilled_child_merges_into_one_causal_timeline(tmp_path):
    """A child process streams HLC-stamped heartbeats (with its wall
    clock skewed +45s) until it is SIGKILLed mid-run. The parent's and
    the orphaned child's timeline files must merge into ONE stream
    with zero causality inversions — the dead process's last words
    still land in causal order."""
    from clonos_tpu.obs import configure_hlc
    from clonos_tpu.parallel import transport as tp

    parent_tl = str(tmp_path / "timeline-parent.jsonl")
    child_tl = str(tmp_path / "timeline-child.jsonl")
    configure_hlc(node="parent")
    configure_timeline("parent", path=parent_tl)
    seen = []

    def handler(mtype, payload):
        obj = tp.unpack_json(payload)
        tp.adopt_hlc(obj, verb="HEARTBEAT")
        seen.append(obj["seq"])
        return mtype, tp.pack_json({"ok": True})

    srv = tp.ControlServer(handler)
    child_src = str(tmp_path / "child.py")
    with open(child_src, "w") as f:
        f.write(_CHILD)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    pb = subprocess.Popen(
        [sys.executable, child_src, str(srv.address[1]), child_tl],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    try:
        assert pb.stdout.readline().strip() == "ready"
        deadline = time.monotonic() + 20.0
        while len(seen) < 8 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(seen) >= 8, "child never delivered 8 heartbeats"
        pb.send_signal(signal.SIGKILL)   # mid-loop, mid-write maybe
        pb.wait(timeout=10.0)
    finally:
        if pb.poll() is None:
            pb.kill()
        srv.close()
    assert pb.returncode == -signal.SIGKILL

    merged = merge_records(read_timeline(parent_tl)
                           + read_timeline(child_tl))
    assert causality_inversions(merged) == []
    sends = [r for r in merged if r["kind"] == "msg.send"]
    recvs = [r for r in merged if r["kind"] == "msg.recv"]
    assert len(sends) >= 8 and len(recvs) >= 8
    assert len(recvs) <= len(sends)      # the kill can orphan sends
    assert {r["service"] for r in sends} == {"child"}
    assert {r["service"] for r in recvs} == {"parent"}
    # despite the +45s skew, every recv sorts after its send; spot-
    # check the interleave: the first record is a send
    assert merged[0]["kind"] == "msg.send"


# --- acceptance: gray soak — suspect BEFORE the first SLO breach -------------


@pytest.mark.slow
def test_gray_soak_suspect_fires_before_first_slo_breach(tmp_path):
    """The end-to-end detection story: a paced soak takes a gray
    failure (worker 3's beats lag 30ms, transport stretched) and the
    detector must call it — ``health.gray-suspect`` lands in the
    merged timeline BEFORE the first ``slo.breach``, the audit ledger
    stays clean, and the whole detection sequence replays
    bit-identically from the pinned snapshot log."""
    from clonos_tpu.obs import configure_detector
    from clonos_tpu.soak import (SLOSpec, SoakConfig, SoakDriver,
                                 build_soak_fixture)

    tl_path = str(tmp_path / "timeline-soak.jsonl")
    configure_timeline("soak", path=tl_path)
    # hb threshold under the 30ms injected lag; staleness channel
    # silenced (complete_every=2 legitimately lets replicas trail)
    configure_detector(DetectorConfig(
        hb_age_high_ms=15.0, staleness_high=100.0, sustain_fences=1))
    runner, control, election = build_soak_fixture(
        str(tmp_path / "fx"), rate=1200.0, duration_s=3.5,
        steps_per_epoch=32, seed=11)
    driver = SoakDriver(
        runner, SoakConfig(rate=1200.0, duration_s=3.5, window_s=1.0,
                           chunk_steps=8),
        schedule=parse_schedule("at 0.2s gray 3 delay=30ms for 60s"),
        spec=SLOSpec(exactly_once=True, max_p99_ms=400.0),
        control=control, election=election, records_per_step=16)
    v = driver.run()

    assert v["audit"]["exactly_once"] is True
    assert v["audit"]["divergences"] == []
    assert "w3" in v["health"]["suspects"]
    assert v["health"]["replay_bit_identical"] is True
    assert v["health"]["gray_events"] >= 1

    merged = merge_records(read_timeline(tl_path))
    assert causality_inversions(merged) == []
    kinds = [r["kind"] for r in merged]
    suspect_at = kinds.index("health.gray-suspect")
    assert merged[suspect_at]["worker"] == "w3"
    # the detector got there first: the suspect precedes every breach
    # (the gray-stretched transport guarantees at least one)
    assert "slo.breach" in kinds
    assert suspect_at < kinds.index("slo.breach")
    # the chaos event itself is on the same timeline, before the call
    assert kinds.index("chaos") < suspect_at
