"""Transactional (2PC) sink: exactly-once committed egress across
failures (reference TwoPhaseCommitSinkFunction semantics)."""

import numpy as np
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.runtime.cluster import ClusterRunner


def _job():
    env = StreamEnvironment(name="txn", num_key_groups=16)
    (env.synthetic_source(vocab=13, batch_size=4, parallelism=2)
        .key_by()
        .window_count(num_keys=13, window_size=40)
        .sink(transactional=True))
    return env.build()


def _runner():
    r = ClusterRunner(_job(), steps_per_epoch=3, seed=3)
    r.executor.time_source.now = lambda it=iter(range(0, 4000, 17)): next(it)
    return r


def _sink_vid(r):
    return next(iter(r.txn_logs))


def test_commit_only_on_checkpoint_complete():
    r = _runner()
    r.run_epoch(complete_checkpoint=False)      # sealed, NOT committed
    tl = r.txn_logs[_sink_vid(r)]
    assert tl.pending_epochs() == [0]
    assert tl.committed_stream().shape[0] == 0  # nothing externalized
    r.coordinator.ack_all(0)                    # checkpoint completes
    assert tl.pending_epochs() == []
    assert len(tl.committed) == 1


def test_committer_callback_sees_each_epoch_once():
    r = _runner()
    seen = []
    r.txn_logs[_sink_vid(r)].committer = \
        lambda e, recs: seen.append((e, recs.shape[0]))
    r.run_epoch()
    r.run_epoch()
    assert [e for e, _ in seen] == [0, 1]


def test_sink_failure_rebuilds_pending_exactly_once():
    """Kill a transactional sink subtask with sealed-but-uncommitted
    transactions pending; after recovery the committed stream is
    bit-identical to a never-failed run's — no loss, no duplication."""
    golden = _runner()
    r = _runner()
    for rr in (golden, r):
        rr.run_epoch()                          # epoch 0 commits
        rr.run_epoch(complete_checkpoint=False)  # epoch 1 pending
        rr.run_epoch(complete_checkpoint=False)  # epoch 2 pending
    sink_vid = _sink_vid(r)
    base = r.job.subtask_base(sink_vid)
    r.inject_failure([base + 1])
    rep = r.recover()
    assert rep.steps_replayed == 6
    # The failed run IGNORED checkpoints 1 and 2 (un-acked by the dead
    # task) — their transactions commit under the next completed
    # checkpoint, exactly like the reference's subsuming commit.
    golden.run_epoch()
    r.run_epoch()
    g = golden.txn_logs[_sink_vid(golden)].committed_stream()
    got = r.txn_logs[sink_vid].committed_stream()
    np.testing.assert_array_equal(got, g)
    assert got.shape[0] > 0


def test_window_failure_leaves_sink_transactions_intact():
    golden = _runner()
    r = _runner()
    for rr in (golden, r):
        rr.run_epoch()
        rr.run_epoch(complete_checkpoint=False)
    r.inject_failure([3])                       # window subtask 1
    r.recover()
    golden.run_epoch()
    r.run_epoch()
    np.testing.assert_array_equal(
        r.txn_logs[_sink_vid(r)].committed_stream(),
        golden.txn_logs[_sink_vid(golden)].committed_stream())


def test_file_sink_exactly_once_across_failure(tmp_path):
    """Durable part-file egress (StreamingFileSink analog): pendings at
    every seal, atomic finals at commit; a sink failure mid-pending ends
    with committed FILES bit-identical to a never-failed run's; only
    .final files are ever observable; stale pendings sweep on restart."""
    import os
    golden = _runner()
    gd = str(tmp_path / "golden")
    gfs = golden.attach_file_sink(_sink_vid(golden), gd)
    r = _runner()
    rd = str(tmp_path / "failed")
    rfs = r.attach_file_sink(_sink_vid(r), rd)
    for rr in (golden, r):
        rr.run_epoch()                           # epoch 0 commits
        rr.run_epoch(complete_checkpoint=False)  # epoch 1 pending
        rr.run_epoch(complete_checkpoint=False)  # epoch 2 pending
    # Pendings are durable BEFORE their checkpoints complete.
    assert any(f.endswith(".pending") for f in os.listdir(rd))
    assert rfs.committed_epochs() == [0]

    sink_base = r.job.subtask_base(_sink_vid(r))
    r.inject_failure([sink_base + 1])
    r.recover()                 # ignores the dead task's unacked ckpts
    # Epochs 1-2 commit with the NEXT completed checkpoint (an ignored
    # checkpoint can never complete) — run epoch 3 to completion on both.
    for rr in (golden, r):
        rr.run_epoch(complete_checkpoint=True)
    assert rfs.committed_epochs() == gfs.committed_epochs() \
        == [0, 1, 2, 3]
    np.testing.assert_array_equal(rfs.read_committed(),
                                  gfs.read_committed())
    # Nothing pending remains; a restart sweep finds nothing to remove.
    assert not any(f.endswith(".pending") for f in os.listdir(rd))
    assert rfs.sweep_pending() == []


def test_file_sink_sweeps_stale_pendings_on_restart(tmp_path):
    """A dead incarnation's sealed-but-never-committed pendings must not
    survive into the next incarnation's observable output."""
    import os
    root = str(tmp_path / "sink")
    r = _runner()
    fs = r.attach_file_sink(_sink_vid(r), root)
    r.run_epoch()                                # epoch 0 commits
    r.run_epoch(complete_checkpoint=False)       # epoch 1 pending, dies
    assert any(f.endswith(".pending") for f in os.listdir(root))
    committed_before = fs.read_committed()

    # New incarnation over the same directory: pendings of epochs it is
    # not resuming are aborted (recoverAndAbort).
    r2 = _runner()
    fs2 = r2.attach_file_sink(_sink_vid(r2), root)
    assert not any(f.endswith(".pending") for f in os.listdir(root))
    np.testing.assert_array_equal(fs2.read_committed(), committed_before)


def test_file_sink_sweep_is_token_fenced(tmp_path):
    """The unfenced-sweep bug, pinned: during a handoff (live re-cut,
    standby takeover) two incarnations briefly share one sink root. A
    STALE sweeper (older fencing token) must never delete the newer
    writer's in-progress pendings or temp files; a NEWER sweeper
    removes a fenced-off predecessor's pendings regardless of
    keep_epochs; and commit never certifies a successor's parts."""
    import os
    from clonos_tpu.runtime.filesink import FileSystemSink

    root = str(tmp_path / "shared")
    old = FileSystemSink(root, token=0)
    new = FileSystemSink(root, token=1)
    old.write_pending(3, {0: np.arange(6).reshape(2, 3)})
    new.write_pending(4, {0: np.arange(9).reshape(3, 3)})
    orphan = os.path.join(root, "part-5-0-t1.pending.tmp")
    open(orphan, "wb").close()

    # stale sweeper: only its own (token-0) pendings go; the newer
    # incarnation's pending AND temp orphan survive
    removed = old.sweep_pending()
    assert removed == ["part-3-0-t0.pending"]
    assert sorted(os.listdir(root)) == ["part-4-0-t1.pending",
                                       "part-5-0-t1.pending.tmp"]

    # the stale writer completing its checkpoint must not certify the
    # successor's epoch-4 pending either
    old.commit(4, None)
    assert new.committed_epochs() == []

    # newer sweeper: the predecessor's pendings are always dead — even
    # ones keep_epochs would retain at its own token
    old.write_pending(4, {1: np.arange(3).reshape(1, 3)})
    removed = new.sweep_pending(keep_epochs=[4])
    assert removed == ["part-4-1-t0.pending", "part-5-0-t1.pending.tmp"]
    assert sorted(os.listdir(root)) == ["part-4-0-t1.pending"]
    new.commit(4, None)
    assert new.committed_epochs() == [4]
