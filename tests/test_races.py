"""Whole-program static race detector (clonos_tpu/analysis/threads.py
+ races.py): thread-root inventory, lockset ∩ happens-before checking,
and join discipline for the overlapped pipelines.

The acceptance pairs:

- Every seeded concurrency bug (``SEEDED_BUGS``) yields EXACTLY its
  rule's finding, naming the racing attribute, both thread roots, and
  the minimal call chain — while each bug's corrected twin in the same
  module stays quiet.
- The repo itself is race-clean: every race finding is discharged by a
  happens-before edge or carries a justified waiver, so
  ``clonos_tpu analyze --races --report json`` exits 0 at HEAD.
- The thread-root census fingerprint matches the ``.clonos-threads``
  pin (drift = a new/removed/re-homed thread root that must be
  re-reviewed).
"""

import json
import os
import textwrap
import time

import pytest

from clonos_tpu.analysis import (CallGraph, JOIN_DISCIPLINE,
                                 LockOrderGraph, SEEDED_BUGS,
                                 THREAD_RACE, ThreadInventory,
                                 run_analysis, run_races,
                                 seeded_findings, threads_fingerprint)
from clonos_tpu.lint import FileContext

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RACE_RULES = {THREAD_RACE, JOIN_DISCIPLINE}


def _pipeline(src, name="mod.py"):
    ctx = FileContext(name, textwrap.dedent(src))
    graph = CallGraph([ctx])
    return (ctx, graph, LockOrderGraph([ctx], graph),
            ThreadInventory([ctx], graph))


def _race_findings(src, name="mod.py"):
    ctx, graph, lockgraph, inv = _pipeline(src, name)
    return run_races([ctx], graph, lockgraph, inv)


def _inventory(src, name="mod.py"):
    return _pipeline(src, name)[3]


# --- thread-root inventory ------------------------------------------------

_METHOD_ROOT_SRC = """\
    import threading

    class Pump:
        def __init__(self):
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()

        def _loop(self):
            pass

        def close(self):
            self._thread.join()
    """


def test_inventory_resolves_method_root():
    inv = _inventory(_METHOD_ROOT_SRC)
    (root,) = inv.roots
    assert root.kind == "method"
    assert root.entry == "mod.Pump._loop"
    assert root.daemon is True
    assert root.spawner == "mod.Pump.__init__"
    assert [s[2] for s in root.start_sites] == ["mod.Pump.__init__"]
    assert [s[2] for s in root.join_sites] == ["mod.Pump.close"]
    assert root.joined


def test_inventory_resolves_closure_root():
    inv = _inventory("""\
        import threading

        class Job:
            def run(self):
                done = []
                def _work():
                    done.append(1)
                t = threading.Thread(target=_work)
                t.start()
                t.join()
                return done
        """)
    (root,) = inv.roots
    assert root.kind == "closure"
    assert root.entry == "mod.Job.run.<_work>"
    assert root.joined


def test_fingerprint_ignores_line_shifts_not_renames():
    base = threads_fingerprint(_inventory(_METHOD_ROOT_SRC))
    shifted = threads_fingerprint(_inventory(
        "    # a comment that moves every line down\n"
        + _METHOD_ROOT_SRC))
    assert shifted == base
    renamed = threads_fingerprint(_inventory(
        _METHOD_ROOT_SRC.replace("_loop", "_pump_loop")))
    assert renamed != base


# --- seeded bugs: each rule provably bites --------------------------------

@pytest.mark.parametrize("name", sorted(SEEDED_BUGS))
def test_seeded_bug_yields_minimal_counterexample(name):
    spec = SEEDED_BUGS[name]
    findings = seeded_findings(name)
    assert len(findings) == 1, [f.message for f in findings]
    (f,) = findings
    assert f.rule == spec["rule"]
    assert f.severity == "error"
    # the finding names the racing attribute, BOTH roots, and a chain
    assert f"`{spec['attr']}`" in f.message
    assert "thread roots" in f.message
    assert "chain[" in f.message


def test_seeded_bug_corrected_twins_stay_quiet():
    # each seed module carries a corrected twin of its bug; the only
    # finding is the seeded one, and the twin attr is never named
    twins = {"drop-a-join": "_joined_product",
             "unguarded-cross-thread-write": "_guarded",
             "queue-bypass": "_q"}
    for name, twin in twins.items():
        for f in seeded_findings(name):
            assert twin not in f.message.split(";")[0]


def test_unknown_seed_name_rejected():
    with pytest.raises(ValueError, match="drop-a-join"):
        seeded_findings("no-such-bug")


# --- happens-before discharges --------------------------------------------

def test_shared_lock_discharges():
    assert _race_findings("""\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._totals = {}
                self._t = threading.Thread(target=self._loop)
                self._t.start()

            def _loop(self):
                with self._lock:
                    self._totals["beat"] = 1

            def bump(self):
                with self._lock:
                    self._totals["n"] = 1
        """) == []


def test_condition_guard_discharges():
    # threading.Condition is a lock for guard purposes (type-resolved,
    # no name hint: "_cv" says nothing)
    assert _race_findings("""\
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self._items = []
                self._t = threading.Thread(target=self._loop)
                self._t.start()

            def _loop(self):
                with self._cv:
                    self._items.append(1)

            def take(self):
                with self._cv:
                    return self._items.pop()
        """) == []


def test_queue_handoff_discharges():
    assert _race_findings("""\
        import queue
        import threading

        class C:
            def __init__(self):
                self._q = queue.Queue()
                self._t = threading.Thread(target=self._loop)
                self._t.start()

            def _loop(self):
                self._q.put(1)

            def take(self):
                return self._q.get()
        """) == []


def test_prestart_publication_discharges():
    # the spawner writes BEFORE start(): Thread.start() is a
    # happens-before edge, the worker's unguarded read is ordered
    assert _race_findings("""\
        import threading

        class C:
            def __init__(self):
                self._cfg = {}
                self._cfg["mode"] = "fast"
                self._t = threading.Thread(target=self._loop)
                self._t.start()

            def _loop(self):
                return self._cfg["mode"]
        """) == []


def test_join_dominance_discharges():
    assert _race_findings("""\
        import threading

        class C:
            def __init__(self):
                self._out = []
                self._t = threading.Thread(target=self._work)

            def _work(self):
                self._out.append(1)

            def run(self):
                self._t.start()
                self._t.join()
                return list(self._out)
        """) == []


def test_plain_scalar_publish_discharges():
    # reference-swap publish: every write is a plain `self.x = ...`
    # rebind, so a bare read is a GIL-atomic reference read
    assert _race_findings("""\
        import threading

        class C:
            def __init__(self):
                self.result = None
                self._t = threading.Thread(target=self._work)
                self._t.start()

            def _work(self):
                self.result = 42

            def peek(self):
                return self.result
        """) == []


# --- waivers ---------------------------------------------------------------

def _analyze_src(tmp_path, monkeypatch, files, use_waivers=True):
    monkeypatch.chdir(tmp_path)
    for name, src in files.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    return run_analysis(sorted(files), use_waivers=use_waivers)


_RACY_SRC = """\
    import threading

    class C:
        def __init__(self):
            self._totals = {}
            self._t = threading.Thread(target=self._loop)
            self._t.start()

        def _loop(self):
            __WAIVER__self._totals["beat"] = 1

        def bump(self):
            self._totals["n"] = 1
    """


def test_inline_waiver_suppresses_and_persists(tmp_path, monkeypatch):
    waiver = ("# clonos: allow(thread-race): test fixture\n"
              "            ")
    res = _analyze_src(
        tmp_path, monkeypatch,
        {"mod.py": _RACY_SRC.replace("__WAIVER__", waiver)})
    races = [f for f in res.findings if f.rule in RACE_RULES]
    assert races and all(f.waived for f in races)
    assert res.ok

    # without the waiver the same source fails
    res = _analyze_src(
        tmp_path, monkeypatch,
        {"mod.py": _RACY_SRC.replace("__WAIVER__", "")})
    races = [f for f in res.findings if f.rule in RACE_RULES]
    assert races and not any(f.waived for f in races)
    assert res.exit_code() == 1


def test_stale_race_waiver_warns(tmp_path, monkeypatch):
    res = _analyze_src(tmp_path, monkeypatch, {"mod.py": """\
        # clonos: allow(join-discipline): nothing to waive here
        X = 1
        """})
    assert any(f.rule == "stale-waiver"
               and "join-discipline" in f.message
               for f in res.warnings)


# --- the repo itself -------------------------------------------------------

def test_repo_is_race_clean(monkeypatch):
    """Every race finding in the repo is waived with a justification —
    the `clonos_tpu analyze --races` CI gate, in-process."""
    monkeypatch.chdir(_REPO)
    res = run_analysis(["clonos_tpu", "examples"])
    assert res.errors == [], "\n".join(
        f"{f.location()}: [{f.rule}] {f.message}" for f in res.errors)
    races = [f for f in res.findings if f.rule in RACE_RULES]
    assert races, "race pass found nothing at all — lost its teeth?"
    assert all(f.waived for f in races)


def test_repo_thread_census_matches_pin(monkeypatch):
    monkeypatch.chdir(_REPO)
    res = run_analysis(["clonos_tpu", "examples"])
    with open(os.path.join(_REPO, ".clonos-threads")) as f:
        pinned = f.read().split()[0]
    assert res.threads_fingerprint == pinned, (
        "thread-root census drifted; review `clonos_tpu analyze "
        "--threads` and re-pin .clonos-threads")
    assert res.threads["roots"], "empty thread inventory"


# --- CLI -------------------------------------------------------------------

def test_cli_races_json_exits_zero_at_head(monkeypatch, capsys):
    from clonos_tpu import cli

    monkeypatch.chdir(_REPO)
    rc = cli.main(["analyze", "--races", "--report", "json",
                   "--no-census"])
    out = capsys.readouterr().out.strip()
    assert rc == 0
    rep = json.loads(out)
    assert rep["ok"] is True
    assert all(f["rule"] in RACE_RULES or "waiver" in f["rule"]
               for f in rep["findings"])
    assert rep["threads_fingerprint"]


@pytest.mark.parametrize("name", sorted(SEEDED_BUGS))
def test_cli_seed_bug_exits_one_with_counterexample(name, capsys):
    from clonos_tpu import cli

    rc = cli.main(["analyze", "--seed-bug", name])
    out = capsys.readouterr().out
    assert rc == 1
    assert SEEDED_BUGS[name]["rule"] in out
    assert SEEDED_BUGS[name]["attr"] in out


def test_cli_seed_bug_unknown_exits_two(capsys):
    from clonos_tpu import cli

    assert cli.main(["analyze", "--seed-bug", "no-such"]) == 2


def test_cli_expect_threads_gate(monkeypatch, capsys):
    from clonos_tpu import cli

    monkeypatch.chdir(_REPO)
    rc = cli.main(["analyze", "--races", "--expect-threads",
                   ".clonos-threads"])
    capsys.readouterr()
    assert rc == 0
    rc = cli.main(["analyze", "--races", "--expect-threads",
                   "0" * 16])
    err = capsys.readouterr().err
    assert rc == 1
    assert "thread-census drift" in err


# --- satellite: cross-host wall-clock lease regression ---------------------

def test_lease_deadlines_are_wall_clock_not_per_boot(tmp_path):
    """Regression (advisor round 5, since fixed): lease deadlines in
    the shared claim file must be WALL-CLOCK — claim files are read by
    contenders on other hosts, where a per-boot CLOCK_MONOTONIC value
    is meaningless (premature takeover or failover that never fires)."""
    from clonos_tpu.runtime.leader import FileLeaderElection

    lease = str(tmp_path / "lease")
    a = FileLeaderElection(lease, "jm-a", lease_ttl_s=30.0)
    assert a.try_acquire()
    with open(f"{lease}.epoch1.claim") as f:
        rec = json.load(f)
    # wall-clock epoch seconds, not a small per-boot monotonic value
    assert abs(rec["deadline_wall"] - (time.time() + 30.0)) < 60.0

    # a contender on another "host" (its own clock object) reads the
    # same file and agrees the lease is live, then sees it lapse
    b = FileLeaderElection(lease, "jm-b", lease_ttl_s=30.0,
                           clock=lambda: time.time())
    assert b.leader() == "jm-a"
    assert not b.try_acquire()
    b._clock = lambda: time.time() + 3600.0   # an hour later, anywhere
    assert b.try_acquire() and b.epoch == 2
