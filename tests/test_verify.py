"""Protocol model checker (clonos_tpu/verify/): exhaustive exploration
of the checkpoint / recovery / lease-fencing / admission / repartition
/ scale-policy transition models, seeded-bug counterexamples, the
counterexample→chaos bridge, and the conformance layer that replays
model traces against the real components.

The acceptance spine: (1) all six models are violation-free at the
default bound; (2) every seeded bug in verify/models.py BUGS yields a
MINIMAL counterexample (the invariants are not vacuous); (3) a
counterexample round-trips through the chaos DSL byte-for-byte and —
for the audit-bait bug — reproduces the audit divergence on a live
soak cluster; (4) the real components match the models' observable
transitions bit-for-bit over model-generated traces.
"""

import json
import os
import subprocess
import sys

import pytest

from clonos_tpu.verify import (BUGS, MODELS, Action, Model, compile_trace,
                               explore, run_verify, traces,
                               write_counterexample)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- explorer -------------------------------------------------------------

class _Counter(Model):
    """Toy model: two counters that may each step to 3; invariant
    forbids both reaching 2+, liveness demands both leave 0."""

    name = "counter"

    def __init__(self, bound=3, bad_pair=True):
        self.bound = bound
        self.bad_pair = bad_pair

    def initial_state(self):
        return (0, 0)

    def enabled(self, state):
        return [Action("inc", (i,)) for i in (0, 1)
                if state[i] < self.bound]

    def apply(self, state, action):
        i = action.args[0]
        return tuple(v + 1 if j == i else v
                     for j, v in enumerate(state))

    def invariants(self):
        if not self.bad_pair:
            return []
        return [("not-both-2", lambda s:
                 "both counters >= 2" if min(s) >= 2 else None)]

    def canon(self, state):
        return tuple(sorted(state))      # counters are symmetric

    def settled(self, state):
        return "a counter never moved" if 0 in state else None


def test_explorer_finds_minimal_counterexample_bfs():
    r = explore(_Counter())
    assert not r.ok
    v = r.violations[0]
    # BFS: the first violating state found is at minimal depth (2+2).
    assert v.depth == 4
    assert [a.kind for a in v.trace] == ["inc"] * 4
    assert v.invariant == "not-both-2"


def test_explorer_symmetry_canon_dedups_states():
    r = explore(_Counter(bad_pair=False))
    # Without canon: (bound+1)^2 = 16 states; with sorted-pair canon
    # only the triangle remains.
    assert r.states == 10
    assert r.ok


def test_explorer_liveness_flags_wedged_terminal_states():
    class Wedge(_Counter):
        def enabled(self, state):
            return []                    # initial state is terminal

    r = explore(Wedge(bad_pair=False))
    assert [v.invariant for v in r.violations] == ["liveness"]
    assert "never moved" in r.violations[0].detail


def test_explorer_truncation_is_reported_not_judged():
    r = explore(_Counter(bound=50, bad_pair=False), depth=3)
    assert r.truncated
    # cut-off states are not deadlocks: no liveness violations
    assert r.ok


def test_traces_prefers_full_protocol_rounds():
    ts = traces(_Counter(bound=2, bad_pair=False), n=3)
    assert len(ts) == 3
    # deepest-first: the first trace reaches the (2, 2) terminal state
    assert len(ts[0]) == 4
    sigs = {tuple(a.label() for a in t) for t in ts}
    assert len(sigs) == 3                # distinct by construction


# --- the six models -------------------------------------------------------

def test_all_models_clean_at_default_bound():
    r = run_verify()
    assert r.ok and r.exit_code() == 0
    assert {rep.model for rep in r.reports} == set(MODELS)
    for rep in r.reports:
        assert rep.states > 0 and not rep.truncated, rep.model


@pytest.mark.parametrize("model,bug", [(m, b) for m in sorted(BUGS)
                                       for b in sorted(BUGS[m])])
def test_every_seeded_bug_yields_a_counterexample(model, bug):
    r = run_verify(models=[model], quick=True, bugs={model: bug})
    assert not r.ok and r.exit_code() == 1, f"{model}:{bug} not caught"
    assert r.violations[0].trace          # with a concrete trace


def test_lease_bug_counterexample_is_the_minimal_three_steps():
    r = run_verify(models=["lease"], quick=True,
                   bugs={"lease": "no-fencing-check"})
    v = r.violations[0]
    assert v.invariant == "single-fenced-writer"
    # The classic split-brain: A acquires, the lease lapses, B acquires
    # — and with no receiver-side check both tokens stay accepted.
    assert [a.label() for a in v.trace] == ["acquire(0)", "expire",
                                            "acquire(1)"]


def test_checkpoint_late_ack_regresses_the_truncation_fence():
    r = run_verify(models=["checkpoint"], quick=True,
                   bugs={"checkpoint": "late-ack"})
    v = r.violations[0]
    assert v.invariant == "truncate-monotone"
    labels = [a.label() for a in v.trace]
    # the late completion lands after a newer fence truncated higher
    assert labels[-1].startswith("ack(1")


def test_unknown_model_and_bug_are_rejected():
    with pytest.raises(ValueError):
        run_verify(models=["nope"])
    with pytest.raises(ValueError):
        run_verify(bugs={"lease": "nope"})


@pytest.mark.slow
def test_full_depth_sweep_is_clean():
    """The big bound: 3 workers, 3 epochs, 2 faults — tens of
    thousands of states per model, still violation-free."""
    r = run_verify(workers=3, epochs=3, faults=2, depth=64,
                   max_states=500_000)
    assert r.ok, "\n".join(str(v.to_dict()) for v in r.violations)
    ckpt = next(rep for rep in r.reports if rep.model == "checkpoint")
    assert ckpt.states > 5_000           # genuinely exhaustive
    for model, bugs in BUGS.items():
        for bug in bugs:
            rb = run_verify(models=[model], workers=3, epochs=3,
                            faults=2, depth=64, max_states=500_000,
                            bugs={model: bug})
            assert not rb.ok, f"{model}:{bug} escaped the big bound"


# --- counterexample -> chaos bridge ---------------------------------------

def test_bridge_round_trips_through_the_chaos_dsl(tmp_path):
    from clonos_tpu.soak.chaos import parse_schedule, read_trace_schedule

    r = run_verify(models=["lease"], quick=True,
                   bugs={"lease": "no-fencing-check"})
    v = r.violations[0]
    sched = compile_trace(v)
    assert sched.kinds() == ["leader-loss"]
    assert parse_schedule(sched.to_text()) == sched

    out = write_counterexample(str(tmp_path), v)
    assert os.path.exists(out["chaos"]) and os.path.exists(out["trace"])
    # the .chaos file is valid DSL and equal to the compiled schedule
    with open(out["chaos"]) as f:
        assert parse_schedule(f.read()) == sched
    # the .jsonl trace imports back as the same schedule, and records
    # every model step (including the ones with no live-fault analog)
    assert read_trace_schedule(out["trace"]) == sched
    with open(out["trace"]) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    assert [rec["action"] for rec in recs] == ["acquire(0)", "expire",
                                              "acquire(1)"]
    assert sum(1 for rec in recs if rec["chaos"]) == 1


def test_trace_import_tolerates_a_torn_tail(tmp_path):
    from clonos_tpu.soak.chaos import read_trace_schedule

    r = run_verify(models=["checkpoint"], quick=True,
                   bugs={"checkpoint": "unlogged-write"})
    out = write_counterexample(str(tmp_path), r.violations[0])
    with open(out["trace"], "a") as f:
        f.write('{"model": "checkpoint", "truncated-mid-wri')
    sched = read_trace_schedule(out["trace"])
    assert sched.kinds() == ["nondet"]   # torn tail dropped, not fatal


def test_shared_jsonl_reader_contract(tmp_path):
    from clonos_tpu.utils.jsonl import read_jsonl

    p = tmp_path / "log.jsonl"
    assert read_jsonl(str(p)) == []      # missing file: empty log
    p.write_text('{"a": 1}\n\n{"b": 2}\n{"torn": ')
    assert read_jsonl(str(p)) == [{"a": 1}, {"b": 2}]
    # mid-file corruption is NOT a torn tail: it must raise, and with
    # a label the error names the file and line
    p.write_text('{"a": 1}\njunk\n{"b": 2}\n')
    with pytest.raises(json.JSONDecodeError):
        read_jsonl(str(p))
    with pytest.raises(ValueError, match=r"log\.jsonl:2"):
        read_jsonl(str(p), label=str(p))


# --- conformance: models vs the real components ---------------------------

def test_conformance_all_components_match_bit_for_bit(tmp_path):
    from clonos_tpu.verify.conformance import run_conformance

    reports = run_conformance(n_traces=3, workdir=str(tmp_path))
    assert set(reports) == {"checkpoint", "recovery", "lease",
                            "admission", "repartition", "scalepolicy"}
    for name, rep in sorted(reports.items()):
        assert rep.traces >= 3, f"{name}: only {rep.traces} trace(s)"
        assert rep.steps >= rep.traces   # every trace drove real code
        assert rep.ok, (f"{name} diverged: "
                        f"{[d.to_dict() for d in rep.divergences]}")


def test_conformance_catches_an_implementation_divergence(tmp_path):
    """Negative control: sabotage one observable transition and the
    conformance layer must flag it (divergence fails CI, not silently
    passes)."""
    from clonos_tpu.runtime.dispatcher import AdmissionController
    from clonos_tpu.verify.conformance import conform_admission

    orig = AdmissionController.request
    def sabotaged(self, job_id, tenant, slots, free_slots):
        verdict = orig(self, job_id, tenant, slots, free_slots)
        if verdict == "admitted":        # leak a phantom reservation
            self._pending[job_id + "-ghost"] = (tenant, 1)
        return verdict
    AdmissionController.request = sabotaged
    try:
        rep = conform_admission(n_traces=3)
    finally:
        AdmissionController.request = orig
    assert not rep.ok
    assert any("projection" in str(d.expected) for d in rep.divergences)


# --- CLI ------------------------------------------------------------------

def _run_cli(args, timeout=120):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "clonos_tpu", "verify"] + args,
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)


def test_cli_verify_quick_report_json_exit_zero():
    p = _run_cli(["--quick", "--report", "json"])
    assert p.returncode == 0, p.stderr[-2000:]
    line = json.loads(p.stdout.strip().splitlines()[-1])
    assert line["ok"] is True and line["quick"] is True
    assert {m["model"] for m in line["models"]} == set(MODELS)
    assert all(m["violations"] == [] for m in line["models"])


def test_cli_verify_seeded_bug_exits_one_with_counterexample(tmp_path):
    p = _run_cli(["--quick", "--model", "lease", "--seed-bug",
                  "lease:no-fencing-check", "--report", "json",
                  "--chaos-out", str(tmp_path)])
    assert p.returncode == 1, p.stderr[-2000:]
    line = json.loads(p.stdout.strip().splitlines()[-1])
    (m,) = line["models"]
    assert m["violations"][0]["trace"] == ["acquire(0)", "expire",
                                           "acquire(1)"]
    names = os.listdir(tmp_path)
    assert any(n.endswith(".chaos") for n in names)
    assert any(n.endswith(".jsonl") for n in names)


def test_cli_verify_bad_arguments_exit_two():
    assert _run_cli(["--model", "nope"]).returncode == 2
    assert _run_cli(["--seed-bug", "no-colon"]).returncode == 2


# --- the live acceptance chain --------------------------------------------

@pytest.mark.slow
def test_counterexample_reproduces_audit_divergence_live(tmp_path):
    """The full bridge, end to end: the checkpoint model with the
    seeded ``unlogged-write`` bug produces a counterexample whose
    ``perturb`` step compiles to a ``nondet`` chaos event; importing
    that schedule from the written trace file and driving a LIVE soak
    cluster with it must trip the epoch-digest audit — the model's
    exactly-once-logged invariant and the runtime's audit are catching
    the same hazard."""
    from clonos_tpu.soak import (SLOSpec, SoakConfig, SoakDriver,
                                 build_soak_fixture)
    from clonos_tpu.soak.chaos import read_trace_schedule

    r = run_verify(models=["checkpoint"], quick=True,
                   bugs={"checkpoint": "unlogged-write"})
    v = r.violations[0]
    assert v.invariant == "exactly-once-logged"
    out = write_counterexample(str(tmp_path), v, start_s=1.5)
    sched = read_trace_schedule(out["trace"])
    assert sched.kinds() == ["nondet"]

    runner, control, election = build_soak_fixture(
        str(tmp_path / "soak"), rate=1200.0, duration_s=4.0,
        steps_per_epoch=32, seed=11)
    driver = SoakDriver(
        runner, SoakConfig(rate=1200.0, duration_s=4.0, window_s=2.0),
        schedule=sched, spec=SLOSpec(exactly_once=True),
        control=control, election=election, records_per_step=16)
    verdict = driver.run()

    assert verdict["pass"] is False
    assert verdict["audit"]["exactly_once"] is False
    assert verdict["audit"]["divergences"]
