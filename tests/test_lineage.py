"""Record-level lineage plane (clonos_tpu/obs/lineage.py).

Unit layers first — the dye sampler (a pure key-hash function, so a
control twin dyes the SAME records with zero coordination), the
NullLineage identity (disabled = zero wire fields, zero per-record
work), and the torn-tail-tolerant observation reader. Then the live
capture path: an in-process cluster runs epochs under a plane and the
reconstructed report must join every dyed record's hops and
determinant context into an unbroken path; byte-identity of ``lineage
--report json`` is asserted across two fresh interpreter processes
(the rootcause.py convention). The serve-read terminus rides the
router's provenance stamp (replica id, epoch, rerouted flag). The slow
test is the headline acceptance: a soak with ``--lineage`` armed takes
a mid-run kill, and the dyed records' reconstructed paths must come
out byte-identical to the fault-free control twin's.
"""

import json
import os
import subprocess
import sys

import pytest

from clonos_tpu.obs import lineage as lin
from clonos_tpu.utils.metrics import MetricRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_globals():
    yield
    lin.reset_lineage()


def _cli_lineage(*args):
    return subprocess.run(
        [sys.executable, "-m", "clonos_tpu.cli", "lineage", *args],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))


# --- the dye sampler ---------------------------------------------------------


def test_select_dyed_pure_function_of_the_key_set():
    keys = [5, 3, 9, 3, 5, 12, 44, 7]
    a = lin.select_dyed(keys, epoch=6, salt=17, k=3)
    # permutation + duplicates never change the dye set (the control
    # twin sees the same keys in a different ring order)
    b = lin.select_dyed(list(reversed(sorted(set(keys)))), epoch=6,
                        salt=17, k=3)
    assert a == b
    assert len(a) == 3 and len(set(a)) == 3
    assert set(a) <= set(keys)
    # k >= population dyes everything; k=0 dyes nothing
    assert set(lin.select_dyed(keys, epoch=6, salt=17, k=99)) \
        == set(keys)
    assert lin.select_dyed(keys, epoch=6, salt=17, k=0) == []
    # epoch and salt both rotate the sample
    assert lin.select_dyed(keys, epoch=7, salt=17, k=3) != a \
        or lin.select_dyed(keys, epoch=8, salt=17, k=3) != a


def test_dye_hash_is_stable():
    assert lin.dye_hash(7, 3, 17) == lin.dye_hash(7, 3, 17)
    assert lin.dye_hash(7, 3, 17) != lin.dye_hash(7, 4, 17)


# --- the disabled identity ---------------------------------------------------


def test_null_lineage_is_inert_and_default():
    g = lin.get_lineage()
    assert isinstance(g, lin.NullLineage)
    assert g.enabled is False
    assert g.wire_config() is None
    assert g.observe_epoch(0, {"logs": {}, "rings": {}}) == 0
    assert g.observe_serve(5, epoch=0, replica="r") is False
    assert g.is_dyed(5) is False
    g.register_gauges(MetricRegistry())
    g.sync()
    g.close()


def test_wire_stamp_only_when_enabled(tmp_path):
    from clonos_tpu.parallel import transport as tp

    hdr = tp.attach_lineage({"verb": "DEPLOY"})
    assert "lineage" not in hdr, "disabled must add ZERO wire fields"
    lin.configure_lineage(str(tmp_path), k=2, salt=99)
    hdr = tp.attach_lineage({"verb": "DEPLOY"})
    assert hdr["lineage"]["k"] == 2 and hdr["lineage"]["salt"] == 99
    # a fresh (disabled) receiver adopts the sender's dye config
    lin.reset_lineage()
    tp.adopt_lineage(hdr)
    g = lin.get_lineage()
    assert g.enabled and g.k == 2 and g.salt == 99


def test_lineage_tag_codec_roundtrip():
    from clonos_tpu.causal import serde

    tags = [(100, 2, 7, 1, 3), (5, 0, 0, 0, 0)]
    frame = serde.encode_lineage_tags(tags)
    assert serde.decode_lineage_tags(frame) == tags
    with pytest.raises(ValueError):
        serde.decode_lineage_tags(frame[:-1] + b"\x00")


# --- observation files -------------------------------------------------------


def test_read_observations_tolerates_torn_tail(tmp_path):
    p = lin.LineagePlane(str(tmp_path), service="t", k=2)
    p.observe_epoch(0, {"logs": {}, "rings": {
        0: [([3, 5], [1, 1], [0, 1])]}})
    p.close()
    (path,) = [str(tmp_path / f) for f in os.listdir(tmp_path)]
    n = len(lin.read_observations(path))
    assert n > 0
    with open(path, "a") as f:
        f.write('{"kind": "hop", "torn')       # SIGKILL mid-append
    assert len(lin.read_observations([path])) == n
    # mid-file corruption is damage, not a torn tail
    with open(path, "a") as f:
        f.write('\n{"kind": "dye", "key": 3}\n')
    with pytest.raises(ValueError):
        lin.read_observations(path)


def test_observe_epoch_is_idempotent(tmp_path):
    p = lin.LineagePlane(str(tmp_path), service="t", k=2)
    win = {"logs": {}, "rings": {0: [([3, 5], [1, 1], [0, 1])]}}
    n1 = p.observe_epoch(4, win)
    assert p.observe_epoch(4, win) == 0, \
        "a recovery-replayed fence must not double-observe"
    p.close()
    (path,) = [str(tmp_path / f) for f in os.listdir(tmp_path)]
    assert len(lin.read_observations(path)) == n1


def test_self_check_clean():
    assert lin.lineage_self_check() == []


# --- live capture + reconstruction ------------------------------------------


def _make_runner(tmp_path, plane, seed=3):
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    env = StreamEnvironment(name="linjob", num_key_groups=16)
    (env.synthetic_source(vocab=11, batch_size=8, parallelism=2)
        .key_by()
        .window_count(num_keys=11, window_size=1 << 30, name="w"))
    return ClusterRunner(env.build(), steps_per_epoch=4,
                         log_capacity=256, max_epochs=8,
                         inflight_ring_steps=16, seed=seed,
                         checkpoint_dir=str(tmp_path / "ck"),
                         lineage=plane)


def test_cluster_fence_observes_dyed_records(tmp_path):
    plane = lin.LineagePlane(str(tmp_path), service="run", k=3)
    r = _make_runner(tmp_path, plane)
    for _ in range(3):
        r.run_epoch(complete_checkpoint=True)
    r.drain_fence()
    plane.close()
    assert plane.dyed > 0 and plane.observations > plane.dyed
    # the lineage.* gauges landed in the runner registry
    snap = r.metrics.snapshot()
    assert snap["lineage.dyed"] == plane.dyed
    assert snap["lineage.epochs-observed"] == 3
    assert snap["lineage.k"] == 3

    obs = lin.read_observations(str(tmp_path / "lineage-run.jsonl"))
    rep = lin.reconstruct(obs)
    assert rep["ok"] is True and rep["broken_keys"] == []
    assert len(rep["keys"]) >= 3
    for path in rep["keys"].values():
        assert path["dyed_at"] is not None
        assert path["hops"], "a dyed record must have ring hops"
        assert path["determinants"], \
            "hops must carry ORDER/TIMESTAMP/RNG context"
        # hop attribution: key-group routing resolved to a subtask
        assert all("subtask" in h and "key_group" in h
                   for h in path["hops"])


def test_trace_byte_identical_across_two_processes(tmp_path):
    plane = lin.LineagePlane(str(tmp_path), service="run", k=3)
    r = _make_runner(tmp_path, plane)
    for _ in range(2):
        r.run_epoch(complete_checkpoint=True)
    r.drain_fence()
    plane.close()
    path = str(tmp_path / "lineage-run.jsonl")

    a = _cli_lineage(path, "--report", "json")
    b = _cli_lineage(path, "--report", "json")
    assert a.returncode == 0, a.stderr
    assert b.returncode == 0, b.stderr
    assert a.stdout and a.stdout == b.stdout, \
        "two fresh processes must render identical bytes"
    rep = json.loads(a.stdout)
    assert rep["ok"] is True
    assert rep["schema_fingerprint"] == lin.lineage_schema_fingerprint()
    # --key narrows to one record, same canonical encoding
    key = sorted(rep["keys"], key=int)[0]
    k1 = _cli_lineage(path, "--key", key, "--report", "json")
    k2 = _cli_lineage(path, "--key", key, "--report", "json")
    assert k1.returncode == 0 and k1.stdout == k2.stdout


def test_cli_self_check_and_chrome_export(tmp_path):
    out = _cli_lineage("--self-check")
    assert out.returncode == 0, out.stderr
    line = json.loads(out.stdout)
    assert line["ok"] is True and line["findings"] == []

    plane = lin.LineagePlane(str(tmp_path), service="run", k=2)
    r = _make_runner(tmp_path, plane)
    r.run_epoch(complete_checkpoint=True)
    r.drain_fence()
    plane.close()
    dst = str(tmp_path / "chrome.json")
    out = _cli_lineage(str(tmp_path / "lineage-run.jsonl"),
                       "--chrome", dst)
    assert out.returncode == 0, out.stderr
    doc = json.load(open(dst))
    assert doc["traceEvents"]


# --- serve-read terminus + provenance stamp ----------------------------------


def test_serve_reads_carry_provenance_and_feed_lineage(tmp_path):
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.serve import build_serve_tier

    plane = lin.LineagePlane(str(tmp_path), service="serve", k=4)
    env = StreamEnvironment(name="serve", num_key_groups=16,
                            default_edge_capacity=64)
    (env.synthetic_source(vocab=11, batch_size=8, parallelism=2)
        .key_by().reduce(num_keys=11, name="r").sink())
    r = ClusterRunner(env.build(), steps_per_epoch=4,
                      log_capacity=256, max_epochs=8,
                      inflight_ring_steps=16, seed=3, lineage=plane)
    tier = build_serve_tier(r, 1, n_replicas=1)
    try:
        r.run_epoch(complete_checkpoint=True)
        r.drain_fence()
        dyed = sorted(plane._dyed_recent)
        assert dyed, "an epoch must have dyed records"
        out = tier.router.query(1, dyed[0])
        # provenance stamp: who served, at which fence, rerouted?
        assert out["replica"] == "replica-0"
        assert out["rerouted"] is False
        assert out["epoch"] >= 0
        # the endpoint itself stamps its identity too (direct reads)
        rep = tier.clients[0].query(1, dyed[0])
        assert rep["replica"] == "replica-0"
        batch = tier.router.query_batch(1, [0, 1, 2])
        assert batch["rerouted"] == [False, False, False]
        before = plane.serve_hits
        assert before >= 1, "dyed reads must land serve observations"
        tier.router.query(1, dyed[0])
        assert plane.serve_hits == before + 1
    finally:
        tier.close()
        plane.close()
    obs = lin.read_observations(str(tmp_path / "lineage-serve.jsonl"))
    serves = [o for o in obs if o["kind"] == "serve"]
    assert serves and any(o["key"] == dyed[0] for o in serves)
    path = lin.reconstruct(obs)["keys"][str(dyed[0])]
    assert path["serves"] and path["broken"] == []


# --- the headline acceptance (slow) ------------------------------------------


@pytest.mark.slow
def test_soak_lineage_paths_bit_identical_across_kill(tmp_path):
    """The headline proof: arm lineage on a soak fixture, kill a
    subtask mid-run, recover, and the dyed records' reconstructed
    end-to-end paths must come out BYTE-identical to the fault-free
    control twin's — recovery replayed the dyed records through the
    exact same hops, determinants and termini."""
    from clonos_tpu.soak import build_soak_fixture
    from clonos_tpu.soak.driver import default_kill_targets

    lin.configure_lineage(str(tmp_path), service="soak", k=4)
    runner, control, election = build_soak_fixture(
        str(tmp_path), rate=1200.0, duration_s=4.0,
        steps_per_epoch=32, seed=11)
    try:
        assert runner.lineage is not control.lineage
        assert runner.lineage.enabled and control.lineage.enabled
        assert runner.lineage.salt == control.lineage.salt

        for e in range(6):
            runner.run_epoch(complete_checkpoint=True)
            control.run_epoch(complete_checkpoint=True)
            if e == 2:      # mid-soak kill on the live runner only
                runner.drain_fence()
                runner.inject_failure(default_kill_targets(runner.job))
                runner.recover()
        runner.drain_fence()
        control.drain_fence()
        # both twins dyed the SAME records, zero coordination
        assert runner.lineage.dyed == control.lineage.dyed > 0
    finally:
        runner.lineage.close()
        control.lineage.close()

    run_f = str(tmp_path / "lineage-soak-run.jsonl")
    ctl_f = str(tmp_path / "lineage-soak-control.jsonl")
    a = _cli_lineage(run_f, "--report", "json")
    b = _cli_lineage(ctl_f, "--report", "json")
    assert a.returncode == 0, a.stderr
    assert b.returncode == 0, b.stderr
    assert a.stdout == b.stdout, \
        "faulted path must replay bit-identical to the fault-free twin"
    rep = json.loads(a.stdout)
    assert rep["ok"] is True and len(rep["keys"]) > 0
    # the joined view across BOTH twins also reconstructs cleanly
    both = _cli_lineage(run_f, ctl_f, "--report", "json")
    assert both.returncode == 0
    assert json.loads(both.stdout)["ok"] is True
