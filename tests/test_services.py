"""Causal services: record/replay of host nondeterminism, the
append-even-during-replay invariant, async rows interleaved with the sync
log, and recovery with async determinants present (reference
causal/services/* behaviors + AsyncDeterminant handling)."""

import numpy as np
import jax
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.causal import determinant as det
from clonos_tpu.causal import log as clog
from clonos_tpu.causal import services as svc
from clonos_tpu.runtime.cluster import ClusterRunner


def _collect_append():
    logged = []
    return logged, logged.append


def test_time_service_records_then_replays():
    logged, append = _collect_append()
    clock_vals = iter([100, 200])
    t = svc.CausalTimeService(append, clock=lambda: next(clock_vals))
    assert t.current_time_millis() == 100
    assert t.current_time_millis() == 200
    # Replay: identical values, clock untouched, still appended (invariant).
    feed = svc.ReplayFeed(list(logged))
    logged2, append2 = _collect_append()
    t2 = svc.CausalTimeService(append2, replay_feed=feed,
                               clock=lambda: 1 / 0)
    assert t2.current_time_millis() == 100
    assert t2.current_time_millis() == 200
    assert logged2 == logged
    assert feed.exhausted()
    # Past the feed: back to live mode.
    t3_clock = iter([300])
    t2._clock = lambda: next(t3_clock)
    assert t2.current_time_millis() == 300


def test_random_service_replay_and_mismatch():
    logged, append = _collect_append()
    r = svc.CausalRandomService(append, seed=5)
    vals = [r.next_int() for _ in range(3)]
    feed = svc.ReplayFeed(list(logged))
    r2 = svc.CausalRandomService(lambda d: None, replay_feed=feed, seed=99)
    assert [r2.next_int() for _ in range(3)] == vals
    # Type mismatch (call order divergence) raises.
    feed2 = svc.ReplayFeed(list(logged))
    t = svc.CausalTimeService(lambda d: None, replay_feed=feed2)
    with pytest.raises(RuntimeError):
        t.current_time_millis()


def test_serializable_service_replays_without_external_call():
    logged, append = _collect_append()
    store = det.SidecarStore(owner=1)
    calls = []

    def external(req: bytes) -> bytes:
        calls.append(req)
        return b"resp:" + req

    s = svc.CausalSerializableService(append, external, store,
                                      epoch_of=lambda: 0)
    assert s.apply(b"a") == b"resp:a"
    assert s.apply(b"b") == b"resp:b"
    assert len(calls) == 2
    feed = svc.ReplayFeed(list(logged))
    s2 = svc.CausalSerializableService(
        append, external, store, epoch_of=lambda: 0, replay_feed=feed)
    assert s2.apply(b"a") == b"resp:a"
    assert s2.apply(b"b") == b"resp:b"
    assert len(calls) == 2  # external system NOT re-invoked


def test_sidecar_integrity_and_truncation():
    store = det.SidecarStore(owner=2)
    d = store.put(b"payload", epoch=3)
    assert store.get(d) == b"payload"
    store.truncate(oldest_live_epoch=4)
    with pytest.raises(KeyError):
        store.get(d)


def _job():
    env = StreamEnvironment(num_key_groups=16)
    (env.synthetic_source(vocab=11, batch_size=8, parallelism=2)
        .key_by().window_count(num_keys=11, window_size=50).sink())
    return env.build()


TIMES = list(range(0, 400, 20))


def test_async_rows_interleave_and_recovery_stays_bit_identical():
    """A task's host code logs async determinants via the service; a later
    failure replays around them and reproduces the exact log."""
    def drive(r):
        r.executor.time_source.now = lambda it=iter(TIMES): next(it)
        store = det.SidecarStore(owner=1)
        fac = r.executor.service_factory(3, store, clock=lambda: 777)
        ts = fac.time_service()
        r.run_epoch()
        r.step()
        ts.current_time_millis()          # async row between steps
        r.step()
        # (No trailing append: an async determinant logged after the last
        # replicated step dies with the task — same durability boundary as
        # the reference's not-yet-piggybacked delta, and harmless for the
        # same reason: nothing downstream observed it.)
        return r

    golden = drive(ClusterRunner(_job(), steps_per_epoch=3, seed=3))
    r = drive(ClusterRunner(_job(), steps_per_epoch=3, seed=3))

    r.inject_failure([3])
    report = r.recover()
    mgr = report.managers[0]
    evs = mgr.result.async_events
    assert [(s, type(d).__name__) for s, d in evs] == [
        (1, "TimestampDeterminant")]
    assert all(d.timestamp == 777 for _, d in evs)

    from clonos_tpu.runtime.executor import canonical_carry
    a = jax.device_get(canonical_carry(r.executor.carry))
    b = jax.device_get(canonical_carry(golden.executor.carry))
    fa, _ = jax.tree_util.tree_flatten(a)
    fb, _ = jax.tree_util.tree_flatten(b)
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def test_periodic_time_service_amortizes_but_replays_exact():
    """PeriodicCausalTimeService: the (possibly expensive) time source
    is sampled at most once per period, every read still logs, and
    replay reproduces the recorded values exactly (reference
    PeriodicCausalTimeService.java)."""
    from clonos_tpu.causal.services import PeriodicCausalTimeService

    wall = iter([100, 150, 260, 300, 301, 302])
    samples = []

    def clock():
        v = next(wall)
        samples.append(v)
        return v

    logged = []
    # A huge period: the expensive source is sampled exactly ONCE for
    # any number of reads — the amortization the class exists for.
    svc = PeriodicCausalTimeService(logged.append, clock=clock,
                                    period_ms=1 << 30)
    got = [svc.current_time_millis() for _ in range(4)]
    assert got == [100, 100, 100, 100]
    assert len(samples) == 1                # one expensive sample
    assert len(logged) == 4                 # every read logged
    # period 0: every read refreshes from the source.
    svc0 = PeriodicCausalTimeService(logged.append, clock=clock,
                                     period_ms=0)
    assert [svc0.current_time_millis() for _ in range(2)] == [150, 260]
    # Replay: the recorded determinants reproduce the values with NO
    # clock access at all.
    from clonos_tpu.causal.services import ReplayFeed
    feed = ReplayFeed(list(logged[:4]))
    svc2 = PeriodicCausalTimeService(lambda d: None, replay_feed=feed,
                                     clock=lambda: 1 / 0)
    assert [svc2.current_time_millis() for _ in range(4)] == got
