"""The pipelined fence (runtime/cluster.py ``overlap_epoch``): the
epoch seal/ledger/checkpoint tail runs on a fence-worker thread while
the next epoch's compute is already on the device.

Three invariants make the overlap safe, and each gets a test here:

- **Bit-identity**: an overlapped run's durable digest ledger AND its
  live state digests are byte-identical to a strictly sequential
  control of the same job/seed/schedule (``diff_ledgers == []``) — the
  pipeline changed WHEN the tail ran, never WHAT it recorded.
- **Attribution identity**: ``sum(fence.* sub-spans) − overlap-saved ==
  fence-tail`` in both modes; the sequential control never writes the
  ``fence.overlap-saved`` key (its absence IS the control marker).
- **Drain ordering**: a kill that lands while a tail is in flight joins
  it first (seal + ack complete, nothing pending), so recovery appends
  no IGNORE determinants and the ledger stays control-comparable.

Plus the supporting machinery: the one-epoch ring-headroom check the
deferred overflow read requires, the ``overlap-window`` lint rule that
keeps the capture window dispatch-only, and the group-committed ledger
whose torn batched tail the tolerant reader drops.
"""

import os

import pytest

from clonos_tpu import obs
from clonos_tpu.obs.digest import diff_ledgers


@pytest.fixture(autouse=True)
def _null_obs_after():
    yield
    obs.reset()
    obs.reset_audit()


def _window_job(name):
    from clonos_tpu.api.environment import StreamEnvironment
    env = StreamEnvironment(name=name, num_key_groups=8)
    (env.synthetic_source(vocab=11, batch_size=4, parallelism=2)
        .key_by()
        .window_count(num_keys=11, window_size=1 << 30)
        .sink())
    return env.build()


def _runner(name, ck_dir, overlap, **kw):
    from clonos_tpu.runtime.cluster import ClusterRunner
    kw.setdefault("inflight_ring_steps", 32)
    return ClusterRunner(_window_job(name), steps_per_epoch=8,
                         log_capacity=512, max_epochs=8,
                         seed=3, audit=True, logical_time=True,
                         checkpoint_dir=ck_dir,
                         overlap_epoch=overlap, **kw)


def _fence_identity(phases, rel=0.15, abs_ms=2.0):
    """sum(fence.* sub-spans) − overlap-saved == fence-tail (the
    recovery-phase identity, applied to the fence tail)."""
    subs = {k: v for k, v in phases.items()
            if k.startswith("fence.") and k != "fence.overlap-saved"}
    saved = phases.get("fence.overlap-saved", 0.0)
    assert saved >= 0.0
    assert sum(subs.values()) - saved == pytest.approx(
        phases["fence-tail"], rel=rel, abs=abs_ms), (
        f"fence attribution broke: subs={subs} saved={saved} "
        f"fence-tail={phases['fence-tail']}")
    return subs, saved


def test_overlapped_ledger_and_state_identical_to_sequential(tmp_path):
    """The headline invariant: same job, same seed, same schedule —
    pipelined vs strictly sequential — identical durable ledgers AND
    identical live state digests."""
    completes = [True, False, True, False]

    def run(tag, overlap):
        from clonos_tpu.causal.recovery import AuditValidator
        r = _runner(f"pf-{tag}", str(tmp_path / tag), overlap)
        for c in completes:
            r.run_epoch(complete_checkpoint=c)
        r.drain_fence()
        ledger = r.coordinator.read_ledger()
        live = AuditValidator(r.executor, []).recompute_entries(
            [r.executor.epoch_id - 1])
        return ledger, live

    seq_ledger, seq_live = run("seq", False)
    ovl_ledger, ovl_live = run("ovl", True)
    assert [e["epoch"] for e in ovl_ledger] == [0, 1, 2, 3]
    assert diff_ledgers(seq_ledger, ovl_ledger) == []
    assert diff_ledgers(seq_live, ovl_live) == []


def test_fence_attribution_identity_both_modes(tmp_path):
    """Both modes satisfy the identity; ONLY the overlapped run writes
    fence.overlap-saved (absence is the sequential-control marker)."""
    r = _runner("pf-seq-attr", str(tmp_path / "seq"), False)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    pm = r.last_fence_phases
    assert "fence.overlap-saved" not in pm
    subs, _ = _fence_identity(pm)
    assert {"fence.health-read", "fence.digest-seal",
            "fence.ledger-write", "fence.snapshot"} <= set(subs)

    r2 = _runner("pf-ovl-attr", str(tmp_path / "ovl"), True)
    r2.run_epoch(complete_checkpoint=True)
    r2.run_epoch(complete_checkpoint=False)
    r2.drain_fence()
    pm2 = r2.last_fence_phases
    assert "fence.overlap-saved" in pm2
    subs2, _ = _fence_identity(pm2)
    assert {"fence.capture", "fence.health-read",
            "fence.digest-seal", "fence.snapshot"} <= set(subs2)
    # cumulative saved wall is what bench reports as
    # fence_overlap_saved_ms
    assert r2.fence_overlap_saved_total_ms >= 0.0


def test_kill_mid_fence_tail_recovers_bit_identical(tmp_path):
    """A kill injected while the fence tail is STILL IN FLIGHT joins it
    first (the seal and the completion ack land before any state is
    torn down), recovers, and the post-recovery ledger diffs clean
    against a fault-free sequential control."""
    r = _runner("pf-kill", str(tmp_path / "kill"), True)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=True)
    assert r.fence_tail_in_flight(), \
        "the second fence's tail should still be on the worker"
    r.inject_failure([2 + 1])              # window vertex, subtask 1
    report = r.recover()
    assert report.steps_replayed >= 0
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    r.drain_fence()

    c = _runner("pf-kill-ctrl", str(tmp_path / "ctrl"), False)
    for comp in (True, True, True, False):
        c.run_epoch(complete_checkpoint=comp)

    assert diff_ledgers(c.coordinator.read_ledger(),
                        r.coordinator.read_ledger()) == []
    snap = r.metrics.snapshot()
    assert snap["job.pf-kill.audit.divergences"] == 0


def test_zero_step_replay_after_joined_tail(tmp_path):
    """A connected owner+holder kill landing right after a completed
    fence whose overlapped tail just joined replays ZERO steps and
    fetches ZERO determinant responses. The empty merge must stay
    lane-shaped — a (0, 0)-shaped merge crashed the tag parse
    (``rows[:, LANE_TAG]``) the first time the soak driver fired a kill
    mid-fence-tail."""
    from clonos_tpu.causal.determinant import NUM_LANES
    from clonos_tpu.causal.replication import merge_determinant_responses
    rows, start = merge_determinant_responses([])
    assert rows.shape == (0, NUM_LANES) and start == 0

    r = _runner("pf-zerostep", str(tmp_path / "zs"), True,
                replication_factor=1)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=True)
    owner = 2 + 1                     # window vertex, subtask 1
    holder = next(h for (o, h) in r.executor.compiled.plan.pairs
                  if o == owner)
    r.inject_failure([owner, holder])   # joins the in-flight tail
    report = r.recover()
    assert report.steps_replayed == 0
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    r.drain_fence()

    c = _runner("pf-zerostep-ctrl", str(tmp_path / "zsc"), False,
                replication_factor=1)
    for comp in (True, True, True, False):
        c.run_epoch(complete_checkpoint=comp)
    assert diff_ledgers(c.coordinator.read_ledger(),
                        r.coordinator.read_ledger()) == []


def test_overlap_needs_one_epoch_of_ring_headroom(tmp_path):
    """The deferred overflow read only lands at the NEXT fence, so the
    in-flight ring must absorb a full extra epoch; a ring without that
    headroom is rejected up front, not discovered as corruption."""
    r = _runner("pf-headroom", str(tmp_path / "hr"), True,
                inflight_ring_steps=8)      # == steps_per_epoch: too small
    with pytest.raises(ValueError, match="ring headroom"):
        r.run_epoch(complete_checkpoint=True)
    # the same shape stays valid under the sequential fence
    r2 = _runner("pf-headroom-seq", str(tmp_path / "hr2"), False,
                 inflight_ring_steps=8)
    r2.run_epoch(complete_checkpoint=True)


def test_overlap_window_lint_rule_flags_host_syncs():
    """clonos_tpu/lint/overlapwindow.py: any blocking host read between
    the overlap-window markers re-serializes the tail the pipeline
    hides; copy_to_host_async (the async primitive) stays allowed."""
    from clonos_tpu.lint.core import FileContext
    from clonos_tpu.lint.overlapwindow import OverlapWindowSyncRule

    src = (
        "import numpy as np\n"
        "import jax\n"
        "def fence(x, h):\n"
        "    # clonos: overlap-window-begin\n"
        "    a = np.asarray(x)\n"
        "    h.copy_to_host_async()\n"
        "    b = jax.block_until_ready(x)\n"
        "    # clonos: overlap-window-end\n"
        "    return np.asarray(a)\n"
    )
    rule = OverlapWindowSyncRule()
    findings = rule.check(FileContext("fake.py", src))
    lines = sorted(f.line for f in findings)
    assert lines == [5, 7], [f.message for f in findings]

    # outside a window (or with no window at all): silent
    assert rule.check(FileContext(
        "fake.py", "import numpy as np\nx = np.asarray(1)\n")) == []

    # an unclosed begin marker is itself a finding
    torn = rule.check(FileContext(
        "fake.py", "# clonos: overlap-window-begin\n"))
    assert any("unbalanced" in f.message for f in torn)

    # the production overlap window must be clean right now
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cpath = os.path.join(repo, "clonos_tpu", "runtime", "cluster.py")
    with open(cpath) as f:
        csrc = f.read()
    assert "clonos: overlap-window-begin" in csrc, \
        "capture window markers disappeared from cluster.py"
    assert rule.check(FileContext(cpath, csrc)) == []


def test_group_commit_ledger_torn_batched_tail_roundtrip(tmp_path):
    """FileCheckpointStorage group commit: appends are flushed per line
    but fsynced every K. A SIGKILL inside the batch window can tear the
    last line mid-byte; the tolerant reader drops ONLY that torn tail,
    and flush_ledger() (the completion path) zeroes the unsynced
    window."""
    from clonos_tpu.runtime.checkpoint import (FileCheckpointStorage,
                                               read_ledger_file)

    st = FileCheckpointStorage(str(tmp_path / "ck"))
    assert st.ledger_group_commit == 8
    for i in range(11):
        st.write_ledger({"epoch": i, "records": 10 * i})
    # 11 appends with K=8: one fsync fired, 3 entries sit unsynced
    assert st._ledger_unsynced == 3
    # flushed lines are visible to a same-OS reader before any fsync
    assert [e["epoch"] for e in st.read_ledger()] == list(range(11))

    # completion marker path: fsync-now, batch window zeroed
    st.flush_ledger()
    assert st._ledger_unsynced == 0

    # tear the batched tail mid-line (the SIGKILL shape) and re-read
    st._close_ledger()
    path = st.ledger_path()
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.splitlines(keepends=True)
    torn = b"".join(lines[:-1]) + lines[-1][: len(lines[-1]) // 2]
    with open(path, "wb") as f:
        f.write(torn)
    assert [e["epoch"] for e in read_ledger_file(path)] \
        == list(range(10)), "only the torn LAST line is dropped"

    # base-class contract: every storage has flush_ledger (in-memory
    # ledgers are durable-by-definition no-ops)
    from clonos_tpu.runtime.checkpoint import CheckpointStorage
    CheckpointStorage().flush_ledger()
