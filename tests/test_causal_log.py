"""ThreadCausalLog ring-buffer tests: append, epoch index, truncation,
delta slicing, upstream-delta dedup (the coverage SURVEY §4 calls for)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clonos_tpu.causal import determinant as det
from clonos_tpu.causal import log as clog


def rows_of(values, tag=det.RNG):
    return det.pack_batch([det.RNGDeterminant(value=v) for v in values])


def test_append_and_read_back():
    tl = clog.ThreadCausalLog(capacity=64, max_epochs=8)
    tl.start_epoch(0)
    tl.append_rows(rows_of([10, 11, 12]))
    assert tl.head == 3 and tl.tail == 0 and len(tl) == 3
    got = tl.determinants_from_epoch(0, max_out=16)
    assert [d.value for d in det.unpack_batch(got)] == [10, 11, 12]


def test_epoch_truncation_rebases_tail():
    tl = clog.ThreadCausalLog(capacity=64, max_epochs=8)
    tl.start_epoch(0)
    tl.append_rows(rows_of([1, 2]))
    tl.start_epoch(1)
    tl.append_rows(rows_of([3, 4, 5]))
    tl.start_epoch(2)
    tl.append_rows(rows_of([6]))
    assert len(tl) == 6
    tl.notify_checkpoint_complete(0)  # drops epoch 0
    assert tl.tail == 2 and len(tl) == 4
    got = tl.determinants_from_epoch(1, max_out=16)
    assert [d.value for d in det.unpack_batch(got)] == [3, 4, 5, 6]
    # duplicate / late notification is a no-op
    tl.notify_checkpoint_complete(0)
    assert tl.tail == 2


def test_ring_wraparound():
    tl = clog.ThreadCausalLog(capacity=8, max_epochs=4)
    tl.start_epoch(0)
    tl.append_rows(rows_of(list(range(6))))
    tl.start_epoch(1)
    tl.notify_checkpoint_complete(0)  # tail -> 6
    tl.append_rows(rows_of(list(range(100, 107))))  # head -> 13, wraps
    assert tl.head == 13 and tl.tail == 6
    got = tl.determinants_from_epoch(1, max_out=8)
    assert [d.value for d in det.unpack_batch(got)] == list(range(100, 107))
    assert not bool(clog.overflowed(tl.state))


def test_overflow_detection():
    tl = clog.ThreadCausalLog(capacity=8, max_epochs=4)
    tl.start_epoch(0)
    tl.append_rows(rows_of(list(range(9))))
    assert bool(clog.overflowed(tl.state))


def test_delta_for_consumer_and_offsets():
    tl = clog.ThreadCausalLog(capacity=64, max_epochs=8)
    tl.start_epoch(0)
    tl.append_rows(rows_of([1, 2, 3]))
    d1, start1 = tl.delta_for_consumer(0, max_out=8)
    assert start1 == 0 and d1.shape[0] == 3
    tl.append_rows(rows_of([4, 5]))
    d2, start2 = tl.delta_for_consumer(3, max_out=8)
    assert start2 == 3
    assert [d.value for d in det.unpack_batch(d2)] == [4, 5]


def test_merge_delta_dedups_by_offset():
    # replica ingests overlapping deltas, must dedup like
    # processUpstreamDelta:117
    replica = clog.ThreadCausalLog(capacity=64, max_epochs=8)
    assert replica.merge_delta(rows_of([1, 2, 3]), abs_start=0)
    assert replica.head == 3
    # overlapping delta: offsets 1..4 — only 3,4 are fresh... (values 2,3,9)
    assert replica.merge_delta(rows_of([2, 3, 9]), abs_start=1)
    assert replica.head == 4
    got = replica.determinants_from_epoch(0, max_out=16)
    assert [d.value for d in det.unpack_batch(got)] == [1, 2, 3, 9]
    # fully-stale delta is a no-op
    assert replica.merge_delta(rows_of([1, 2]), abs_start=0)
    assert replica.head == 4


def test_merge_delta_gap_rejected():
    """A gapped delta (abs_start > head) is rejected, not absorbed at wrong
    offsets; the caller re-requests from head."""
    replica = clog.ThreadCausalLog(capacity=64, max_epochs=8)
    assert replica.merge_delta(rows_of([1, 2]), abs_start=0)
    ok = replica.merge_delta(rows_of([9, 10]), abs_start=5)  # gap: lost 2..4
    assert not ok
    assert replica.head == 2  # nothing merged
    # full re-send from head succeeds
    assert replica.merge_delta(rows_of([3, 4, 5, 9, 10]), abs_start=2)
    assert replica.head == 7


def test_epoch_index_overflow_detection():
    tl = clog.ThreadCausalLog(capacity=64, max_epochs=4)
    for e in range(4):
        tl.start_epoch(e)
        tl.append_rows(rows_of([e]))
    assert not bool(clog.epoch_index_overflowed(tl.state))
    tl.start_epoch(4)  # 5 live epochs, slot of epoch 0 overwritten
    assert bool(clog.epoch_index_overflowed(tl.state))
    tl.notify_checkpoint_complete(0)
    assert not bool(clog.epoch_index_overflowed(tl.state))


def test_rebase_preserves_content():
    tl = clog.ThreadCausalLog(capacity=8, max_epochs=8)
    tl.start_epoch(0)
    tl.append_rows(rows_of([0, 1, 2, 3, 4]))       # head 5
    tl.start_epoch(1)
    tl.notify_checkpoint_complete(0)               # tail 5
    tl.append_rows(rows_of([10, 11, 12, 13]))      # head 9
    tl.start_epoch(2)                              # starts at 9
    tl.append_rows(rows_of([20, 21]))              # head 11
    tl.notify_checkpoint_complete(1)               # tail 9
    before = det.unpack_batch(tl.determinants_from_epoch(2, max_out=8))
    assert [d.value for d in before] == [20, 21]
    # coordinated rebase: amount is a multiple of capacity, <= tail
    tl.state = clog.rebase(tl.state, 8)
    assert tl.tail == 1 and tl.head == 3
    after = det.unpack_batch(tl.determinants_from_epoch(2, max_out=8))
    assert before == after
    assert not bool(clog.near_offset_wrap(tl.state))


def test_slice_from_respects_tail():
    tl = clog.ThreadCausalLog(capacity=16, max_epochs=4)
    tl.start_epoch(0)
    tl.append_rows(rows_of([1, 2]))
    tl.start_epoch(1)
    tl.append_rows(rows_of([3]))
    tl.notify_checkpoint_complete(0)
    buf, count, start = clog.slice_from(tl.state, 0, 8)
    # request below tail gets clamped to tail
    assert int(start) == 2 and int(count) == 1


def test_stacked_vmap_append_and_slice():
    logs = [clog.create(32, 4) for _ in range(4)]
    stacked = clog.stack_logs(logs)
    batch = jnp.stack([jnp.asarray(rows_of([i, i + 1]), jnp.int32)
                       for i in range(4)])
    counts = jnp.array([2, 1, 2, 0], jnp.int32)
    stacked = clog.v_append(stacked, batch, counts)
    np.testing.assert_array_equal(np.asarray(stacked.head), [2, 1, 2, 0])
    bufs, cnts, starts = clog.v_slice_from(
        stacked, jnp.zeros(4, jnp.int32), 8)
    np.testing.assert_array_equal(np.asarray(cnts), [2, 1, 2, 0])
    per = clog.unstack_logs(stacked)
    assert int(per[1].head) == 1


def test_append_under_jit_scan():
    """Appends inside lax.scan (the real hot-path shape)."""
    state = clog.create(64, 8)

    def step(s, v):
        row = jnp.zeros((det.NUM_LANES,), jnp.int32)
        row = row.at[det.LANE_TAG].set(det.RNG).at[det.LANE_P].set(v)
        return clog.append_one(s, row), None

    state, _ = jax.jit(lambda s: jax.lax.scan(step, s, jnp.arange(10, dtype=jnp.int32)))(state)
    assert int(state.head) == 10
    buf, count, _ = clog.slice_from(state, 0, 16)
    assert [d.value for d in det.unpack_batch(np.asarray(buf)[:int(count)])] == list(range(10))
