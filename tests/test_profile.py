"""Overhead attribution profiler, metrics history, ledger compaction,
and the ``clonos_tpu top`` cluster view (obs/profile.py, obs/history.py,
cli.py).

The paper's headline overhead claim (causal logging costs a few percent
of steady-state throughput) is measured here as a first-class runtime
metric: section timers attribute each superstep's wall between user
compute and fault-tolerance machinery, rolled up per epoch into
``overhead.ft-fraction``. All of it is opt-in — the default NullProfiler
must add nothing to the hot path, like NullTracer and NullAuditor.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from clonos_tpu import obs
from clonos_tpu.obs import profile as prof_mod
from clonos_tpu.utils import metrics as met

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _null_obs_after():
    """Every test leaves the process-global tracer/auditor/profiler
    off."""
    yield
    obs.reset()
    obs.reset_audit()
    obs.reset_profile()


def _small_job(name):
    from clonos_tpu.api.environment import StreamEnvironment
    env = StreamEnvironment(name=name, num_key_groups=8)
    (env.synthetic_source(vocab=11, batch_size=4, parallelism=2)
        .key_by()
        .window_count(num_keys=11, window_size=1 << 30)
        .sink())
    return env.build()


# --- profiler unit behavior --------------------------------------------------


def test_null_profiler_default_zero_overhead():
    """Default process profiler is the Null one: sections are a shared
    no-op context manager, ``fence`` passes values through untouched
    (no device sync), and every aggregate reads as zero."""
    p = obs.get_profiler()
    assert isinstance(p, obs.NullProfiler) and not p.enabled
    assert p.section("roll") is p.section("truncate"), \
        "null sections are one shared object — no per-call allocation"
    with p.section("anything"):
        pass
    sentinel = object()
    assert p.fence(sentinel) is sentinel
    p.observe("roll", 1.0)
    assert p.rollup() == 0.0 and p.ft_fraction() == 0.0
    assert p.lifetime() == {} and p.lifetime_ft_fraction() == 0.0


def test_profiler_attribution_rollup_and_binding():
    """FT fraction = ft seconds / total attributed seconds per rollup
    window; histograms and the gauge land in the bound metric group."""
    t = [0.0]
    p = prof_mod.Profiler(clock=lambda: t[0], fence_device=False)
    reg = met.MetricRegistry()
    g = reg.group("job.t")
    p.bind(g)

    with p.section("compute", kind=prof_mod.COMPUTE):
        t[0] += 3.0
    with p.section("roll"):
        t[0] += 0.5
    with p.section("digest-seal"):
        t[0] += 0.5
    assert p.rollup() == pytest.approx(0.25)
    assert p.ft_fraction() == pytest.approx(0.25)

    # Second window: only FT work -> fraction 1.0; empty windows keep
    # the last real fraction instead of snapping the gauge to zero.
    with p.section("truncate"):
        t[0] += 1.0
    assert p.rollup() == pytest.approx(1.0)
    assert p.rollup() == pytest.approx(1.0), "empty window keeps last"

    snap = reg.snapshot()
    assert snap["job.t.overhead.ft-fraction"] == pytest.approx(1.0)
    assert snap["job.t.overhead.roll-ms"]["count"] == 1
    assert snap["job.t.overhead.roll-ms"]["mean"] == pytest.approx(500.0)
    assert snap["job.t.overhead.compute-ms"]["count"] == 1
    # Lifetime spans both windows: 2s FT of 5s total.
    assert p.lifetime_ft_fraction() == pytest.approx(0.4)
    assert p.lifetime()["compute"] == pytest.approx(3.0)


def test_profiled_run_exposes_ft_fraction(tmp_path):
    """A profiled runner attributes real epochs: the per-epoch rollup
    lands in the registry as ``overhead.ft-fraction`` with the
    per-section histograms beside it."""
    from clonos_tpu.runtime.cluster import ClusterRunner

    obs.configure_profile()
    r = ClusterRunner(_small_job("prof"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"), audit=True)
    assert r.profiler.enabled
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    snap = r.metrics.snapshot()
    frac = snap["job.prof.overhead.ft-fraction"]
    assert 0.0 < frac < 1.0, \
        "an epoch has both compute and FT sections attributed"
    assert snap["job.prof.overhead.compute-ms"]["count"] == 2
    assert snap["job.prof.overhead.roll-ms"]["count"] == 2
    assert snap["job.prof.overhead.snapshot-ms"]["count"] >= 1
    assert snap["job.prof.overhead.digest-seal-ms"]["count"] >= 1
    life = r.profiler.lifetime()
    assert life["compute"] > 0 and life["roll"] > 0


def test_disabled_run_adds_no_overhead_keys(tmp_path):
    """Profiling off (the default): no overhead.* metric exists —
    the instrumented call sites register nothing."""
    from clonos_tpu.runtime.cluster import ClusterRunner

    r = ClusterRunner(_small_job("noprof"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"))
    assert not r.profiler.enabled
    r.run_epoch(complete_checkpoint=True)
    snap = r.metrics.snapshot()
    assert not [k for k in snap if ".overhead." in k]


def test_profile_config_option_enables_via_from_config(tmp_path):
    """``observability.profile.enabled`` is the config-file gate."""
    from clonos_tpu.config.options import Configuration
    from clonos_tpu.runtime.cluster import ClusterRunner

    cfg = Configuration()
    cfg.set_raw("observability.profile.enabled", True)
    r = ClusterRunner.from_config(_small_job("cfgprof"), cfg,
                                  steps_per_epoch=8, log_capacity=512,
                                  max_epochs=8, inflight_ring_steps=32,
                                  seed=3)
    assert r.profiler.enabled


def test_profile_context_rides_deploy_headers():
    """DEPLOY-header convention like trace/audit: a profiling JobMaster
    stamps ``profile`` so deployed runners inherit; disabled adds no
    wire fields at all."""
    from clonos_tpu.parallel import transport as tp

    h = tp.attach_profile({})
    assert h == {}, "disabled profiler leaves wire bytes identical"
    tp.adopt_profile(h)
    assert not obs.get_profiler().enabled

    obs.configure_profile()
    h = tp.attach_profile({})
    assert h == {"profile": True}
    obs.reset_profile()
    tp.adopt_profile(h)
    assert obs.get_profiler().enabled


# --- finalize attribution ----------------------------------------------------


def test_recover_finalize_subspans_partition_finalize(tmp_path):
    """The finalize mystery, attributable: ``recover()`` splits its
    finalize phase into named sub-spans that are in ``phase_ms`` AND
    account for the recorded finalize total (within 10%), each emitted
    as a span under the recovery's trace id. With the overlapped tail,
    sub-spans keep their true wall durations and the concurrency gain
    is surfaced as ``finalize.overlap-saved`` — so the identity is
    sum(sub-spans) - overlap-saved == finalize (overlap is attributed,
    never hidden)."""
    from clonos_tpu.runtime.cluster import ClusterRunner

    tr = obs.configure("runner")
    r = ClusterRunner(_small_job("fin"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"))
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    r.inject_failure([2 + 1])
    report = r.recover()
    pm = report.phase_ms
    assert "finalize" in pm
    subs = {k: v for k, v in pm.items() if k.startswith("finalize.")}
    saved = subs.pop("finalize.overlap-saved")
    assert set(subs) == {"finalize.barrier-read",
                        "finalize.state-verify"}
    assert saved >= 0.0
    assert sum(subs.values()) - saved == pytest.approx(
        pm["finalize"], rel=0.10, abs=0.5)
    recs = tr.records()
    recovery = next(x for x in recs if x["name"] == "recovery")
    for name in ("recovery.finalize.barrier-read",
                 "recovery.finalize.state-verify"):
        span = next(x for x in recs if x["name"] == name)
        assert span["trace"] == recovery["trace"]

    # The sequential control path is still reachable and keeps the old
    # strict partition — and never writes the overlap key, so its
    # absence marks a control run.
    r.inject_failure([2 + 1])
    ctrl = r.recover(overlap_finalize=False)
    cm = ctrl.phase_ms
    csubs = {k: v for k, v in cm.items() if k.startswith("finalize.")}
    assert "finalize.overlap-saved" not in csubs
    assert sum(csubs.values()) == pytest.approx(cm["finalize"],
                                                rel=0.10, abs=0.5)


# --- ledger compaction -------------------------------------------------------


def test_compact_ledger_entries_last_wins_below_fence():
    from clonos_tpu.runtime.checkpoint import compact_ledger_entries

    e = lambda ep, tag: {"epoch": ep, "combined": tag}
    entries = [e(0, "a"), e(1, "b"), e(0, "a2"),       # re-sealed epoch 0
               e(2, "c"), {"weird": True}, e(1, "b2"), e(2, "c2")]
    out = compact_ledger_entries(entries, below_epoch=2)
    # Below the fence: one per epoch, last wins, epoch order. At/above
    # (and unparseable): verbatim in append order, after them.
    assert out == [e(0, "a2"), e(1, "b2"),
                   e(2, "c"), {"weird": True}, e(2, "c2")]
    assert compact_ledger_entries(entries, below_epoch=0) == entries


def test_checkpoint_completion_compacts_ledger(tmp_path):
    """Completion-driven compaction keeps a long run's ledger bounded:
    duplicates below the completed fence collapse to one line per
    epoch in ledger.jsonl, resolved last-wins like the readers do."""
    from clonos_tpu.runtime.cluster import ClusterRunner

    r = ClusterRunner(_small_job("cmp"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"), audit=True)
    r.run_epoch(complete_checkpoint=True)     # seals + completes epoch 0
    # A rebuilt runner re-seals replayed epochs: simulate the duplicate
    # appends a few recoveries would leave behind.
    dup = dict(r.coordinator.read_ledger()[0])
    dup["combined"] = "resealed-last"
    for _ in range(3):
        r.coordinator.storage.write_ledger(dup)
    assert len(r.coordinator.read_ledger()) == 4
    r.run_epoch(complete_checkpoint=True)     # fence moves past epoch 0
    entries = r.coordinator.read_ledger()
    by_epoch = [e["epoch"] for e in entries]
    assert by_epoch.count(0) == 1, "duplicates below the fence collapse"
    assert next(e for e in entries
                if e["epoch"] == 0)["combined"] == "resealed-last"
    # The file itself shrank, not just the parsed view.
    lines = open(str(tmp_path / "ck" / "ledger.jsonl")).read().splitlines()
    assert len(lines) == len(entries)


# --- metrics history ---------------------------------------------------------


def test_metrics_history_ring_torn_tail_and_resume(tmp_path):
    """History samples ring-buffer in memory and append to a JSONL a
    torn final line cannot corrupt; a restarted history resumes from
    the file tail; the file compacts once it outgrows 2*window."""
    path = str(tmp_path / "history.jsonl")
    t = [100.0]
    h = obs.MetricsHistory(sample_fn=lambda: {"x": t[0]}, path=path,
                           interval_s=60.0, window=4,
                           clock=lambda: t[0])
    for _ in range(6):                   # > window: ring drops oldest
        h.sample_once()
        t[0] += 1.0
    assert [r["ts"] for r in h.query()] == [102.0, 103.0, 104.0, 105.0]
    assert [r["ts"] for r in h.query(since=104.0)] == [104.0, 105.0]
    assert [r["ts"] for r in h.query(last=2)] == [104.0, 105.0]
    h.close()

    with open(path, "a") as f:           # SIGKILL artifact
        f.write('{"ts": 999, "metr')
    assert obs.read_history_file(path)[-1]["ts"] == 105.0
    h2 = obs.MetricsHistory(sample_fn=lambda: {}, path=path,
                            interval_s=60.0, window=4,
                            clock=lambda: t[0])
    assert [r["ts"] for r in h2.query()] == [102.0, 103.0, 104.0, 105.0]
    # Push past 2*window file lines: compaction rewrites to ring size.
    for _ in range(6):
        h2.sample_once()
        t[0] += 1.0
    h2.close()
    assert len(open(path).read().splitlines()) <= 2 * 4
    recs = obs.read_history_file(path)
    ts = [r["ts"] for r in recs]
    assert ts == sorted(ts) and ts[-1] == 111.0


def test_history_endpoint_serves_samples_under_concurrent_scrapes():
    """Endpoint integration: /metrics/history.json grows while /metrics
    is scraped concurrently; exposition keeps # HELP/# TYPE; history
    timestamps are monotone and ?last= windows the payload."""
    reg = met.MetricRegistry()
    reg.group("job.t").counter("things").inc(5)
    hist = obs.MetricsHistory(interval_s=0.05, window=64)
    ep = met.MetricsEndpoint(reg, history=hist)
    host, port = ep.address
    base = f"http://{host}:{port}"
    errors = []

    def scrape_loop():
        try:
            for _ in range(20):
                txt = urllib.request.urlopen(base + "/metrics").read()
                assert b"# HELP" in txt and b"# TYPE" in txt
                assert b"job_t_things 5" in txt
        except Exception as e:           # surfaced on the main thread
            errors.append(e)

    scraper = threading.Thread(target=scrape_loop)
    scraper.start()
    try:
        deadline = time.monotonic() + 20
        samples = []
        while len(samples) < 2:
            assert time.monotonic() < deadline, "sampler never produced"
            js = json.loads(urllib.request.urlopen(
                base + "/metrics/history.json").read())
            samples = js["samples"]
            time.sleep(0.02)
        ts = [s["ts"] for s in samples]
        assert ts == sorted(ts), "ring order means monotone timestamps"
        assert all(s["metrics"]["job.t.things"] == 5 for s in samples)
        js = json.loads(urllib.request.urlopen(
            base + "/metrics/history.json?last=1").read())
        assert len(js["samples"]) == 1
        assert js["samples"][0]["ts"] == max(ts) or \
            js["samples"][0]["ts"] > max(ts)     # sampler kept running
    finally:
        scraper.join()
        ep.close()
    assert not errors
    assert not hist.started or hist._thread is None, \
        "endpoint owns the history it started: close() stopped it"


# --- audit --report json (CI convention) -------------------------------------


def test_audit_report_json_exit_codes(tmp_path, capsys):
    from clonos_tpu.cli import main
    from clonos_tpu.obs.digest import EpochDigest

    def write_ledger(dirpath, entries):
        os.makedirs(dirpath, exist_ok=True)
        with open(os.path.join(dirpath, "ledger.jsonl"), "w") as f:
            for e in entries:
                f.write(json.dumps(e) + "\n")

    def entry(epoch, payload):
        d = EpochDigest(epoch)
        d.fold("ring/v2", payload, 4)
        return d.to_entry()

    run1 = tmp_path / "run1"
    run2 = tmp_path / "run2"
    write_ledger(str(run1 / "g0"), [entry(0, b"aa"), entry(1, b"bb")])
    write_ledger(str(run2 / "g0"), [entry(0, b"aa"), entry(1, b"XX")])

    assert main(["audit", str(run1), "--diff", str(run1),
                 "--report", "json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["match"] is True and js["problems"] == []
    assert js["groups"]["g0/ledger.jsonl"]["entries"] == 2

    assert main(["audit", str(run1), "--diff", str(run2),
                 "--report", "json"]) == 1
    js = json.loads(capsys.readouterr().out)
    assert js["match"] is False
    assert any("epoch 1" in p for p in js["problems"])
    assert js["groups"]["g0/ledger.jsonl"]["problems"]

    assert main(["audit", str(run1), "--report", "json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["match"] is True and js["groups"]

    assert main(["audit", str(tmp_path / "absent"),
                 "--report", "json"]) == 1
    assert json.loads(capsys.readouterr().out)["match"] is False


# --- clonos_tpu top ----------------------------------------------------------


_TOP_SNAP = {
    "worker.w-0.slots": 2,
    "worker.w-0.group.g0.job.b.audit.epochs-sealed": 5,
    "worker.w-0.group.g0.job.b.audit.epochs-validated": 3,
    "worker.w-0.group.g0.job.b.backpressure.inflight-occupancy": 0.25,
    "worker.w-0.group.g0.job.b.causal-log.max-occupancy": 0.5,
    "worker.w-0.group.g0.job.b.recovery.replay-lag-steps": 7,
    "worker.w-0.group.g0.job.b.overhead.ft-fraction": 0.031,
    "worker.w-0.group.g0.job.b.recovery.finalize-ms":
        {"count": 2, "mean": 450.0, "p50": 448.0, "p99": 460.0},
    "worker.w-1.slots": 1,
    "worker.w-1.group.g1.job.b.audit.epochs-sealed": 4,
    "cluster.audit.exactly-once-ok": 1,
    "cluster.overhead.ft-fraction-max": 0.031,
}


def test_top_table_parses_cluster_snapshot():
    from clonos_tpu.cli import _top_rows, _top_table

    rows = _top_rows(_TOP_SNAP)
    assert set(rows) == {"w-0", "w-1"}
    r0 = rows["w-0"]
    assert r0["slots"] == 2 and r0["sealed"] == 5 and \
        r0["validated"] == 3
    assert r0["ring"] == 0.5, "max over ring occupancy gauges"
    assert r0["lag"] == 7 and r0["ft"] == 0.031
    assert r0["phases"] == {"finalize": 448.0}
    assert rows["w-1"]["slots"] == 1 and rows["w-1"]["ft"] is None

    table = _top_table(_TOP_SNAP)
    lines = table.splitlines()
    assert lines[0].split()[:4] == ["WORKER", "SLOTS", "GROUPS",
                                    "SEALED"]
    w0 = next(l for l in lines if l.startswith("w-0"))
    cols = w0.split()
    assert cols[1] == "2" and cols[3] == "5" and cols[7] == "3.10"
    assert "finalize=448" in w0
    assert next(l for l in lines if l.startswith("w-1")).split()[1] == "1"
    assert "ft-fraction-max=0.031" in table


@pytest.mark.slow
def test_top_once_against_live_endpoint(capsys):
    """Smoke: ``clonos_tpu top --once`` renders every worker row from a
    live MetricsEndpoint serving a cluster snapshot."""
    from clonos_tpu import cli

    reg = met.MetricRegistry()
    ep = met.MetricsEndpoint(reg, extra=lambda: dict(_TOP_SNAP))
    try:
        host, port = ep.address
        rc = cli.main(["top", f"{host}:{port}", "--once"])
    finally:
        ep.close()
    assert rc == 0
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.strip()]
    assert lines[0].startswith("WORKER")
    for eid in ("w-0", "w-1"):
        assert any(l.startswith(eid) for l in lines), \
            f"every worker gets a row ({eid})"
    assert "cluster:" in out
