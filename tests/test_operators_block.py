"""process_block must be bit-identical to scanning process over the block
(the vectorized hot path vs the per-superstep semantic definition)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.api.operators import (
    BlockContext, HostFeedSource, IntervalJoinOperator, KeyedReduceOperator,
    MapOperator, Operator, SinkOperator, SyntheticSource,
    TumblingWindowCountOperator, UnionOperator,
)
from clonos_tpu.api.records import RecordBatch, zero_invalid


K, P, B, NK = 7, 3, 8, 13


def _bctx(times=None):
    t = jnp.asarray(times if times is not None
                    else np.arange(K) * 3, jnp.int32)
    return BlockContext(
        times=t, rng_bits=jnp.arange(K, dtype=jnp.int32) + 100,
        epoch=jnp.zeros((), jnp.int32), step0=jnp.zeros((), jnp.int32),
        subtask=jnp.arange(P, dtype=jnp.int32))


def _batches(seed=0):
    rng = np.random.RandomState(seed)
    keys = rng.randint(0, NK, (K, P, B)).astype(np.int32)
    vals = rng.randint(1, 5, (K, P, B)).astype(np.int32)
    ts = rng.randint(0, 50, (K, P, B)).astype(np.int32)
    valid = rng.rand(K, P, B) < 0.7
    return zero_invalid(RecordBatch(
        jnp.asarray(keys), jnp.asarray(vals), jnp.asarray(ts),
        jnp.asarray(valid)))


def _scan_reference(op, state, batches, bctx):
    """The semantic definition: lax.scan of the per-step process."""
    return Operator.process_block(op, state, batches, bctx)


def _assert_equal(a, b):
    fa, ta = jax.tree_util.tree_flatten(a)
    fb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


@pytest.mark.parametrize("op,needs_batch", [
    (SyntheticSource(vocab=11, batch_size=B), False),
    (SyntheticSource(vocab=11, batch_size=B, rate_limit=5), False),
    (MapOperator(lambda k, v, t: (k + 1, v * 2, t)), True),
    (KeyedReduceOperator(num_keys=NK), True),
    (TumblingWindowCountOperator(num_keys=NK, window_size=5), True),
    (HostFeedSource(batch_size=B), True),
    (SinkOperator(), True),
])
def test_block_equals_scan(op, needs_batch):
    state = op.init_state(P)
    batches = _batches() if needs_batch else zero_invalid(RecordBatch(
        jnp.zeros((K, P, B), jnp.int32), jnp.zeros((K, P, B), jnp.int32),
        jnp.zeros((K, P, B), jnp.int32), jnp.zeros((K, P, B), jnp.bool_)))
    bctx = _bctx()
    ref_state, ref_out = jax.jit(
        lambda s, b, c: _scan_reference(op, s, b, c))(state, batches, bctx)
    blk_state, blk_out = jax.jit(op.process_block)(state, batches, bctx)
    _assert_equal(ref_state, blk_state)
    _assert_equal(ref_out, blk_out)


def test_window_block_fires_like_stepwise():
    # Times that cross window boundaries mid-block (incl. repeated windows).
    op = TumblingWindowCountOperator(num_keys=NK, window_size=10)
    state = op.init_state(P)
    batches = _batches(3)
    bctx = _bctx(times=[0, 4, 12, 13, 25, 26, 27])
    ref = jax.jit(lambda s, b, c: _scan_reference(op, s, b, c))(
        state, batches, bctx)
    blk = jax.jit(op.process_block)(state, batches, bctx)
    _assert_equal(ref, blk)
    # Something actually fired.
    assert int(jnp.sum(blk[1].valid)) > 0


def test_reduce_static_keys_equals_dynamic():
    """The static-gather aggregation (StaticRoutePlan-fed input) must be
    bit-identical to the dynamic process_block on the same batch."""
    rng = np.random.RandomState(5)
    # Static layout: each slot is bound to a fixed key; some slots unmapped.
    slot_keys = rng.randint(-1, NK, size=(P, B)).astype(np.int32)
    keys = np.broadcast_to(np.clip(slot_keys, 0, NK - 1), (K, P, B)).copy()
    vals = rng.randint(1, 9, size=(K, P, B)).astype(np.int32)
    valid = (rng.rand(K, P, B) < 0.6) & (slot_keys >= 0)[None]
    batch = zero_invalid(RecordBatch(
        jnp.asarray(keys), jnp.asarray(vals),
        jnp.zeros((K, P, B), jnp.int32), jnp.asarray(valid)))
    op = KeyedReduceOperator(num_keys=NK)
    state = op.init_state(P)
    bctx = _bctx()
    dyn = jax.jit(op.process_block)(state, batch, bctx)
    sta = jax.jit(lambda s, b, c: op.process_block_static_keys(
        s, b, c, slot_keys))(state, batch, bctx)
    _assert_equal(dyn, sta)


def test_two_input_union_block_equals_scan():
    op = UnionOperator(capacity=2 * B)
    left, right = _batches(1), _batches(2)
    bctx = _bctx()
    from clonos_tpu.api.operators import TwoInputOperator
    ref = jax.jit(lambda s, b, c: TwoInputOperator.process_block(
        op, s, b, c))((), (left, right), bctx)
    blk = jax.jit(op.process_block)((), (left, right), bctx)
    _assert_equal(ref[1], blk[1])


def test_interval_join_grouped_block_equals_scan():
    """The grouped join block (G steps fused per scan iteration) must be
    bit-identical to the sequential per-step semantics — state (ring
    contents, cursors) AND per-step output batches, including intra-step
    overflow drops, ring-slot emission order and cross-group windows."""
    from clonos_tpu.api.operators import TwoInputOperator
    for seed, cap, w, kk in ((0, 16, 4, 8), (1, 4, 2, 8), (2, 8, 1, 6),
                             (3, 64, 3, 12)):
        op = IntervalJoinOperator(num_keys=NK, window=w, interval=20,
                                  capacity=cap)
        rng = np.random.RandomState(seed)

        def mk(b):
            return zero_invalid(RecordBatch(
                jnp.asarray(rng.randint(0, NK, (kk, P, b)), jnp.int32),
                jnp.asarray(rng.randint(1, 5, (kk, P, b)), jnp.int32),
                jnp.asarray(rng.randint(0, 60, (kk, P, b)), jnp.int32),
                jnp.asarray(rng.rand(kk, P, b) < 0.6)))
        left, right = mk(B), mk(B)
        state = op.init_state(P)
        t = jnp.asarray(np.arange(kk) * 3, jnp.int32)
        bctx = BlockContext(
            times=t, rng_bits=t + 100, epoch=jnp.zeros((), jnp.int32),
            step0=jnp.zeros((), jnp.int32),
            subtask=jnp.arange(P, dtype=jnp.int32))
        ref = jax.jit(lambda s, l, r, c: TwoInputOperator.process_block(
            op, s, (l, r), c))(state, left, right, bctx)
        blk = jax.jit(lambda s, l, r, c: op.process_block(
            s, (l, r), c))(state, left, right, bctx)
        _assert_equal(ref[0], blk[0])
        _assert_equal(ref[1], blk[1])
