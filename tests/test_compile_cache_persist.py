"""Compile-cache persistence across process restarts (PR12 tentpole
part 1): a fresh subprocess restoring the same job must HIT the
persistent cache its predecessor wrote — the restarted standby pays
cache-deserialize, not XLA recompile, for the first-step executable.
Namespacing (sharded vs unsharded) must keep distinct cache universes.
"""

import json
import os
import subprocess
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One restore cycle of a tiny job in a clean interpreter: build the
# runner with compile_cache_dir wired (the ctor enables the cache BEFORE
# the executor compiles), run an epoch, AOT-lower the first-step
# program, report timings + the persistent entry census.
_PROBE = """
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
cache, ck = sys.argv[1], sys.argv[2]
from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.runtime.cluster import ClusterRunner
env = StreamEnvironment(name="persist", num_key_groups=8)
(env.synthetic_source(vocab=7, batch_size=4, parallelism=1)
    .key_by().window_count(num_keys=7, window_size=1 << 30).sink())
t0 = time.monotonic()
r = ClusterRunner(env.build(), steps_per_epoch=4, log_capacity=256,
                  max_epochs=8, inflight_ring_steps=16, seed=5,
                  checkpoint_dir=ck,
                  compile_cache_dir=None if cache == "NONE" else cache)
r.run_epoch(complete_checkpoint=True)
build_s = time.monotonic() - t0
from clonos_tpu.utils.compile_cache import aot_lower_first_step
t0 = time.monotonic()
exe = aot_lower_first_step(r.executor)
aot_s = time.monotonic() - t0
entries = (sorted(f for f in os.listdir(cache) if f.endswith("-cache"))
           if cache != "NONE" else [])
print(json.dumps({"aot_s": aot_s, "build_s": build_s,
                  "ok": exe is not None, "entries": entries}))
"""


def _run_probe(cache_dir, ck_dir):
    env = dict(os.environ, PYTHONPATH=REPO,
               CLONOS_COMPILE_CACHE_MIN_S="0")  # tiny job: persist all
    out = subprocess.run(
        [sys.executable, "-c", _PROBE, str(cache_dir), str(ck_dir)],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_fresh_process_hits_persistent_cache(tmp_path):
    """Restart cycle: process 1 populates the shared cache dir, process
    2 (same job, fresh interpreter) must add ZERO new entries — every
    compile, including the AOT first-step lower, was a cache hit — and
    its first-step compile must cost a fraction of the cold-cache
    control's (a third subprocess against its own empty dir)."""
    shared = tmp_path / "cache"
    shared.mkdir()
    p1 = _run_probe(shared, tmp_path / "ck1")
    assert p1["ok"] and p1["entries"], \
        "first process must populate the persistent cache"
    p2 = _run_probe(shared, tmp_path / "ck2")
    assert p2["ok"]
    assert p2["entries"] == p1["entries"], \
        "restarted process recompiled (new persistent entries appeared)"

    # Cold-cache control: no persistent cache at all, so the AOT
    # first-step lower pays the full XLA compile (with a cache, even a
    # FIRST process's AOT hits entries its own ctor just wrote).
    p3 = _run_probe("NONE", tmp_path / "ck3")
    # The satellite's threshold: warm first-step compile well under the
    # cold control (measured ~0.09s vs ~1.0s; 0.6 leaves CI headroom).
    assert p2["aot_s"] < 0.6 * p3["aot_s"], \
        f"warm aot {p2['aot_s']:.3f}s not below 0.6x cold {p3['aot_s']:.3f}s"


def test_sharded_and_unsharded_namespaces_never_collide(tmp_path):
    """The unsharded program uses the bare cache dir; a mesh-sharded
    twin gets a fingerprint-keyed subdirectory, and refining with the
    carry's PartitionSpec pytree moves it again — three distinct
    universes, so executables can never cross sharding boundaries."""
    from clonos_tpu.utils.compile_cache import (enable_compile_cache,
                                                sharding_cache_key)

    prev = jax.config.jax_compilation_cache_dir
    try:
        root = str(tmp_path / "ns")
        bare = enable_compile_cache(root)
        assert bare == root

        mesh = jax.sharding.Mesh(jax.devices(), ("task",))
        meshed = enable_compile_cache(root, mesh=mesh)
        assert meshed.startswith(root) and meshed != bare

        specs = {"w": jax.sharding.PartitionSpec("task")}
        refined = enable_compile_cache(root, mesh=mesh, specs=specs)
        assert refined.startswith(root)
        assert len({bare, meshed, refined}) == 3

        # the key function itself: stable, and sharding-sensitive
        assert sharding_cache_key(mesh) == sharding_cache_key(mesh)
        assert sharding_cache_key(mesh) != sharding_cache_key(mesh, specs)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
