"""Cross-host control plane: a REAL two-process test — a worker process
runs a job and serves its determinant logs over TCP; this process acts as
the JobMaster + a standby-host mirror (registration, heartbeats,
delta fetch/merge with the wire serde, and failure detection when the
worker dies). Reference analogs: AkkaRpcService typed gateways,
DeterminantRequest/ResponseEvent, heartbeat JM<->TM."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from clonos_tpu.causal import serde
from clonos_tpu.runtime.remote import JobMasterServer, RemoteReplicaMirror

WORKER = r"""
import json, os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.runtime.cluster import ClusterRunner
from clonos_tpu.runtime.remote import HostLogEndpoint, TaskExecutorClient

env = StreamEnvironment(name="remote-job", num_key_groups=8)
(env.synthetic_source(vocab=13, batch_size=4, parallelism=2)
    .key_by().window_count(num_keys=13, window_size=1 << 30).sink())
r = ClusterRunner(env.build(), steps_per_epoch=4, log_capacity=256,
                  max_epochs=8, seed=3)
ep = HostLogEndpoint(r.executor)
tx = TaskExecutorClient("worker-0", (sys.argv[1], int(sys.argv[2])),
                        interval_s=0.2)
r.run_epoch(complete_checkpoint=False)
ep.refresh()                               # snapshot on the main thread
print(json.dumps({{"port": ep.address[1],
                   "heads": np.asarray(
                       r.executor.carry.logs.head).tolist()}}), flush=True)
for line in sys.stdin:                     # step on command
    if line.strip() == "epoch":
        r.run_epoch(complete_checkpoint=False)
        ep.refresh()
        print(json.dumps({{"heads": np.asarray(
            r.executor.carry.logs.head).tolist()}}), flush=True)
    elif line.strip() == "rows":
        import jax
        one = jax.tree_util.tree_map(lambda x: x[1],
                                     r.executor.carry.logs)
        head = int(one.head)
        print(json.dumps({{"rows": np.asarray(
            one.rows)[:head].tolist()}}), flush=True)
    else:
        break
"""


@pytest.fixture
def jm():
    s = JobMasterServer(heartbeat_timeout_s=1.0)
    yield s
    s.close()


def test_two_process_register_mirror_and_failure_detection(jm):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", WORKER.format(repo=repo),
         jm.address[0], str(jm.address[1])],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env)
    try:
        hello = json.loads(proc.stdout.readline())
        port = hello["port"]
        # (1) registration + heartbeats arrived.
        deadline = time.monotonic() + 10
        while "worker-0" not in jm.registered():
            assert time.monotonic() < deadline
            time.sleep(0.05)
        assert jm.expired() == []

        # (2) standby-host mirror: fetch + merge the worker's device log
        # deltas over TCP; mirror head matches the worker's.
        mirror = RemoteReplicaMirror(("127.0.0.1", port), flats=[1, 2],
                                     capacity=256, max_epochs=8)
        absorbed = mirror.sync()
        assert absorbed > 0
        assert mirror.head(1) == hello["heads"][1]

        # (3) incremental: another epoch, another sync — offset-dedup
        # absorbs only the fresh suffix.
        proc.stdin.write("epoch\n")
        proc.stdin.flush()
        heads2 = json.loads(proc.stdout.readline())["heads"]
        before = {f: mirror.head(f) for f in (1, 2)}
        absorbed2 = mirror.sync()
        assert mirror.head(1) == heads2[1]
        assert absorbed2 == sum(heads2[f] - before[f] for f in (1, 2))
        # (bit-identity of the mirrored bytes)
        proc.stdin.write("rows\n")
        proc.stdin.flush()
        worker_rows = np.asarray(
            json.loads(proc.stdout.readline())["rows"], np.int32)
        np.testing.assert_array_equal(mirror.rows(1), worker_rows)

        # (4) kill the worker: the JobMaster's deadline heartbeat monitor
        # reports it failed.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        deadline = time.monotonic() + 5
        while "worker-0" not in jm.expired():
            assert time.monotonic() < deadline, "missed-heartbeat not seen"
            time.sleep(0.1)
    finally:
        if proc.poll() is None:
            proc.kill()


def test_delta_serde_roundtrip_flat_and_grouped():
    rng = np.random.RandomState(0)
    deltas = [(5, 100, rng.randint(-9, 9, (7, 8)).astype(np.int32)),
              (6, 40, rng.randint(-9, 9, (3, 8)).astype(np.int32)),
              (9, 0, np.zeros((0, 8), np.int32))]
    for enc in ("flat", "grouped"):
        frame = serde.encode_delta(deltas, encoding=enc,
                                   subtasks_per_vertex=4)
        out = serde.decode_delta(frame, subtasks_per_vertex=4)
        assert [(i, s) for i, s, _ in out] == [(i, s) for i, s, _ in deltas]
        for (_, _, a), (_, _, b) in zip(deltas, out):
            np.testing.assert_array_equal(a, b)


def test_delta_serde_detects_corruption():
    rows = np.arange(16, dtype=np.int32).reshape(2, 8)
    frame = bytearray(serde.encode_delta([(1, 0, rows)]))
    frame[-8] ^= 0xFF                      # flip a row byte
    with pytest.raises(ValueError):
        serde.decode_delta(bytes(frame))


def test_native_codec_matches_python_fallback():
    """When the C++ codec built, its frames must be byte-identical to the
    pure-Python encoder (and CRCs agree)."""
    from clonos_tpu.ops import native
    rng = np.random.RandomState(1)
    rows = rng.randint(-99, 99, (11, 8)).astype(np.int32)
    import zlib
    assert native.crc32(rows) == zlib.crc32(rows.tobytes()) & 0xFFFFFFFF
    if not native.available():
        pytest.skip("no C++ toolchain in this environment")
    deltas = [(3, 17, rows), (4, 0, rows[:5])]
    with_native = serde.encode_delta(deltas)
    native._lib, keep = None, native._lib
    try:
        pure = serde.encode_delta(deltas)
    finally:
        native._lib = keep
    assert with_native == pure

def test_mirror_rebases_across_owner_truncation():
    """When the owner truncates across a completed checkpoint, the mirror
    applies the same truncation (rebase) instead of stalling forever
    (review finding: the gap branch must not silently no-op)."""
    import numpy as np
    from clonos_tpu.parallel import transport as tp
    from clonos_tpu.causal import serde as sd

    class FakeEndpoint:
        """Serves scripted (start, rows) deltas."""
        def __init__(self):
            self.script = []
            self.server = tp.ControlServer(self._handle)
            self.address = self.server.address

        def _handle(self, mtype, payload):
            start, rows = self.script.pop(0)
            hdr = tp.pack_json({"floors": {"1": start}})
            return tp.DETERMINANT_RESPONSE, (
                len(hdr).to_bytes(4, "little") + hdr
                + sd.encode_delta([(1, start, rows)]))

    ep = FakeEndpoint()
    rows1 = np.arange(24, dtype=np.int32).reshape(3, 8)
    rows2 = np.arange(16, dtype=np.int32).reshape(2, 8) + 100
    ep.script = [(0, rows1),
                 (10, rows2)]            # owner truncated [3, 10)
    m = RemoteReplicaMirror(ep.address, flats=[1], capacity=64,
                            max_epochs=8)
    assert m.sync() == 3
    assert m.head(1) == 3
    assert m.sync() == 2                 # gap -> rebase to 10, absorb
    assert m.head(1) == 12
    np.testing.assert_array_equal(m.rows(1), rows2)
    ep.server.close()
    m.close()


def test_mirror_releases_history_at_floor_and_fails_loud_when_undersized():
    """The response's floors (owner truncation points) bound mirror
    memory: rows below them are released — the remote checkpoint-
    complete. A mirror too small for the owner's un-truncated window
    raises instead of wrapping its ring into garbage (review finding)."""
    import numpy as np
    from clonos_tpu.parallel import transport as tp
    from clonos_tpu.causal import serde as sd
    from clonos_tpu.runtime.remote import RemoteReplicaMirror

    class FakeEndpoint:
        def __init__(self):
            self.script = []
            self.server = tp.ControlServer(self._handle)
            self.address = self.server.address

        def _handle(self, mtype, payload):
            floor, start, rows = self.script.pop(0)
            hdr = tp.pack_json({"floors": {"1": floor}})
            return tp.DETERMINANT_RESPONSE, (
                len(hdr).to_bytes(4, "little") + hdr
                + sd.encode_delta([(1, start, rows)]))

    ep = FakeEndpoint()
    mk = lambda n, off: (np.arange(n * 8, dtype=np.int32).reshape(n, 8)
                         + off)
    # Round 1: 6 rows from offset 0, owner floor 0. Round 2: 6 more,
    # owner has truncated below 6 -> mirror releases [0, 6).
    ep.script = [(0, 0, mk(6, 0)), (6, 6, mk(6, 100))]
    m = RemoteReplicaMirror(ep.address, flats=[1], capacity=8,
                            max_epochs=8)
    assert m.sync() == 6
    assert m.sync() == 6
    assert m.head(1) == 12
    np.testing.assert_array_equal(m.rows(1), mk(6, 100))  # floor applied
    # Round 3: owner did NOT truncate (floor stays 6) and serves 6 more:
    # 12 live rows > capacity 8 -> loud failure, not ring corruption.
    ep.script = [(6, 12, mk(6, 200))]
    with pytest.raises(RuntimeError, match="exceed capacity"):
        m.sync()
    ep.server.close()
    m.close()


def test_host_loss_rebuild_from_mirror_and_checkpoint(tmp_path):
    """THE standby-host failover, end to end across two OS processes: a
    worker process runs a job under the JobMaster (cli worker entrypoint
    — registration, heartbeats, durable checkpoints, per-fence log
    service); this process mirrors its determinant logs; the worker is
    SIGKILLed mid-run; heartbeat expiry flags it; the controller rebuilds
    the ENTIRE job here from checkpoint + mirror, and the rebuilt state's
    digest equals the digest the dead worker itself reported at its last
    mirrored fence (cross-process bit-identity). The rebuilt job then
    keeps running and survives a further ordinary task failure.
    Reference analogs: TaskExecutor.java:422 deployment,
    RunStandbyTaskStrategy.java:186-227, DeterminantResponseEvent."""
    from clonos_tpu.runtime.remote import JobMasterController

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    jm = JobMasterServer(heartbeat_timeout_s=1.5)
    ctl = JobMasterController(jm)
    ckdir = os.path.join(str(tmp_path), "ck")
    proc = subprocess.Popen(
        [sys.executable, "-m", "clonos_tpu", "worker",
         "examples.wordcount:build_job",
         "--jm", f"127.0.0.1:{jm.address[1]}",
         "--checkpoint-dir", ckdir,
         "--executor-id", "worker-0",
         "--epochs", "64", "--steps-per-epoch", "8",
         "--complete-every", "3", "--seed", "5",
         "--heartbeat-interval", "0.3", "--epoch-sleep", "0.05"],
        cwd=repo, env=env, stdout=subprocess.PIPE, text=True)
    digests = {}
    try:
        first = json.loads(proc.stdout.readline())
        assert first["registered"] == "worker-0"
        assert ctl.attach() == ["worker-0"]
        last_step = None
        for line in iter(proc.stdout.readline, ""):
            st = json.loads(line)
            ctl.sync()                 # pull the fence's delta
            digests[st["global_step"]] = st["digest"]
            last_step = st["global_step"]
            if st["epoch"] >= 7:       # ckpts 0,3,6 completed by now
                break
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        # Drain lines the worker printed before dying — the mirror may
        # hold fences past the last line read pre-kill.
        for line in proc.stdout:
            try:
                st = json.loads(line)
                digests[st["global_step"]] = st["digest"]
            except ValueError:
                break

        deadline = time.monotonic() + 6
        while time.monotonic() < deadline and "worker-0" not in ctl.failed():
            time.sleep(0.1)
        assert "worker-0" in ctl.failed()

        import examples.wordcount as wc
        runner, report = ctl.rebuild("worker-0", wc.build_job(),
                                     steps_per_epoch=8, seed=5)
        assert runner.global_step in digests
        assert runner.state_digest() == digests[runner.global_step], (
            "rebuilt state diverges from the dead worker's reported "
            "digest")
        assert report.steps_replayed == runner.global_step - \
            runner._fence_step[report.from_epoch]
        # The rebuilt job is LIVE: runs on, checkpoints, and survives an
        # ordinary single-task failure through the normal protocol.
        runner.run_epoch(complete_checkpoint=True)
        runner.run_epoch(complete_checkpoint=False)
        runner.inject_failure([5])
        runner.recover()
    finally:
        if proc.poll() is None:
            proc.kill()
        ctl.close()
        jm.close()


def test_inflight_log_wire_request():
    """The InFlightLogRequestEvent wire analog: a remote peer pulls a
    window of an upstream's in-flight ring over TCP and gets the exact
    device-ring bytes (reference
    .../causal/events/InFlightLogRequestEvent.java — a recovering task's
    lost inputs can come from a REMOTE upstream)."""
    import jax
    import jax.numpy as jnp
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.inflight import log as ifl
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.remote import HostLogEndpoint

    env = StreamEnvironment(name="ifl-wire", num_key_groups=8,
                            default_edge_capacity=32)
    (env.synthetic_source(vocab=13, batch_size=4, parallelism=2)
        .key_by().window_count(num_keys=13, window_size=1 << 30).sink())
    r = ClusterRunner(env.build(), steps_per_epoch=6, log_capacity=256,
                      max_epochs=8, inflight_ring_steps=16, seed=3)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    ep = HostLogEndpoint(r.executor)
    ep.refresh_inflight(max_steps=8)
    try:
        mirror = RemoteReplicaMirror(ep.address, flats=[0], capacity=256,
                                     max_epochs=8)
        start, fields = mirror.fetch_inflight(ring=0, start=0, count=64)
        assert fields is not None
        el = r.executor.carry.out_rings[0]
        n = fields["keys"].shape[0]
        want, _, _ = ifl.slice_steps(el, jnp.asarray(start, jnp.int32), n)
        np.testing.assert_array_equal(fields["keys"],
                                      np.asarray(want.keys)[:n])
        np.testing.assert_array_equal(fields["valid"],
                                      np.asarray(want.valid)[:n])
        # Range below the retained floor comes back empty, with the floor.
        floor, none = mirror.fetch_inflight(ring=0, start=-100, count=2)
        assert none is None and floor >= 0
        mirror.close()
    finally:
        ep.close()
