"""Pallas log kernels: property equivalence against the XLA scatter path
(interpret mode on the CPU mesh; the same kernel compiles via Mosaic on
real TPU — exercised by bench/driver runs)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.causal import log as clog
from clonos_tpu.ops.histogram import keyed_hist


@pytest.mark.parametrize("b", [100, 128, 300])
def test_keyed_hist_kernel_matches_xla(b):
    """The Pallas histogram (the keyed-aggregation scatter replacement)
    must be bit-identical to the XLA fallback — including non-128-multiple
    record axes (pad slots must not count as key-0 records) and
    out-of-range keys (mode=drop parity)."""
    rng = np.random.RandomState(1)
    nk = 13
    keys = jnp.asarray(rng.randint(-3, nk + 4, (5, 4, b)), jnp.int32)
    vals = jnp.asarray(rng.randint(-50, 50, (5, 4, b)), jnp.int32)
    valid = jnp.asarray(rng.rand(5, 4, b) < 0.7)
    s1, c1 = keyed_hist(keys, vals, valid, nk, force="interpret")
    s2, c2 = keyed_hist(keys, vals, valid, nk, force="xla")
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


@pytest.mark.parametrize("cap,sizes", [
    (64, (4, 16, 28)),       # dense pad/roll branch (n * 64 >= cap)
    (512, (4, 6, 3)),        # small-append scatter branch (n * 64 < cap)
    (512, (4, 200, 3, 380)), # mixed: scatter resumes at a head the
                             # dense branch advanced, and wraps
])
def test_bulk_append_full_matches_masked_append(cap, sizes):
    """The block executor's bulk path (append_full — dense pad/roll for
    large appends, unique-index scatter for small ones) must agree with
    the general masked append, including ring wraps."""
    rng = np.random.RandomState(3)
    L = 4
    a = jax.vmap(lambda _: clog.create(cap, 8))(jnp.arange(L))
    b = jax.vmap(lambda _: clog.create(cap, 8))(jnp.arange(L))
    for n in sizes:
        rows = jnp.asarray(rng.randint(-9, 9, (L, n, 8)), jnp.int32)
        a = clog.v_append_full(a, rows)
        b = clog.v_append(b, rows, jnp.full((L,), n, jnp.int32))
    np.testing.assert_array_equal(np.asarray(a.rows), np.asarray(b.rows))
    np.testing.assert_array_equal(np.asarray(a.head), np.asarray(b.head))
