"""Pallas log kernels: property equivalence against the XLA scatter path
(interpret mode on the CPU mesh; the same kernel compiles via Mosaic on
real TPU — exercised by bench/driver runs)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.causal import log as clog
from clonos_tpu.ops.log_kernels import ring_append_stacked


def test_ring_append_matches_scatter_property():
    rng = np.random.RandomState(7)
    L, cap, mb = 6, 64, 8
    state = jax.vmap(lambda _: clog.create(cap, 8))(jnp.arange(L))
    storage, heads = state.rows, state.head
    for round_ in range(6):
        rows = jnp.asarray(rng.randint(-5, 100, (L, mb, 8)), jnp.int32)
        counts = jnp.asarray(rng.randint(0, mb + 1, L), jnp.int32)
        storage, heads = ring_append_stacked(storage, heads, rows, counts,
                                             interpret=True)
        state = clog.v_append(state, rows, counts)
    np.testing.assert_array_equal(np.asarray(storage), np.asarray(state.rows))
    np.testing.assert_array_equal(np.asarray(heads), np.asarray(state.head))
    # Heads advanced past one wrap of the ring.
    assert int(jnp.max(heads)) > 0


def test_executor_pallas_path_matches_default():
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.executor import CompiledJob, StepInputs

    def job():
        env = StreamEnvironment(num_key_groups=8, default_edge_capacity=32)
        (env.synthetic_source(vocab=7, batch_size=4, parallelism=2)
            .key_by().window_count(num_keys=7, window_size=1 << 30).sink())
        return env.build()

    ca = CompiledJob(job(), log_capacity=1 << 6, max_epochs=8,
                     inflight_ring_steps=8, use_pallas_append="interpret")
    cb = CompiledJob(job(), log_capacity=1 << 6, max_epochs=8,
                     inflight_ring_steps=8, use_pallas_append=False)
    ins = StepInputs(jnp.asarray(5, jnp.int32), jnp.asarray(9, jnp.int32))
    carry_a, carry_b = ca.init_carry(), cb.init_carry()
    step_a, step_b = jax.jit(ca.superstep), jax.jit(cb.superstep)
    for _ in range(3):
        carry_a, _ = step_a(carry_a, ins)
        carry_b, _ = step_b(carry_b, ins)
    fa = jax.tree_util.tree_leaves(jax.device_get(carry_a))
    fb = jax.tree_util.tree_leaves(jax.device_get(carry_b))
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
