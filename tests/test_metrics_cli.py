"""Observability + CLI front end (reference MetricRegistryImpl /
CliFrontend analogs)."""

import json

import numpy as np
import pytest

from clonos_tpu.utils import metrics as met


def test_metric_types_and_snapshot():
    reg = met.MetricRegistry()
    g = reg.group("job.test")
    c = g.counter("events")
    c.inc(3)
    g.gauge("level", lambda: 42)
    h = g.histogram("latency")
    for v in [1.0, 2.0, 3.0, 4.0]:
        h.update(v)
    t = [0.0]
    m = met.Meter(window_s=10.0, clock=lambda: t[0])
    reg._register("job.test.rate", m)
    m.mark(50)
    t[0] = 5.0
    snap = reg.snapshot()
    assert snap["job.test.events"] == 3
    assert snap["job.test.level"] == 42
    assert snap["job.test.latency"]["count"] == 4
    assert snap["job.test.rate"] == 5.0
    # Same name returns the same metric (no duplicate registration).
    assert g.counter("events") is c
    text = reg.prometheus_text()
    assert "job_test_events 3" in text
    assert "job_test_latency_p99" in text


def test_jsonlines_reporter(tmp_path):
    reg = met.MetricRegistry()
    reg.group("a").counter("x").inc()
    path = str(tmp_path / "metrics.jsonl")
    reg.add_reporter(met.JsonLinesReporter(path, clock=lambda: 123.0))
    reg.report()
    reg.report()
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2 and lines[0]["a.x"] == 1 and lines[0]["ts"] == 123.0


def test_cluster_metrics_and_watchdog():
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    env = StreamEnvironment(num_key_groups=8)
    (env.synthetic_source(vocab=5, batch_size=4, parallelism=1)
        .key_by().window_count(num_keys=5, window_size=1 << 30).sink())
    r = ClusterRunner(env.build(), steps_per_epoch=2, log_capacity=1 << 6)
    r.run_epoch()
    snap = r.metrics.snapshot()
    name = env.graph.name
    assert snap[f"job.{name}.supersteps"] == 2
    assert snap[f"job.{name}.epochs"] == 1
    assert snap[f"job.{name}.checkpoint.latest-bytes"] > 0
    # The completed checkpoint truncated every log back to the fence; only
    # the post-fence SOURCE_CHECKPOINT determinant of the (single) source
    # subtask survives (StreamTask.performCheckpoint:833-840 parity).
    assert snap[f"job.{name}.causal-log.total-rows"] == 1
    # An epoch whose checkpoint stays pending keeps its rows live.
    r.run_epoch(complete_checkpoint=False)
    assert 0 < r.metrics.snapshot()[f"job.{name}.causal-log.total-rows"]
    warnings = []
    r.watchdog._warn = warnings.append
    # 2 retained steps * 4 rows = 8 rows of 64 -> no warning yet.
    assert not r.watchdog.check()
    for _ in range(11):               # 8 + 44 = 52 rows >= 80% of 64
        r.executor.step()
    assert r.watchdog.check()
    assert warnings and "occupancy" in warnings[0]


def test_cli_info_and_run(capsys):
    from clonos_tpu import cli
    rc = cli.main(["info", "examples.wordcount:build_job"])
    assert rc == 0
    info = json.loads(capsys.readouterr().out)
    assert info["name"] == "socket-window-wordcount"
    assert info["total_subtasks"] == 12
    rc = cli.main(["run", "examples.wordcount:build_job", "--epochs", "1",
                   "--steps-per-epoch", "2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["epochs"] == 1
    assert out["metrics"][f"job.socket-window-wordcount.supersteps"] == 2


def test_metrics_http_endpoint_serves_prometheus_and_json():
    import json
    import urllib.request
    from clonos_tpu.utils import metrics as met

    reg = met.MetricRegistry()
    g = reg.group("job.test")
    c = g.counter("things")
    c.inc(5)
    ep = met.MetricsEndpoint(reg)
    try:
        host, port = ep.address
        txt = urllib.request.urlopen(
            f"http://{host}:{port}/metrics").read().decode()
        assert "job_test_things 5" in txt
        js = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/metrics.json").read())
        assert js["job.test.things"] == 5
        import urllib.error
        try:
            urllib.request.urlopen(f"http://{host}:{port}/nope")
            assert False, "404 expected"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        ep.close()


def test_latency_markers_populate_and_replay_stable(tmp_path):
    """Latency markers ride the causal RNG path (reference
    RecordWriter.randomEmit:131-137): marker steps are chosen by the
    recorded per-step rng draws, so (a) the latency-ms histogram
    populates on a live job, and (b) a recovered task's replayed rng
    stream re-derives the SAME marker schedule bit-for-bit."""
    import numpy as np
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.causal import determinant as det
    from clonos_tpu.runtime.cluster import ClusterRunner, LatencyMarkers

    env = StreamEnvironment(name="lat", num_key_groups=8,
                            default_edge_capacity=32)
    (env.synthetic_source(vocab=11, batch_size=4, parallelism=2)
        .key_by().window_count(num_keys=11, window_size=1 << 30)
        .sink())
    r = ClusterRunner(env.build(), steps_per_epoch=8, log_capacity=512,
                      max_epochs=8, inflight_ring_steps=32, seed=3,
                      latency_marker_every=3)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    assert r.latency.hist.count > 0

    # Fail a window subtask; the recovered log's RNG lanes must yield the
    # same marker schedule as the live step-input ledger over the
    # replayed range.
    fence = r._fence_step[r.standbys.latest.checkpoint_id + 1]
    r.inject_failure([2 + 1])
    report = r.recover()
    mgr = report.managers[0]
    n = report.steps_replayed
    if mgr.plan.det_device is not None:
        rngs = np.asarray(mgr.plan.det_device[1])[:n]
    else:
        rows = np.asarray(mgr.plan.det_rows)
        anchors = det.sync_anchors(rows)[:n]
        rngs = rows[anchors + 1, det.LANE_P]
    live = [rg for (_t, rg) in
            r.executor.step_input_history[fence:fence + n]]
    assert LatencyMarkers.schedule(rngs.tolist(), 3) == \
        LatencyMarkers.schedule(live, 3)
    assert len(LatencyMarkers.schedule(live, 3)) > 0
