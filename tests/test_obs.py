"""Observability: distributed tracing + the recovery flight recorder
(clonos_tpu/obs; reference MetricRegistryImpl scopes + the ad-hoc log
lines around RecoveryManager.java state transitions, here turned into
spans that follow one job across worker OS processes).

The headline test re-drives the slot-pool SIGKILL scenario
(tests/test_scheduler.py) with tracing enabled: the JobMaster's and
both workers' trace files must reconstruct the full recovery timeline —
failure detect -> redeploy -> determinant fetch -> rebuild -> replay ->
caught up — under ONE trace id carried over the control wire, with
per-phase durations in the registries and the worker metrics
piggybacked on HEARTBEAT into the JobMaster's cluster-wide view, and
the merged files must convert to valid Chrome trace JSON.
"""

import collections
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from clonos_tpu import obs
from clonos_tpu.parallel import transport as tp
from clonos_tpu.utils import metrics as met

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _null_tracer_after():
    """Every test leaves the process-global tracer disabled."""
    yield
    obs.reset()


# --- tracer core -------------------------------------------------------------


def test_tracer_spans_nest_backdate_and_persist(tmp_path):
    t = [100.0]
    path = str(tmp_path / "t.jsonl")
    tr = obs.Tracer("svc", path=path, clock=lambda: t[0])
    with tr.span("outer", epoch=3) as outer:
        t[0] += 1.0
        with tr.span("inner") as inner:
            t[0] += 0.5
        tr.event("mark", k=7)
    t[0] += 2.0
    tr.complete("measured", 2.0, phase="replay")
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("x")

    recs = tr.records()
    assert [r["name"] for r in recs] == ["inner", "mark", "outer",
                                        "measured", "boom"]
    by = {r["name"]: r for r in recs}
    # Parent nesting: inner span and the instant event sit under outer.
    assert by["inner"]["parent"] == outer.span_id
    assert by["inner"]["span"] == inner.span_id
    assert by["mark"]["parent"] == outer.span_id
    assert by["outer"]["parent"] is None
    # Complete spans carry ts + dur; the event is an instant.
    assert by["outer"]["ph"] == "X"
    assert by["outer"]["ts"] == 100.0
    assert by["outer"]["dur"] == pytest.approx(1.5)
    assert by["inner"]["ts"] == 101.0
    assert by["inner"]["dur"] == pytest.approx(0.5)
    assert by["mark"]["ph"] == "i" and by["mark"]["args"] == {"k": 7}
    # complete() back-dates ts so the timeline lays out correctly.
    assert by["measured"]["ts"] == pytest.approx(101.5)
    assert by["measured"]["dur"] == pytest.approx(2.0)
    # A span that raises still closes, recording the error.
    assert "ValueError" in by["boom"]["args"]["error"]
    # Every record is tagged with the one trace id + emitting service.
    assert {r["trace"] for r in recs} == {tr.trace_id}
    assert {r["service"] for r in recs} == {"svc"}
    # Flushed per record: the file is complete BEFORE close (SIGKILL
    # loses at most the record being written).
    lines = [json.loads(ln) for ln in open(path)]
    assert [ln["name"] for ln in lines] == [r["name"] for r in recs]
    tr.close()

    # The flight-recorder ring is bounded: only the most recent survive.
    small = obs.Tracer("s2", clock=lambda: t[0], buffer=4)
    for i in range(9):
        small.event(f"e{i}")
    assert [r["name"] for r in small.records()] == ["e5", "e6", "e7", "e8"]


def test_wire_context_propagation_and_null_tracer_zero_overhead():
    # Default: the NullTracer. attach_trace adds NO wire field, spans
    # are no-ops, nothing is recorded.
    tr0 = obs.get_tracer()
    assert isinstance(tr0, obs.NullTracer) and not tr0.enabled
    hdr = tp.attach_trace({"group": 1})
    assert hdr == {"group": 1}, "disabled tracer must add no wire fields"
    tp.adopt_trace({"group": 1, "trace": {"trace_id": "deadbeef"}})  # no-op
    with tr0.span("x") as s:
        assert s.span_id is None
    tr0.event("y")
    tr0.complete("z", 1.0)
    assert tr0.records() == [] and tr0.wire_context() is None

    # Opt-in: the sender's header carries {trace_id, span}; the
    # receiving process adopts it and lands under the SAME trace id.
    jm = obs.configure("jm")
    with jm.span("deploy", group=1) as sp:
        hdr = tp.attach_trace({"group": 1})
    assert hdr["trace"] == {"trace_id": jm.trace_id, "span": sp.span_id}

    worker = obs.Tracer("worker-a")
    assert worker.trace_id != jm.trace_id
    worker.adopt(hdr["trace"])
    worker.event("recovery.caught_up", group=1)
    assert worker.records()[0]["trace"] == jm.trace_id
    worker.adopt(None)                      # idempotent / null-safe
    assert worker.trace_id == jm.trace_id

    # adopt_trace routes a received header into the process tracer.
    tp.adopt_trace({"trace": {"trace_id": "feedc0de00000000"}})
    assert jm.trace_id == "feedc0de00000000"
    obs.reset()
    assert not obs.get_tracer().enabled


# --- Chrome conversion + the standalone converter ----------------------------


def test_chrome_conversion_validation_and_converter_tool(tmp_path):
    t = [50.0]
    jm_path = str(tmp_path / "trace-jm.jsonl")
    jm = obs.Tracer("jm", path=jm_path, clock=lambda: t[0])
    jm.event("recovery.detect", worker="b")
    with jm.span("recovery.redeploy", worker="b"):
        t[0] += 0.25
    jm.close()
    # A worker file of the same trace (context carried over the wire).
    wk_path = str(tmp_path / "trace-a.jsonl")
    wk = obs.Tracer("a", path=wk_path, trace_id=jm.trace_id,
                    clock=lambda: t[0])
    wk.complete("recovery.replay", 0.1)
    wk.close()

    records = obs.load_jsonl([jm_path, wk_path])
    assert len(records) == 3
    assert records == sorted(records, key=lambda r: r["ts"])
    doc = obs.to_chrome(records)
    n = obs.validate_chrome(doc)
    evs = doc["traceEvents"]
    assert n == len(evs)
    # process_name metadata labels each (pid, service) lane.
    metas = [e for e in evs if e["ph"] == "M"]
    assert {m["args"]["name"] for m in metas} == {"jm", "a"}
    # Seconds -> microseconds; instants carry process scope.
    redeploy = next(e for e in evs
                    if e["ph"] == "X" and e["name"] == "recovery.redeploy")
    assert redeploy["dur"] == pytest.approx(0.25 * 1e6)
    assert all(e["s"] == "p" for e in evs if e["ph"] == "i")
    # Span ids survive the conversion (stashed in args).
    assert redeploy["args"]["trace"] == jm.trace_id

    # trace_id filtering drops foreign records.
    other = obs.Tracer("x")
    other.event("noise")
    only = obs.to_chrome(records + other.records(), trace_id=jm.trace_id)
    assert all(e["ph"] == "M" or e["args"]["trace"] == jm.trace_id
               for e in only["traceEvents"])

    # Malformed docs are rejected loudly.
    with pytest.raises(ValueError, match="traceEvents"):
        obs.validate_chrome({})
    with pytest.raises(ValueError, match="unknown ph"):
        obs.validate_chrome({"traceEvents": [
            {"ph": "Z", "name": "x", "ts": 0, "pid": 1, "tid": 1}]})
    with pytest.raises(ValueError, match="dur"):
        obs.validate_chrome({"traceEvents": [
            {"ph": "X", "name": "x", "ts": 0, "pid": 1, "tid": 1,
             "dur": -1}]})

    s = obs.summarize(records)
    assert s["records"] == 3 and s["main_trace"] == jm.trace_id
    assert s["names"]["recovery.redeploy"]["count"] == 1
    assert [e["name"] for e in s["timeline"]] == [
        "recovery.detect", "recovery.redeploy", "recovery.replay"]

    # The standalone converter (tools/trace2chrome.py) over the same
    # files: validates and writes a loadable Chrome trace.
    out = str(tmp_path / "chrome.json")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace2chrome.py"),
         jm_path, wk_path, "-o", out, "--trace-id", jm.trace_id],
        cwd=REPO, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert res.returncode == 0, res.stderr
    info = json.loads(res.stdout)
    assert info["valid"] and info["records"] == 3
    assert info["traces"] == [jm.trace_id]
    assert obs.validate_chrome(json.load(open(out))) > 0


# --- metrics satellites ------------------------------------------------------


def test_meter_and_histogram_use_bounded_deques():
    t = [0.0]
    m = met.Meter(window_s=10.0, clock=lambda: t[0])
    assert isinstance(m._events, collections.deque)
    for _ in range(5):
        m.mark(2)
        t[0] += 1.0
    assert m.rate == pytest.approx(1.0)
    # mark() prunes everything past the window from the left in O(1).
    t[0] = 100.0
    m.mark(1)
    assert len(m._events) == 1
    assert m.rate == pytest.approx(0.1)

    h = met.Histogram(max_samples=4)
    assert isinstance(h._buf, collections.deque)
    for v in (1, 2, 3, 4, 5, 6):
        h.update(v)
    assert h.count == 4                       # oldest two evicted
    assert h.mean == pytest.approx(4.5)
    assert h.quantile(0.5) == pytest.approx(4.5)
    assert h.quantile(0.99) == pytest.approx(5.97)


def test_jsonlines_reporter_single_handle_flush_and_close(tmp_path):
    path = str(tmp_path / "m.jsonl")
    r = met.JsonLinesReporter(path, clock=lambda: 1.0)
    r.report({"a": 1})
    handle = r._file
    r.report({"a": 2})
    assert r._file is handle, "one append-mode handle for the lifetime"
    # Flushed per record: both lines readable before close.
    assert [json.loads(ln)["a"] for ln in open(path)] == [1, 2]
    r.close()
    assert r._file is None
    r.report({"a": 3})                        # reopens, appends
    r.close()
    assert [json.loads(ln)["a"] for ln in open(path)] == [1, 2, 3]

    # ReporterThread.stop() closes closeable reporters.
    reg = met.MetricRegistry()
    reg.group("g").counter("c").inc()
    r2 = met.JsonLinesReporter(str(tmp_path / "n.jsonl"))
    reg.add_reporter(r2)
    th = met.ReporterThread(reg, interval_s=0.05)
    th.start()
    deadline = time.monotonic() + 10
    while not os.path.exists(r2._path) or not os.path.getsize(r2._path):
        assert time.monotonic() < deadline
        time.sleep(0.02)
    th.stop()
    assert r2._file is None


def test_metrics_endpoint_serves_cluster_view_and_trace():
    reg = met.MetricRegistry()
    reg.group("scheduler").counter("deploys").inc(3)
    tr = obs.Tracer("jm")
    tr.event("recovery.detect", worker="b")
    # ``extra`` is the JobMaster's aggregated per-worker heartbeat view.
    extra = lambda: {"worker.a.group.1.supersteps": 12}
    ep = met.MetricsEndpoint(reg, port=0, extra=extra, tracer=tr)
    try:
        base = "http://%s:%d" % ep.address
        txt = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "scheduler_deploys 3" in txt
        assert "worker_a_group_1_supersteps 12" in txt
        js = json.loads(urllib.request.urlopen(base
                                               + "/metrics.json").read())
        assert js["scheduler.deploys"] == 3
        assert js["worker.a.group.1.supersteps"] == 12
        # /trace serves the flight-recorder ring as valid Chrome JSON.
        doc = json.loads(urllib.request.urlopen(base + "/trace").read())
        assert obs.validate_chrome(doc) > 0
        assert "recovery.detect" in [e["name"] for e in doc["traceEvents"]]
    finally:
        ep.close()

    # Without a tracer the /trace surface does not exist.
    ep2 = met.MetricsEndpoint(met.MetricRegistry(), port=0)
    try:
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen("http://%s:%d/trace" % ep2.address)
    finally:
        ep2.close()


def test_heartbeat_piggybacks_metrics_into_jobmaster_view():
    from clonos_tpu.runtime.remote import JobMasterServer, TaskExecutorClient

    jm = JobMasterServer(heartbeat_timeout_s=30.0)
    good = bad = None
    try:
        good = TaskExecutorClient(
            "a", jm.address, interval_s=0.05,
            payload_fn=lambda: {"metrics": {"group.1.supersteps": 4}})
        deadline = time.monotonic() + 20
        while "worker.a.group.1.supersteps" not in jm.cluster_metrics():
            assert time.monotonic() < deadline, "piggyback never arrived"
            time.sleep(0.02)
        assert jm.cluster_metrics()["worker.a.group.1.supersteps"] == 4

        # A crashing payload_fn must not kill the heartbeat itself.
        bad = TaskExecutorClient("b", jm.address, interval_s=0.05,
                                 payload_fn=lambda: 1 // 0)
        time.sleep(0.3)
        assert bad.missed_beats == 0
        assert not any(k.startswith("worker.b.")
                       for k in jm.cluster_metrics())
    finally:
        for c in (good, bad):
            if c is not None:
                c.close()
        jm.close()


# --- lifecycle instrumentation, in-process -----------------------------------


def test_checkpoint_lifecycle_traced_with_latency():
    from clonos_tpu.runtime.checkpoint import (CheckpointCoordinator,
                                               InMemoryCheckpointStorage)

    tr = obs.configure("runner")
    co = CheckpointCoordinator(InMemoryCheckpointStorage(), num_subtasks=2)
    carry = {"w": np.zeros(4, np.float32)}
    co.trigger(7, carry, async_write=False, owned=True)
    co.ack(7, 0)
    assert 7 not in co.completion_latency_s, "half-acked is not complete"
    co.ack(7, 1)
    assert co.completion_latency_s[7] >= 0.0

    recs = tr.records()
    names = [r["name"] for r in recs]
    assert names.index("checkpoint.trigger") \
        < names.index("checkpoint") < names.index("checkpoint.truncate")
    ck = next(r for r in recs if r["name"] == "checkpoint")
    assert ck["ph"] == "X" and ck["args"]["cid"] == 7
    assert ck["args"]["size_bytes"] == 16
    assert ck["dur"] == pytest.approx(co.completion_latency_s[7])

    # The latency ledger is bounded (oldest entries pruned).
    for cid in range(100, 170):
        co.trigger(cid, carry, async_write=False, owned=True)
        co.ack_all(cid)
    assert len(co.completion_latency_s) <= 64
    assert 169 in co.completion_latency_s


def test_epoch_spans_and_histograms_in_process(tmp_path):
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    tr = obs.configure("runner")
    env = StreamEnvironment(name="obsjob", num_key_groups=8)
    env.synthetic_source(vocab=7, batch_size=4, parallelism=1)
    job = env.build()
    r = ClusterRunner(job, steps_per_epoch=2,
                      checkpoint_dir=str(tmp_path / "ck"),
                      log_capacity=256, max_epochs=8, seed=2)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=True)

    recs = tr.records()
    names = [rec["name"] for rec in recs]
    for want in ("epoch", "epoch.steps", "epoch.fence",
                 "checkpoint.trigger", "checkpoint", "checkpoint.truncate",
                 "epoch.inflight_truncate"):
        assert want in names, f"missing {want} in {sorted(set(names))}"
    # Phase records nest under their epoch span.
    epoch0 = next(rec for rec in recs if rec["name"] == "epoch")
    assert epoch0["args"]["epoch"] == 0
    steps0 = next(rec for rec in recs if rec["name"] == "epoch.steps")
    fence0 = next(rec for rec in recs if rec["name"] == "epoch.fence")
    assert steps0["parent"] == epoch0["span"]
    assert fence0["parent"] == epoch0["span"]
    assert epoch0["dur"] >= steps0["dur"]

    # Per-phase durations feed the registry histograms.
    snap = r.metrics.snapshot()
    assert snap["job.obsjob.epoch.steps-ms"]["count"] == 2
    assert snap["job.obsjob.epoch.fence-ms"]["count"] == 2
    assert snap["job.obsjob.checkpoint.trigger-to-complete-ms"]["count"] >= 1
    assert snap["job.obsjob.epoch.steps-ms"]["p99"] >= 0.0


# --- THE acceptance run: SIGKILL recovery under one trace id -----------------


def _line_server(lines):
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(2)

    def serve():
        try:
            while True:
                conn, _ = srv.accept()
                conn.sendall("".join(f"{k}:{v}\n"
                                     for k, v in lines).encode())
        except OSError:
            return

    threading.Thread(target=serve, daemon=True).start()
    return srv, srv.getsockname()[1]


def _read_status(proc, want, deadline_s=300.0):
    deadline = time.monotonic() + deadline_s
    for line in iter(proc.stdout.readline, ""):
        assert time.monotonic() < deadline, "worker status timeout"
        st = json.loads(line)
        if want(st):
            return st
    raise AssertionError("worker stdout closed before expected status")


def test_trace_reconstructs_recovery_timeline_across_processes(tmp_path):
    """Acceptance: the slot-pool SIGKILL/redeploy run with tracing on.
    The JobMaster (this process, ``--trace-dir``-equivalent via
    obs.configure) and both worker processes (``--trace-dir``) write
    JSON-lines trace files; DEPLOY/DETERMINANT_REQUEST/FETCH_EDGE
    headers carry the trace context, so afterwards the three files
    reconstruct the whole recovery — detect -> redeploy -> determinant
    fetch -> rebuild -> replay -> caught up — under ONE trace id, with
    per-phase durations in the scheduler's registry, worker metrics
    aggregated over HEARTBEAT, and a valid Chrome trace out of
    tools/trace2chrome.py."""
    from clonos_tpu.runtime import scheduler as sch
    from clonos_tpu.runtime.leader import FileLeaderElection
    from clonos_tpu.runtime.remote import JobMasterServer

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    lease = str(tmp_path / "jm.lease")
    lines = [((i * 37) % 997, 1 + i % 5) for i in range(600)]
    srv, lport = _line_server(lines)

    jm_tracer = obs.configure("jm", path=str(trace_dir / "trace-jm.jsonl"))
    jm = JobMasterServer(heartbeat_timeout_s=2.0)
    election = FileLeaderElection(lease, "jm-0", lease_ttl_s=30.0)
    assert election.try_acquire()
    runner_kw = dict(steps_per_epoch=4, log_capacity=512, max_epochs=64,
                     inflight_ring_steps=64, seed=7, logical_time=True)
    scheduler = sch.SlotPoolScheduler(
        jm, election, "examples.spanning:build_job", runner_kw=runner_kw,
        feed_batch=4, target_epochs=8, complete_every=2,
        checkpoint_root=str(tmp_path / "ck"), deploy_timeout_s=300.0)

    def spawn(eid):
        return subprocess.Popen(
            [sys.executable, "-m", "clonos_tpu", "slotworker",
             "--jm", f"127.0.0.1:{jm.address[1]}",
             "--executor-id", eid, "--slots", "2", "--lease", lease,
             "--heartbeat-interval", "0.3", "--max-seconds", "600",
             "--epoch-sleep", "0.25", "--trace-dir", str(trace_dir)],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)

    pa, pb = spawn("a"), spawn("b")
    try:
        assert json.loads(pa.stdout.readline())["registered"] == "a"
        assert json.loads(pb.stdout.readline())["registered"] == "b"
        deadline = time.monotonic() + 30
        while {"a", "b"} - set(jm.registered()):
            assert time.monotonic() < deadline
            time.sleep(0.05)

        placements = scheduler.deploy(external_feeds={
            0: {"kind": "socket", "host": "127.0.0.1", "port": lport,
                "num_subtasks": 1}})
        assert placements == {0: "a", 1: "b"}
        _read_status(pa, lambda st: st.get("deployed") == 0)
        _read_status(pb, lambda st: st.get("deployed") == 1)
        _read_status(pa, lambda st: st.get("finished") == 0)

        # Mirror determinants at each downstream fence; kill at
        # epoch >= 5 (checkpoints 0, 2, 4 completed by then).
        def at_fence(st):
            if "group" in st and "digest" in st:
                scheduler.sync()
            return st.get("epoch", -1) >= 5 or "finished" in st

        _read_status(pb, at_fence)
        pb.send_signal(signal.SIGKILL)
        pb.wait(timeout=15)

        deadline = time.monotonic() + 20
        while "b" not in scheduler.failed_workers():
            assert time.monotonic() < deadline, "heartbeat expiry not seen"
            time.sleep(0.1)

        assert scheduler.recover_worker("b") == {1: "a"}
        dep = _read_status(pa, lambda st: st.get("deployed") == 1)
        assert dep["recovered"] and dep["vertices"] == [2, 3]

        # Per-phase recovery durations landed in the JobMaster-side
        # registry histograms...
        snap = scheduler.metrics.snapshot()
        assert snap["scheduler.deploy-ms"]["count"] >= 3
        assert snap["scheduler.recovery.redeploy-ms"]["count"] == 1
        assert snap["scheduler.recovery.determinant-fetch-ms"]["count"] == 1
        assert snap["scheduler.recovery.redeploy-ms"]["p99"] > 0.0

        # ...and the worker's (recovery.replay-ms & co) reach the
        # JobMaster's cluster-wide view piggybacked on HEARTBEAT.
        deadline = time.monotonic() + 60
        while not any(k.startswith("worker.a.")
                      and k.endswith("recovery.replay-ms")
                      for k in jm.cluster_metrics()):
            assert time.monotonic() < deadline, \
                f"no replay histogram in {sorted(jm.cluster_metrics())}"
            time.sleep(0.2)
        replay_ms = next(v for k, v in jm.cluster_metrics().items()
                         if k.startswith("worker.a.")
                         and k.endswith("recovery.replay-ms"))
        assert replay_ms["count"] >= 1

        # The rebuilt slice runs on to the job's target.
        fin = _read_status(pa, lambda st: st.get("finished") == 1)
        assert fin["global_step"] == 8 * runner_kw["steps_per_epoch"]
    finally:
        for p in (pa, pb):
            if p.poll() is None:
                p.kill()
        scheduler.close()
        jm.close()
        srv.close()
        obs.reset()          # also flushes/closes trace-jm.jsonl

    # --- reconstruct the timeline from the three trace files -----------------
    T = jm_tracer.trace_id
    paths = [str(trace_dir / f"trace-{s}.jsonl") for s in ("jm", "a", "b")]
    for p in paths:
        assert os.path.exists(p), f"missing trace file {p}"
    records = obs.load_jsonl(paths)
    ours = [r for r in records if r["trace"] == T]

    # One trace id spans all three processes: the workers ADOPTED the
    # JobMaster's id from the DEPLOY header.
    assert {r["service"] for r in ours} >= {"jm", "a", "b"}
    assert len({r["pid"] for r in ours}) >= 3

    def first(name, service=None):
        for r in ours:
            if r["name"] == name and (service is None
                                      or r["service"] == service):
                return r
        raise AssertionError(
            f"{name} ({service}) not in trace: "
            f"{sorted({(r['service'], r['name']) for r in ours})}")

    # The full recovery timeline, each phase attributed to its process.
    detect = first("recovery.detect", "jm")
    assert detect["args"]["worker"] == "b"
    redeploy = first("recovery.redeploy", "jm")
    fetch = first("recovery.determinant_fetch", "jm")
    rebuild = first("recovery.rebuild", "a")
    replay = first("recovery.replay", "a")
    caught = first("recovery.caught_up", "a")
    recovery = first("recovery", "a")
    first("recovery.restore", "a")
    first("recovery.fetch_determinants", "a")
    first("epoch", "b")                  # pre-kill epochs, same trace
    first("epoch", "a")
    # The deploy that carried the recovery is in the trace too.
    rec_deploy = next(r for r in ours
                      if r["name"] == "deploy" and r["service"] == "jm"
                      and r["args"].get("recover"))
    assert rec_deploy["args"]["worker"] == "a"

    # Causal order: detect -> redeploy window covering fetch/rebuild,
    # replay ends before the worker reports caught up.
    assert detect["ts"] <= redeploy["ts"]
    assert redeploy["ts"] <= fetch["ts"]
    assert rebuild["ts"] + rebuild["dur"] <= caught["ts"] + 1e-6
    assert replay["ts"] + replay["dur"] <= caught["ts"] + 1e-6
    assert recovery["dur"] > 0           # recovery_ms, back-dated span
    # The determinant fetch nests inside the redeploy span.
    assert fetch["parent"] == redeploy["span"]

    # The merged files convert to a VALID Chrome trace, and the
    # standalone converter agrees.
    doc = obs.to_chrome(records, trace_id=T)
    assert obs.validate_chrome(doc) > len(ours)      # + metadata events
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace2chrome.py"),
         *paths, "--check"],
        cwd=REPO, capture_output=True, text=True, env=env)
    assert res.returncode == 0, res.stderr
    info = json.loads(res.stdout)
    assert info["valid"] and T in info["traces"]
