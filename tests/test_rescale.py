"""Rescaling restore: a completed checkpoint taken at parallelism P
restores into a job running the keyed vertices at a different P', with
dense keyed state split/merged along key-group ranges
(Operator.rescale_keyed_state; reference StateAssignmentOperation +
KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup). The rescaled
incarnation's sink output must equal the unrescaled run's."""

import numpy as np
import jax.numpy as jnp
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.api.operators import rescale_dense_table
from clonos_tpu.causal import recovery as rec
from clonos_tpu.parallel.routing import key_group, subtask_for_key_group
from clonos_tpu.runtime.cluster import ClusterRunner

VOCAB = 23


class TickTime:
    """Deterministic causal-time source: both incarnations must see the
    same times or windows fire at different steps."""

    def __init__(self, t0: int = 0, step: int = 3):
        self.t = t0
        self.step = step

    def now(self) -> int:
        self.t += self.step
        return self.t


def _job(window_p: int, reduce_p: int):
    env = StreamEnvironment(name=f"rescale-{window_p}-{reduce_p}",
                            num_key_groups=16, default_edge_capacity=96)
    (env.synthetic_source(vocab=VOCAB, batch_size=8, parallelism=2)
        .key_by()
        .window_count(num_keys=VOCAB, window_size=7, parallelism=window_p,
                      name="w")
        .key_by()
        .reduce(num_keys=VOCAB, parallelism=reduce_p, name="r")
        .key_by()                 # HASH into the sink: partition kind is
        .sink(parallelism=2))     # then independent of the reduce P
    return env.build()


def _collect_sink(runner, epochs, complete=False):
    """Run epochs, returning the multiset of sink records."""
    got = []
    sink_vid = 3

    def absorb(outs, _epoch):
        b = outs.sinks.get(sink_vid)
        if b is None:
            return
        k = np.asarray(b.keys)
        v = np.asarray(b.values)
        t = np.asarray(b.timestamps)
        m = np.asarray(b.valid)
        got.extend(zip(k[m].tolist(), v[m].tolist(), t[m].tolist()))

    runner.executor.on_block_outputs = absorb
    for _ in range(epochs):
        runner.run_epoch(complete_checkpoint=complete)
    runner.executor.on_block_outputs = None
    return sorted(got)


@pytest.mark.parametrize("p_old,p_new", [(2, 4), (4, 2)])
def test_rescale_restore_identical_sink_output(p_old, p_new, tmp_path):
    spe = 6
    # Reference incarnation: checkpoint at the fence, keep running.
    ref = ClusterRunner(_job(p_old, p_old), steps_per_epoch=spe,
                        log_capacity=256, max_epochs=8,
                        inflight_ring_steps=16, seed=11,
                        checkpoint_dir=str(tmp_path))
    ref.executor.time_source = TickTime()
    ref.run_epoch(complete_checkpoint=True)
    ckpt = ref.standbys.latest
    fence_t = ref.executor.time_source.t
    want = _collect_sink(ref, 2)

    # Rescaled incarnation: same topology, keyed vertices at p_new.
    res = ClusterRunner.restore_rescaled(
        _job(p_new, p_new), _job(p_old, p_old), ckpt,
        steps_per_epoch=spe, log_capacity=256, max_epochs=8,
        inflight_ring_steps=16, seed=11)
    res.executor.time_source = TickTime(t0=fence_t)
    got = _collect_sink(res, 2)
    assert got == want and len(got) > 0

    # The rescaled keyed tables respect the new ownership exactly.
    acc = np.asarray(res.executor.vertex_state(2)["acc"])
    kg = np.asarray(key_group(jnp.arange(VOCAB), 16))
    owner = np.asarray(subtask_for_key_group(jnp.asarray(kg), p_new, 16))
    for s in range(p_new):
        assert not np.any(acc[s][owner != s])


def test_rescale_dense_table_conserves_and_partitions():
    rng = np.random.RandomState(0)
    G, K = 16, VOCAB
    for p_old, p_new in ((2, 4), (4, 2), (3, 5)):
        kg = np.asarray(key_group(jnp.arange(K), G))
        owner_old = np.asarray(subtask_for_key_group(
            jnp.asarray(kg), p_old, G))
        table = np.zeros((p_old, K), np.int32)
        for k in range(K):
            table[owner_old[k], k] = rng.randint(1, 100)
        out = np.asarray(rescale_dense_table(jnp.asarray(table), p_new, G))
        assert out.shape == (p_new, K)
        np.testing.assert_array_equal(out.sum(axis=0), table.sum(axis=0))
        owner_new = np.asarray(subtask_for_key_group(
            jnp.asarray(kg), p_new, G))
        for s in range(p_new):
            assert not np.any(out[s][owner_new != s])


def test_rescale_rejects_non_hash_edges():
    env = StreamEnvironment(name="fwd", num_key_groups=8,
                            default_edge_capacity=16)
    (env.synthetic_source(vocab=5, batch_size=4, parallelism=2)
        .key_by().reduce(num_keys=5, parallelism=2).sink(parallelism=2))
    job_old = env.build()
    env2 = StreamEnvironment(name="fwd", num_key_groups=8,
                             default_edge_capacity=16)
    (env2.synthetic_source(vocab=5, batch_size=4, parallelism=2)
         .key_by().reduce(num_keys=5, parallelism=4).sink(parallelism=2))
    job_new = env2.build()
    r = ClusterRunner(job_old, steps_per_epoch=4, log_capacity=128,
                      max_epochs=8, inflight_ring_steps=8, seed=1)
    r.run_epoch(complete_checkpoint=True)
    # Sabotage: claim the reduce input edge is FORWARD.
    from clonos_tpu.graph.job_graph import PartitionType
    job_new.edges[0].partition = PartitionType.FORWARD
    job_old.edges[0].partition = PartitionType.FORWARD
    with pytest.raises(rec.RecoveryError):
        ClusterRunner.restore_rescaled(
            job_new, job_old, r.standbys.latest, steps_per_epoch=4,
            log_capacity=128, max_epochs=8, inflight_ring_steps=8, seed=1)
