"""Rescaling restore: a completed checkpoint taken at parallelism P
restores into a job running the keyed vertices at a different P', with
dense keyed state split/merged along key-group ranges
(Operator.rescale_keyed_state; reference StateAssignmentOperation +
KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup). The rescaled
incarnation's sink output must equal the unrescaled run's."""

import numpy as np
import jax.numpy as jnp
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.api.operators import rescale_dense_table
from clonos_tpu.causal import recovery as rec
from clonos_tpu.parallel.routing import key_group, subtask_for_key_group
from clonos_tpu.runtime.cluster import ClusterRunner

VOCAB = 23


class TickTime:
    """Deterministic causal-time source: both incarnations must see the
    same times or windows fire at different steps."""

    def __init__(self, t0: int = 0, step: int = 3):
        self.t = t0
        self.step = step

    def now(self) -> int:
        self.t += self.step
        return self.t


def _job(window_p: int, reduce_p: int):
    env = StreamEnvironment(name=f"rescale-{window_p}-{reduce_p}",
                            num_key_groups=16, default_edge_capacity=96)
    (env.synthetic_source(vocab=VOCAB, batch_size=8, parallelism=2)
        .key_by()
        .window_count(num_keys=VOCAB, window_size=7, parallelism=window_p,
                      name="w")
        .key_by()
        .reduce(num_keys=VOCAB, parallelism=reduce_p, name="r")
        .key_by()                 # HASH into the sink: partition kind is
        .sink(parallelism=2))     # then independent of the reduce P
    return env.build()


def _collect_sink(runner, epochs, complete=False):
    """Run epochs, returning the multiset of sink records."""
    got = []
    sink_vid = 3

    def absorb(outs, _epoch):
        b = outs.sinks.get(sink_vid)
        if b is None:
            return
        k = np.asarray(b.keys)
        v = np.asarray(b.values)
        t = np.asarray(b.timestamps)
        m = np.asarray(b.valid)
        got.extend(zip(k[m].tolist(), v[m].tolist(), t[m].tolist()))

    runner.executor.on_block_outputs = absorb
    for _ in range(epochs):
        runner.run_epoch(complete_checkpoint=complete)
    runner.executor.on_block_outputs = None
    return sorted(got)


@pytest.mark.parametrize("p_old,p_new", [(2, 4), (4, 2)])
def test_rescale_restore_identical_sink_output(p_old, p_new, tmp_path):
    spe = 6
    # Reference incarnation: checkpoint at the fence, keep running.
    ref = ClusterRunner(_job(p_old, p_old), steps_per_epoch=spe,
                        log_capacity=256, max_epochs=8,
                        inflight_ring_steps=16, seed=11,
                        checkpoint_dir=str(tmp_path))
    ref.executor.time_source = TickTime()
    ref.run_epoch(complete_checkpoint=True)
    ckpt = ref.standbys.latest
    fence_t = ref.executor.time_source.t
    want = _collect_sink(ref, 2)

    # Rescaled incarnation: same topology, keyed vertices at p_new.
    res = ClusterRunner.restore_rescaled(
        _job(p_new, p_new), _job(p_old, p_old), ckpt,
        steps_per_epoch=spe, log_capacity=256, max_epochs=8,
        inflight_ring_steps=16, seed=11)
    res.executor.time_source = TickTime(t0=fence_t)
    got = _collect_sink(res, 2)
    assert got == want and len(got) > 0

    # The rescaled keyed tables respect the new ownership exactly.
    acc = np.asarray(res.executor.vertex_state(2)["acc"])
    kg = np.asarray(key_group(jnp.arange(VOCAB), 16))
    owner = np.asarray(subtask_for_key_group(jnp.asarray(kg), p_new, 16))
    for s in range(p_new):
        assert not np.any(acc[s][owner != s])


def test_rescale_dense_table_conserves_and_partitions():
    rng = np.random.RandomState(0)
    G, K = 16, VOCAB
    for p_old, p_new in ((2, 4), (4, 2), (3, 5)):
        kg = np.asarray(key_group(jnp.arange(K), G))
        owner_old = np.asarray(subtask_for_key_group(
            jnp.asarray(kg), p_old, G))
        table = np.zeros((p_old, K), np.int32)
        for k in range(K):
            table[owner_old[k], k] = rng.randint(1, 100)
        out = np.asarray(rescale_dense_table(jnp.asarray(table), p_new, G))
        assert out.shape == (p_new, K)
        np.testing.assert_array_equal(out.sum(axis=0), table.sum(axis=0))
        owner_new = np.asarray(subtask_for_key_group(
            jnp.asarray(kg), p_new, G))
        for s in range(p_new):
            assert not np.any(out[s][owner_new != s])


def test_rescale_rejects_non_hash_edges():
    env = StreamEnvironment(name="fwd", num_key_groups=8,
                            default_edge_capacity=16)
    (env.synthetic_source(vocab=5, batch_size=4, parallelism=2)
        .key_by().reduce(num_keys=5, parallelism=2).sink(parallelism=2))
    job_old = env.build()
    env2 = StreamEnvironment(name="fwd", num_key_groups=8,
                             default_edge_capacity=16)
    (env2.synthetic_source(vocab=5, batch_size=4, parallelism=2)
         .key_by().reduce(num_keys=5, parallelism=4).sink(parallelism=2))
    job_new = env2.build()
    r = ClusterRunner(job_old, steps_per_epoch=4, log_capacity=128,
                      max_epochs=8, inflight_ring_steps=8, seed=1)
    r.run_epoch(complete_checkpoint=True)
    # Sabotage: claim the reduce input edge is FORWARD.
    from clonos_tpu.graph.job_graph import PartitionType
    job_new.edges[0].partition = PartitionType.FORWARD
    job_old.edges[0].partition = PartitionType.FORWARD
    with pytest.raises(rec.RecoveryError):
        ClusterRunner.restore_rescaled(
            job_new, job_old, r.standbys.latest, steps_per_epoch=4,
            log_capacity=128, max_epochs=8, inflight_ring_steps=8, seed=1)


# --- cold paths: guards and state surgery ------------------------------------


def _cap_job(window_p: int, cap: int):
    env = StreamEnvironment(name=f"cap-{window_p}-{cap}",
                            num_key_groups=16, default_edge_capacity=cap)
    (env.synthetic_source(vocab=VOCAB, batch_size=8, parallelism=2)
        .key_by()
        .window_count(num_keys=VOCAB, window_size=7,
                      parallelism=window_p, name="w")
        .key_by()
        .sink(parallelism=2))
    return env.build()


def test_restore_rescaled_topology_mismatch():
    """A re-cut is a repartition, not a redeploy: a job with a
    different vertex/edge count must be refused loudly."""
    env = StreamEnvironment(name="topo", num_key_groups=16,
                            default_edge_capacity=96)
    (env.synthetic_source(vocab=VOCAB, batch_size=8, parallelism=2)
        .key_by().reduce(num_keys=VOCAB, parallelism=2, name="r")
        .key_by().sink(parallelism=2))
    job_short = env.build()
    r = ClusterRunner(_job(2, 2), steps_per_epoch=4, log_capacity=256,
                      max_epochs=8, inflight_ring_steps=16, seed=1)
    r.run_epoch(complete_checkpoint=True)
    with pytest.raises(rec.RecoveryError, match="topology mismatch"):
        ClusterRunner.restore_rescaled(
            job_short, r.job, r.standbys.latest, steps_per_epoch=4,
            log_capacity=256, max_epochs=8, inflight_ring_steps=16,
            seed=1)


def test_restore_rescaled_edge_buffer_overflow_fails_loud():
    """Rescaling DOWN concentrates old lanes' in-flight records; if the
    new cut's edge capacity cannot hold them the restore must raise —
    silently dropping them would break the identical-output contract."""
    r = ClusterRunner(_cap_job(4, 96), steps_per_epoch=5,
                      log_capacity=256, max_epochs=8,
                      inflight_ring_steps=16, seed=2)
    r.run_epoch(complete_checkpoint=True)
    buf = r.standbys.latest.carry.edge_bufs[0]
    assert int(np.asarray(buf.valid).sum()) > 8, \
        "fixture must capture enough in-flight records to overflow"
    with pytest.raises(rec.RecoveryError, match="overflows capacity"):
        ClusterRunner.restore_rescaled(
            _cap_job(1, 8), _cap_job(4, 8), r.standbys.latest,
            steps_per_epoch=5, log_capacity=256, max_epochs=8,
            inflight_ring_steps=16, seed=2)


def test_rescale_keyed_state_roundtrip_up_down():
    """rescale_keyed_state up then back down is the identity on a real
    run's keyed operator states: the split/merge moves every row to its
    key-group owner and conserves content, so returning to the original
    cut returns the original tables."""
    import jax

    r = ClusterRunner(_job(2, 2), steps_per_epoch=6, log_capacity=256,
                      max_epochs=8, inflight_ring_steps=16, seed=7)
    r.run_epoch(complete_checkpoint=True)
    G = r.job.num_key_groups
    for vid in (1, 2):                          # window, reduce
        op = r.job.vertices[vid].operator
        st = r.executor.carry.op_states[vid]
        up = op.rescale_keyed_state(st, 4, G)
        back = op.rescale_keyed_state(up, 2, G)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), st, back)


# --- the live re-cut (rescale_live) ------------------------------------------


def test_rescale_live_handoff_exactly_once(tmp_path):
    """Elastic repartition under live traffic, end to end: a 2->4
    re-cut at a completed fence produces sink output identical to a
    never-rescaled control, the protocol transitions fire in verified
    order (fence -> drain -> migrate -> redirect), the old incarnation
    is fenced off, the cross-layout ledger diff is clean while the
    exact diff refuses (the mapped path engaged), and a failure AFTER
    the re-cut recovers at the new parallelism."""
    from clonos_tpu.obs import audit as audit_mod
    from clonos_tpu.obs.digest import diff_ledgers

    kw = dict(steps_per_epoch=6, log_capacity=256, max_epochs=8,
              inflight_ring_steps=16, seed=11)
    ctl = ClusterRunner(_job(2, 2), checkpoint_dir=str(tmp_path / "a"),
                        audit=True, **kw)
    ctl.executor.time_source = TickTime()
    want = _collect_sink(ctl, 4, complete=True)

    r = ClusterRunner(_job(2, 2), checkpoint_dir=str(tmp_path / "b"),
                      audit=True, **kw)
    r.executor.time_source = TickTime()
    got = _collect_sink(r, 1, complete=True)
    r2, stats = r.rescale_live(_job(4, 4),
                               checkpoint_dir=str(tmp_path / "b"),
                               audit=True, **kw)
    got += _collect_sink(r2, 3, complete=True)
    assert sorted(got) == want and len(want) > 0

    kinds = [k for k, _ in stats["transitions"]]
    assert kinds[0] == "fence" and kinds[-1] == "redirect"
    assert kinds.count("migrate") == stats["groups"]
    assert stats["drained_records"] > 0
    assert stats["moved_key_groups"] and all(
        m > 0 for m in stats["moved_key_groups"].values())

    with pytest.raises(rec.RecoveryError):
        r.run_epoch()                    # stale writer: fenced off

    # exactly-once across the cut, via the audit layer's group mapping
    assert audit_mod.diff_ledgers_cross(ctl.auditor.ledger(),
                                        r2.auditor.ledger()) == []
    assert diff_ledgers(ctl.auditor.ledger(), r2.auditor.ledger()), \
        "exact diff must refuse across layouts (mapped path engaged)"

    # a failure AFTER the re-cut recovers at the new parallelism
    r2.inject_failure([2])
    assert r2.recover() is not None


def test_rescale_live_guards_refuse_bad_fences(tmp_path):
    """The protocol guards the model checks: no completed checkpoint,
    or a mid-epoch caller, cannot start a re-cut."""
    kw = dict(steps_per_epoch=6, log_capacity=256, max_epochs=8,
              inflight_ring_steps=16, seed=3)
    r = ClusterRunner(_job(2, 2), checkpoint_dir=str(tmp_path),
                      **kw)
    with pytest.raises(rec.RecoveryError, match="no completed"):
        r.rescale_live(_job(4, 4), checkpoint_dir=str(tmp_path), **kw)
    r.run_epoch(complete_checkpoint=True)
    r.step()                             # mid-epoch now
    with pytest.raises(rec.RecoveryError, match="mid-epoch"):
        r.rescale_live(_job(4, 4), checkpoint_dir=str(tmp_path), **kw)
