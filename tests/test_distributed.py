"""Rule-driven carry partitioning (parallel/distributed.py), the
sharding-aware compile-cache namespaces (utils/compile_cache.py),
per-shard snapshot slicing (runtime/checkpoint.py), and the lint's
pjit/shard_map traced-scope detection — all host-side and fast."""

import textwrap
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clonos_tpu.parallel import distributed as dist
from clonos_tpu.utils.compile_cache import (enable_compile_cache,
                                            sharding_cache_key)

P = jax.sharding.PartitionSpec

needs2 = pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")


def _tree(n=8):
    """A fake carry with one leaf per partition-rule family."""
    return {
        "op_states": [{"acc": jnp.zeros((n, 4))}],
        "out_rings": [{"keys": jnp.zeros((3, n, 16)),
                       "head": jnp.zeros((3,), jnp.int32)}],
        "logs": {"rows": jnp.zeros((3 * n, 5))},
        "rr_offsets": {"window": jnp.zeros((n,), jnp.int32)},
        "record_counts": jnp.zeros((3 * n,), jnp.int32),
        "epoch": jnp.zeros((), jnp.int32),
    }


@needs2
def test_partition_rules_per_leaf_family():
    mesh = dist.task_mesh(max_devices=2)
    spec = dist.infer_partition_spec(_tree(8), mesh)
    assert spec["op_states"][0]["acc"] == P("tasks")
    assert spec["out_rings"][0]["keys"] == P(None, "tasks"), \
        "ring tensors shard the subtask axis (axis 1 of [S, P, cap])"
    assert spec["out_rings"][0]["head"] == P(), "ring scalars replicate"
    assert spec["logs"]["rows"] == P("tasks")
    assert spec["rr_offsets"]["window"] == P(), "rr offsets replicate"
    assert spec["record_counts"] == P("tasks")
    assert spec["epoch"] == P(), "unmatched scalars replicate"


@needs2
def test_partition_rules_divisibility_guard():
    mesh = dist.task_mesh(max_devices=2)
    tree = {"op_states": [{"odd": jnp.zeros((7, 4))}],
            "logs": {"rows": jnp.zeros((0, 5))}}
    spec = dist.infer_partition_spec(tree, mesh)
    assert spec["op_states"][0]["odd"] == P(), \
        "a dim not divisible by the mesh replicates instead of failing"
    assert spec["logs"]["rows"] == P(), "zero-size dims never shard"


@needs2
def test_named_shardings_wrap_the_specs():
    mesh = dist.task_mesh(max_devices=2)
    ns = dist.named_shardings(_tree(8), mesh)
    leaf = ns["op_states"][0]["acc"]
    assert isinstance(leaf, jax.sharding.NamedSharding)
    assert leaf.spec == P("tasks") and leaf.mesh.shape["tasks"] == 2


def test_mesh_and_spec_fingerprints():
    assert dist.mesh_fingerprint(None) == "nomesh"
    m1 = dist.task_mesh(max_devices=1)
    f1 = dist.mesh_fingerprint(m1)
    assert f1 != "nomesh" and f1 == dist.mesh_fingerprint(m1), \
        "fingerprint is deterministic"
    if len(jax.devices()) >= 2:
        m2 = dist.task_mesh(max_devices=2)
        assert dist.mesh_fingerprint(m2) != f1
        sa = dist.infer_partition_spec(_tree(8), m2)
        sb = dist.infer_partition_spec({"epoch": jnp.zeros(())}, m2)
        assert dist.spec_fingerprint(sa) != dist.spec_fingerprint(sb)


def test_sharding_cache_key_namespaces(tmp_path):
    assert sharding_cache_key() == "nomesh-nospec"
    m1 = dist.task_mesh(max_devices=1)
    k1 = sharding_cache_key(mesh=m1)
    assert k1 != "nomesh-nospec"
    keys = [sharding_cache_key(), k1]
    if len(jax.devices()) >= 2:
        m2 = dist.task_mesh(max_devices=2)
        keys.append(sharding_cache_key(mesh=m2))
        keys.append(sharding_cache_key(
            mesh=m2, specs=dist.infer_partition_spec(_tree(8), m2)))
    assert len(keys) == len(set(keys)), "namespaces never collide"

    # enable_compile_cache namespaces the directory; restore the session
    # cache dir afterwards (conftest owns it).
    old = jax.config.jax_compilation_cache_dir
    try:
        used = enable_compile_cache(str(tmp_path / "cc"), mesh=m1)
        assert used == str(tmp_path / "cc" / k1)
        import os
        assert os.path.isdir(used)
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_snapshot_subtask_slice_and_nbytes():
    from clonos_tpu.runtime import checkpoint as cp

    snap = types.SimpleNamespace(op_states={
        1: {"a": np.zeros((4, 3), np.float32),
            "s": np.float32(0.0)}})
    sl = cp.snapshot_subtask_slice(snap, 1, 2)
    assert sl["a"].shape == (1, 3), "one [P, ...] row, batch dim kept"
    # One row of `a` (3 floats) + the scalar: 12 + 4 bytes.
    assert cp.snapshot_subtask_nbytes(snap, 1, 2) == 16
    full = sum(x.nbytes for x in (snap.op_states[1]["a"],
                                  snap.op_states[1]["s"]))
    assert cp.snapshot_subtask_nbytes(snap, 1, 2) < full


def test_lint_flags_pjit_and_shard_map_scopes(tmp_path, monkeypatch):
    from clonos_tpu.lint import run_lint

    monkeypatch.chdir(tmp_path)
    (tmp_path / "m.py").write_text(textwrap.dedent("""\
        from jax.experimental.pjit import pjit
        from jax.experimental.shard_map import shard_map

        @pjit
        def f(x):
            print(x)
            return x

        @shard_map
        def g(y):
            if y > 0:
                return y
            return -y
        """))
    res = run_lint(["m.py"], use_waivers=False)
    hits = {(f.rule, f.line) for f in res.findings}
    assert ("host-callback", 6) in hits, \
        "host call inside a pjit-wrapped def must be flagged"
    assert ("host-branch", 11) in hits, \
        "host branch inside a shard_map-wrapped def must be flagged"
