"""Incremental checkpoints: device-diffed chunk deltas, delta-chain
reads, base-liveness GC, and end-to-end recovery from an incremental
store (reference RocksDBKeyedStateBackend incremental checkpoints)."""

import os

import numpy as np
import pytest

from clonos_tpu.runtime.checkpoint import CompletedCheckpoint
from clonos_tpu.runtime.incremental import (DeviceDiffSnapshotter,
                                            IncrementalCheckpointStorage)


def _tree(rng, shapes=((64,), (7, 33), (128, 4))):
    return {f"leaf{i}": rng.randint(-99, 99, s).astype(np.int32)
            for i, s in enumerate(shapes)}


def _mutate(tree, rng, frac=0.02):
    out = {}
    for k, v in tree.items():
        v = v.copy()
        n = max(1, int(v.size * frac))
        idx = rng.choice(v.size, n, replace=False)
        v.reshape(-1)[idx] = rng.randint(-99, 99, n)
        out[k] = v
    return out


def _trees_equal(a, b):
    import jax
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_diff_roundtrip_sparse_and_dense():
    rng = np.random.RandomState(0)
    snap = DeviceDiffSnapshotter(chunk_elems=16, budget_frac=0.5)
    t0 = _tree(rng)
    kind, payload = snap.snapshot(t0)
    assert kind == "full"
    cur = t0
    for frac in (0.01, 0.05, 0.9):     # sparse deltas and a dense one
        nxt = _mutate(cur, rng, frac)
        kind, entries = snap.snapshot(nxt)
        assert kind == "delta"
        rebuilt = DeviceDiffSnapshotter.apply(cur, entries, 16)
        _trees_equal(rebuilt, nxt)
        cur = nxt
    # Unchanged snapshot -> all-None entries (nothing crosses the link).
    kind, entries = snap.snapshot(cur)
    assert kind == "delta" and all(e is None for e in entries)


def test_storage_chain_read_and_delta_files_smaller(tmp_path):
    rng = np.random.RandomState(1)
    st = IncrementalCheckpointStorage(str(tmp_path), base_every=4,
                                      chunk_elems=32)
    trees = [_tree(rng, shapes=((4096,),))]
    for i in range(6):
        trees.append(_mutate(trees[-1], rng, 0.01))
    for i, t in enumerate(trees):
        st.write(CompletedCheckpoint(checkpoint_id=i, carry=t,
                                     wall_time=0.0))
    for i, t in enumerate(trees):
        _trees_equal(st.read(i).carry, t)
    sizes = st.delta_bytes_on_disk()
    kinds = {c: st._index[c][0] for c in sorted(st._index)}
    assert kinds[0] == "full" and kinds[4] == "full"   # period base_every=4
    assert kinds[1] == kinds[2] == kinds[3] == kinds[5] == "delta"
    # ~1% mutations: each delta writes a fraction of the full size.
    assert sizes[1] < sizes[0] / 2
    assert sizes[5] < sizes[4] / 2
    assert st.list_ids() == list(range(7))


def test_delete_keeps_base_alive_until_chain_dies(tmp_path):
    rng = np.random.RandomState(2)
    st = IncrementalCheckpointStorage(str(tmp_path), base_every=10,
                                      chunk_elems=32)
    trees = [_tree(rng, shapes=((512,),))]
    for i in range(3):
        trees.append(_mutate(trees[-1], rng))
    for i, t in enumerate(trees):
        st.write(CompletedCheckpoint(checkpoint_id=i, carry=t,
                                     wall_time=0.0))
    st.delete(0)                       # base of the whole chain
    assert st.list_ids() == [1, 2, 3]
    with pytest.raises(KeyError):
        st.read(0)
    _trees_equal(st.read(3).carry, trees[3])   # chain still reads
    assert os.path.exists(st._path(0))         # physically retained
    for cid in (1, 2, 3):
        st.delete(cid)
    assert not os.path.exists(st._path(0))     # gc'd with its chain


def test_runner_recovers_from_incremental_store(tmp_path):
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    env = StreamEnvironment(name="inc", num_key_groups=8,
                            default_edge_capacity=64)
    (env.synthetic_source(vocab=13, batch_size=4, parallelism=2)
        .key_by().window_count(num_keys=13, window_size=1 << 30,
                               parallelism=2).sink(parallelism=2))
    runner = ClusterRunner(env.build(), steps_per_epoch=4,
                           log_capacity=256, max_epochs=8,
                           inflight_ring_steps=16, seed=17,
                           checkpoint_dir=str(tmp_path),
                           incremental_checkpoints=True,
                           incremental_base_every=2)
    for _ in range(3):
        runner.run_epoch(complete_checkpoint=True)
    runner.run_epoch(complete_checkpoint=False)
    runner.inject_failure([3])
    report = runner.recover()
    assert report.records_replayed > 0
    # The store shows the full/delta cadence on disk.
    from clonos_tpu.runtime.incremental import IncrementalCheckpointStorage
    st = runner.coordinator.storage
    assert isinstance(st, IncrementalCheckpointStorage)
    kinds = [st._index[c][0] for c in sorted(st._index)]
    assert "delta" in kinds and "full" in kinds


def test_index_survives_restart_and_orphans_are_gcd(tmp_path):
    rng = np.random.RandomState(3)
    st = IncrementalCheckpointStorage(str(tmp_path), base_every=3,
                                      chunk_elems=32)
    trees = [_tree(rng, shapes=((256,),))]
    for i in range(4):
        trees.append(_mutate(trees[-1], rng))
    for i, t in enumerate(trees):
        st.write(CompletedCheckpoint(checkpoint_id=i, carry=t,
                                     wall_time=0.0))
    # New process over the same dir: same ids, same content.
    st2 = IncrementalCheckpointStorage(str(tmp_path), base_every=3,
                                       chunk_elems=32)
    assert st2.list_ids() == st.list_ids() == [0, 1, 2, 3, 4]
    for i, t in enumerate(trees):
        _trees_equal(st2.read(i).carry, t)
    # A broken chain (base file removed out-of-band) is swept on startup.
    assert st._index[3][0] == "full"    # period 3: fulls at 0 and 3
    os.remove(st._path(3))              # base of the second chain
    st3 = IncrementalCheckpointStorage(str(tmp_path), base_every=3,
                                       chunk_elems=32)
    assert st3.list_ids() == [0, 1, 2]
    assert not os.path.exists(st._path(4))   # delta orphaned by 3's loss
    # Writes resume cleanly (fresh shadow -> full).
    st3.write(CompletedCheckpoint(checkpoint_id=9, carry=trees[0],
                                  wall_time=0.0))
    _trees_equal(st3.read(9).carry, trees[0])


def test_tombstones_survive_restart(tmp_path):
    """Logically deleted checkpoints must stay deleted across a restart
    (review finding: an in-memory-only zombie set resurrected them and
    stranded their files forever)."""
    rng = np.random.RandomState(5)
    st = IncrementalCheckpointStorage(str(tmp_path), base_every=10,
                                      chunk_elems=32)
    trees = [_tree(rng, shapes=((128,),))]
    for i in range(3):
        trees.append(_mutate(trees[-1], rng))
    for i, t in enumerate(trees):
        st.write(CompletedCheckpoint(checkpoint_id=i, carry=t,
                                     wall_time=0.0))
    st.delete(0)
    st.delete(1)
    assert st.list_ids() == [2, 3]
    st2 = IncrementalCheckpointStorage(str(tmp_path), base_every=10,
                                       chunk_elems=32)
    assert st2.list_ids() == [2, 3]          # not resurrected
    with pytest.raises(KeyError):
        st2.read(0)
    _trees_equal(st2.read(3).carry, trees[3])
    st2.delete(2)
    st2.delete(3)
    st3 = IncrementalCheckpointStorage(str(tmp_path), base_every=10,
                                       chunk_elems=32)
    assert st3.list_ids() == []
    assert [f for f in os.listdir(tmp_path)
            if f.startswith("inc_")] == []   # chain fully GC'd
