"""Deterministic autoscaler (clonos_tpu/autoscale): policy discipline
under adversarial signal traces, byte-identical SCALE determinant logs,
replay-not-re-decide recovery, the chaos ``load-spike`` plumbing, and
the runtime replica-count knob the replica arm executes through.

The model-level guarantees (no oscillation, monotone in sustained
signals, never rescale mid-recovery) live in verify/models.py's
ScalePolicyModel and ride the standard verify/conformance tests; here
the three seeded bugs are pinned to their exact minimal
counterexamples, and the real controller is driven through the same
protocol the soak driver uses.
"""

import json
import os

import numpy as np
import pytest

from clonos_tpu.autoscale import (HOLD, SCALE_REPLICAS, SCALE_WORKERS,
                                  AutoscaleController, DecisionLog,
                                  PolicyConfig, PolicyState,
                                  ScalePolicy, ScaleSignals,
                                  SignalAggregator, decision_row,
                                  signals_for_level)
from clonos_tpu.causal import determinant as det


def sig(epoch, load, workers=2, failed=0, unfenced=False, staleness=0,
        p99=0.0, replicas=1):
    return ScaleSignals(epoch=epoch, load=load, workers=workers,
                        failed_subtasks=failed, unfenced=unfenced,
                        max_staleness=staleness, p99_read_ms=p99,
                        replicas_alive=replicas,
                        replicas_total=replicas)


def drive(policy, signals, st=None):
    """Thread a signal trace through the pure policy; returns the
    decision list and the final state."""
    st = st or PolicyState()
    out = []
    for s in signals:
        d, st = policy.decide(s, st)
        out.append(d)
    return out, st


# --- the pure policy ------------------------------------------------------

def test_hysteresis_dead_band_never_scales():
    """An adversarial trace oscillating INSIDE the dead band
    (low_load < load < high_load) must hold forever — the classic
    flapping input hysteresis exists to ignore."""
    p = ScalePolicy()                      # high 1.25 / low 0.55
    decs, st = drive(p, [sig(e, load) for e, load in
                         enumerate([1.2, 0.6, 1.24, 0.56] * 4)])
    assert all(d.action == HOLD for d in decs)
    assert st.over_streak == 0 and st.under_streak == 0


def test_sustained_high_load_scales_up_one_bounded_step():
    p = ScalePolicy(PolicyConfig(sustain_fences=2, cooldown_fences=3,
                                 max_step=1, max_workers=4))
    decs, _ = drive(p, [sig(0, 2.0), sig(1, 2.0)])
    assert decs[0].action == HOLD          # one hot fence != a trend
    d = decs[1]
    assert d.action == SCALE_WORKERS and d.delta == 1
    assert d.target_workers == 3 and d.reason == "sustained-high-load"


def test_step_bound_and_worker_ceiling():
    """However hard the signals push, one action moves at most
    ``max_step`` workers, and never past ``max_workers`` — at the
    ceiling the policy holds rather than overshooting."""
    p = ScalePolicy(PolicyConfig(sustain_fences=1, cooldown_fences=2,
                                 max_step=1, max_workers=3))
    decs, _ = drive(p, [sig(e, 50.0, workers=w)
                        for e, w in enumerate([2, 3, 3])])
    assert [d.action for d in decs] == [SCALE_WORKERS, HOLD, HOLD]
    assert decs[0].target_workers == 3
    assert decs[1].reason == "cooldown"
    assert decs[2].reason == "steady"      # at ceiling: no arm fires


def test_cooldown_blocks_thrash_on_adversarial_flip():
    """High→action, then an immediate hard flip to low: the cooldown
    must absorb the flip — no opposite-direction action inside the
    window, and the post-cooldown trend is re-measured from scratch
    (streaks reset on action)."""
    cfg = PolicyConfig(sustain_fences=2, cooldown_fences=3)
    p = ScalePolicy(cfg)
    trace = [sig(0, 2.0), sig(1, 2.0)] + \
            [sig(e, 0.1, workers=3) for e in range(2, 8)]
    decs, _ = drive(p, trace)
    assert decs[1].action == SCALE_WORKERS
    # cooldown fences: nothing fires, reason says why
    assert [d.reason for d in decs[2:4]] == ["cooldown", "cooldown"]
    down = [d for d in decs if d.action == SCALE_WORKERS and d.delta < 0]
    assert down and down[0].seq - decs[1].seq >= cfg.cooldown_fences, \
        "opposite action landed inside the cooldown window"


def test_unhealthy_or_unfenced_always_holds():
    p = ScalePolicy(PolicyConfig(sustain_fences=1))
    d1, _ = drive(p, [sig(0, 9.0, failed=1)])
    d2, _ = drive(p, [sig(0, 9.0, unfenced=True)])
    assert d1[0].action == HOLD and d1[0].reason == "unhealthy"
    assert d2[0].action == HOLD and d2[0].reason == "unhealthy"


def test_replica_arms_lag_adds_idle_drops():
    """The read tier's arms: sustained staleness/p99 lag adds a
    replica (lower priority than a worker re-cut); sustained idle
    drops one only after the worker floor is reached."""
    cfg = PolicyConfig(sustain_fences=2, cooldown_fences=1,
                       staleness_high=2, min_workers=2, max_replicas=2)
    p = ScalePolicy(cfg)
    lag = [sig(e, 1.0, staleness=5, replicas=1) for e in range(2)]
    decs, _ = drive(p, lag)
    d = decs[1]
    assert d.action == SCALE_REPLICAS and d.delta == 1
    assert d.target_replicas == 2 and d.reason == "read-tier-lagging"
    # idle at the worker floor: drop a replica, never a worker
    idle = [sig(e, 0.1, workers=2, replicas=2) for e in range(2)]
    decs, _ = drive(p, idle)
    d = decs[1]
    assert d.action == SCALE_REPLICAS and d.delta == -1
    assert d.reason == "read-tier-idle"


def test_worker_recut_outranks_replica_add():
    p = ScalePolicy(PolicyConfig(sustain_fences=1, max_replicas=4))
    decs, _ = drive(p, [sig(0, 9.0, staleness=9, replicas=1)])
    assert decs[0].action == SCALE_WORKERS


# --- determinant log: byte identity + replay ------------------------------

TRACE = [1.0, 2.0, 2.0, 1.0, 0.2, 0.2, 0.2, 2.0, 2.0]


def _controller(path=None, **cfg):
    cfg.setdefault("sustain_fences", 2)
    cfg.setdefault("cooldown_fences", 2)
    executed = []
    c = AutoscaleController(
        ScalePolicy(PolicyConfig(**cfg)),
        log=DecisionLog(path),
        execute_workers=lambda t: executed.append(("workers", t)),
        add_replica=lambda: executed.append(("add", None)),
        drop_replica=lambda: executed.append(("drop", None)))
    return c, executed


def _run_trace(c, loads, workers=2, start=0):
    for i, load in enumerate(loads):
        w = workers
        c.on_fence(start + i, sig(start + i, load, workers=w))


def test_same_signal_trace_byte_identical_log(tmp_path):
    ca, _ = _controller(str(tmp_path / "a.det"))
    cb, _ = _controller(str(tmp_path / "b.det"))
    _run_trace(ca, TRACE)
    _run_trace(cb, TRACE)
    assert len(ca.log) == len(TRACE)
    assert ca.log.to_bytes() == cb.log.to_bytes()
    assert ca.log.digest() == cb.log.digest()
    # the on-disk bytes ARE the in-memory bytes (contiguous <i4 rows)
    with open(ca.log.path, "rb") as f:
        assert f.read() == ca.log.to_bytes()
    # and every row round-trips through the SCALE determinant class
    for row in ca.log.determinants():
        assert isinstance(row, det.ScaleDeterminant)
        assert row.record_count >= 1      # seq: never a sync anchor


def test_recovered_controller_replays_never_re_executes(tmp_path):
    """Kill-mid-cooldown, in miniature: the first incarnation executes
    a re-cut, then 'dies'. A new controller over the same log REPLAYS
    the logged SCALE determinants — same decisions, zero executions —
    and continues the sequence live from where the log ends."""
    path = str(tmp_path / "scale.det")
    c1, exec1 = _controller(path)
    _run_trace(c1, TRACE[:4])
    assert exec1, "the trace must have executed a scale action"
    n_logged = len(c1.log)

    c2, exec2 = _controller(path)          # recovery: log found, replayed
    assert len(c2.log) == n_logged
    assert c2.state == c1.state, "PolicyState rebuilt bit-identically"
    # re-observing the already-logged fences returns the logged
    # decisions and executes NOTHING — no double re-cut
    for i, load in enumerate(TRACE[:4]):
        d, executed = c2.on_fence(i, sig(i, load))
        assert executed is None
    assert exec2 == []
    assert c2.replayed_decisions == n_logged
    assert len(c2.log) == n_logged, "replay appends nothing"
    # live continuation: the next unseen fence decides and logs anew
    c2.on_fence(4, sig(4, TRACE[4]))
    assert len(c2.log) == n_logged + 1
    assert c2.log.records[-1]["decision"]["seq"] == n_logged + 1


def test_tampered_sidecar_refuses_replay(tmp_path):
    path = str(tmp_path / "scale.det")
    c1, _ = _controller(path)
    _run_trace(c1, TRACE[:3])
    lines = open(path + ".signals.jsonl").read().splitlines()
    rec = json.loads(lines[1])
    rec["signals"]["load"] = 77.0          # break the crc pin
    lines[1] = json.dumps(rec, sort_keys=True)
    with open(path + ".signals.jsonl", "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="crc pin"):
        _controller(path)


def test_torn_log_tail_truncates_to_agreed_prefix(tmp_path):
    path = str(tmp_path / "scale.det")
    c1, _ = _controller(path)
    _run_trace(c1, TRACE[:3])
    with open(path, "ab") as f:
        f.write(b"\x01\x02\x03")           # torn final row
    log = DecisionLog(path)
    assert len(log) == 3
    # torn sidecar line: rows past it are unreplayable, so they drop
    with open(path + ".signals.jsonl", "a") as f:
        f.write('{"broken')
    c2, _ = _controller(path)
    assert len(c2.log) == 3


# --- seeded bugs: exact minimal counterexamples ---------------------------

def _ce(bug):
    from clonos_tpu.verify.runner import run_verify
    r = run_verify(models=["scalepolicy"], quick=True,
                   bugs={"scalepolicy": bug})
    assert not r.ok and r.exit_code() == 1
    return r.violations[0]


def test_no_cooldown_bug_minimal_thrash():
    """Without the cooldown clock, one spike-then-idle flip thrashes:
    up at one fence, straight back down at the next."""
    v = _ce("no-cooldown")
    assert v.invariant == "no-thrash"
    assert [a.label() for a in v.trace] == [
        "signal(2)", "fence", "decide", "execute",
        "signal(0)", "fence", "decide", "execute"]


def test_unlogged_decision_bug_minimal_ce():
    """An executed scale action whose decision never hit the SCALE
    log: recovery would re-decide instead of replaying — the exact
    double-re-cut hazard the log exists to kill."""
    v = _ce("unlogged-decision")
    assert v.invariant == "decision-logged"
    assert [a.label() for a in v.trace] == [
        "signal(2)", "fence", "decide", "execute"]


def test_rescale_mid_recovery_bug_minimal_ce():
    """A kill lands between decide and execute; skipping the execute-
    time health re-check re-cuts over an in-progress recovery."""
    v = _ce("rescale-mid-recovery")
    assert v.invariant == "no-rescale-mid-recovery"
    assert [a.label() for a in v.trace] == [
        "signal(2)", "fence", "decide", "kill", "execute"]


def test_conformance_real_controller_matches_model():
    from clonos_tpu.verify.conformance import conform_scalepolicy
    rep = conform_scalepolicy()
    assert rep.ok, rep.divergences
    assert rep.steps > 0 and rep.traces > 0


# --- chaos DSL: load-spike ------------------------------------------------

def test_load_spike_parse_and_round_trip():
    from clonos_tpu.soak.chaos import parse_schedule
    s = parse_schedule("at 1.2s load-spike 4x for 2s")
    (ev,) = list(s)
    assert ev.kind == "load-spike" and ev.factor == 4.0
    assert ev.at_s == 1.2 and ev.duration_s == 2.0
    assert parse_schedule(s.to_text()).to_text() == s.to_text()
    # bare multiplier (no 'x') parses too
    (ev2,) = list(parse_schedule("at 500ms load-spike 2.5 for 1s"))
    assert ev2.factor == 2.5


def test_load_spike_rejects_bad_factor_or_missing_duration():
    from clonos_tpu.soak.chaos import parse_schedule
    with pytest.raises(ValueError):
        parse_schedule("at 1s load-spike 0x for 2s")
    with pytest.raises(ValueError):
        parse_schedule("at 1s load-spike 4x")


def test_seeded_schedule_covers_load_spike_and_round_trips():
    from clonos_tpu.soak.chaos import ChaosSchedule, parse_schedule
    s = ChaosSchedule.seeded(seed=7, duration_s=30.0,
                             targets=[1, 2], kinds=("load-spike",),
                             n_events=3)
    evs = list(s)
    assert len(evs) == 3
    assert all(ev.factor in (2.0, 4.0) for ev in evs)
    assert all(ev.duration_s > 0 for ev in evs)
    assert parse_schedule(s.to_text()).to_text() == s.to_text()


def test_model_ce_compiles_to_load_spike_chaos_event():
    """The verify→chaos bridge: a scalepolicy counterexample's
    signal(2) step carries a load-spike hint that compiles to a
    parseable DSL event."""
    from clonos_tpu.verify.bridge import compile_trace
    from clonos_tpu.soak.chaos import parse_schedule
    v = _ce("no-cooldown")
    sched = compile_trace(v)
    spikes = [ev for ev in sched if ev.kind == "load-spike"]
    assert spikes and spikes[0].factor == 4.0
    assert parse_schedule(sched.to_text()).to_text() == sched.to_text()


# --- runtime replica knob (the replica arm's executor) --------------------

VID = 1
NUM_KEYS = 11


def _serve_runner(seed=3):
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner
    env = StreamEnvironment(name="serve", num_key_groups=16,
                            default_edge_capacity=64)
    (env.synthetic_source(vocab=NUM_KEYS, batch_size=8, parallelism=2)
        .key_by().reduce(num_keys=NUM_KEYS, name="r").sink())
    return ClusterRunner(env.build(), steps_per_epoch=4,
                         log_capacity=256, max_epochs=8,
                         inflight_ring_steps=16, seed=seed)


def test_add_replica_serves_at_next_seal_and_drop_contracts():
    from clonos_tpu.runtime.serve import build_serve_tier
    r = _serve_runner()
    tier = build_serve_tier(r, VID, n_replicas=1)
    try:
        r.run_epoch(complete_checkpoint=True)
        r.drain_fence()
        keys = list(range(NUM_KEYS))
        owner_vals = tier.owner_client.query_batch(VID, keys)["values"]

        i = tier.add_replica()
        assert i == 1 and len(tier.router.replicas) == 2
        # adopted the standby restore point: honest staleness, and the
        # next seal refills it to the fence
        r.run_epoch(complete_checkpoint=True)
        r.drain_fence()
        assert tier.staleness()[1] == 0
        out = tier.router.query_batch(VID, keys)
        assert out["values"] == tier.owner_client.query_batch(
            VID, keys)["values"]
        # the kg % 2 map now routes some groups to the new replica
        groups = {tier.router.replica_for_group(g) for g in range(16)}
        assert groups == {0, 1}
        snap = r.metrics.snapshot()
        assert "serve.replica.1.staleness-epochs" in snap

        dropped = tier.drop_replica()
        assert dropped == 1 and len(tier.router.replicas) == 1
        assert "serve.replica.1.staleness-epochs" not in \
            r.metrics.snapshot()
        # reads still answer, all groups back on replica 0 / owner
        out = tier.router.query_batch(VID, keys)
        assert out["values"] == owner_vals or out["values"] == \
            tier.owner_client.query_batch(VID, keys)["values"]
        with pytest.raises(ValueError):
            tier.drop_replica()            # never below one replica
    finally:
        tier.close()


def test_controller_replica_arm_drives_the_tier():
    """The controller's add/drop callbacks wired to a real tier: a
    sustained read-lag trace grows the tier, a sustained idle trace
    (at the worker floor) shrinks it."""
    from clonos_tpu.runtime.serve import build_serve_tier
    r = _serve_runner()
    tier = build_serve_tier(r, VID, n_replicas=1)
    try:
        r.run_epoch(complete_checkpoint=True)
        r.drain_fence()
        c = AutoscaleController(
            ScalePolicy(PolicyConfig(sustain_fences=2,
                                     cooldown_fences=1, min_workers=2,
                                     max_replicas=2)),
            add_replica=tier.add_replica,
            drop_replica=tier.drop_replica)
        for e in range(2):
            c.on_fence(e, sig(e, 1.0, staleness=9,
                              replicas=len(tier.replicas)))
        assert len(tier.replicas) == 2 and c.replicas_added == 1
        for e in range(2, 5):
            c.on_fence(e, sig(e, 0.1, workers=2,
                              replicas=len(tier.replicas)))
        assert len(tier.replicas) == 1 and c.replicas_dropped == 1
    finally:
        tier.close()


# --- signal plane off a real registry snapshot ----------------------------

def test_signal_aggregator_samples_registry_rollup():
    from clonos_tpu.utils.metrics import MetricRegistry
    reg = MetricRegistry()
    g = reg.group("soak")
    g.gauge("offered-rate", lambda: 4000.0)
    g.gauge("rate", lambda: 2000.0)
    g.gauge("backlog-chunks", lambda: 3)
    sg = reg.group("serve")
    sg.gauge("replica.0.staleness-epochs", lambda: 1)
    sg.gauge("replica.1.staleness-epochs", lambda: 4)
    sg.gauge("p99-read-ms", lambda: 12.5)
    agg = SignalAggregator(window=2)
    s = agg.sample_from(reg.snapshot(), epoch=7, workers=2)
    assert s.load == 2.0                   # offered / achieved
    assert s.backlog_chunks == 3
    assert s.max_staleness == 4 and s.replicas_total == 2
    assert s.p99_read_ms == 12.5
    # window smoothing: a second, calmer fence averages in
    g.remove("offered-rate")
    g.gauge("offered-rate", lambda: 2000.0)
    s2 = agg.sample_from(reg.snapshot(), epoch=8, workers=2)
    assert s2.load == 1.5
    # canonical bytes: equal snapshots, equal crc; dicts round-trip
    assert ScaleSignals.from_dict(
        json.loads(s2.canonical())).crc() == s2.crc()


def test_signals_for_level_matches_conformance_loads():
    lo = signals_for_level(0, epoch=0, workers=2)
    hi = signals_for_level(2, epoch=0, workers=2)
    p = ScalePolicy(PolicyConfig(sustain_fences=1))
    d, _ = p.decide(hi, PolicyState())
    assert d.action == SCALE_WORKERS
    d, _ = p.decide(lo, PolicyState())
    assert d.action == HOLD or d.delta <= 0


def test_top_table_renders_autoscale_row():
    from clonos_tpu.cli import _top_table
    snap = {"autoscale.decisions-total": 5,
            "autoscale.rescales-executed": 1,
            "autoscale.cooldown-active": 2,
            "autoscale.target-workers": 3,
            "autoscale.actual-workers": 3}
    table = _top_table(snap)
    assert "autoscale:" in table
    line = next(l for l in table.splitlines()
                if l.startswith("autoscale:"))
    assert "decisions-total=5" in line and "target-workers=3" in line
    # suffix matching survives a worker.<eid> prefix
    assert "autoscale:" in _top_table(
        {"worker.w1.autoscale.decisions-total": 2})
    assert "autoscale:" not in _top_table({"worker.w0.slots": 1})


# --- the closed loop, end to end (acceptance) -----------------------------

@pytest.mark.slow
def test_closed_loop_soak_recuts_itself_under_load_spike(tmp_path):
    """The PR's acceptance bar: a mid-run ``load-spike 4x`` drives the
    system to re-cut ITSELF at a completed fence — zero operator
    rescale events — while the byte-exact exactly-once audit against
    the fault-free control twin stays clean across the self-directed
    handoff, and the cooldown rate-limits to at most one scale action
    per window."""
    from clonos_tpu.obs import audit as audit_mod
    from clonos_tpu.soak import (SLOSpec, SoakConfig, SoakDriver,
                                 build_soak_fixture, parse_schedule)

    runner, control, election = build_soak_fixture(
        str(tmp_path), rate=4000.0, duration_s=4.0,
        steps_per_epoch=32, seed=11)
    ctl = AutoscaleController(
        ScalePolicy(PolicyConfig(sustain_fences=2, cooldown_fences=3,
                                 min_workers=1, max_workers=4)),
        log=DecisionLog(str(tmp_path / "scale.det")))
    driver = SoakDriver(
        runner, SoakConfig(rate=4000.0, duration_s=4.0, window_s=1.0,
                           chunk_steps=8, complete_every=2),
        schedule=parse_schedule("at 1.2s load-spike 4x for 1.5s"),
        spec=SLOSpec(exactly_once=True),
        control=control, election=election, records_per_step=16,
        autoscaler=ctl)
    v = driver.run()

    assert v["pass"] is True
    assert v["audit"]["exactly_once"] is True
    assert v["audit"]["divergences"] == []
    a = v["autoscale"]
    assert a["operator_rescale_events"] == 0, "the loop must be closed"
    assert a["autoscale_rescales"] >= 1, "the spike must force a re-cut"
    assert a["rescales_executed"] == a["autoscale_rescales"]
    assert a["max_actions_per_cooldown"] <= 1
    assert a["decisions"] == len(ctl.log)
    for st in a["rescale_stats"]:
        assert sum(st["moved_key_groups"].values()) > 0
    # the driver really swapped to the re-cut incarnation
    assert driver.runner is not runner
    snap = driver.runner.metrics.snapshot()
    assert snap["autoscale.rescales-executed"] == \
        a["autoscale_rescales"]
    assert snap["soak.offered-rate"] == 4000.0   # spike expired
    # layout-aware cross diff agrees with the exact per-fence audit
    assert audit_mod.diff_ledgers_cross(
        driver.harness.control.auditor.ledger(),
        driver.runner.auditor.ledger()) == []
    # every decision replayable: a fresh controller over the log
    # reproduces it bit-for-bit (the ValueError path is the witness)
    c2 = AutoscaleController(
        ScalePolicy(PolicyConfig(sustain_fences=2, cooldown_fences=3,
                                 min_workers=1, max_workers=4)),
        log=DecisionLog(str(tmp_path / "scale.det")))
    assert len(c2.log) == len(ctl.log)
    assert c2.log.digest() == ctl.log.digest()
