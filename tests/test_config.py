"""Typed config system tests (reference ConfigOptions/Configuration)."""

import pytest

from clonos_tpu.config import ConfigOption, Configuration, defaults


def test_defaults_and_typed_get():
    c = Configuration()
    assert c.get(defaults.DETERMINANT_SHARING_DEPTH) == -1
    assert c.get(defaults.INFLIGHT_TYPE) == "inmemory"
    c.set(defaults.DETERMINANT_SHARING_DEPTH, 2)
    assert c.get(defaults.DETERMINANT_SHARING_DEPTH) == 2


def test_type_enforcement():
    c = Configuration()
    with pytest.raises(TypeError):
        c.set(defaults.NUM_STANDBY_TASKS, "two")
    with pytest.raises(TypeError):
        c.set(defaults.NUM_STANDBY_TASKS, True)  # bool is not int here


def test_validator():
    c = Configuration()
    with pytest.raises(ValueError):
        c.set(defaults.INFLIGHT_TYPE, "bogus")
    with pytest.raises(ValueError):
        c.set(defaults.DETERMINANT_LOG_CAPACITY, 1000)  # not a power of two
    c.set(defaults.DETERMINANT_LOG_CAPACITY, 1024)


def test_int_to_float_coercion():
    c = Configuration()
    c.set(defaults.CHECKPOINT_BACKOFF_MULTIPLIER, 3)
    assert c.get(defaults.CHECKPOINT_BACKOFF_MULTIPLIER) == 3.0


def test_merge_and_raw():
    a = Configuration({"x": 1})
    b = Configuration({"x": 2, "y": 3})
    m = a.merged_with(b)
    assert m.to_dict() == {"x": 2, "y": 3}
    opt = ConfigOption("x", 0)
    assert m.get(opt) == 2
