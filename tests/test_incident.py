"""Incident forensics plane (clonos_tpu/obs/incident.py + rootcause.py).

Unit layers first — the shared JSONL substrate (appender, torn-tail
tolerant reader, atomic rewrite) that every durable log in the repo now
rides, the streaming k-way timeline merge (byte-equal to the
materializing merge it replaced), and the flight recorder's capture
discipline: bundles land atomically, deduplicate by trigger
fingerprint, rate-limit per kind, cap at max_bundles, and a restarted
manager resumes numbering + dedup from the bundles on disk. The
root-cause analyzer is pure — the byte-identity test runs ``incident
explain --report json`` in two fresh interpreter processes and demands
identical bytes. The slow test is the end-to-end acceptance: an
unlogged nondet perturbation (the examples/audit_nondet.py class)
injected under a live soak must auto-capture a bundle whose
localization names the salted ring channel, the first divergent
determinant step, and the injecting worker.
"""

import json
import os
import subprocess
import sys

import pytest

from clonos_tpu.obs import incident as inc
from clonos_tpu.obs import rootcause as rc
from clonos_tpu.obs.hlc import reset_hlc
from clonos_tpu.obs.timeline import (causality_inversions,
                                     causality_inversions_stream,
                                     configure_timeline, get_timeline,
                                     iter_merged, merge_records,
                                     read_timeline, reset_timeline)
from clonos_tpu.utils.jsonl import (JsonlAppender, atomic_rewrite_jsonl,
                                    iter_jsonl)
from clonos_tpu.utils.metrics import MetricRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_globals():
    yield
    inc.reset_incidents()
    reset_timeline()
    reset_hlc()


# --- JSONL substrate ---------------------------------------------------------


def test_jsonl_appender_roundtrip(tmp_path):
    path = str(tmp_path / "a.jsonl")
    w = JsonlAppender(path, sort_keys=True)
    w.append({"b": 2, "a": 1})
    w.append({"x": [1, 2]})
    w.sync()
    w.close()
    assert w.appended == 2
    rows = list(iter_jsonl(path, "test"))
    assert rows == [{"a": 1, "b": 2}, {"x": [1, 2]}]
    # sort_keys really landed on disk (deterministic ledger encoding)
    with open(path) as f:
        assert f.readline().startswith('{"a"')


def test_iter_jsonl_tolerates_torn_tail_only(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        f.write('{"ok": 1}\n{"torn": ')   # crash mid-append
    assert list(iter_jsonl(path, "test")) == [{"ok": 1}]
    # mid-file corruption (valid data AFTER the bad line) must raise —
    # that is not a torn tail, it is a damaged file
    with open(path, "w") as f:
        f.write('{"ok": 1}\nGARBAGE\n{"ok": 2}\n')
    with pytest.raises(ValueError):
        list(iter_jsonl(path, "test"))


def test_atomic_rewrite_jsonl(tmp_path):
    path = str(tmp_path / "r.jsonl")
    with open(path, "w") as f:
        f.write('{"old": 1}\n' * 5)
    n = atomic_rewrite_jsonl(path, [{"new": i} for i in range(3)])
    assert n == 3
    assert [r["new"] for r in iter_jsonl(path, "test")] == [0, 1, 2]
    assert not os.path.exists(path + ".tmp")


def _write_timeline(path, service, stamps):
    w = JsonlAppender(str(path), default=str)
    for i, (l_us, c) in enumerate(stamps):
        w.append({"kind": f"k{i}", "ts": 0.0, "hlc": [l_us, c, service],
                  "service": service, "pid": 1})
    w.close()


def test_iter_merged_matches_materialized_merge(tmp_path):
    a, b = tmp_path / "ta.jsonl", tmp_path / "tb.jsonl"
    _write_timeline(a, "a", [(10, 0), (30, 0), (30, 2)])
    _write_timeline(b, "b", [(20, 0), (30, 1), (40, 0)])
    paths = [str(a), str(b)]
    streamed = list(iter_merged(paths))
    batch = merge_records(read_timeline(paths))
    assert streamed == batch
    assert [r["hlc"][0] for r in streamed] == [10, 20, 30, 30, 30, 40]


def test_causality_inversions_stream_matches_batch(tmp_path):
    # one clean exchange + one inversion: the recv's HLC is NOT past
    # the send's (a broken receive rule)
    recs = [
        {"kind": "msg.send", "ts": 0.0, "hlc": [10, 0, "a"],
         "service": "a", "pid": 1},
        {"kind": "msg.recv", "ts": 0.0, "hlc": [11, 0, "b"],
         "service": "b", "pid": 2, "sent": [10, 0, "a"]},
        {"kind": "msg.send", "ts": 0.0, "hlc": [20, 0, "a"],
         "service": "a", "pid": 1},
        {"kind": "msg.recv", "ts": 0.0, "hlc": [15, 0, "b"],
         "service": "b", "pid": 2, "sent": [20, 0, "a"]},
    ]
    merged = merge_records(recs)
    batch = causality_inversions(merged)
    streamed = causality_inversions_stream(iter(merged))
    assert batch and streamed
    assert len(batch) == len(streamed)
    assert {f["rule"] for f in streamed} == {f["rule"] for f in batch} \
        == {"stamp", "merge"}


def test_cli_timeline_streaming_report_counts_inversions(tmp_path):
    a = tmp_path / "timeline-a.jsonl"
    _write_timeline(a, "a", [(10, 0), (20, 0)])
    out = subprocess.run(
        [sys.executable, "-m", "clonos_tpu.cli", "timeline",
         str(a), "--report", "json"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["ok"] is True
    assert line["records"] == 2
    assert line["inversions"] == []


def test_cli_timeline_inversion_fires_armed_recorder(tmp_path, capsys):
    from clonos_tpu import cli
    path = tmp_path / "timeline-bad.jsonl"
    recs = [
        {"kind": "msg.send", "ts": 0.0, "hlc": [20, 0, "a"],
         "service": "a", "pid": 1},
        {"kind": "msg.recv", "ts": 0.0, "hlc": [15, 0, "b"],
         "service": "b", "pid": 2, "sent": [20, 0, "a"]},
    ]
    path.write_text("".join(json.dumps(r) + "\n"
                            for r in sorted(recs,
                                            key=lambda r: r["hlc"])))
    mgr = inc.configure_incidents(str(tmp_path), service="cli")
    rc_code = cli.main(["timeline", str(path), "--report", "json"])
    capsys.readouterr()
    assert rc_code == 1
    (bundle_path,) = mgr.bundles()
    b = inc.load_bundle(bundle_path)
    assert b["trigger"]["kind"] == "timeline.inversion"
    assert b["trigger"]["count"] == 2      # stamp + merge rule findings


# --- the flight recorder -----------------------------------------------------


def test_null_incident_manager_is_inert():
    mgr = inc.get_incidents()
    assert mgr.enabled is False
    assert mgr.signal("slo.breach", window=0) is None
    assert mgr.bundles() == []
    assert (mgr.captured, mgr.deduped, mgr.suppressed,
            mgr.signals) == (0, 0, 0, 0)
    # zero wire/metric surface: registering the Null plane adds nothing
    reg = MetricRegistry()
    mgr.register_gauges(reg)
    assert not any(k.startswith("incident.") for k in reg.snapshot())


def _manager(tmp_path, **kw):
    clock = {"t": 100.0}
    kw.setdefault("service", "test")
    kw.setdefault("min_interval_s", 5.0)
    mgr = inc.IncidentManager(str(tmp_path), clock=lambda: clock["t"],
                              **kw)
    return mgr, clock


def test_unknown_kind_raises(tmp_path):
    mgr, _ = _manager(tmp_path)
    with pytest.raises(ValueError):
        mgr.signal("not-a-kind")


def test_capture_lands_atomic_bundle_with_sections(tmp_path):
    mgr, _ = _manager(tmp_path)
    mgr.attach(
        ledgers=lambda: {"expected": [{"epoch": 3, "channels": {}}],
                         "actual": [{"epoch": 3, "channels": {}}]},
        chaos=lambda: "at 1s nondet",
        config=lambda: {"rate": 100.0},
        metrics=lambda: [{"metrics": {"m": 1}}])
    path = mgr.signal("audit.divergence", epoch=3, problem="p")
    assert path is not None and os.path.isfile(path)
    assert os.path.basename(path) == "incident-0001-audit.divergence.json"
    assert not any(n.endswith(".tmp") for n in os.listdir(mgr.dir))
    b = inc.load_bundle(path)
    assert b["bundle"]["schema"] == "clonos-incident-bundle/v1"
    assert b["bundle"]["schema_fingerprint"] == \
        inc.bundle_schema_fingerprint()
    assert b["trigger"] == {"kind": "audit.divergence", "epoch": 3,
                            "problem": "p"}
    assert b["ledgers"]["actual"][0]["epoch"] == 3
    assert b["chaos"] == "at 1s nondet"
    assert b["config"] == {"rate": 100.0}
    assert mgr.captured == 1 and mgr.signals == 1
    # incident.* gauges ride a registry like every other plane
    reg = MetricRegistry()
    mgr.register_gauges(reg)
    assert reg.snapshot()["incident.captured"] == 1


def test_dedup_rate_limit_and_cap(tmp_path):
    mgr, clock = _manager(tmp_path, max_bundles=3)
    assert mgr.signal("slo.breach", window=1) is not None
    # novel trigger inside min_interval_s of the last capture →
    # rate-limited
    clock["t"] += 1.0
    assert mgr.signal("slo.breach", window=2) is None
    assert mgr.suppressed == 1
    # identical trigger → dedup, even after the rate window passes
    clock["t"] += 100.0
    assert mgr.signal("slo.breach", window=1) is None
    assert mgr.deduped == 1
    assert mgr.signal("slo.breach", window=2) is not None
    clock["t"] += 100.0
    assert mgr.signal("slo.breach", window=3) is not None
    # bundle cap: the 4th novel signal is suppressed, not captured
    clock["t"] += 100.0
    assert mgr.signal("slo.breach", window=4) is None
    assert mgr.captured == 3 and len(mgr.bundles()) == 3


def test_restart_resumes_seq_and_dedup(tmp_path):
    mgr, clock = _manager(tmp_path)
    mgr.signal("slo.breach", window=1)
    clock["t"] += 100.0
    mgr.signal("timeline.inversion", rule="stamp")
    # a fresh manager over the same root: dedups the old triggers,
    # continues the sequence numbering
    mgr2, clock2 = _manager(tmp_path)
    assert mgr2.signal("slo.breach", window=1) is None
    assert mgr2.deduped == 1
    clock2["t"] += 100.0
    path = mgr2.signal("slo.breach", window=9)
    assert os.path.basename(path).startswith("incident-0003-")


def test_provider_error_degrades_section_not_bundle(tmp_path):
    mgr, _ = _manager(tmp_path)
    mgr.attach(ledgers=lambda: 1 / 0)
    path = mgr.signal("recovery.failure", epoch=1, error="x")
    b = inc.load_bundle(path)
    assert "provider-error" in b["ledgers"]


def test_ledger_section_trimmed_to_epoch_radius(tmp_path):
    mgr, _ = _manager(tmp_path, epoch_radius=1)
    entries = [{"epoch": e, "channels": {}} for e in range(10)]
    mgr.attach(ledgers=lambda: {"expected": entries, "actual": entries})
    b = inc.load_bundle(mgr.signal("audit.divergence", epoch=5))
    assert [e["epoch"] for e in b["ledgers"]["actual"]] == [4, 5, 6]


def test_attach_rejects_unknown_slot(tmp_path):
    mgr, _ = _manager(tmp_path)
    with pytest.raises(ValueError):
        mgr.attach(ledgrs=lambda: {})


def test_signal_records_capture_on_timeline(tmp_path):
    configure_timeline("test")
    mgr, _ = _manager(tmp_path)
    mgr.signal("slo.breach", window=0)
    kinds = [r["kind"] for r in get_timeline().records()]
    assert "incident.captured" in kinds


# --- deterministic root cause ------------------------------------------------


def test_incident_self_check_clean():
    assert inc.incident_self_check() == []


def test_rootcause_localizes_synthetic_ring_bundle():
    b = inc._synthetic_bundles()["unlogged-ring"]
    rep = rc.analyze_bundle(b)
    assert rep["verdict"] == "localized"
    assert rep["first_divergent_epoch"] == 2
    assert rep["first_divergent_channel"] == "ring/v1"
    assert rep["determinant"]["kind"] == "ring-step"
    assert "unlogged nondeterminism" in rep["determinant"]["note"]
    assert rep["injected_by"] == "w0"
    assert rep["causal_chain"][0]["kind"] == "chaos"


def test_rootcause_no_divergence_verdict():
    entries = [{"epoch": 0, "channels": {
        "log/0": {"count": 1, "fp": "aa"}}}]
    b = {"bundle": {"fingerprint": "f", "schema_fingerprint": "s"},
         "trigger": {"kind": "slo.breach"},
         "ledgers": {"expected": entries, "actual": entries}}
    assert rc.analyze_bundle(b)["verdict"] == "no-divergence"


def test_explain_byte_identical_across_two_processes(tmp_path):
    bdir = tmp_path / "incidents"
    bdir.mkdir()
    bundle = inc._synthetic_bundles()["unlogged-ring"]
    path = bdir / "incident-0001-audit.divergence.json"
    path.write_text(inc.canonical_json(bundle) + "\n")

    def run():
        return subprocess.run(
            [sys.executable, "-m", "clonos_tpu.cli", "incident",
             "explain", str(path), "--report", "json"],
            capture_output=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
    one, two = run(), run()
    assert one.returncode == 0, one.stderr
    assert two.returncode == 0
    assert one.stdout == two.stdout          # byte-identical
    rep = json.loads(one.stdout)
    assert rep["verdict"] == "localized"
    assert rep["first_divergent_channel"] == "ring/v1"


def test_cli_incident_list_show_and_self_check(tmp_path):
    mgr = inc.configure_incidents(str(tmp_path), service="cli",
                                  min_interval_s=0.0)
    mgr.signal("slo.breach", window=0, breaches=["p99"])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "clonos_tpu.cli", "incident", "list",
         "--dir", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert out.returncode == 0
    assert "slo.breach" in out.stdout
    out = subprocess.run(
        [sys.executable, "-m", "clonos_tpu.cli", "incident", "show",
         "1", "--dir", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert out.returncode == 0
    assert json.loads(out.stdout)["trigger"]["kind"] == "slo.breach"
    out = subprocess.run(
        [sys.executable, "-m", "clonos_tpu.cli", "incident",
         "--self-check"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert out.returncode == 0
    line = json.loads(out.stdout)
    assert line["ok"] is True
    assert line["schema"] == inc.bundle_schema_fingerprint()


# --- end-to-end: soak + injected nondet --------------------------------------


@pytest.mark.slow
def test_soak_nondet_auto_captures_and_localizes(tmp_path):
    """The acceptance path: an unlogged value perturbation (the
    examples/audit_nondet.py class — ring VALUES salted, counts/keys/
    timestamps untouched) injected under a live soak. The audit diff
    fires the flight recorder unprompted; the landed bundle's
    localization must name a salted ring/* channel, descend to the
    first divergent determinant ring step, and attribute the injecting
    worker from the chaos record on the HLC timeline."""
    from clonos_tpu.soak import (ChaosEvent, ChaosSchedule, SLOSpec,
                                 SoakConfig, SoakDriver,
                                 build_soak_fixture)

    mgr = inc.configure_incidents(str(tmp_path / "forensics"),
                                  service="soak", min_interval_s=0.0)
    configure_timeline("soak")
    runner, control, election = build_soak_fixture(
        str(tmp_path), rate=1200.0, duration_s=4.0,
        steps_per_epoch=32, seed=11)
    driver = SoakDriver(
        runner, SoakConfig(rate=1200.0, duration_s=4.0, window_s=2.0),
        schedule=ChaosSchedule([ChaosEvent(1.5, "nondet",
                                           targets=(1,))]),
        spec=SLOSpec(exactly_once=True),
        control=control, election=election, records_per_step=16)
    v = driver.run()

    assert v["pass"] is False                 # the audit caught it
    assert mgr.captured >= 1                  # ...and the recorder fired
    paths = mgr.bundles()
    assert paths
    bundle = inc.load_bundle(paths[0])
    assert bundle["trigger"]["kind"] == "audit.divergence"
    assert bundle["bundle"]["service"] == "soak"
    assert bundle["chaos"].strip().startswith("at 1.5s nondet")

    rep = rc.analyze_bundle(bundle)
    assert rep["verdict"].startswith("localized")
    chan = rep["first_divergent_channel"]
    assert chan is not None and chan.split("/")[0] in ("ring", "ringsum")
    # the walk-back found the injection and named the worker
    assert any(e["kind"] == "chaos" for e in rep["causal_chain"])
    assert rep["injected_by"] == "1"
    # determinant descent: when the divergent epoch's window was still
    # resident at capture time the report names the exact ring step —
    # and because the salt is value-only, flags it as unlogged nondet
    det = rep["determinant"]
    if det is not None:
        assert det["kind"] == "ring-step"
        assert det["field"] in ("values", "count", "keys",
                                "timestamps", "missing-step")
        assert "unlogged nondeterminism" in det.get("note", "")
    # the report is byte-stable: a fresh analysis of the re-read
    # bundle renders identical bytes
    again = rc.analyze_bundle(inc.load_bundle(paths[0]))
    assert rc.render_report(rep) == rc.render_report(again)
