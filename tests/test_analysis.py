"""Whole-program static analysis (clonos_tpu/analysis/): call graph,
nondet reachability, lock-order cycles, census + cost model, ablation.

The acceptance pairs:

- ``clonos_tpu analyze clonos_tpu/ examples/`` exits 0 on the repo
  (every exemption a justified waiver), and a synthetic helper chain
  from a step function to ``time.time()`` exits 1 naming BOTH ends.
- An injected A->B / B->A lock pair is reported as a ``lock-order``
  ERROR naming both acquisition sites (the deadlock the per-class lint
  cannot see).
- The no-FT ablation twin produces bit-identical record outputs to the
  real executor (only its logs stay empty), and stripping FT from
  ``examples/audit_nondet.py``'s world is REFUSED — its nondeterminism
  is load-bearing.
"""

import json
import os
import textwrap

import numpy as np
import pytest

from clonos_tpu.analysis import (ANALYSIS_RULES, AblationRefused,
                                 CallGraph, LOCK_ORDER, NONDET_REACH,
                                 ablated_executor,
                                 build_census, census_fingerprint,
                                 check_ablatable, fingerprint,
                                 format_json, format_text,
                                 run_analysis, static_cost_model)
from clonos_tpu.analysis.ablate import transform_source
from clonos_tpu.lint import FileContext

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ctx(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return FileContext(name, textwrap.dedent(src))


def _analyze_src(tmp_path, monkeypatch, files, use_waivers=True):
    monkeypatch.chdir(tmp_path)
    for name, src in files.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    return run_analysis(sorted(files), use_waivers=use_waivers)


def _hits(result, rule):
    return [f for f in result.findings if f.rule == rule]


# --- call graph ----------------------------------------------------------

def test_callgraph_resolves_methods_and_attr_chains(tmp_path):
    ctx = _ctx(tmp_path, "m.py", """\
        class Helper:
            def leaf(self):
                return 1

        class Op:
            def __init__(self):
                self.h = Helper()

            def process_block(self, state, ins):
                return self._step(state)

            def _step(self, state):
                return self.h.leaf()
        """)
    g = CallGraph([ctx])
    entries = g.step_entries()
    assert [e.qname for e in entries] == ["m.Op.process_block"]
    chain = g.chain("m.Op.process_block", {"m.Helper.leaf"})
    assert chain == ["m.Op.process_block", "m.Op._step",
                     "m.Helper.leaf"]


def test_callgraph_resolves_import_aliases(tmp_path):
    a = _ctx(tmp_path, "util.py", """\
        def helper():
            return 2
        """)
    b = _ctx(tmp_path, "op.py", """\
        import util as u

        class Op:
            def process_block(self, state, ins):
                return u.helper()
        """)
    g = CallGraph([a, b])
    chain = g.chain("op.Op.process_block", {"util.helper"})
    assert chain == ["op.Op.process_block", "util.helper"]


def test_callgraph_enclosing_and_nested_defs(tmp_path):
    # Nested defs are analyzed as part of their enclosing function (a
    # closure acquiring locks / reading clocks is charged to the
    # function that built it); methods resolve innermost-span-first.
    ctx = _ctx(tmp_path, "n.py", """\
        def outer():
            x = 1
            def inner():
                return 2
            return inner

        class C:
            def method(self):
                return 3
        """)
    g = CallGraph([ctx])
    fi = g.enclosing("n.py", 4)
    assert fi is not None and fi.name == "outer"
    fi2 = g.enclosing("n.py", 9)
    assert fi2 is not None and fi2.qname == "n.C.method"


# --- nondet-reach --------------------------------------------------------

def test_nondet_reach_through_helper_chain(tmp_path, monkeypatch):
    res = _analyze_src(tmp_path, monkeypatch, {"mod.py": """\
        import time

        class Op:
            def process_block(self, state, ins):
                return self._helper(state)

            def _helper(self, state):
                return deep_helper(state)

        def deep_helper(state):
            return state + time.time()
        """}, use_waivers=False)
    reach = _hits(res, NONDET_REACH)
    assert len(reach) == 1
    f = reach[0]
    assert f.line == 11                    # the SOURCE line
    assert "process_block" in f.message
    assert "_helper" in f.message and "deep_helper" in f.message
    assert res.exit_code() == 1


def test_nondet_reach_waived_source_is_quiet(tmp_path, monkeypatch):
    res = _analyze_src(tmp_path, monkeypatch, {"mod.py": """\
        import time

        class Op:
            def process_block(self, state, ins):
                # clonos: allow(wallclock): test fixture, never replayed
                return state + time.time()
        """})
    assert _hits(res, NONDET_REACH) == []
    assert res.ok


def test_nondet_unreachable_helper_not_escalated(tmp_path, monkeypatch):
    # The lint still flags the line, but no step function reaches it,
    # so there is no nondet-reach escalation.
    res = _analyze_src(tmp_path, monkeypatch, {"mod.py": """\
        import time

        def orphan_helper():
            return time.time()

        class Op:
            def process_block(self, state, ins):
                return state
        """}, use_waivers=False)
    assert _hits(res, NONDET_REACH) == []


# --- lock-order ----------------------------------------------------------

LOCK_CYCLE_SRC = """\
    import threading

    class Dispatcher:
        def __init__(self):
            self._admission_lock = threading.Lock()
            self.jm = JobMaster()

        def submit(self, job):
            with self._admission_lock:
                self.jm.seal(job)

    class JobMaster:
        def __init__(self):
            self._lock = threading.Lock()

        def seal(self, job):
            with self._lock:
                return job

        def heartbeat(self, d: "Dispatcher"):
            with self._lock:
                with d._admission_lock:
                    return 1
    """


def test_lock_order_cycle_detected(tmp_path, monkeypatch):
    res = _analyze_src(tmp_path, monkeypatch,
                       {"locks.py": LOCK_CYCLE_SRC}, use_waivers=False)
    cyc = _hits(res, LOCK_ORDER)
    assert len(cyc) == 1
    msg = cyc[0].message
    assert "Dispatcher._admission_lock" in msg
    assert "JobMaster._lock" in msg
    assert "submit" in msg and "heartbeat" in msg
    assert res.exit_code() == 1


def test_lock_order_consistent_order_is_quiet(tmp_path, monkeypatch):
    # Same two locks, both paths take them in the SAME order: no cycle.
    res = _analyze_src(tmp_path, monkeypatch, {"locks.py": """\
        import threading

        class Dispatcher:
            def __init__(self):
                self._admission_lock = threading.Lock()
                self.jm = JobMaster()

            def submit(self, job):
                with self._admission_lock:
                    self.jm.seal(job)

            def cancel(self, job):
                with self._admission_lock:
                    with self.jm._lock:
                        return job

        class JobMaster:
            def __init__(self):
                self._lock = threading.Lock()

            def seal(self, job):
                with self._lock:
                    return job
        """}, use_waivers=False)
    assert _hits(res, LOCK_ORDER) == []


def test_lock_order_reentrant_not_flagged(tmp_path, monkeypatch):
    res = _analyze_src(tmp_path, monkeypatch, {"locks.py": """\
        import threading

        class Log:
            def __init__(self):
                self._lock = threading.RLock()

            def append(self, row):
                with self._lock:
                    self._extend(row)

            def _extend(self, row):
                with self._lock:
                    return row
        """}, use_waivers=False)
    assert _hits(res, LOCK_ORDER) == []


def test_lock_balance_bare_acquire_without_release_warns(
        tmp_path, monkeypatch):
    from clonos_tpu.analysis import LOCK_BALANCE
    from clonos_tpu.lint.core import WARNING

    res = _analyze_src(tmp_path, monkeypatch, {"locks.py": """\
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()

            def seal(self, row):
                self._lock.acquire()
                return row
        """}, use_waivers=False)
    (w,) = _hits(res, LOCK_BALANCE)
    assert w.severity == WARNING
    assert "release()" in w.message and "with" in w.message
    # a warning, not an error: the run still exits 0
    assert res.exit_code() == 0


def test_lock_balance_matched_pair_is_quiet(tmp_path, monkeypatch):
    from clonos_tpu.analysis import LOCK_BALANCE

    res = _analyze_src(tmp_path, monkeypatch, {"locks.py": """\
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()

            def seal(self, row):
                self._lock.acquire()
                try:
                    return row
                finally:
                    self._lock.release()
        """}, use_waivers=False)
    assert _hits(res, LOCK_BALANCE) == []


def test_lock_order_sees_bare_acquire_release_pairs(
        tmp_path, monkeypatch):
    # The cycle only exists because one leg holds its lock through
    # bare .acquire()/.release() calls instead of a with block — the
    # order graph must treat both idioms as the same held region.
    res = _analyze_src(tmp_path, monkeypatch, {"locks.py": """\
        import threading

        class Dispatcher:
            def __init__(self):
                self._admission_lock = threading.Lock()
                self.jm = JobMaster()

            def submit(self, job):
                self._admission_lock.acquire()
                try:
                    self.jm.seal(job)
                finally:
                    self._admission_lock.release()

        class JobMaster:
            def __init__(self):
                self._lock = threading.Lock()

            def seal(self, job):
                with self._lock:
                    return job

            def heartbeat(self, d):
                with self._lock:
                    with d._admission_lock:
                        return 1
        """}, use_waivers=False)
    cyc = _hits(res, LOCK_ORDER)
    assert len(cyc) == 1
    assert "Dispatcher._admission_lock" in cyc[0].message
    assert "JobMaster._lock" in cyc[0].message


# --- census + cost model -------------------------------------------------

def test_repo_census_sync_lanes_and_fingerprint_stable():
    fp1 = census_fingerprint()
    fp2 = census_fingerprint()
    assert fp1 == fp2 and len(fp1) == 16
    res = run_analysis()
    assert res.census_fingerprint == fp1
    # The executor's fixed per-step sync rows, in stamp order.
    assert res.census["sync_lanes"] == [
        "TIMESTAMP", "RNG", "ORDER", "BUFFER_BUILT"]
    assert res.census["dets_per_step"] == 4
    assert res.census["encoding"]["row_bytes"] == 32
    assert len(res.census["step_functions"]) > 0
    assert any(s["callee"] == "serializable_service"
               for s in res.census["service_call_sites"])


def test_census_fingerprint_tracks_source_changes(tmp_path):
    c1 = build_census([_ctx(tmp_path, "a.py", """\
        class Op:
            def process_block(self, state, ins, ctx):
                return state + ctx.times
        """)])
    c2 = build_census([_ctx(tmp_path, "b.py", """\
        class Op:
            def process_block(self, state, ins, ctx):
                return state + ctx.times + ctx.rng_bits
        """)])
    assert fingerprint(c1) != fingerprint(c2)


def test_static_cost_model_scales_linearly():
    census = run_analysis().census
    m1 = static_cost_model(census, steps_per_epoch=100, subtasks=8,
                           records_per_step=64)
    m2 = static_cost_model(census, steps_per_epoch=200, subtasks=8,
                           records_per_step=64)
    assert m1["calls_per_step"] == census["dets_per_step"] * 8
    assert m2["determinant_bytes_per_epoch"] == \
        2 * m1["determinant_bytes_per_epoch"]
    assert 0.0 < m1["ft_fraction_static"] < 1.0
    # No rings, no replicas -> determinants are the only FT bytes.
    assert m1["ring_bytes_per_epoch"] == 0
    assert m1["replica_bytes_per_epoch"] == 0


def test_static_cost_model_spill_lanes():
    """``spill=True`` adds the tiered-storage lanes (d2h staging + disk
    write, both sized at the spilled payload) and raises the predicted
    ft-fraction; off, the lanes are present but zero (stable schema for
    BENCH json diffing)."""
    census = run_analysis().census
    base = static_cost_model(census, steps_per_epoch=100, subtasks=8,
                             records_per_step=64, ring_vertices=2)
    on = static_cost_model(census, steps_per_epoch=100, subtasks=8,
                           records_per_step=64, ring_vertices=2,
                           spill=True)
    assert base["spill_d2h_bytes_per_epoch"] == 0
    assert base["spill_disk_bytes_per_epoch"] == 0
    assert on["spill_d2h_bytes_per_epoch"] > 0
    assert on["spill_disk_bytes_per_epoch"] == \
        on["spill_d2h_bytes_per_epoch"]
    assert on["ft_fraction_static"] > base["ft_fraction_static"]


# --- repo gate -----------------------------------------------------------

def test_repo_analyzes_clean(monkeypatch):
    monkeypatch.chdir(_REPO)
    res = run_analysis(["clonos_tpu", "examples"])
    assert res.errors == [], format_text(res)
    assert res.exit_code() == 0


def test_format_json_one_line_contract(tmp_path, monkeypatch):
    res = _analyze_src(tmp_path, monkeypatch, {"mod.py": """\
        import time

        class Op:
            def process_block(self, state, ins):
                return time.time()
        """}, use_waivers=False)
    line = format_json(res)
    assert "\n" not in line
    doc = json.loads(line)
    assert doc["ok"] is False and doc["errors"] >= 1
    assert doc["census_fingerprint"] == res.census_fingerprint
    assert "census" in doc
    slim = json.loads(format_json(res, with_census=False))
    assert "census" not in slim


def test_stale_analysis_waiver_warns_not_fails(tmp_path, monkeypatch):
    res = _analyze_src(tmp_path, monkeypatch, {"mod.py": """\
        # clonos: allow(nondet-reach): nothing here any more
        X = 1
        """})
    assert res.ok                 # warnings don't flip the exit code
    assert any(f.rule == "stale-waiver" for f in res.warnings)


def test_analysis_rules_registered_for_waiver_validation():
    from clonos_tpu.lint import rule_names
    assert ANALYSIS_RULES <= set(rule_names())


# --- ablation ------------------------------------------------------------

def test_transform_strips_ft_lanes(tmp_path):
    src = textwrap.dedent("""\
        from clonos_tpu.causal import log as clog
        from clonos_tpu.inflight import log as ifl

        def run(logs, ring, rows, out):
            logs = clog.v_append_full(logs, rows)
            ring = ifl.append_block(ring, out)
            return logs, ring
        """)
    tree, report = transform_source("twin.py", src)
    assert {c for _l, c in report.stripped} == {
        "clonos_tpu.causal.log.v_append_full",
        "clonos_tpu.inflight.log.append_block"}
    import ast
    code = ast.unparse(tree)
    assert "v_append_full" not in code
    assert "logs = logs" in code


def test_ablation_refused_on_load_bearing_nondet(monkeypatch):
    monkeypatch.chdir(_REPO)
    with pytest.raises(AblationRefused) as ei:
        check_ablatable([os.path.join("examples", "audit_nondet.py")])
    assert any(f.rule == "entropy" for f in ei.value.findings)
    assert "stripping FT would change results" in str(ei.value)


def test_ablated_twin_bit_identical_outputs():
    """The golden equivalence run: same tiny job, same seed, logical
    time — the twin's sinks/states/counts are bit-identical to the real
    executor's; only the causal logs differ (twin logs stay empty)."""
    import jax
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime import executor as real_ex

    twin_mod, report = ablated_executor()
    assert len(report.stripped) >= 7, report.to_dict()

    def build():
        env = StreamEnvironment(name="ablate-golden", num_key_groups=16)
        (env.synthetic_source(vocab=13, batch_size=8, parallelism=2)
            .key_by()
            .window_count(num_keys=13, window_size=1 << 30)
            .sink())
        return env.build()

    def drive(ex_mod):
        ex = ex_mod.LocalExecutor(build(), steps_per_epoch=16,
                                  log_capacity=1 << 10, max_epochs=8,
                                  inflight_ring_steps=32, block_steps=8,
                                  seed=3, logical_time=True)
        outs = None
        for _ in range(2):
            outs = ex.run_epoch()
        leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(
            (ex.carry.op_states, ex.carry.edge_bufs,
             ex.carry.record_counts, outs.sinks))]
        return leaves, int(np.asarray(ex.carry.logs.head).max())

    real_leaves, real_head = drive(real_ex)
    twin_leaves, twin_head = drive(twin_mod)
    assert len(real_leaves) == len(twin_leaves)
    for a, b in zip(real_leaves, twin_leaves):
        np.testing.assert_array_equal(a, b)
    # Only the FT side differs: real logged, twin logged nothing.
    assert real_head > 0
    assert twin_head == 0


# --- CLI -----------------------------------------------------------------

def test_cli_analyze_json_and_exit_codes(monkeypatch, capsys):
    from clonos_tpu import cli

    monkeypatch.chdir(_REPO)
    rc = cli.main(["analyze", "--report", "json", "--no-census"])
    doc = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and doc["ok"] is True
    assert len(doc["census_fingerprint"]) == 16


def test_cli_analyze_census_dump(monkeypatch, capsys):
    from clonos_tpu import cli

    monkeypatch.chdir(_REPO)
    rc = cli.main(["analyze", "--census"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["sync_lanes"] == ["TIMESTAMP", "RNG", "ORDER",
                                 "BUFFER_BUILT"]


def test_cli_analyze_expect_census_pin_and_drift(monkeypatch, capsys):
    """The census-drift gate: the repo's pinned fingerprint
    (.clonos-census) passes; a wrong pin fails with a drift message
    naming both fingerprints."""
    from clonos_tpu import cli

    monkeypatch.chdir(_REPO)
    rc = cli.main(["analyze", "--expect-census", ".clonos-census"])
    capsys.readouterr()
    assert rc == 0
    rc = cli.main(["analyze", "--expect-census", "0" * 16])
    err = capsys.readouterr().err
    assert rc == 1
    assert "census drift" in err and "0" * 16 in err
