"""Determinant codec round-trip tests.

The reference's causal core is essentially untested in-repo (SURVEY §4:
only causal/NettyTests.java); this suite provides the codec coverage the
reference lacks: pack/unpack round-trip for every determinant type, 64-bit
splitting, bytes serde, and sidecar integrity.
"""

import numpy as np
import pytest

from clonos_tpu.causal import determinant as det


ALL_DETS = [
    det.OrderDeterminant(channel=3),
    det.TimestampDeterminant(timestamp=1753789000123),
    det.TimestampDeterminant(timestamp=-1),
    det.RNGDeterminant(value=-123456789),
    det.SerializableDeterminant(sidecar_key=7, length=42, crc32=0xDEADBEEF),
    det.TimerTriggerDeterminant(record_count=100, callback_id=5,
                                timestamp=999999999999),
    det.SourceCheckpointDeterminant(record_count=7, checkpoint_id=1 << 40,
                                    timestamp=-5, checkpoint_type=2,
                                    storage_ref=11),
    det.IgnoreCheckpointDeterminant(record_count=3, checkpoint_id=17),
    det.BufferBuiltDeterminant(num_records=256),
]


@pytest.mark.parametrize("d", ALL_DETS, ids=lambda d: type(d).__name__)
def test_roundtrip(d):
    row = d.pack()
    assert row.shape == (det.NUM_LANES,)
    assert row.dtype == np.int32
    assert det.Determinant.unpack(row) == d


def test_tag_numbering_matches_reference():
    # Determinant.java:20-35 tag order
    assert det.ORDER == 0 and det.TIMESTAMP == 1 and det.RNG == 2
    assert det.SERIALIZABLE == 3 and det.TIMER_TRIGGER == 4
    assert det.SOURCE_CHECKPOINT == 5 and det.IGNORE_CHECKPOINT == 6
    assert det.BUFFER_BUILT == 7


def test_split_join64_extremes():
    for v in (0, 1, -1, (1 << 62), -(1 << 62), (1 << 63) - 1, -(1 << 63)):
        hi, lo = det.split64(v)
        assert -(1 << 31) <= hi < (1 << 31)
        assert -(1 << 31) <= lo < (1 << 31)
        assert det.join64(hi, lo) == v


def test_batch_pack_and_bytes_roundtrip():
    rows = det.pack_batch(ALL_DETS)
    assert rows.shape == (len(ALL_DETS), det.NUM_LANES)
    assert det.unpack_batch(rows) == list(ALL_DETS)
    data = det.to_bytes(rows)
    assert len(data) == len(ALL_DETS) * det.ROW_BYTES
    back = det.from_bytes(data)
    np.testing.assert_array_equal(back, rows)


def test_bytes_rejects_ragged():
    with pytest.raises(ValueError):
        det.from_bytes(b"\x00" * (det.ROW_BYTES + 1))


def test_empty_batch():
    rows = det.pack_batch([])
    assert rows.shape == (0, det.NUM_LANES)
    assert det.unpack_batch(rows) == []


def test_sidecar_store_roundtrip_and_truncate():
    store = det.SidecarStore()
    d1 = store.put(b"hello external world", epoch=1)
    d2 = store.put(b"second", epoch=3)
    assert store.get(d1) == b"hello external world"
    # round-trip the determinant row itself
    d1b = det.Determinant.unpack(d1.pack())
    assert store.get(d1b) == b"hello external world"
    store.truncate(oldest_live_epoch=2)
    with pytest.raises(KeyError):
        store.get(d1)
    assert store.get(d2) == b"second"


def test_sidecar_integrity_check():
    store = det.SidecarStore()
    d = store.put(b"payload", epoch=0)
    bad = det.SerializableDeterminant(sidecar_key=d.sidecar_key,
                                      length=d.length, crc32=d.crc32 ^ 1)
    with pytest.raises(ValueError):
        store.get(bad)


def test_sidecar_merge_from_owner_namespacing():
    a = det.SidecarStore(owner=1)
    b = det.SidecarStore(owner=2)
    da = a.put(b"from-a", epoch=0)
    db = b.put(b"from-b", epoch=0)
    assert da.sidecar_key != db.sidecar_key  # distinct owners never collide
    a.merge_from(b)
    assert a.get(da) == b"from-a"
    assert a.get(db) == b"from-b"
    # divergent duplicate owner -> protocol violation
    c = det.SidecarStore(owner=1)
    c.put(b"divergent", epoch=0)
    with pytest.raises(ValueError):
        a.merge_from(c)


def test_async_tags():
    assert det.TIMER_TRIGGER in det.ASYNC_TAGS
    assert det.SOURCE_CHECKPOINT in det.ASYNC_TAGS
    assert det.ORDER not in det.ASYNC_TAGS
