"""Finalize-phase accounting guard (PR12): the overlapped recovery
pipeline must ATTRIBUTE its concurrency, never hide it. Invariant, for
every RecoveryReport.phase_ms:

    sum(finalize.* sub-spans) - finalize.overlap-saved == finalize

Sub-spans keep their true wall durations (what each piece of work
cost); ``finalize`` is the critical-path wall the job actually waited;
``finalize.overlap-saved`` is the difference the worker-thread overlap
bought. The sequential control path (``overlap_finalize=False``) keeps
the strict partition and never writes the overlap key — its absence
marks a control run. Wired next to the conftest lint/analyze gates:
this file is tier-1, so any accounting regression fails CI fast.
"""

import numpy as np
import pytest

from clonos_tpu import obs


def _finalize_identity(pm, rel=0.15, abs_ms=2.0):
    subs = {k: v for k, v in pm.items()
            if k.startswith("finalize.") and k != "finalize.overlap-saved"}
    saved = pm.get("finalize.overlap-saved", 0.0)
    assert saved >= 0.0
    assert sum(subs.values()) - saved == pytest.approx(
        pm["finalize"], rel=rel, abs=abs_ms), (
        f"finalize attribution broke: subs={subs} saved={saved} "
        f"finalize={pm['finalize']}")
    return subs, saved


def _window_job(name):
    from clonos_tpu.api.environment import StreamEnvironment
    env = StreamEnvironment(name=name, num_key_groups=8)
    (env.synthetic_source(vocab=11, batch_size=4, parallelism=2)
        .key_by()
        .window_count(num_keys=11, window_size=1 << 30)
        .sink())
    return env.build()


def test_recover_overlap_and_sequential_keep_the_identity(tmp_path):
    from clonos_tpu.runtime.cluster import ClusterRunner

    obs.configure("phases")
    r = ClusterRunner(_window_job("ph"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"))
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)

    r.inject_failure([2 + 1])
    pm = r.recover().phase_ms                  # overlapped (the default)
    assert "finalize.overlap-saved" in pm
    subs, _saved = _finalize_identity(pm)
    assert {"finalize.barrier-read", "finalize.state-verify"} <= set(subs)

    r.inject_failure([2 + 1])
    cm = r.recover(overlap_finalize=False).phase_ms   # sequential control
    assert "finalize.overlap-saved" not in cm
    _finalize_identity(cm)


def test_bootstrap_standby_folds_overlap_into_the_identity(tmp_path):
    """The standby-host rebuild runs ledger derivation + RNG
    fast-forward + AOT warm on a worker thread; its report must still
    satisfy the identity, with the bootstrap sub-spans (rehydrate /
    listener-reattach / first-step-recompile) folded in and the thread's
    off-critical-path time credited to finalize.overlap-saved."""
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    env = StreamEnvironment(name="phboot", num_key_groups=8)
    env.synthetic_source(vocab=7, batch_size=4, parallelism=1)
    job = env.build()
    ck = str(tmp_path / "ck")
    r = ClusterRunner(job, steps_per_epoch=4, checkpoint_dir=ck,
                      log_capacity=256, max_epochs=8, seed=2)
    for _ in range(3):
        r.run_epoch(complete_checkpoint=True)
    logs = r.executor.carry.logs
    head = int(np.asarray(logs.head)[0])
    tail = int(np.asarray(logs.tail)[0])
    cap = np.asarray(logs.rows).shape[1]
    pos = np.arange(tail, head) & (cap - 1)
    mirror_rows = {0: (np.asarray(logs.rows)[0][pos], tail)}

    rebuilt, report = ClusterRunner.bootstrap_standby(
        job, ck, mirror_rows, steps_per_epoch=4, log_capacity=256,
        max_epochs=8, seed=2)
    pm = report.phase_ms
    subs, saved = _finalize_identity(pm)
    assert {"finalize.state-rehydrate", "finalize.listener-reattach",
            "finalize.first-step-recompile", "finalize.barrier-read",
            "finalize.state-verify"} <= set(subs)
    # the worker thread existed: derive+warm walls were recorded
    assert pm["finalize.first-step-recompile"] >= 0.0
    # the rebuilt runner is live (the join points held)
    assert rebuilt.global_step == 12 + report.steps_replayed
