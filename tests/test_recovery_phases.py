"""Finalize-phase accounting guard (PR12): the overlapped recovery
pipeline must ATTRIBUTE its concurrency, never hide it. Invariant, for
every RecoveryReport.phase_ms:

    sum(finalize.* sub-spans) - finalize.overlap-saved == finalize

Sub-spans keep their true wall durations (what each piece of work
cost); ``finalize`` is the critical-path wall the job actually waited;
``finalize.overlap-saved`` is the difference the worker-thread overlap
bought. The sequential control path (``overlap_finalize=False``) keeps
the strict partition and never writes the overlap key — its absence
marks a control run. Wired next to the conftest lint/analyze gates:
this file is tier-1, so any accounting regression fails CI fast.
"""

import numpy as np
import pytest

from clonos_tpu import obs


def _finalize_identity(pm, rel=0.15, abs_ms=2.0):
    subs = {k: v for k, v in pm.items()
            if k.startswith("finalize.") and k != "finalize.overlap-saved"}
    saved = pm.get("finalize.overlap-saved", 0.0)
    assert saved >= 0.0
    assert sum(subs.values()) - saved == pytest.approx(
        pm["finalize"], rel=rel, abs=abs_ms), (
        f"finalize attribution broke: subs={subs} saved={saved} "
        f"finalize={pm['finalize']}")
    return subs, saved


def _window_job(name):
    from clonos_tpu.api.environment import StreamEnvironment
    env = StreamEnvironment(name=name, num_key_groups=8)
    (env.synthetic_source(vocab=11, batch_size=4, parallelism=2)
        .key_by()
        .window_count(num_keys=11, window_size=1 << 30)
        .sink())
    return env.build()


def test_recover_overlap_and_sequential_keep_the_identity(tmp_path):
    from clonos_tpu.runtime.cluster import ClusterRunner

    obs.configure("phases")
    r = ClusterRunner(_window_job("ph"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"))
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)

    r.inject_failure([2 + 1])
    pm = r.recover().phase_ms                  # overlapped (the default)
    assert "finalize.overlap-saved" in pm
    subs, _saved = _finalize_identity(pm)
    assert {"finalize.barrier-read", "finalize.state-verify"} <= set(subs)

    r.inject_failure([2 + 1])
    cm = r.recover(overlap_finalize=False).phase_ms   # sequential control
    assert "finalize.overlap-saved" not in cm
    _finalize_identity(cm)


def test_bootstrap_standby_folds_overlap_into_the_identity(tmp_path):
    """The standby-host rebuild runs ledger derivation + RNG
    fast-forward + AOT warm on a worker thread; its report must still
    satisfy the identity, with the bootstrap sub-spans (rehydrate /
    listener-reattach / first-step-recompile) folded in and the thread's
    off-critical-path time credited to finalize.overlap-saved."""
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    env = StreamEnvironment(name="phboot", num_key_groups=8)
    env.synthetic_source(vocab=7, batch_size=4, parallelism=1)
    job = env.build()
    ck = str(tmp_path / "ck")
    r = ClusterRunner(job, steps_per_epoch=4, checkpoint_dir=ck,
                      log_capacity=256, max_epochs=8, seed=2)
    for _ in range(3):
        r.run_epoch(complete_checkpoint=True)
    logs = r.executor.carry.logs
    head = int(np.asarray(logs.head)[0])
    tail = int(np.asarray(logs.tail)[0])
    cap = np.asarray(logs.rows).shape[1]
    pos = np.arange(tail, head) & (cap - 1)
    mirror_rows = {0: (np.asarray(logs.rows)[0][pos], tail)}

    rebuilt, report = ClusterRunner.bootstrap_standby(
        job, ck, mirror_rows, steps_per_epoch=4, log_capacity=256,
        max_epochs=8, seed=2)
    pm = report.phase_ms
    subs, saved = _finalize_identity(pm)
    assert {"finalize.state-rehydrate", "finalize.listener-reattach",
            "finalize.first-step-recompile", "finalize.barrier-read",
            "finalize.state-verify"} <= set(subs)
    # the worker thread existed: derive+warm walls were recorded
    assert pm["finalize.first-step-recompile"] >= 0.0
    # the rebuilt runner is live (the join points held)
    assert rebuilt.global_step == 12 + report.steps_replayed


def test_overlap_verify_failure_keeps_subtasks_dead_and_retryable(tmp_path):
    """Safety-order guard: in overlapped mode, revive bookkeeping must
    run AFTER the barrier join + state-verify (the sequential order). A
    packed-read deferred assert that raises must leave ``self.failed``
    and the heartbeat dead-set intact, so the failure is visible and
    ``recover()`` can simply be retried; the barrier thread must not
    outlive the call."""
    import threading

    from clonos_tpu.causal import recovery as rec
    from clonos_tpu.runtime.cluster import ClusterRunner

    r = ClusterRunner(_window_job("phdead"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"))
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)

    flat = 2 + 1
    orig_bounds = r._ring_bounds_dev
    assert orig_bounds() is not None        # the job has in-flight rings
    r.inject_failure([flat])
    # Deterministic verify trip: skew the ring-bounds lanes of the
    # packed read so the deferred assert sees device bounds that
    # contradict the host mirror. Routing coverage decisions read the
    # (valid, untampered) host mirror, so the replay itself is sound —
    # only the final state-verify fires.
    r._ring_bounds_dev = lambda: orig_bounds() + 1
    with pytest.raises(rec.RecoveryError, match="state suspect"):
        r.recover()
    assert flat in r.failed                    # NOT marked healthy
    assert flat in r.heartbeats._dead
    assert not any(t.name == "recovery-finalize-barrier"
                   for t in threading.enumerate())
    # Un-tamper and retry: the protocol reruns end-to-end, and only a
    # recover() that passed verify revives the subtask.
    r._ring_bounds_dev = orig_bounds
    report = r.recover()
    assert not r.failed
    assert flat not in r.heartbeats._dead
    assert "finalize.state-verify" in report.phase_ms


def test_overlap_audit_divergence_defers_past_verify_and_joins(tmp_path):
    """An audit divergence under the abort policy in overlapped mode
    must not short-circuit the window: the barrier thread is joined,
    state-verify's deferred asserts still run, revive keeps its
    sequential place, and only then does AuditDivergenceError
    propagate — the same observable order as the sequential control."""
    import json
    import threading

    from clonos_tpu.causal.recovery import AuditDivergenceError
    from clonos_tpu.runtime.cluster import ClusterRunner

    tr = obs.configure("phaud")
    r = ClusterRunner(_window_job("phaud"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"),
                      audit=True, audit_on_divergence="abort")
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)

    # Tamper every sealed fingerprint on disk: whatever epoch window the
    # recovery validates, its recompute diverges from the ledger.
    ledger = tmp_path / "ck" / "ledger.jsonl"
    entries = [json.loads(ln) for ln in
               ledger.read_text().splitlines() if ln]
    for e in entries:
        for ch in e["channels"].values():
            ch["fp"] = "00" * 8
    ledger.write_text("".join(json.dumps(e) + "\n" for e in entries))

    flat = 2 + 1
    r.inject_failure([flat])
    with pytest.raises(AuditDivergenceError):
        r.recover()
    # state-verify ran before the deferred divergence propagated
    assert any(x["name"] == "recovery.finalize.state-verify"
               for x in tr.records())
    # ... and so did revive (verify passed), matching the sequential
    # control where the abort fires after barrier→verify→revive.
    assert not r.failed
    assert flat not in r.heartbeats._dead
    assert not any(t.name == "recovery-finalize-barrier"
                   for t in threading.enumerate())
