"""Multi-tenant dispatcher units (runtime/dispatcher.py): fair-share
admission (quotas, strict-FIFO queueing, typed rejection, slot release),
per-job lease scoping and worker-side fencing lanes, the SUBMIT_JOB /
JOB_STATUS / CANCEL_JOB wire surface, per-job cluster-metric rollups,
the `top` per-job section, and `audit --job` ledger resolution.

The 2-process, multi-job SIGKILL acceptance test lives in
tests/test_multitenant.py; everything here runs in-process.
"""

import argparse
import json
import os

import pytest

from clonos_tpu.parallel import transport as tp
from clonos_tpu.runtime import scheduler as sch
from clonos_tpu.runtime.dispatcher import (AdmissionController, Dispatcher,
                                           QuotaExceededError, TenantConfig)
from clonos_tpu.runtime.leader import FileLeaderElection, job_lease_path
from clonos_tpu.runtime.remote import JobMasterServer


# --- admission control -------------------------------------------------------


def test_quota_rejection_is_typed_and_counts_reservations():
    adm = AdmissionController(quotas={"red": 3}, default_quota=None)
    assert adm.request("red-001", "red", 2, free_slots=8) == "admitted"
    with pytest.raises(QuotaExceededError) as ei:
        adm.request("red-002", "red", 2, free_slots=8)
    e = ei.value
    assert (e.tenant, e.requested, e.quota, e.held) == ("red", 2, 3, 2)
    payload = e.wire_payload()
    assert payload["error_type"] == "quota-exceeded"
    assert payload["quota"] == 3 and payload["requested"] == 2
    # No quota configured -> unlimited (default_quota=None).
    assert adm.request("blue-005", "blue", 50, free_slots=60) == "admitted"
    assert adm.quota("blue") is None
    # Queued jobs count against the quota too: a submission that would
    # only overflow once its queued sibling admits is rejected up front.
    assert adm.request("red-003", "red", 1, free_slots=0) == "queued"
    with pytest.raises(QuotaExceededError):
        adm.request("red-004", "red", 1, free_slots=8)


def test_fifo_queueing_no_jumping_and_head_blocking():
    adm = AdmissionController()
    assert adm.request("a-001", "a", 3, free_slots=4) == "admitted"
    assert adm.request("b-002", "b", 3, free_slots=1) == "queued"
    # 1 slot IS free for this 1-slot job, but the queue is non-empty:
    # later arrivals never jump earlier ones.
    assert adm.request("c-003", "c", 1, free_slots=1) == "queued"
    assert adm.queued() == ["b-002", "c-003"]
    # Strict FIFO drain: the 3-slot head blocks on 2 free slots even
    # though the 1-slot job behind it would fit.
    assert adm.admit_queued(free_slots=2) == []
    adm.release("a", 3)
    assert adm.held("a") == 0
    assert adm.admit_queued(free_slots=4) == ["b-002", "c-003"]
    assert adm.held("b") == 3 and adm.held("c") == 1
    assert adm.queued() == []
    # Release clamps at zero (double release is not an underflow).
    adm.release("b", 99)
    assert adm.held("b") == 0


def test_cancel_queued_and_total_held():
    adm = AdmissionController()
    assert adm.request("a-001", "a", 2, free_slots=2) == "admitted"
    assert adm.request("b-002", "b", 1, free_slots=0) == "queued"
    assert adm.total_held() == 2
    assert adm.cancel_queued("b-002") is True
    assert adm.cancel_queued("b-002") is False
    assert adm.queued() == []
    assert adm.admit_queued(free_slots=8) == []


def test_cancel_of_queued_job_releases_its_quota_charge():
    # A queued job was never admitted, but its reservation charges the
    # tenant's quota; cancelling it must return that headroom — the
    # admission model's no-leak invariant (verify/models.py).
    adm = AdmissionController(quotas={"red": 3})
    assert adm.request("red-001", "red", 2, free_slots=0) == "queued"
    assert adm.reserved("red") == 2
    # 2 queued + 2 requested > quota 3: rejected while the charge holds.
    with pytest.raises(QuotaExceededError):
        adm.request("red-002", "red", 2, free_slots=8)
    assert adm.cancel_queued("red-001") is True
    assert adm.reserved("red") == 0
    # The exact submission that was rejected now fits.
    assert adm.request("red-002", "red", 2, free_slots=8) == "admitted"
    assert adm.held("red") == 2


def test_double_release_clamps_and_never_mints_slots():
    adm = AdmissionController()
    assert adm.request("a-001", "a", 2, free_slots=4) == "admitted"
    adm.release("a", 2)
    assert adm.held("a") == 0
    # An erroneous second release of the same job clamps at zero:
    # no negative held count, no phantom free slots later.
    adm.release("a", 2)
    assert adm.held("a") == 0 and adm.total_held() == 0
    assert adm.request("a-002", "a", 2, free_slots=2) == "admitted"
    assert adm.held("a") == 2


def test_admission_transition_observers_see_every_verdict():
    adm = AdmissionController(quotas={"red": 2})
    obs = []
    adm.transition_observers.append(
        lambda kind, **f: obs.append((kind, f.get("job_id",
                                                  f.get("tenant")))))
    assert adm.request("red-001", "red", 2, free_slots=2) == "admitted"
    with pytest.raises(QuotaExceededError):
        adm.request("red-002", "red", 1, free_slots=1)
    adm.release("red", 2)
    assert adm.request("red-003", "red", 1, free_slots=0) == "queued"
    assert adm.cancel_queued("red-003") is True
    assert obs == [("admit", "red-001"), ("reject", "red-002"),
                   ("release", "red"), ("queue", "red-003"),
                   ("cancel", "red-003")]


def test_tenant_config_validation_and_from_any():
    cfg = TenantConfig.from_any({"tenant": "red", "slots": 2,
                                 "unknown_knob": 1})
    assert cfg.tenant == "red" and cfg.slots == 2
    assert cfg.max_concurrent_recoveries == 1
    assert TenantConfig.from_any(None).tenant == "default"
    assert TenantConfig.from_any(cfg) is cfg
    # Tenant names embed into job ids / metric keys / lease paths.
    for bad in ("", "a.b", "a/b", "a-b"):
        with pytest.raises(ValueError):
            TenantConfig(tenant=bad)
    with pytest.raises(ValueError):
        TenantConfig(slots=0)
    with pytest.raises(TypeError):
        TenantConfig.from_any("red")


# --- per-job leases + worker fencing lanes -----------------------------------


def test_job_lease_path_scoping():
    assert job_lease_path("/tmp/jm.lease", "") == "/tmp/jm.lease"
    assert job_lease_path("/tmp/jm.lease", None) == "/tmp/jm.lease"
    assert job_lease_path("/tmp/jm.lease", "red-001") \
        == "/tmp/jm.lease.red-001"
    with pytest.raises(ValueError, match="must not contain"):
        job_lease_path("/tmp/jm.lease", "red/001")


def _deploy_frame(tdd):
    hdr = tp.pack_json(tdd)
    return len(hdr).to_bytes(4, "little") + hdr


def test_endpoint_fencing_lanes_are_per_job(tmp_path):
    """Two jobs share one worker and one lease directory; each runs its
    own election. Job B's leader change (epoch 2) must not fence job A's
    epoch-1 DEPLOYs — the lanes are independent — while within one lane
    stale tokens are still rejected."""
    base = str(tmp_path / "jm.lease")
    ea = FileLeaderElection(job_lease_path(base, "red-001"), "jm-a")
    assert ea.try_acquire() and ea.epoch == 1
    t = [0.0]
    b1 = FileLeaderElection(job_lease_path(base, "blue-002"), "jm-b1",
                            lease_ttl_s=2.0, clock=lambda: t[0])
    b2 = FileLeaderElection(job_lease_path(base, "blue-002"), "jm-b2",
                            lease_ttl_s=2.0, clock=lambda: t[0])
    assert b1.try_acquire() and b1.epoch == 1
    t[0] = 3.5                       # b1's lease lapses; b2 takes over
    assert b2.try_acquire() and b2.epoch == 2

    ep = sch.TaskExecutorEndpoint(lease_path=base)
    cl = tp.ControlClient(ep.address)
    try:
        # Blue's live token is accepted; its deposed token is not.
        rt, _ = cl.call(tp.DEPLOY, _deploy_frame(
            {"group": 0, "fencing_epoch": 2, "job_id": "blue-002"}))
        assert rt == tp.OK
        rt, resp = cl.call(tp.DEPLOY, _deploy_frame(
            {"group": 0, "fencing_epoch": 1, "job_id": "blue-002"}))
        assert rt == tp.ERROR
        assert "stale fencing" in tp.unpack_json(resp)["error"]
        # Red's epoch-1 token stays valid: blue's epoch sequence is a
        # DIFFERENT lane and must not depose red's JobMaster.
        rt, _ = cl.call(tp.DEPLOY, _deploy_frame(
            {"group": 0, "fencing_epoch": 1, "job_id": "red-001"}))
        assert rt == tp.OK
        # The legacy (job-less) lane reads the UNSCOPED base path, where
        # no claim exists — rejected at the lease check.
        rt, resp = cl.call(tp.DEPLOY, _deploy_frame(
            {"group": 0, "fencing_epoch": 1}))
        assert rt == tp.ERROR
        assert "lease claim" in tp.unpack_json(resp)["error"]
        # Drain the two accepted descriptors; job_id rides along.
        jobs = {ep.queue.get_nowait().get("job_id") for _ in range(2)}
        assert jobs == {"blue-002", "red-001"}
    finally:
        cl.close()
        ep.close()


# --- dispatcher intake (wire + direct) ---------------------------------------


class _StubJM:
    """JobMasterServer stand-in for admission tests: advertised slots
    and expiry only (no sockets, no workers)."""

    def __init__(self, slots=None, expired=()):
        self._slots = dict(slots or {})
        self._expired = list(expired)

    def slots(self):
        return dict(self._slots)

    def expired(self):
        return list(self._expired)

    def cluster_metrics(self):
        return {}

    def close(self):
        pass


def _dispatcher(tmp_path, jm, serve=False, **kw):
    return Dispatcher(lease_path=str(tmp_path / "jm.lease"),
                      checkpoint_root=str(tmp_path / "ck"),
                      jm=jm, serve=serve, **kw)


def test_submit_mints_deterministic_job_ids_and_states(tmp_path):
    disp = _dispatcher(tmp_path, _StubJM(slots={"a": 4}))
    try:
        r1 = disp.submit_job("examples.wordcount:build_job",
                             {"tenant": "red", "slots": 2})
        assert r1 == {"job_id": "red-001", "state": "ADMITTED"}
        r2 = disp.submit_job("examples.wordcount:build_job",
                             {"tenant": "blue", "slots": 2})
        assert r2 == {"job_id": "blue-002", "state": "ADMITTED"}
        # Pool exhausted (4 slots, 4 held) -> FIFO queue.
        r3 = disp.submit_job("examples.wordcount:build_job",
                             {"tenant": "red", "slots": 1})
        assert r3["state"] == "QUEUED"
        assert disp.admission.queued() == ["red-003"]
        # Cancelling an ADMITTED job releases its slots; cancelling a
        # QUEUED job leaves the queue.
        assert disp.cancel_job("red-001")["state"] == "CANCELLED"
        assert disp.admission.held("red") == 0
        assert disp.cancel_job("red-003")["state"] == "CANCELLED"
        assert disp.admission.queued() == []
        with pytest.raises(KeyError, match="unknown job"):
            disp.cancel_job("nope-999")
        states = {j["job_id"]: j["state"] for j in disp.jobs()}
        assert states == {"red-001": "CANCELLED", "blue-002": "ADMITTED",
                          "red-003": "CANCELLED"}
    finally:
        disp.close()


def test_wire_submit_status_cancel_and_typed_quota_error(tmp_path):
    disp = _dispatcher(tmp_path, _StubJM(), serve=True,
                       quotas={"red": 1})
    cl = tp.ControlClient(disp.address)
    try:
        # No workers registered -> 0 free slots -> queued, over the wire.
        res = cl.call_json(tp.SUBMIT_JOB, {
            "job": "examples.wordcount:build_job",
            "tenant_config": {"tenant": "red", "slots": 1}})
        assert res == {"job_id": "red-001", "state": "QUEUED"}
        # Over quota -> tp.ERROR with the TYPED payload, not a generic
        # string (clients must distinguish policy from infrastructure).
        rt, resp = cl.call(tp.SUBMIT_JOB, tp.pack_json({
            "job": "examples.wordcount:build_job",
            "tenant_config": {"tenant": "red", "slots": 1}}))
        body = tp.unpack_json(resp)
        assert rt == tp.ERROR
        assert body["error_type"] == "quota-exceeded"
        assert body["tenant"] == "red" and body["quota"] == 1
        # JOB_STATUS: single record, unknown-id error, and the full list.
        st = cl.call_json(tp.JOB_STATUS, {"job_id": "red-001"})
        assert st["state"] == "QUEUED" and st["tenant"] == "red"
        rt, resp = cl.call(tp.JOB_STATUS, tp.pack_json(
            {"job_id": "ghost-7"}))
        assert rt == tp.ERROR
        assert "red-001" in tp.unpack_json(resp)["error"]
        allj = cl.call_json(tp.JOB_STATUS, {})
        assert [j["job_id"] for j in allj["jobs"]] == ["red-001"]
        # CANCEL_JOB drains the queue entry.
        res = cl.call_json(tp.CANCEL_JOB, {"job_id": "red-001"})
        assert res["state"] == "CANCELLED"
        assert disp.admission.queued() == []
    finally:
        cl.close()
        disp.close()


def test_metrics_extra_reports_tenant_gauges(tmp_path):
    disp = _dispatcher(tmp_path, _StubJM(slots={"a": 4}),
                       quotas={"red": 3})
    try:
        disp.submit_job("examples.wordcount:build_job",
                        {"tenant": "red", "slots": 2})
        disp.submit_job("examples.wordcount:build_job",
                        {"tenant": "blue", "slots": 4})   # -> queued
        m = disp.metrics_extra()
        assert m["tenant.red.slots-held"] == 2
        assert m["tenant.red.quota"] == 3
        assert m["tenant.red.jobs-running"] == 1   # ADMITTED counts active
        assert m["tenant.blue.jobs-queued"] == 1
        assert m["tenant.blue.slots-held"] == 0
        assert m["dispatcher.queue-depth"] == 1
        assert m["dispatcher.jobs-total"] == 2
    finally:
        disp.close()


# --- per-job cluster rollups + top rendering ---------------------------------


def test_cluster_metrics_rolls_up_per_job(tmp_path):
    jm = JobMasterServer(heartbeat_timeout_s=5.0)
    try:
        with jm._lock:
            jm._hb_metrics["a"] = {
                "job.red-001.group.0.job.wc.audit.epochs-sealed": 4,
                "job.red-001.group.0.job.wc.audit.epochs-validated": 2,
                "job.red-001.group.0.job.wc.audit.divergences": 0,
                "job.blue-002.group.0.job.wc.records-total": 10,
                "job.blue-002.group.1.job.wc.records-total": 12,
                "group.0.job.legacy.audit.epochs-sealed": 3,
            }
            jm._slots["a"] = 2
        out = jm.cluster_metrics()
        assert out["cluster.job.red-001.groups"] == 1
        assert out["cluster.job.red-001.audit.epochs-sealed"] == 4
        assert out["cluster.job.red-001.audit.exactly-once-ok"] == 1
        # blue reports no audit gauges: it gets a group count, no
        # fabricated audit rows.
        assert out["cluster.job.blue-002.groups"] == 2
        assert "cluster.job.blue-002.audit.epochs-sealed" not in out
        # Legacy (job-less) keys still roll into the flat cluster line.
        assert out["cluster.audit.epochs-sealed"] == 7
    finally:
        jm.close()


def test_top_table_renders_per_job_and_tenant_sections():
    from clonos_tpu.cli import _top_rows, _top_table

    snap = {
        "worker.a.slots": 4,
        "worker.a.job.red-001.group.0.job.wc.audit.epochs-sealed": 4,
        "cluster.job.red-001.groups": 1,
        "cluster.job.red-001.audit.epochs-sealed": 4,
        "cluster.job.red-001.audit.epochs-validated": 2,
        "cluster.job.red-001.audit.divergences": 0,
        "cluster.job.red-001.audit.exactly-once-ok": 1,
        "cluster.audit.exactly-once-ok": 1,
        "tenant.red.slots-held": 1,
        "tenant.red.quota": 2,
        "dispatcher.queue-depth": 0,
    }
    rows = _top_rows(snap)
    assert rows["a"]["groups"] == {"red-001:g0"}
    assert rows["a"]["sealed"] == 4
    out = _top_table(snap)
    assert "XONCE" in out
    assert "red-001" in out
    assert "tenant.red.slots-held=1" in out
    assert "dispatcher.queue-depth=0" in out
    # The flat cluster footer must not repeat the per-job rows.
    cluster_line = [ln for ln in out.splitlines()
                    if ln.startswith("cluster: ")]
    assert cluster_line and "job.red-001" not in cluster_line[0]


# --- audit --job resolution --------------------------------------------------


def _write_ledger(path, epochs=3):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for ep in range(epochs):
            f.write(json.dumps({"epoch": ep, "combined": f"d{ep}",
                                "records": 8 * (ep + 1),
                                "channels": {}, "det_counts": {}}) + "\n")


def _audit_args(**kw):
    base = dict(dir="", diff=None, job=None, report="text", json=False)
    base.update(kw)
    return argparse.Namespace(**base)


def test_audit_job_scoped_ledgers_and_ambiguity(tmp_path, capsys):
    from clonos_tpu.cli import _find_ledgers, _ledger_job_ids, cmd_audit

    root = tmp_path / "ck"
    _write_ledger(str(root / "red-001" / "g0" / "ledger.jsonl"))
    _write_ledger(str(root / "blue-002" / "g0" / "ledger.jsonl"))
    ledgers = _find_ledgers(str(root))
    assert [lab for lab, _ in ledgers] == [
        os.path.join("blue-002", "g0", "ledger.jsonl"),
        os.path.join("red-001", "g0", "ledger.jsonl")]
    assert _ledger_job_ids(ledgers) == ["blue-002", "red-001"]

    # --job picks one job's tree; its labels drop the job prefix.
    assert cmd_audit(_audit_args(dir=str(root), job="red-001")) == 0
    out = capsys.readouterr().out
    assert "g0" in out and "blue-002" not in out

    # Unknown job id -> exit 2 listing what IS there.
    assert cmd_audit(_audit_args(dir=str(root), job="nope-9")) == 2
    err = capsys.readouterr().err
    assert "available job ids: blue-002, red-001" in err

    # A diff over a multi-job root without --job is ambiguous -> exit 2.
    assert cmd_audit(_audit_args(dir=str(root), diff=str(root))) == 2
    assert "ambiguous" in capsys.readouterr().err

    # --job scopes the diff, and lines up against a SINGLE-job run's
    # unprefixed g0/ layout.
    single = tmp_path / "single"
    _write_ledger(str(single / "g0" / "ledger.jsonl"))
    assert cmd_audit(_audit_args(dir=str(root), diff=str(single),
                                 job="red-001")) == 0
    assert "ledgers match" in capsys.readouterr().out

    # ...and a diverging single-job run still fails the diff.
    bad = tmp_path / "bad"
    _write_ledger(str(bad / "g0" / "ledger.jsonl"), epochs=2)
    assert cmd_audit(_audit_args(dir=str(root), diff=str(bad),
                                 job="red-001")) == 1


# --- shared-pool slot keying -------------------------------------------------


def test_slot_pool_job_scoped_keys_share_one_pool():
    pool = sch.SlotPool()
    pool.sync_offers({"a": 2, "b": 2})
    sa = pool.allocate(("red-001", 0), prefer="a")
    sb = pool.allocate(("blue-002", 0), prefer="a")
    assert sa.worker_id == "a" and sb.worker_id == "a"
    assert pool.placements() == {("red-001", 0): "a",
                                 ("blue-002", 0): "a"}
    # Releasing one job's group leaves the co-hosted job untouched.
    pool.release_group(("red-001", 0))
    assert pool.placements() == {("blue-002", 0): "a"}
    # A dead worker strands BOTH jobs' groups; drop is idempotent (the
    # dispatcher calls it once per affected job).
    pool.allocate(("red-001", 0), prefer="a")
    assert sorted(pool.drop_worker("a")) == [("blue-002", 0),
                                             ("red-001", 0)]
    assert pool.drop_worker("a") == []
