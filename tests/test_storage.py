"""Tiered determinant & in-flight storage (clonos_tpu/storage/):
device ring -> host buffer -> checksummed disk segments.

The acceptance pairs:

- a recovery whose replay backlog exceeds device ring capacity succeeds
  by refilling from the host/disk tiers, bit-identical under the audit
  ledger (``diff_ledgers == []`` vs a no-spill control run);
- a torn/truncated/bit-rotted segment is REFUSED with a labeled
  :class:`SegmentCorruptError`, and through the recovery path surfaces
  as a :class:`RecoveryError` — never as silently wrong replay bytes.
"""

import glob
import os

import jax
import numpy as np
import pytest

from clonos_tpu.obs.digest import diff_ledgers
from clonos_tpu.storage import (SegmentCorruptError, StorageError,
                                TieredEpochStore, read_segment,
                                segment_checksum, write_segment)


def _arrays(seed):
    rng = np.random.RandomState(seed)
    return {
        "rows": rng.randint(0, 1 << 30, size=(4, 6, 3)).astype(np.int32),
        "weights": rng.rand(8).astype(np.float32),
        "valid": (rng.rand(5, 2) > 0.5),
        "empty": np.zeros((0, 7), np.int64),
    }


# --- segment container -------------------------------------------------------


def test_segment_raw_container_roundtrip(tmp_path):
    path = str(tmp_path / "e0.seg")
    arrays = _arrays(0)
    nbytes, checksum = write_segment(path, 37, arrays)
    assert nbytes == os.path.getsize(path)
    with open(path, "rb") as f:
        assert segment_checksum(f.read()) == checksum
    start, out = read_segment(path, checksum, "t:epoch0")
    assert start == 37
    assert set(out) == set(arrays)
    for k, v in arrays.items():
        assert out[k].dtype == v.dtype and out[k].shape == v.shape
        np.testing.assert_array_equal(out[k], v)


def test_corrupt_or_torn_segment_refused_with_label(tmp_path):
    path = str(tmp_path / "e1.seg")
    _, checksum = write_segment(path, 0, _arrays(1))
    data = open(path, "rb").read()
    # Bit rot: one flipped byte in the middle of the payload.
    rot = bytearray(data)
    rot[len(rot) // 2] ^= 0x40
    open(path, "wb").write(bytes(rot))
    with pytest.raises(SegmentCorruptError,
                       match=r"rotted:epoch1.*checksum mismatch"):
        read_segment(path, checksum, "rotted:epoch1")
    # Torn tail: a SIGKILLed writer's truncated file.
    open(path, "wb").write(data[:len(data) - 11])
    with pytest.raises(SegmentCorruptError, match="refill refused"):
        read_segment(path, checksum, "torn:epoch1")
    # Missing entirely.
    os.remove(path)
    with pytest.raises(SegmentCorruptError, match="unreadable"):
        read_segment(path, checksum, "gone:epoch1")


# --- tiered store ------------------------------------------------------------


def test_tiered_roundtrip_budget_demotion_and_truncate(tmp_path):
    store = TieredEpochStore(str(tmp_path), "dets",
                             host_budget_epochs=1)
    put = {e: _arrays(e) for e in range(4)}
    for e, arrs in put.items():
        store.put(e, e * 10, arrs)
    store.drain()
    occ = store.occupancy()
    # Budget 1: only the newest epoch keeps a host copy; all four are
    # durable on disk.
    assert occ["host_epochs"] == 1 and occ["disk_epochs"] == 4
    assert occ["disk_bytes"] > occ["host_bytes"] > 0
    # Oldest epoch refills from disk, newest from host — bit-identical
    # either way.
    start, out = store.load_epoch(0)
    assert start == 0
    np.testing.assert_array_equal(out["rows"], put[0]["rows"])
    start, out = store.load_epoch(3)
    assert start == 30
    np.testing.assert_array_equal(out["weights"], put[3]["weights"])
    stats = store.stats()
    assert stats["disk_hits"] == 1 and stats["host_hits"] == 1
    assert stats["bytes_refilled"] > 0
    assert stats["segments_written"] == 4 and stats["bytes_spilled"] > 0
    # Checkpoint completion truncates tier-wide: host dict AND files.
    store.truncate(2)
    store.drain()
    assert store.retained_epochs() == [3]
    assert not os.path.exists(store.segment_path(0))
    assert not os.path.exists(store.segment_path(2))
    assert os.path.exists(store.segment_path(3))
    with pytest.raises(StorageError, match="dets:epoch1.*not retained"):
        store.load_epoch(1)
    store.close()


def test_open_index_fresh_process_refill_and_torn_tail(tmp_path):
    store = TieredEpochStore(str(tmp_path), "edge0",
                             host_budget_epochs=0)
    put = {e: _arrays(10 + e) for e in range(3)}
    for e, arrs in put.items():
        store.put(e, e * 4, arrs)
        store.attach_digest(e, f"d{e:016x}")
    store.truncate(0)                  # completed checkpoint drops e0
    store.drain()
    store.close()
    # A SIGKILLed writer leaves a torn final index line: dropped
    # silently, like every other append log (utils/jsonl.py).
    with open(os.path.join(str(tmp_path), "edge0.index.jsonl"), "a") as f:
        f.write('{"kind":"segm')
    fresh = TieredEpochStore.open_index(str(tmp_path), "edge0")
    # The truncate record held: epoch 0 must NOT resurrect.
    assert fresh.retained_epochs() == [1, 2]
    assert fresh.epoch_digest(1) == "d" + format(1, "016x")
    start, out = fresh.load_epoch(2)   # disk-tier read, checksum-gated
    assert start == 8
    np.testing.assert_array_equal(out["rows"], put[2]["rows"])
    assert fresh.stats()["disk_hits"] == 1
    fresh.close()


# --- cluster integration -----------------------------------------------------


def _build_job():
    from clonos_tpu.api.environment import StreamEnvironment
    env = StreamEnvironment(name="tiered", num_key_groups=8)
    (env.synthetic_source(vocab=13, batch_size=4, parallelism=2)
        .key_by().window_count(num_keys=13, window_size=1 << 30)
        .sink())
    return env.build()


def _runner(tmp_path=None, ring_steps=8, budget=0):
    from clonos_tpu.runtime.cluster import ClusterRunner
    kw = dict(steps_per_epoch=4, log_capacity=1 << 9, max_epochs=16,
              inflight_ring_steps=ring_steps, seed=11,
              logical_time=True, audit=True)
    if tmp_path is not None:
        kw.update(spool_dir=str(tmp_path),
                  spill_host_budget_epochs=budget)
    return ClusterRunner(_build_job(), **kw)


def _backlog(runner, pending=3):
    runner.run_epoch(complete_checkpoint=True)     # restore point
    for _ in range(pending):
        runner.run_epoch(complete_checkpoint=False)


def test_deep_backlog_recovery_refills_from_disk_bit_identical(tmp_path):
    """THE tentpole acceptance: 12 backlog steps against an 8-step
    device ring — the leading epoch's in-flight batches exist only in
    the tiers (host budget 0 forces the DISK leg) and recovery is
    audit-verified bit-identical against a no-spill control whose ring
    held everything."""
    control = _runner(None, ring_steps=32)         # ring holds the span
    _backlog(control)
    r = _runner(tmp_path, ring_steps=8, budget=0)  # ring holds 2 epochs
    _backlog(r)
    r.executor.drain_spill()                       # segments durable
    r.inject_failure([3])                          # window subtask 1
    report = r.recover()
    assert report.steps_replayed == 12 > 8         # beyond the ring
    assert r.executor.spill_stats()["disk_hits"] > 0
    hi = r.auditor.last_epoch
    expected = [e for e in control.auditor.ledger() if e["epoch"] <= hi]
    actual = [e for e in r.auditor.ledger() if e["epoch"] <= hi]
    assert expected and diff_ledgers(expected, actual) == []


def test_torn_segment_tail_surfaces_as_labeled_recovery_error(tmp_path):
    """Recovery that needs a tier refill across a torn segment must
    fail loudly with the storage label — never replay garbage."""
    from clonos_tpu.causal.recovery import RecoveryError
    r = _runner(tmp_path, ring_steps=8, budget=0)
    _backlog(r)
    r.executor.drain_spill()
    # Tear every ring-edge segment of the epoch that outran the ring
    # (epoch 1: its 4 steps sit below head - ring_steps at the kill).
    torn = [p for p in glob.glob(os.path.join(str(tmp_path),
                                              "edge*_epoch1.seg"))]
    assert torn, "expected spilled ring segments for epoch 1"
    for p in torn:
        data = open(p, "rb").read()
        open(p, "wb").write(data[:len(data) // 2])
    r.inject_failure([3])
    with pytest.raises(RecoveryError,
                       match=r"tiered refill failed.*epoch1"):
        r.recover()


def test_det_store_refill_bit_identical_to_seal_window(tmp_path):
    """The determinant tier's promise: ``det_rows_for_epoch`` returns
    exactly the ``epoch_window(e)["logs"][flat]`` slice that was sealed
    (and digested) at the fence."""
    r = _runner(tmp_path, ring_steps=32, budget=0)
    _backlog(r, pending=2)
    r.executor.drain_spill()
    ex = r.executor
    assert sorted(ex.det_store.retained_epochs()) == [1, 2]
    for epoch in (1, 2):
        win = ex.epoch_window(epoch)["logs"]
        for flat, rows in win.items():
            got = ex.det_rows_for_epoch(flat, epoch)
            np.testing.assert_array_equal(got, np.asarray(rows))
    # The digest attached at the seal rides the index (audit linkage).
    assert ex.det_store.epoch_digest(2)


def test_spill_occupancy_and_stats_aggregate_across_stores(tmp_path):
    r = _runner(tmp_path, ring_steps=8, budget=1)
    _backlog(r, pending=2)
    r.executor.drain_spill()
    occ = r.executor.spill_occupancy()
    assert occ["disk_epochs"] > 0 and occ["disk_bytes"] > 0
    assert occ["host_epochs"] > 0                  # budget keeps newest
    stats = r.executor.spill_stats()
    # Written >= resident: the completed checkpoint truncated epoch 0's
    # segments after they were written.
    assert stats["segments_written"] >= occ["disk_epochs"]
    assert stats["bytes_spilled"] >= occ["disk_bytes"]


# --- chaos DSL: the backlog fault --------------------------------------------


def test_chaos_backlog_event_roundtrip_and_seeding():
    from clonos_tpu.soak.chaos import ChaosSchedule, parse_schedule
    sched = parse_schedule("at 35s backlog for 4s")
    (ev,) = sched.events
    assert ev.kind == "backlog" and ev.duration_s == 4.0
    assert parse_schedule(ev.to_text()) == sched   # byte round-trip
    with pytest.raises(ValueError, match="backlog needs"):
        parse_schedule("at 1s backlog")
    seeded = ChaosSchedule.seeded(7, 60.0, targets=[1],
                                  kinds=("kill", "backlog"))
    assert "backlog" in seeded.kinds()
    assert parse_schedule(seeded.to_text()) == seeded
