"""Soak & chaos harness (clonos_tpu/soak/): open-loop SLO tracking
with exactly-once asserted under injected failure.

Unit layers first — the chaos DSL must be seeded-replayable (same seed,
same fault sequence, byte for byte), the SLO windows must breach on the
right bound, the coordinated-omission correction must charge queueing
delay to exactly the samples whose fence ran late, and a gray failure
must land a worker in ``degraded()`` without ever reaching
``expired()``. The slow tests then run the real driver: a paced run
surviving a kill cascade + gray failure with the audit ledger clean
end-to-end, an injected unlogged perturbation that MUST fail the run,
and the ``clonos_tpu soak --report json`` exit-0/1 CI contract.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from clonos_tpu.soak import (ChaosEvent, ChaosSchedule, SLOSpec,
                             SLOTracker, Window, corrected_closed_loop,
                             parse_schedule, quantile)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- chaos DSL ---------------------------------------------------------------


def test_dsl_parse_all_kinds_and_roundtrip():
    text = """
    # warm-in stays quiet
    at 5s kill 1,9,17
    at 12s gray 2 delay=50ms for 3s
    at 20s leader-loss hold=1s ; at 30s stall delay=200ms for 2s
    at 40s nondet
    """
    sched = parse_schedule(text)
    assert sched.kinds() == ["kill", "gray", "leader-loss", "stall",
                             "nondet"]
    kill, gray, ll, stall, nondet = list(sched)
    assert kill.targets == (1, 9, 17)
    assert gray.targets == (2,) and gray.delay_s == 0.05 \
        and gray.duration_s == 3.0
    assert ll.hold_s == 1.0
    assert stall.delay_s == 0.2 and stall.duration_s == 2.0
    assert nondet.at_s == 40.0
    # Round-trip: to_text() re-parses to the identical schedule.
    assert parse_schedule(sched.to_text()) == sched


def test_dsl_sorts_events_by_fire_time():
    sched = parse_schedule("at 30s nondet\nat 5s kill 1")
    assert [e.at_s for e in sched] == [5.0, 30.0]


@pytest.mark.parametrize("line", [
    "kill 1",                            # missing 'at <time>'
    "at 5s explode 1",                   # unknown kind
    "at 5s kill",                        # kill needs targets
    "at 5s kill a,b",                    # non-integer targets
    "at 5s gray 2,3 delay=50ms for 3s",  # gray takes exactly one
    "at 5s gray 2",                      # gray needs delay + for
    "at 5s stall delay=200ms",           # stall needs for
    "at 5s stall delay=200ms for",       # 'for' needs a duration
    "at 5m kill 1",                      # bad duration unit
    "at 5s kill 1 bogus=1",              # unexpected token
    "at 5s rescale",                     # rescale needs a target cut
    "at 5s rescale 0",                   # target must be >= 1 worker
    "at 5s rescale 2 4",                 # exactly one target
])
def test_dsl_rejects_malformed_events(line):
    with pytest.raises(ValueError):
        parse_schedule(line)


def test_dsl_rescale_parses_and_roundtrips():
    """`rescale N` is a first-class chaos kind: the target cut rides in
    targets, and to_text() reproduces the line byte-exactly."""
    text = "at 1.5s rescale 4"
    sched = parse_schedule(text)
    (ev,) = list(sched)
    assert ev.kind == "rescale" and ev.targets == (4,)
    assert sched.to_text() == text
    assert parse_schedule(sched.to_text()) == sched


def test_seeded_schedule_can_draw_rescales():
    sched = ChaosSchedule.seeded(7, 60.0, [0, 1], kinds=("rescale",),
                                 n_events=3)
    assert len(sched) == 3
    assert all(e.kind == "rescale" and e.targets[0] in (2, 4)
               for e in sched)
    assert parse_schedule(sched.to_text()) == sched
    assert ChaosSchedule.seeded(7, 60.0, [0, 1], kinds=("rescale",),
                                n_events=3) == sched


def test_seeded_schedule_is_replayable():
    """Same seed + same args -> the identical fault sequence; the whole
    point of the DSL split is that a soak that tripped the audit can be
    re-run bit for bit."""
    a = ChaosSchedule.seeded(5, 60.0, [1, 3, 5])
    b = ChaosSchedule.seeded(5, 60.0, [1, 3, 5])
    assert a == b and a.to_text() == b.to_text()
    # ... and a different seed gives a different sequence.
    c = ChaosSchedule.seeded(6, 60.0, [1, 3, 5])
    assert a != c


def test_seeded_schedule_covers_kinds_inside_the_paced_band():
    kinds = ("kill", "gray", "leader-loss", "stall", "nondet")
    sched = ChaosSchedule.seeded(11, 100.0, [1, 3, 5, 7], kinds=kinds,
                                 n_events=8, cascade=3)
    assert len(sched) == 8
    assert set(sched.kinds()) == set(kinds)     # every kind at least once
    for ev in sched:
        # warm-in and the final seal/audit window stay fault-free
        assert 20.0 <= ev.at_s <= 85.0
        if ev.kind == "kill":
            assert len(ev.targets) == 3
            assert len(set(ev.targets)) == 3    # distinct cascade
        if ev.kind == "gray":
            assert len(ev.targets) == 1
            assert ev.delay_s > 0 and ev.duration_s > 0
    assert parse_schedule(sched.to_text()) == sched


def test_seeded_schedule_rejects_unknown_kind_and_missing_targets():
    with pytest.raises(ValueError):
        ChaosSchedule.seeded(1, 60.0, [1], kinds=("explode",))
    with pytest.raises(ValueError):
        ChaosSchedule.seeded(1, 60.0, [], kinds=("kill",))


# --- SLO windows -------------------------------------------------------------


def test_quantile_empty_is_zero():
    assert quantile([], 0.99) == 0.0


def test_window_evaluate_breaches_each_bound():
    spec = SLOSpec(max_p99_ms=100.0, min_throughput=50.0,
                   max_recovery_ms=500.0)
    w = Window(0, 0.0, 2.0)
    for _ in range(95):
        w.observe(corrected_ms=10.0, actual_ms=10.0, records=1)
    for _ in range(5):
        w.observe(corrected_ms=900.0, actual_ms=900.0, records=1)
    w.recoveries_ms.append(800.0)
    breaches = w.evaluate(spec)
    # 100 records / 2s = 50/s is AT the floor (no breach); p99 and the
    # recovery both breach.
    assert len(breaches) == 2
    assert any("p99" in b for b in breaches)
    assert any("recovery" in b for b in breaches)
    assert w.stats()["breaches"] == breaches


def test_window_throughput_breach():
    spec = SLOSpec(min_throughput=100.0)
    w = Window(0, 0.0, 2.0)
    w.observe(corrected_ms=1.0, actual_ms=1.0, records=60)
    assert w.evaluate(spec) == ["throughput 30/s < 100/s"]


class _FakeTracer:
    def __init__(self):
        self.events = []

    def event(self, name, **kw):
        self.events.append((name, kw))


def test_slo_tracker_rolls_windows_on_the_soak_clock():
    tr = _FakeTracer()
    t = SLOTracker(SLOSpec(max_p99_ms=50.0), window_s=5.0, tracer=tr)
    t.observe(1.0, corrected_ms=10.0, actual_ms=10.0, records=8)
    t.observe(6.0, corrected_ms=500.0, actual_ms=20.0, records=8)
    t.observe_fault(6.5, "kill")
    t.observe_recovery(7.0, 321.0)
    windows = t.finish()
    assert [w.index for w in windows] == [0, 1]
    assert windows[0].breaches == []
    assert windows[1].breaches and "p99" in windows[1].breaches[0]
    assert windows[1].faults == ["kill"]
    assert windows[1].recoveries_ms == [321.0]
    # breach trace instant emitted at window close
    assert any(n == "soak.slo.breach" and kw["window"] == 1
               for n, kw in tr.events)
    assert t.breached_windows() == [windows[1]]
    assert t.worst_window() is windows[1]


# --- coordinated-omission correction (closed-loop bench) ---------------------


def test_corrected_closed_loop_charges_late_fences_only():
    """One fence runs 500ms late on a fixed 1ms/step schedule: every
    marker sample in that epoch (and the still-late next one) gets the
    queueing delay added; samples under on-time fences are untouched."""
    fences = [(100, 0.1), (200, 0.2), (300, 0.8), (400, 0.9)]
    samples = [(50, 1.0), (250, 2.0), (350, 3.0)]
    out = corrected_closed_loop(samples, fences, steps_per_epoch=100,
                                records_per_step=10, rate=10_000.0)
    assert out["max_queue_ms"] == pytest.approx(500.0)
    assert out["per_step_us"] == pytest.approx(1000.0)
    # sample 50 -> fence 100 (on time): stays 1.0ms; 250 -> fence 300:
    # 2.0 + 500; 350 -> fence 400: 3.0 + 500
    assert out["p99_ms"] == pytest.approx(
        quantile([1.0, 502.0, 503.0], 0.99))
    assert out["p50_ms"] == pytest.approx(502.0)


def test_corrected_closed_loop_derives_rate_from_fence_span():
    # 1ms/step derived from the (step, wall) span when rate is omitted;
    # evenly paced fences carry zero queueing delay.
    fences = [(0, 0.0), (100, 0.1), (200, 0.2)]
    out = corrected_closed_loop([(10, 7.0), (110, 9.0)], fences,
                                steps_per_epoch=100, records_per_step=10)
    assert out["per_step_us"] == pytest.approx(1000.0)
    assert out["max_queue_ms"] == pytest.approx(0.0)
    assert out["p99_ms"] == pytest.approx(quantile([7.0, 9.0], 0.99))


def test_corrected_closed_loop_empty_inputs():
    assert corrected_closed_loop([], [(0, 0.0), (8, 1.0)], 8, 4) == {
        "p50_ms": 0.0, "p99_ms": 0.0, "max_queue_ms": 0.0}
    assert corrected_closed_loop([(1, 2.0)], [(0, 0.0)], 8, 4)[
        "p99_ms"] == 0.0


# --- gray failure: degraded, never dead --------------------------------------


def test_heartbeat_monitor_gray_degrades_without_killing():
    from clonos_tpu.runtime.cluster import HeartbeatMonitor

    t = [0.0]
    mon = HeartbeatMonitor([0, 1, 2], timeout_s=5.0,
                           clock=lambda: t[0])
    mon.beat_all_except(set())
    assert mon.degraded(0.01) == []
    # inject a 0.5s heartbeat lag on subtask 1 (the chaos injector's
    # surface): its beats now ARRIVE half a second behind its peers'
    mon.lag[1] = 0.5
    t[0] = 1.0
    mon.beat_all_except(set())
    assert mon.degraded(0.01) == [1]
    assert mon.expired() == []          # degraded, NOT dead
    # paced-driver gap: all beats age identically while the driver
    # sleeps — relative lateness keeps the healthy workers out
    t[0] = 4.0
    assert mon.degraded(0.01) == [1]
    assert mon.expired() == []
    # past the death timeout the worker leaves degraded() for expired()
    t[0] = 7.0
    assert 1 not in mon.degraded(0.01)
    assert mon.expired() == [0, 1, 2]
    # revive clears the injected lag
    mon.revive(1)
    assert 1 not in mon.lag


def test_standby_pool_completion_is_monotonic():
    """Out-of-order async checkpoint completions must never regress the
    restore point behind the ring truncation the newer completion
    already performed."""
    from clonos_tpu.runtime.cluster import StandbyPool

    class _Ckpt:
        def __init__(self, cid):
            self.checkpoint_id = cid

    pool = StandbyPool()
    pool.on_completed_checkpoint(_Ckpt(5))
    pool.on_completed_checkpoint(_Ckpt(3))     # stale completion
    assert pool.latest.checkpoint_id == 5
    pool.on_completed_checkpoint(_Ckpt(7))
    assert pool.latest.checkpoint_id == 7


# --- metrics history: pacing under load + torn tail --------------------------


def test_history_interval_holds_under_slow_sampler(tmp_path):
    """Absolute-deadline pacing: a sample_fn that takes a large slice
    of the interval must NOT stretch the period (the old wait-then-
    sample loop ran at interval + sample_time)."""
    from clonos_tpu.obs.history import MetricsHistory

    def slow_sample():
        time.sleep(0.03)
        return {"x": 1}

    h = MetricsHistory(sample_fn=slow_sample, interval_s=0.05,
                       window=64)
    h.start()
    time.sleep(0.53)
    h.close()
    n = len(h.query())
    # drift pacing would deliver ~6 samples in 0.53s (0.08s period);
    # deadline pacing ~10. Assert safely above the drifted count.
    assert n >= 8, f"only {n} samples: interval drifted under load"
    assert h.missed_slots == 0


def test_history_counts_missed_slots_instead_of_bursting():
    from clonos_tpu.obs.history import MetricsHistory

    def very_slow_sample():
        time.sleep(0.12)
        return {}

    h = MetricsHistory(sample_fn=very_slow_sample, interval_s=0.05,
                       window=64)
    h.start()
    time.sleep(0.5)
    h.close()
    samples = h.query()
    assert h.missed_slots >= 2
    # no catch-up burst: consecutive samples stay >= one sample time
    ts = [r["ts"] for r in samples]
    assert all(b - a >= 0.1 for a, b in zip(ts, ts[1:]))


def test_history_file_torn_tail_readable_mid_run(tmp_path):
    """The JSONL file stays readable WHILE the sampler appends, and a
    SIGKILL-torn final line is tolerated on resume."""
    from clonos_tpu.obs.history import MetricsHistory, read_history_file

    path = str(tmp_path / "hist.jsonl")
    h = MetricsHistory(sample_fn=lambda: {"ok": 1}, path=path,
                       interval_s=0.02, window=64)
    h.start()
    deadline = time.monotonic() + 0.4
    reads = 0
    while time.monotonic() < deadline:
        recs = read_history_file(path)      # concurrent with appends
        for r in recs:
            assert "ts" in r
        reads += 1
    h.close()
    assert reads > 0 and len(read_history_file(path)) > 0
    # SIGKILL artifact: torn final append
    with open(path, "a") as f:
        f.write('{"ts": 1, "metr')
    recs = read_history_file(path)
    assert all("metrics" in r for r in recs)


# --- top: soak status row ----------------------------------------------------


def test_top_table_renders_soak_row():
    from clonos_tpu.cli import _top_table

    snap = {"soak.target-rate": 2000.0, "soak.rate": 1874.2,
            "soak.faults-injected": 4, "soak.audit-ok": 1,
            "worker.w0.slots": 2}
    table = _top_table(snap)
    soak_lines = [ln for ln in table.splitlines()
                  if ln.startswith("soak:")]
    assert len(soak_lines) == 1
    assert "audit-ok=1" in soak_lines[0]
    assert "target-rate=2000.0" in soak_lines[0]
    # suffix match: worker-prefixed gauges feed the same row
    table2 = _top_table({"worker.w1.soak.rate": 9.0})
    assert any(ln.startswith("soak: rate=9.0")
               for ln in table2.splitlines())
    # absent gauges, absent row
    assert "soak:" not in _top_table({"worker.w0.slots": 1})


# --- runner surfaces the driver depends on -----------------------------------


def _small_job(name):
    from clonos_tpu.api.environment import StreamEnvironment
    env = StreamEnvironment(name=name, num_key_groups=8)
    (env.synthetic_source(vocab=11, batch_size=4, parallelism=2)
        .key_by()
        .window_count(num_keys=11, window_size=1 << 30)
        .sink())
    return env.build()


def test_latency_markers_keep_raw_samples(tmp_path):
    """The histogram forgets WHEN a sample happened; the raw (step,
    latency) series behind it is what coordinated-omission correction
    re-attributes queueing delay from."""
    from clonos_tpu.runtime.cluster import ClusterRunner

    r = ClusterRunner(_small_job("lat"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      latency_marker_every=2)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    samples = r.latency.samples
    assert samples and len(samples) == r.latency.hist.count
    steps = [s for s, _ in samples]
    assert steps == sorted(steps)
    assert all(isinstance(ms, float) for _, ms in samples)
    # bounded: the series trims from the front, keeping the newest
    r.latency.max_samples = 4
    r.run_epoch(complete_checkpoint=False)
    assert len(r.latency.samples) <= 4
    assert r.latency.samples[-1][0] == max(steps + [
        s for s, _ in r.latency.samples])


def test_discard_pending_through_abandons_skipped_fences(tmp_path):
    """complete_every>1 leaves skipped fences' checkpoints pending
    forever; a completing fence must be able to abandon them WITHOUT
    firing completion listeners (completing old checkpoints late would
    regress the standby restore point — see the monotonic test above)."""
    from clonos_tpu.runtime.cluster import ClusterRunner

    r = ClusterRunner(_small_job("dp"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=64, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"))
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    r.run_epoch(complete_checkpoint=False)
    r.run_epoch(complete_checkpoint=True)
    co = r.coordinator
    latest_before = r.standbys.latest.checkpoint_id
    pending = sorted(co._pending)
    assert pending, "expected skipped fences to leave pendings"
    discarded = co.discard_pending_through(max(pending))
    assert discarded == pending
    assert not co._pending
    # quiet abandon: no completion fired, restore point unchanged
    assert r.standbys.latest.checkpoint_id == latest_before
    assert co.discard_pending_through(10**6) == []


# --- the real driver (slow) --------------------------------------------------


def _fixture(tmp_path, duration_s, rate=1200.0):
    from clonos_tpu.soak import build_soak_fixture
    return build_soak_fixture(str(tmp_path), rate=rate,
                              duration_s=duration_s,
                              steps_per_epoch=32, seed=11)


@pytest.mark.slow
def test_soak_smoke_kill_and_gray_hold_slo_and_audit(tmp_path):
    """~20s smoke: a paced run takes one kill + one gray failure and
    must come out with every SLO window evaluated on corrected latency,
    both faults survived, and the audit ledger byte-identical to the
    fault-free control chain (exactly_once: true). The kill exercises
    the OVERLAPPED recovery tail end-to-end: its window is held to a
    per-window max_recovery_ms budget, the finalize.overlap-saved
    attribution is recorded per kill, and the immediate post-kill
    ledger re-diff vs the control twin stays empty. (The 150 ms device
    budget is asserted by bench.py at bench shapes; the CPU-CI bound
    here guards the SLO plumbing, not device latency.)"""
    from clonos_tpu.soak import SLOSpec, SoakConfig, SoakDriver

    runner, control, election = _fixture(tmp_path, duration_s=5.0)
    schedule = parse_schedule(
        "at 1.2s kill 1,3\nat 2.2s gray 3 delay=30ms for 1.5s")
    driver = SoakDriver(
        runner, SoakConfig(rate=1200.0, duration_s=5.0, window_s=2.0,
                           chunk_steps=8),
        schedule=schedule,
        spec=SLOSpec(exactly_once=True, max_recovery_ms=30000.0),
        control=control, election=election, records_per_step=16)
    v = driver.run()

    assert v["pass"] is True
    assert v["audit"]["exactly_once"] is True
    assert v["audit"]["divergences"] == []
    assert v["audit"]["epochs_checked"] > 0
    assert v["faults"]["injected"] == 2
    assert v["faults"]["survived"] == 2
    assert v["faults"]["by_kind"] == {"gray": 1, "kill": 1}
    assert v["faults"]["recoveries_ms"]          # the kill's recovery
    assert v["slo"]["max_recovery_ms"] == 30000.0
    # overlapped-recovery acceptance under chaos kill
    assert len(v["faults"]["kill_overlap_saved_ms"]) == 1
    assert v["faults"]["kill_overlap_saved_ms"][0] >= 0.0
    assert v["faults"]["kill_rediff_problems"] == 0
    assert v["windows"] and all(
        "p99_ms" in w and "p50_ms" in w for w in v["windows"])
    assert "corrected" in v["latency"]["basis"]
    assert v["events_fired"] == 2
    # the soak.* gauges top renders are live in the registry
    snap = runner.metrics.snapshot()
    assert snap["soak.faults-injected"] == 2
    assert snap["soak.audit-ok"] == 1
    assert snap["soak.target-rate"] == 1200.0


@pytest.mark.slow
def test_soak_injected_nondet_fails_the_run(tmp_path):
    """Audit bait: an unlogged value perturbation survives every
    structural check and MUST be caught by the post-event ledger diff —
    the run fails even though nothing crashed and no SLO breached."""
    from clonos_tpu.soak import SLOSpec, SoakConfig, SoakDriver

    runner, control, election = _fixture(tmp_path, duration_s=4.0)
    driver = SoakDriver(
        runner, SoakConfig(rate=1200.0, duration_s=4.0, window_s=2.0),
        schedule=parse_schedule("at 1.5s nondet"),
        spec=SLOSpec(exactly_once=True),
        control=control, election=election, records_per_step=16)
    v = driver.run()

    assert v["pass"] is False
    assert v["audit"]["exactly_once"] is False
    assert v["audit"]["divergences"]
    assert any("ring" in d for d in v["audit"]["divergences"])
    assert runner.metrics.snapshot()["soak.audit-ok"] == 0


@pytest.mark.slow
def test_soak_mid_run_rescale_holds_exactly_once(tmp_path):
    """Elastic repartition under live soak traffic: a `rescale 4` event
    re-cuts the running 2-wide job to 4 keyed workers at a completing
    fence. The control twin is re-cut identically, so the byte-exact
    ledger diff must stay empty across the handoff — no record lost or
    duplicated — and the driver must keep pacing the NEW incarnation."""
    from clonos_tpu.soak import SLOSpec, SoakConfig, SoakDriver

    runner, control, election = _fixture(tmp_path, duration_s=4.0,
                                         rate=4000.0)
    driver = SoakDriver(
        runner, SoakConfig(rate=4000.0, duration_s=4.0, window_s=1.0,
                           chunk_steps=8, complete_every=2),
        schedule=parse_schedule("at 1.2s rescale 4"),
        spec=SLOSpec(exactly_once=True),
        control=control, election=election, records_per_step=16)
    v = driver.run()

    assert v["pass"] is True
    assert v["audit"]["exactly_once"] is True
    assert v["audit"]["divergences"] == []
    assert v["audit"]["epochs_checked"] > 0
    assert v["faults"]["rescales"] == 1
    (stats,) = v["faults"]["rescale_stats"]
    assert stats["target"] == 4
    assert stats["drained_records"] >= 0
    assert sum(stats["moved_key_groups"].values()) > 0
    assert stats["fence_stall_ms"] >= 0.0
    # the driver really swapped to the re-cut incarnation
    assert driver.runner is not runner
    assert any(vx.parallelism == 4 for vx in driver.runner.job.vertices)
    assert driver.runner.metrics.snapshot()["soak.rescales"] == 1


@pytest.mark.slow
def test_soak_cli_report_json_exit_codes(tmp_path):
    """CI contract: ``clonos_tpu soak --report json`` prints one JSON
    line and exits 0 on a clean run, 1 when the audit catches an
    injected nondeterminism."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    base = [sys.executable, "-m", "clonos_tpu", "soak",
            "--rate", "1200", "--duration", "4", "--window", "2",
            "--steps-per-epoch", "32", "--report", "json"]

    ok = subprocess.run(
        base + ["--schedule", "at 1.2s kill 1,3",
                "--workdir", str(tmp_path / "ok"),
                "--out", str(tmp_path / "ok.json")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert ok.returncode == 0, ok.stderr[-2000:]
    line = json.loads(ok.stdout.strip().splitlines()[-1])
    assert line["pass"] is True and line["exactly_once"] is True
    # durable artifact with the full verdict
    art = json.load(open(tmp_path / "ok.json"))
    assert art["metric"] == "soak_slo_verdict" and art["windows"]

    bad = subprocess.run(
        base + ["--schedule", "at 1.5s nondet",
                "--workdir", str(tmp_path / "bad"),
                "--out", str(tmp_path / "bad.json")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert bad.returncode == 1, bad.stderr[-2000:]
    line = json.loads(bad.stdout.strip().splitlines()[-1])
    assert line["pass"] is False and line["divergences"] >= 1
