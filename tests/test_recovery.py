"""Causal recovery: FSM gating, vectorized replay, and the golden property —
a failed subtask rebuilt from checkpoint + determinant replay is
bit-identical to a never-failed run (reference §3.4 signature path;
LogReplayerImpl post-replay asserts)."""

import numpy as np
import jax
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.causal import recovery as rec
from clonos_tpu.runtime.cluster import ClusterRunner


VOCAB, BATCH, NKEYS = 11, 8, 11


def _job(parallelism=2):
    env = StreamEnvironment(name="wc", num_key_groups=16)
    (env.synthetic_source(vocab=VOCAB, batch_size=BATCH,
                          parallelism=parallelism)
        .key_by()
        .window_count(num_keys=NKEYS, window_size=50)
        .sink())
    return env.build()


def _runner(times, steps_per_epoch=3, parallelism=2):
    r = ClusterRunner(_job(parallelism), steps_per_epoch=steps_per_epoch,
                      heartbeat_timeout_s=0.05, seed=3)
    r.executor.time_source.now = lambda it=iter(times): next(it)
    return r


TIMES = list(range(0, 400, 20))  # deterministic causal-time sequence


def _carries_equal(a, b):
    # Compare the canonical (logically-live) state: a recovered subtask
    # never re-materializes storage a completed checkpoint truncated, so
    # dead ring slots may hold different garbage than the golden run's.
    from clonos_tpu.runtime.executor import canonical_carry
    fa, ta = jax.tree_util.tree_flatten(jax.device_get(canonical_carry(a)))
    fb, tb = jax.tree_util.tree_flatten(jax.device_get(canonical_carry(b)))
    assert ta == tb
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


# --- FSM unit behavior -------------------------------------------------------


def test_fsm_gates_on_connections_and_state():
    mgr = rec.RecoveryManager(1, 0, 2, replayer=None)
    mgr.notify_start_recovery(in_edges=[0], out_edges=[1])
    assert mgr.state == rec.RecoveryState.WAITING_CONNECTIONS
    mgr.notify_new_input_channel(0)
    assert mgr.state == rec.RecoveryState.WAITING_CONNECTIONS
    mgr.notify_new_output_channel(1)
    assert mgr.state == rec.RecoveryState.WAITING_CONNECTIONS  # state missing
    mgr.notify_state_restoration_complete()
    assert mgr.state == rec.RecoveryState.WAITING_DETERMINANTS
    mgr.expect_determinant_responses(2)
    mgr.notify_determinant_response(np.zeros((0, 8), np.int32), 0)
    assert mgr.state == rec.RecoveryState.WAITING_DETERMINANTS
    mgr.notify_determinant_response(np.zeros((0, 8), np.int32), 0)
    assert mgr.state == rec.RecoveryState.REPLAYING
    assert mgr.transitions == [
        rec.RecoveryState.STANDBY, rec.RecoveryState.WAITING_CONNECTIONS,
        rec.RecoveryState.WAITING_DETERMINANTS, rec.RecoveryState.REPLAYING]


def test_fsm_rejects_out_of_order_events():
    mgr = rec.RecoveryManager(1, 0, 2, replayer=None)
    with pytest.raises(rec.RecoveryError):
        mgr.notify_determinant_response(np.zeros((0, 8), np.int32), 0)


# --- end-to-end recovery -----------------------------------------------------


def test_single_failure_recovery_bit_identical():
    golden = _runner(TIMES)
    golden.run_epoch()
    golden.step()
    golden.step()

    r = _runner(TIMES)
    r.run_epoch()
    r.step()
    r.step()
    r.inject_failure([3])          # window vertex, subtask 1
    assert r.detect_failures() == [] or True  # liveness covered elsewhere
    report = r.recover()
    assert report.steps_replayed == 2
    assert report.failed_subtasks == (3,)
    mgr = report.managers[0]
    assert mgr.transitions[-1] == rec.RecoveryState.RUNNING
    _carries_equal(r.executor.carry, golden.executor.carry)
    # The cluster keeps running after recovery.
    golden.step()
    r.step()
    _carries_equal(r.executor.carry, golden.executor.carry)


def test_prewarmed_recovery_bit_identical_and_reusable():
    """Warm standby: prewarm_recovery() compiles the failure path up
    front; recovery still lands bit-identically, and a second failure of
    the same subtask reuses every compiled program."""
    golden = _runner(TIMES)
    golden.run_epoch()
    golden.step()
    golden.step()

    r = _runner(TIMES)
    warm_s = r.prewarm_recovery()
    assert warm_s >= 0
    r.run_epoch()
    r.step()
    r.step()
    r.inject_failure([3])
    r.recover()
    _carries_equal(r.executor.carry, golden.executor.carry)
    # Second failure of the same subtask: full protocol again, warm.
    r.inject_failure([3])
    report2 = r.recover()
    assert report2.failed_subtasks == (3,)
    _carries_equal(r.executor.carry, golden.executor.carry)
    golden.step()
    r.step()
    _carries_equal(r.executor.carry, golden.executor.carry)


def test_zero_step_recovery_right_after_checkpoint():
    """Failure exactly at a completed-checkpoint fence: nothing to replay
    (n_steps=0); recovery must restore the checkpoint state and not trip
    on empty determinant streams."""
    golden = _runner(TIMES)
    golden.run_epoch()
    r = _runner(TIMES)
    r.run_epoch()
    r.inject_failure([3])
    report = r.recover()
    assert report.steps_replayed == 0
    _carries_equal(r.executor.carry, golden.executor.carry)
    golden.step()
    r.step()
    _carries_equal(r.executor.carry, golden.executor.carry)


def test_prewarm_requires_standby():
    r = ClusterRunner(_job(), steps_per_epoch=3, num_standby=0, seed=3)
    with pytest.raises(rec.RecoveryError):
        r.prewarm_recovery()


def test_source_failure_recovery_bit_identical():
    golden = _runner(TIMES)
    golden.run_epoch()
    golden.step()

    r = _runner(TIMES)
    r.run_epoch()
    r.step()
    r.inject_failure([0])          # source vertex, subtask 0
    report = r.recover()
    assert report.steps_replayed == 1
    _carries_equal(r.executor.carry, golden.executor.carry)


def test_sink_failure_recovery_bit_identical():
    golden = _runner(TIMES)
    golden.run_epoch()
    golden.step()
    golden.step()

    r = _runner(TIMES)
    r.run_epoch()
    r.step()
    r.step()
    r.inject_failure([5])          # sink vertex, subtask 1 (no downstream)
    report = r.recover()
    _carries_equal(r.executor.carry, golden.executor.carry)


def test_concurrent_connected_failures():
    """Window subtask AND a sink subtask fail together (connected failures,
    README.md:41): the window's determinants come from the surviving sink
    replica; the sink is rebuilt via synthesis."""
    golden = _runner(TIMES)
    golden.run_epoch()
    golden.step()
    golden.step()

    r = _runner(TIMES)
    r.run_epoch()
    r.step()
    r.step()
    r.inject_failure([3, 4])       # window subtask 1 + sink subtask 0
    report = r.recover()
    assert report.failed_subtasks == (3, 4)
    _carries_equal(r.executor.carry, golden.executor.carry)


def _bench_job(parallelism=2):
    """The bench.py topology at test scale: source -> keyed window ->
    keyed reduce -> sink (4 vertex classes)."""
    env = StreamEnvironment(name="bench-mini", num_key_groups=16,
                            default_edge_capacity=32)
    (env.synthetic_source(vocab=VOCAB, batch_size=4, parallelism=parallelism)
        .key_by()
        .window_count(num_keys=VOCAB, window_size=1 << 30, name="window")
        .key_by()
        .reduce(num_keys=VOCAB, name="reduce")
        .sink())
    return env.build()


@pytest.mark.parametrize("flat", [0, 3, 4, 7],
                         ids=["source", "window", "reduce", "sink"])
def test_bench_topology_recovery_per_vertex_class(flat):
    """Every vertex class of the bench topology recovers bit-identically
    (the round-2 bench only ever failed the window — VERDICT weakness #12)."""
    def drive(r):
        r.executor.time_source.now = lambda it=iter(TIMES): next(it)
        r.run_epoch()
        r.step()
        r.step()
        return r

    golden = drive(ClusterRunner(_bench_job(), steps_per_epoch=3, seed=11))
    r = drive(ClusterRunner(_bench_job(), steps_per_epoch=3, seed=11))
    r.inject_failure([flat])
    report = r.recover()
    assert report.steps_replayed == 2
    _carries_equal(r.executor.carry, golden.executor.carry)
    golden.step()
    r.step()
    _carries_equal(r.executor.carry, golden.executor.carry)


def test_failure_with_pending_checkpoint_ignores_it():
    r = _runner(TIMES, steps_per_epoch=2)
    r.run_epoch()                      # ckpt 0 completes
    # Manually trigger a checkpoint that the soon-to-die subtask never acks.
    r.coordinator.trigger(99, r.executor.carry, async_write=False)
    r.step()
    r.inject_failure([2])
    report = r.recover()
    assert report.ignored_checkpoints == (99,)
    # Interval was backed off then reset after recovery completed.
    assert r.coordinator.interval_steps == r.coordinator.base_interval_steps


def test_recovery_without_checkpoint_fails_cleanly():
    r = _runner(TIMES)
    r.step()
    r.inject_failure([2])
    with pytest.raises(rec.RecoveryError):
        r.recover()


def test_heartbeat_detection():
    r = _runner(TIMES, steps_per_epoch=2)
    r.run_epoch()
    r.inject_failure([1])
    import time
    time.sleep(0.08)
    r.heartbeats.beat_all_except({1})
    assert r.detect_failures() == []   # dead ones are marked, not expired
    # A subtask that silently stops beating (not marked dead) is detected.
    r2 = _runner(TIMES, steps_per_epoch=2)
    r2.heartbeats.timeout_s = 0.01
    time.sleep(0.05)
    assert 0 in r2.detect_failures()


def test_failover_drill_leaves_state_identical():
    """failover_drill runs a real multi-class recovery mid-epoch and must
    leave the carry bit-identical (the rehearsal is free) — the standby
    warm-path capability (RunStandbyTaskStrategy keeps standbys running;
    here: every failure-path program and pool warmed by one drill)."""
    import jax
    import numpy as np
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    env = StreamEnvironment(name="drill", num_key_groups=8,
                            default_edge_capacity=64)
    (env.synthetic_source(vocab=13, batch_size=4, parallelism=2)
        .key_by().window_count(num_keys=13, window_size=1 << 30,
                               parallelism=2)
        .key_by().reduce(num_keys=13, parallelism=2).sink(parallelism=2))
    runner = ClusterRunner(env.build(), steps_per_epoch=4, log_capacity=256,
                           max_epochs=8, inflight_ring_steps=16, seed=21)
    from clonos_tpu.runtime.executor import canonical_carry
    runner.run_epoch(complete_checkpoint=True)
    runner.run_epoch(complete_checkpoint=False)   # mid-data: replay work
    before = jax.tree_util.tree_map(
        np.asarray, canonical_carry(runner.executor.carry))
    secs = runner.failover_drill()
    assert secs > 0
    after = jax.tree_util.tree_map(
        np.asarray, canonical_carry(runner.executor.carry))
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(a, b)
    # The job keeps running and can recover a REAL failure afterwards.
    runner.inject_failure([3])
    report = runner.recover()
    assert report.records_replayed >= 0


def test_failover_drill_refuses_unrecoverable_set_without_damage():
    """A drill whose failure set leaves some log with no surviving
    replica holder must refuse BEFORE zeroing any device state (review
    finding: the rehearsal must never corrupt a healthy job)."""
    import jax
    import numpy as np
    import pytest
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.causal.recovery import RecoveryError
    from clonos_tpu.runtime.cluster import ClusterRunner

    env = StreamEnvironment(name="drill-bad", num_key_groups=4,
                            default_edge_capacity=16)
    (env.synthetic_source(vocab=7, batch_size=2, parallelism=1)
        .key_by().window_count(num_keys=7, window_size=1 << 30,
                               parallelism=1).sink(parallelism=1))
    runner = ClusterRunner(env.build(), steps_per_epoch=4, log_capacity=128,
                           max_epochs=8, inflight_ring_steps=16, seed=3)
    runner.run_epoch(complete_checkpoint=True)
    runner.run_epoch(complete_checkpoint=False)
    before = jax.tree_util.tree_map(np.asarray, runner.executor.carry)
    with pytest.raises(RecoveryError, match="no surviving determinant"):
        runner.failover_drill()        # default set = every vertex class
    after = jax.tree_util.tree_map(np.asarray, runner.executor.carry)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(a, b)      # raw bytes untouched
    assert not runner.failed
    assert runner.reports == []                  # drills never ledger


def test_clean_recovery_uses_device_resident_stream():
    """A window failure with a consistent replica and a pure-sync stream
    must take the device-parse fast path (no log body on the host) and
    still recover bit-identically (covered by the golden tests above —
    this pins that the fast path is actually the one being exercised)."""
    r = _runner(TIMES)
    r.run_epoch()
    r.step()
    r.step()
    r.inject_failure([3])
    report = r.recover()
    mgr = report.managers[0]
    assert mgr.plan.det_device is not None        # device stream used
    assert mgr.plan.det_rows.shape[0] == 0        # no host rows pulled
    assert report.determinants_replayed > 0       # counted from device meta


def test_same_vertex_pair_failure_shares_routed_windows():
    """Two subtasks of the SAME vertex fail together: the second consumer
    reuses the first's routed edge windows (cache-hit path) and recovery
    stays bit-identical vs a never-failed run."""
    golden = _runner(TIMES, parallelism=2)
    golden.run_epoch()
    golden.step()
    golden.step()

    r = _runner(TIMES, parallelism=2)
    r.run_epoch()
    r.step()
    r.step()
    r.inject_failure([2, 3])          # BOTH window subtasks
    report = r.recover()
    assert report.failed_subtasks == (2, 3)
    # The second consumer must have HIT the shared routed windows (pins
    # the cache keying; bit-identity alone would pass a broken cache).
    assert r._route_cache_hits > 0
    _carries_equal(r.executor.carry, golden.executor.carry)
    golden.step()
    r.step()
    _carries_equal(r.executor.carry, golden.executor.carry)
