"""Queryable state (flink-runtime/query analog) and JobMaster leader
election with fencing tokens (flink-runtime leaderelection /
highavailability analog)."""

import numpy as np
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.runtime.cluster import ClusterRunner
from clonos_tpu.runtime.leader import FileLeaderElection
from clonos_tpu.runtime.query import (QueryableStateClient,
                                      QueryableStateEndpoint)


def test_queryable_state_point_lookup():
    """External client resolves (vertex, key) to the OWNING subtask's
    dense-table entry — same key-group assignment as the exchange — and
    sees fence-consistent values that advance with epochs."""
    env = StreamEnvironment(name="qs", num_key_groups=16,
                            default_edge_capacity=64)
    (env.synthetic_source(vocab=11, batch_size=8, parallelism=2)
        .key_by().reduce(num_keys=11, name="r").sink())
    r = ClusterRunner(env.build(), steps_per_epoch=4, log_capacity=256,
                      max_epochs=8, inflight_ring_steps=16, seed=3)
    r.run_epoch(complete_checkpoint=True)
    ep = QueryableStateEndpoint(r)
    try:
        c = QueryableStateClient(ep.address)
        acc = np.asarray(r.executor.vertex_state(1)["acc"])
        for key in range(11):
            out = c.query(vertex=1, key=key)
            assert out["value"] == int(acc[out["subtask"], key])
            assert int(acc[:, key].sum()) == out["value"], \
                "key owned by exactly one subtask"
        e0 = out["epoch"]
        # State advances with the next fence refresh.
        r.run_epoch(complete_checkpoint=False)
        ep.refresh()
        out2 = c.query(vertex=1, key=3)
        assert out2["epoch"] > e0
        acc2 = np.asarray(r.executor.vertex_state(1)["acc"])
        assert out2["value"] == int(acc2[out2["subtask"], 3])
        with pytest.raises(KeyError):
            c.query(vertex=1, key=999)
        c.close()
    finally:
        ep.close()


def test_leader_election_takeover_and_fencing(tmp_path):
    """Exactly one leader; a lapsed lease is taken over with a HIGHER
    fencing epoch; the deposed leader's renew fails and its stale epoch
    is rejected (no split brain)."""
    path = str(tmp_path / "jm.lease")
    t = [0.0]
    clock = lambda: t[0]
    a = FileLeaderElection(path, "jm-a", lease_ttl_s=2.0, clock=clock)
    b = FileLeaderElection(path, "jm-b", lease_ttl_s=2.0, clock=clock)

    assert a.try_acquire() and a.is_leader() and a.epoch == 1
    assert not b.try_acquire() and not b.is_leader()
    assert a.leader() == "jm-a"

    # Healthy renewal keeps the same fencing token.
    t[0] = 1.0
    assert a.renew() and a.epoch == 1

    # Leader stalls past the TTL; standby takes over with epoch 2.
    t[0] = 3.5
    assert b.try_acquire() and b.epoch == 2
    assert b.leader() == "jm-b"

    # The deposed leader cannot renew — and critically its renew can
    # NEVER clobber the takeover: it rewrites only its own epoch's
    # claim, which no reader looks at once a higher epoch exists (the
    # split-brain race a shared lease file cannot avoid).
    assert not a.renew() and not a.is_leader()
    assert b.leader() == "jm-b"
    assert not b.fencing_valid(1)
    assert b.fencing_valid(2)
    # A forged token for an epoch nobody won through O_EXCL arbitration
    # is rejected too: valid tokens are EXACTLY the highest claim.
    assert not b.fencing_valid(3)
    # Claims carry wall-clock deadlines — comparable across hosts/boots.
    import json
    with open(b._claim_path(2)) as f:
        rec = json.load(f)
    assert rec["leader_id"] == "jm-b" and "deadline_wall" in rec

    # Re-acquire by the old leader only after the new lease lapses,
    # with a fresh higher epoch.
    t[0] = 4.0
    assert not a.try_acquire()
    t[0] = 6.0
    assert a.try_acquire() and a.epoch == 3
    # Superseded claims are garbage-collected (epochs < current-1).
    assert a._claims() == [2, 3]

    # The race arbiter: an epoch is claimable exactly once (O_EXCL), and
    # a just-created still-empty claim counts as live (mid-write grace)
    # so nobody steals an epoch whose owner is between create and write.
    import os
    fd = os.open(b._claim_path(9), os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    os.close(fd)
    assert not a.try_acquire() and not b.try_acquire()
    with pytest.raises(FileExistsError):
        os.close(os.open(b._claim_path(9),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY))


def test_file_sink_gated_on_leadership_fencing(tmp_path):
    """A FileSystemSink owned by a deposed JobMaster incarnation must not
    write, commit, or sweep: a stale leader sweeping pending files would
    destroy the NEW leader's in-flight transactions. The sink checks its
    election handle at every mutation."""
    from clonos_tpu.runtime.filesink import FileSystemSink

    path = str(tmp_path / "jm.lease")
    t = [0.0]
    a = FileLeaderElection(path, "jm-a", lease_ttl_s=2.0,
                           clock=lambda: t[0])
    assert a.try_acquire()
    rows = np.asarray([[1, 2, 3]], np.int32)
    sink = FileSystemSink(str(tmp_path / "out"), fencing=a)
    sink.write_pending(1, {0: rows})          # leader: allowed
    sink.commit(1, rows)
    assert sink.sweep_pending() == []

    # Depose jm-a; its sink handle must refuse every mutation.
    t[0] = 3.5
    b = FileLeaderElection(path, "jm-b", lease_ttl_s=2.0,
                           clock=lambda: t[0])
    assert b.try_acquire()
    assert not a.renew()
    for op in (lambda: sink.write_pending(2, {0: rows}),
               lambda: sink.commit(2, rows),
               lambda: sink.sweep_pending()):
        with pytest.raises(PermissionError):
            op()
    # The new incarnation's sink over the same root works.
    sink_b = FileSystemSink(str(tmp_path / "out"), fencing=b)
    sink_b.write_pending(2, {0: rows})
    sink_b.commit(2, rows)
    assert sink_b.committed_epochs() == [1, 2]
