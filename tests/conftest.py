"""Test harness: run on a virtual 8-device CPU mesh.

The reference tests multi-node behavior without a cluster via the in-JVM
MiniCluster (flink-runtime .../minicluster/MiniCluster.java:108). The JAX
analog is forcing the host platform to expose 8 virtual devices, so every
sharding/collective path is exercised single-process.

Note: the JAX_PLATFORMS *environment variable* is overridden by the axon
TPU PJRT plugin in this image; ``jax.config.update`` is authoritative, so
the platform is forced through the config API after import.
"""

import os

import pytest

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after XLA_FLAGS is set)

jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the suite is compile-dominated; a warm cache
# cuts repeat runs several-fold. Keyed by HLO hash — safe across edits.
from clonos_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache(os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache"))

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pytest_configure(config):
    """Register this repo's markers (clonos_tpu/lint/markers.py is the
    single source of truth) and run the full determinism lint — a
    typo'd marker is a silent no-op under ``-m 'not slow'``, and an
    unlogged time.time() is a replay divergence waiting for a failure
    to surface it, so both fail the session here with file:line
    findings instead."""
    from clonos_tpu.lint import format_text, run_lint
    from clonos_tpu.lint.markers import REGISTERED_MARKERS

    for name, help_text in REGISTERED_MARKERS.items():
        config.addinivalue_line("markers", f"{name}: {help_text}")
    cwd = os.getcwd()
    os.chdir(_REPO_ROOT)   # finding paths & waiver globs repo-relative
    try:
        result = run_lint(["clonos_tpu", "examples", "tests"])
    finally:
        os.chdir(cwd)
    if not result.ok:
        raise pytest.UsageError(
            "determinism lint failed (clonos_tpu lint):\n"
            + format_text(result))
    # Same gate for the whole-program analysis (clonos_tpu analyze):
    # a nondet escape that reaches a step function, or a lock-order
    # cycle, fails the session before any test runs. Stale analysis
    # waivers are warnings — printed, not fatal.
    import sys as _sys
    from clonos_tpu.analysis import (format_text as a_format,
                                     run_analysis)
    cwd = os.getcwd()
    os.chdir(_REPO_ROOT)
    try:
        aresult = run_analysis(["clonos_tpu", "examples"])
    finally:
        os.chdir(cwd)
    if not aresult.ok:
        raise pytest.UsageError(
            "whole-program analysis failed (clonos_tpu analyze):\n"
            + a_format(aresult))
    for w in aresult.warnings:
        print(f"analyze warning: {w.location()}: [{w.rule}] "
              f"{w.message}", file=_sys.stderr)
    # Census-drift gate: the pinned fingerprint (.clonos-census) must
    # match — the FT call-site population changing silently is how a
    # new unlogged call site slips past review.
    pin_path = os.path.join(_REPO_ROOT, ".clonos-census")
    if os.path.isfile(pin_path):
        with open(pin_path) as f:
            toks = f.read().split()
        pinned = toks[0] if toks else ""
        if aresult.census_fingerprint != pinned:
            raise pytest.UsageError(
                f"census drift: fingerprint "
                f"{aresult.census_fingerprint} != pinned {pinned} "
                f"(.clonos-census) — the FT call-site population "
                f"changed; review `clonos_tpu analyze --census`, then "
                f"re-pin with\n  python -m clonos_tpu.cli analyze "
                f"--report json | python -c \"import json,sys; "
                f"print(json.load(sys.stdin)['census_fingerprint'])\" "
                f"> .clonos-census")
    # Thread-census drift gate: the pinned fingerprint (.clonos-threads)
    # must match — a new thread root appearing (or one being re-homed)
    # silently is how an unreviewed concurrency interaction slips past
    # the race pass's discharge reasoning.
    tpin_path = os.path.join(_REPO_ROOT, ".clonos-threads")
    if os.path.isfile(tpin_path):
        with open(tpin_path) as f:
            toks = f.read().split()
        pinned = toks[0] if toks else ""
        if aresult.threads_fingerprint != pinned:
            raise pytest.UsageError(
                f"thread-census drift: fingerprint "
                f"{aresult.threads_fingerprint} != pinned {pinned} "
                f"(.clonos-threads) — the thread-root population "
                f"changed (a thread was added, removed, or re-homed); "
                f"review `clonos_tpu analyze --threads`, then re-pin "
                f"with\n  python -m clonos_tpu.cli analyze "
                f"--report json --no-census | python -c \"import json,"
                f"sys; print(json.load(sys.stdin)"
                f"['threads_fingerprint'])\" > .clonos-threads")
    # Protocol model-checker gate (clonos_tpu verify --quick): every
    # safety invariant on every reachable state of the four protocol
    # models at the quick bound, sub-second and jax-free. A violation
    # prints the minimal counterexample trace.
    from clonos_tpu.verify import format_text as v_format, run_verify
    vresult = run_verify(quick=True)
    if not vresult.ok:
        raise pytest.UsageError(
            "protocol model check failed (clonos_tpu verify --quick):\n"
            + v_format(vresult))
    # Timeline causality gate (clonos_tpu timeline --self-check): two
    # skew-clocked simulated processes exchange HLC-stamped messages;
    # the merged stream must show zero inversions. Pure and sub-
    # millisecond — a broken receive rule fails the session here, not
    # in a flaky multi-process soak.
    from clonos_tpu.obs.timeline import timeline_self_check
    findings = timeline_self_check()
    if findings:
        raise pytest.UsageError(
            "HLC causality self-check failed (clonos_tpu timeline "
            "--self-check): " + "; ".join(
                f"[{f['rule']}] {f['detail']}" for f in findings))
    # Incident forensics gate (clonos_tpu incident --self-check):
    # synthetic bundles through capture → root-cause localization,
    # byte-identity enforced across a JSON round-trip. Pure and
    # jax-free — a drifting report encoding fails the session here,
    # not in a post-mortem.
    from clonos_tpu.obs.incident import (bundle_schema_fingerprint,
                                         incident_self_check)
    ifindings = incident_self_check()
    if ifindings:
        raise pytest.UsageError(
            "incident forensics self-check failed (clonos_tpu "
            "incident --self-check): " + "; ".join(
                f"[{f['rule']}] {f['detail']}" for f in ifindings))
    # Bundle-schema drift gate: landed bundles are durable post-mortem
    # artifacts — the schema changing silently orphans every bundle
    # already on disk. The pinned fingerprint must match.
    ipin_path = os.path.join(_REPO_ROOT, ".clonos-incident-schema")
    if os.path.isfile(ipin_path):
        with open(ipin_path) as f:
            toks = f.read().split()
        pinned = toks[0] if toks else ""
        fp = bundle_schema_fingerprint()
        if fp != pinned:
            raise pytest.UsageError(
                f"incident bundle-schema drift: fingerprint {fp} != "
                f"pinned {pinned} (.clonos-incident-schema) — the "
                f"bundle layout changed; bump BUNDLE_SCHEMA's version "
                f"(obs/incident.py) so old bundles stay decodable, "
                f"then re-pin with\n  python -c \"from clonos_tpu.obs."
                f"incident import bundle_schema_fingerprint; "
                f"print(bundle_schema_fingerprint())\" "
                f"> .clonos-incident-schema")
    # Record-lineage gate (clonos_tpu lineage --self-check): synthetic
    # observations through the full dye → hop → terminus join, with
    # byte-identity enforced across a JSON round-trip AND a shuffled
    # observation order (two processes must render the same trace).
    # Pure and jax-free — a drifting reconstructor fails the session
    # here, not while someone is tracing a lost record.
    from clonos_tpu.obs.lineage import (lineage_schema_fingerprint,
                                        lineage_self_check)
    lfindings = lineage_self_check()
    if lfindings:
        raise pytest.UsageError(
            "record-lineage self-check failed (clonos_tpu lineage "
            "--self-check): " + "; ".join(
                f"[{f['rule']}] {f['detail']}" for f in lfindings))
    # Lineage-schema drift gate: lineage-*.jsonl observation files are
    # durable run artifacts — the schema changing silently orphans
    # every file already on disk. The pinned fingerprint must match.
    lpin_path = os.path.join(_REPO_ROOT, ".clonos-lineage-schema")
    if os.path.isfile(lpin_path):
        with open(lpin_path) as f:
            toks = f.read().split()
        pinned = toks[0] if toks else ""
        fp = lineage_schema_fingerprint()
        if fp != pinned:
            raise pytest.UsageError(
                f"lineage schema drift: fingerprint {fp} != pinned "
                f"{pinned} (.clonos-lineage-schema) — the observation "
                f"layout changed; bump LINEAGE_SCHEMA's version "
                f"(obs/lineage.py) so old observation files stay "
                f"readable, then re-pin with\n  python -c \"from "
                f"clonos_tpu.obs.lineage import "
                f"lineage_schema_fingerprint; "
                f"print(lineage_schema_fingerprint())\" "
                f"> .clonos-lineage-schema")


@pytest.fixture
def eight_devices():
    """The 8 virtual host devices the multi-device (mesh-sharded) tests
    run on. XLA_FLAGS above forces the count before the backend
    initializes; if something else initialized it first (e.g. a real
    single-chip backend), skip rather than fail."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip(f"needs 8 devices, have {len(devs)}")
    return devs[:8]
