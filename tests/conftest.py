"""Test harness: run on a virtual 8-device CPU mesh.

The reference tests multi-node behavior without a cluster via the in-JVM
MiniCluster (flink-runtime .../minicluster/MiniCluster.java:108). The JAX
analog is forcing the host platform to expose 8 virtual devices, so every
sharding/collective path is exercised single-process.

Note: the JAX_PLATFORMS *environment variable* is overridden by the axon
TPU PJRT plugin in this image; ``jax.config.update`` is authoritative, so
the platform is forced through the config API after import.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after XLA_FLAGS is set)

jax.config.update("jax_platforms", "cpu")
