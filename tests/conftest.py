"""Test harness: run on a virtual 8-device CPU mesh.

The reference tests multi-node behavior without a cluster via the in-JVM
MiniCluster (flink-runtime .../minicluster/MiniCluster.java:108). The JAX
analog is forcing the host platform to expose 8 virtual devices, so every
sharding/collective path is exercised single-process.

Note: the JAX_PLATFORMS *environment variable* is overridden by the axon
TPU PJRT plugin in this image; ``jax.config.update`` is authoritative, so
the platform is forced through the config API after import.
"""

import os

import pytest

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after XLA_FLAGS is set)

jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the suite is compile-dominated; a warm cache
# cuts repeat runs several-fold. Keyed by HLO hash — safe across edits.
from clonos_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache(os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache"))

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pytest_configure(config):
    """Register this repo's markers (tools/check_markers.py is the
    single source of truth) and lint the suite for unregistered ones —
    a typo'd marker is a silent no-op under ``-m 'not slow'``, so it
    fails the session here instead."""
    import sys
    sys.path.insert(0, os.path.join(_REPO_ROOT, "tools"))
    try:
        import check_markers
    finally:
        sys.path.pop(0)
    for name, help_text in check_markers.REGISTERED_MARKERS.items():
        config.addinivalue_line("markers", f"{name}: {help_text}")
    violations = check_markers.check(os.path.join(_REPO_ROOT, "tests"))
    if violations:
        raise pytest.UsageError("\n".join(violations))
