"""Multi-device execution: the job sharded over an 8-device mesh must
compute bit-identically to the single-device program, with state actually
distributed (the TaskManager-deployment analog; conftest forces 8 virtual
CPU devices like the reference's MiniCluster forces in-JVM TMs)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.runtime.executor import CompiledJob, StepInputs


def _job(parallelism):
    env = StreamEnvironment(num_key_groups=32, default_edge_capacity=64)
    (env.synthetic_source(vocab=17, batch_size=8, parallelism=parallelism)
        .key_by().window_count(num_keys=17, window_size=1 << 30).sink())
    return env.build()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_mesh_execution_matches_single_device():
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("tasks",))
    job_m = _job(8)
    cm = CompiledJob(job_m, log_capacity=1 << 9, max_epochs=8,
                     inflight_ring_steps=8, mesh=mesh)
    job_s = _job(8)
    cs = CompiledJob(job_s, log_capacity=1 << 9, max_epochs=8,
                     inflight_ring_steps=8, mesh=None)

    inputs = StepInputs(jnp.asarray(3, jnp.int32), jnp.asarray(7, jnp.int32))
    with mesh:
        carry_m = jax.jit(cm.init_carry)()
        step_m = jax.jit(cm.superstep)
        for _ in range(3):
            carry_m, out_m = step_m(carry_m, inputs)
        jax.block_until_ready(carry_m)
        # State is genuinely distributed across devices.
        acc = carry_m.op_states[1]["acc"]
        assert len(acc.sharding.device_set) == 8

    carry_s = cs.init_carry()
    step_s = jax.jit(cs.superstep)
    for _ in range(3):
        carry_s, out_s = step_s(carry_s, inputs)

    fa, ta = jax.tree_util.tree_flatten(jax.device_get(carry_m))
    fb, tb = jax.tree_util.tree_flatten(jax.device_get(carry_s))
    assert ta == tb
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_graft_dryrun_multichip():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)
