"""Multi-device execution: the job sharded over an 8-device mesh must
compute bit-identically to the single-device program, with state actually
distributed (the TaskManager-deployment analog; conftest forces 8 virtual
CPU devices like the reference's MiniCluster forces in-JVM TMs)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.runtime.executor import CompiledJob, StepInputs


def _job(parallelism):
    env = StreamEnvironment(num_key_groups=32, default_edge_capacity=64)
    (env.synthetic_source(vocab=17, batch_size=8, parallelism=parallelism)
        .key_by().window_count(num_keys=17, window_size=1 << 30).sink())
    return env.build()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_mesh_execution_matches_single_device():
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("tasks",))
    job_m = _job(8)
    cm = CompiledJob(job_m, log_capacity=1 << 9, max_epochs=8,
                     inflight_ring_steps=8, mesh=mesh)
    job_s = _job(8)
    cs = CompiledJob(job_s, log_capacity=1 << 9, max_epochs=8,
                     inflight_ring_steps=8, mesh=None)

    inputs = StepInputs(jnp.asarray(3, jnp.int32), jnp.asarray(7, jnp.int32))
    with mesh:
        carry_m = jax.jit(cm.init_carry)()
        step_m = jax.jit(cm.superstep)
        for _ in range(3):
            carry_m, out_m = step_m(carry_m, inputs)
        jax.block_until_ready(carry_m)
        # State is genuinely distributed across devices.
        acc = carry_m.op_states[1]["acc"]
        assert len(acc.sharding.device_set) == 8

    carry_s = cs.init_carry()
    step_s = jax.jit(cs.superstep)
    for _ in range(3):
        carry_s, out_s = step_s(carry_s, inputs)

    fa, ta = jax.tree_util.tree_flatten(jax.device_get(carry_m))
    fb, tb = jax.tree_util.tree_flatten(jax.device_get(carry_s))
    assert ta == tb
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_graft_dryrun_multichip():
    import __graft_entry__ as ge
    out = ge.dryrun_multichip(8)
    assert out["ok"] and out["n_devices"] == 8
    assert out["records_per_sec_sharded"] > 0
    assert out["per_shard_records_per_sec"] is not None \
        and len(out["per_shard_records_per_sec"]) == 8
    assert out["scaling_efficiency"] > 0


@pytest.mark.slow
def test_sharded_vs_unsharded_digest_equality(tmp_path, eight_devices):
    """The exactly-once fence contract is sharding-invariant: the same
    job run under a 1-device mesh and an 8-device mesh seals
    bit-identical epoch digests (``diff_ledgers`` empty)."""
    from clonos_tpu.obs.digest import diff_ledgers
    from clonos_tpu.parallel import distributed as dist
    from clonos_tpu.runtime.cluster import ClusterRunner

    ledgers = {}
    for ndev in (1, 8):
        r = ClusterRunner(_job(8), steps_per_epoch=8, log_capacity=512,
                          max_epochs=8, inflight_ring_steps=32, seed=3,
                          checkpoint_dir=str(tmp_path / f"m{ndev}"),
                          audit=True, logical_time=True,
                          mesh=dist.task_mesh(max_devices=ndev))
        for _ in range(3):
            r.run_epoch(complete_checkpoint=True)
        health = r.per_shard_health()
        assert health is not None and health.shape == (ndev, 3)
        # Per-shard detail depends on which flats a shard owns (sink-only
        # shards count 0 records; a completed checkpoint truncates most
        # log rows) — assert the aggregates moved.
        assert health[:, 0].sum() > 0 and health[:, 1].sum() > 0
        ledgers[ndev] = r.coordinator.read_ledger()
    assert [e["epoch"] for e in ledgers[1]] == [0, 1, 2]
    assert diff_ledgers(ledgers[1], ledgers[8]) == []


@pytest.mark.slow
def test_shard_local_recovery(tmp_path, eight_devices):
    """A failed subtask on one shard recovers by restoring/replaying only
    that shard's slice: the report's restore bytes stay below the full
    checkpoint, and healthy shards keep their live state untouched."""
    from clonos_tpu.parallel import distributed as dist
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.utils.compile_cache import aot_lower_first_step

    r = ClusterRunner(_job(8), steps_per_epoch=8, log_capacity=512,
                      max_epochs=8, inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"),
                      logical_time=True,
                      mesh=dist.task_mesh(max_devices=8))
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    # The standby's sharded first-step program AOT-lowers cleanly.
    assert aot_lower_first_step(r.executor) is not None

    before = jax.device_get(r.executor.carry)
    failed = 8 + 2          # one window subtask = one shard's slice
    r.inject_failure([failed])
    report = r.recover()

    assert set(report.failed_subtasks) == {failed}
    assert len(report.managers) == 1, "only the failed slice replays"
    assert 0 < report.restore_bytes < report.checkpoint_bytes, \
        "per-shard restore must move less than the full carry"
    # Healthy shards kept their live buffers: every non-failed window
    # subtask's operator state and record count is bit-identical.
    after = jax.device_get(r.executor.carry)
    acc_b = np.asarray(before.op_states[1]["acc"])
    acc_a = np.asarray(after.op_states[1]["acc"])
    for i in range(8):
        if i != 2:
            np.testing.assert_array_equal(acc_a[i], acc_b[i])
    rc_b = np.asarray(before.record_counts)
    rc_a = np.asarray(after.record_counts)
    healthy = [i for i in range(rc_b.shape[0]) if i != failed]
    np.testing.assert_array_equal(rc_a[healthy], rc_b[healthy])
