"""Epoch tracker tests: device scalars + host async-determinant firing
(reference EpochTrackerImpl.java:40)."""

import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.causal import epoch as ep
from clonos_tpu.causal.determinant import TimerTriggerDeterminant


def test_device_epoch_state_under_jit():
    s = ep.EpochState.initial()

    @jax.jit
    def f(s):
        s = ep.inc_record_count(s, 5)
        s = ep.inc_record_count(s, 3)
        s = ep.start_new_epoch(s, 1)
        s = ep.inc_record_count(s, 2)
        return s

    s = f(s)
    assert int(s.epoch_id) == 1
    assert int(s.record_count) == 2
    assert int(s.total_records) == 10


def test_host_tracker_listeners():
    t = ep.EpochTracker()
    seen = []
    t.subscribe_epoch_start(seen.append)
    t.subscribe_checkpoint_complete(lambda c: seen.append(("ckpt", c)))
    t.start_new_epoch(1)
    t.notify_checkpoint_complete(0)
    assert seen == [1, ("ckpt", 0)]
    assert t.record_count == 0


def test_async_determinant_fires_at_target():
    t = ep.EpochTracker()
    fired = []
    d5 = TimerTriggerDeterminant(record_count=5, callback_id=1)
    d2 = TimerTriggerDeterminant(record_count=2, callback_id=2)
    t.set_record_count_target(5, d5, fired.append)
    t.set_record_count_target(2, d2, fired.append)
    t.inc_record_count(1)
    assert fired == []
    t.inc_record_count(1)  # rc=2
    assert fired == [d2]
    t.inc_record_count(4)  # rc=6, passes 5
    assert fired == [d2, d5]
    assert t.pending_targets == 0


def test_same_target_fifo_order():
    t = ep.EpochTracker()
    fired = []
    a = TimerTriggerDeterminant(record_count=3, callback_id=1)
    b = TimerTriggerDeterminant(record_count=3, callback_id=2)
    t.set_record_count_target(3, a, fired.append)
    t.set_record_count_target(3, b, fired.append)
    t.inc_record_count(3)
    assert fired == [a, b]


def test_target_in_past_rejected():
    t = ep.EpochTracker()
    t.inc_record_count(10)
    with pytest.raises(ValueError):
        t.set_record_count_target(
            5, TimerTriggerDeterminant(record_count=5), lambda d: None)


def test_target_at_current_count_fires_immediately():
    """Reference setRecordCountTarget:111 fires when recordCount == target
    at registration time."""
    t = ep.EpochTracker()
    t.inc_record_count(5)
    fired = []
    d = TimerTriggerDeterminant(record_count=5, callback_id=9)
    t.set_record_count_target(5, d, fired.append)
    assert fired == [d]


def test_target_zero_fires_on_epoch_start():
    """A determinant recorded as the first event of an epoch must fire when
    the epoch starts (record_count resets to 0)."""
    t = ep.EpochTracker()
    t.inc_record_count(3)
    fired = []
    # registered during replay setup for the *next* epoch
    t.start_new_epoch(1)
    d = TimerTriggerDeterminant(record_count=0, callback_id=1)
    t.set_record_count_target(0, d, fired.append)
    assert fired == [d]
