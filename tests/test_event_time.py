"""Event-time windows + watermarks: semantics against a python oracle,
block == scan equivalence, and bit-identical recovery under failure
(reference WindowOperator event-time/sliding/session breadth with
watermarks; here the watermark is a pure fold over record timestamps so
replay needs no watermark determinant)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.api.operators import (
    BlockContext, EventTimeTumblingWindowOperator, Operator,
    SessionWindowOperator, SlidingEventTimeWindowOperator)
from clonos_tpu.api.records import RecordBatch, zero_invalid
from clonos_tpu.runtime.cluster import ClusterRunner


def _step_batch(recs, cap=8, p=1):
    keys = np.zeros((p, cap), np.int32)
    vals = np.zeros((p, cap), np.int32)
    ts = np.zeros((p, cap), np.int32)
    valid = np.zeros((p, cap), bool)
    for j, (k, v, t) in enumerate(recs):
        keys[0, j], vals[0, j], ts[0, j], valid[0, j] = k, v, t, True
    return zero_invalid(RecordBatch(jnp.asarray(keys), jnp.asarray(vals),
                                    jnp.asarray(ts), jnp.asarray(valid)))


def _ctx(p=1):
    return BlockContext(
        times=jnp.zeros((1,), jnp.int32), rng_bits=jnp.zeros((1,), jnp.int32),
        epoch=jnp.zeros((), jnp.int32), step0=jnp.zeros((), jnp.int32),
        subtask=jnp.arange(p, dtype=jnp.int32)).at_step(0)


def _run_steps(op, steps):
    state = op.init_state(1)
    fired = []
    for recs in steps:
        state, out = op.process(state, _step_batch(recs), _ctx())
        m = np.asarray(out.valid[0])
        for k, v, t in zip(np.asarray(out.keys[0])[m],
                           np.asarray(out.values[0])[m],
                           np.asarray(out.timestamps[0])[m]):
            fired.append((int(k), int(v), int(t)))
    return state, fired


def test_tumbling_event_time_fires_on_watermark():
    op = EventTimeTumblingWindowOperator(num_keys=4, window_size=10,
                                         out_of_orderness=5)
    state, fired = _run_steps(op, [
        [(1, 2, 3), (2, 1, 7)],          # window 0
        [(1, 1, 12)],                    # window 1; wm=7: nothing closes
        [(1, 1, 9)],                     # late-ish but wm=7 allows w0
        [(2, 5, 21)],                    # wm=16 -> window 0 fires
        [(3, 1, 40)],                    # wm=35 -> windows 1,2 fire
    ])
    assert (1, 3, 10) in fired and (2, 1, 10) in fired   # window 0 sums
    assert (1, 1, 20) in fired                           # window 1
    assert (2, 5, 30) in fired                           # window 2
    assert int(state["late"][0]) == 0


def test_tumbling_late_records_dropped_and_counted():
    op = EventTimeTumblingWindowOperator(num_keys=4, window_size=10,
                                         out_of_orderness=0)
    state, fired = _run_steps(op, [
        [(1, 1, 5)],
        [(1, 1, 25)],                    # wm=25 -> window 0,1 closed
        [(1, 9, 3)],                     # late: window 0 already closed
    ])
    assert int(state["late"][0]) == 1
    assert (1, 1, 10) in fired
    assert all(v != 9 for _, v, _ in fired)


def test_sliding_event_time_oracle():
    op = SlidingEventTimeWindowOperator(num_keys=4, window_size=20,
                                        slide=10, out_of_orderness=0)
    state, fired = _run_steps(op, [
        [(1, 1, 5)],                     # windows starting at -10, 0
        [(1, 2, 15)],                    # windows 0, 10
        [(1, 4, 42)],                    # wm=42: windows [-10,10],[0,20],
                                         # [10,30] close
    ])
    # window [0, 20) = 1+2 = 3; window [-10, 10) = 1; window [10, 30) = 2
    assert (1, 1, 10) in fired
    assert (1, 3, 20) in fired
    assert (1, 2, 30) in fired


def test_session_window_gap_merging_and_late():
    op = SessionWindowOperator(num_keys=4, gap=10, out_of_orderness=0)
    state, fired = _run_steps(op, [
        [(1, 1, 0), (1, 2, 5)],          # one session [0, 5]
        [(1, 3, 12)],                    # extends (12 - 5 < gap... 7<10)
        [(2, 1, 40)],                    # wm=40 -> key1 session fires
    ])
    assert (1, 6, 22) in fired           # sum 6, end 12+gap
    s2, fired2 = _run_steps(op, [
        [(1, 1, 0)],
        [(2, 1, 50)],                    # closes key1's session
        [(1, 5, 2)],                     # late for the closed frontier
    ])
    assert (1, 1, 10) in fired2
    assert int(s2["late"][0]) == 1


@pytest.mark.parametrize("op", [
    EventTimeTumblingWindowOperator(num_keys=5, window_size=8,
                                    out_of_orderness=6),
    SlidingEventTimeWindowOperator(num_keys=5, window_size=8, slide=4,
                                   out_of_orderness=6),
    SessionWindowOperator(num_keys=5, gap=6, out_of_orderness=4),
])
def test_event_windows_block_equals_scan(op):
    rng = np.random.RandomState(0)
    K, P, B = 6, 2, 8
    keys = rng.randint(0, 5, (K, P, B)).astype(np.int32)
    vals = rng.randint(1, 4, (K, P, B)).astype(np.int32)
    # Mostly-increasing event times with bounded disorder.
    base = np.sort(rng.randint(0, 60, (K, P, B)), axis=0).astype(np.int32)
    valid = rng.rand(K, P, B) < 0.8
    batches = zero_invalid(RecordBatch(
        jnp.asarray(keys), jnp.asarray(vals), jnp.asarray(base),
        jnp.asarray(valid)))
    bctx = BlockContext(
        times=jnp.arange(K, dtype=jnp.int32),
        rng_bits=jnp.zeros((K,), jnp.int32),
        epoch=jnp.zeros((), jnp.int32), step0=jnp.zeros((), jnp.int32),
        subtask=jnp.arange(P, dtype=jnp.int32))
    state = op.init_state(P)
    ref = jax.jit(lambda s, b, c: Operator.process_block(op, s, b, c))(
        state, batches, bctx)
    blk = jax.jit(op.process_block)(state, batches, bctx)
    for xa, xb in zip(jax.tree_util.tree_leaves(ref),
                      jax.tree_util.tree_leaves(blk)):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def test_event_time_job_recovers_bit_identically():
    """An event-time window job survives a window-subtask failure with
    bit-identical state — watermarks replay because they are a pure
    function of the replayed inputs (no watermark determinant)."""
    def build():
        env = StreamEnvironment(name="evt", num_key_groups=16)
        (env.synthetic_source(vocab=19, batch_size=6, parallelism=2)
            .key_by()
            .window_event_time(num_keys=19, window_size=64,
                               out_of_orderness=16)
            .sink())
        return env.build()

    def runner():
        r = ClusterRunner(build(), steps_per_epoch=3, seed=3)
        r.executor.time_source.now = \
            lambda it=iter(range(0, 4000, 20)): next(it)
        return r

    golden = runner()
    r = runner()
    for rr in (golden, r):
        rr.run_epoch()
        rr.step()
        rr.step()
    r.inject_failure([3])               # window subtask 1
    rep = r.recover()
    assert rep.steps_replayed == 2
    from clonos_tpu.runtime.executor import canonical_carry
    for xa, xb in zip(
            jax.tree_util.tree_leaves(canonical_carry(r.executor.carry)),
            jax.tree_util.tree_leaves(
                canonical_carry(golden.executor.carry))):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
    golden.step()
    r.step()
    for xa, xb in zip(
            jax.tree_util.tree_leaves(canonical_carry(r.executor.carry)),
            jax.tree_util.tree_leaves(
                canonical_carry(golden.executor.carry))):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def test_session_far_apart_records_make_two_sessions():
    """Records separated by more than gap must NOT merge (review finding:
    the absorb rule needs the gap-distance check, not just the frontier)."""
    op = SessionWindowOperator(num_keys=4, gap=10, out_of_orderness=0)
    state, fired = _run_steps(op, [
        [(1, 1, 50)],
        [(1, 2, 95)],                    # 45 > gap: closes the first
        [(2, 1, 200)],                   # closes the second
    ])
    assert (1, 1, 60) in fired
    assert (1, 2, 105) in fired
    assert all(v != 3 for _, v, _ in fired)   # never merged


def test_tumbling_negative_timestamps_floor_correctly():
    op = EventTimeTumblingWindowOperator(num_keys=4, window_size=10,
                                         out_of_orderness=0)
    state, fired = _run_steps(op, [
        [(1, 7, -10)],                   # window [-10, 0), id -1
        [(2, 1, 50)],                    # wm=50 closes it
    ])
    assert (1, 7, 0) in fired


def test_session_zero_sum_session_closes_and_key_recovers():
    """A session whose values sum to zero must still close on watermark
    passage (no emission) and free the key for later sessions."""
    op = SessionWindowOperator(num_keys=4, gap=10, out_of_orderness=0)
    state, fired = _run_steps(op, [
        [(1, 0, 0)],                     # zero-valued session
        [(2, 1, 100)],                   # wm=100 closes it silently
        [(1, 5, 200)],                   # key 1 must accept a new session
        [(2, 1, 300)],                   # closes it
    ])
    assert (1, 5, 210) in fired
    assert int(state["late"][0]) == 0
