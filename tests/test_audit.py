"""Epoch audit ledger: per-epoch digests, replay divergence detection,
and live exactly-once health (clonos_tpu/obs/audit.py + digest.py).

The framework's recovery tests prove replay lands bit-identically for
DETERMINISTIC jobs; the audit plane is the runtime check that it
actually did, every time. The headline test here is the converse of
every other recovery test: a job with an *injected unlogged
nondeterminism* (examples/audit_nondet.py — a value salt drawn outside
the causal log) survives a SIGKILL recovery against every structural
invariant and is caught ONLY by the audit validator, which names the
first diverging epoch and channel in a ``recovery.audit.divergence``
instant under the recovery's trace id.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from clonos_tpu import obs
from clonos_tpu.obs.digest import EpochDigest, diff, diff_ledgers
from clonos_tpu.parallel import transport as tp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _null_obs_after():
    """Every test leaves the process-global tracer AND auditor off."""
    yield
    obs.reset()
    obs.reset_audit()


# --- digest unit tests -------------------------------------------------------


def test_digest_fold_interleaving_and_merge_associativity():
    """The epoch fingerprint is invariant to channel interleaving and to
    how partial digests over disjoint channel sets are merged — but
    sensitive to fold ORDER within one channel (the chain is ordered)."""
    chunks = {"log/0": [b"d0", b"d1"], "ring/v2": [b"r0"],
              "ring/v3": [b"r1", b"r2", b"r3"]}

    def folded(order):
        dg = EpochDigest(7)
        for chan in order:
            for c in chunks[chan]:
                dg.fold(chan, c)
        return dg

    a = folded(["log/0", "ring/v2", "ring/v3"])
    b = folded(["ring/v3", "log/0", "ring/v2"])
    # Different channel interleavings: equal digests, equal fingerprints.
    assert a == b and a.combined() == b.combined()
    assert diff(a, b) is None

    # Within-channel order matters: swapping two chunks diverges.
    c = EpochDigest(7)
    c.fold("log/0", b"d1")
    c.fold("log/0", b"d0")
    for ch in ("ring/v2", "ring/v3"):
        for x in chunks[ch]:
            c.fold(ch, x)
    assert c.combined() != a.combined()
    chan, reason = diff(a, c)
    assert chan == "log/0" and "fingerprint" in reason

    # Merge associativity over disjoint channel splits.
    def part(*chans):
        dg = EpochDigest(7)
        for ch in chans:
            for x in chunks[ch]:
                dg.fold(ch, x)
        return dg

    p1, p2, p3 = part("log/0"), part("ring/v2"), part("ring/v3")
    left = p1.merge(p2).merge(p3)
    right = p1.merge(p2.merge(p3))
    assert left == right == a
    assert left.combined() == a.combined()
    # Overlapping channels and mismatched epochs are caller bugs.
    with pytest.raises(ValueError, match="sharing channels"):
        p1.merge(part("log/0"))
    with pytest.raises(ValueError, match="epochs"):
        p1.merge(EpochDigest(8))

    # det counts merge by summation and diff as the "det_counts" channel.
    p1.count_det("rng", 3)
    p2.count_det("rng", 1)
    merged = p1.merge(p2)
    assert merged.det_counts == {"rng": 4}
    same_chans = part("log/0", "ring/v2", "ring/v3")
    same_chans.count_det("rng", 5)
    other = part("log/0", "ring/v2", "ring/v3")
    other.count_det("rng", 4)
    chan, reason = diff(same_chans, other)
    assert chan == "det_counts"


def test_digest_entry_roundtrip_and_ledger_diff():
    dg = EpochDigest(3)
    dg.fold("log/0", b"abc", 5)
    dg.fold("ring/v1", b"xyz", 2)
    dg.count_det("timestamp", 4)
    entry = dg.to_entry()
    # JSON-able and lossless.
    back = EpochDigest.from_entry(json.loads(json.dumps(entry)))
    assert back == dg and back.to_entry() == entry
    assert entry["records"] == 7 and entry["epoch"] == 3
    assert entry["channels"]["log/0"]["count"] == 5

    # diff names the first diverging channel in sorted order.
    short = EpochDigest(3)
    short.fold("log/0", b"abc", 4)
    short.fold("ring/v1", b"xyz", 2)
    chan, reason = diff(dg, short)
    assert chan == "log/0" and "count" in reason
    missing = EpochDigest(3)
    missing.fold("ring/v1", b"xyz", 2)
    assert diff(dg, missing)[0] == "log/0"
    assert diff(missing, dg)[1].startswith("unexpected")

    # Ledger-level diff: per-epoch first divergences + missing epochs.
    lines = diff_ledgers([entry, EpochDigest(4).to_entry()],
                         [short.to_entry()])
    assert any("epoch 3" in ln and "log/0" in ln for ln in lines)
    assert any("epoch 4" in ln and "missing" in ln for ln in lines)
    assert diff_ledgers([entry], [entry]) == []


def test_null_auditor_default_no_wire_fields():
    """Audit off (the default): NullAuditor, no wire fields, nothing
    recorded — the exact NullTracer contract."""
    a0 = obs.get_auditor()
    assert isinstance(a0, obs.NullAuditor) and not a0.enabled
    hdr = tp.attach_audit({"group": 1})
    assert hdr == {"group": 1}, "disabled auditor must add no wire fields"
    a0.seal(EpochDigest(0))
    assert a0.ledger() == [] and a0.last_epoch == -1

    # Opt-in: attach stamps the policy; a fresh process adopts it.
    obs.configure_audit(on_divergence="abort")
    hdr = tp.attach_audit({"group": 1})
    assert hdr["audit"] == {"on_divergence": "abort"}
    obs.reset_audit()
    assert not obs.get_auditor().enabled
    tp.adopt_audit(hdr)
    assert obs.get_auditor().enabled
    assert obs.get_auditor().on_divergence == "abort"
    obs.reset_audit()
    tp.adopt_audit({"group": 1})            # no audit field: stays off
    assert not obs.get_auditor().enabled
    with pytest.raises(ValueError, match="on_divergence"):
        obs.configure_audit(on_divergence="explode")


# --- in-process: seal at the fence, validate on recovery ---------------------


def _small_job(name):
    from clonos_tpu.api.environment import StreamEnvironment
    env = StreamEnvironment(name=name, num_key_groups=8)
    (env.synthetic_source(vocab=11, batch_size=4, parallelism=2)
        .key_by()
        .window_count(num_keys=11, window_size=1 << 30)
        .sink())
    return env.build()


def test_recovery_validates_replayed_epochs_against_ledger(tmp_path):
    """Acceptance (match path): every replayed epoch gets a
    ``recovery.audit.match`` instant under the recovery's trace id, the
    ledger persists next to the checkpoints, and the health gauges are
    live in the registry."""
    from clonos_tpu.runtime.cluster import ClusterRunner

    tr = obs.configure("runner")
    r = ClusterRunner(_small_job("aud"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"), audit=True)
    assert r.auditor.enabled
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)
    r.run_epoch(complete_checkpoint=False)

    # One durable ledger entry per sealed epoch, readable back.
    lp = tmp_path / "ck" / "ledger.jsonl"
    assert lp.exists()
    entries = r.coordinator.read_ledger()
    assert [e["epoch"] for e in entries] == [0, 1, 2, 3]
    assert all(e["records"] > 0 and e["combined"] for e in entries)
    assert r.auditor.epochs_sealed == 4 and r.auditor.last_epoch == 3

    r.inject_failure([2 + 1])
    report = r.recover()
    assert report.from_epoch == 2
    assert "audit" in report.phase_ms

    recs = tr.records()
    matches = [x for x in recs if x["name"] == "recovery.audit.match"]
    assert [x["args"]["epoch"] for x in matches] == [2, 3], \
        "one match instant per replayed epoch"
    assert all(x["args"]["records"] > 0 for x in matches)
    recovery = next(x for x in recs if x["name"] == "recovery")
    assert {x["trace"] for x in matches} == {recovery["trace"]}, \
        "audit instants join the recovery trace id"
    assert not any(x["name"] == "recovery.audit.divergence" for x in recs)

    snap = r.metrics.snapshot()
    assert snap["job.aud.audit.enabled"] == 1
    assert snap["job.aud.audit.epochs-sealed"] == 4
    assert snap["job.aud.audit.epochs-validated"] == 2
    assert snap["job.aud.audit.divergences"] == 0
    assert snap["job.aud.audit.last-sealed-epoch"] == 3
    assert 0.0 <= snap["job.aud.backpressure.inflight-occupancy"] <= 1.0
    assert snap["job.aud.recovery.replay-lag-steps"] >= 0


def test_audit_disabled_by_default_writes_no_ledger(tmp_path):
    from clonos_tpu.runtime.cluster import ClusterRunner

    r = ClusterRunner(_small_job("noaud"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"))
    assert not r.auditor.enabled
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=True)
    assert not (tmp_path / "ck" / "ledger.jsonl").exists()
    assert r.coordinator.read_ledger() == []
    snap = r.metrics.snapshot()
    assert snap["job.noaud.audit.enabled"] == 0
    assert snap["job.noaud.audit.epochs-sealed"] == 0


def test_tampered_ledger_divergence_warn_and_abort(tmp_path):
    """A ledger that does not match the replay: warn counts and records
    the instant; abort raises AuditDivergenceError naming epoch and
    channel. Driven by tampering a sealed entry, the cheap determinated
    stand-in for real nondeterminism (the SIGKILL test injects the real
    thing)."""
    from clonos_tpu.causal.recovery import (AuditDivergenceError,
                                            AuditValidator)
    from clonos_tpu.runtime.cluster import ClusterRunner

    tr = obs.configure("runner")
    r = ClusterRunner(_small_job("tamper"), steps_per_epoch=8,
                      log_capacity=512, max_epochs=8,
                      inflight_ring_steps=32, seed=3,
                      checkpoint_dir=str(tmp_path / "ck"), audit=True)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=True)
    r.run_epoch(complete_checkpoint=False)

    entries = r.coordinator.read_ledger()
    bad = json.loads(json.dumps(entries[-1]))          # epoch 2
    first_chan = sorted(bad["channels"])[0]
    bad["channels"][first_chan]["fp"] = "00" * 8

    v = AuditValidator(r.executor, [bad], on_divergence="warn")
    stats = v.validate([2])
    assert stats == {"match": 0, "divergence": 1, "missing": 0}
    ev = next(x for x in tr.records()
              if x["name"] == "recovery.audit.divergence")
    assert ev["args"]["epoch"] == 2
    assert ev["args"]["channel"] == first_chan
    assert "fingerprint" in ev["args"]["reason"]

    va = AuditValidator(r.executor, [bad], on_divergence="abort")
    with pytest.raises(AuditDivergenceError, match=first_chan.replace(
            "/", "/")):
        va.validate([2])
    assert va.stats["divergence"] == 1

    # Epochs absent from the ledger count as missing, not divergence.
    vm = AuditValidator(r.executor, [], on_divergence="abort")
    assert vm.validate([1]) == {"match": 0, "divergence": 0, "missing": 1}


# --- torn-tail tolerance (SIGKILL artifacts) ---------------------------------


def test_torn_final_lines_tolerated_everywhere(tmp_path):
    """A SIGKILLed process tears its final JSONL line; both the trace
    loader and the ledger reader drop the tail and keep everything
    before it. Corruption ANYWHERE ELSE still raises."""
    from clonos_tpu.runtime.checkpoint import read_ledger_file

    torn = tmp_path / "trace-x.jsonl"
    torn.write_text('{"name": "a", "ts": 1.0}\n'
                    '{"name": "b", "ts": 2.0}\n'
                    '{"name": "c", "ts": 3.')
    recs = obs.load_jsonl(str(torn))
    assert [r["name"] for r in recs] == ["a", "b"]

    led = tmp_path / "ledger.jsonl"
    led.write_text('{"epoch": 0, "combined": "aa"}\n'
                   '{"epoch": 1, "com')
    assert [e["epoch"] for e in read_ledger_file(str(led))] == [0]
    assert read_ledger_file(str(tmp_path / "absent.jsonl")) == []

    broken = tmp_path / "trace-y.jsonl"
    broken.write_text('{"name": "a", "ts": 1.0}\n'
                      'NOT JSON\n'
                      '{"name": "c", "ts": 3.0}\n')
    with pytest.raises(ValueError, match="trace-y.jsonl:2"):
        obs.load_jsonl(str(broken))
    bled = tmp_path / "ledger2.jsonl"
    bled.write_text('NOT JSON\n{"epoch": 1}\n')
    with pytest.raises(json.JSONDecodeError):
        read_ledger_file(str(bled))


# --- prometheus exposition hygiene -------------------------------------------


def test_prometheus_exposition_hygiene():
    from clonos_tpu.utils import metrics as met

    reg = met.MetricRegistry()
    g = reg.group("job.x")
    g.counter("audit.epochs-sealed").inc(4)
    g.gauge("audit.enabled", lambda: True)
    g.histogram("epoch.steps-ms").update(2.0)
    snap = reg.snapshot()
    snap["worker.a.status"] = 'up "and\\running"\nok'
    snap["9lives"] = 1
    txt = reg.prometheus_text(snap)
    lines = txt.splitlines()

    # Flattened sample lines keep the historical shape...
    assert "job_x_audit_epochs_sealed 4" in lines
    assert "job_x_audit_enabled 1" in lines, "bools render as 0/1"
    assert any(ln.startswith("job_x_epoch_steps_ms_p99 ") for ln in lines)
    # ...now under HELP/TYPE headers with registry-derived types.
    assert "# TYPE job_x_audit_epochs_sealed counter" in lines
    assert "# TYPE job_x_audit_enabled gauge" in lines
    assert "# TYPE job_x_epoch_steps_ms summary" in lines
    assert "# HELP job_x_audit_epochs_sealed source metric " \
           "job.x.audit.epochs-sealed" in lines
    # Leading digits are guarded; every sample name is exposition-legal.
    assert "_9lives 1" in lines
    import re
    name_re = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
    for ln in lines:
        if ln and not ln.startswith("#"):
            assert name_re.match(ln), ln
    # String values render as labeled info samples, fully escaped,
    # instead of being dropped.
    esc = next(ln for ln in lines if ln.startswith("worker_a_status"))
    assert esc == ('worker_a_status{value="up \\"and\\\\running\\"\\nok"} 1')


def test_cluster_metrics_rolls_up_audit_health():
    """The JobMaster's cluster view appends a ``cluster.audit.*`` rollup
    (the exactly-once health line) iff any worker reports audit gauges."""
    from clonos_tpu.runtime.remote import JobMasterServer, TaskExecutorClient

    jm = JobMasterServer(heartbeat_timeout_s=30.0)
    c = None
    try:
        assert "cluster.audit.exactly-once-ok" not in jm.cluster_metrics()
        c = TaskExecutorClient(
            "a", jm.address, interval_s=0.05,
            payload_fn=lambda: {"metrics": {
                "group.1.audit.epochs-sealed": 6,
                "group.1.audit.epochs-validated": 2,
                "group.1.audit.divergences": 1,
                "group.1.supersteps": 12}})
        deadline = time.monotonic() + 20
        while "cluster.audit.exactly-once-ok" not in jm.cluster_metrics():
            assert time.monotonic() < deadline, "rollup never appeared"
            time.sleep(0.02)
        cm = jm.cluster_metrics()
        assert cm["cluster.audit.epochs-sealed"] == 6
        assert cm["cluster.audit.epochs-validated"] == 2
        assert cm["cluster.audit.divergences"] == 1
        assert cm["cluster.audit.exactly-once-ok"] == 0
    finally:
        if c is not None:
            c.close()
        jm.close()


# --- the audit CLI -----------------------------------------------------------


def test_audit_cli_prints_and_diffs_ledgers(tmp_path, capsys):
    from clonos_tpu.cli import main

    def write_ledger(dirpath, entries):
        os.makedirs(dirpath, exist_ok=True)
        with open(os.path.join(dirpath, "ledger.jsonl"), "w") as f:
            for e in entries:
                f.write(json.dumps(e) + "\n")

    def entry(epoch, payload):
        d = EpochDigest(epoch)
        d.fold("ring/v2", payload, 4)
        d.count_det("rng", 2)
        return d.to_entry()

    run1 = tmp_path / "run1"
    run2 = tmp_path / "run2"
    write_ledger(str(run1 / "g0"), [entry(0, b"aa"), entry(1, b"bb")])
    write_ledger(str(run1 / "g1"), [entry(0, b"cc")])
    write_ledger(str(run2 / "g0"), [entry(0, b"aa"), entry(1, b"XX")])
    write_ledger(str(run2 / "g1"), [entry(0, b"cc")])

    assert main(["audit", str(run1)]) == 0
    out = capsys.readouterr().out
    assert "g0/ledger.jsonl" in out and "g1/ledger.jsonl" in out
    assert "epoch    0" in out and "rng=2" in out

    # Identical ledgers: exit 0; diverging: exit 1 naming epoch+channel.
    assert main(["audit", str(run1), "--diff", str(run1)]) == 0
    assert "ledgers match" in capsys.readouterr().out
    assert main(["audit", str(run1), "--diff", str(run2)]) == 1
    out = capsys.readouterr().out
    assert "epoch 1" in out and "ring/v2" in out and "g0" in out
    assert "epoch 0" not in out

    assert main(["audit", str(run1), "--json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["g0/ledger.jsonl"][0]["epoch"] == 0

    assert main(["audit", str(tmp_path / "empty")]) == 1


def test_audit_cli_diff_across_partition_layouts(tmp_path, capsys):
    """`clonos_tpu audit A --diff B` across two DIFFERENTLY-partitioned
    runs of one job: epochs stamped with different layouts compare
    through the group-directory mapping on the partition-invariant
    channels (ring counts + ringsum content), so a clean re-cut diffs
    empty where the exact byte diff would refuse."""
    from clonos_tpu.cli import main

    def write_ledger(dirpath, entries):
        os.makedirs(dirpath, exist_ok=True)
        with open(os.path.join(dirpath, "ledger.jsonl"), "w") as f:
            for e in entries:
                f.write(json.dumps(e) + "\n")

    SUM = (123456789).to_bytes(8, "little")

    def entry(epoch, layout, ring_chunks, ringsum=SUM):
        # lanes differ per cut, so chunking (and ring/ fp) differ; the
        # record multiset — count and content sum — must not
        d = EpochDigest(epoch, layout=layout)
        n_lanes = dict(layout)[1]
        for flat in range(sum(p for _, p in layout)):
            d.fold(f"log/{flat}", b"rows-%d-%d" % (flat, n_lanes), 1)
        total = 0
        for chunk, n in ring_chunks:
            d.fold("ring/v1", chunk, n)
            total += n
        d.fold("ringsum/v1", ringsum, total)
        return d.to_entry()

    two = ((0, 1), (1, 2))
    four = ((0, 1), (1, 4))
    a = tmp_path / "a"
    b = tmp_path / "b"
    c = tmp_path / "c"
    write_ledger(str(a / "g0"),
                 [entry(0, two, [(b"aa", 2), (b"bb", 2)])])
    write_ledger(str(b / "g0"),
                 [entry(0, four, [(b"x", 1)] * 4)])
    write_ledger(str(c / "g0"),
                 [entry(0, four, [(b"x", 1)] * 4,
                        ringsum=(99).to_bytes(8, "little"))])

    # the exact byte diff refuses across cuts...
    ea = [json.loads(line) for line in
          (a / "g0" / "ledger.jsonl").read_text().splitlines()]
    eb = [json.loads(line) for line in
          (b / "g0" / "ledger.jsonl").read_text().splitlines()]
    assert diff_ledgers(ea, eb)

    # ...but the CLI's mapped diff sees one job, cut two ways
    assert main(["audit", str(a), "--diff", str(b)]) == 0
    assert "ledgers match" in capsys.readouterr().out

    # a record lost AND another duplicated (count matches, content
    # moved) is still named, epoch + channel
    assert main(["audit", str(a), "--diff", str(c)]) == 1
    out = capsys.readouterr().out
    assert "epoch 0" in out and "ringsum/v1" in out and "content sum" in out


def test_marker_lint_passes_and_flags_unregistered(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_markers
    finally:
        sys.path.pop(0)
    assert check_markers.check(os.path.join(REPO, "tests")) == []
    bad = tmp_path / "test_bad.py"
    # the typo'd marker is assembled at runtime so THIS file (which the
    # lint also scans) doesn't trip it
    bad.write_text("import pytest\n"
                   "@pytest.mark.%s\ndef test_x():\n    pass\n" % "sloow")
    violations = check_markers.check(str(tmp_path))
    assert len(violations) == 1 and "sloow" in violations[0]


# --- THE acceptance run: injected nondeterminism caught over SIGKILL ---------


def _line_server(lines):
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(2)

    def serve():
        try:
            while True:
                conn, _ = srv.accept()
                conn.sendall("".join(f"{k}:{v}\n"
                                     for k, v in lines).encode())
        except OSError:
            return

    threading.Thread(target=serve, daemon=True).start()
    return srv, srv.getsockname()[1]


def _read_status(proc, want, deadline_s=300.0):
    deadline = time.monotonic() + deadline_s
    for line in iter(proc.stdout.readline, ""):
        assert time.monotonic() < deadline, "worker status timeout"
        st = json.loads(line)
        if want(st):
            return st
    raise AssertionError("worker stdout closed before expected status")


def test_sigkill_replay_divergence_detected_across_processes(tmp_path):
    """Acceptance: the slot-pool SIGKILL run over
    examples/audit_nondet.py — a job whose ``salt`` map perturbs record
    values with an unlogged per-process random constant. The kill lands
    on the worker running ``[salt, window, sink]``; the rebuild on the
    surviving worker replays under a DIFFERENT salt, reproducing every
    key, count, determinant row and window total — so recovery's
    structural checks all pass and the run completes. Only the audit
    validator can see it: the replayed ring contents differ, and every
    replayed epoch must produce a ``recovery.audit.divergence`` naming
    the epoch and a ``ring/*`` channel, under the recovery's trace id,
    with the divergence count surfacing in the JobMaster's cluster
    health rollup."""
    from clonos_tpu.runtime import scheduler as sch
    from clonos_tpu.runtime.leader import FileLeaderElection
    from clonos_tpu.runtime.remote import JobMasterServer

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    lease = str(tmp_path / "jm.lease")
    lines = [((i * 37) % 997, 1 + i % 5) for i in range(600)]
    srv, lport = _line_server(lines)

    jm_tracer = obs.configure("jm", path=str(trace_dir / "trace-jm.jsonl"))
    obs.configure_audit(on_divergence="warn")
    jm = JobMasterServer(heartbeat_timeout_s=2.0)
    election = FileLeaderElection(lease, "jm-0", lease_ttl_s=30.0)
    assert election.try_acquire()
    runner_kw = dict(steps_per_epoch=4, log_capacity=512, max_epochs=64,
                     inflight_ring_steps=64, seed=7, logical_time=True,
                     audit=True)
    scheduler = sch.SlotPoolScheduler(
        jm, election, "examples.audit_nondet:build_job",
        runner_kw=runner_kw, feed_batch=4, target_epochs=8,
        complete_every=4, checkpoint_root=str(tmp_path / "ck"),
        deploy_timeout_s=300.0)

    def spawn(eid):
        return subprocess.Popen(
            [sys.executable, "-m", "clonos_tpu", "slotworker",
             "--jm", f"127.0.0.1:{jm.address[1]}",
             "--executor-id", eid, "--slots", "2", "--lease", lease,
             "--heartbeat-interval", "0.3", "--max-seconds", "600",
             "--epoch-sleep", "0.25", "--trace-dir", str(trace_dir)],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)

    pa, pb = spawn("a"), spawn("b")
    try:
        assert json.loads(pa.stdout.readline())["registered"] == "a"
        assert json.loads(pb.stdout.readline())["registered"] == "b"
        deadline = time.monotonic() + 30
        while {"a", "b"} - set(jm.registered()):
            assert time.monotonic() < deadline
            time.sleep(0.05)

        placements = scheduler.deploy(external_feeds={
            0: {"kind": "socket", "host": "127.0.0.1", "port": lport,
                "num_subtasks": 1}})
        # The nondeterministic slice [salt, window, sink] is group 1.
        assert placements == {0: "a", 1: "b"}
        _read_status(pa, lambda st: st.get("deployed") == 0)
        _read_status(pb, lambda st: st.get("deployed") == 1)
        _read_status(pa, lambda st: st.get("finished") == 0)

        # Kill timing is what makes the replay window NON-EMPTY: with
        # completions only at epochs 0 and 4 (complete_every=4), any
        # kill after epoch 5 closes (epoch_id >= 6, mirror fence >= 6,
        # restore point chk_4) replays at least epoch 5 — killing right
        # after a completed checkpoint would replay nothing and give the
        # validator an empty range.
        def at_fence(st):
            if "group" in st and "digest" in st:
                scheduler.sync()
            return st.get("epoch", -1) >= 6 or "finished" in st

        _read_status(pb, at_fence)
        pb.send_signal(signal.SIGKILL)
        pb.wait(timeout=15)

        deadline = time.monotonic() + 20
        while "b" not in scheduler.failed_workers():
            assert time.monotonic() < deadline, "heartbeat expiry not seen"
            time.sleep(0.1)

        # Recovery SUCCEEDS under warn: the job is structurally sound.
        assert scheduler.recover_worker("b") == {1: "a"}
        dep = _read_status(pa, lambda st: st.get("deployed") == 1)
        assert dep["recovered"] and dep["vertices"] == [2, 3, 4]

        # The divergence count reaches the JobMaster's cluster rollup
        # over HEARTBEAT: the live exactly-once health line trips.
        deadline = time.monotonic() + 60
        while jm.cluster_metrics().get("cluster.audit.divergences", 0) < 1:
            assert time.monotonic() < deadline, \
                f"no divergence in rollup: {sorted(jm.cluster_metrics())}"
            time.sleep(0.2)
        cm = jm.cluster_metrics()
        assert cm["cluster.audit.exactly-once-ok"] == 0
        assert cm["cluster.audit.epochs-sealed"] >= 1

        # ...and the job still runs to its target (warn, not abort).
        fin = _read_status(pa, lambda st: st.get("finished") == 1)
        assert fin["global_step"] == 8 * runner_kw["steps_per_epoch"]
    finally:
        for p in (pa, pb):
            if p.poll() is None:
                p.kill()
        scheduler.close()
        jm.close()
        srv.close()
        obs.reset()

    # --- the audit evidence, reconstructed from the trace files --------------
    T = jm_tracer.trace_id
    paths = [str(trace_dir / f"trace-{s}.jsonl") for s in ("jm", "a", "b")]
    records = obs.load_jsonl([p for p in paths if os.path.exists(p)])
    ours = [r for r in records if r["trace"] == T]

    divs = [r for r in ours if r["name"] == "recovery.audit.divergence"]
    assert divs, ("no recovery.audit.divergence in trace: "
                  f"{sorted({r['name'] for r in ours})}")
    # Emitted by the surviving worker's rebuild, under the SAME trace id
    # as the recovery spans.
    assert {r["service"] for r in divs} == {"a"}
    recovery = next(r for r in ours
                    if r["name"] == "recovery" and r["service"] == "a")
    assert {r["trace"] for r in divs} == {recovery["trace"]}
    # The first divergence names the first replayed epoch and a ring
    # channel (the salted VALUES): determinant logs reproduced fine.
    first = min(divs, key=lambda r: r["args"]["epoch"])
    assert first["args"]["channel"].startswith("ring/")
    assert "content divergence" in first["args"]["reason"]
    replayed = sorted(r["args"]["epoch"] for r in divs)
    assert replayed[0] == min(replayed)
    # Every pre-kill epoch was sealed by the dead worker: entries exist
    # in the group's durable ledger for everything the validator saw.
    from clonos_tpu.runtime.checkpoint import read_ledger_file
    entries = read_ledger_file(str(tmp_path / "ck" / "g1" /
                                   "ledger.jsonl"))
    sealed = {e["epoch"] for e in entries}
    assert set(replayed) <= sealed
