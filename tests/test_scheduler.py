"""Slot-pool scheduler: one job spanning multiple worker processes, with
fenced per-task recovery (runtime/scheduler.py; reference
jobmaster/slotpool/SlotPool.java offers/allocation,
TaskExecutorGateway.submitTask + TaskDeploymentDescriptor, the JobMaster
fencing token on every RPC, and RunStandbyTaskStrategy placement).

The headline test drives a REAL spanned job: two slot-worker OS
processes each run only their slice of the graph (records cross between
them over the edge-export wire, the upstream slice fed by a
SocketFeedReader), one worker is SIGKILLed, and the scheduler redeploys
ONLY its task group onto the survivor — causal replay bit-identical to
the dead worker's last mirrored fence AND to a no-failure control run
over the same record stream; a stale fencing token's DEPLOY is rejected.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from clonos_tpu.graph.job_graph import PartitionType
from clonos_tpu.parallel import transport as tp
from clonos_tpu.parallel.distributed import standby_worker_order
from clonos_tpu.runtime import scheduler as sch
from clonos_tpu.runtime.leader import FileLeaderElection
from clonos_tpu.runtime.remote import JobMasterServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spanning_job():
    import examples.spanning as sp
    return sp.build_job()


def _wordcount_job():
    import examples.wordcount as wc
    return wc.build_job()


# --- placement ---------------------------------------------------------------


def test_partition_vertices_cuts_on_exchange_edges():
    job = _spanning_job()          # lines -> tag -> (HASH) window -> sink
    parts = sch.partition_vertices(job, 2)
    assert parts == [[0, 1], [2, 3]]
    # Every crossing edge of every cut is an exchange edge.
    for part in parts:
        ins, outs = sch.cut_edges(job, part)
        for eidx in ins + outs:
            assert job.edges[eidx].partition != PartitionType.FORWARD
    assert sch.partition_vertices(job, 1) == [[0, 1, 2, 3]]

    wc = _wordcount_job()          # source -(HASH)- window -(FWD)- sink
    assert sch.partition_vertices(wc, 2) == [[0], [1, 2]]
    with pytest.raises(ValueError, match="cut points"):
        sch.partition_vertices(wc, 3)    # only one exchange cut exists
    with pytest.raises(ValueError, match="cannot cut"):
        sch.partition_vertices(wc, 0)
    with pytest.raises(ValueError, match="cannot cut"):
        sch.partition_vertices(wc, 9)


def test_subgraph_boundaries_feeds_exports_and_forward_rejection():
    job = _spanning_job()
    sub0, vmap0, feeds0, exports0 = job.subgraph([0, 1], feed_batch_size=4)
    assert vmap0 == {0: 0, 1: 1} and feeds0 == {}
    assert exports0 == {1: 1}            # cut out-edge 1 served by "tag"
    assert [v.name for v in sub0.vertices] == ["lines", "tag", "export-1"]
    # The export consumer rides a FORWARD edge (keeps tag's ring local).
    assert sub0.edges[-1].partition == PartitionType.FORWARD

    sub1, vmap1, feeds1, exports1 = job.subgraph([2, 3], feed_batch_size=4)
    assert vmap1 == {2: 0, 3: 1} and exports1 == {}
    assert feeds1 == {1: 2}              # cut in-edge 1 -> boundary feed
    fv = sub1.vertices[feeds1[1]]
    assert fv.name == "feed-in-1" and fv.parallelism == 1
    assert fv.operator.batch_size == 4
    # The boundary feed drives window through the ORIGINAL HASH exchange.
    (feed_edge,) = [e for e in sub1.edges if e.src == feeds1[1]]
    assert feed_edge.partition == PartitionType.HASH
    assert feed_edge.dst == vmap1[2]

    # A cut across a FORWARD edge cannot be served by the flattened wire
    # export — rejected loudly (window -> sink in wordcount is FORWARD).
    with pytest.raises(ValueError, match="FORWARD"):
        _wordcount_job().subgraph([2])


def test_slot_pool_allocation_standby_and_worker_loss():
    pool = sch.SlotPool()
    pool.sync_offers({"a": 2, "b": 1})
    assert pool.workers() == ["a", "b"]
    assert len(pool.free_slots()) == 3

    s0 = pool.allocate(0, prefer="a")
    s1 = pool.allocate(1, prefer="b")
    assert (s0.worker_id, s1.worker_id) == ("a", "b")
    assert pool.placements() == {0: "a", 1: "b"}
    # Anti-affinity: avoid excludes a worker even when preferred.
    s2 = pool.allocate(2, prefer="b", avoid=("b",))
    assert s2.worker_id == "a"
    with pytest.raises(RuntimeError, match="no free slot"):
        pool.allocate(3, avoid=("a", "b"))

    # Worker death strands its groups for redeployment.
    assert pool.drop_worker("b") == [1]
    assert pool.workers() == ["a"]
    pool.release_group(2)
    assert pool.allocate(1).worker_id == "a"

    # Rotate-by-one standby order: a group's standby never shares its
    # primary's process.
    assert list(standby_worker_order(3)) == [1, 2, 0]
    assert list(standby_worker_order(1)) == [0]
    with pytest.raises(ValueError):
        standby_worker_order(0)


# --- fenced deployment gateway ----------------------------------------------


def _deploy_frame(tdd, frame=b""):
    hdr = tp.pack_json(tdd)
    return len(hdr).to_bytes(4, "little") + hdr + frame


def test_endpoint_rejects_stale_and_forged_fencing_tokens(tmp_path):
    lease = str(tmp_path / "jm.lease")
    t = [0.0]
    a = FileLeaderElection(lease, "jm-a", lease_ttl_s=2.0,
                           clock=lambda: t[0])
    b = FileLeaderElection(lease, "jm-b", lease_ttl_s=2.0,
                           clock=lambda: t[0])
    assert a.try_acquire() and a.epoch == 1
    t[0] = 3.5                            # jm-a's lease lapses
    assert b.try_acquire() and b.epoch == 2

    ep = sch.TaskExecutorEndpoint(lease_path=lease)
    cl = tp.ControlClient(ep.address)
    try:
        # No token at all -> rejected.
        rt, resp = cl.call(tp.DEPLOY, _deploy_frame({"group": 0}))
        assert rt == tp.ERROR and "no fencing" in tp.unpack_json(resp)["error"]
        # The deposed leader's token (below the highest claim) -> rejected.
        rt, resp = cl.call(tp.DEPLOY,
                           _deploy_frame({"group": 0, "fencing_epoch": 1}))
        assert rt == tp.ERROR
        assert "lease claim" in tp.unpack_json(resp)["error"]
        # A forged token above every real claim -> rejected.
        rt, resp = cl.call(tp.DEPLOY,
                           _deploy_frame({"group": 0, "fencing_epoch": 9}))
        assert rt == tp.ERROR
        # The live leader's token -> accepted and queued.
        rt, resp = cl.call(tp.DEPLOY,
                           _deploy_frame({"group": 7, "fencing_epoch": 2}))
        assert rt == tp.OK and tp.unpack_json(resp)["accepted"]
        assert ep.queue.get_nowait()["group"] == 7
        assert ep.queue.empty()
    finally:
        cl.close()
        ep.close()

    # Without a lease dir the gate still enforces monotone tokens: once
    # an epoch was accepted, anything below it is a deposed JobMaster.
    ep2 = sch.TaskExecutorEndpoint()
    cl2 = tp.ControlClient(ep2.address)
    try:
        rt, _ = cl2.call(tp.DEPLOY,
                         _deploy_frame({"group": 0, "fencing_epoch": 5}))
        assert rt == tp.OK
        rt, resp = cl2.call(tp.DEPLOY,
                            _deploy_frame({"group": 0, "fencing_epoch": 4}))
        assert rt == tp.ERROR
        assert "stale fencing" in tp.unpack_json(resp)["error"]
    finally:
        cl2.close()
        ep2.close()


# --- ring-less bootstrap fence (satellite) -----------------------------------


def test_bootstrap_standby_derives_ringless_fence_from_cadence(tmp_path):
    """An edge-less job's lean snapshot carries no ring heads, but
    checkpoint cadence pins the fence anyway: checkpoint id e seals
    epochs 0..e, so its fence is exactly (e + 1) * steps_per_epoch. The
    rebuild must derive that — never silently fence at step 0 (which
    would replay from the wrong offsets)."""
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    env = StreamEnvironment(name="ringless", num_key_groups=8)
    env.synthetic_source(vocab=7, batch_size=4, parallelism=1)
    job = env.build()
    ck = str(tmp_path / "ck")
    r = ClusterRunner(job, steps_per_epoch=4, checkpoint_dir=ck,
                      log_capacity=256, max_epochs=8, seed=2)
    for _ in range(3):
        r.run_epoch(complete_checkpoint=True)
    logs = r.executor.carry.logs
    head = int(np.asarray(logs.head)[0])
    tail = int(np.asarray(logs.tail)[0])
    cap = np.asarray(logs.rows).shape[1]
    pos = np.arange(tail, head) & (cap - 1)
    mirror_rows = {0: (np.asarray(logs.rows)[0][pos], tail)}
    rebuilt, report = ClusterRunner.bootstrap_standby(
        job, ck, mirror_rows, steps_per_epoch=4, log_capacity=256,
        max_epochs=8, seed=2)
    # 3 completed checkpoints (ids 0..2) -> fence at step (2+1)*4 = 12;
    # everything at/below the fence rode the checkpoint, nothing replays.
    assert rebuilt.global_step == 12 + report.steps_replayed
    assert rebuilt.executor.epoch_id == 3 + report.steps_replayed // 4


# --- cross-worker edge wire, in-process --------------------------------------


def _epochs(runner, n, complete_every=2):
    out = {}
    for _ in range(n):
        closed = runner.executor.epoch_id
        runner.run_epoch(complete_checkpoint=(closed % complete_every == 0))
        out[runner.global_step] = runner.state_digest()
    return out


def test_edge_export_wire_is_deterministic_and_rewindable():
    """The downstream half of a cut edge consumed over the WIRE
    (EdgeExportServer -> RemoteEdgeFeedReader, blocking exact-count
    pulls) produces bit-identical digests to the same slice fed the same
    records from memory — per-step batch boundaries must not depend on
    transport timing. Also pins read_at (the replay path) and the
    loud-failure contract past a finished stream."""
    from clonos_tpu.api.feeds import ListFeedReader
    from clonos_tpu.runtime.cluster import ClusterRunner

    job = _spanning_job()
    # logical_time: wall-clock TIMESTAMP determinants are the one step
    # input two independent runs never share — with the logical clock,
    # digests are a pure function of (job, seed, records).
    kw = dict(steps_per_epoch=4, log_capacity=512, max_epochs=16,
              inflight_ring_steps=32, seed=7, logical_time=True)
    lines = [((i * 37) % 997, 1 + i % 5) for i in range(128)]

    sub0, vmap0, _f, exports0 = job.subgraph([0, 1], feed_batch_size=8)
    up = ClusterRunner(sub0, **kw)
    up.executor.register_feed(vmap0[0], ListFeedReader([lines]))
    export = sch.EdgeExportServer(up, exports0)   # hooks the fence
    try:
        # 4 epochs drain the feed + 1 flush epoch: the source->tag hop is
        # one superstep deep, so the last batch reaches the export ring
        # only after the feed is already exhausted.
        _epochs(up, 5)
        export.mark_final()

        sub1, _v, feeds1, _e = job.subgraph([2, 3], feed_batch_size=8)
        down = ClusterRunner(sub1, **kw)
        reader = sch.RemoteEdgeFeedReader(export.address, edge=1)
        down.executor.register_feed(feeds1[1], reader)
        wire_digests = _epochs(down, 4)

        # read_at re-serves exact absolute ranges (causal replay path).
        k0, v0 = reader.read_at(0, 0, 16)
        k1, v1 = reader.read_at(0, 8, 8)
        assert k0[8:] == k1 and v0[8:] == v1
        # Reading past a FINISHED stream fails loudly, never hangs.
        with pytest.raises(RuntimeError, match="finished"):
            reader.read_at(0, 0, 10_000)

        # Control: the same slice over the same records from memory.
        cl = tp.ControlClient(export.address)
        rt, resp = cl.call(tp.FETCH_EDGE, tp.pack_json(
            {"edge": 1, "start": 0, "count": 1 << 20}))
        assert rt == tp.EDGE_DATA
        hlen = int.from_bytes(resp[:4], "little")
        hdr = tp.unpack_json(resp[4: 4 + hlen])
        assert hdr["final"] and hdr["count"] == hdr["avail"] == 128
        recs = np.frombuffer(resp[4 + hlen:], np.int32).reshape(-1, 2)
        cl.close()

        ctrl = ClusterRunner(sub1, **kw)
        ctrl.executor.register_feed(feeds1[1],
                                    ListFeedReader([recs.tolist()]))
        ctrl_digests = _epochs(ctrl, 4)
        assert wire_digests == ctrl_digests
        reader.close()
    finally:
        export.close()


# --- THE spanned job: 2 worker processes, SIGKILL, fenced recovery ----------


def _line_server(lines):
    """Minimal TCP line feed: accepts one client, sends every line
    immediately, keeps the connection open."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(2)
    conns = []

    def serve():
        try:
            while True:
                conn, _ = srv.accept()
                conns.append(conn)
                conn.sendall("".join(f"{k}:{v}\n"
                                     for k, v in lines).encode())
        except OSError:
            return

    threading.Thread(target=serve, daemon=True).start()
    return srv, srv.getsockname()[1], conns


def _read_status(proc, want, deadline_s=300.0):
    """Read JSON lines from a worker's stdout until ``want(st)``; returns
    (matching record, all digest-bearing group records seen)."""
    seen = {}
    deadline = time.monotonic() + deadline_s
    for line in iter(proc.stdout.readline, ""):
        assert time.monotonic() < deadline, "worker status timeout"
        st = json.loads(line)
        if "group" in st and "digest" in st:
            seen[st["global_step"]] = st["digest"]
        if want(st):
            return st, seen
    raise AssertionError("worker stdout closed before expected status")


def test_job_spans_two_workers_with_fenced_per_task_recovery(tmp_path):
    """Acceptance: vertices of ONE job deployed across 2 worker OS
    processes (neither holds the full graph); the downstream worker is
    SIGKILLed; the JobMaster redeploys only ITS vertices onto the
    survivor with causal replay; the post-recovery digests are
    bit-identical both to the dead worker's reported fences and to a
    no-failure control run over the same exported record stream; a
    deposed fencing token's DEPLOY is rejected. The upstream slice
    ingests through a SocketFeedReader (the cross-worker source)."""
    from clonos_tpu.api.feeds import ListFeedReader
    from clonos_tpu.runtime.cluster import ClusterRunner

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    lease = str(tmp_path / "jm.lease")
    lines = [((i * 37) % 997, 1 + i % 5) for i in range(600)]
    srv, lport, _conns = _line_server(lines)

    jm = JobMasterServer(heartbeat_timeout_s=2.0)
    election = FileLeaderElection(lease, "jm-0", lease_ttl_s=30.0)
    assert election.try_acquire()
    runner_kw = dict(steps_per_epoch=4, log_capacity=512, max_epochs=64,
                     inflight_ring_steps=64, seed=7, logical_time=True)
    # feed_batch 4 < the source batch 8: the downstream slice demands
    # records at half the rate the upstream can produce them, so early
    # partially-filled socket pulls can never starve the blocking
    # cross-worker reader at the end of the stream.
    scheduler = sch.SlotPoolScheduler(
        jm, election, "examples.spanning:build_job", runner_kw=runner_kw,
        feed_batch=4, target_epochs=8, complete_every=2,
        checkpoint_root=str(tmp_path / "ck"), deploy_timeout_s=300.0)

    def spawn(eid):
        return subprocess.Popen(
            [sys.executable, "-m", "clonos_tpu", "slotworker",
             "--jm", f"127.0.0.1:{jm.address[1]}",
             "--executor-id", eid, "--slots", "2", "--lease", lease,
             "--heartbeat-interval", "0.3", "--max-seconds", "600",
             "--epoch-sleep", "0.25"],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)

    pa, pb = spawn("a"), spawn("b")
    try:
        assert json.loads(pa.stdout.readline())["registered"] == "a"
        assert json.loads(pb.stdout.readline())["registered"] == "b"
        deadline = time.monotonic() + 30
        while {"a", "b"} - set(jm.registered()):
            assert time.monotonic() < deadline
            time.sleep(0.05)

        placements = scheduler.deploy(external_feeds={
            0: {"kind": "socket", "host": "127.0.0.1", "port": lport,
                "num_subtasks": 1}})
        assert placements == {0: "a", 1: "b"}
        assert scheduler.standby == {0: "b", 1: "a"}

        # Neither process holds the full job: each got only its slice.
        da, _ = _read_status(pa, lambda st: st.get("deployed") == 0)
        db, _ = _read_status(pb, lambda st: st.get("deployed") == 1)
        assert da["vertices"] == [0, 1] and db["vertices"] == [2, 3]
        assert not da["recovered"] and not db["recovered"]

        # Upstream drains the socket and finishes; its edge export stays
        # up (final), so the downstream can never deadlock on it.
        _read_status(pa, lambda st: st.get("finished") == 0)

        # Downstream fences: record digests, mirror each one, kill at
        # epoch >= 5 (checkpoints 0, 2, 4 completed by then).
        digests_b = {}

        def at_fence(st):
            if "group" in st and "digest" in st:
                scheduler.sync()
            return st.get("epoch", -1) >= 5 or "finished" in st

        _, digests_b = _read_status(pb, at_fence)
        pb.send_signal(signal.SIGKILL)
        pb.wait(timeout=15)
        for line in pb.stdout:            # drain pre-kill reports
            try:
                st = json.loads(line)
            except ValueError:
                break
            if "group" in st and "digest" in st:
                digests_b[st["global_step"]] = st["digest"]

        deadline = time.monotonic() + 20
        while "b" not in scheduler.failed_workers():
            assert time.monotonic() < deadline, "heartbeat expiry not seen"
            time.sleep(0.1)

        # A deposed JobMaster's DEPLOY is rejected at the worker's door.
        with pytest.raises(RuntimeError,
                           match="stale fencing|lease claim"):
            scheduler._send_deploy(
                "a", {"group": 1, "fencing_epoch": election.epoch - 1})

        # Redeploy ONLY the dead worker's group, onto its standby.
        moved = scheduler.recover_worker("b")
        assert moved == {1: "a"}
        assert scheduler.placements == {0: "a", 1: "a"}

        # The rebuilt slice's replayed state is bit-identical to what the
        # DEAD worker reported at that fence.
        dep, _ = _read_status(pa, lambda st: st.get("deployed") == 1)
        assert dep["recovered"] and dep["vertices"] == [2, 3]
        assert dep["global_step"] > 0
        assert dep["global_step"] in digests_b, \
            "recovery fence was never reported by the dead worker"
        assert dep["digest"] == digests_b[dep["global_step"]]

        # ...and the rebuilt slice RUNS ON to the job's target.
        fin, digests_a = _read_status(pa, lambda st:
                                      st.get("finished") == 1)
        assert fin["global_step"] == 8 * runner_kw["steps_per_epoch"]

        # No-failure control: the same slice over the same exported
        # stream, in this process. Every fence digest — the dead
        # worker's, the recovery fence, and the rebuilt continuation —
        # must be bit-identical to it.
        host, eport = scheduler._export_addr[1]
        cl = tp.ControlClient((host, eport))
        rt, resp = cl.call(tp.FETCH_EDGE, tp.pack_json(
            {"edge": 1, "start": 0, "count": 1 << 20}))
        assert rt == tp.EDGE_DATA
        hlen = int.from_bytes(resp[:4], "little")
        hdr = tp.unpack_json(resp[4: 4 + hlen])
        assert hdr["final"], "upstream export should be finished"
        recs = np.frombuffer(resp[4 + hlen:], np.int32).reshape(-1, 2)
        cl.close()

        job = _spanning_job()
        sub1, _v, feeds1, _e = job.subgraph([2, 3], feed_batch_size=4)
        ctrl = ClusterRunner(sub1, **runner_kw)
        ctrl.executor.register_feed(feeds1[1],
                                    ListFeedReader([recs.tolist()]))
        ctrl_digests = _epochs(ctrl, 8)

        assert dep["digest"] == ctrl_digests[dep["global_step"]]
        for step, d in digests_b.items():
            assert d == ctrl_digests[step], \
                f"dead worker's fence {step} diverges from no-failure run"
        for step, d in digests_a.items():
            assert d == ctrl_digests[step], \
                f"rebuilt fence {step} diverges from no-failure run"
    finally:
        for p in (pa, pb):
            if p.poll() is None:
                p.kill()
        scheduler.close()
        jm.close()
        srv.close()
