"""Exchange routing vs a numpy oracle (reference partitioner semantics:
KeyGroupStreamPartitioner / RebalancePartitioner / BroadcastPartitioner)."""

import numpy as np
import jax.numpy as jnp
import pytest

from clonos_tpu.api import records
from clonos_tpu.parallel import routing


def _np_hash32(x):
    u = np.asarray(x, np.uint64) & 0xFFFFFFFF
    u = ((u ^ (u >> 16)) * 0x7FEB352D) & 0xFFFFFFFF
    u = ((u ^ (u >> 15)) * 0x846CA68B) & 0xFFFFFFFF
    return (u ^ (u >> 16)) & 0xFFFFFFFF


def _mkbatch(rows, cap):
    """rows: list per upstream subtask of (key, val) lists."""
    p = len(rows)
    keys = np.zeros((p, cap), np.int32)
    vals = np.zeros((p, cap), np.int32)
    valid = np.zeros((p, cap), bool)
    for i, r in enumerate(rows):
        for j, (k, v) in enumerate(r):
            keys[i, j], vals[i, j], valid[i, j] = k, v, True
    return records.RecordBatch(jnp.asarray(keys), jnp.asarray(vals),
                               jnp.zeros((p, cap), jnp.int32),
                               jnp.asarray(valid))


def test_hash32_matches_oracle():
    xs = np.arange(-50, 50, dtype=np.int32)
    got = np.asarray(routing.hash32(jnp.asarray(xs)))
    want = _np_hash32(xs).astype(np.uint32)
    np.testing.assert_array_equal(got, want)


def test_key_group_routing_owns_all_records():
    G, P = 16, 4
    batch = _mkbatch([[(k, k * 10) for k in range(5)],
                      [(k, k) for k in range(7, 12)]], cap=8)
    routed, dropped = routing.route_hash(batch, P, G, out_capacity=16)
    assert int(dropped.sum()) == 0
    # Every record lands on the subtask owning its key group.
    out = []
    for t in range(P):
        lo, hi = routing.key_group_range(t, P, G)
        row = records.to_numpy(
            records.RecordBatch(routed.keys[t], routed.values[t],
                                routed.timestamps[t], routed.valid[t]))
        for k, v, _ in row:
            kg = int(_np_hash32(k) % G)
            assert lo <= kg < hi, (k, kg, t)
            out.append((k, v))
    assert sorted(out) == sorted((int(k), int(v)) for k, v, _ in
                                 records.to_numpy(batch))


def test_routing_preserves_arrival_order_within_target():
    # All keys equal -> single target; order must match flattened input.
    batch = _mkbatch([[(7, i) for i in range(4)],
                      [(7, 10 + i) for i in range(4)]], cap=4)
    routed, _ = routing.route_hash(batch, 2, 8, out_capacity=16)
    t = int(routing.subtask_for_key_group(
        routing.key_group(jnp.asarray([7]), 8), 2, 8)[0])
    vals = [v for _, v, _ in records.to_numpy(
        records.RecordBatch(routed.keys[t], routed.values[t],
                            routed.timestamps[t], routed.valid[t]))]
    assert vals == [0, 1, 2, 3, 10, 11, 12, 13]


def test_overflow_drops_are_counted():
    batch = _mkbatch([[(3, i) for i in range(6)]], cap=6)
    routed, dropped = routing.route_hash(batch, 1, 4, out_capacity=4)
    assert int(routed.valid.sum()) == 4
    assert int(dropped.sum()) == 2


def test_rebalance_round_robin_deterministic():
    batch = _mkbatch([[(i, i) for i in range(6)]], cap=6)
    routed, dropped = routing.route_rebalance(batch, 3, out_capacity=4)
    assert int(dropped.sum()) == 0
    per = [sorted(v for _, v, _ in records.to_numpy(
        records.RecordBatch(routed.keys[t], routed.values[t],
                            routed.timestamps[t], routed.valid[t])))
           for t in range(3)]
    assert per == [[0, 3], [1, 4], [2, 5]]
    # offset shifts the cycle
    routed2, _ = routing.route_rebalance(batch, 3, out_capacity=4, offset=1)
    per2 = sorted(v for _, v, _ in records.to_numpy(
        records.RecordBatch(routed2.keys[0], routed2.values[0],
                            routed2.timestamps[0], routed2.valid[0])))
    assert per2 == [2, 5]


def test_broadcast_replicates_and_compacts():
    batch = _mkbatch([[(1, 1)], [(2, 2)]], cap=3)
    routed, dropped = routing.route_broadcast(batch, 3, out_capacity=4)
    assert int(dropped.sum()) == 0
    for t in range(3):
        vals = sorted(v for _, v, _ in records.to_numpy(
            records.RecordBatch(routed.keys[t], routed.values[t],
                                routed.timestamps[t], routed.valid[t])))
        assert vals == [1, 2]


def _rand_block(rng, K, P, B, vocab=37, fill=0.7):
    keys = rng.randint(0, vocab, size=(K, P, B)).astype(np.int32)
    vals = rng.randint(-1000, 1000, size=(K, P, B)).astype(np.int32)
    ts = rng.randint(0, 100, size=(K, P, B)).astype(np.int32)
    valid = rng.rand(K, P, B) < fill
    return records.zero_invalid(records.RecordBatch(
        jnp.asarray(keys), jnp.asarray(vals), jnp.asarray(ts),
        jnp.asarray(valid)))


@pytest.mark.parametrize("cap,K,P,B", [
    (4, 7, 3, 16), (16, 7, 3, 16), (64, 7, 3, 16),
    (32, 5, 8, 600),
])
@pytest.mark.parametrize("force_sort", [False, True])
def test_block_routes_bit_identical_to_per_step(cap, K, P, B, force_sort,
                                                monkeypatch):
    """The block exchange (both the counting branch and the flat-sort
    fallback) must equal vmapping the per-step exchange, including
    overflow-drop accounting (the executor switched to the block form for
    speed; semantics are pinned here)."""
    import jax
    if force_sort:   # shrink the scratch budget so the sort path runs
        monkeypatch.setattr(routing, "_COUNT_ROUTE_MAX_BYTES", 0)
    rng = np.random.RandomState(3)
    batch = _rand_block(rng, K, P, B)
    for T, G in [(4, 8), (1, 4), (5, 20)]:
        r1, d1 = jax.vmap(
            lambda b: routing.route_hash(b, T, G, cap))(batch)
        r2, d2 = routing.route_hash_block(batch, T, G, cap)
        for a, b in zip(jax.tree_util.tree_leaves((r1, d1)),
                        jax.tree_util.tree_leaves((r2, d2))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Rebalance with a running per-step offset.
    counts = np.asarray(batch.count().sum(axis=1))
    offs = jnp.asarray(5 + np.cumsum(counts) - counts, jnp.int32)
    r1, d1 = jax.vmap(lambda b, o: routing.route_rebalance(
        b, 3, cap, o))(batch, offs)
    r2, d2 = routing.route_rebalance_block(batch, 3, cap, offs)
    for a, b in zip(jax.tree_util.tree_leaves((r1, d1)),
                    jax.tree_util.tree_leaves((r2, d2))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Broadcast.
    r1, d1 = jax.vmap(lambda b: routing.route_broadcast(b, 3, cap))(batch)
    r2, d2 = routing.route_broadcast_block(batch, 3, cap)
    for a, b in zip(jax.tree_util.tree_leaves((r1, d1)),
                    jax.tree_util.tree_leaves((r2, d2))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Forward at smaller/equal/larger capacity.
    for oc in (B // 2, B, B + 5):
        r1, d1 = jax.vmap(lambda b: routing.route_forward(b, oc))(batch)
        r2, d2 = routing.route_forward_block(batch, oc)
        for a, b in zip(jax.tree_util.tree_leaves((r1, d1)),
                        jax.tree_util.tree_leaves((r2, d2))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_static_route_plan_matches_dynamic_multiset():
    """StaticRoutePlan routes the same per-(step,target) record multiset
    as the dynamic hash exchange (layout differs: static slots keep holes
    instead of compacting)."""
    import jax
    rng = np.random.RandomState(11)
    K, P, NK, G, T, CAP = 5, 3, 29, 8, 4, 32
    slot_keys = np.arange(NK, dtype=np.int32)
    plan = routing.plan_static_hash(slot_keys, P, T, G, CAP)
    # Dense-table emission: slot i carries key i; random validity.
    keys = np.broadcast_to(slot_keys, (K, P, NK)).copy()
    vals = rng.randint(1, 100, size=(K, P, NK)).astype(np.int32)
    ts = rng.randint(0, 50, size=(K, P, NK)).astype(np.int32)
    valid = rng.rand(K, P, NK) < 0.6
    batch = records.zero_invalid(records.RecordBatch(
        jnp.asarray(keys), jnp.asarray(vals), jnp.asarray(ts),
        jnp.asarray(valid)))
    r_static, d_static = plan.apply(batch)
    r_dyn, d_dyn = routing.route_hash_block(batch, T, G, CAP)
    for k in range(K):
        for t in range(T):
            def multiset(r):
                m = np.asarray(r.valid[k, t])
                return sorted(zip(np.asarray(r.keys[k, t])[m].tolist(),
                                  np.asarray(r.values[k, t])[m].tolist(),
                                  np.asarray(r.timestamps[k, t])[m].tolist()))
            assert multiset(r_static) == multiset(r_dyn), (k, t)
    assert int(jnp.sum(d_static)) == 0 == int(jnp.sum(d_dyn))
    # slot_keys metadata matches what actually flows in mapped slots.
    assert np.all((plan.slot_keys >= 0) == plan.ok)


def test_static_route_plan_drop_accounting():
    """Capacity overflow drops whole static slots and counts them."""
    NK, P, T, G, CAP = 16, 2, 1, 4, 8
    plan = routing.plan_static_hash(
        np.arange(NK, dtype=np.int32), P, T, G, CAP)
    # All 16*2=32 slots target subtask 0; capacity 8 -> 24 static drops.
    assert plan.ok.sum() == CAP
    assert len(plan.drop_p) == NK * P - CAP
    batch = records.RecordBatch(
        jnp.broadcast_to(jnp.arange(NK, dtype=jnp.int32), (3, P, NK)),
        jnp.ones((3, P, NK), jnp.int32), jnp.zeros((3, P, NK), jnp.int32),
        jnp.ones((3, P, NK), jnp.bool_))
    routed, dropped = plan.apply(batch)
    assert int(routed.valid.sum()) == 3 * CAP
    assert np.all(np.asarray(dropped) == NK * P - CAP)


def test_forward_identity():
    batch = _mkbatch([[(1, 5)], [(2, 6)]], cap=3)
    routed, dropped = routing.route_forward(batch, out_capacity=3)
    assert int(dropped.sum()) == 0
    np.testing.assert_array_equal(np.asarray(routed.keys),
                                  np.asarray(batch.keys))


@pytest.mark.parametrize("cap,K,P,B", [
    (4, 7, 3, 16), (16, 7, 3, 16), (64, 5, 8, 600),
])
def test_lane_routes_bit_identical_to_full_route_lane(cap, K, P, B):
    """The single-lane exchange (recovery's fused single-failure path)
    must equal the full block route's lane slice bit-for-bit — survivors,
    positions, overflow drops, everything."""
    rng = np.random.RandomState(11)
    batch = _rand_block(rng, K, P, B)
    T, G = 3, 8
    full, _ = routing.route_hash_block(batch, T, G, cap)
    for lane in range(T):
        got = routing.route_hash_block_lane(batch, lane, T, G, cap)
        for a, b in zip(got, full):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b[:, lane]))
    offs = jnp.asarray(rng.randint(0, 5, size=(K,)), jnp.int32)
    full_rb, _ = routing.route_rebalance_block(batch, T, cap, offs)
    for lane in range(T):
        got = routing.route_rebalance_block_lane(batch, lane, T, cap, offs)
        for a, b in zip(got, full_rb):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b[:, lane]))
    full_bc, _ = routing.route_broadcast_block(batch, T, cap)
    for lane in range(T):
        got = routing.route_broadcast_block_lane(batch, lane, cap)
        for a, b in zip(got, full_bc):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b[:, lane]))
    full_fw, _ = routing.route_forward_block(batch, cap)
    for lane in range(P):
        got = routing.route_forward_block_lane(batch, lane, cap)
        for a, b in zip(got, full_fw):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b[:, lane]))
