"""Multi-tenant acceptance: many jobs, one slot pool, contained blast
radius (runtime/dispatcher.py; reference Dispatcher.submitJob — many
JobGraphs against one TaskManager pool).

THE test drives a real 2-process cluster: worker ``a`` (4 slots) and
worker ``b`` (2 slots) under one in-process Dispatcher. Three tenants
submit the same single-slice job (one over the control wire): red and
blue land on ``b``, green on ``a``. Worker ``b`` is SIGKILLed mid-epoch;
the dispatcher must recover red and blue INDEPENDENTLY onto ``a`` —
each with its own job-tagged trace, its own ``<root>/<job_id>/``
checkpoint/ledger tree, causal replay bit-identical to the dead
worker's reported fences and to a no-failure control — while green is
never redeployed and its checkpoint fences keep landing at a bounded
cadence THROUGH the recovery storm (worker-side fence-priority: one
rebuild per round, after every healthy epoch). Afterwards the audit
chain proves exactly-once PER JOB and ``audit --job`` resolves each
job's ledgers.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

from clonos_tpu.obs import configure_audit, reset_audit
from clonos_tpu.parallel import transport as tp
from clonos_tpu.runtime.dispatcher import Dispatcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: identical to tests/test_scheduler.py — digests are a pure function of
#: (job, seed, records) under the logical clock, so every tenant's run
#: (and the in-process control) is comparable bit-for-bit.
RUNNER_KW = dict(steps_per_epoch=4, log_capacity=512, max_epochs=64,
                 inflight_ring_steps=64, seed=7, logical_time=True)

JOB = "examples.wordcount:build_job"      # synthetic source — no feeds


def _fences(events, jid):
    """(t, status) pairs of job ``jid``'s epoch-fence reports."""
    return [(t, s) for t, s in events
            if s.get("job") == jid and "group" in s and "digest" in s]


def test_two_tenants_recover_independently_third_unharmed(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    lease = str(tmp_path / "jm.lease")
    ckroot = str(tmp_path / "ck")
    tracedir = str(tmp_path / "traces")

    configure_audit(on_divergence="warn")
    disp = Dispatcher(lease_path=lease, checkpoint_root=ckroot,
                      runner_kw=RUNNER_KW, target_epochs=8,
                      complete_every=2, deploy_timeout_s=300.0,
                      trace_dir=tracedir, heartbeat_timeout_s=2.0)

    def spawn(eid, slots):
        return subprocess.Popen(
            [sys.executable, "-m", "clonos_tpu", "slotworker",
             "--jm", f"127.0.0.1:{disp.jm.address[1]}",
             "--executor-id", eid, "--slots", str(slots),
             "--lease", lease, "--heartbeat-interval", "0.3",
             "--max-seconds", "600", "--epoch-sleep", "0.25"],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)

    pa, pb = spawn("a", 4), spawn("b", 2)
    lk = threading.Lock()
    ev_a, ev_b = [], []

    def reader(proc, out):
        for line in iter(proc.stdout.readline, ""):
            try:
                st = json.loads(line)
            except ValueError:
                continue
            with lk:
                out.append((time.monotonic(), st))

    ta = threading.Thread(target=reader, args=(pa, ev_a), daemon=True)
    tb = threading.Thread(target=reader, args=(pb, ev_b), daemon=True)
    ta.start()
    tb.start()

    def pump(pred, deadline_s, what):
        """Drive the dispatcher main loop until ``pred`` over the two
        workers' status streams returns something truthy."""
        deadline = time.monotonic() + deadline_s
        while True:
            disp.step()
            with lk:
                ea, eb = list(ev_a), list(ev_b)
            got = pred(ea, eb)
            if got:
                return got
            assert time.monotonic() < deadline, f"timeout: {what}"
            time.sleep(0.05)

    try:
        deadline = time.monotonic() + 30
        while {"a", "b"} - set(disp.jm.registered()):
            assert time.monotonic() < deadline, "workers never registered"
            time.sleep(0.05)

        # Red submits over the control wire (the deployment surface);
        # blue and green through the embedded API. One shared pool.
        cl = tp.ControlClient(disp.address)
        # target_epochs 20 keeps red/blue far from their finish line at
        # kill time (epoch >= 5): dispatcher-side kill detection lags
        # the fence stream by a few main-loop rounds.
        rx = cl.call_json(tp.SUBMIT_JOB, {
            "job": JOB, "target_epochs": 20,
            "tenant_config": {"tenant": "red", "slots": 1,
                              "workers": ["b"]}})
        cl.close()
        assert rx == {"job_id": "red-001", "state": "ADMITTED"}
        ry = disp.submit_job(JOB, {"tenant": "blue", "slots": 1,
                                   "workers": ["b"]}, target_epochs=20)
        rz = disp.submit_job(JOB, {"tenant": "green", "slots": 1,
                                   "workers": ["a"]}, target_epochs=30)
        assert ry["job_id"] == "blue-002" and rz["job_id"] == "green-003"

        def deployed(ea, eb):
            dx = [s for _, s in eb if s.get("deployed") == 0
                  and s.get("job") == "red-001"]
            dy = [s for _, s in eb if s.get("deployed") == 0
                  and s.get("job") == "blue-002"]
            dz = [s for _, s in ea if s.get("deployed") == 0
                  and s.get("job") == "green-003"]
            return (dx[0], dy[0], dz[0]) if dx and dy and dz else None

        dx, dy, dz = pump(deployed, 240, "initial deploys")
        for d in (dx, dy, dz):
            assert d["vertices"] == [0, 1, 2] and not d["recovered"]
        # One pool, job-scoped slot keys; placement follows the hints.
        assert disp.pool.placements() == {("red-001", 0): "b",
                                          ("blue-002", 0): "b",
                                          ("green-003", 0): "a"}
        assert all(j["state"] == "RUNNING" for j in disp.jobs())

        # Let red and blue pass checkpoints 0, 2, 4 (complete_every=2)
        # and collect enough green fences for a latency baseline.
        def ripe(ea, eb):
            ex = _fences(eb, "red-001")
            ey = _fences(eb, "blue-002")
            ez = _fences(ea, "green-003")
            return (ex and ey and len(ez) >= 4
                    and max(s["epoch"] for _, s in ex) >= 5
                    and max(s["epoch"] for _, s in ey) >= 5)

        pump(ripe, 240, "pre-kill epochs")
        t_kill = time.monotonic()
        pb.send_signal(signal.SIGKILL)
        pb.wait(timeout=15)
        tb.join(timeout=30)          # EOF: every fence b reported is in
        assert not tb.is_alive()

        with lk:
            eb = list(ev_b)
        digests_b = {jid: {s["global_step"]: s["digest"]
                           for _, s in _fences(eb, jid)}
                     for jid in ("red-001", "blue-002")}

        def recovered(ea, eb):
            out = {}
            for _, s in ea:
                if s.get("deployed") == 0 and s.get("recovered"):
                    out[s.get("job")] = s
            if {"red-001", "blue-002"} <= set(out):
                return out
            return None

        rec = pump(recovered, 240, "independent recoveries")
        t_rec = time.monotonic()

        # Each tenant's rebuild replayed to a fence ITS dead incarnation
        # reported — bit-identical, per job.
        for jid in ("red-001", "blue-002"):
            d = rec[jid]
            assert d["vertices"] == [0, 1, 2]
            assert d["global_step"] > 0
            assert d["global_step"] in digests_b[jid], \
                f"{jid}: recovery fence never reported by dead worker"
            assert d["digest"] == digests_b[jid][d["global_step"]]

        with lk:
            ea = list(ev_a)
        # Only the affected tenants were redeployed: green was deployed
        # exactly once, never with recover set.
        dz_all = [s for _, s in ea if s.get("deployed") == 0
                  and s.get("job") == "green-003"]
        assert len(dz_all) == 1 and not dz_all[0]["recovered"]
        # Fence-priority interleave: between the two causal rebuilds the
        # surviving worker ran green's healthy epoch — a tenant's storm
        # never serializes a neighbor behind the whole backlog.
        idx = [i for i, (_, s) in enumerate(ea)
               if s.get("deployed") == 0 and s.get("recovered")]
        assert len(idx) == 2
        i1, i2 = sorted(idx)
        assert any(s.get("job") == "green-003" and "group" in s
                   for _, s in ea[i1 + 1:i2]), \
            "no green fence between the two recovery rebuilds"

        # Bounded fence-latency inflation for the unharmed tenant: its
        # max inter-fence gap through the storm stays within a bounded
        # factor of its pre-kill cadence.
        tz = [t for t, _ in _fences(ea, "green-003")]
        pre = [t for t in tz if t <= t_kill]
        assert len(pre) >= 4
        gaps = sorted(b - a for a, b in zip(pre, pre[1:]))
        median = gaps[len(gaps) // 2]
        storm = [pre[-1]] + [t for t in tz if t_kill < t <= t_rec]
        assert len(storm) >= 2, "green never fenced during recovery"
        max_gap = max(b - a for a, b in zip(storm, storm[1:]))
        bound = max(30.0, 25 * median)
        assert max_gap <= bound, \
            f"fence gap {max_gap:.1f}s breaches bound {bound:.1f}s"

        # Every job runs on to ITS OWN target and the dispatcher reaps
        # them; finished slots drain back to the admission view.
        def all_done(ea, eb):
            states = {j["job_id"]: j["state"] for j in disp.jobs()}
            return states if set(states.values()) == {"FINISHED"} else None

        pump(all_done, 300, "jobs running to completion")
        with lk:
            ea = list(ev_a)
        fins = {s["job"]: s for _, s in ea if "finished" in s}
        assert fins["red-001"]["global_step"] == 20 * 4
        assert fins["blue-002"]["global_step"] == 20 * 4
        assert fins["green-003"]["global_step"] == 30 * 4

        # No-failure control in this process: every fence any tenant
        # ever reported — pre-kill on b, recovery, and the rebuilt
        # continuations on a — matches one seed-7 run of the job.
        import examples.wordcount as wc
        from clonos_tpu.runtime.cluster import ClusterRunner
        sub, _v, _f, _e = wc.build_job().subgraph(
            [0, 1, 2], feed_batch_size=8)
        ctrl = ClusterRunner(sub, **RUNNER_KW)
        ctrl_digests = {}
        for _ in range(30):
            closed = ctrl.executor.epoch_id
            ctrl.run_epoch(complete_checkpoint=(closed % 2 == 0))
            ctrl_digests[ctrl.global_step] = ctrl.state_digest()
        for jid in ("red-001", "blue-002", "green-003"):
            for events in (ea, eb):
                for _, s in _fences(events, jid):
                    assert s["digest"] == ctrl_digests[s["global_step"]], \
                        f"{jid} fence {s['global_step']} diverges"

        # Job-scoped durable artifacts: each tenant's ledger lives under
        # <root>/<job_id>/g0/, and `audit --job` resolves it while an
        # unscoped diff over the multi-job root refuses (ambiguous).
        from clonos_tpu.cli import cmd_audit
        for jid in ("red-001", "blue-002", "green-003"):
            assert os.path.exists(
                os.path.join(ckroot, jid, "g0", "ledger.jsonl"))

        def ns(**kw):
            base = dict(dir=ckroot, diff=None, job=None, report="text",
                        json=False)
            base.update(kw)
            return argparse.Namespace(**base)

        assert cmd_audit(ns(job="red-001")) == 0
        assert cmd_audit(ns(diff=ckroot)) == 2

        # Per-tenant rollups: exactly-once health PER JOB, admission
        # gauges drained after completion.
        m = disp.metrics_extra()
        for jid in ("red-001", "blue-002", "green-003"):
            assert m[f"cluster.job.{jid}.audit.exactly-once-ok"] == 1
            assert m[f"cluster.job.{jid}.audit.divergences"] == 0
            assert m[f"cluster.job.{jid}.groups"] >= 1
        for tenant in ("red", "blue", "green"):
            assert m[f"tenant.{tenant}.slots-held"] == 0
        assert m["dispatcher.jobs-total"] == 3
        assert m["dispatcher.queue-depth"] == 0

        # Job-tagged traces: one file per job, every span under the
        # job's own trace id; the harmed tenants carry recovery spans,
        # the unharmed one does not.
        for jid, stormy in (("red-001", True), ("blue-002", True),
                            ("green-003", False)):
            path = os.path.join(tracedir, f"trace-jm.{jid}.jsonl")
            with open(path) as f:
                recs = [json.loads(ln) for ln in f if ln.strip()]
            assert recs
            assert all(r["trace"].startswith(f"{jid}:") for r in recs)
            names = {r["name"] for r in recs}
            assert ("recovery.redeploy" in names) == stormy
    finally:
        for p in (pa, pb):
            if p.poll() is None:
                p.kill()
        disp.close()
        reset_audit()
