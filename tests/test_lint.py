"""Static determinism lint (clonos_tpu/lint/): rules, waivers, CLI.

The acceptance pair: ``clonos_tpu lint clonos_tpu/ examples/`` exits 0
on the repo (every exemption explicit), and pointed straight at
``examples/audit_nondet.py`` exits 1 naming the exact line of the
unlogged SALT — the same bug the PR-3 runtime audit catches as a
digest divergence, which test_same_bug_static_and_runtime pairs up.

NOTE: this file is itself linted at session configure (markers rule is
line-regex based), so unregistered-marker fixtures below are built by
string concatenation, never written literally.
"""

import importlib.util
import json
import os
import textwrap

import pytest

from clonos_tpu.lint import (ERROR, WARNING, RULES, FileContext,
                             rule_names, run_lint)
from clonos_tpu.lint.runner import collect_files, format_json

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_src(tmp_path, monkeypatch, src, name="mod.py",
              waiver_text=None, use_waivers=True, rules=None):
    monkeypatch.chdir(tmp_path)
    (tmp_path / name).write_text(textwrap.dedent(src))
    if waiver_text is not None:
        (tmp_path / ".clonos-waivers").write_text(
            textwrap.dedent(waiver_text))
    return run_lint([name], use_waivers=use_waivers, rules=rules)


def _hits(result, rule):
    return [f for f in result.findings if f.rule == rule]


# --- rule family 1: nondeterminism escapes -------------------------------


def test_wallclock_flags_aliased_import(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import time as _t
        def now():
            return _t.time()
        """, use_waivers=False)
    (f,) = _hits(res, "wallclock")
    assert f.line == 3 and "causal time service" in f.message
    assert res.exit_code() == 1


def test_wallclock_reference_without_call_flagged(tmp_path, monkeypatch):
    # `clock=time.time` stashes the wall clock as surely as calling it.
    res = _lint_src(tmp_path, monkeypatch, """\
        import time
        def mk(clock=time.time):
            return clock
        """, use_waivers=False)
    assert len(_hits(res, "wallclock")) == 1


def test_monotonic_not_flagged(tmp_path, monkeypatch):
    # Durations are not replayed data; time.monotonic is fine.
    res = _lint_src(tmp_path, monkeypatch, """\
        import time
        def span():
            t0 = time.monotonic()
            return time.monotonic() - t0
        """, use_waivers=False)
    assert res.ok


def test_rng_global_draw_and_unseeded_ctor(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import random
        import numpy as np
        def a():
            return random.random()
        def b():
            return np.random.rand(3)
        def c():
            return np.random.RandomState()
        """, use_waivers=False)
    assert len(_hits(res, "rng")) == 3


def test_rng_seeded_ctor_is_deterministic(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import numpy as np
        def mk(seed):
            return np.random.RandomState(seed)
        """, use_waivers=False)
    assert res.ok


def test_entropy_urandom_and_uuid(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import os
        import uuid
        SALT = int.from_bytes(os.urandom(3), "little")
        TAG = uuid.uuid4().hex
        """, use_waivers=False)
    assert {f.line for f in _hits(res, "entropy")} == {3, 4}


def test_entropy_from_import_aliases_flagged(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        from os import urandom
        from uuid import uuid4 as mkid
        SALT = urandom(3)
        TAG = mkid().hex
        """, use_waivers=False)
    assert {f.line for f in _hits(res, "entropy")} == {3, 4}


def test_entropy_getpid_dotted_and_aliased(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import os
        from os import getpid as gp
        KEY = os.getpid() & 0xFF
        KEY2 = gp() & 0xFF
        """, use_waivers=False)
    hits = _hits(res, "entropy")
    assert {f.line for f in hits} == {3, 4}
    assert "restart" in hits[0].message


def test_entropy_unimported_getpid_name_not_flagged(tmp_path,
                                                    monkeypatch):
    # A local function that merely shares the name is not os.getpid.
    res = _lint_src(tmp_path, monkeypatch, """\
        def getpid():
            return 7
        KEY = getpid()
        """, use_waivers=False)
    assert _hits(res, "entropy") == []


def test_unordered_iter_set_flagged_sorted_ok(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        def bad(xs, out):
            for x in set(xs):
                out.append(x)
        def good(xs, out):
            for x in sorted(set(xs)):
                out.append(x)
        def comp(xs):
            return [x for x in {1, 2, 3}]
        """, use_waivers=False)
    assert {f.line for f in _hits(res, "unordered-iter")} == {2, 8}


# --- rule family 2: trace safety -----------------------------------------


def test_host_branch_on_traced_param(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        class Op:
            def process(self, state, batch):
                if batch > 0:
                    return state
                return state + 1
        """, use_waivers=False)
    (f,) = _hits(res, "host-branch")
    assert f.line == 3 and "batch" in f.message


def test_host_branch_static_shape_exempt(tmp_path, monkeypatch):
    # .shape/.dtype are static at trace time — not a host branch on a
    # traced VALUE; and self-config branches are static too.
    res = _lint_src(tmp_path, monkeypatch, """\
        class Op:
            def process(self, state, batch):
                if batch.shape[0] == 8:
                    return state
                if self.fancy:
                    return state
                return state
        """, use_waivers=False)
    assert res.ok


def test_host_branch_in_map_lambda(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        def build(env):
            return env.map(lambda k, v, t: v if v > 0 else -v)
        """, use_waivers=False)
    assert len(_hits(res, "host-branch")) == 1


def test_mutable_closure_capture(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        seen = []
        class Op:
            def process(self, state, batch):
                seen.append(batch)
                return state
        """, use_waivers=False)
    (f,) = _hits(res, "mutable-closure")
    assert "seen" in f.message


def test_mutable_local_ok(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        class Op:
            def process(self, state, batch):
                acc = []
                acc.append(batch)
                return state
        """, use_waivers=False)
    assert res.ok


def test_host_callback_and_item_sync(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        class Op:
            def process(self, state, batch):
                print(batch)
                x = batch.item()
                return state
        """, use_waivers=False)
    assert len(_hits(res, "host-callback")) == 2


def test_plain_methods_not_traced(tmp_path, monkeypatch):
    # Only step-function entry points are traced scopes.
    res = _lint_src(tmp_path, monkeypatch, """\
        class Helper:
            def run(self, batch):
                if batch > 0:
                    print(batch)
                return batch
        """, use_waivers=False)
    assert res.ok


# --- rule family 3: lock discipline --------------------------------------


def test_lock_discipline_unlocked_mutation(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
            def put(self, x):
                with self._lock:
                    self._items.append(x)
            def race(self, x):
                self._items.append(x)
        """, use_waivers=False)
    (f,) = _hits(res, "lock-discipline")
    assert f.line == 10 and "_items" in f.message


def test_lock_discipline_helper_called_under_lock_ok(tmp_path,
                                                     monkeypatch):
    # A helper only ever reached with the lock held is lock-held
    # itself (the _trim_to pattern in api/feeds.py).
    res = _lint_src(tmp_path, monkeypatch, """\
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
            def put(self, x):
                with self._lock:
                    self._items.append(x)
                    self._fill(x)
            def _fill(self, x):
                self._items.append(x)
            def drain_locked(self):
                self._items.clear()
        """, use_waivers=False)
    assert res.ok


def test_lock_discipline_del_statement_is_a_mutation(tmp_path,
                                                     monkeypatch):
    # `del self._jobs[jid]` shrinks guarded state just like a store
    # does (the dispatcher's job-table pattern) — flagged when the
    # lock is not held.
    res = _lint_src(tmp_path, monkeypatch, """\
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._jobs = {}
            def put(self, jid, rec):
                with self._lock:
                    self._jobs[jid] = rec
            def evict(self, jid):
                del self._jobs[jid]
        """, use_waivers=False)
    (f,) = _hits(res, "lock-discipline")
    assert f.line == 10 and "deletes from" in f.message
    assert "_jobs" in f.message


def test_lock_discipline_init_exempt_and_unlocked_class_quiet(
        tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        class NoLocks:
            def __init__(self):
                self._items = []
            def put(self, x):
                self._items.append(x)
        """, use_waivers=False)
    assert res.ok


# --- waivers --------------------------------------------------------------


def test_inline_waiver_same_line(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import time
        STARTED = time.time()  # clonos: allow(wallclock) banner only
        """)
    assert res.ok and len(res.waived) == 1


def test_inline_waiver_comment_block_above(tmp_path, monkeypatch):
    # A multi-line justification block waives the next CODE line.
    res = _lint_src(tmp_path, monkeypatch, """\
        import time
        # clonos: allow(wallclock) — process start banner,
        # never replayed data.
        STARTED = time.time()
        """)
    assert res.ok and len(res.waived) == 1


def test_inline_waiver_in_string_is_documentation(tmp_path, monkeypatch):
    # Waiver syntax quoted in a docstring must not waive anything.
    res = _lint_src(tmp_path, monkeypatch, '''\
        """Docs: write `# clonos: allow(wallclock)` to waive."""
        import time
        STARTED = time.time()
        ''')
    assert not res.ok and not res.waived


def test_waiver_file_rule_glob(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import time
        STARTED = time.time()
        """, waiver_text="wallclock mod.py\n")
    assert res.ok and len(res.waived) == 1


def test_unknown_rule_inline_is_error(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import time
        STARTED = time.time()  # clonos: allow(wallclok) typo
        """)
    errs = _hits(res, "waiver-unknown-rule")
    assert len(errs) == 1 and "wallclok" in errs[0].message
    assert res.exit_code() == 1


def test_unknown_rule_in_waiver_file_is_error(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, "X = 1\n",
                    waiver_text="wallclok mod.py\n")
    errs = _hits(res, "waiver-unknown-rule")
    assert len(errs) == 1 and res.exit_code() == 1


def test_stale_inline_waiver_warns_exit_zero(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        X = 1  # clonos: allow(wallclock) nothing here any more
        """)
    (w,) = _hits(res, "stale-waiver")
    assert w.severity == WARNING
    assert res.exit_code() == 0


def test_stale_waiver_file_entry_warns(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, "X = 1\n",
                    waiver_text="entropy other_*.py\n")
    (w,) = _hits(res, "stale-waiver")
    assert ".clonos-waivers" in w.path and res.exit_code() == 0


def test_exclude_skips_traversal_but_not_explicit_target(
        tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "bait.py").write_text(
        "import os\nS = os.urandom(3)\n")
    (tmp_path / "pkg" / "clean.py").write_text("X = 1\n")
    (tmp_path / ".clonos-waivers").write_text("exclude pkg/bait.py\n")
    # Directory traversal: bait excluded, tree is clean.
    res = run_lint(["pkg"])
    assert res.ok and res.files == ["pkg/clean.py"]
    # Naming the file is the override: finding comes back, no stale
    # warning for the exclude that was deliberately bypassed.
    res2 = run_lint(["pkg/bait.py"])
    assert res2.exit_code() == 1
    assert not _hits(res2, "stale-waiver")


def test_no_waivers_flag_shows_raw_findings(tmp_path, monkeypatch):
    res = _lint_src(tmp_path, monkeypatch, """\
        import time
        STARTED = time.time()  # clonos: allow(wallclock) reason
        """, use_waivers=False)
    assert res.exit_code() == 1 and not res.waived


def test_unknown_rule_filter_raises(tmp_path, monkeypatch):
    with pytest.raises(ValueError, match="no-such-rule"):
        _lint_src(tmp_path, monkeypatch, "X = 1\n",
                  rules=["no-such-rule"])


# --- registry -------------------------------------------------------------


def test_registry_contents_and_custom_rule():
    assert {"wallclock", "rng", "entropy", "unordered-iter",
            "host-branch", "mutable-closure", "host-callback",
            "lock-discipline", "markers"} <= set(rule_names())

    from clonos_tpu.lint import Rule, register_rule

    class NoTodo(Rule):
        name = "no-todo-test-rule"
        description = "test-only rule"

        def check(self, ctx):
            return [self.finding(ctx, i, "todo")
                    for i, line in enumerate(ctx.lines, 1)
                    if "TODO" in line]

    try:
        register_rule(NoTodo)
        assert "no-todo-test-rule" in RULES
        ctx = FileContext("x.py", "A = 1  # TODO later\n")
        assert len(RULES["no-todo-test-rule"].check(ctx)) == 1
        with pytest.raises(ValueError, match="duplicate"):
            register_rule(NoTodo)
    finally:
        RULES.pop("no-todo-test-rule", None)


# --- the repo itself ------------------------------------------------------


def test_self_lint_repo_clean(monkeypatch):
    """The tree lints clean with every exemption explicit (satellite:
    self-lint), and the bait file is excluded from traversal only."""
    monkeypatch.chdir(_REPO)
    res = run_lint(["clonos_tpu", "examples"])
    assert res.ok, "\n".join(
        f.location() + " " + f.message for f in res.errors)
    assert res.waived, "expected explicit waivers, found none"
    assert not res.warnings


def test_examples_wordcount_nexmark_clean(monkeypatch):
    monkeypatch.chdir(_REPO)
    res = run_lint(["examples/wordcount.py", "examples/nexmark_join.py"])
    assert res.ok and not res.findings


def test_audit_nondet_flagged_at_salt_line(monkeypatch):
    monkeypatch.chdir(_REPO)
    with open(os.path.join(_REPO, "examples", "audit_nondet.py")) as f:
        src = f.read()
    salt_line = 1 + next(i for i, l in enumerate(src.splitlines())
                         if "os.urandom" in l)
    res = run_lint(["examples/audit_nondet.py"])
    (f,) = res.errors
    assert (f.rule, f.path, f.line) == (
        "entropy", "examples/audit_nondet.py", salt_line)
    payload = json.loads(format_json(res))
    assert payload["ok"] is False
    assert payload["findings"][0]["line"] == salt_line


def _load_audit_nondet():
    path = os.path.join(_REPO, "examples", "audit_nondet.py")
    spec = importlib.util.spec_from_file_location("_audit_nondet", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_same_bug_static_and_runtime(monkeypatch):
    """The line the linter names is the line the audit blames: re-import
    draws a fresh SALT (the process-restart path), and per-epoch ring
    digests of the salted values diverge exactly as
    ``recovery.audit.divergence`` reports it."""
    from clonos_tpu.obs.digest import EpochDigest, diff_ledgers

    monkeypatch.chdir(_REPO)
    res = run_lint(["examples/audit_nondet.py"])
    (finding,) = res.errors
    assert finding.rule == "entropy"

    salt_a = _load_audit_nondet().SALT
    salt_b = salt_a
    for _ in range(8):                # 2^-24 collision: retry, don't flake
        salt_b = _load_audit_nondet().SALT
        if salt_b != salt_a:
            break
    assert salt_a != salt_b

    def ledger(salt):
        d = EpochDigest(0)
        for v in range(16):           # the example's salt-map transform
            salted = (v * 31 + salt) % 9973
            d.fold("ring/salt", salted.to_bytes(4, "little"))
        return [d.to_entry()]

    lines = diff_ledgers(ledger(salt_a), ledger(salt_b))
    assert lines and "ring/salt" in lines[0]
    assert "content divergence" in lines[0]


# --- markers rule (absorbed check_markers) --------------------------------


def test_markers_rule_flags_unregistered(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    # Built by concatenation: a literal marker here would trip the
    # session-configure lint on THIS file (see module docstring).
    bad = "import pytest\n@pytest." + "mark.mystery\ndef test_x():\n    pass\n"
    (tests_dir / "test_bad.py").write_text(bad)
    res = run_lint(["tests"])
    (f,) = _hits(res, "markers")
    assert f.line == 2 and "mystery" in f.message
    # The nondet families stay out of tests/ — no cross-talk.
    assert {x.rule for x in res.findings} == {"markers"}


def test_check_markers_shim(tmp_path):
    import subprocess
    import sys
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "check_markers.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "markers ok" in proc.stdout


# --- CLI ------------------------------------------------------------------


def test_cli_lint_json_and_exit_codes(monkeypatch, capsys):
    from clonos_tpu.cli import main

    monkeypatch.chdir(_REPO)
    assert main(["lint", "clonos_tpu", "examples"]) == 0
    capsys.readouterr()
    rc = main(["lint", "examples/audit_nondet.py", "--report", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out.strip())
    assert payload["ok"] is False and payload["errors"] == 1
    (f,) = payload["findings"]
    assert f["rule"] == "entropy"
    assert f["path"] == "examples/audit_nondet.py"


def test_cli_list_rules_and_bad_rule_filter(monkeypatch, capsys):
    from clonos_tpu.cli import main

    monkeypatch.chdir(_REPO)
    assert main(["lint", "--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "wallclock" in out and "lock-discipline" in out
    assert main(["lint", "--rule", "bogus-rule", "clonos_tpu"]) == 2


def test_collect_files_dedup_and_skip_dirs(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "a.py").write_text("X = 1\n")
    (tmp_path / "__pycache__").mkdir()
    (tmp_path / "__pycache__" / "junk.py").write_text("X = 1\n")
    files = collect_files(["a.py", "."])
    assert files == ["a.py"]
