"""End-to-end dataflow: the SocketWindowWordCount shape running as jitted
supersteps, checked against a plain-Python oracle. (The reference's analog
tier is the MiniCluster ITCases, e.g.
flink-tests/.../checkpointing/*ITCase*.)"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.api import records
from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.api.operators import SyntheticSource
from clonos_tpu.causal import log as clog
from clonos_tpu.causal import determinant as det
from clonos_tpu.parallel import routing
from clonos_tpu.runtime.executor import LocalExecutor, DETS_PER_STEP


VOCAB, BATCH, NKEYS = 13, 8, 13


def _build_wordcount(parallelism=2, window=1_000_000):
    env = StreamEnvironment(name="wordcount", num_key_groups=16)
    (env.synthetic_source(vocab=VOCAB, batch_size=BATCH,
                          parallelism=parallelism)
        .key_by()
        .window_count(num_keys=NKEYS, window_size=window)
        .sink())
    return env.build()


def _oracle_counts(parallelism, steps):
    """Reproduce SyntheticSource key generation on the host."""
    counts = np.zeros(VOCAB, np.int64)
    seq = np.zeros(parallelism, np.int64)
    for _ in range(steps):
        for s in range(parallelism):
            lane = np.arange(BATCH)
            mix = ((seq[s] + lane) * 1024 + s).astype(np.int32)
            u = np.asarray(mix, np.uint64) & 0xFFFFFFFF
            u = ((u ^ (u >> 16)) * 0x7FEB352D) & 0xFFFFFFFF
            u = ((u ^ (u >> 15)) * 0x846CA68B) & 0xFFFFFFFF
            u = (u ^ (u >> 16)) & 0xFFFFFFFF
            np.add.at(counts, (u % VOCAB).astype(np.int64), 1)
            seq[s] += BATCH
    return counts


def test_wordcount_counts_match_oracle():
    job = _build_wordcount(parallelism=2)
    ex = LocalExecutor(job, steps_per_epoch=4, log_capacity=1 << 10)
    for _ in range(6):
        ex.step()
    # Window never fired (huge window) -> all counts in the window operator
    # state. Records need one superstep to traverse the source->window edge,
    # so the window has seen 5 of the 6 source batches.
    acc = np.asarray(ex.vertex_state(1)["acc"]).sum(axis=0)
    np.testing.assert_array_equal(acc, _oracle_counts(2, 5))
    # Key ownership: each subtask only holds keys of its key-group range.
    acc2 = np.asarray(ex.vertex_state(1)["acc"])
    G, P = job.num_key_groups, 2
    for k in range(VOCAB):
        kg = int(np.asarray(routing.key_group(jnp.asarray([k]), G))[0])
        owner = kg * P // G
        for t in range(P):
            if t != owner:
                assert acc2[t, k] == 0


def test_window_fires_and_sink_receives():
    job = _build_wordcount(parallelism=1, window=5)
    ex = LocalExecutor(job, steps_per_epoch=4)
    seen = []
    # Force time forward by faking the time source.
    times = iter([0, 1, 2, 10, 11, 12, 13])
    ex.time_source.now = lambda: next(times)
    for _ in range(6):
        out = ex.step()
        for vid, batch in out.sinks.items():
            seen += records.to_numpy(records.RecordBatch(
                batch.keys.reshape(-1), batch.values.reshape(-1),
                batch.timestamps.reshape(-1), batch.valid.reshape(-1)))
    # Window [0,5) fired when time jumped to 10. With the depth-1 pipeline,
    # the window had received the batches emitted at times 0 and 1 (the
    # time-2 batch arrives at time 10 and joins the *new* window).
    assert seen, "window never fired into sink"
    total = sum(v for _, v, _ in seen)
    assert total == 2 * BATCH
    assert all(ts == 5 for _, _, ts in seen)  # window end timestamp


def test_determinants_logged_per_superstep():
    job = _build_wordcount(parallelism=2)
    ex = LocalExecutor(job, steps_per_epoch=4)
    n = 3
    for _ in range(n):
        ex.step()
    sizes = ex.log_sizes()
    assert sizes.shape == (job.total_subtasks(),)
    np.testing.assert_array_equal(sizes, np.full(sizes.shape, n * DETS_PER_STEP))
    # Decode one log: tags cycle TIMESTAMP, ORDER, BUFFER_BUILT and the
    # TIMESTAMP payload matches the recorded host time.
    one = jax.tree_util.tree_map(lambda x: x[0], ex.carry.logs)
    buf, count, _ = clog.get_determinants(one, 0, 64)
    rows = np.asarray(buf)[: int(count)]
    dets = det.unpack_batch(rows)
    assert [d.TAG for d in dets[:4]] == [det.TIMESTAMP, det.RNG, det.ORDER,
                                         det.BUFFER_BUILT]
    assert dets[0].timestamp == ex.step_input_history[0][0]
    assert dets[1].value == ex.step_input_history[0][1]
    src_emit = dets[3]
    assert src_emit.num_records == BATCH


def test_epoch_roll_and_truncation():
    job = _build_wordcount(parallelism=1)
    ex = LocalExecutor(job, steps_per_epoch=2)
    ex.run_epoch()          # epoch 0: 2 steps
    ex.run_epoch()          # epoch 1: 2 steps
    assert ex.epoch_id == 2
    sizes = ex.log_sizes()
    np.testing.assert_array_equal(sizes, np.full(sizes.shape,
                                                 4 * DETS_PER_STEP))
    ex.notify_checkpoint_complete(0)   # drop epoch 0 determinants
    sizes = ex.log_sizes()
    np.testing.assert_array_equal(sizes, np.full(sizes.shape,
                                                 2 * DETS_PER_STEP))


def test_scan_epoch_equals_stepwise():
    job = _build_wordcount(parallelism=2)
    ex1 = LocalExecutor(job, steps_per_epoch=4)
    ex2 = LocalExecutor(job, steps_per_epoch=4)
    times = list(range(0, 40, 10))
    ex1.time_source.now = lambda it=iter(times): next(it)
    ex2.time_source.now = lambda it=iter(times): next(it)
    ex1._rng = np.random.RandomState(7)
    ex2._rng = np.random.RandomState(7)
    for _ in range(4):
        ex1.step()
    ex1.run_epoch()   # no steps left; just rolls the epoch marker
    ex2.run_epoch()
    a = jax.device_get(ex1.carry)
    b = jax.device_get(ex2.carry)
    flat_a, _ = jax.tree_util.tree_flatten(a)
    flat_b, _ = jax.tree_util.tree_flatten(b)
    for xa, xb in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
