"""Determinant replication: sharing-depth plan, step-boundary delta pull,
offset dedup, lag catch-up, response merging (reference piggyback +
DeterminantResponseEvent behaviors)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.api.operators import SyntheticSource
from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.causal import log as clog
from clonos_tpu.causal import replication as rep


def _job(depth_chain=3, parallelism=2):
    env = StreamEnvironment(num_key_groups=8)
    s = env.synthetic_source(vocab=10, batch_size=4, parallelism=parallelism)
    for i in range(depth_chain - 2):
        s = s.key_by().reduce(num_keys=10, name=f"op{i}")
    s.sink()
    return env.build()


def test_plan_respects_sharing_depth():
    job = _job(depth_chain=4, parallelism=1)  # 4-vertex chain, p=1
    full = rep.ReplicationPlan.from_job(job, sharing_depth=-1)
    # Full sharing: every downstream vertex holds every upstream log.
    assert (0, 3) in full.pairs and (0, 1) in full.pairs
    d1 = rep.ReplicationPlan.from_job(job, sharing_depth=1)
    assert (0, 1) in d1.pairs and (1, 2) in d1.pairs
    assert (0, 2) not in d1.pairs and (0, 3) not in d1.pairs
    # Upstream never holds downstream logs.
    assert (1, 0) not in full.pairs


def test_replication_pull_and_dedup():
    # 2 owner logs, 3 replicas (r0,r1 of owner0; r2 of owner1).
    owners = jax.vmap(lambda _: clog.create(64, 8))(jnp.arange(2))
    rows = jnp.arange(2 * 5 * 8, dtype=jnp.int32).reshape(2, 5, 8)
    owners = clog.v_append(owners, rows, jnp.asarray([5, 3]))
    replicas = jax.vmap(lambda _: clog.create(64, 8))(jnp.arange(3))
    owner_idx = jnp.asarray([0, 0, 1], jnp.int32)
    replicas, lag = rep.replicate_step(replicas, owners, owner_idx, max_delta=8)
    np.testing.assert_array_equal(np.asarray(lag), [0, 0, 0])
    np.testing.assert_array_equal(np.asarray(replicas.head), [5, 5, 3])
    # Replica contents equal owner prefix.
    buf, count, _ = clog.v_slice_from(replicas, jnp.zeros(3, jnp.int32), 8)
    np.testing.assert_array_equal(np.asarray(buf[0][:5]), np.asarray(rows[0]))
    np.testing.assert_array_equal(np.asarray(buf[2][:3]),
                                  np.asarray(rows[1][:3]))
    # Second round with no new owner rows: no-op (dedup by offset).
    replicas2, lag2 = rep.replicate_step(replicas, owners, owner_idx, 8)
    np.testing.assert_array_equal(np.asarray(replicas2.head), [5, 5, 3])


def test_replication_lag_catches_up():
    owners = jax.vmap(lambda _: clog.create(64, 8))(jnp.arange(1))
    rows = jnp.ones((1, 10, 8), jnp.int32)
    owners = clog.v_append(owners, rows, jnp.asarray([10]))
    replicas = jax.vmap(lambda _: clog.create(64, 8))(jnp.arange(1))
    owner_idx = jnp.asarray([0], jnp.int32)
    replicas, lag = rep.replicate_step(replicas, owners, owner_idx, max_delta=4)
    assert int(lag[0]) == 6
    replicas, lag = rep.replicate_step(replicas, owners, owner_idx, max_delta=4)
    assert int(lag[0]) == 2
    replicas, lag = rep.replicate_step(replicas, owners, owner_idx, max_delta=4)
    assert int(lag[0]) == 0
    assert int(replicas.head[0]) == 10


def test_merge_determinant_responses():
    full = np.arange(6 * 8, dtype=np.int32).reshape(6, 8)
    a = (full[:4], 0)     # holder saw rows [0,4)
    b = (full[2:6], 2)    # holder saw rows [2,6)
    rows, start = rep.merge_determinant_responses([a, b])
    assert start == 0
    np.testing.assert_array_equal(rows, full)
    # Divergent overlap is a protocol violation.
    bad = (full[2:6] + 1, 2)
    with pytest.raises(ValueError):
        rep.merge_determinant_responses([a, bad])


def test_truncated_owner_slice_serves_from_tail():
    # After checkpoint truncation the owner only serves retained rows;
    # replica that is already past the tail merges cleanly.
    owners = jax.vmap(lambda _: clog.create(16, 8))(jnp.arange(1))
    replicas = jax.vmap(lambda _: clog.create(16, 8))(jnp.arange(1))
    owner_idx = jnp.asarray([0], jnp.int32)
    owners = clog.v_start_epoch(owners, 0)
    replicas = rep.sync_replica_epochs(replicas, 0)
    owners = clog.v_append(owners, jnp.ones((1, 4, 8), jnp.int32),
                           jnp.asarray([4]))
    # Epoch fence: catch-up replication, then both sides record epoch 1.
    replicas, lag = rep.replicate_step(replicas, owners, owner_idx, 16)
    assert int(lag[0]) == 0
    owners = clog.v_start_epoch(owners, 1)
    replicas = rep.sync_replica_epochs(replicas, 1)
    owners = clog.v_append(owners, 2 * jnp.ones((1, 4, 8), jnp.int32),
                           jnp.asarray([4]))
    replicas, _ = rep.replicate_step(replicas, owners, owner_idx, 16)
    # Checkpoint 0 completes: truncate both sides.
    owners = clog.v_truncate(owners, 0)
    replicas = clog.v_truncate(replicas, 0)
    replicas, lag = rep.replicate_step(replicas, owners, owner_idx, 16)
    assert int(lag[0]) == 0
    assert int(replicas.head[0]) == 8 and int(replicas.tail[0]) == 4
    # Retained replica rows equal the owner's epoch-1 rows.
    buf, count, start = clog.v_slice_from(replicas, replicas.tail, 8)
    assert int(count[0]) == 4 and int(start[0]) == 4
    np.testing.assert_array_equal(np.asarray(buf[0][:4]),
                                  2 * np.ones((4, 8), np.int32))
