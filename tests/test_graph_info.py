"""Vertex graph info: distance computation and sharing-depth masks
(reference CausalGraphUtils.computeDistances:106,
JobCausalLogImpl.respondToDeterminantRequest:192 depth cut)."""

import numpy as np

from clonos_tpu.graph.vertex_info import (
    UNREACHABLE, CausalLogID, VertexGraphInformation, compute_distances)


def diamond():
    # 0 -> 1 -> 3, 0 -> 2 -> 3
    return 4, [(0, 1), (0, 2), (1, 3), (2, 3)]


def test_distances_diamond():
    n, edges = diamond()
    d = compute_distances(n, edges)
    assert d[0, 3] == 2 and d[0, 1] == 1 and d[1, 3] == 1
    assert d[3, 0] == UNREACHABLE  # directed
    assert d[1, 2] == UNREACHABLE
    assert (np.diag(d) == 0).all()


def test_upstream_downstream():
    n, edges = diamond()
    info = VertexGraphInformation(vertex=3, num_vertices=n,
                                  edges=tuple(edges), parallelism=(1, 2, 2, 1))
    assert info.upstream == (1, 2)
    assert info.downstream == ()


def test_logs_to_replicate_depth():
    n, edges = diamond()
    v3 = VertexGraphInformation(3, n, tuple(edges), (1, 1, 1, 1))
    assert v3.logs_to_replicate(sharing_depth=1) == frozenset({1, 2})
    assert v3.logs_to_replicate(sharing_depth=2) == frozenset({0, 1, 2})
    assert v3.logs_to_replicate(sharing_depth=-1) == frozenset({0, 1, 2})
    v1 = VertexGraphInformation(1, n, tuple(edges), (1, 1, 1, 1))
    assert v1.logs_to_replicate(sharing_depth=1) == frozenset({0})


def test_sharing_mask():
    n, edges = diamond()
    info = VertexGraphInformation(0, n, tuple(edges), (1, 1, 1, 1))
    m1 = info.sharing_mask(sharing_depth=1)
    # owner 0 replicated at holders 1,2 (distance 1) but not 3 (distance 2)
    assert m1[0, 1] and m1[0, 2] and not m1[0, 3]
    assert m1[0, 0] and m1[3, 3]  # self always
    mfull = info.sharing_mask(sharing_depth=-1)
    assert mfull[0, 3]
    assert not mfull[3, 0]  # never replicate upstream


def test_chain_depth_cut():
    # 0 -> 1 -> 2 -> 3 -> 4
    n, edges = 5, [(i, i + 1) for i in range(4)]
    info = VertexGraphInformation(4, n, tuple(edges), (1,) * 5)
    assert info.logs_to_replicate(2) == frozenset({2, 3})
    assert info.logs_to_replicate(-1) == frozenset({0, 1, 2, 3})


def test_causal_log_id():
    main = CausalLogID(vertex=2, subtask=1)
    assert main.is_main_thread()
    sp = main.for_subpartition(3)
    assert not sp.is_main_thread() and sp.subpartition == 3
    assert sorted([sp, main]) == [main, sp]
