"""Two-input operators (union, interval join), host-fed sources, timers —
and their recovery paths (BASELINE configs #4/#5 shapes)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from clonos_tpu.api import records
from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.api.feeds import ListFeedReader
from clonos_tpu.api.operators import OpContext, UnionOperator, \
    IntervalJoinOperator
from clonos_tpu.causal import determinant as det
from clonos_tpu.runtime.cluster import ClusterRunner
from clonos_tpu.runtime.timers import ProcessingTimeService
from clonos_tpu.causal.services import ReplayFeed


def _ctx(p, time=0):
    return OpContext(time=jnp.asarray(time, jnp.int32),
                     epoch=jnp.zeros((), jnp.int32),
                     step=jnp.zeros((), jnp.int32),
                     rng_bits=jnp.zeros((), jnp.int32),
                     subtask=jnp.arange(p, dtype=jnp.int32))


def _batch(rows, cap, p=1):
    keys = np.zeros((p, cap), np.int32)
    vals = np.zeros((p, cap), np.int32)
    ts = np.zeros((p, cap), np.int32)
    valid = np.zeros((p, cap), bool)
    for i, r in enumerate(rows):
        for j, (k, v, t) in enumerate(r):
            keys[i, j], vals[i, j], ts[i, j], valid[i, j] = k, v, t, True
    return records.RecordBatch(jnp.asarray(keys), jnp.asarray(vals),
                               jnp.asarray(ts), jnp.asarray(valid))


def test_union_merges_and_compacts():
    op = UnionOperator(capacity=4)
    left = _batch([[(1, 10, 0)]], cap=3)
    right = _batch([[(2, 20, 0), (3, 30, 0)]], cap=3)
    _, out = op.process2((), left, right, _ctx(1))
    got = records.to_numpy(jax.tree_util.tree_map(lambda x: x[0], out))
    assert got == [(1, 10, 0), (2, 20, 0), (3, 30, 0)]


def test_interval_join_matches_within_interval():
    op = IntervalJoinOperator(num_keys=8, window=4, interval=5, capacity=8)
    st = op.init_state(1)
    # Buffer left records at t=0 and t=10 for key 2.
    left = _batch([[(2, 100, 0), (2, 200, 10)]], cap=2)
    right = _batch([[]], cap=2)
    st, out = op.process2(st, left, right, _ctx(1))
    assert int(out.valid.sum()) == 0
    # Right record at t=8 joins only the t=10 left record (|8-0| > 5).
    left2 = _batch([[]], cap=2)
    right2 = _batch([[(2, 1, 8)]], cap=2)
    st, out2 = op.process2(st, left2, right2, _ctx(1))
    got = records.to_numpy(jax.tree_util.tree_map(lambda x: x[0], out2))
    assert got == [(2, 201, 8)]   # 200 + 1 at right ts
    # A different key joins nothing.
    right3 = _batch([[(3, 1, 8)]], cap=2)
    st, out3 = op.process2(st, left2, right3, _ctx(1))
    assert int(out3.valid.sum()) == 0


def _join_job(parallelism=2):
    env = StreamEnvironment(name="nexmark-ish", num_key_groups=16,
                            default_edge_capacity=32)
    auctions = env.synthetic_source(vocab=7, batch_size=4,
                                    parallelism=parallelism, name="auctions")
    bids = env.synthetic_source(vocab=7, batch_size=4,
                                parallelism=parallelism, name="bids")
    joined = auctions.key_by().join(
        bids.key_by(), num_keys=7, window=8, interval=1 << 30, name="join")
    joined.sink()
    return env.build()


TIMES = list(range(0, 400, 10))


def _drive(r):
    r.executor.time_source.now = lambda it=iter(TIMES): next(it)
    r.run_epoch()
    r.step()
    r.step()
    return r


def _assert_carries_equal(a, b):
    from clonos_tpu.runtime.executor import canonical_carry
    fa = jax.tree_util.tree_leaves(jax.device_get(canonical_carry(a)))
    fb = jax.tree_util.tree_leaves(jax.device_get(canonical_carry(b)))
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def test_join_topology_runs_and_join_subtask_recovers():
    golden = _drive(ClusterRunner(_join_job(), steps_per_epoch=3, seed=5))
    r = _drive(ClusterRunner(_join_job(), steps_per_epoch=3, seed=5))
    # join vertex is id 2; subtask 1 -> flat 4+1=5.
    r.inject_failure([5])
    rep = r.recover()
    assert rep.steps_replayed == 2
    _assert_carries_equal(r.executor.carry, golden.executor.carry)
    golden.step()
    r.step()
    _assert_carries_equal(r.executor.carry, golden.executor.carry)


def _feed_job():
    env = StreamEnvironment(name="kafka-ish", num_key_groups=16,
                            default_edge_capacity=32)
    (env.host_source(batch_size=4, parallelism=2)
        .key_by().window_count(num_keys=9, window_size=1 << 30).sink())
    return env.build()


def _mk_reader():
    parts = [[(k % 9, k) for k in range(s, 200, 2)] for s in range(2)]
    return ListFeedReader(parts, records_per_pull=3)


def test_host_feed_source_and_recovery():
    def drive(r):
        r.executor.time_source.now = lambda it=iter(TIMES): next(it)
        r.executor.register_feed(0, _mk_reader())
        r.run_epoch()
        r.step()
        r.step()
        return r

    golden = drive(ClusterRunner(_feed_job(), steps_per_epoch=3, seed=5))
    r = drive(ClusterRunner(_feed_job(), steps_per_epoch=3, seed=5))
    # Records flowed (3 per pull per subtask per step).
    assert int(np.asarray(golden.executor.carry.record_counts)[0]) == 15
    r.inject_failure([0])          # host-source subtask 0
    rep = r.recover()
    assert rep.steps_replayed == 2
    _assert_carries_equal(r.executor.carry, golden.executor.carry)


def test_timer_service_fires_and_replays():
    logged = []
    fired = []
    svc_ = ProcessingTimeService(logged.append)
    cid = svc_.register_callback(fired.append, callback_id=7)
    svc_.register_timer(fire_time=10, callback_id=cid)
    svc_.register_timer(fire_time=20, callback_id=cid)
    assert svc_.advance(now=5, stamp=1) == 0
    assert svc_.advance(now=15, stamp=2) == 1
    assert fired == [10]
    assert svc_.advance(now=25, stamp=3) == 1
    assert fired == [10, 20]
    assert logged[0] == det.TimerTriggerDeterminant(
        record_count=2, callback_id=7, timestamp=10)
    # Replay: force-fire from the recorded determinants.
    svc2 = ProcessingTimeService(lambda d: None)
    fired2 = []
    svc2.register_callback(fired2.append, callback_id=7)
    svc2.register_timer(10, 7)     # re-registered pending timer is dedup'd
    n = svc2.replay_all(ReplayFeed(list(logged)))
    assert n == 2 and fired2 == [10, 20]
    assert svc2.pending == 0
