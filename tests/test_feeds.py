"""Durable-connector semantics: bounded retention, committed offsets,
and the block-pull fast path (reference flink-connectors Kafka consumer:
offsets in checkpoints, committed on completion, reads below the
topic's retention window fail)."""

import numpy as np
import pytest

from clonos_tpu.api.feeds import (FeedReader, ListFeedReader,
                                  RetentionExpiredError)


def _mk(n=64, parts=2, seed=0):
    rng = np.random.RandomState(seed)
    return [[(int(k), int(v)) for k, v in
             zip(rng.randint(0, 100, n), rng.randint(0, 100, n))]
            for _ in range(parts)]


class _LoopReader(FeedReader):
    """Reference semantics: the base-class pull_block loop over pull."""

    def __init__(self, parts, rpp):
        self._inner = ListFeedReader(parts, records_per_pull=rpp)

    def pull(self, subtask, max_n):
        return self._inner.pull(subtask, max_n)


@pytest.mark.parametrize("rpp,b,k", [(1 << 30, 8, 4), (3, 8, 4),
                                     (8, 8, 16), (5, 7, 9)])
def test_pull_block_matches_pull_loop(rpp, b, k):
    parts = _mk(n=50)
    fast = ListFeedReader(parts, records_per_pull=rpp)
    slow = _LoopReader(parts, rpp)
    for _ in range(3):                       # cross partition exhaustion
        for s in range(2):
            fk, fv, fc = fast.pull_block(s, b, k)
            sk, sv, sc = slow.pull_block(s, b, k)
            np.testing.assert_array_equal(fc, sc)
            np.testing.assert_array_equal(fk, sk)
            np.testing.assert_array_equal(fv, sv)


def test_read_at_roundtrip_and_exhaustion():
    parts = _mk(n=20)
    r = ListFeedReader(parts)
    ks, vs = r.pull(0, 12)
    k2, v2 = r.read_at(0, 3, 6)
    assert (k2, v2) == (ks[3:9], vs[3:9])
    with pytest.raises(ValueError):
        r.read_at(0, 15, 10)                 # past the end


def test_retention_expires_consumed_history():
    r = ListFeedReader(_mk(n=40), retention=8)
    r.pull(0, 30)
    # Within the window: replayable.
    assert len(r.read_at(0, 25, 5)[0]) == 5
    # Below the floor (30 - 8 = 22): loud, typed failure.
    with pytest.raises(RetentionExpiredError):
        r.read_at(0, 10, 5)
    # Unconsumed future records are never dropped by retention.
    ks, _ = r.pull(0, 10)
    assert len(ks) == 10


def test_commit_trims_and_is_bounded_by_cursor():
    r = ListFeedReader(_mk(n=40))
    r.pull(0, 10)
    r.pull(1, 4)
    # Commit offset 20 on part 1 while only 4 consumed: floor caps at 4.
    r.notify_checkpoint_complete([8, 20])
    with pytest.raises(RetentionExpiredError):
        r.read_at(0, 7, 2)
    assert len(r.read_at(0, 8, 2)[0]) == 2
    assert len(r.read_at(1, 4, 3)[0]) == 3


def test_runner_commits_offsets_on_checkpoint_complete():
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    P, B, SPE = 2, 4, 4
    env = StreamEnvironment(name="feeds-commit", num_key_groups=8,
                            default_edge_capacity=64)
    (env.host_source(batch_size=B, parallelism=P)
        .key_by().reduce(num_keys=13, parallelism=P).sink(parallelism=P))
    job = env.build()
    reader = ListFeedReader(_mk(n=4 * SPE * B, parts=P, seed=3),
                            retention=1 << 20)
    runner = ClusterRunner(job, steps_per_epoch=SPE, log_capacity=256,
                           max_epochs=8, inflight_ring_steps=16, seed=11)
    runner.executor.register_feed(0, reader)
    runner.run_epoch(complete_checkpoint=True)
    # The completed checkpoint captured offsets at the fence; the reader's
    # retention floor advanced exactly to them.
    assert reader._base == [SPE * B] * P
    # Recovery after the commit still works: it re-reads only from the
    # latest completed checkpoint, which is at/above the floor.
    runner.run_epoch(complete_checkpoint=False)
    runner.inject_failure([1])
    report = runner.recover()
    assert report.records_replayed > 0


def test_recovery_past_expired_offsets_fails_loudly():
    from clonos_tpu.api.environment import StreamEnvironment
    from clonos_tpu.runtime.cluster import ClusterRunner

    P, B, SPE = 2, 4, 4
    env = StreamEnvironment(name="feeds-expired", num_key_groups=8,
                            default_edge_capacity=64)
    (env.host_source(batch_size=B, parallelism=P)
        .key_by().reduce(num_keys=13, parallelism=P).sink(parallelism=P))
    job = env.build()
    # Retention far smaller than an epoch of records: the un-checkpointed
    # epoch's history is gone by the time the failure needs it.
    reader = ListFeedReader(_mk(n=4 * SPE * B, parts=P, seed=4),
                            retention=2)
    runner = ClusterRunner(job, steps_per_epoch=SPE, log_capacity=256,
                           max_epochs=8, inflight_ring_steps=16, seed=12)
    runner.executor.register_feed(0, reader)
    runner.run_epoch(complete_checkpoint=True)
    runner.run_epoch(complete_checkpoint=False)
    runner.inject_failure([0])
    with pytest.raises(RetentionExpiredError):
        runner.recover()
