"""Read-path scale-out (runtime/serve.py): replicas tail sealed-epoch
deltas, serve fence-consistent batched reads, and degrade — never
error — under replica loss.

The bit-identity contract is asserted the same way the exactly-once
audit asserts its own: fold the served values into real
:class:`EpochDigest` ledger entries per epoch on both the owner path
and the replica path, then require ``diff_ledgers`` to find nothing.
"""

import threading
import time

import numpy as np
import pytest

from clonos_tpu.api.environment import StreamEnvironment
from clonos_tpu.obs.digest import EpochDigest, diff_ledgers
from clonos_tpu.runtime.cluster import ClusterRunner
from clonos_tpu.runtime.query import QueryRejectedError
from clonos_tpu.runtime.serve import build_serve_tier

VID = 1          # the reduce vertex in the fixture below
NUM_KEYS = 11


def make_runner(seed=3, max_epochs=8):
    env = StreamEnvironment(name="serve", num_key_groups=16,
                            default_edge_capacity=64)
    (env.synthetic_source(vocab=NUM_KEYS, batch_size=8, parallelism=2)
        .key_by().reduce(num_keys=NUM_KEYS, name="r").sink())
    return ClusterRunner(env.build(), steps_per_epoch=4,
                         log_capacity=256, max_epochs=max_epochs,
                         inflight_ring_steps=16, seed=seed)


def served_entry(epoch, values):
    """Ledger entry from one epoch's served values — diff_ledgers-style
    comparison material."""
    d = EpochDigest(int(epoch))
    d.fold("acc", np.asarray(values, np.int64).tobytes(), len(values))
    return d.to_entry()


def test_replica_serves_bit_identical_fence_state():
    """A replica tailing sealed-epoch deltas serves, at every fence,
    byte-for-byte the state the owner serves at the same epoch stamp —
    including across epochs whose checkpoint never completes (the
    delta path, not the restore path, carries freshness)."""
    r = make_runner()
    tier = build_serve_tier(r, VID, n_replicas=1)
    try:
        rep_c = tier.clients[0]
        keys = list(range(NUM_KEYS))
        owner_led, replica_led = [], []
        epochs = []
        for e in range(4):
            # Odd epochs leave the checkpoint pending: only the sealed
            # delta can keep the replica fresh there.
            r.run_epoch(complete_checkpoint=(e % 2 == 0))
            r.drain_fence()
            ro = tier.owner_client.query_batch(VID, keys)
            rr = rep_c.query_batch(VID, keys)
            assert rr["epoch"] == ro["epoch"], \
                "replica and owner must stamp the same fence"
            assert rr["staleness_epochs"] == 0
            assert rr["served_by"] == "replica-0"
            assert rr["subtasks"] == ro["subtasks"], \
                "one key-group assignment across every read path"
            epochs.append(rr["epoch"])
            owner_led.append(served_entry(ro["epoch"], ro["values"]))
            replica_led.append(served_entry(rr["epoch"], rr["values"]))
        assert epochs == sorted(set(epochs)), "fences advance, never tear"
        assert diff_ledgers(owner_led, replica_led) == []
        # Point reads go through the same fused gather: same values.
        for k in (0, 5, NUM_KEYS - 1):
            out = rep_c.query(VID, k)
            assert out["value"] == rr["values"][k]
            assert out["epoch"] == rr["epoch"]
        rep = tier.replicas[0]
        assert rep.tailable
        assert rep.applied_epochs >= 2, "odd epochs arrived via deltas"
    finally:
        tier.close()


def test_reads_rejected_before_first_seal():
    """No fence, no consistency point: both the owner endpoint and the
    replica endpoint refuse reads (typed rejection, routable) until the
    first epoch seals — then serve."""
    r = make_runner()
    tier = build_serve_tier(r, VID, n_replicas=1)
    try:
        with pytest.raises(QueryRejectedError):
            tier.owner_client.query(VID, 0)
        with pytest.raises(QueryRejectedError):
            tier.clients[0].query(VID, 0)
        r.run_epoch(complete_checkpoint=True)
        r.drain_fence()
        assert tier.owner_client.query(VID, 0)["epoch"] >= 0
        assert tier.clients[0].query(VID, 0)["epoch"] >= 0
        # Application errors are NOT rejections: out-of-range key is a
        # KeyError on both paths (the router must not reroute those).
        with pytest.raises(KeyError):
            tier.owner_client.query(VID, NUM_KEYS + 500)
        with pytest.raises(KeyError):
            tier.clients[0].query(VID, NUM_KEYS + 500)
    finally:
        tier.close()


def test_replica_kill_reroutes_then_revives():
    """The acceptance chaos cycle, in miniature: kill a replica mid-run
    and every read still answers (rerouted to the owner, counted);
    staleness spikes while dead; the next fence revives the replica
    from the standby pool and staleness recovers to zero."""
    r = make_runner()
    tier = build_serve_tier(r, VID, n_replicas=2, staleness_bound=2)
    try:
        for _ in range(2):
            r.run_epoch(complete_checkpoint=True)
            r.drain_fence()
        router = tier.router
        # a key whose group routes to replica 0
        k0 = next(k for k in range(NUM_KEYS)
                  if router.replica_for_group(router.key_group(k)) == 0)
        assert router.query(VID, k0)["served_by"] == "replica-0"
        owner_vals = tier.owner_client.query_batch(
            VID, list(range(NUM_KEYS)))["values"]

        tier.kill_replica(0)
        assert tier.staleness()[0] >= 1, "dead replica is behind every seal"
        time.sleep(0.06)            # let the router's status cache expire
        reroutes0 = router.reroutes
        out = router.query(VID, k0)  # no exception: degradation, not error
        assert out.get("served_by", "owner") == "owner"
        assert out["value"] == owner_vals[k0]
        assert router.reroutes > reroutes0
        batch = router.query_batch(VID, list(range(NUM_KEYS)))
        assert batch["values"] == owner_vals

        r.run_epoch(complete_checkpoint=True)   # next fence: revival
        r.drain_fence()
        rep = tier.replicas[0]
        assert rep.alive and rep.revivals == 1
        assert tier.staleness()[0] == 0, "staleness recovered"
        time.sleep(0.06)
        assert router.query(VID, k0)["served_by"] == "replica-0"
    finally:
        tier.close()


def test_endpoint_coalesces_reads_into_single_dispatches():
    """The batching win's mechanism: a wire batch of N keys costs ONE
    device dispatch, and concurrent point lookups coalesce (dispatches
    strictly fewer than requests under contention is not asserted —
    only the invariant that they never exceed them)."""
    r = make_runner()
    tier = build_serve_tier(r, VID, n_replicas=1)
    try:
        r.run_epoch(complete_checkpoint=True)
        r.drain_fence()
        ep = tier.endpoints[0]
        rep_c = tier.clients[0]
        rep_c.query(VID, 0)                       # warm the gather
        d0, k0 = ep.dispatches, ep.keys_served
        keys = [k % NUM_KEYS for k in range(100)]
        out = rep_c.query_batch(VID, keys)
        assert ep.dispatches == d0 + 1, "one fused gather for the batch"
        assert ep.keys_served == k0 + len(keys)
        acc = np.asarray(r.executor.vertex_state(VID)["acc"])
        for k, v, s in zip(keys, out["values"], out["subtasks"]):
            assert v == int(acc[s, k])
        # Concurrency smoke: parallel point readers — ONE connection
        # each, like real clients (a single client socket is not a
        # concurrency primitive) — all answer correctly and never
        # out-dispatch their request count.
        from clonos_tpu.runtime.serve import ReplicaStateClient
        d1 = ep.dispatches
        results = {}

        def read(k):
            c = ReplicaStateClient(ep.address)
            try:
                results[k] = c.query(VID, k)["value"]
            finally:
                c.close()

        threads = [threading.Thread(target=read, args=(k,))
                   for k in range(NUM_KEYS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {k: int(acc[:, k].sum())
                           for k in range(NUM_KEYS)}
        assert ep.dispatches - d1 <= NUM_KEYS
    finally:
        tier.close()


def test_serve_window_lint_rule():
    """Satellite: the overlap-window lint family covers the batched
    read path — a blocking host sync inside the serve window is flagged,
    the production dispatch region is clean."""
    from clonos_tpu.lint.core import FileContext
    from clonos_tpu.lint.overlapwindow import ServeWindowSyncRule

    rule = ServeWindowSyncRule()
    bad = (
        "import numpy as np\n"
        "def dispatch(fn, acc, keys):\n"
        "    # clonos: serve-window-begin\n"
        "    vals, subs, kgs = fn(acc, keys)\n"
        "    host = np.asarray(vals)\n"
        "    ready = vals.block_until_ready()\n"
        "    # clonos: serve-window-end\n"
        "    return host, ready\n"
    )
    found = rule.check(FileContext("fake.py", bad))
    assert sorted(f.line for f in found) == [5, 6]
    assert all(f.rule == "serve-window" for f in found)

    ok = bad.replace("    host = np.asarray(vals)\n", "") \
            .replace("    ready = vals.block_until_ready()\n",
                     "    ready = vals\n") \
            .replace("return host, ready", "return np.asarray(ready)")
    assert rule.check(FileContext("fake.py", ok)) == []

    torn = bad.replace("    # clonos: serve-window-end\n", "")
    msgs = [f.message for f in rule.check(FileContext("fake.py", torn))]
    assert any("unbalanced" in m for m in msgs)

    # The production dispatch region must carry the markers AND pass.
    import clonos_tpu.runtime.serve as serve_mod
    path = serve_mod.__file__
    src = open(path).read()
    assert "clonos: serve-window-begin" in src
    assert rule.check(FileContext(path, src)) == []


def test_serve_tier_rehomes_across_live_recut(tmp_path):
    """Elastic re-cut under a live read tier: while the job is re-cut
    2->4 keyed workers (ClusterRunner.rescale_live), reads in the
    handoff window keep answering the last fence — reroute/degrade,
    never a client-visible error — and after ``tier.rehome(new)`` the
    replica re-adopts in the NEW shape and serves the next fences with
    the owner's exact values and epoch stamps."""
    def recut_job(keyed_par):
        env = StreamEnvironment(name=f"serve-recut-{keyed_par}",
                                num_key_groups=16,
                                default_edge_capacity=64)
        (env.synthetic_source(vocab=NUM_KEYS, batch_size=8,
                              parallelism=2)
            .key_by().reduce(num_keys=NUM_KEYS, parallelism=keyed_par,
                             name="r")
            .key_by().sink(parallelism=2))
        return env.build()

    kw = dict(steps_per_epoch=4, log_capacity=256, max_epochs=8,
              inflight_ring_steps=16, seed=3)
    r = ClusterRunner(recut_job(2), checkpoint_dir=str(tmp_path), **kw)
    tier = build_serve_tier(r, VID, n_replicas=1)
    try:
        keys = list(range(NUM_KEYS))
        r.run_epoch(complete_checkpoint=True)
        r.drain_fence()
        before = tier.clients[0].query_batch(VID, keys)

        r2, stats = r.rescale_live(recut_job(4),
                                   checkpoint_dir=str(tmp_path), **kw)
        assert stats["transitions"][-1][0] == "redirect"

        # handoff window: the tier still points at the fenced-off
        # incarnation — reads must answer the last fence, not error
        mid = tier.clients[0].query_batch(VID, keys)
        assert mid["epoch"] == before["epoch"]
        assert mid["values"] == before["values"]

        tier.rehome(r2)
        # the replica re-adopted from the new-shape restore point the
        # re-cut fenced at the same checkpoint id: same fence, served
        again = tier.clients[0].query_batch(VID, keys)
        assert again["epoch"] == before["epoch"]
        assert again["values"] == before["values"]

        # new fences under the new cut: replica matches the owner
        # bit for bit and the ownership map is the 4-wide one
        r2.run_epoch(complete_checkpoint=True)
        r2.drain_fence()
        after = tier.clients[0].query_batch(VID, keys)
        owner = tier.owner_client.query_batch(VID, keys)
        assert after["epoch"] > before["epoch"]
        assert after["epoch"] == owner["epoch"]
        assert after["values"] == owner["values"]
        assert after["subtasks"] == owner["subtasks"]
        assert max(owner["subtasks"]) > 1, "4-wide ownership visible"
        assert after["staleness_epochs"] == 0
    finally:
        tier.close()
