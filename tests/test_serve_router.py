"""Key-group routing policy for the read tier (runtime/serve.py).

Unit tests against FAKE endpoints — the router is duck-typed exactly so
the policy (key -> key group -> replica, staleness bound, reroute on
liveness failure) is testable without a cluster, a transport, or a
device. The one cluster-free device check here is host/device routing
agreement: the jitted gather must assign owners byte-for-byte like the
host twin every other read path uses.
"""

import socket
import threading
import time

import numpy as np
import pytest

from clonos_tpu.runtime.query import (QueryRejectedError,
                                      QueryTimeoutError,
                                      QueryableStateClient,
                                      owner_subtask_np)
from clonos_tpu.runtime.serve import ServeRouter, _bucket, _gather_fn

G = 64  # key groups


class FakeEndpoint:
    """Duck-typed endpoint: records traffic, serves value = key * 10,
    and fails on demand — status liveness and query liveness are
    separate knobs because a replica can probe healthy yet time out on
    the read itself."""

    def __init__(self, name, epoch=5, staleness=0, alive=True,
                 status_exc=None, query_exc=None):
        self.name = name
        self.epoch = epoch
        self.staleness = staleness
        self.alive = alive
        self.status_exc = status_exc
        self.query_exc = query_exc
        self.queried = []

    def status(self):
        if self.status_exc is not None:
            raise self.status_exc
        return {"epoch": self.epoch,
                "staleness_epochs": self.staleness, "alive": self.alive}

    def query(self, vertex, key, state="acc"):
        if self.query_exc is not None:
            raise self.query_exc
        self.queried.append(key)
        return {"value": key * 10, "epoch": self.epoch,
                "staleness_epochs": self.staleness,
                "served_by": self.name}

    def query_batch(self, vertex, keys, state="acc"):
        if self.query_exc is not None:
            raise self.query_exc
        self.queried.extend(keys)
        return {"values": [k * 10 for k in keys], "epoch": self.epoch,
                "staleness_epochs": self.staleness,
                "served_by": self.name}


def make_router(replicas, staleness_bound=2):
    owner = FakeEndpoint("owner", epoch=5, staleness=0)
    # ttl=0 => every routing decision re-probes; no cache staleness in
    # the tests themselves.
    return owner, ServeRouter(owner, replicas, num_key_groups=G,
                              staleness_bound=staleness_bound,
                              status_ttl_s=0.0)


# --- owner assignment --------------------------------------------------


def test_every_key_exactly_one_owner():
    """The host key->owner map is total, deterministic, and in range —
    each key lands on exactly one subtask, twice in a row."""
    keys = np.arange(997)
    kg1, sub1 = owner_subtask_np(keys, 8, G)
    kg2, sub2 = owner_subtask_np(keys, 8, G)
    assert np.array_equal(kg1, kg2) and np.array_equal(sub1, sub2)
    assert kg1.shape == sub1.shape == keys.shape
    assert kg1.min() >= 0 and kg1.max() < G
    assert sub1.min() >= 0 and sub1.max() < 8
    # ownership is a pure function of the key group: no key group maps
    # to two subtasks.
    owners_per_group = {}
    for kg, sub in zip(kg1.tolist(), sub1.tolist()):
        assert owners_per_group.setdefault(kg, sub) == sub


def test_device_gather_agrees_with_host_routing():
    """The jitted serve gather's (key_group, subtask) must equal the
    host twin byte-for-byte — replicas and the exchange share one
    assignment."""
    P, K = 4, 101
    keys = np.arange(K, dtype=np.int32)
    acc = np.arange(P * K, dtype=np.float32).reshape(P, K)
    vals_d, subs_d, kgs_d = _gather_fn(P, G)(acc, keys)
    kg_h, sub_h = owner_subtask_np(keys, P, G)
    assert np.array_equal(np.asarray(kgs_d, np.int64), kg_h)
    assert np.array_equal(np.asarray(subs_d, np.int64), sub_h)
    assert np.array_equal(np.asarray(vals_d), acc[sub_h, keys])


def test_bucket_padding_is_pow2_bounded():
    assert _bucket(1) == 64 and _bucket(64) == 64
    assert _bucket(65) == 128 and _bucket(4096) == 4096


# --- routing policy ----------------------------------------------------


def test_router_prefers_fresh_replica():
    reps = [FakeEndpoint("replica-0"), FakeEndpoint("replica-1")]
    owner, router = make_router(reps)
    for key in range(40):
        out = router.query(0, key)
        assert out["value"] == key * 10
        i = router.key_group(key) % 2
        assert out["served_by"] == f"replica-{i}"
    assert router.replica_reads == 40 and router.owner_reads == 0
    assert router.reroutes == 0 and not owner.queried


def test_router_skips_stale_replica_for_owner():
    """A replica past the staleness bound is skipped: the read lands on
    the owner and is counted as a reroute, not an error."""
    stale = FakeEndpoint("replica-0", staleness=5)
    owner, router = make_router([stale], staleness_bound=2)
    out = router.query(0, 7)
    assert out["served_by"] == "owner" and out["value"] == 70
    assert router.reroutes == 1 and router.owner_reads == 1
    assert not stale.queried
    # at the bound is still usable — the bound is inclusive.
    stale.staleness = 2
    assert router.query(0, 7)["served_by"] == "replica-0"


def test_router_reroutes_on_dead_or_failing_replica():
    """Liveness failures (dead status, rejection, timeout, transport)
    reroute to the owner with zero client-visible exceptions."""
    for bad in (
        FakeEndpoint("r", alive=False),
        FakeEndpoint("r", status_exc=QueryTimeoutError(("h", 1), 3, 0.1)),
        FakeEndpoint("r", query_exc=QueryRejectedError("replica dead")),
        FakeEndpoint("r", query_exc=OSError("connection reset")),
    ):
        owner, router = make_router([bad])
        out = router.query(0, 3)
        assert out["served_by"] == "owner" and out["value"] == 30
        assert router.reroutes == 1 and router.owner_reads == 1
        assert router.reads == 1


def test_router_with_no_replicas_serves_from_owner():
    owner, router = make_router([])
    out = router.query(0, 11)
    assert out["served_by"] == "owner"
    # owner-only is the configured topology, not a degradation.
    assert router.reroutes == 0


def test_batch_routing_preserves_order_and_provenance():
    """query_batch groups keys per destination, one wire request per
    group, and reassembles results in input order with per-key
    provenance."""
    stale = FakeEndpoint("replica-0", staleness=9)
    fresh = FakeEndpoint("replica-1")
    owner, router = make_router([stale, fresh], staleness_bound=2)
    keys = list(range(50))
    out = router.query_batch(0, keys)
    assert out["values"] == [k * 10 for k in keys]
    for pos, k in enumerate(keys):
        want = ("replica-1" if router.key_group(k) % 2 == 1
                else "owner")
        assert out["served_by"][pos] == want
    n_stale = sum(1 for k in keys if router.key_group(k) % 2 == 0)
    assert 0 < n_stale < len(keys)  # both destinations exercised
    assert router.reroutes == n_stale
    assert router.owner_reads == n_stale
    assert router.replica_reads == len(keys) - n_stale
    assert not stale.queried
    assert sorted(owner.queried + fresh.queried) == keys


def test_batch_reroutes_midflight_failure():
    """A replica that probes healthy but fails the read itself: its
    whole group falls back to the owner, counted per key."""
    flaky = FakeEndpoint("replica-0",
                         query_exc=QueryTimeoutError(("h", 1), 3, 0.1))
    owner, router = make_router([flaky])
    keys = list(range(16))
    out = router.query_batch(0, keys)
    assert out["values"] == [k * 10 for k in keys]
    assert set(out["served_by"]) == {"owner"}
    assert router.reroutes == len(keys)


def test_status_probe_cache_ttl():
    """Within the TTL the router reuses the cached probe instead of
    doubling every read's round trips."""
    rep = FakeEndpoint("replica-0")
    probes = {"n": 0}
    real = rep.status

    def counting_status():
        probes["n"] += 1
        return real()

    rep.status = counting_status
    owner = FakeEndpoint("owner")
    router = ServeRouter(owner, [rep], num_key_groups=G,
                         staleness_bound=2, status_ttl_s=60.0)
    for key in range(10):
        router.query(0, key)
    assert probes["n"] == 1


# --- client timeout discipline (satellite: typed QueryTimeoutError) ----


def test_query_timeout_typed_and_bounded():
    """Against an endpoint that accepts but never replies, the client
    burns exactly its (timeout x retries) budget and raises the typed
    error — never an indefinite block."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    srv.settimeout(0.1)
    addr = srv.getsockname()
    stop = threading.Event()
    conns = []

    def accept_forever():
        while not stop.is_set():
            try:
                c, _ = srv.accept()
                conns.append(c)
            except socket.timeout:
                continue

    th = threading.Thread(target=accept_forever, daemon=True)
    th.start()
    cli = QueryableStateClient(addr, timeout_s=0.15, retries=1,
                               backoff_s=0.01)
    t0 = time.monotonic()
    with pytest.raises(QueryTimeoutError) as ei:
        cli.query(0, 1)
    elapsed = time.monotonic() - t0
    assert ei.value.attempts == 2          # initial + 1 retry
    assert ei.value.address == tuple(addr)
    assert elapsed < 2.0                   # bounded, not wedged
    assert isinstance(ei.value, TimeoutError)  # typed for except-clauses
    cli.close()
    stop.set()
    th.join(timeout=2.0)
    for c in conns:
        c.close()
    srv.close()
