"""Command-line front end.

Capability analog of the reference's client layer
(flink-clients .../cli/CliFrontend.java:97 — run/info/list actions against
a cluster). The TPU build is single-binary: the CLI builds/loads a job and
drives the in-process ClusterRunner (MiniCluster-style), which is also the
deployment model for one TPU host; multi-host runs launch the same
entrypoint under ``jax.distributed`` (see parallel/distributed.py).

Usage:
    python -m clonos_tpu run <module:function> [--steps N] [--epochs N] ...
    python -m clonos_tpu info <module:function>
    python -m clonos_tpu bench [--jobs N] [--multichip [N]]
    python -m clonos_tpu dryrun [--devices N]
    python -m clonos_tpu dispatcher --lease DIR [--quota TENANT=N ...]
    python -m clonos_tpu submit <module:function> --dispatcher HOST:PORT
    python -m clonos_tpu jobs --dispatcher HOST:PORT
    python -m clonos_tpu audit <checkpoint-dir> [--diff DIR2] [--job ID]
    python -m clonos_tpu dissect [--trials N]
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time


def _load_job(spec: str):
    """Load 'module.path:function' returning a JobGraph."""
    mod_name, _, fn_name = spec.partition(":")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name or "build_job")
    job = fn()
    from clonos_tpu.graph.job_graph import JobGraph
    if not isinstance(job, JobGraph):
        raise TypeError(f"{spec} returned {type(job).__name__}, not JobGraph")
    return job


def _setup_tracer(args, service: str):
    """Opt-in tracing: ``--trace-dir`` installs the process tracer
    writing trace-<service>.jsonl there. Returns the tracer or None."""
    if getattr(args, "trace_dir", None) is None:
        return None
    import os
    from clonos_tpu import obs
    os.makedirs(args.trace_dir, exist_ok=True)
    return obs.configure(service, path=os.path.join(
        args.trace_dir, f"trace-{service}.jsonl"))


def _setup_timeline(args, service: str):
    """Opt-in causal timeline: ``--timeline-dir`` installs the process
    TimelineStore (and an HLC, so every cross-process message carries a
    causal stamp) writing timeline-<service>.jsonl there. Returns the
    store or None."""
    if getattr(args, "timeline_dir", None) is None:
        return None
    import os
    from clonos_tpu.obs import configure_timeline
    os.makedirs(args.timeline_dir, exist_ok=True)
    return configure_timeline(service, path=os.path.join(
        args.timeline_dir, f"timeline-{service}.jsonl"))


def _setup_profile(args) -> None:
    """Opt-in overhead attribution: ``--profile`` installs the process
    profiler BEFORE any runner is built (runners bind the process
    profiler at construction — a slotworker's deployed slices inherit
    it the same way)."""
    if getattr(args, "profile", False):
        from clonos_tpu.obs import configure_profile
        configure_profile()


def _make_history(args):
    """A MetricsHistory per the ``--history-*`` flags (sampled by the
    endpoint it is handed to)."""
    from clonos_tpu.obs import MetricsHistory
    return MetricsHistory(path=getattr(args, "history_file", None),
                          interval_s=args.history_interval,
                          window=args.history_window)


def _add_profile_args(sp) -> None:
    """Shared observability flags for the serving entrypoints."""
    sp.add_argument("--profile", action="store_true",
                    help="attribute fault-tolerance overhead per section "
                         "(overhead.* metrics + overhead.ft-fraction; "
                         "off by default: zero overhead, async dispatch "
                         "preserved)")
    sp.add_argument("--history-interval", type=float, default=2.0,
                    help="metrics-history sampling period for "
                         "/metrics/history.json (seconds)")
    sp.add_argument("--history-window", type=int, default=512,
                    help="samples kept in the metrics-history ring")
    sp.add_argument("--history-file", default=None,
                    help="also persist history samples to this JSONL "
                         "file (ring resumes from its tail on restart)")


def cmd_run(args) -> int:
    from clonos_tpu.runtime.cluster import ClusterRunner

    tracer = _setup_tracer(args, "run")
    _setup_timeline(args, "run")
    _setup_profile(args)
    job = _load_job(args.job)
    runner = ClusterRunner(job, steps_per_epoch=args.steps_per_epoch,
                           checkpoint_dir=args.checkpoint_dir)
    endpoint = None
    if args.metrics_port is not None:
        from clonos_tpu.utils.metrics import MetricsEndpoint
        endpoint = MetricsEndpoint(runner.metrics, port=args.metrics_port,
                                   tracer=tracer,
                                   history=_make_history(args))
        print(f"# metrics: http://{endpoint.address[0]}:"
              f"{endpoint.address[1]}/metrics", file=sys.stderr)
    t0 = time.monotonic()
    try:
        for _ in range(args.epochs):
            runner.run_epoch()
            runner.watchdog.check()
    finally:
        if endpoint is not None:
            endpoint.close()
    dt = time.monotonic() - t0
    snap = runner.metrics.snapshot()
    print(json.dumps({"job": job.name, "epochs": args.epochs,
                      "wall_s": round(dt, 3), "metrics": snap},
                     default=str))
    return 0


def cmd_info(args) -> int:
    job = _load_job(args.job)
    info = {
        "name": job.name,
        "vertices": [
            {"id": v.vertex_id, "name": v.name,
             "operator": type(v.operator).__name__,
             "parallelism": v.parallelism}
            for v in job.vertices],
        "edges": [
            {"src": e.src, "dst": e.dst, "partition": e.partition.value,
             "capacity": e.capacity}
            for e in job.edges],
        "num_key_groups": job.num_key_groups,
        "sharing_depth": job.sharing_depth,
        "total_subtasks": job.total_subtasks(),
        "topological_order": job.topo_order(),
    }
    print(json.dumps(info, indent=2))
    return 0


def cmd_bench(args) -> int:
    import bench
    rc = bench.main(jobs=getattr(args, "jobs", None),
                    multichip=getattr(args, "multichip", None),
                    soak=getattr(args, "soak", None),
                    ablate=getattr(args, "ablate", False),
                    serve=getattr(args, "serve", None),
                    rescale=getattr(args, "rescale", None))
    return int(rc or 0)


def cmd_dryrun(args) -> int:
    import __graft_entry__ as ge
    ge.dryrun_multichip(args.devices)
    return 0


def cmd_worker(args) -> int:
    """TaskExecutor-process entrypoint (reference TaskExecutor.java:422):
    run a job under a remote JobMaster — register + heartbeat, serve the
    determinant logs to standby-host mirrors at every epoch fence, and
    write durable checkpoints the JobMaster can rebuild from after this
    host dies. One JSON status line per epoch on stdout."""
    from clonos_tpu.parallel import distributed
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.remote import (HostLogEndpoint,
                                           TaskExecutorClient)

    _setup_tracer(args, args.executor_id)
    _setup_timeline(args, args.executor_id)
    _setup_profile(args)
    ctx = distributed.initialize(args.coordinator, args.num_processes,
                                 args.process_id)
    job = _load_job(args.job)
    runner = ClusterRunner(job, steps_per_epoch=args.steps_per_epoch,
                           checkpoint_dir=args.checkpoint_dir,
                           seed=args.seed)
    endpoint = HostLogEndpoint(runner.executor, host=args.bind_host)
    host, _, port = args.jm.partition(":")
    tx = TaskExecutorClient(
        args.executor_id, (host, int(port)),
        interval_s=args.heartbeat_interval,
        info={"log_host": args.advertise_host or args.bind_host,
              "log_port": endpoint.address[1],
              "num_subtasks": job.total_subtasks(),
              "checkpoint_dir": args.checkpoint_dir, "job": args.job,
              "process_id": ctx.process_id})
    print(json.dumps({"registered": args.executor_id,
                      "log_port": endpoint.address[1],
                      "subtasks": job.total_subtasks()}), flush=True)
    try:
        for i in range(args.epochs):
            runner.run_epoch(
                complete_checkpoint=(i % args.complete_every == 0))
            # Status BEFORE the endpoint refresh: a mirror can then never
            # hold a fence whose digest was not yet reported (watchers
            # key their cross-process bit-identity checks on these
            # lines; a kill between the two leaves the mirror one fence
            # behind the last report, never ahead).
            print(json.dumps({"epoch": runner.executor.epoch_id,
                              "global_step": runner.global_step,
                              "digest": runner.state_digest()}),
                  flush=True)
            endpoint.refresh()         # fence snapshot for the mirrors
            if args.epoch_sleep:
                time.sleep(args.epoch_sleep)
    finally:
        tx.close()
        endpoint.close()
    return 0


def cmd_slotworker(args) -> int:
    """Slot-pool TaskExecutor entrypoint (runtime/scheduler.py): the
    process advertises slot capacity and runs ONLY the task slices the
    JobMaster deploys onto it — a job spans several of these processes.
    Job spec, runner settings, and recovery state all arrive inside the
    fenced deployment descriptors; this process brings nothing but
    slots. One JSON line per deployment and per (group, epoch)."""
    from clonos_tpu.runtime.scheduler import SliceWorker

    tracer = _setup_tracer(args, args.executor_id)
    _setup_timeline(args, args.executor_id)
    _setup_profile(args)
    host, _, port = args.jm.partition(":")
    worker = SliceWorker(
        args.executor_id, (host, int(port)), lease_path=args.lease,
        slots=args.slots, bind_host=args.bind_host,
        heartbeat_interval=args.heartbeat_interval,
        chaos_step_delay_s=args.chaos_step_delay)
    endpoint = None
    if args.metrics_port is not None:
        from clonos_tpu.utils.metrics import (MetricRegistry,
                                              MetricsEndpoint)
        # The worker's metric view is its per-slice snapshot cache (the
        # same dict its heartbeats piggyback to the JobMaster).
        endpoint = MetricsEndpoint(
            MetricRegistry(), port=args.metrics_port,
            extra=lambda: dict(worker._metrics_cache), tracer=tracer,
            history=_make_history(args))
        print(f"# metrics: http://{endpoint.address[0]}:"
              f"{endpoint.address[1]}/metrics", file=sys.stderr)
    print(json.dumps({"registered": args.executor_id,
                      "deploy_port": worker.endpoint.address[1],
                      "slots": args.slots}), flush=True)
    try:
        worker.run(max_seconds=args.max_seconds,
                   epoch_sleep=args.epoch_sleep)
    finally:
        worker.close()
        if endpoint is not None:
            endpoint.close()
    return 0


def cmd_dispatcher(args) -> int:
    """Multi-tenant dispatcher entrypoint (runtime/dispatcher.py): one
    shared slot pool serving many concurrent jobs. Slot workers point
    their ``--jm`` at the printed jm address; clients submit over the
    printed dispatcher address (``clonos_tpu submit`` / ``jobs``). One
    JSON line with both addresses on startup."""
    from clonos_tpu.runtime.dispatcher import Dispatcher

    _setup_tracer(args, "dispatcher")
    _setup_timeline(args, "dispatcher")
    _setup_profile(args)
    if args.audit:
        from clonos_tpu.obs import configure_audit
        configure_audit(on_divergence=args.audit)
    quotas = {}
    for spec in args.quota or []:
        tenant, _, n = spec.partition("=")
        quotas[tenant] = int(n)
    disp = Dispatcher(
        lease_path=args.lease, checkpoint_root=args.checkpoint_root,
        quotas=quotas, default_quota=args.default_quota,
        runner_kw={"steps_per_epoch": args.steps_per_epoch,
                   "seed": args.seed},
        target_epochs=args.epochs, complete_every=args.complete_every,
        trace_dir=args.trace_dir, host=args.bind_host, port=args.port,
        heartbeat_timeout_s=args.heartbeat_timeout)
    endpoint = None
    if args.metrics_port is not None:
        from clonos_tpu.utils.metrics import (MetricRegistry,
                                              MetricsEndpoint)
        endpoint = MetricsEndpoint(
            MetricRegistry(), port=args.metrics_port,
            extra=disp.metrics_extra, history=_make_history(args))
        print(f"# metrics: http://{endpoint.address[0]}:"
              f"{endpoint.address[1]}/metrics", file=sys.stderr)
    print(json.dumps({"dispatcher": list(disp.address),
                      "jm": list(disp.jm.address)}), flush=True)
    try:
        disp.run(max_seconds=args.max_seconds)
    finally:
        disp.close()
        if endpoint is not None:
            endpoint.close()
    return 0


def cmd_submit(args) -> int:
    """Submit a job to a running dispatcher. Prints the admission
    result ({job_id, state}) or, with ``--wait``, the terminal job
    record; a typed quota rejection prints its error JSON and exits
    1."""
    from clonos_tpu.parallel import transport as tp

    host, _, port = args.dispatcher.partition(":")
    client = tp.ControlClient((host, int(port)))
    cfg = {"tenant": args.tenant, "slots": args.slots,
           "max_concurrent_recoveries": args.max_recoveries}
    if args.workers:
        cfg["workers"] = [w for w in args.workers.split(",") if w]
    req = {"job": args.job, "tenant_config": cfg}
    if args.target_epochs is not None:
        req["target_epochs"] = args.target_epochs
    try:
        rt, resp = client.call(tp.SUBMIT_JOB, tp.pack_json(req))
        body = tp.unpack_json(resp)
        if rt == tp.ERROR:
            print(json.dumps(body))
            return 1
        if args.wait:
            deadline = time.monotonic() + args.timeout
            while time.monotonic() < deadline:
                body = client.call_json(
                    tp.JOB_STATUS, {"job_id": body["job_id"]})
                if body["state"] in ("FINISHED", "FAILED", "CANCELLED"):
                    break
                time.sleep(0.5)
    finally:
        client.close()
    print(json.dumps(body))
    return 1 if body.get("state") == "FAILED" else 0


def cmd_jobs(args) -> int:
    """List a dispatcher's jobs (or cancel one with ``--cancel``)."""
    from clonos_tpu.parallel import transport as tp

    host, _, port = args.dispatcher.partition(":")
    client = tp.ControlClient((host, int(port)))
    try:
        if args.cancel:
            print(json.dumps(client.call_json(
                tp.CANCEL_JOB, {"job_id": args.cancel})))
            return 0
        res = client.call_json(tp.JOB_STATUS, {})
    finally:
        client.close()
    if args.json:
        print(json.dumps(res))
        return 0
    jobs = res.get("jobs", [])
    print(f"{'JOB':<20} {'TENANT':<12} {'STATE':<11} {'SLOTS':>5}  "
          f"PLACEMENTS")
    for j in jobs:
        placements = " ".join(
            f"g{g}={w}" for g, w in sorted(
                (j.get("placements") or {}).items()))
        if j.get("error"):
            placements = (placements + "  " if placements else "") \
                + f"error: {j['error']}"
        print(f"{j['job_id']:<20} {j['tenant']:<12} {j['state']:<11} "
              f"{j['slots']:>5}  {placements}")
    if not jobs:
        print("(no jobs submitted)")
    return 0


def _find_ledgers(root):
    """Ledger files under ``root``: the path itself (file or dir with
    ledger.jsonl), per-group ``g*/ledger.jsonl`` subdirs (slot-pool
    layout), or per-job ``<job_id>/g*/ledger.jsonl`` trees (dispatcher
    layout — every job's artifacts live under ``<root>/<job_id>/``).
    Returns [(label, entries)] sorted by label; dispatcher-layout
    labels carry the job-id prefix (``<job_id>/g0/ledger.jsonl``)."""
    import glob
    import os
    from clonos_tpu.runtime.checkpoint import read_ledger_file

    if os.path.isfile(root):
        return [(os.path.basename(root), read_ledger_file(root))]
    direct = os.path.join(root, "ledger.jsonl")
    if os.path.exists(direct):
        return [("ledger.jsonl", read_ledger_file(direct))]
    out = []
    for pat in (os.path.join(root, "*", "ledger.jsonl"),
                os.path.join(root, "*", "*", "ledger.jsonl")):
        for p in sorted(glob.glob(pat)):
            label = os.path.relpath(p, root)
            out.append((label, read_ledger_file(p)))
    return sorted(out)


def _ledger_job_ids(ledgers):
    """Job ids present in a dispatcher-layout ledger set: the leading
    path component of every ``<job_id>/g*/ledger.jsonl`` label."""
    import os
    jobs = set()
    for label, _ in ledgers:
        parts = label.split(os.sep)
        if len(parts) >= 3:
            jobs.add(parts[0])
    return sorted(jobs)


def cmd_audit(args) -> int:
    """Print or diff a job's epoch audit ledger (``clonos_tpu audit``):
    the per-epoch digests obs/audit.py sealed at each checkpoint
    barrier. ``--diff`` compares against a second run's ledger and
    exits 1 on the first divergence (epoch + channel named). A
    dispatcher root holds MANY jobs' ledgers (``<root>/<job_id>/g*/``);
    ``--job`` selects one (labels lose the job prefix so they line up
    against a single-job run's), and a diff over an ambiguous
    multi-job root exits 2 listing the available job ids."""
    import os
    from clonos_tpu.obs import audit as _audit_mod

    ledgers = _find_ledgers(args.dir)
    if not ledgers:
        if args.report == "json":
            print(json.dumps({"match": False, "groups": {},
                              "problems": [f"no ledger.jsonl under "
                                           f"{args.dir}"]}))
        else:
            print(f"no ledger.jsonl under {args.dir}", file=sys.stderr)
        return 1
    job_ids = _ledger_job_ids(ledgers)
    job = getattr(args, "job", None)
    if job:
        pre = job + os.sep
        picked = [(label[len(pre):], entries)
                  for label, entries in ledgers
                  if label.startswith(pre)]
        if not picked:
            print(f"no ledgers for job {job!r} under {args.dir} "
                  f"(available job ids: "
                  f"{', '.join(job_ids) or 'none'})", file=sys.stderr)
            return 2
        ledgers = picked
    elif args.diff and len(job_ids) > 1:
        print(f"ambiguous: {args.dir} holds ledgers for "
              f"{len(job_ids)} jobs ({', '.join(job_ids)}) — pass "
              f"--job <id> to pick one", file=sys.stderr)
        return 2
    if args.diff:
        other_ledgers = _find_ledgers(args.diff)
        if job:
            pre = job + os.sep
            picked = [(label[len(pre):], entries)
                      for label, entries in other_ledgers
                      if label.startswith(pre)]
            # The compared run may itself be single-job (no prefix);
            # fall through to its raw labels then.
            other_ledgers = picked or other_ledgers
        other = dict(other_ledgers)
        problems = []
        groups = {}
        for label, entries in ledgers:
            # Layout-aware: epochs sealed under the same cut compare
            # bit for bit; across a live re-cut the group-directory
            # mapping compares the partition-invariant channels.
            lines = _audit_mod.diff_ledgers_cross(entries,
                                                  other.get(label, []))
            groups[label] = {"entries": len(entries),
                             "epochs": len({e.get("epoch")
                                            for e in entries}),
                             "problems": lines}
            problems += [f"{label}: {line}" for line in lines]
        if args.report == "json":
            # CI convention: one machine-readable line, exit 0/1.
            print(json.dumps({"match": not problems, "groups": groups,
                              "problems": problems}))
            return 1 if problems else 0
        for line in problems:
            print(line)
        if not problems:
            print(f"ledgers match ({sum(len(e) for _, e in ledgers)} "
                  f"entries)")
        return 1 if problems else 0
    if args.report == "json":
        groups = {label: {"entries": len(entries),
                          "epochs": len({e.get("epoch")
                                         for e in entries})}
                  for label, entries in ledgers}
        print(json.dumps({"match": True, "groups": groups,
                          "problems": []}))
        return 0
    if args.json:
        print(json.dumps({label: entries for label, entries in ledgers},
                         indent=2))
        return 0
    for label, entries in ledgers:
        # last-wins per epoch: a rebuilt runner re-seals replayed epochs
        by_epoch = {e["epoch"]: e for e in entries}
        print(f"# {label} — {len(by_epoch)} epochs "
              f"({len(entries)} entries)")
        for ep in sorted(by_epoch):
            e = by_epoch[ep]
            dets = " ".join(f"{k}={v}" for k, v in
                            sorted((e.get("det_counts") or {}).items()))
            print(f"epoch {ep:>4}  records {e.get('records', 0):>8}  "
                  f"channels {len(e.get('channels') or {}):>3}  "
                  f"combined {e.get('combined', '?')}  {dets}")
    return 0


def _top_rows(snap):
    """Fold a JobMaster ``/metrics.json`` snapshot into per-worker rows.

    Keys arrive flattened as ``worker.<eid>.<metric>`` where ``<metric>``
    is the worker's own snapshot name (e.g.
    ``group.g0.job.bench.audit.epochs-sealed``); suffix-match so the row
    survives arbitrary group/job nesting. Histogram values are the
    flattened ``{count, mean, p50, p99}`` dicts snapshot() emits."""
    workers = {}

    def row(eid):
        return workers.setdefault(eid, {
            "slots": None, "groups": set(), "sealed": 0, "validated": 0,
            "ring": None, "lag": None, "ft": None,
            "spill_host": None, "spill_disk": None, "phases": {}})

    for key, v in snap.items():
        if not key.startswith("worker."):
            continue
        eid, _, rest = key[len("worker."):].partition(".")
        if not eid or not rest:
            continue
        r = row(eid)
        if rest == "slots" and isinstance(v, (int, float)):
            r["slots"] = int(v)
            continue
        if rest.startswith("group."):
            r["groups"].add(rest.split(".", 2)[1])
        elif rest.startswith("job."):
            # multi-tenant prefix: job.<jid>.group.<g>.<metric>
            jparts = rest.split(".")
            if len(jparts) >= 4 and jparts[2] == "group":
                r["groups"].add(f"{jparts[1]}:g{jparts[3]}")
        num = isinstance(v, (int, float)) and not isinstance(v, bool)
        if num and rest.endswith(".audit.epochs-sealed"):
            r["sealed"] += int(v)
        elif num and rest.endswith(".audit.epochs-validated"):
            r["validated"] += int(v)
        elif num and (rest.endswith(".backpressure.inflight-occupancy")
                      or rest.endswith(".causal-log.max-occupancy")):
            r["ring"] = max(r["ring"] or 0.0, float(v))
        elif num and rest.endswith(".recovery.replay-lag-steps"):
            r["lag"] = max(r["lag"] or 0, int(v))
        elif num and rest.endswith(".overhead.ft-fraction"):
            r["ft"] = max(r["ft"] or 0.0, float(v))
        elif num and rest.endswith(".spill.host-epochs"):
            r["spill_host"] = (r["spill_host"] or 0) + int(v)
        elif num and rest.endswith(".spill.disk-epochs"):
            r["spill_disk"] = (r["spill_disk"] or 0) + int(v)
        elif (isinstance(v, dict) and ".recovery." in rest
              and rest.endswith("-ms") and v.get("count")):
            phase = rest.rsplit(".recovery.", 1)[1][:-len("-ms")]
            r["phases"][phase] = float(v.get("p50") or v.get("mean") or 0)
    return workers


def _top_table(snap) -> str:
    """Render one ``clonos_tpu top`` frame from a /metrics.json dict."""
    rows = _top_rows(snap)
    lines = [f"{'WORKER':<18} {'SLOTS':>5} {'GROUPS':>6} {'SEALED':>6} "
             f"{'VALID':>5} {'RING':>6} {'LAG':>5} {'FT%':>7} "
             f"{'SPILL':>7}  RECOVERY p50 ms"]
    for eid in sorted(rows):
        r = rows[eid]
        slots = "-" if r["slots"] is None else str(r["slots"])
        ring = "-" if r["ring"] is None else f"{r['ring']:.2f}"
        lag = "-" if r["lag"] is None else str(r["lag"])
        ft = "-" if r["ft"] is None else f"{r['ft'] * 100:.2f}"
        # tier residency: host-tier / disk-tier sealed epochs held
        # (the spill.* gauges; storage/tiered.py)
        spill = ("-" if r["spill_host"] is None and r["spill_disk"] is None
                 else f"{r['spill_host'] or 0}/{r['spill_disk'] or 0}")
        phases = " ".join(f"{k}={v:.0f}"
                          for k, v in sorted(r["phases"].items()))
        lines.append(f"{eid:<18} {slots:>5} {len(r['groups']):>6} "
                     f"{r['sealed']:>6} {r['validated']:>5} {ring:>6} "
                     f"{lag:>5} {ft:>7} {spill:>7}  {phases}")
    if not rows:
        lines.append("(no worker.* metrics yet)")
    # Per-job section (multi-tenant dispatcher): one row per job id
    # from the cluster.job.<jid>.* rollups remote.cluster_metrics()
    # computes, plus the dispatcher's tenant admission gauges.
    jobs = {}
    for k, v in snap.items():
        if k.startswith("cluster.job."):
            jid, _, metric = k[len("cluster.job."):].partition(".")
            if jid and metric:
                jobs.setdefault(jid, {})[metric] = v

    def _cell(m, name):
        v = m.get(name)
        return "-" if v is None else str(v)

    if jobs:
        lines.append("")
        lines.append(f"{'JOB':<20} {'GROUPS':>6} {'SEALED':>6} "
                     f"{'VALID':>5} {'DIV':>4} {'XONCE':>5}")
        for jid in sorted(jobs):
            m = jobs[jid]
            lines.append(
                f"{jid:<20} {_cell(m, 'groups'):>6} "
                f"{_cell(m, 'audit.epochs-sealed'):>6} "
                f"{_cell(m, 'audit.epochs-validated'):>5} "
                f"{_cell(m, 'audit.divergences'):>4} "
                f"{_cell(m, 'audit.exactly-once-ok'):>5}")
    # Soak status row: the open-loop driver's soak.* gauges (rate vs
    # target, backlog, SLO breaches, fault + audit tallies). Matched by
    # suffix too, so the row survives a worker.<eid> prefix.
    soak = {}
    for k, v in sorted(snap.items()):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k.startswith("soak."):
            soak[k[len("soak."):]] = v
        elif ".soak." in k:
            soak.setdefault(k.rsplit(".soak.", 1)[1], v)
    if soak:
        lines.append("")
        lines.append("soak: " + "  ".join(
            f"{k}={v}" for k, v in sorted(soak.items())))
    # Serve status row: the read tier's serve.* gauges (read QPS, p99
    # read latency, per-replica staleness-epochs, reroutes) — same
    # suffix matching, so the row survives a worker.<eid> prefix on
    # metrics that rode a HEARTBEAT into cluster_metrics().
    serve = {}
    for k, v in sorted(snap.items()):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k.startswith("serve."):
            serve[k[len("serve."):]] = v
        elif ".serve." in k:
            serve.setdefault(k.rsplit(".serve.", 1)[1], v)
    if serve:
        lines.append("")
        lines.append("serve: " + "  ".join(
            f"{k}={v}" for k, v in sorted(serve.items())))
    # Autoscale status row: the closed-loop controller's autoscale.*
    # gauges (decision/action tallies, cooldown, target vs actual cut)
    # — same suffix matching as soak:/serve:.
    autoscale = {}
    for k, v in sorted(snap.items()):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k.startswith("autoscale."):
            autoscale[k[len("autoscale."):]] = v
        elif ".autoscale." in k:
            autoscale.setdefault(k.rsplit(".autoscale.", 1)[1], v)
    if autoscale:
        lines.append("")
        lines.append("autoscale: " + "  ".join(
            f"{k}={v}" for k, v in sorted(autoscale.items())))
    # Health status row: the gray-failure detector's cluster.health.*
    # gauges (sustained suspects, events, fences scored) — same suffix
    # matching as soak:/serve:, so the row survives any prefix.
    health = {}
    for k, v in sorted(snap.items()):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k.startswith("cluster.health."):
            health[k[len("cluster.health."):]] = v
        elif ".cluster.health." in k:
            health.setdefault(k.rsplit(".cluster.health.", 1)[1], v)
    if health:
        lines.append("")
        lines.append("health: " + "  ".join(
            f"{k}={v}" for k, v in sorted(health.items())))
    # Incidents status row: the flight recorder's incident.* gauges
    # (bundles captured, dedup/rate-limit drops, signals seen) — same
    # suffix matching as soak:/serve:/health:.
    incidents = {}
    for k, v in sorted(snap.items()):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k.startswith("incident."):
            incidents[k[len("incident."):]] = v
        elif ".incident." in k:
            incidents.setdefault(k.rsplit(".incident.", 1)[1], v)
    if incidents:
        lines.append("")
        lines.append("incidents: " + "  ".join(
            f"{k}={v}" for k, v in sorted(incidents.items())))
    # Lineage status row: the dye plane's lineage.* gauges (records
    # dyed, observations logged, epochs scanned) — same convention.
    lineage = {}
    for k, v in sorted(snap.items()):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if k.startswith("lineage."):
            lineage[k[len("lineage."):]] = v
        elif ".lineage." in k:
            lineage.setdefault(k.rsplit(".lineage.", 1)[1], v)
    if lineage:
        lines.append("")
        lines.append("lineage: " + "  ".join(
            f"{k}={v}" for k, v in sorted(lineage.items())))
    tenant = {k: v for k, v in sorted(snap.items())
              if (k.startswith("tenant.")
                  or k.startswith("dispatcher."))
              and isinstance(v, (int, float))}
    if tenant:
        lines.append("")
        lines.append("tenants: " + "  ".join(
            f"{k}={v}" for k, v in tenant.items()))
    cluster = {k: v for k, v in sorted(snap.items())
               if k.startswith("cluster.")
               and not k.startswith("cluster.job.")
               and not k.startswith("cluster.health.")
               and isinstance(v, (int, float))}
    if cluster:
        lines.append("")
        lines.append("cluster: " + "  ".join(
            f"{k[len('cluster.'):]}={v}" for k, v in cluster.items()))
    # Trace-ring truncation: a nonzero dropped count means the flight
    # recorder (and /trace) no longer holds the full run.
    dropped = snap.get("trace.dropped-records")
    if isinstance(dropped, (int, float)) and dropped:
        lines.append("")
        lines.append(f"trace: dropped-records={int(dropped)} "
                     f"(flight-recorder ring truncated)")
    return "\n".join(lines)


def cmd_lint(args) -> int:
    """Static determinism lint (``clonos_tpu lint``): check pipeline
    and runtime code against the causal-services contract — the audit
    (``clonos_tpu audit``) proves a replay diverged; this names the
    line that made it diverge, before the job ever runs. Deliberately
    jax-free: it must be runnable from any CI box."""
    from clonos_tpu import lint as _lint

    if args.list_rules:
        for rule in _lint.all_rules():
            print(f"{rule.name:16} {rule.description}")
        return 0
    try:
        result = _lint.run_lint(args.paths, waiver_file=args.waivers,
                                use_waivers=not args.no_waivers,
                                rules=args.rule or None)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    if args.report == "json":
        # CI convention: one machine-readable line, exit 0/1.
        print(_lint.format_json(result))
    else:
        print(_lint.format_text(result, verbose=args.verbose))
    return result.exit_code()


def cmd_analyze(args) -> int:
    """Whole-program static analysis (``clonos_tpu analyze``): the
    interprocedural passes the per-file lint cannot run — nondet-escape
    propagation to step functions, the whole-repo lock-order cycle
    check, the thread-root race detector, and the FT census + static
    cost model (analysis/). Same waiver file, same ``--report json``
    one-liner, same 0/1 exit convention as the lint. Jax-free:
    runnable from any CI box."""
    from clonos_tpu import analysis as _an

    if args.seed_bug is not None:
        # Self-test: the seeded-bug registry must make its rule bite.
        if args.seed_bug not in _an.SEEDED_BUGS:
            known = ", ".join(sorted(_an.SEEDED_BUGS))
            print(f"unknown seeded bug {args.seed_bug!r} "
                  f"(known: {known})", file=sys.stderr)
            return 2
        findings = _an.seeded_findings(args.seed_bug)
        for f in findings:
            print(f"{f.location()}: [{f.rule}] {f.message}")
        if not findings:
            print(f"seeded bug {args.seed_bug!r} produced NO finding "
                  f"— the race detector lost its teeth",
                  file=sys.stderr)
            return 2
        return 1        # the bug was detected, as it must be

    result = _an.run_analysis(args.paths, waiver_file=args.waivers,
                              use_waivers=not args.no_waivers)
    if args.races:
        # Restrict the report and the exit code to the race pass.
        race_rules = {_an.THREAD_RACE, _an.JOIN_DISCIPLINE}
        kept = [f for f in result.findings
                if f.rule in race_rules
                or any(r in f.message for r in race_rules)]
        result = _an.AnalysisResult(
            findings=kept, files=result.files, census=result.census,
            census_fingerprint=result.census_fingerprint,
            threads=result.threads,
            threads_fingerprint=result.threads_fingerprint)
    if args.report == "json":
        # CI convention: one machine-readable line, exit 0/1.
        print(_an.format_json(result, with_census=not args.no_census))
    elif args.census:
        print(json.dumps(result.census, indent=2, sort_keys=True))
    elif args.threads:
        print(json.dumps(result.threads, indent=2, sort_keys=True))
    else:
        print(_an.format_text(result, verbose=args.verbose))
    rc = result.exit_code()
    if args.expect_census is not None:
        expect = args.expect_census
        if os.path.isfile(expect):
            # a pin file (.clonos-census): first token is the pin
            with open(expect) as f:
                toks = f.read().split()
            expect = toks[0] if toks else ""
        if result.census_fingerprint != expect:
            print(f"census drift: fingerprint "
                  f"{result.census_fingerprint} != pinned {expect} — "
                  f"the FT call-site population changed; review "
                  f"`clonos_tpu analyze --census` and re-pin the "
                  f"fingerprint", file=sys.stderr)
            rc = max(rc, 1)
    if args.expect_threads is not None:
        expect = args.expect_threads
        if os.path.isfile(expect):
            # a pin file (.clonos-threads): first token is the pin
            with open(expect) as f:
                toks = f.read().split()
            expect = toks[0] if toks else ""
        if result.threads_fingerprint != expect:
            print(f"thread-census drift: fingerprint "
                  f"{result.threads_fingerprint} != pinned {expect} — "
                  f"the thread-root population changed (a thread was "
                  f"added, removed, or re-homed); review "
                  f"`clonos_tpu analyze --threads` and re-pin the "
                  f"fingerprint in .clonos-threads", file=sys.stderr)
            rc = max(rc, 1)
    return rc


def cmd_verify(args) -> int:
    """Protocol model checker (``clonos_tpu verify``): exhaustively
    explore the checkpoint / recovery / lease-fencing / admission
    transition models at a small bound, checking every safety invariant
    on every reachable state and bounded liveness on every terminal
    state. ``--seed-bug model:bug`` injects a named protocol defect
    (verify/models.py BUGS) — the checker must then find a minimal
    counterexample (exit 1), which ``--chaos-out`` compiles into a
    replayable chaos-DSL schedule for `clonos_tpu soak`. Pure Python
    (no jax) except ``--conformance``, which replays model traces
    against the real components."""
    from clonos_tpu import verify as _v

    if args.list_bugs:
        for model in sorted(_v.BUGS):
            for bug, what in sorted(_v.BUGS[model].items()):
                print(f"{model}:{bug:20} {what}")
        return 0
    bugs = {}
    for spec in args.seed_bug:
        model, sep, bug = spec.partition(":")
        if not sep:
            print(f"--seed-bug wants model:bug, got {spec!r} "
                  f"(see --list-bugs)", file=sys.stderr)
            return 2
        bugs[model] = bug
    try:
        result = _v.run_verify(
            models=args.model or None, workers=args.workers,
            epochs=args.epochs, faults=args.faults, depth=args.depth,
            max_states=args.max_states, quick=args.quick, bugs=bugs,
            conformance=args.conformance)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    if args.chaos_out:
        os.makedirs(args.chaos_out, exist_ok=True)
        for v in result.violations:
            out = _v.write_counterexample(args.chaos_out, v)
            print(f"counterexample: {out['chaos']}", file=sys.stderr)
    if args.report == "json":
        # CI convention: one machine-readable line, exit 0/1.
        print(_v.format_json(result))
    else:
        print(_v.format_text(result))
    return result.exit_code()


def cmd_top(args) -> int:
    """Live per-worker cluster view (``clonos_tpu top``): poll a
    JobMaster metrics endpoint's /metrics.json and render slots, sealed/
    validated epochs, ring occupancy, replay lag, overhead fraction, and
    last recovery phase times per worker. ``--once`` prints a single
    snapshot (scriptable); otherwise redraws every ``--interval`` s
    until interrupted."""
    import urllib.request

    url = args.endpoint
    if "://" not in url:
        url = "http://" + url
    url = url.rstrip("/") + "/metrics.json"

    def fetch():
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            return json.loads(resp.read().decode("utf-8"))

    if args.once:
        print(_top_table(fetch()))
        return 0
    try:
        while True:
            frame = _top_table(fetch())
            sys.stdout.write("\x1b[2J\x1b[H")   # clear + home
            sys.stdout.write(f"clonos_tpu top — {url} — "
                             f"{time.strftime('%H:%M:%S')}\n\n")
            sys.stdout.write(frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_dissect(args) -> int:
    """Dissect the warm replay at full bench shapes: what the min-of-N
    ``replayer.replay(plan)`` wall actually spends — dispatch-chain
    compute (amortized over a chained loop, tunnel RTT excluded) vs the
    single d2h sync. Optimization must target whichever dominates.
    (Absorbed from tools/replay_dissect.py.)"""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import bench
    from clonos_tpu.runtime.cluster import ClusterRunner
    from clonos_tpu.runtime.executor import DETS_PER_STEP
    from clonos_tpu.utils.devsync import device_sync

    SPE = bench.STEPS_PER_EPOCH
    job = bench.build_job()
    need = bench.FILL_EPOCHS * SPE * DETS_PER_STEP
    cap = 1 << need.bit_length()
    runner = ClusterRunner(job, steps_per_epoch=SPE, log_capacity=cap,
                           max_epochs=16,
                           inflight_ring_steps=1 << max(
                               bench.FILL_EPOCHS * SPE, 2).bit_length(),
                           recovery_block_steps=8192, block_steps=1024,
                           seed=7)
    t0 = time.monotonic()
    runner.run_epoch(complete_checkpoint=True)
    device_sync(runner.executor.carry)
    print("epoch0:", round(time.monotonic() - t0, 1), "s", flush=True)
    t0 = time.monotonic()
    for _ in range(bench.FILL_EPOCHS):
        runner.run_epoch(complete_checkpoint=False)
    device_sync(runner.executor.carry)
    print("fill:", round(time.monotonic() - t0, 1), "s", flush=True)

    failed = bench.PAR + 1
    runner.inject_failure([failed])
    t0 = time.monotonic()
    report = runner.recover()
    device_sync(runner.executor.carry)
    print("cold recover:", round(time.monotonic() - t0, 1), "s",
          {k: round(v, 1) for k, v in report.phase_ms.items()}, flush=True)

    mgr = report.managers[0]
    replayer = mgr.replayer
    plan = mgr.plan

    # (a) bench's exact warm-replay measurement
    for trial in range(args.trials):
        t1 = time.monotonic()
        result = replayer.replay(plan)
        device_sync(result.emit_counts)
        print(f"warm replay #{trial}: "
              f"{(time.monotonic() - t1) * 1e3:.1f}ms  phases:",
              {k: round(v, 1) for k, v in result.phase_ms.items()},
              flush=True)

    # (b) amortized compute of the core block program alone (tunnel RTT
    # excluded): chain N iterations inside one jit, one sync at the end.
    dev = plan.det_device is not None
    print("clean device path:", dev, "n_steps:", plan.n_steps, flush=True)
    if dev:
        t_dev, r_dev, _exp = plan.det_device
        chunk = plan.input_steps[0] if isinstance(plan.input_steps, list) \
            else plan.input_steps
        state0 = jax.tree_util.tree_map(
            lambda x: x[plan.subtask][None], plan.checkpoint_op_state)
        sub = jnp.asarray(plan.subtask, jnp.int32)
        N = 10
        jb = replayer._jit_block

        def chained():
            acc = jnp.zeros((), jnp.int32)
            for _ in range(N):
                st, out, counts, acc = jb(
                    state0, chunk, t_dev[:replayer.block_steps],
                    r_dev[:replayer.block_steps], sub, acc)
            return counts
        r = chained()
        np.asarray(r.ravel()[0])
        ts = []
        for _ in range(3):
            t1 = time.monotonic()
            r = chained()
            np.asarray(r.ravel()[0])
            ts.append((time.monotonic() - t1) * 1e3)
        print(f"block program amortized: {min(ts) / N:.2f}ms per call "
              f"(chain of {N}: {min(ts):.1f}ms)", flush=True)

        # (c) tail ops: tslice + concat cost
        def tail():
            acc = jnp.zeros((), jnp.int32)
            st, out, counts, acc = jb(state0, chunk,
                                      t_dev[:replayer.block_steps],
                                      r_dev[:replayer.block_steps], sub, acc)
            packed = jnp.concatenate(
                [counts, acc.reshape(1), _exp[:plan.n_steps]], axis=0)
            return packed
        p = tail()
        np.asarray(p.ravel()[0])
        ts = []
        for _ in range(5):
            t1 = time.monotonic()
            p = tail()
            np.asarray(p.ravel()[0])
            ts.append((time.monotonic() - t1) * 1e3)
        print(f"block+concat+sync single: min={min(ts):.1f}ms "
              f"p50={sorted(ts)[2]:.1f}ms", flush=True)
    return 0


def cmd_trace(args) -> int:
    """Dump / convert recorded trace files (``clonos_tpu trace``):
    summary by default, Chrome trace_event JSON with ``--chrome`` (the
    output is validated before writing — Perfetto-loadable or error)."""
    from clonos_tpu import obs

    records = obs.load_jsonl(args.files)
    if args.trace_id:
        records = [r for r in records if r.get("trace") == args.trace_id]
    if args.chrome:
        doc = obs.to_chrome(records)
        n = obs.validate_chrome(doc)
        with open(args.chrome, "w") as f:
            json.dump(doc, f)
        print(json.dumps({"events": n, "out": args.chrome}))
        return 0
    summary = obs.summarize(records)
    timeline = summary.pop("timeline")
    print(json.dumps(summary, indent=2, default=str))
    if args.timeline:
        for ev in timeline:
            dur = (f" dur={ev['dur'] * 1e3:.1f}ms"
                   if ev.get("dur") is not None else "")
            print(f"{ev['ts']:.6f} [{ev['service']}] "
                  f"{ev['ph']} {ev['name']}{dur}")
    return 0


def cmd_timeline(args) -> int:
    """Merge, check, filter, diff and export causal timelines
    (``clonos_tpu timeline``): any number of per-process
    timeline-*.jsonl files reconstruct ONE HLC-ordered incident
    timeline; ``--report json`` is the causality gate (exit 1 on any
    inversion); ``--diff`` compares two runs structurally; ``--chrome``
    exports through the same validated trace_event path as
    ``clonos_tpu trace``."""
    from clonos_tpu import obs

    if args.self_check:
        findings = obs.timeline_self_check()
        print(json.dumps({"ok": not findings, "check": "hlc-causality",
                          "inversions": findings}))
        return 0 if not findings else 1

    if not args.files:
        print("timeline: at least one timeline-*.jsonl file required "
              "(or --self-check)", file=sys.stderr)
        return 2

    def _match(rec) -> bool:
        if args.kind and not str(rec.get("kind", "")).startswith(
                args.kind):
            return False
        if args.job is not None and str(
                rec.get("job", rec.get("service", ""))) != args.job:
            return False
        if args.epoch is not None and rec.get("epoch") != args.epoch:
            return False
        if args.worker is not None:
            cands = [rec.get("worker"), rec.get("flat"),
                     rec.get("subtask")]
            targets = rec.get("targets")
            if isinstance(targets, list):
                cands.extend(targets)
            if args.worker not in {str(c) for c in cands
                                   if c is not None}:
                return False
        return True

    # The default and --report paths STREAM: a k-way heap merge over
    # per-file cursors (obs.iter_merged) keeps memory O(open files),
    # not O(total events) — a long soak's timelines merge flat.
    # --trace/--diff/--chrome mix in unsorted sources or need the full
    # set in hand, so they still materialize.
    if not (args.trace or args.diff is not None or args.chrome):
        # inversions are checked over the FULL merged stream — filters
        # narrow what is shown, never what is proven
        inversions = obs.causality_inversions_stream(
            obs.iter_merged(args.files))
        if inversions:
            # A broken receive rule IS an incident: when a flight
            # recorder is armed in this process, the first inversion
            # lands a bundle (Null manager: no-op).
            from clonos_tpu.obs.incident import get_incidents
            get_incidents().signal(
                "timeline.inversion", rule=inversions[0]["rule"],
                detail=inversions[0]["detail"],
                count=len(inversions))
        if args.report == "json":
            by_kind: dict = {}
            total = shown_n = 0
            for r in obs.iter_merged(args.files):
                total += 1
                if not _match(r):
                    continue
                shown_n += 1
                k = str(r.get("kind", "?"))
                by_kind[k] = by_kind.get(k, 0) + 1
            print(json.dumps({"ok": not inversions, "records": total,
                              "shown": shown_n,
                              "by_kind": dict(sorted(by_kind.items())),
                              "inversions": inversions}))
            return 0 if not inversions else 1
        for r in obs.iter_merged(args.files):
            if not _match(r):
                continue
            hlc = r.get("hlc")
            stamp = (f"{hlc[0]}.{hlc[1]}@{hlc[2]}" if hlc
                     else f"~{r.get('ts', 0):.6f}")
            extras = " ".join(
                f"{k}={v}" for k, v in sorted(r.items())
                if k not in ("kind", "ts", "hlc", "service", "pid"))
            print(f"{stamp:<40} [{r.get('service')}] "
                  f"{r.get('kind')} {extras}".rstrip())
        if inversions:
            print(f"\nCAUSALITY INVERSIONS: {len(inversions)}",
                  file=sys.stderr)
            for f in inversions:
                print(f"  {f['rule']}: {f['detail']} "
                      f"(verb={f.get('verb')})", file=sys.stderr)
        return 0 if not inversions else 1

    records = obs.read_timeline(args.files)
    if args.trace:
        records = records + obs.from_trace_records(
            obs.load_jsonl(args.trace))
    # inversions are checked over the FULL merged set — filters narrow
    # what is shown, never what is proven
    inversions = obs.causality_inversions(records)
    merged = obs.merge_records(records)
    shown = [r for r in merged if _match(r)]

    if args.diff is not None:
        other = obs.read_timeline(args.diff)
        findings = obs.diff_timelines(shown,
                                      [r for r in obs.merge_records(other)
                                       if _match(r)])
        if args.report == "json":
            print(json.dumps({"match": not findings,
                              "only_a": sum(f["count"] for f in findings
                                            if f["only"] == "a"),
                              "only_b": sum(f["count"] for f in findings
                                            if f["only"] == "b")}))
        else:
            for f in findings:
                print(f"only in {'A' if f['only'] == 'a' else 'B'} "
                      f"(x{f['count']}): "
                      f"{json.dumps(f['record'], sort_keys=True)}")
            print(f"{'match' if not findings else 'DIVERGED'}: "
                  f"{len(findings)} differing record shapes")
        return 0 if not findings else 1

    if args.chrome:
        doc = obs.to_chrome(obs.to_trace_records(shown))
        n = obs.validate_chrome(doc)
        with open(args.chrome, "w") as f:
            json.dump(doc, f)
        print(json.dumps({"events": n, "out": args.chrome}))
        return 0

    if args.report == "json":
        by_kind: dict = {}
        for r in shown:
            k = str(r.get("kind", "?"))
            by_kind[k] = by_kind.get(k, 0) + 1
        print(json.dumps({"ok": not inversions, "records": len(merged),
                          "shown": len(shown),
                          "by_kind": dict(sorted(by_kind.items())),
                          "inversions": inversions}))
        return 0 if not inversions else 1

    for r in shown:
        hlc = r.get("hlc")
        stamp = (f"{hlc[0]}.{hlc[1]}@{hlc[2]}" if hlc
                 else f"~{r.get('ts', 0):.6f}")
        extras = " ".join(
            f"{k}={v}" for k, v in sorted(r.items())
            if k not in ("kind", "ts", "hlc", "service", "pid"))
        print(f"{stamp:<40} [{r.get('service')}] "
              f"{r.get('kind')} {extras}".rstrip())
    if inversions:
        print(f"\nCAUSALITY INVERSIONS: {len(inversions)}",
              file=sys.stderr)
        for f in inversions:
            print(f"  {f['rule']}: {f['detail']} "
                  f"(verb={f.get('verb')})", file=sys.stderr)
    return 0 if not inversions else 1


def cmd_incident(args) -> int:
    """Incident forensics (``clonos_tpu incident``): list, dump and
    root-cause-localize the flight-recorder bundles an IncidentManager
    landed under ``<dir>/incidents/``. ``explain`` runs the pure
    deterministic analyzer (obs/rootcause.py) — same bundle, same
    bytes, in any process; ``--report json`` prints the canonical
    one-line report and exits 0 (localized) / 1 (could not localize).
    ``--self-check`` is the conftest gate: synthetic bundles through
    the full pipeline, byte-identity enforced."""
    from clonos_tpu.obs import incident as inc
    from clonos_tpu.obs import rootcause as rc

    if args.self_check:
        findings = inc.incident_self_check()
        print(json.dumps({"ok": not findings, "check": "incident-forensics",
                          "schema": inc.bundle_schema_fingerprint(),
                          "findings": findings}))
        return 0 if not findings else 1

    if args.action is None:
        print("incident: an action (list|show|explain) or --self-check "
              "is required", file=sys.stderr)
        return 2

    bdir = os.path.join(args.dir, "incidents")
    if os.path.isdir(args.dir) and os.path.basename(
            os.path.normpath(args.dir)) == "incidents":
        bdir = args.dir            # already pointed at the bundle dir
    try:
        names = sorted(n for n in os.listdir(bdir)
                       if n.startswith("incident-")
                       and n.endswith(".json"))
    except OSError:
        names = []
    paths = [os.path.join(bdir, n) for n in names]

    if args.action == "list":
        if not paths:
            print(f"no incident bundles under {bdir}")
            return 0
        print(f"{'seq':>4}  {'kind':<20} {'epoch':>5}  "
              f"{'fingerprint':<16} file")
        for path in paths:
            try:
                b = inc.load_bundle(path)
            except (OSError, ValueError):
                print(f"  ??  {'<unreadable>':<20} {'':>5}  {'':<16} "
                      f"{os.path.basename(path)}")
                continue
            info = b.get("bundle", {})
            trig = b.get("trigger", {})
            ep = trig.get("epoch")
            print(f"{info.get('seq', 0):>4}  "
                  f"{trig.get('kind', '?'):<20} "
                  f"{'-' if ep is None else ep:>5}  "
                  f"{info.get('fingerprint', '?'):<16} "
                  f"{os.path.basename(path)}")
        return 0

    # show/explain take a bundle: a path, a seq number, or a substring
    def _resolve(target):
        if target is None:
            return paths[-1] if paths else None   # newest
        if os.path.isfile(target):
            return target
        if target.isdigit():
            want = f"incident-{int(target):04d}-"
            for path in paths:
                if os.path.basename(path).startswith(want):
                    return path
        for path in paths:
            if target in os.path.basename(path):
                return path
        return None

    path = _resolve(args.bundle)
    if path is None:
        print(f"incident: no bundle matching "
              f"{args.bundle!r} under {bdir}", file=sys.stderr)
        return 2
    try:
        bundle = inc.load_bundle(path)
    except (OSError, ValueError) as e:
        print(f"incident: cannot read {path}: {e}", file=sys.stderr)
        return 1

    if args.action == "show":
        print(json.dumps(bundle, indent=2, sort_keys=True))
        return 0

    report = rc.analyze_bundle(bundle)
    ok = str(report.get("verdict", "")).startswith("localized")
    if args.report == "json":
        sys.stdout.write(rc.render_report(report))
        return 0 if ok else 1
    print(f"bundle: {path}")
    print(rc.format_report(report))
    return 0 if ok else 1


def cmd_lineage(args) -> int:
    """Record-level lineage (``clonos_tpu lineage``): reconstruct dyed
    records' causal paths from any number of per-process
    ``lineage-*.jsonl`` observation files (source offset → every
    vertex/step → sink part or serve read, with the ORDER/TIMESTAMP/RNG
    determinant rows that influenced them). The reconstructor is pure
    and order-free, so any process renders the same bytes
    (obs/lineage.render_trace — the rootcause convention).
    ``--report json`` is the CI gate: the canonical one-line report,
    exit 0 (every path reaches a terminus) / 1 (broken paths);
    ``--key`` traces one record; ``--chrome`` exports the paths through
    the same validated trace_event writer as ``clonos_tpu trace``;
    ``--self-check`` is the conftest gate (synthetic observations
    through the full join, byte-identity enforced)."""
    from clonos_tpu import obs
    from clonos_tpu.obs import lineage as lin

    if args.self_check:
        findings = lin.lineage_self_check()
        print(json.dumps({"ok": not findings, "check": "record-lineage",
                          "schema": lin.lineage_schema_fingerprint(),
                          "findings": findings}))
        return 0 if not findings else 1

    if not args.files:
        print("lineage: at least one lineage-*.jsonl file required "
              "(or --self-check)", file=sys.stderr)
        return 2
    try:
        observations = lin.read_observations(args.files)
    except (OSError, ValueError) as e:
        print(f"lineage: {e}", file=sys.stderr)
        return 1

    if args.key is not None:
        report = lin.trace_key(observations, args.key)
        if args.report == "json":
            sys.stdout.write(lin.render_trace(report))
            return 0 if report["ok"] else 1
        path = report["path"]
        if path is None:
            print(f"lineage: key {args.key} was never dyed/observed",
                  file=sys.stderr)
            return 1
        full = lin.reconstruct(observations)
        full["keys"] = {str(args.key): path}
        print(lin.format_trace(dict(full, ok=report["ok"],
                                    broken_keys=path["broken"])),
              end="")
        return 0 if report["ok"] else 1

    report = lin.reconstruct(observations)
    if args.chrome:
        doc = obs.to_chrome(lin.to_trace_records(report))
        n = obs.validate_chrome(doc)
        with open(args.chrome, "w") as f:
            json.dump(doc, f)
        print(json.dumps({"events": n, "out": args.chrome}))
        return 0
    if args.report == "json":
        sys.stdout.write(lin.render_trace(report))
        return 0 if report["ok"] else 1
    print(lin.format_trace(report), end="")
    return 0 if report["ok"] else 1


def cmd_soak(args) -> int:
    """Open-loop soak run (``clonos_tpu soak``): paced load at a fixed
    ingestion rate, a seeded (or explicit) chaos schedule, windowed SLO
    evaluation on coordinated-omission-corrected latency, and the
    exactly-once audit re-validated against a fault-free control twin
    after every injected fault. Writes the full verdict to a durable
    ``SOAK_r0N.json`` artifact and exits 0 (pass) / 1 (fail)."""
    import os
    import tempfile
    from clonos_tpu.soak import (ChaosSchedule, SLOSpec, SoakConfig,
                                 SoakDriver, build_soak_fixture,
                                 default_kill_targets,
                                 next_autoscale_artifact_path,
                                 next_soak_artifact_path, parse_schedule)

    tracer = _setup_tracer(args, "soak")
    _setup_timeline(args, "soak")
    _setup_profile(args)
    if args.detect_gray:
        from clonos_tpu.obs import configure_detector
        configure_detector()
    workdir = args.workdir or tempfile.mkdtemp(prefix="clonos-soak-")
    if args.incidents:
        # Flight recorder: any failure signal during the soak (audit
        # divergence, SLO breach, gray suspect, conformance mismatch)
        # lands a durable forensic bundle under <workdir>/incidents/;
        # `clonos_tpu incident explain` localizes it afterwards.
        from clonos_tpu.obs import configure_incidents
        configure_incidents(workdir, service="soak")
    if args.lineage:
        # Record-level dye (obs/lineage.py): arm the process plane so
        # build_soak_fixture gives BOTH twins per-twin planes with the
        # same dye config — k records per epoch dyed by key hash, every
        # hop/sink observed at the seals; `clonos_tpu lineage
        # <workdir>/lineage-*.jsonl` reconstructs the paths afterwards.
        from clonos_tpu.obs import configure_lineage
        configure_lineage(workdir, service="soak")
    runner, control, election = build_soak_fixture(
        workdir, rate=args.rate, duration_s=args.duration,
        steps_per_epoch=args.steps_per_epoch, par=args.parallelism,
        batch=args.batch, seed=args.seed, audit=not args.no_audit)

    if args.schedule is not None:
        text = args.schedule
        if os.path.exists(text):
            with open(text) as f:
                text = f.read()
        schedule = parse_schedule(text)
    else:
        # one kill/gray candidate per vertex class (a cascade must not
        # take every replica of one vertex with it); fire times stay
        # inside the paced window
        targets = default_kill_targets(runner.job)
        schedule = ChaosSchedule.seeded(
            args.seed, args.duration, targets,
            kinds=tuple(args.faults.split(",")) if args.faults
            else ("kill", "gray", "leader-loss"),
            n_events=args.events, cascade=args.cascade)

    spec = SLOSpec(max_p99_ms=args.max_p99_ms,
                   min_throughput=args.min_throughput,
                   max_recovery_ms=args.max_recovery_ms,
                   exactly_once=not args.no_audit)
    cfg = SoakConfig(rate=args.rate, duration_s=args.duration,
                     window_s=args.window,
                     chunk_steps=args.chunk_steps,
                     complete_every=args.complete_every)
    autoscaler = None
    if args.autoscale:
        # the closed loop: a deterministic policy engine evaluates at
        # every completed fence and re-cuts the job itself (zero
        # operator rescale events); every decision + signal snapshot
        # lands in the SCALE determinant log under the workdir, so a
        # recovered controller REPLAYS it instead of re-deciding.
        from clonos_tpu.autoscale import (AutoscaleController,
                                          DecisionLog, PolicyConfig,
                                          ScalePolicy)
        autoscaler = AutoscaleController(
            ScalePolicy(PolicyConfig(
                min_workers=1, max_workers=max(args.parallelism * 2,
                                               args.parallelism + 2))),
            log=DecisionLog(os.path.join(workdir, "scale.det")))
    driver = SoakDriver(runner, cfg, schedule=schedule, spec=spec,
                        control=control, election=election,
                        records_per_step=args.parallelism * args.batch,
                        autoscaler=autoscaler)

    endpoint = None
    if args.metrics_port is not None:
        from clonos_tpu.utils.metrics import MetricsEndpoint
        endpoint = MetricsEndpoint(runner.metrics,
                                   port=args.metrics_port,
                                   tracer=tracer,
                                   history=_make_history(args))
        print(f"# metrics: http://{endpoint.address[0]}:"
              f"{endpoint.address[1]}/metrics", file=sys.stderr)
    try:
        verdict = driver.run()
    finally:
        if endpoint is not None:
            endpoint.close()

    out_path = args.out or (next_autoscale_artifact_path()
                            if args.autoscale
                            else next_soak_artifact_path())
    with open(out_path, "w") as f:
        json.dump(verdict, f, indent=2)
    rc = 0 if verdict["pass"] else 1
    if args.report == "json":
        # CI convention: one machine-readable line, exit 0/1.
        lat = verdict["latency"]
        line = {
            "pass": verdict["pass"],
            "rate_target": verdict["rate_target"],
            "rate_achieved": verdict["rate_achieved"],
            "p50_ms": lat["p50_ms"], "p99_ms": lat["p99_ms"],
            "windows_breached": verdict["windows_breached"],
            "faults": verdict["faults"]["injected"],
            "survived": verdict["faults"]["survived"],
            "exactly_once": verdict["audit"]["exactly_once"],
            "divergences": len(verdict["audit"]["divergences"]),
            "artifact": out_path}
        if "autoscale" in verdict:
            asc = verdict["autoscale"]
            line["autoscale_decisions"] = asc["decisions"]
            line["autoscale_rescales"] = asc["autoscale_rescales"]
            line["operator_rescale_events"] = \
                asc["operator_rescale_events"]
        if "health" in verdict:
            hl = verdict["health"]
            line["gray_suspects"] = hl["suspects"]
            line["gray_replay_ok"] = hl["replay_bit_identical"]
        if args.incidents:
            from clonos_tpu.obs.incident import get_incidents
            line["incidents"] = get_incidents().captured
        if args.lineage:
            line["lineage_dyed"] = runner.lineage.dyed
            line["lineage_observations"] = runner.lineage.observations
        print(json.dumps(line))
        return rc
    lat = verdict["latency"]
    print(f"soak {'PASS' if verdict['pass'] else 'FAIL'}: "
          f"{verdict['rate_achieved']:.0f}/{verdict['rate_target']:.0f} "
          f"rec/s over {verdict['duration_s']:.1f}s")
    print(f"latency (corrected): p50={lat['p50_ms']}ms "
          f"p99={lat['p99_ms']}ms p99.9={lat['p999_ms']}ms "
          f"(actual-send p99={lat['actual_send_p99_ms']}ms)")
    f_ = verdict["faults"]
    print(f"faults: {f_['injected']} injected, {f_['survived']} "
          f"survived {f_['by_kind']}; recoveries "
          f"{[round(m) for m in f_['recoveries_ms']]} ms")
    a = verdict["audit"]
    print(f"audit: exactly_once={a['exactly_once']} "
          f"({a['epochs_checked']} epochs checked, "
          f"{len(a['divergences'])} divergences)")
    if "health" in verdict:
        hl = verdict["health"]
        print(f"health: suspects={hl['suspects']} "
              f"gray_events={hl['gray_events']} "
              f"fences_scored={hl['fences_scored']} "
              f"replay_ok={hl['replay_bit_identical']}")
    if "autoscale" in verdict:
        asc = verdict["autoscale"]
        print(f"autoscale: {asc['decisions']} decisions "
              f"{asc['by_action']}; {asc['autoscale_rescales']} "
              f"self-directed re-cuts, "
              f"{asc['operator_rescale_events']} operator events; "
              f"max {asc['max_actions_per_cooldown']} action(s) per "
              f"{asc['cooldown_fences']}-fence cooldown; "
              f"log {asc['log_digest']}")
    for d in a["divergences"]:
        print(f"  divergence: {d}")
    for w in verdict["windows"]:
        for b in w["breaches"]:
            print(f"  window {w['window']} breach: {b}")
    if args.incidents:
        from clonos_tpu.obs.incident import get_incidents
        mgr = get_incidents()
        if mgr.captured:
            print(f"incidents: {mgr.captured} bundle(s) under "
                  f"{mgr.dir} — `clonos_tpu incident explain "
                  f"--dir {workdir}`")
    if args.lineage:
        lin = runner.lineage
        print(f"lineage: {lin.dyed} records dyed, "
              f"{lin.observations} observations across "
              f"{lin.epochs_observed} epochs — `clonos_tpu lineage "
              f"{workdir}/lineage-*.jsonl`")
    print(f"artifact: {out_path}")
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="clonos_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("run", help="run a job to completion of N epochs")
    pr.add_argument("job", help="module:function returning a JobGraph")
    pr.add_argument("--epochs", type=int, default=4)
    pr.add_argument("--steps-per-epoch", type=int, default=16)
    pr.add_argument("--checkpoint-dir", default=None)
    pr.add_argument("--metrics-port", type=int, default=None,
                    help="serve /metrics (Prometheus) + /metrics.json "
                         "+ /trace on this port while running "
                         "(0 = ephemeral)")
    pr.add_argument("--trace-dir", default=None,
                    help="record trace spans to trace-run.jsonl here "
                         "(off by default: zero overhead)")
    pr.add_argument("--timeline-dir", default=None,
                    help="record HLC-stamped causal events to "
                         "timeline-run.jsonl here (off by default)")
    _add_profile_args(pr)
    pr.set_defaults(fn=cmd_run)

    pi = sub.add_parser("info", help="describe a job graph")
    pi.add_argument("job")
    pi.set_defaults(fn=cmd_info)

    pb = sub.add_parser("bench", help="run the headline benchmark")
    pb.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="run ONLY the multi-job throughput probe with "
                         "N concurrent in-process jobs (per-tenant "
                         "steady-state records/sec + fairness ratio)")
    pb.add_argument("--multichip", type=int, nargs="?", const=8,
                    default=None, metavar="N",
                    help="run ONLY the mesh-sharding probe over N "
                         "devices (per-shard throughput, scaling "
                         "efficiency, sealed-digest equality vs the "
                         "1-device run)")
    pb.add_argument("--soak", type=float, nargs="?", const=30.0,
                    default=None, metavar="SECONDS",
                    help="run ONLY the open-loop soak probe: paced "
                         "fixed-rate load + seeded chaos + exactly-"
                         "once audit (see `clonos_tpu soak` for the "
                         "full-control version)")
    pb.add_argument("--serve", type=float, nargs="?", const=20.0,
                    default=None, metavar="SECONDS",
                    help="run ONLY the read-path probe: batched "
                         "replica reads vs sequential point queries, "
                         "bit-identity vs the owner, and mixed "
                         "read/ingest load with a replica-kill "
                         "(writes SERVE_r0N.json)")
    pb.add_argument("--rescale", type=float, nargs="?", const=12.0,
                    default=None, metavar="SECONDS",
                    help="run ONLY the elastic-repartition probe: a "
                         "live 2->4 keyed re-cut at a checkpoint fence "
                         "under load — throughput before/after, fence-"
                         "stall cost, exactly-once handoff evidence, "
                         "cross-layout ledger diff vs a never-rescaled "
                         "control (writes RESCALE_r0N.json)")
    pb.add_argument("--ablate", action="store_true",
                    help="run ONLY the no-FT ablation probe: the "
                         "semantics-preserving twin head-to-head "
                         "against the real executor (measured vs "
                         "static ft-fraction + model relative error)")
    pb.set_defaults(fn=cmd_bench)

    pd = sub.add_parser("dryrun", help="multichip sharding dry run")
    pd.add_argument("--devices", type=int, default=8)
    pd.set_defaults(fn=cmd_dryrun)

    pw = sub.add_parser("worker", help="run a job as a TaskExecutor "
                                       "process under a remote JobMaster")
    pw.add_argument("job", help="module:function returning a JobGraph")
    pw.add_argument("--jm", required=True, help="JobMaster host:port")
    pw.add_argument("--executor-id", default="worker-0")
    pw.add_argument("--checkpoint-dir", required=True)
    pw.add_argument("--epochs", type=int, default=8)
    pw.add_argument("--steps-per-epoch", type=int, default=16)
    pw.add_argument("--complete-every", type=int, default=4,
                    help="complete (ack) every k-th checkpoint; others "
                         "stay pending (the large-interval regime)")
    pw.add_argument("--seed", type=int, default=0)
    pw.add_argument("--heartbeat-interval", type=float, default=0.5)
    pw.add_argument("--epoch-sleep", type=float, default=0.0,
                    help="pause between epochs (lets tests kill mid-run)")
    pw.add_argument("--bind-host", default="127.0.0.1",
                    help="interface the determinant-log endpoint binds "
                         "(use the host's fabric address for cross-host "
                         "mirroring)")
    pw.add_argument("--advertise-host", default=None,
                    help="address mirrors should dial (defaults to "
                         "--bind-host)")
    pw.add_argument("--coordinator", default=None,
                    help="jax.distributed coordinator address "
                         "(multi-host bootstrap)")
    pw.add_argument("--num-processes", type=int, default=None)
    pw.add_argument("--process-id", type=int, default=None)
    pw.add_argument("--trace-dir", default=None,
                    help="record trace spans to "
                         "trace-<executor-id>.jsonl here")
    pw.add_argument("--timeline-dir", default=None,
                    help="record HLC-stamped causal events to "
                         "timeline-<executor-id>.jsonl here")
    pw.add_argument("--profile", action="store_true",
                    help="attribute fault-tolerance overhead per section "
                         "(overhead.* metrics; off by default: zero "
                         "overhead, async dispatch preserved)")
    pw.set_defaults(fn=cmd_worker)

    ps = sub.add_parser("slotworker",
                        help="serve task slots to a slot-pool JobMaster; "
                             "runs only the task slices deployed onto it")
    ps.add_argument("--jm", required=True, help="JobMaster host:port")
    ps.add_argument("--executor-id", default="slotworker-0")
    ps.add_argument("--slots", type=int, default=1)
    ps.add_argument("--lease", default=None,
                    help="shared leader-lease dir; DEPLOY fencing tokens "
                         "are validated against its claims")
    ps.add_argument("--bind-host", default="127.0.0.1")
    ps.add_argument("--heartbeat-interval", type=float, default=0.5)
    ps.add_argument("--max-seconds", type=float, default=600.0,
                    help="wall guard: exit after this long")
    ps.add_argument("--epoch-sleep", type=float, default=0.0,
                    help="pause after each served epoch round (lets "
                         "tests kill mid-run)")
    ps.add_argument("--chaos-step-delay", type=float, default=0.0,
                    metavar="SECONDS",
                    help="gray-failure injection: sleep this long "
                         "before each slice epoch — degraded (late "
                         "fences) but never dead (heartbeats keep "
                         "flowing); the soak/chaos harness's "
                         "multi-process slow-worker surface")
    ps.add_argument("--metrics-port", type=int, default=None,
                    help="serve this worker's /metrics + /metrics.json "
                         "+ /trace on this port (0 = ephemeral)")
    ps.add_argument("--trace-dir", default=None,
                    help="record trace spans to "
                         "trace-<executor-id>.jsonl here; DEPLOY "
                         "headers make the spans join the JobMaster's "
                         "trace id (off by default: zero overhead)")
    ps.add_argument("--timeline-dir", default=None,
                    help="record HLC-stamped causal events to "
                         "timeline-<executor-id>.jsonl here; merges "
                         "with the JobMaster's file via `clonos_tpu "
                         "timeline` (off by default)")
    _add_profile_args(ps)
    ps.set_defaults(fn=cmd_slotworker)

    pc = sub.add_parser("dispatcher",
                        help="multi-tenant dispatcher: one shared slot "
                             "pool serving many concurrent jobs")
    pc.add_argument("--lease", required=True,
                    help="cluster lease path; each job's leader claims "
                         "<lease>.<job-id>.epochN.claim (slot workers "
                         "validate DEPLOY fencing against the same "
                         "path)")
    pc.add_argument("--checkpoint-root",
                    default="/tmp/clonos-dispatcher",
                    help="every job's checkpoints + ledgers land under "
                         "<root>/<job-id>/")
    pc.add_argument("--quota", action="append", default=[],
                    metavar="TENANT=N",
                    help="per-tenant slot quota (repeatable); "
                         "submissions beyond it are rejected with a "
                         "typed quota-exceeded error")
    pc.add_argument("--default-quota", type=int, default=None,
                    help="slot quota for tenants without an explicit "
                         "--quota (default: unlimited)")
    pc.add_argument("--port", type=int, default=0,
                    help="dispatcher submit/status port (0 = ephemeral)")
    pc.add_argument("--bind-host", default="127.0.0.1")
    pc.add_argument("--epochs", type=int, default=8,
                    help="default target epochs per job (submit may "
                         "override)")
    pc.add_argument("--steps-per-epoch", type=int, default=16)
    pc.add_argument("--seed", type=int, default=0)
    pc.add_argument("--complete-every", type=int, default=1)
    pc.add_argument("--heartbeat-timeout", type=float, default=5.0)
    pc.add_argument("--max-seconds", type=float, default=600.0,
                    help="wall guard: exit after this long")
    pc.add_argument("--audit", choices=["warn", "abort"], default=None,
                    help="enable the exactly-once audit for every "
                         "deployed job (DEPLOY headers carry the "
                         "stance)")
    pc.add_argument("--metrics-port", type=int, default=None,
                    help="serve /metrics + /metrics.json with "
                         "per-tenant rollups (0 = ephemeral)")
    pc.add_argument("--trace-dir", default=None,
                    help="per-job trace files "
                         "(trace-jm.<job-id>.jsonl) land here")
    pc.add_argument("--timeline-dir", default=None,
                    help="record HLC-stamped causal events to "
                         "timeline-dispatcher.jsonl here")
    _add_profile_args(pc)
    pc.set_defaults(fn=cmd_dispatcher)

    pj = sub.add_parser("submit", help="submit a job to a running "
                                       "dispatcher")
    pj.add_argument("job", help="module:function returning a JobGraph "
                                "(resolved by the slot workers)")
    pj.add_argument("--dispatcher", required=True,
                    help="dispatcher host:port")
    pj.add_argument("--tenant", default="default")
    pj.add_argument("--slots", type=int, default=1,
                    help="slices to cut the job into (= pool slots "
                         "held)")
    pj.add_argument("--max-recoveries", type=int, default=1,
                    help="cap on concurrently rebuilt groups after a "
                         "worker death (storm containment)")
    pj.add_argument("--workers", default=None,
                    help="comma-separated placement hint (slice i "
                         "prefers the i-th worker)")
    pj.add_argument("--target-epochs", type=int, default=None)
    pj.add_argument("--wait", action="store_true",
                    help="poll until the job reaches a terminal state")
    pj.add_argument("--timeout", type=float, default=600.0,
                    help="--wait deadline (seconds)")
    pj.set_defaults(fn=cmd_submit)

    po = sub.add_parser("jobs", help="list (or cancel) a dispatcher's "
                                     "jobs")
    po.add_argument("--dispatcher", required=True,
                    help="dispatcher host:port")
    po.add_argument("--json", action="store_true",
                    help="machine-readable job list")
    po.add_argument("--cancel", default=None, metavar="JOB_ID",
                    help="cancel this job instead of listing")
    po.set_defaults(fn=cmd_jobs)

    pt = sub.add_parser("trace", help="summarize or convert recorded "
                                      "trace JSON-lines files")
    pt.add_argument("files", nargs="+",
                    help="trace-*.jsonl files (a run's set of "
                         "per-process files reconstructs one timeline)")
    pt.add_argument("--chrome", default=None, metavar="OUT",
                    help="write Chrome trace_event JSON (load in "
                         "Perfetto / about:tracing)")
    pt.add_argument("--trace-id", default=None,
                    help="keep only records of this trace id")
    pt.add_argument("--timeline", action="store_true",
                    help="also print the dominant trace's ordered "
                         "event timeline")
    pt.set_defaults(fn=cmd_trace)

    pm = sub.add_parser("timeline",
                        help="merge, check and export causal timelines "
                             "(HLC-ordered, cross-process)")
    pm.add_argument("files", nargs="*",
                    help="timeline-*.jsonl files (each process writes "
                         "one; together they reconstruct ONE causally-"
                         "ordered incident timeline)")
    pm.add_argument("--trace", action="append", default=[],
                    metavar="FILE",
                    help="also merge tracer trace-*.jsonl files "
                         "(wall-clock ordered within their process)")
    pm.add_argument("--kind", default=None,
                    help="show only records whose kind starts with "
                         "this (e.g. msg., epoch.seal, health.)")
    pm.add_argument("--job", default=None,
                    help="show only records of this job / service")
    pm.add_argument("--worker", default=None,
                    help="show only records naming this worker / "
                         "flat subtask")
    pm.add_argument("--epoch", type=int, default=None,
                    help="show only records of this epoch")
    pm.add_argument("--diff", default=None, metavar="FILE",
                    help="second run's timeline file(s); structural "
                         "record diff (volatile fields ignored), "
                         "exit 1 on divergence")
    pm.add_argument("--chrome", default=None, metavar="OUT",
                    help="write Chrome trace_event JSON of the merged "
                         "timeline (validated; load in Perfetto)")
    pm.add_argument("--report", choices=["json"], default=None,
                    help="machine-readable summary for CI: one JSON "
                         "line {ok, records, by_kind, inversions}; "
                         "exit 0 iff zero causality inversions")
    pm.add_argument("--self-check", action="store_true",
                    help="run the deterministic in-memory HLC "
                         "causality self-check instead of reading "
                         "files (the conftest gate)")
    pm.set_defaults(fn=cmd_timeline)

    pn = sub.add_parser("incident",
                        help="list / show / root-cause-explain the "
                             "flight-recorder bundles an incident "
                             "manager landed")
    pn.add_argument("action", nargs="?",
                    choices=["list", "show", "explain"],
                    help="list bundles, dump one, or run the "
                         "deterministic root-cause analyzer on one")
    pn.add_argument("bundle", nargs="?", default=None,
                    help="bundle selector for show/explain: a path, a "
                         "seq number, or a filename substring "
                         "(default: the newest bundle)")
    pn.add_argument("--dir", default=".",
                    help="run workdir holding incidents/ (or the "
                         "incidents/ dir itself); default cwd")
    pn.add_argument("--report", choices=["json"], default=None,
                    help="explain: one canonical JSON line (byte-"
                         "identical across processes); exit 0 "
                         "localized / 1 not")
    pn.add_argument("--self-check", action="store_true",
                    help="run the deterministic forensics self-check "
                         "on synthetic bundles (no files); exit 0/1")
    pn.set_defaults(fn=cmd_incident)

    pg = sub.add_parser("lineage",
                        help="reconstruct dyed records' end-to-end "
                             "causal paths from lineage-*.jsonl "
                             "observation files")
    pg.add_argument("files", nargs="*",
                    help="per-process lineage-*.jsonl files (any "
                         "subset joins; torn tails from a SIGKILLed "
                         "writer are tolerated)")
    pg.add_argument("--key", type=int, default=None, metavar="K",
                    help="trace one record key end to end; exit 1 if "
                         "its path is broken or it was never dyed")
    pg.add_argument("--report", choices=["json"], default=None,
                    help="one canonical JSON line (byte-identical "
                         "across processes); exit 0 when every dyed "
                         "path reaches a terminus / 1 on broken paths")
    pg.add_argument("--chrome", default=None, metavar="OUT",
                    help="export the paths as a validated Chrome "
                         "trace_event file (chrome://tracing, "
                         "Perfetto)")
    pg.add_argument("--self-check", action="store_true",
                    help="run the deterministic lineage self-check on "
                         "synthetic observations (no files); exit 0/1")
    pg.set_defaults(fn=cmd_lineage)

    pa = sub.add_parser("audit", help="print or diff a job's epoch "
                                      "audit ledger")
    pa.add_argument("dir", help="checkpoint dir (or slot-pool "
                                "checkpoint root with g*/ subdirs, or a "
                                "ledger.jsonl file)")
    pa.add_argument("--diff", default=None, metavar="DIR",
                    help="second run's checkpoint dir; exit 1 naming "
                         "the first diverging epoch and channel per "
                         "group (layout-aware: epochs sealed under "
                         "different cuts of one job compare via the "
                         "key-group directory)")
    pa.add_argument("--job", default=None, metavar="ID",
                    help="select one job's ledgers under a dispatcher "
                         "root (<dir>/<job-id>/g*/ledger.jsonl); "
                         "without it a --diff over a multi-job root "
                         "exits 2 listing the available job ids")
    pa.add_argument("--json", action="store_true",
                    help="dump raw ledger entries as JSON")
    pa.add_argument("--report", choices=["json"], default=None,
                    help="machine-readable summary for CI: one JSON "
                         "line {match, groups, problems}; exit code "
                         "stays 0 on match / 1 on divergence")
    pa.set_defaults(fn=cmd_audit)

    pk = sub.add_parser("soak", help="open-loop soak: fixed-rate load "
                                     "+ chaos schedule + SLO windows + "
                                     "exactly-once audit under fault")
    pk.add_argument("--rate", type=float, default=2000.0,
                    help="ingestion rate the token bucket sustains "
                         "(records/sec); chunks falling behind are "
                         "charged from their intended-send instant")
    pk.add_argument("--duration", type=float, default=60.0,
                    help="paced-phase length (seconds of soak clock)")
    pk.add_argument("--window", type=float, default=5.0,
                    help="SLO evaluation window width (seconds)")
    pk.add_argument("--seed", type=int, default=11,
                    help="seeds BOTH the job and the generated chaos "
                         "schedule — same seed, same run, bit for bit")
    pk.add_argument("--schedule", default=None, metavar="DSL|FILE",
                    help="explicit chaos schedule: DSL text (';'-"
                         "separated) or a path to a schedule file; "
                         "overrides the seeded generator")
    pk.add_argument("--faults", default=None,
                    metavar="KIND[,KIND...]",
                    help="fault kinds for the seeded generator "
                         "(default kill,gray,leader-loss; add nondet "
                         "to prove the audit catches an unlogged "
                         "perturbation — that run MUST exit 1)")
    pk.add_argument("--events", type=int, default=None,
                    help="events in the seeded schedule (default: one "
                         "per kind)")
    pk.add_argument("--cascade", type=int, default=3,
                    help="subtasks per cascading kill")
    pk.add_argument("--max-p99-ms", type=float, default=None,
                    help="SLO: per-window corrected p99 bound")
    pk.add_argument("--min-throughput", type=float, default=None,
                    help="SLO: per-window records/sec floor")
    pk.add_argument("--max-recovery-ms", type=float, default=None,
                    help="SLO: bound on any single recovery/pause")
    pk.add_argument("--no-audit", action="store_true",
                    help="skip the control twin + exactly-once "
                         "re-validation (halves the compute; the "
                         "verdict then rests on SLO windows alone)")
    pk.add_argument("--steps-per-epoch", type=int, default=64)
    pk.add_argument("--parallelism", type=int, default=2)
    pk.add_argument("--batch", type=int, default=8)
    pk.add_argument("--chunk-steps", type=int, default=8,
                    help="supersteps per token-bucket release")
    pk.add_argument("--complete-every", type=int, default=2,
                    help="complete every Nth checkpoint (in-between "
                         "fences stay pending: checkpoint-under-load)")
    pk.add_argument("--autoscale", action="store_true",
                    help="close the loop: a deterministic policy "
                         "engine samples the metric rollup at every "
                         "completed fence and re-cuts the job itself "
                         "(rescale_live) — decisions ride the SCALE "
                         "determinant log so recovery replays them; "
                         "the verdict lands in AUTOSCALE_r0N.json")
    pk.add_argument("--workdir", default=None,
                    help="checkpoint/lease dir (default: a fresh "
                         "tempdir)")
    pk.add_argument("--out", default=None, metavar="FILE",
                    help="verdict artifact path (default: next free "
                         "SOAK_r0N.json in the cwd, AUTOSCALE_r0N."
                         "json with --autoscale)")
    pk.add_argument("--report", choices=["json"], default=None,
                    help="machine-readable summary for CI: one JSON "
                         "line; exit 0 pass / 1 fail either way")
    pk.add_argument("--metrics-port", type=int, default=None,
                    help="serve /metrics + /metrics.json with the "
                         "soak.* gauges while running (0 = ephemeral; "
                         "point `clonos_tpu top` here)")
    pk.add_argument("--trace-dir", default=None,
                    help="record soak/chaos/breach trace spans to "
                         "trace-soak.jsonl here")
    pk.add_argument("--timeline-dir", default=None,
                    help="record the unified causal timeline (chaos / "
                         "epoch seals / scale decisions / SLO breaches "
                         "/ gray suspicion, HLC-stamped) to "
                         "timeline-soak.jsonl here (off by default: "
                         "zero overhead)")
    pk.add_argument("--incidents", action="store_true",
                    help="arm the incident flight recorder: failure "
                         "signals (audit divergence, SLO breach, gray "
                         "suspect, conformance mismatch) land durable "
                         "forensic bundles under <workdir>/incidents/ "
                         "for `clonos_tpu incident explain` (off by "
                         "default: zero overhead, zero wire fields)")
    pk.add_argument("--lineage", action="store_true",
                    help="arm the record-level lineage plane: a "
                         "deterministic sampler dyes k records per "
                         "epoch by key hash (the control twin dyes "
                         "the SAME records, zero coordination) and "
                         "every fence logs their hops, determinant "
                         "rows, and sink/serve termini to "
                         "<workdir>/lineage-*.jsonl for `clonos_tpu "
                         "lineage` (off by default: zero overhead, "
                         "zero wire fields)")
    pk.add_argument("--detect-gray", action="store_true",
                    help="score the gray-failure detector at every "
                         "completed fence (cluster.health.* gauges, "
                         "health.gray-suspect timeline events, and a "
                         "health section in the verdict; feeds the "
                         "autoscaler's unhealthy arm)")
    _add_profile_args(pk)
    pk.set_defaults(fn=cmd_soak)

    pl = sub.add_parser("lint", help="static determinism lint of "
                                     "pipeline and runtime code")
    pl.add_argument("paths", nargs="*",
                    default=["clonos_tpu", "examples"],
                    help="files and/or directories to lint (default: "
                         "clonos_tpu examples); naming a file directly "
                         "overrides waiver-file `exclude` entries")
    pl.add_argument("--report", choices=["json"], default=None,
                    help="machine-readable summary for CI: one JSON "
                         "line {ok, files, errors, warnings, waived, "
                         "findings}; exit 0 clean / 1 on findings")
    pl.add_argument("--waivers", default=None, metavar="FILE",
                    help="waiver file (default: ./.clonos-waivers if "
                         "present)")
    pl.add_argument("--no-waivers", action="store_true",
                    help="ignore all waivers (inline and file) — show "
                         "every raw finding")
    pl.add_argument("--rule", action="append", default=[],
                    metavar="NAME",
                    help="restrict to one rule (repeatable); unknown "
                         "names exit 2")
    pl.add_argument("--list-rules", action="store_true",
                    help="print the rule registry and exit")
    pl.add_argument("-v", "--verbose", action="store_true",
                    help="also print waived findings")
    pl.set_defaults(fn=cmd_lint)

    pa = sub.add_parser("analyze",
                        help="whole-program static analysis: nondet "
                             "reachability, lock-order cycles, FT "
                             "census + cost model")
    pa.add_argument("paths", nargs="*",
                    default=["clonos_tpu", "examples"],
                    help="files or directories (default: clonos_tpu "
                         "examples)")
    pa.add_argument("--report", choices=["text", "json"],
                    default="text",
                    help="json = one machine-readable line {ok, files, "
                         "errors, warnings, waived, census_fingerprint, "
                         "findings, census}; exit 0 clean / 1 on "
                         "findings")
    pa.add_argument("--waivers", default=None, metavar="FILE",
                    help="waiver file (default: ./.clonos-waivers if "
                         "present)")
    pa.add_argument("--no-waivers", action="store_true",
                    help="ignore all waivers (inline and file) — show "
                         "every raw finding")
    pa.add_argument("--census", action="store_true",
                    help="print the full FT census as indented JSON "
                         "instead of the findings")
    pa.add_argument("--no-census", action="store_true",
                    help="omit the census body from --report json "
                         "(fingerprint stays)")
    pa.add_argument("-v", "--verbose", action="store_true",
                    help="also print waived findings")
    pa.add_argument("--expect-census", default=None, metavar="FP",
                    help="census-drift gate: fail (exit 1) unless the "
                         "census fingerprint equals FP — a hex "
                         "fingerprint or a pin file like "
                         "./.clonos-census whose first token is one")
    pa.add_argument("--races", action="store_true",
                    help="restrict the report and exit code to the "
                         "race pass (thread-race / join-discipline)")
    pa.add_argument("--threads", action="store_true",
                    help="print the thread-root inventory as indented "
                         "JSON instead of the findings")
    pa.add_argument("--expect-threads", default=None, metavar="FP",
                    help="thread-census drift gate: fail (exit 1) "
                         "unless the thread-root fingerprint equals FP "
                         "— a hex fingerprint or a pin file like "
                         "./.clonos-threads whose first token is one")
    pa.add_argument("--seed-bug", default=None, metavar="NAME",
                    help="self-test: run the race pass on a seeded "
                         "concurrency bug (drop-a-join, unguarded-"
                         "cross-thread-write, queue-bypass) — must "
                         "exit 1 with the minimal counterexample")
    pa.set_defaults(fn=cmd_analyze)

    pv = sub.add_parser("verify",
                        help="protocol model checker: exhaustive "
                             "exploration of the checkpoint/recovery/"
                             "lease/admission/repartition protocols "
                             "with chaos-replayable counterexamples")
    pv.add_argument("--model", action="append", default=[],
                    metavar="NAME",
                    help="model to check: checkpoint, recovery, lease, "
                         "admission, repartition (repeatable; "
                         "default: all six)")
    pv.add_argument("--workers", type=int, default=2,
                    help="worker/contender count in the bound "
                         "(default 2)")
    pv.add_argument("--epochs", type=int, default=2,
                    help="checkpoint epochs in the bound (default 2)")
    pv.add_argument("--faults", type=int, default=1,
                    help="injected faults in the bound (default 1)")
    pv.add_argument("--depth", type=int, default=48,
                    help="BFS depth budget (default 48)")
    pv.add_argument("--max-states", type=int, default=200_000,
                    help="state budget per model (default 200000)")
    pv.add_argument("--quick", action="store_true",
                    help="the session-gate bound: workers=2 epochs=2 "
                         "faults=1 at reduced depth/state budget "
                         "(sub-second)")
    pv.add_argument("--seed-bug", action="append", default=[],
                    metavar="MODEL:BUG",
                    help="inject a named protocol defect (repeatable); "
                         "the checker must find a counterexample "
                         "(exit 1). See --list-bugs")
    pv.add_argument("--list-bugs", action="store_true",
                    help="print the seeded-bug registry and exit")
    pv.add_argument("--conformance", action="store_true",
                    help="also replay model traces against the real "
                         "components and fail on observable-transition "
                         "divergence (imports the full runtime)")
    pv.add_argument("--chaos-out", default=None, metavar="DIR",
                    help="compile each counterexample into a chaos-DSL "
                         "schedule (.chaos) + trace (.jsonl) under DIR")
    pv.add_argument("--report", choices=["text", "json"],
                    default="text",
                    help="json = one machine-readable line {ok, bound, "
                         "models, ...}; exit 0 clean / 1 on violations")
    pv.set_defaults(fn=cmd_verify)

    pp = sub.add_parser("top", help="live per-worker cluster view from "
                                    "a JobMaster metrics endpoint")
    pp.add_argument("endpoint",
                    help="metrics endpoint, host:port or http://... "
                         "(the server started with --metrics-port)")
    pp.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (scriptable)")
    pp.add_argument("--interval", type=float, default=2.0,
                    help="redraw period in live mode (seconds)")
    pp.set_defaults(fn=cmd_top)

    px = sub.add_parser("dissect", help="dissect warm-replay wall time "
                                        "at bench shapes")
    px.add_argument("--trials", type=int, default=5,
                    help="warm replay trials to time")
    px.set_defaults(fn=cmd_dissect)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
