"""Command-line front end.

Capability analog of the reference's client layer
(flink-clients .../cli/CliFrontend.java:97 — run/info/list actions against
a cluster). The TPU build is single-binary: the CLI builds/loads a job and
drives the in-process ClusterRunner (MiniCluster-style), which is also the
deployment model for one TPU host; multi-host runs launch the same
entrypoint under ``jax.distributed`` (see parallel/distributed.py).

Usage:
    python -m clonos_tpu run <module:function> [--steps N] [--epochs N] ...
    python -m clonos_tpu info <module:function>
    python -m clonos_tpu bench
    python -m clonos_tpu dryrun [--devices N]
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
import time


def _load_job(spec: str):
    """Load 'module.path:function' returning a JobGraph."""
    mod_name, _, fn_name = spec.partition(":")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name or "build_job")
    job = fn()
    from clonos_tpu.graph.job_graph import JobGraph
    if not isinstance(job, JobGraph):
        raise TypeError(f"{spec} returned {type(job).__name__}, not JobGraph")
    return job


def cmd_run(args) -> int:
    from clonos_tpu.runtime.cluster import ClusterRunner

    job = _load_job(args.job)
    runner = ClusterRunner(job, steps_per_epoch=args.steps_per_epoch,
                           checkpoint_dir=args.checkpoint_dir)
    endpoint = None
    if args.metrics_port is not None:
        from clonos_tpu.utils.metrics import MetricsEndpoint
        endpoint = MetricsEndpoint(runner.metrics, port=args.metrics_port)
        print(f"# metrics: http://{endpoint.address[0]}:"
              f"{endpoint.address[1]}/metrics", file=sys.stderr)
    t0 = time.monotonic()
    try:
        for _ in range(args.epochs):
            runner.run_epoch()
            runner.watchdog.check()
    finally:
        if endpoint is not None:
            endpoint.close()
    dt = time.monotonic() - t0
    snap = runner.metrics.snapshot()
    print(json.dumps({"job": job.name, "epochs": args.epochs,
                      "wall_s": round(dt, 3), "metrics": snap},
                     default=str))
    return 0


def cmd_info(args) -> int:
    job = _load_job(args.job)
    info = {
        "name": job.name,
        "vertices": [
            {"id": v.vertex_id, "name": v.name,
             "operator": type(v.operator).__name__,
             "parallelism": v.parallelism}
            for v in job.vertices],
        "edges": [
            {"src": e.src, "dst": e.dst, "partition": e.partition.value,
             "capacity": e.capacity}
            for e in job.edges],
        "num_key_groups": job.num_key_groups,
        "sharing_depth": job.sharing_depth,
        "total_subtasks": job.total_subtasks(),
        "topological_order": job.topo_order(),
    }
    print(json.dumps(info, indent=2))
    return 0


def cmd_bench(args) -> int:
    import bench
    bench.main()
    return 0


def cmd_dryrun(args) -> int:
    import __graft_entry__ as ge
    ge.dryrun_multichip(args.devices)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="clonos_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("run", help="run a job to completion of N epochs")
    pr.add_argument("job", help="module:function returning a JobGraph")
    pr.add_argument("--epochs", type=int, default=4)
    pr.add_argument("--steps-per-epoch", type=int, default=16)
    pr.add_argument("--checkpoint-dir", default=None)
    pr.set_defaults(fn=cmd_run)

    pr.add_argument("--metrics-port", type=int, default=None,
                    help="serve /metrics (Prometheus) + /metrics.json "
                         "on this port while running (0 = ephemeral)")
    pi = sub.add_parser("info", help="describe a job graph")
    pi.add_argument("job")
    pi.set_defaults(fn=cmd_info)

    pb = sub.add_parser("bench", help="run the headline benchmark")
    pb.set_defaults(fn=cmd_bench)

    pd = sub.add_parser("dryrun", help="multichip sharding dry run")
    pd.add_argument("--devices", type=int, default=8)
    pd.set_defaults(fn=cmd_dryrun)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
