"""Control-plane transport: length-prefixed typed messages over TCP.

The reference's control plane is Akka actor RPC with typed gateways
(rpc/akka/AkkaRpcService.java:84, TaskExecutorGateway.java:170-233) and
its recovery events flow in-band over netty data channels
(DeterminantRequestEvent / DeterminantResponseEvent /
InFlightLogRequestEvent). The TPU build keeps intra-chip coordination as
host calls (one process, one device), and uses THIS transport for the
cross-host analogs: registration, heartbeats, checkpoint RPCs, and
determinant-delta fetches between a running host and a remote standby
host (runtime/remote.py drives it; the delta bytes use causal/serde.py).

Wire: frame = u32 length | u16 msg_type | payload. Payloads are either
raw bytes (delta frames) or UTF-8 JSON for small control records —
explicit, versionable, no pickle on the wire.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

_FRAME = struct.Struct("<IH")

# message types (reference gateway methods / task events)
REGISTER = 1               # TaskExecutor -> JobMaster
HEARTBEAT = 2              # TaskExecutor -> JobMaster
TRIGGER_CHECKPOINT = 3     # JobMaster -> TaskExecutor
ACK_CHECKPOINT = 4
NOTIFY_COMPLETE = 5
IGNORE_CHECKPOINT = 6      # rpcIgnoreUnacknowledgedPendingCheckpointsFor
DETERMINANT_REQUEST = 7    # standby host -> running host
DETERMINANT_RESPONSE = 8   # payload = serde delta frame
INFLIGHT_REQUEST = 9
INFLIGHT_RESPONSE = 10
SHUTDOWN = 11
OK = 12
ERROR = 13
QUERY_STATE = 14           # external client -> queryable-state endpoint
QUERY_RESPONSE = 15
# scheduler / slot-pool surface (runtime/scheduler.py; reference
# SlotPool.java offers + TaskExecutorGateway.submitTask + task state
# reports)
SLOT_OFFER = 16            # TaskExecutor -> JobMaster: add slot capacity
DEPLOY = 17                # JobMaster -> TaskExecutor: fenced task slice
TASK_STATE = 18            # TaskExecutor -> JobMaster: task transition
FETCH_EDGE = 19            # downstream worker -> upstream edge export
EDGE_DATA = 20             # payload = JSON header | int32 record rows
# dispatcher surface (runtime/dispatcher.py; reference
# Dispatcher.submitJob -> per-job JobMaster over one TaskManager pool).
# DEPLOY / TASK_STATE / FETCH_EDGE headers carry a ``job_id`` field in
# multi-job deployments so one worker routes per-job state; absent
# job_id means the legacy single-job cluster (wire bytes unchanged).
SUBMIT_JOB = 21            # client -> Dispatcher: JobGraph + tenant config
JOB_STATUS = 22            # client -> Dispatcher: one job / list all jobs
CANCEL_JOB = 23            # client -> Dispatcher: cancel / abandon a job
# read-path serving surface (runtime/serve.py): a replica endpoint
# coalesces concurrent point lookups into ONE batched device gather per
# dispatch; SERVE_STATUS is the router's cheap freshness probe
# (epoch + staleness, no state read).
QUERY_BATCH = 24           # client -> serve endpoint: many keys, one gather
QUERY_BATCH_RESPONSE = 25
SERVE_STATUS = 26          # client -> serve endpoint: epoch/staleness probe


def _send(sock: socket.socket, mtype: int, payload: bytes) -> None:
    sock.sendall(_FRAME.pack(len(payload), mtype) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv(sock: socket.socket) -> Tuple[int, bytes]:
    hdr = _recv_exact(sock, _FRAME.size)
    length, mtype = _FRAME.unpack(hdr)
    return mtype, _recv_exact(sock, length)


def pack_json(obj: Any) -> bytes:
    return json.dumps(obj).encode("utf-8")


def unpack_json(b: bytes) -> Any:
    return json.loads(b.decode("utf-8"))


# --- trace-context convention ------------------------------------------------
# Control messages whose payload is a JSON header (DEPLOY,
# TRIGGER_CHECKPOINT, DETERMINANT_REQUEST, FETCH_EDGE) MAY carry a
# ``trace`` field: the sender's obs.Tracer.wire_context() dict. Receivers
# adopt it so both sides' spans land under one trace id. A disabled
# tracer has wire_context() None — these helpers then leave the header
# untouched, keeping the wire bytes identical to an untraced build.

def attach_trace(header: Dict[str, Any]) -> Dict[str, Any]:
    """Add the process tracer's context to a JSON header (in place)."""
    from clonos_tpu.obs import get_tracer
    ctx = get_tracer().wire_context()
    if ctx is not None:
        header["trace"] = ctx
    return header


def adopt_trace(header: Dict[str, Any]) -> None:
    """Join the trace a received JSON header carries (no-op when the
    local tracer is disabled or the header has no ``trace``)."""
    from clonos_tpu.obs import get_tracer
    tr = get_tracer()
    if tr.enabled:
        tr.adopt(header.get("trace"))


# --- audit-context convention ------------------------------------------------
# Same shape as the trace convention, for the exactly-once audit plane
# (obs/audit.py): a JobMaster with auditing configured stamps its stance
# on DEPLOY headers so every worker runner seals and validates epoch
# digests under the same policy. A disabled auditor attaches NOTHING —
# audit-off wire bytes are identical to a pre-audit build.

def attach_audit(header: Dict[str, Any]) -> Dict[str, Any]:
    """Add the process auditor's stance to a JSON header (in place)."""
    from clonos_tpu.obs import get_auditor
    a = get_auditor()
    if a.enabled:
        header["audit"] = {"on_divergence": a.on_divergence}
    return header


def adopt_audit(header: Dict[str, Any]) -> None:
    """Enable process-wide auditing per a received header's ``audit``
    field (no-op without one; runners built AFTER adoption inherit)."""
    from clonos_tpu.obs import configure_audit, get_auditor
    ctx = header.get("audit")
    if ctx and not get_auditor().enabled:
        configure_audit(on_divergence=ctx.get("on_divergence", "warn"))


# --- profile-context convention ----------------------------------------------
# Same shape again, for overhead attribution (obs/profile.py): a
# JobMaster running with the profiler on stamps DEPLOY headers so every
# deployed runner attributes its FT overhead — the whole slot pool then
# reports ``overhead.ft-fraction`` without per-worker flags. A disabled
# profiler attaches NOTHING: profile-off wire bytes stay identical.

def attach_profile(header: Dict[str, Any]) -> Dict[str, Any]:
    """Stamp the process profiler's stance on a JSON header (in
    place)."""
    from clonos_tpu.obs import get_profiler
    if get_profiler().enabled:
        header["profile"] = True
    return header


def adopt_profile(header: Dict[str, Any]) -> None:
    """Enable process-wide overhead profiling per a received header's
    ``profile`` field (runners built AFTER adoption inherit)."""
    from clonos_tpu.obs import configure_profile, get_profiler
    if header.get("profile") and not get_profiler().enabled:
        configure_profile()


# --- hlc-context convention --------------------------------------------------
# Same shape once more, for the unified causal timeline (obs/hlc.py +
# obs/timeline.py): every cross-process control message (DEPLOY,
# HEARTBEAT, FETCH_EDGE, DETERMINANT_REQUEST, serve verbs) MAY carry an
# ``hlc`` field — the sender's hybrid-logical-clock stamp. The receiver
# folds it into its own clock (the HLC receive rule), so the two
# processes' timeline records merge into one causally-consistent order
# no matter how their wall clocks disagree. A disabled clock attaches
# NOTHING: hlc-off wire bytes stay identical to a pre-HLC build.

def attach_hlc(header: Dict[str, Any],
               verb: Optional[str] = None) -> Dict[str, Any]:
    """Tick the process HLC and stamp a JSON header (in place); emits a
    ``msg.send`` timeline record carrying the same stamp."""
    from clonos_tpu.obs import get_hlc, get_timeline
    h = get_hlc()
    if h.enabled:
        stamp = h.tick()
        header["hlc"] = {"ts": [stamp[0], stamp[1]], "node": stamp[2]}
        tl = get_timeline()
        if tl.enabled:
            tl.record("msg.send", hlc=stamp, verb=verb)
    return header


def adopt_hlc(header: Dict[str, Any],
              verb: Optional[str] = None) -> None:
    """Fold a received header's ``hlc`` stamp into the process clock
    (no-op when either side has no clock); emits a ``msg.recv``
    timeline record echoing the sender's stamp so causality is
    checkable per record."""
    from clonos_tpu.obs import get_hlc, get_timeline
    h = get_hlc()
    ctx = header.get("hlc")
    if h.enabled and isinstance(ctx, dict) and "ts" in ctx:
        sent = (int(ctx["ts"][0]), int(ctx["ts"][1]),
                str(ctx.get("node", "?")))
        stamp = h.observe(sent)
        tl = get_timeline()
        if tl.enabled:
            tl.record("msg.recv", hlc=stamp, verb=verb,
                      sent=list(sent))


# --- lineage-context convention ----------------------------------------------
# Same shape once more, for the record-lineage plane (obs/lineage.py):
# a JobMaster with lineage configured stamps its dye config (root, k,
# salt) on DEPLOY headers so every worker runner dyes the SAME records
# — the dye is a pure key-hash function, so shipping three ints IS the
# whole coordination; the per-record tag codec
# (causal/serde.encode_lineage_tags) rides ordinary data messages when
# exchanges leave the process. A disabled plane attaches NOTHING:
# lineage-off wire bytes are identical to a pre-lineage build.

def attach_lineage(header: Dict[str, Any]) -> Dict[str, Any]:
    """Stamp the process lineage plane's dye config on a JSON header
    (in place)."""
    from clonos_tpu.obs.lineage import get_lineage
    ctx = get_lineage().wire_config()
    if ctx is not None:
        header["lineage"] = ctx
    return header


def adopt_lineage(header: Dict[str, Any]) -> None:
    """Enable process-wide lineage per a received header's ``lineage``
    field (no-op without one; runners built AFTER adoption inherit —
    same dye root/k/salt as the sender, so both sides dye the same
    records)."""
    from clonos_tpu.obs.lineage import configure_lineage, get_lineage
    ctx = header.get("lineage")
    if ctx and not get_lineage().enabled:
        configure_lineage(str(ctx["root"]), k=int(ctx.get("k", 4)),
                          salt=int(ctx.get("salt", 0)))


class ControlServer:
    """Threaded request/response endpoint. ``handler(mtype, payload) ->
    (mtype, payload)`` runs per request; one TCP connection may carry many
    requests (the typed-gateway analog)."""

    def __init__(self, handler: Callable[[int, bytes], Tuple[int, bytes]],
                 host: str = "127.0.0.1", port: int = 0):
        outer = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        mtype, payload = _recv(self.request)
                        if mtype == SHUTDOWN:
                            _send(self.request, OK, b"")
                            return
                        try:
                            rt, rp = outer._handler(mtype, payload)
                        except Exception as e:       # surface, don't die
                            rt, rp = ERROR, pack_json({"error": str(e)})
                        from clonos_tpu.obs import get_profiler
                        prof = get_profiler()
                        if prof.enabled:
                            # Only the response write: the loop's recv
                            # blocks waiting for the NEXT request, which
                            # is idle time, not overhead.
                            with prof.section("transport-send"):
                                _send(self.request, rt, rp)
                        else:
                            _send(self.request, rt, rp)
                except (ConnectionError, OSError):
                    return

        self._handler = handler
        self._srv = socketserver.ThreadingTCPServer((host, port), _H)
        self._srv.daemon_threads = True
        self.address: Tuple[str, int] = self._srv.server_address[:2]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class ControlClient:
    """Blocking request/response client for a ControlServer.

    A failed call leaves the stream with a possibly half-read response,
    so the socket is dropped on ANY transport error and transparently
    re-established on the next call — callers retry calls, never manage
    connections (an Akka RPC client reconnects the same way)."""

    def __init__(self, address: Tuple[str, int], timeout_s: float = 10.0):
        self._address = tuple(address)
        self._timeout = timeout_s
        self._closed = False
        self._sock: Optional[socket.socket] = socket.create_connection(
            self._address, timeout=timeout_s)

    def call(self, mtype: int, payload: bytes = b"") -> Tuple[int, bytes]:
        if self._closed:
            raise RuntimeError("ControlClient is closed")
        from clonos_tpu.obs import get_profiler
        prof = get_profiler()
        try:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self._address, timeout=self._timeout)
            if not prof.enabled:
                _send(self._sock, mtype, payload)
                return _recv(self._sock)
            # Attributed control-plane cost: the request write and the
            # blocking wait for the peer's response (the client holds
            # its thread for both legs).
            with prof.section("transport-send"):
                _send(self._sock, mtype, payload)
            with prof.section("transport-recv"):
                return _recv(self._sock)
        except OSError:
            self._drop()
            raise

    def call_json(self, mtype: int, obj: Any) -> Any:
        rt, rp = self.call(mtype, pack_json(obj))
        if rt == ERROR:
            raise RuntimeError(unpack_json(rp)["error"])
        return unpack_json(rp) if rp else None

    def _drop(self) -> None:
        # A failed call may leave a half-read response; never reuse the
        # stream — the next call reconnects fresh.
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        self._closed = True
        self._drop()
