"""Multi-host distributed backend.

Capability parity with the reference's communication stack (SURVEY §2.6:
netty TCP data plane + akka control RPC + in-band task events), mapped to
the TPU fabric the way the design intends:

- **Data plane**: XLA collectives over ICI within a slice, DCN across
  slices — inserted by the SPMD partitioner from sharding annotations (the
  executor's ``with_sharding_constraint`` over the task axis), never
  hand-written sends. The exchange scatter (parallel/routing.py) lowers to
  all-to-alls; determinant replication's gather-by-owner lowers to
  all-gathers (causal/replication.py).
- **Control plane**: jax.distributed (gRPC) for process bootstrap +
  barriers; the ClusterRunner stays the single logical control plane
  (process 0), matching the reference's single JobMaster.
- **In-band events** (determinant/in-flight requests): host-level gRPC in
  the reference; here they are host-side array reads against the sharded
  carry — jax.device_get on an addressable shard — so the "request" rides
  the same runtime channel as everything else.

Under multi-host, every process runs the SAME jitted superstep over one
global mesh (SPMD); per-host Python only feeds host-local step inputs.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec


@dataclasses.dataclass
class DistributedContext:
    process_id: int
    num_processes: int
    coordinator: Optional[str]

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> DistributedContext:
    """Bootstrap multi-host JAX (gRPC barrier at coordinator_address).
    No-op single-process context when no coordinator is given."""
    if coordinator_address is None:
        return DistributedContext(0, 1, None)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return DistributedContext(jax.process_index(), jax.process_count(),
                              coordinator_address)


def task_mesh(max_devices: Optional[int] = None,
              axis: str = "tasks") -> jax.sharding.Mesh:
    """One-axis mesh over all (global) devices: the subtask-deployment
    axis. Device order is JAX's global enumeration, so intra-host
    neighbors are ICI-adjacent and cross-host hops ride DCN — exchanges
    between adjacent subtasks stay on the faster links."""
    devs = jax.devices()
    if max_devices is not None:
        devs = devs[:max_devices]
    return jax.sharding.Mesh(np.asarray(devs), (axis,))


def hierarchical_mesh(axis_tasks: str = "tasks",
                      axis_hosts: str = "hosts") -> jax.sharding.Mesh:
    """Two-axis mesh [hosts, tasks-per-host] for layouts that want
    replication across hosts (e.g. standby redundancy on a different
    failure domain) while sharding subtasks within a host."""
    n_hosts = jax.process_count()
    devs = jax.devices()
    per_host = len(devs) // n_hosts
    grid = np.asarray(devs).reshape(n_hosts, per_host)
    return jax.sharding.Mesh(grid, (axis_hosts, axis_tasks))


# --- rule-driven carry partitioning -----------------------------------------
#
# The executor's JobCarry is a deep pytree whose leaves disagree about
# WHICH axis is the subtask axis: stacked causal logs / replicas lead
# with it ([L, cap, lanes]), in-flight ring tensors carry it second
# ([S, P, cap] — the leading axis is the ring step), round-robin cursors
# and ring scalars are control state that every shard must see. A single
# "shard the leading axis" heuristic therefore cannot express the
# deployment; these RULES can: ordered (regex over the '/'-joined leaf
# path, shard dim | None) pairs, first match wins, unmatched leaves
# replicate. The same table drives with_sharding_constraint inside the
# traced block program AND the explicit in/out shardings on the jitted
# entry points, so the two can never disagree.

#: (path regex, dim to shard along the task axis; None = replicate).
CARRY_PARTITION_RULES: Tuple[Tuple[str, Optional[int]], ...] = (
    # In-flight ring payload tensors are [ring_step, subtask, cap].
    (r"out_rings/\d+/(keys|values|timestamps|valid)$", 1),
    # Ring bookkeeping (head/tail/epoch index) is scalar control state.
    (r"out_rings/", None),
    # Stacked causal logs + determinant replicas lead with the task axis.
    (r"(^|/)(logs|replicas)/", 0),
    # Rebalance cursors are [1] scalars shared by the whole edge.
    (r"rr_offsets/", None),
    # Operator state / depth-1 edge buffers / record counts lead with
    # the (destination) subtask axis.
    (r"(^|/)(op_states|edge_bufs|record_counts)($|/)", 0),
)


def _path_str(path: Tuple[Any, ...]) -> str:
    """Render a tree_flatten_with_path key path as 'a/0/b' — attribute
    names for NamedTuple/dataclass fields, indices for sequences, keys
    for dicts — the namespace the partition-rule regexes match against."""
    parts = []
    for k in path:
        if hasattr(k, "name"):                 # GetAttrKey / DictKey-like
            parts.append(str(k.name))
        elif hasattr(k, "idx"):                # SequenceKey
            parts.append(str(k.idx))
        elif hasattr(k, "key"):                # DictKey / FlattenedIndexKey
            parts.append(str(k.key))
        else:                                  # pragma: no cover
            parts.append(str(k))
    return "/".join(parts)


def _spec_for_leaf(path_s: str, leaf: Any, n: int, axis: str,
                   rules: Sequence[Tuple[str, Optional[int]]]
                   ) -> PartitionSpec:
    """First matching rule decides the shard dim; a dim the leaf lacks or
    cannot split evenly over the ``n`` mesh devices degrades to
    replication (same guard the in-trace constraint applies, so explicit
    jit shardings and with_sharding_constraint always agree)."""
    ndim = getattr(leaf, "ndim", None)
    if ndim is None:
        ndim = np.ndim(leaf)
    shape = getattr(leaf, "shape", ())
    for pat, dim in rules:
        if re.search(pat, path_s):
            if dim is None or ndim <= dim or shape[dim] == 0 \
                    or shape[dim] % n != 0:
                return PartitionSpec()
            return PartitionSpec(*([None] * dim + [axis]))
    return PartitionSpec()


def infer_partition_spec(tree: Any, mesh: jax.sharding.Mesh,
                         axis: str = "tasks",
                         rules: Sequence[Tuple[str, Optional[int]]]
                         = CARRY_PARTITION_RULES) -> Any:
    """PartitionSpec pytree for ``tree`` (same structure), derived from
    the rule table over flattened leaf names. Scalars and indivisible
    leaves replicate."""
    n = mesh.shape[axis]
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = [_spec_for_leaf(_path_str(p), x, n, axis, rules)
             for p, x in leaves]
    return jax.tree_util.tree_unflatten(treedef, specs)


def named_shardings(tree: Any, mesh: jax.sharding.Mesh,
                    axis: str = "tasks",
                    rules: Sequence[Tuple[str, Optional[int]]]
                    = CARRY_PARTITION_RULES) -> Any:
    """NamedSharding pytree over ``mesh`` for ``tree`` — the form
    ``jax.jit``'s in/out_shardings and ``device_put`` take."""
    specs = infer_partition_spec(tree, mesh, axis=axis, rules=rules)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, PartitionSpec))


def mesh_fingerprint(mesh: Optional[jax.sharding.Mesh]) -> str:
    """Stable short id of a mesh's topology: axis names, axis sizes, and
    device kind — what XLA partitioning actually depends on (NOT device
    ordinals, so equivalent meshes on different hosts key identically)."""
    if mesh is None:
        return "nomesh"
    kinds = sorted({d.platform for d in mesh.devices.flat})
    desc = f"{tuple(mesh.axis_names)}|{tuple(mesh.devices.shape)}|{kinds}"
    return hashlib.blake2b(desc.encode(), digest_size=6).hexdigest()


def spec_fingerprint(specs: Any) -> str:
    """Stable short id of a PartitionSpec pytree (structure + every
    spec), for compile-cache keying: sharded and unsharded lowerings of
    the same HLO-shaped program must never collide."""
    leaves, treedef = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda s: isinstance(s, PartitionSpec))
    desc = repr(treedef) + "|" + "|".join(repr(s) for s in leaves)
    return hashlib.blake2b(desc.encode(), digest_size=6).hexdigest()


def standby_device_order(mesh: jax.sharding.Mesh,
                         axis: str = "tasks") -> Sequence[int]:
    """Placement hint: standby replicas should restore onto devices
    *rotated by one host* relative to their primary, so a host loss never
    takes a primary and its standby together (the reference schedules
    standbys on different TaskManagers, RunStandbyTaskStrategy.java:186)."""
    n = mesh.shape[axis]
    per_host = max(1, n // max(jax.process_count(), 1))
    return [(i + per_host) % n for i in range(n)]


def standby_worker_order(num_workers: int) -> Sequence[int]:
    """Worker-process-level form of :func:`standby_device_order`, used by
    the slot-pool scheduler's anti-affinity rule: task group ``i``'s
    standby (the redeploy target when its primary worker dies) is the
    NEXT worker in registration order — a vertex's standby never shares a
    worker process with its primary, so one process loss cannot take
    both (RunStandbyTaskStrategy.java:186 placement)."""
    if num_workers < 1:
        raise ValueError("standby_worker_order: need at least one worker")
    return [(i + 1) % num_workers for i in range(num_workers)]
