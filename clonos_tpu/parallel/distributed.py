"""Multi-host distributed backend.

Capability parity with the reference's communication stack (SURVEY §2.6:
netty TCP data plane + akka control RPC + in-band task events), mapped to
the TPU fabric the way the design intends:

- **Data plane**: XLA collectives over ICI within a slice, DCN across
  slices — inserted by the SPMD partitioner from sharding annotations (the
  executor's ``with_sharding_constraint`` over the task axis), never
  hand-written sends. The exchange scatter (parallel/routing.py) lowers to
  all-to-alls; determinant replication's gather-by-owner lowers to
  all-gathers (causal/replication.py).
- **Control plane**: jax.distributed (gRPC) for process bootstrap +
  barriers; the ClusterRunner stays the single logical control plane
  (process 0), matching the reference's single JobMaster.
- **In-band events** (determinant/in-flight requests): host-level gRPC in
  the reference; here they are host-side array reads against the sharded
  carry — jax.device_get on an addressable shard — so the "request" rides
  the same runtime channel as everything else.

Under multi-host, every process runs the SAME jitted superstep over one
global mesh (SPMD); per-host Python only feeds host-local step inputs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np


@dataclasses.dataclass
class DistributedContext:
    process_id: int
    num_processes: int
    coordinator: Optional[str]

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> DistributedContext:
    """Bootstrap multi-host JAX (gRPC barrier at coordinator_address).
    No-op single-process context when no coordinator is given."""
    if coordinator_address is None:
        return DistributedContext(0, 1, None)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return DistributedContext(jax.process_index(), jax.process_count(),
                              coordinator_address)


def task_mesh(max_devices: Optional[int] = None,
              axis: str = "tasks") -> jax.sharding.Mesh:
    """One-axis mesh over all (global) devices: the subtask-deployment
    axis. Device order is JAX's global enumeration, so intra-host
    neighbors are ICI-adjacent and cross-host hops ride DCN — exchanges
    between adjacent subtasks stay on the faster links."""
    devs = jax.devices()
    if max_devices is not None:
        devs = devs[:max_devices]
    return jax.sharding.Mesh(np.asarray(devs), (axis,))


def hierarchical_mesh(axis_tasks: str = "tasks",
                      axis_hosts: str = "hosts") -> jax.sharding.Mesh:
    """Two-axis mesh [hosts, tasks-per-host] for layouts that want
    replication across hosts (e.g. standby redundancy on a different
    failure domain) while sharding subtasks within a host."""
    n_hosts = jax.process_count()
    devs = jax.devices()
    per_host = len(devs) // n_hosts
    grid = np.asarray(devs).reshape(n_hosts, per_host)
    return jax.sharding.Mesh(grid, (axis_hosts, axis_tasks))


def standby_device_order(mesh: jax.sharding.Mesh,
                         axis: str = "tasks") -> Sequence[int]:
    """Placement hint: standby replicas should restore onto devices
    *rotated by one host* relative to their primary, so a host loss never
    takes a primary and its standby together (the reference schedules
    standbys on different TaskManagers, RunStandbyTaskStrategy.java:186)."""
    n = mesh.shape[axis]
    per_host = max(1, n // max(jax.process_count(), 1))
    return [(i + per_host) % n for i in range(n)]


def standby_worker_order(num_workers: int) -> Sequence[int]:
    """Worker-process-level form of :func:`standby_device_order`, used by
    the slot-pool scheduler's anti-affinity rule: task group ``i``'s
    standby (the redeploy target when its primary worker dies) is the
    NEXT worker in registration order — a vertex's standby never shares a
    worker process with its primary, so one process loss cannot take
    both (RunStandbyTaskStrategy.java:186 placement)."""
    if num_workers < 1:
        raise ValueError("standby_worker_order: need at least one worker")
    return [(i + 1) % num_workers for i in range(num_workers)]
