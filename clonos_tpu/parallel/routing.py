"""Batched record routing: the TPU form of the network exchange.

The reference partitions record-at-a-time through channel selectors
(flink-streaming-java .../runtime/partitioner/{KeyGroupStreamPartitioner,
RebalancePartitioner,BroadcastPartitioner}.java) and moves bytes over netty
(io/network/partition/ResultPartition.java:86 ->
consumer/SingleInputGate.java:107). Here an exchange is one dense op on the
whole batch: compute a target subtask per record, stable-sort by target, and
scatter into a fixed-capacity per-subtask buffer. Under ``jit`` over a mesh
the scatter lowers to an all-to-all on ICI — XLA inserts the collective;
there is no hand-written transport.

Determinism note: routing is a pure function of the input batch (stable sort
keeps arrival order within a target), so exchanges need **no** determinants —
only the *selection* of which queued batch a multi-input vertex consumes is
nondeterministic (logged as ORDER, see runtime/executor.py).

Key-group discipline matches the reference: state is sharded by
``key_group = hash(key) % num_key_groups`` and key groups map to subtasks as
``kg * parallelism // num_key_groups``
(flink-runtime .../state/KeyGroupRangeAssignment.java).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from clonos_tpu.api.records import RecordBatch, zero_invalid


def hash32(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix32-style avalanche hash on int32 (uint32 arithmetic)."""
    u = x.astype(jnp.uint32)
    u = (u ^ (u >> 16)) * jnp.uint32(0x7FEB352D)
    u = (u ^ (u >> 15)) * jnp.uint32(0x846CA68B)
    u = u ^ (u >> 16)
    return u


def key_group(keys: jnp.ndarray, num_key_groups: int) -> jnp.ndarray:
    return (hash32(keys) % jnp.uint32(num_key_groups)).astype(jnp.int32)


def subtask_for_key_group(kg: jnp.ndarray, parallelism: int,
                          num_key_groups: int) -> jnp.ndarray:
    # Matches KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup.
    return (kg * parallelism) // num_key_groups


def key_group_range(subtask: int, parallelism: int,
                    num_key_groups: int) -> Tuple[int, int]:
    """[start, end) of key groups owned by ``subtask``."""
    start = -(-subtask * num_key_groups // parallelism)  # ceil div
    end = -(-(subtask + 1) * num_key_groups // parallelism)
    return start, end


def _scatter_to_targets(
    batch: RecordBatch, target: jnp.ndarray, num_targets: int, out_capacity: int
) -> Tuple[RecordBatch, jnp.ndarray]:
    """Core exchange: flatten, stable-sort by target, scatter to
    ``[num_targets, out_capacity]``. Returns (routed, dropped_per_target)."""
    flat = jnp.reshape
    n = batch.keys.size
    keys, vals, ts, valid = (flat(batch.keys, (n,)), flat(batch.values, (n,)),
                             flat(batch.timestamps, (n,)), flat(batch.valid, (n,)))
    target = jnp.where(valid, flat(target, (n,)), num_targets)  # invalid last
    order = jnp.argsort(target, stable=True)
    st, sk, sv, sts = target[order], keys[order], vals[order], ts[order]
    # Position of each sorted record within its target's run.
    idx = jnp.arange(n, dtype=jnp.int32)
    run_start = jnp.searchsorted(st, jnp.arange(num_targets + 1, dtype=st.dtype),
                                 side="left").astype(jnp.int32)
    pos = idx - run_start[jnp.clip(st, 0, num_targets)]
    live = st < num_targets
    keep = live & (pos < out_capacity)
    dropped = jnp.zeros((num_targets,), jnp.int32).at[st].add(
        (live & ~keep).astype(jnp.int32), mode="drop")
    # Scatter; out-of-range rows (dropped/invalid) routed to a drop slot.
    row = jnp.where(keep, st, num_targets)
    col = jnp.where(keep, pos, 0)
    shape = (num_targets + 1, out_capacity)
    out = RecordBatch(
        keys=jnp.zeros(shape, jnp.int32).at[row, col].set(sk, mode="drop"),
        values=jnp.zeros(shape, jnp.int32).at[row, col].set(sv, mode="drop"),
        timestamps=jnp.zeros(shape, jnp.int32).at[row, col].set(sts, mode="drop"),
        valid=jnp.zeros(shape, jnp.bool_).at[row, col].set(keep, mode="drop"),
    )
    out = RecordBatch(out.keys[:num_targets], out.values[:num_targets],
                      out.timestamps[:num_targets], out.valid[:num_targets])
    return zero_invalid(out), dropped


def route_hash(batch: RecordBatch, parallelism: int, num_key_groups: int,
               out_capacity: int) -> Tuple[RecordBatch, jnp.ndarray]:
    """keyBy exchange (KeyGroupStreamPartitioner equivalent)."""
    kg = key_group(batch.keys, num_key_groups)
    return _scatter_to_targets(
        batch, subtask_for_key_group(kg, parallelism, num_key_groups),
        parallelism, out_capacity)


def route_rebalance(batch: RecordBatch, parallelism: int, out_capacity: int,
                    offset=0) -> Tuple[RecordBatch, jnp.ndarray]:
    """Deterministic round-robin by global record index (the reference's
    RebalancePartitioner starts at a *random* channel — randomness it must
    log via RandomService, RecordWriter.java:131-137; a deterministic cycle
    with a carried ``offset`` needs no determinant)."""
    n = batch.keys.size
    idx = jnp.arange(n, dtype=jnp.int32) + jnp.asarray(offset, jnp.int32)
    return _scatter_to_targets(batch, (idx % parallelism).reshape(batch.keys.shape),
                               parallelism, out_capacity)


def route_forward(batch: RecordBatch, out_capacity: int
                  ) -> Tuple[RecordBatch, jnp.ndarray]:
    """1:1 edge: same subtask index downstream, re-capacitied."""
    p, b = batch.keys.shape
    if out_capacity == b:
        return zero_invalid(batch), jnp.zeros((p,), jnp.int32)
    if out_capacity > b:
        pad = ((0, 0), (0, out_capacity - b))
        return RecordBatch(*(jnp.pad(x, pad) for x in batch)), jnp.zeros((p,), jnp.int32)
    keep = batch.valid[:, :out_capacity]
    dropped = batch.count() - keep.sum(-1).astype(jnp.int32)
    return zero_invalid(RecordBatch(
        batch.keys[:, :out_capacity], batch.values[:, :out_capacity],
        batch.timestamps[:, :out_capacity], keep)), dropped


def route_broadcast(batch: RecordBatch, parallelism: int, out_capacity: int
                    ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Every downstream subtask receives every record (compacted)."""
    target = jnp.zeros(batch.keys.shape, jnp.int32)
    one, dropped = _scatter_to_targets(batch, target, 1, out_capacity)
    rep = RecordBatch(*(jnp.broadcast_to(x[0], (parallelism,) + x.shape[1:])
                        for x in one))
    return rep, jnp.broadcast_to(dropped[0], (parallelism,)).astype(jnp.int32)
