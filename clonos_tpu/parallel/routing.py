"""Batched record routing: the TPU form of the network exchange.

The reference partitions record-at-a-time through channel selectors
(flink-streaming-java .../runtime/partitioner/{KeyGroupStreamPartitioner,
RebalancePartitioner,BroadcastPartitioner}.java) and moves bytes over netty
(io/network/partition/ResultPartition.java:86 ->
consumer/SingleInputGate.java:107). Here an exchange is one dense op on the
whole batch: compute a target subtask per record, stable-sort by target, and
scatter into a fixed-capacity per-subtask buffer. Under ``jit`` over a mesh
the scatter lowers to an all-to-all on ICI — XLA inserts the collective;
there is no hand-written transport.

Determinism note: routing is a pure function of the input batch (stable sort
keeps arrival order within a target), so exchanges need **no** determinants —
only the *selection* of which queued batch a multi-input vertex consumes is
nondeterministic (logged as ORDER, see runtime/executor.py).

Key-group discipline matches the reference: state is sharded by
``key_group = hash(key) % num_key_groups`` and key groups map to subtasks as
``kg * parallelism // num_key_groups``
(flink-runtime .../state/KeyGroupRangeAssignment.java).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from clonos_tpu.api.records import RecordBatch, zero_invalid


def hash32(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix32-style avalanche hash on int32 (uint32 arithmetic)."""
    u = x.astype(jnp.uint32)
    u = (u ^ (u >> 16)) * jnp.uint32(0x7FEB352D)
    u = (u ^ (u >> 15)) * jnp.uint32(0x846CA68B)
    u = u ^ (u >> 16)
    return u


def key_group(keys: jnp.ndarray, num_key_groups: int) -> jnp.ndarray:
    return (hash32(keys) % jnp.uint32(num_key_groups)).astype(jnp.int32)


def subtask_for_key_group(kg: jnp.ndarray, parallelism: int,
                          num_key_groups: int) -> jnp.ndarray:
    # Matches KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup.
    return (kg * parallelism) // num_key_groups


def key_group_range(subtask: int, parallelism: int,
                    num_key_groups: int) -> Tuple[int, int]:
    """[start, end) of key groups owned by ``subtask``."""
    start = -(-subtask * num_key_groups // parallelism)  # ceil div
    end = -(-(subtask + 1) * num_key_groups // parallelism)
    return start, end


def _scatter_to_targets(
    batch: RecordBatch, target: jnp.ndarray, num_targets: int, out_capacity: int
) -> Tuple[RecordBatch, jnp.ndarray]:
    """Core exchange: flatten, stable-sort by target, scatter to
    ``[num_targets, out_capacity]``. Returns (routed, dropped_per_target)."""
    flat = jnp.reshape
    n = batch.keys.size
    keys, vals, ts, valid = (flat(batch.keys, (n,)), flat(batch.values, (n,)),
                             flat(batch.timestamps, (n,)), flat(batch.valid, (n,)))
    target = jnp.where(valid, flat(target, (n,)), num_targets)  # invalid last
    order = jnp.argsort(target, stable=True)
    st, sk, sv, sts = target[order], keys[order], vals[order], ts[order]
    # Position of each sorted record within its target's run.
    idx = jnp.arange(n, dtype=jnp.int32)
    run_start = jnp.searchsorted(st, jnp.arange(num_targets + 1, dtype=st.dtype),
                                 side="left").astype(jnp.int32)
    pos = idx - run_start[jnp.clip(st, 0, num_targets)]
    live = st < num_targets
    keep = live & (pos < out_capacity)
    dropped = jnp.zeros((num_targets,), jnp.int32).at[st].add(
        (live & ~keep).astype(jnp.int32), mode="drop")
    # Scatter; out-of-range rows (dropped/invalid) routed to a drop slot.
    row = jnp.where(keep, st, num_targets)
    col = jnp.where(keep, pos, 0)
    shape = (num_targets + 1, out_capacity)
    out = RecordBatch(
        keys=jnp.zeros(shape, jnp.int32).at[row, col].set(sk, mode="drop"),
        values=jnp.zeros(shape, jnp.int32).at[row, col].set(sv, mode="drop"),
        timestamps=jnp.zeros(shape, jnp.int32).at[row, col].set(sts, mode="drop"),
        valid=jnp.zeros(shape, jnp.bool_).at[row, col].set(keep, mode="drop"),
    )
    out = RecordBatch(out.keys[:num_targets], out.values[:num_targets],
                      out.timestamps[:num_targets], out.valid[:num_targets])
    return zero_invalid(out), dropped


#: cap on the counting exchange's [K, n, T+1] cumsum scratch (priced at
#: ~3 concurrent buffers); routes past it fall back to the flat sort.
#: Resolved lazily from the device's memory limit (~2% of HBM — a 95GB
#: chip affords the ~0.9GB whole-recovery-window route where the sort
#: is ~10x slower, tools/ab_route.py; a small-memory device falls back
#: instead of OOMing next to its GB-scale log state). None = unresolved.
_COUNT_ROUTE_MAX_BYTES = None
_COUNT_ROUTE_FALLBACK_BYTES = 256 << 20


def _count_route_budget() -> int:
    global _COUNT_ROUTE_MAX_BYTES
    if _COUNT_ROUTE_MAX_BYTES is None:
        budget = _COUNT_ROUTE_FALLBACK_BYTES
        try:
            dev = jax.devices()[0]
            stats = dev.memory_stats() or {}
            limit = int(stats.get("bytes_limit", 0))
            if limit > 0:
                budget = max(budget, min(2 << 30, limit // 48))
            elif dev.platform == "tpu":
                # Stats unavailable (e.g. tunneled backends report
                # None): every TPU generation has >= 16GB HBM, but we
                # can't see what's free — grant 1GB (covers the
                # whole-recovery-window route, ~0.9GB at bench shapes,
                # where the sort fallback is ~10x slower) rather than
                # the full 2GB the stats path would allow.
                budget = 1 << 30
        except Exception:
            pass
        _COUNT_ROUTE_MAX_BYTES = budget
    return _COUNT_ROUTE_MAX_BYTES


def _block_to_targets(
    batch: RecordBatch, target: jnp.ndarray, num_targets: int,
    out_capacity: int
) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block-form exchange: route a whole ``[K, P, B]`` stack of per-step
    batches without sorting at all.

    A record's slot within its target is its *arrival rank*: the count of
    same-target records before it in (p-major, slot) order. With T
    targets that is a running per-bucket count — one cumsum over a
    ``[K, n, T+1]`` one-hot (invalid records get bucket T), no argsort.
    The TPU executes the cumsum as a few vector passes where the sort
    this replaced cost ~2x more at bench shapes (tools/ab_route.py, 49ms -> 24ms
    per 512-step block); placement is then ONE flat scatter of the K*n
    records into ``[K, T+1, cap]`` (the +1 row swallows drops).
    Bit-identical to vmapping :func:`_scatter_to_targets` per step,
    including overflow accounting (first ``cap`` arrivals per target
    survive, the rest count as dropped).

    Routes whose cumsum scratch would exceed ``_COUNT_ROUTE_MAX_BYTES``
    (huge T) fall back to one block-wide composite-key sort
    (``step * (T+1) + target``, stable) with gather placement.
    """
    K, P, B = batch.keys.shape
    T = num_targets
    n = P * B
    # Price the ~3 concurrent [K, n, T+1] buffers this branch holds (the
    # one-hot's int32 cast, the cumsum output, and one fusion temp), not
    # just one — the cap must actually bound peak scratch.
    if K * n * (T + 1) * 4 * 3 <= _count_route_budget():
        fl = lambda x: jnp.reshape(x, (K, n))
        keys, vals, ts, valid = map(fl, batch)
        tgt = jnp.where(valid, fl(target), T)
        onehot = (tgt[:, :, None] ==
                  jnp.arange(T + 1, dtype=jnp.int32)[None, None, :])
        pos_all = jnp.cumsum(onehot.astype(jnp.int32), axis=1)
        pos = jnp.take_along_axis(
            pos_all, tgt[:, :, None], axis=2)[:, :, 0] - 1
        counts = pos_all[:, -1, :T]
        keep = (tgt < T) & (pos < out_capacity)
        dropped = jnp.maximum(counts - out_capacity, 0).astype(jnp.int32)
        # Placement: (target, rank) pairs are UNIQUE per step, so a keyed
        # histogram over the flattened slot id IS the routed batch (sum
        # of one contribution = select) — the Pallas VPU kernel streams
        # it where an XLA element scatter ran ~50ms/field at bench
        # shapes (see _block_to_target_lane).
        from clonos_tpu.ops.histogram import keyed_hist
        nk = T * out_capacity
        # The kernel's per-chunk compare tile is [8, 128, nk-padded] i32;
        # keep it comfortably inside VMEM, else fall back to the scatter.
        if nk <= (1 << 14):
            slot = jnp.where(keep, tgt * out_capacity + pos, -1)
            out_k, cnt = keyed_hist(slot, keys, keep, nk)
            out_v, _ = keyed_hist(slot, vals, keep, nk, want_counts=False)
            out_t, _ = keyed_hist(slot, ts, keep, nk, want_counts=False)
            sh = (K, T, out_capacity)
            out = RecordBatch(out_k.reshape(sh), out_v.reshape(sh),
                              out_t.reshape(sh), cnt.reshape(sh) > 0)
            return zero_invalid(out), dropped
        row = jnp.where(keep, tgt, T)
        col = jnp.where(keep, pos, 0)
        kidx = jnp.arange(K, dtype=jnp.int32)[:, None]
        shape = (K, T + 1, out_capacity)
        mk = lambda src, z: jnp.zeros(shape, z).at[kidx, row, col].set(
            src, mode="drop")
        out = RecordBatch(mk(keys, jnp.int32), mk(vals, jnp.int32),
                          mk(ts, jnp.int32), mk(keep, jnp.bool_))
        out = RecordBatch(out.keys[:, :T], out.values[:, :T],
                          out.timestamps[:, :T], out.valid[:, :T])
        return zero_invalid(out), dropped
    # Flat-sort fallback (huge T): one composite-key sort over the block.
    if K * (T + 1) >= (1 << 31):
        raise ValueError(f"composite sort key overflow: K={K} T={T}")
    flat = lambda x: jnp.reshape(x, (K * n,))
    keys, vals, ts, valid = map(flat, batch)
    tgt = jnp.where(valid, flat(target), T)
    step = jnp.repeat(jnp.arange(K, dtype=jnp.int32), n,
                      total_repeat_length=K * n)
    composite = step * (T + 1) + tgt
    order = jnp.argsort(composite, stable=True)
    sc = composite[order]
    # Boundary of every (step, target) run: [K*(T+1)] starts.
    bounds = jnp.arange(K * (T + 1), dtype=jnp.int32)
    run_start = jnp.searchsorted(sc, bounds,
                                 side="left").astype(jnp.int32)
    run_end = jnp.concatenate(
        [run_start[1:], jnp.asarray([K * n], jnp.int32)])
    run_len = (run_end - run_start).reshape(K, T + 1)[:, :T]  # [K, T]
    dropped = jnp.maximum(run_len - out_capacity, 0).astype(jnp.int32)
    c = jnp.arange(out_capacity, dtype=jnp.int32)
    src = run_start.reshape(K, T + 1)[:, :T, None] + c[None, None, :]
    ok = (c[None, None, :]
          < jnp.minimum(run_len, out_capacity)[:, :, None])
    pick = order[jnp.clip(src, 0, K * n - 1)]                # [K, T, cap]
    out = RecordBatch(keys[pick], vals[pick], ts[pick], ok)
    return zero_invalid(out), dropped


def _block_to_target_lane(batch: RecordBatch, target: jnp.ndarray,
                          lane, out_capacity: int) -> RecordBatch:
    """ONE consumer lane of :func:`_block_to_targets` — bit-identical to
    ``_block_to_targets(...)[0][:, lane]``.

    A record's slot within its target is its arrival rank; for a single
    lane that is a running count over a ``[K, n]`` membership mask — no
    ``[K, n, T+1]`` one-hot — so scratch and compute shrink by (T+1)x
    and the single-failure replay exchange stays on the counting path
    at whole-recovery-window K, where the full route falls back to the
    flat 67M-record sort (~400ms at bench shapes; this is ~10x less)."""
    from clonos_tpu.ops.histogram import keyed_hist
    K, P, B = batch.keys.shape
    n = P * B
    fl = lambda x: jnp.reshape(x, (K, n))
    keys, vals, ts, valid = map(fl, batch)
    tgt = jnp.where(valid, fl(target), -1)
    hit = tgt == lane
    pos = jnp.cumsum(hit.astype(jnp.int32), axis=1) - 1
    keep = hit & (pos < out_capacity)
    # Placement is "field value at the record whose rank == c" — ranks
    # are UNIQUE per step, so a keyed histogram over them IS the routed
    # batch (sum of one contribution = select). The Pallas VPU kernel
    # streams it in compare-accumulate chunks; an XLA element scatter
    # here ran ~50ms/field at bench shapes, the kernel ~5ms.
    slot = jnp.where(keep, pos, -1)
    out_k, cnt = keyed_hist(slot, keys, keep, out_capacity)
    out_v, _ = keyed_hist(slot, vals, keep, out_capacity,
                          want_counts=False)
    out_t, _ = keyed_hist(slot, ts, keep, out_capacity,
                          want_counts=False)
    return zero_invalid(RecordBatch(out_k, out_v, out_t, cnt > 0))


def route_hash_block_lane(batch: RecordBatch, lane, parallelism: int,
                          num_key_groups: int, out_capacity: int
                          ) -> RecordBatch:
    """One consumer lane of :func:`route_hash_block` (single-failure
    replay: only the failed subtask's inputs are reconstructed)."""
    kg = key_group(batch.keys, num_key_groups)
    return _block_to_target_lane(
        batch, subtask_for_key_group(kg, parallelism, num_key_groups),
        lane, out_capacity)


def route_rebalance_block_lane(batch: RecordBatch, lane, parallelism: int,
                               out_capacity: int, offsets: jnp.ndarray
                               ) -> RecordBatch:
    """One consumer lane of :func:`route_rebalance_block`."""
    K, P, B = batch.keys.shape
    idx = jnp.arange(P * B, dtype=jnp.int32)[None, :] + offsets[:, None]
    return _block_to_target_lane(
        batch, (idx % parallelism).reshape(K, P, B), lane, out_capacity)


def route_broadcast_block_lane(batch: RecordBatch, lane,
                               out_capacity: int) -> RecordBatch:
    """One consumer lane of :func:`route_broadcast_block` (every lane
    receives the same packed records; ``lane`` is ignored)."""
    del lane
    return _block_to_target_lane(
        batch, jnp.zeros(batch.keys.shape, jnp.int32), 0, out_capacity)


def route_forward_block_lane(batch: RecordBatch, lane,
                             out_capacity: int) -> RecordBatch:
    """One consumer lane of :func:`route_forward_block`."""
    one = jax.tree_util.tree_map(lambda x: x[:, lane][:, None], batch)
    routed, _ = route_forward_block(one, out_capacity)
    return jax.tree_util.tree_map(lambda x: x[:, 0], routed)


def route_hash(batch: RecordBatch, parallelism: int, num_key_groups: int,
               out_capacity: int) -> Tuple[RecordBatch, jnp.ndarray]:
    """keyBy exchange (KeyGroupStreamPartitioner equivalent)."""
    kg = key_group(batch.keys, num_key_groups)
    return _scatter_to_targets(
        batch, subtask_for_key_group(kg, parallelism, num_key_groups),
        parallelism, out_capacity)


def route_hash_block(batch: RecordBatch, parallelism: int,
                     num_key_groups: int, out_capacity: int
                     ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block form of :func:`route_hash` over ``[K, P, B]`` stacks; returns
    (routed ``[K, parallelism, out_capacity]``, dropped ``[K, parallelism]``),
    bit-identical to ``vmap(route_hash)``."""
    kg = key_group(batch.keys, num_key_groups)
    return _block_to_targets(
        batch, subtask_for_key_group(kg, parallelism, num_key_groups),
        parallelism, out_capacity)


def route_rebalance_block(batch: RecordBatch, parallelism: int,
                          out_capacity: int, offsets: jnp.ndarray
                          ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block form of :func:`route_rebalance`; ``offsets`` is the ``[K]``
    per-step exclusive round-robin cursor."""
    K, P, B = batch.keys.shape
    idx = jnp.arange(P * B, dtype=jnp.int32)[None, :] + offsets[:, None]
    return _block_to_targets(batch, (idx % parallelism).reshape(K, P, B),
                             parallelism, out_capacity)


def route_broadcast_block(batch: RecordBatch, parallelism: int,
                          out_capacity: int
                          ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block form of :func:`route_broadcast`."""
    K = batch.keys.shape[0]
    one, dropped = _block_to_targets(
        batch, jnp.zeros(batch.keys.shape, jnp.int32), 1, out_capacity)
    rep = RecordBatch(*(jnp.broadcast_to(
        x[:, :1], (K, parallelism) + x.shape[2:]) for x in one))
    return rep, jnp.broadcast_to(dropped[:, :1], (K, parallelism)
                                 ).astype(jnp.int32)


def route_forward_block(batch: RecordBatch, out_capacity: int
                        ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block form of :func:`route_forward` (no exchange; re-capacity)."""
    K, P, B = batch.keys.shape
    if out_capacity == B:
        return zero_invalid(batch), jnp.zeros((K, P), jnp.int32)
    if out_capacity > B:
        pad = ((0, 0), (0, 0), (0, out_capacity - B))
        return (RecordBatch(*(jnp.pad(x, pad) for x in batch)),
                jnp.zeros((K, P), jnp.int32))
    keep = batch.valid[:, :, :out_capacity]
    dropped = batch.count() - keep.sum(-1).astype(jnp.int32)
    return zero_invalid(RecordBatch(
        batch.keys[:, :, :out_capacity], batch.values[:, :, :out_capacity],
        batch.timestamps[:, :, :out_capacity], keep)), dropped


def hash32_np(x: np.ndarray) -> np.ndarray:
    """Host-side (numpy) twin of :func:`hash32` for compile-time planning."""
    u = np.asarray(x, np.uint64) & 0xFFFFFFFF
    u = ((u ^ (u >> 16)) * 0x7FEB352D) & 0xFFFFFFFF
    u = ((u ^ (u >> 15)) * 0x846CA68B) & 0xFFFFFFFF
    return (u ^ (u >> 16)) & 0xFFFFFFFF


@dataclasses.dataclass
class StaticRoutePlan:
    """Compile-time hash exchange for producers whose output slots carry
    *statically known* keys (dense table emitters like the window operator:
    slot ``i`` always holds key ``i``).

    Because each slot's key — hence key group, hence target subtask — is a
    compile-time constant, routing needs **no sort and no dynamic
    placement**: the routed batch is a static gather
    ``out[k, t, c] = producer_out[k, src_p[t, c], src_slot[t, c]]``. This
    turns the hottest exchange of keyed pipelines into a few fast vector
    loads (the dynamic block sort costs hundreds of ms per block at bench
    shapes; this costs ~nothing).

    Semantics note: slots are *not* compacted — output slot (t, c) is bound
    to one (producer, slot) pair, and a step's invalid slots stay invalid
    holes. The per-step multiset of valid records equals the dynamic
    exchange's; only the slot layout differs. Capacity overflow drops whole
    *static slots* (deterministically), recorded in ``drop_p/drop_slot``
    for per-step drop accounting. Arrival order within a target (p-major,
    slot ascending) matches the dynamic exchange's stable sort.
    """

    src_p: np.ndarray      # int32 [T, cap]: producer subtask per out slot
    src_slot: np.ndarray   # int32 [T, cap]: producer slot per out slot
    ok: np.ndarray         # bool  [T, cap]: out slot is mapped
    slot_keys: np.ndarray  # int32 [T, cap]: static key (-1 = unmapped)
    drop_p: np.ndarray     # int32 [D]: overflow slots (producer subtask)
    drop_slot: np.ndarray  # int32 [D]
    drop_t: np.ndarray     # int32 [D]: target the overflow belonged to

    def apply(self, out: RecordBatch) -> Tuple[RecordBatch, jnp.ndarray]:
        """Route a producer block ``[K, P, B]`` -> ``[K, T, cap]``."""
        K = out.keys.shape[0]
        T = self.src_p.shape[0]
        g = lambda x: x[:, self.src_p, self.src_slot]
        valid = g(out.valid) & self.ok[None]
        routed = zero_invalid(RecordBatch(
            g(out.keys), g(out.values), g(out.timestamps), valid))
        if len(self.drop_p):
            dv = out.valid[:, self.drop_p, self.drop_slot]  # [K, D]
            dropped = jnp.zeros((K, T), jnp.int32).at[
                :, self.drop_t].add(dv.astype(jnp.int32))
        else:
            dropped = jnp.zeros((K, T), jnp.int32)
        return routed, dropped


def _static_targets(slot_keys: np.ndarray, parallelism: int,
                    num_key_groups: int) -> np.ndarray:
    """Target subtask of each static slot key — THE key->key-group->
    subtask map; every compile-time consumer must share this one copy."""
    kg = (hash32_np(slot_keys) % num_key_groups).astype(np.int64)
    return (kg * parallelism) // num_key_groups


def static_hash_capacity(slot_keys: np.ndarray, src_parallelism: int,
                         parallelism: int, num_key_groups: int) -> int:
    """Smallest per-target receive capacity for which
    :func:`plan_static_hash` has no overflow (drop) slots: the densest
    target's key count times the producer parallelism."""
    slot_keys = np.asarray(slot_keys, np.int64)
    tgt = _static_targets(slot_keys, parallelism, num_key_groups)
    return int(np.bincount(tgt, minlength=parallelism).max()) \
        * src_parallelism


def plan_static_hash(slot_keys: np.ndarray, src_parallelism: int,
                     parallelism: int, num_key_groups: int,
                     out_capacity: int) -> StaticRoutePlan:
    """Build a :class:`StaticRoutePlan` for a HASH edge whose producer
    emits key ``slot_keys[i]`` in slot ``i`` on every subtask."""
    slot_keys = np.asarray(slot_keys, np.int64)
    B = slot_keys.shape[0]
    tgt = _static_targets(slot_keys, parallelism, num_key_groups)
    T, cap = parallelism, out_capacity
    src_p = np.zeros((T, cap), np.int32)
    src_slot = np.zeros((T, cap), np.int32)
    ok = np.zeros((T, cap), bool)
    keys_out = np.full((T, cap), -1, np.int32)
    drop_p, drop_slot, drop_t = [], [], []
    for t in range(T):
        slots = np.nonzero(tgt == t)[0]
        c = 0
        for p in range(src_parallelism):      # p-major = arrival order
            for s in slots:
                if c < cap:
                    src_p[t, c] = p
                    src_slot[t, c] = s
                    ok[t, c] = True
                    keys_out[t, c] = slot_keys[s]
                    c += 1
                else:
                    drop_p.append(p)
                    drop_slot.append(s)
                    drop_t.append(t)
    return StaticRoutePlan(
        src_p=src_p, src_slot=src_slot, ok=ok, slot_keys=keys_out,
        drop_p=np.asarray(drop_p, np.int32),
        drop_slot=np.asarray(drop_slot, np.int32),
        drop_t=np.asarray(drop_t, np.int32))


def route_rebalance(batch: RecordBatch, parallelism: int, out_capacity: int,
                    offset=0) -> Tuple[RecordBatch, jnp.ndarray]:
    """Deterministic round-robin by global record index (the reference's
    RebalancePartitioner starts at a *random* channel — randomness it must
    log via RandomService, RecordWriter.java:131-137; a deterministic cycle
    with a carried ``offset`` needs no determinant)."""
    n = batch.keys.size
    idx = jnp.arange(n, dtype=jnp.int32) + jnp.asarray(offset, jnp.int32)
    return _scatter_to_targets(batch, (idx % parallelism).reshape(batch.keys.shape),
                               parallelism, out_capacity)


def route_forward(batch: RecordBatch, out_capacity: int
                  ) -> Tuple[RecordBatch, jnp.ndarray]:
    """1:1 edge: same subtask index downstream, re-capacitied."""
    p, b = batch.keys.shape
    if out_capacity == b:
        return zero_invalid(batch), jnp.zeros((p,), jnp.int32)
    if out_capacity > b:
        pad = ((0, 0), (0, out_capacity - b))
        return RecordBatch(*(jnp.pad(x, pad) for x in batch)), jnp.zeros((p,), jnp.int32)
    keep = batch.valid[:, :out_capacity]
    dropped = batch.count() - keep.sum(-1).astype(jnp.int32)
    return zero_invalid(RecordBatch(
        batch.keys[:, :out_capacity], batch.values[:, :out_capacity],
        batch.timestamps[:, :out_capacity], keep)), dropped


def route_broadcast(batch: RecordBatch, parallelism: int, out_capacity: int
                    ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Every downstream subtask receives every record (compacted)."""
    target = jnp.zeros(batch.keys.shape, jnp.int32)
    one, dropped = _scatter_to_targets(batch, target, 1, out_capacity)
    rep = RecordBatch(*(jnp.broadcast_to(x[0], (parallelism,) + x.shape[1:])
                        for x in one))
    return rep, jnp.broadcast_to(dropped[0], (parallelism,)).astype(jnp.int32)
