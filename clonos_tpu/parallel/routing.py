"""Batched record routing: the TPU form of the network exchange.

The reference partitions record-at-a-time through channel selectors
(flink-streaming-java .../runtime/partitioner/{KeyGroupStreamPartitioner,
RebalancePartitioner,BroadcastPartitioner}.java) and moves bytes over netty
(io/network/partition/ResultPartition.java:86 ->
consumer/SingleInputGate.java:107). Here an exchange is one dense op on the
whole batch: compute a target subtask per record, stable-sort by target, and
scatter into a fixed-capacity per-subtask buffer. Under ``jit`` over a mesh
the scatter lowers to an all-to-all on ICI — XLA inserts the collective;
there is no hand-written transport.

Determinism note: routing is a pure function of the input batch (stable sort
keeps arrival order within a target), so exchanges need **no** determinants —
only the *selection* of which queued batch a multi-input vertex consumes is
nondeterministic (logged as ORDER, see runtime/executor.py).

Key-group discipline matches the reference: state is sharded by
``key_group = hash(key) % num_key_groups`` and key groups map to subtasks as
``kg * parallelism // num_key_groups``
(flink-runtime .../state/KeyGroupRangeAssignment.java).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from clonos_tpu.api.records import RecordBatch, zero_invalid


def hash32(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix32-style avalanche hash on int32 (uint32 arithmetic)."""
    u = x.astype(jnp.uint32)
    u = (u ^ (u >> 16)) * jnp.uint32(0x7FEB352D)
    u = (u ^ (u >> 15)) * jnp.uint32(0x846CA68B)
    u = u ^ (u >> 16)
    return u


def key_group(keys: jnp.ndarray, num_key_groups: int) -> jnp.ndarray:
    return (hash32(keys) % jnp.uint32(num_key_groups)).astype(jnp.int32)


def subtask_for_key_group(kg: jnp.ndarray, parallelism: int,
                          num_key_groups: int) -> jnp.ndarray:
    # Matches KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup.
    return (kg * parallelism) // num_key_groups


def key_group_range(subtask: int, parallelism: int,
                    num_key_groups: int) -> Tuple[int, int]:
    """[start, end) of key groups owned by ``subtask``."""
    start = -(-subtask * num_key_groups // parallelism)  # ceil div
    end = -(-(subtask + 1) * num_key_groups // parallelism)
    return start, end


def _scatter_to_targets(
    batch: RecordBatch, target: jnp.ndarray, num_targets: int, out_capacity: int
) -> Tuple[RecordBatch, jnp.ndarray]:
    """Core exchange: flatten, stable-sort by target, scatter to
    ``[num_targets, out_capacity]``. Returns (routed, dropped_per_target)."""
    flat = jnp.reshape
    n = batch.keys.size
    keys, vals, ts, valid = (flat(batch.keys, (n,)), flat(batch.values, (n,)),
                             flat(batch.timestamps, (n,)), flat(batch.valid, (n,)))
    target = jnp.where(valid, flat(target, (n,)), num_targets)  # invalid last
    order = jnp.argsort(target, stable=True)
    st, sk, sv, sts = target[order], keys[order], vals[order], ts[order]
    # Position of each sorted record within its target's run.
    idx = jnp.arange(n, dtype=jnp.int32)
    run_start = jnp.searchsorted(st, jnp.arange(num_targets + 1, dtype=st.dtype),
                                 side="left").astype(jnp.int32)
    pos = idx - run_start[jnp.clip(st, 0, num_targets)]
    live = st < num_targets
    keep = live & (pos < out_capacity)
    dropped = jnp.zeros((num_targets,), jnp.int32).at[st].add(
        (live & ~keep).astype(jnp.int32), mode="drop")
    # Scatter; out-of-range rows (dropped/invalid) routed to a drop slot.
    row = jnp.where(keep, st, num_targets)
    col = jnp.where(keep, pos, 0)
    shape = (num_targets + 1, out_capacity)
    out = RecordBatch(
        keys=jnp.zeros(shape, jnp.int32).at[row, col].set(sk, mode="drop"),
        values=jnp.zeros(shape, jnp.int32).at[row, col].set(sv, mode="drop"),
        timestamps=jnp.zeros(shape, jnp.int32).at[row, col].set(sts, mode="drop"),
        valid=jnp.zeros(shape, jnp.bool_).at[row, col].set(keep, mode="drop"),
    )
    out = RecordBatch(out.keys[:num_targets], out.values[:num_targets],
                      out.timestamps[:num_targets], out.valid[:num_targets])
    return zero_invalid(out), dropped


def _block_to_targets(
    batch: RecordBatch, target: jnp.ndarray, num_targets: int,
    out_capacity: int
) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block-form exchange: route a whole ``[K, P, B]`` stack of per-step
    batches in ONE sort instead of K vmapped sorts.

    Composite sort key = ``step * (T+1) + target`` (invalid records get
    target T): one stable flat argsort of ``K*P*B`` int32 keys groups
    records by (step, target) while preserving arrival order within each
    group — bit-identical to vmapping :func:`_scatter_to_targets` per step.
    Placement is then a *gather* ``out[k, t, c] = sorted[run_start[k,t]+c]``
    (run starts via searchsorted), which the TPU executes as fast vector
    loads — unlike the per-step scatter this replaces, which XLA
    serializes. ~5x faster at bench shapes (tools/ab_kernels2.py).

    Range guard: needs ``K * (T+1) < 2^31``; checked.
    """
    K, P, B = batch.keys.shape
    T = num_targets
    n = P * B
    if K * (T + 1) >= (1 << 31):
        raise ValueError(f"composite sort key overflow: K={K} T={T}")
    flat = lambda x: jnp.reshape(x, (K * n,))
    keys, vals, ts, valid = map(flat, batch)
    tgt = jnp.where(valid, flat(target), T)
    step = jnp.repeat(jnp.arange(K, dtype=jnp.int32), n,
                      total_repeat_length=K * n)
    composite = step * (T + 1) + tgt
    order = jnp.argsort(composite, stable=True)
    sc = composite[order]
    # Boundary of every (step, target) run: [K*(T+1)] starts.
    bounds = jnp.arange(K * (T + 1), dtype=jnp.int32)
    run_start = jnp.searchsorted(sc, bounds, side="left").astype(jnp.int32)
    run_end = jnp.concatenate(
        [run_start[1:], jnp.asarray([K * n], jnp.int32)])
    run_len = (run_end - run_start).reshape(K, T + 1)[:, :T]     # [K, T]
    dropped = jnp.maximum(run_len - out_capacity, 0).astype(jnp.int32)
    c = jnp.arange(out_capacity, dtype=jnp.int32)
    src = run_start.reshape(K, T + 1)[:, :T, None] + c[None, None, :]
    ok = c[None, None, :] < jnp.minimum(run_len, out_capacity)[:, :, None]
    pick = order[jnp.clip(src, 0, K * n - 1)]                    # [K, T, cap]
    out = RecordBatch(keys[pick], vals[pick], ts[pick], ok)
    return zero_invalid(out), dropped


def route_hash(batch: RecordBatch, parallelism: int, num_key_groups: int,
               out_capacity: int) -> Tuple[RecordBatch, jnp.ndarray]:
    """keyBy exchange (KeyGroupStreamPartitioner equivalent)."""
    kg = key_group(batch.keys, num_key_groups)
    return _scatter_to_targets(
        batch, subtask_for_key_group(kg, parallelism, num_key_groups),
        parallelism, out_capacity)


def route_hash_block(batch: RecordBatch, parallelism: int,
                     num_key_groups: int, out_capacity: int
                     ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block form of :func:`route_hash` over ``[K, P, B]`` stacks; returns
    (routed ``[K, parallelism, out_capacity]``, dropped ``[K, parallelism]``),
    bit-identical to ``vmap(route_hash)``."""
    kg = key_group(batch.keys, num_key_groups)
    return _block_to_targets(
        batch, subtask_for_key_group(kg, parallelism, num_key_groups),
        parallelism, out_capacity)


def route_rebalance_block(batch: RecordBatch, parallelism: int,
                          out_capacity: int, offsets: jnp.ndarray
                          ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block form of :func:`route_rebalance`; ``offsets`` is the ``[K]``
    per-step exclusive round-robin cursor."""
    K, P, B = batch.keys.shape
    idx = jnp.arange(P * B, dtype=jnp.int32)[None, :] + offsets[:, None]
    return _block_to_targets(batch, (idx % parallelism).reshape(K, P, B),
                             parallelism, out_capacity)


def route_broadcast_block(batch: RecordBatch, parallelism: int,
                          out_capacity: int
                          ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block form of :func:`route_broadcast`."""
    K = batch.keys.shape[0]
    one, dropped = _block_to_targets(
        batch, jnp.zeros(batch.keys.shape, jnp.int32), 1, out_capacity)
    rep = RecordBatch(*(jnp.broadcast_to(
        x[:, :1], (K, parallelism) + x.shape[2:]) for x in one))
    return rep, jnp.broadcast_to(dropped[:, :1], (K, parallelism)
                                 ).astype(jnp.int32)


def route_forward_block(batch: RecordBatch, out_capacity: int
                        ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Block form of :func:`route_forward` (no exchange; re-capacity)."""
    K, P, B = batch.keys.shape
    if out_capacity == B:
        return zero_invalid(batch), jnp.zeros((K, P), jnp.int32)
    if out_capacity > B:
        pad = ((0, 0), (0, 0), (0, out_capacity - B))
        return (RecordBatch(*(jnp.pad(x, pad) for x in batch)),
                jnp.zeros((K, P), jnp.int32))
    keep = batch.valid[:, :, :out_capacity]
    dropped = batch.count() - keep.sum(-1).astype(jnp.int32)
    return zero_invalid(RecordBatch(
        batch.keys[:, :, :out_capacity], batch.values[:, :, :out_capacity],
        batch.timestamps[:, :, :out_capacity], keep)), dropped


def route_rebalance(batch: RecordBatch, parallelism: int, out_capacity: int,
                    offset=0) -> Tuple[RecordBatch, jnp.ndarray]:
    """Deterministic round-robin by global record index (the reference's
    RebalancePartitioner starts at a *random* channel — randomness it must
    log via RandomService, RecordWriter.java:131-137; a deterministic cycle
    with a carried ``offset`` needs no determinant)."""
    n = batch.keys.size
    idx = jnp.arange(n, dtype=jnp.int32) + jnp.asarray(offset, jnp.int32)
    return _scatter_to_targets(batch, (idx % parallelism).reshape(batch.keys.shape),
                               parallelism, out_capacity)


def route_forward(batch: RecordBatch, out_capacity: int
                  ) -> Tuple[RecordBatch, jnp.ndarray]:
    """1:1 edge: same subtask index downstream, re-capacitied."""
    p, b = batch.keys.shape
    if out_capacity == b:
        return zero_invalid(batch), jnp.zeros((p,), jnp.int32)
    if out_capacity > b:
        pad = ((0, 0), (0, out_capacity - b))
        return RecordBatch(*(jnp.pad(x, pad) for x in batch)), jnp.zeros((p,), jnp.int32)
    keep = batch.valid[:, :out_capacity]
    dropped = batch.count() - keep.sum(-1).astype(jnp.int32)
    return zero_invalid(RecordBatch(
        batch.keys[:, :out_capacity], batch.values[:, :out_capacity],
        batch.timestamps[:, :out_capacity], keep)), dropped


def route_broadcast(batch: RecordBatch, parallelism: int, out_capacity: int
                    ) -> Tuple[RecordBatch, jnp.ndarray]:
    """Every downstream subtask receives every record (compacted)."""
    target = jnp.zeros(batch.keys.shape, jnp.int32)
    one, dropped = _scatter_to_targets(batch, target, 1, out_capacity)
    rep = RecordBatch(*(jnp.broadcast_to(x[0], (parallelism,) + x.shape[1:])
                        for x in one))
    return rep, jnp.broadcast_to(dropped[0], (parallelism,)).astype(jnp.int32)
