"""ctypes loader for the C++ runtime components (native/*.cpp).

The compute path is JAX/XLA/Pallas; the byte-level runtime around it
(delta wire codec CRC/framing) is C++ where the reference's is native
(Netty direct buffers). No pybind11 in the image, so the boundary is
plain C ABI via ctypes; builds lazily with the baked-in toolchain and
falls back to bit-identical pure Python (zlib) when compilation is
unavailable. ``tests/test_serde.py`` pins native == fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib

import numpy as np

_lock = threading.Lock()
_lib = None
_tried = False


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        src = os.path.join(_repo_root(), "native", "delta_codec.cpp")
        if not os.path.exists(src):
            return None
        so = os.path.join(_repo_root(), "native", "libdelta_codec.so")
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                # Build to a private temp path, then atomically publish:
                # concurrent processes (pytest-xdist, the two-process
                # remote tests) must never dlopen a half-written ELF.
                # clonos: allow(entropy): pid only names a private
                # temp file — never replayed data
                tmp = f"{so}.tmp.{os.getpid()}"
                try:
                    subprocess.run(
                        ["c++", "-O3", "-shared", "-fPIC", "-o", tmp, src],
                        check=True, capture_output=True, timeout=120)
                    os.replace(tmp, so)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            lib = ctypes.CDLL(so)
            lib.dc_crc32.restype = ctypes.c_uint32
            lib.dc_crc32.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.dc_encode_flat.restype = ctypes.c_int64
            lib.dc_encode_flat.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_int64]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def crc32(rows: np.ndarray) -> int:
    """CRC-32 (zlib polynomial) over a contiguous int32 array."""
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    lib = _load()
    if lib is not None:
        return int(lib.dc_crc32(rows.ctypes.data, rows.nbytes))
    return zlib.crc32(rows.tobytes()) & 0xFFFFFFFF


def encode_flat_entries(log_ids: np.ndarray, starts: np.ndarray,
                        n_rows: np.ndarray, rows_concat: np.ndarray,
                        lanes: int) -> bytes:
    """FLAT delta entry stream (everything after the frame header) in one
    native pass; None-safe fallback is handled by the caller (serde)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    log_ids = np.ascontiguousarray(log_ids, np.int32)
    starts = np.ascontiguousarray(starts, np.int32)
    n_rows_a = np.ascontiguousarray(n_rows, np.uint32)
    rows_concat = np.ascontiguousarray(rows_concat, np.int32)
    cap = (12 + 4) * len(log_ids) + rows_concat.nbytes + 16
    out = (ctypes.c_uint8 * cap)()
    n = lib.dc_encode_flat(
        log_ids.ctypes.data, starts.ctypes.data, n_rows_a.ctypes.data,
        len(log_ids), rows_concat.ctypes.data, lanes,
        ctypes.addressof(out), cap)
    if n < 0:
        raise RuntimeError("native encode buffer overflow")
    return bytes(bytearray(out)[:n])
