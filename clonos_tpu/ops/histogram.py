"""Keyed histogram (segment-sum) kernel: the TPU replacement for the
scatter-add at the heart of every keyed aggregation.

The reference aggregates record-at-a-time into hash-keyed state
(flink-runtime .../state/heap/HeapKeyedStateBackend.java ValueState
update per record). The dense-table TPU design turns that into a per-step
histogram ``contrib[row, key] = sum(values where keys == key)`` — but
XLA's scatter-add serializes its updates on TPU (60-120ms at bench shapes
for ~4M updates). This Pallas kernel streams the records through the VPU
as chunked compare-accumulate instead: for each 128-record chunk, a
``[rows, chunk, key_lanes]`` one-hot compare and an axis reduce — no
scatter anywhere, ~10x faster (tools/profile_block.py).

On non-TPU backends (the CPU test lane) a bit-identical XLA scatter
fallback runs instead; ``tests/test_pallas_kernels.py`` pins kernel ==
fallback in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: rows per kernel program (VPU sublane count)
_ROW_TILE = 8
#: record columns per in-kernel chunk (VPU lane count)
_COL_CHUNK = 128


def _hist_kernel(keys_ref, vals_ref, sum_ref, cnt_ref):
    rt, b = keys_ref.shape
    nkp = sum_ref.shape[1]
    nchunks = b // _COL_CHUNK

    def body(i, carry):
        sums, cnts = carry
        kc = keys_ref[:, pl.ds(i * _COL_CHUNK, _COL_CHUNK)]   # [RT, C]
        vc = vals_ref[:, pl.ds(i * _COL_CHUNK, _COL_CHUNK)]
        iota = jax.lax.broadcasted_iota(jnp.int32, (rt, _COL_CHUNK, nkp), 2)
        oh = kc[:, :, None] == iota
        sums = sums + jnp.sum(jnp.where(oh, vc[:, :, None], 0), axis=1)
        cnts = cnts + jnp.sum(oh.astype(jnp.int32), axis=1)
        return sums, cnts

    sums, cnts = jax.lax.fori_loop(
        0, nchunks, body,
        (jnp.zeros((rt, nkp), jnp.int32), jnp.zeros((rt, nkp), jnp.int32)))
    sum_ref[:] = sums
    cnt_ref[:] = cnts


def _hist_kernel_sums(keys_ref, vals_ref, sum_ref):
    # Sums-only variant: half the vector work of _hist_kernel (the keyed
    # aggregation operators never use the counts).
    rt, b = keys_ref.shape
    nkp = sum_ref.shape[1]
    nchunks = b // _COL_CHUNK

    def body(i, sums):
        kc = keys_ref[:, pl.ds(i * _COL_CHUNK, _COL_CHUNK)]
        vc = vals_ref[:, pl.ds(i * _COL_CHUNK, _COL_CHUNK)]
        iota = jax.lax.broadcasted_iota(jnp.int32, (rt, _COL_CHUNK, nkp), 2)
        oh = kc[:, :, None] == iota
        return sums + jnp.sum(jnp.where(oh, vc[:, :, None], 0), axis=1)

    sum_ref[:] = jax.lax.fori_loop(
        0, nchunks, body, jnp.zeros((rt, nkp), jnp.int32))


def _pad_to(x: jnp.ndarray, axis: int, mult: int,
            fill: int = 0) -> jnp.ndarray:
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _hist_pallas(keys, vals, valid, nk: int, interpret: bool,
                 want_counts: bool = True):
    r, b = keys.shape
    nkp = -(-nk // _COL_CHUNK) * _COL_CHUNK
    # Invalid records AND pad slots get key -1 (matches nothing) — a 0-pad
    # would count phantom records of key 0.
    k = _pad_to(jnp.where(valid, keys, -1), 1, _COL_CHUNK, fill=-1)
    k = _pad_to(k, 0, _ROW_TILE, fill=-1)
    v = _pad_to(jnp.where(valid, vals, 0), 1, _COL_CHUNK)
    v = _pad_to(v, 0, _ROW_TILE)
    rp, bp = k.shape
    grid = (rp // _ROW_TILE,)
    spec_in = pl.BlockSpec((_ROW_TILE, bp), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    spec_out = pl.BlockSpec((_ROW_TILE, nkp), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    if not want_counts:
        sums = pl.pallas_call(
            _hist_kernel_sums,
            out_shape=jax.ShapeDtypeStruct((rp, nkp), jnp.int32),
            grid=grid,
            in_specs=[spec_in, spec_in],
            out_specs=spec_out,
            interpret=interpret,
        )(k, v)
        return sums[:r, :nk], None
    sums, cnts = pl.pallas_call(
        _hist_kernel,
        out_shape=(jax.ShapeDtypeStruct((rp, nkp), jnp.int32),
                   jax.ShapeDtypeStruct((rp, nkp), jnp.int32)),
        grid=grid,
        in_specs=[spec_in, spec_in],
        out_specs=(spec_out, spec_out),
        interpret=interpret,
    )(k, v)
    return sums[:r, :nk], cnts[:r, :nk]


def _hist_xla(keys, vals, valid, nk: int):
    """Scatter-add fallback (bit-identical; used off-TPU)."""
    r, b = keys.shape
    row = jnp.broadcast_to(jnp.arange(r, dtype=jnp.int32)[:, None],
                           keys.shape)
    sums = jnp.zeros((r, nk), jnp.int32).at[row, keys].add(
        jnp.where(valid, vals, 0), mode="drop")
    cnts = jnp.zeros((r, nk), jnp.int32).at[row, keys].add(
        valid.astype(jnp.int32), mode="drop")
    return sums, cnts


def keyed_hist(keys: jnp.ndarray, vals: jnp.ndarray, valid: jnp.ndarray,
               nk: int, force: str = "", want_counts: bool = True):
    """Per-row keyed sums and counts.

    ``keys/vals/valid``: ``[..., B]`` (any leading dims, flattened to rows).
    Returns ``(sums, counts)`` of shape ``[..., nk]`` — for each row, the
    sum of ``vals`` and the count of records carrying each key in
    ``[0, nk)``. Out-of-range keys are dropped (scatter ``mode=drop``
    parity). ``force``: "pallas" | "interpret" | "xla" | "" (auto: pallas
    on TPU, xla elsewhere). ``want_counts=False`` skips the count output
    (returned as None) — half the kernel work; the aggregation operators
    only need sums.
    """
    lead = keys.shape[:-1]
    b = keys.shape[-1]
    r = 1
    for d in lead:
        r *= d
    kf = keys.reshape(r, b)
    vf = vals.reshape(r, b)
    mf = valid.reshape(r, b)
    mode = force or ("pallas" if jax.default_backend() == "tpu" else "xla")
    if mode == "pallas":
        sums, cnts = _hist_pallas(kf, vf, mf, nk, False, want_counts)
    elif mode == "interpret":
        sums, cnts = _hist_pallas(kf, vf, mf, nk, True, want_counts)
    else:
        # Out-of-range guard to mirror mode="drop" exactly.
        ok = mf & (kf >= 0) & (kf < nk)
        sums, cnts = _hist_xla(jnp.where(ok, kf, 0), vf, ok, nk)
        if not want_counts:
            cnts = None
    return (sums.reshape(lead + (nk,)),
            cnts.reshape(lead + (nk,)) if cnts is not None else None)
