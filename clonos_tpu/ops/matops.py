"""MXU-backed exact integer data movement.

Dynamic gathers along the step axis are slow on TPU (~40ms for a
[512, 8, 997] take_along_axis at bench shapes) while one-hot f32 matmuls
on the MXU are ~free. These helpers express int32 gathers as two-matmul
(16-bit split) one-hot contractions with ``Precision.HIGHEST`` — exact
over the full int32 range (each product is 0/1 x 16-bit value; a row has
exactly one nonzero, so f32 accumulation is exact).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_HI = jax.lax.Precision.HIGHEST


def onehot_gather_rows(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``out[k, p, :] = table[idx[k, p], p, :]`` — exact int32 gather along
    axis 0 of a ``[J, P, N]`` table, as two MXU one-hot matmuls.

    ``idx`` must already be clipped to ``[0, J)``.
    """
    j = table.shape[0]
    oh = (idx[:, :, None]
          == jnp.arange(j, dtype=jnp.int32)[None, None, :]
          ).astype(jnp.float32)                           # [K, P, J]
    lo = (table & 0xFFFF).astype(jnp.float32)
    hi = jnp.right_shift(table, 16).astype(jnp.float32)
    glo = jnp.einsum("kpj,jpn->kpn", oh, lo, precision=_HI,
                     preferred_element_type=jnp.float32).astype(jnp.int32)
    ghi = jnp.einsum("kpj,jpn->kpn", oh, hi, precision=_HI,
                     preferred_element_type=jnp.float32).astype(jnp.int32)
    return glo + (ghi << 16)
