"""Pallas TPU kernels for the causal-log hot path.

SURVEY.md §7 marks the determinant log append as the #1 KERNEL (the
reference's per-record JVM hot path, ThreadCausalLogImpl.appendDeterminant:
158). The XLA fallback (causal/log.py append is a masked scatter) is
correct everywhere; this kernel is the TPU-native fast path.

Hardware constraint that shapes the design: TPU DMA and VMEM slicing
operate at 128-lane-line granularity — a determinant row is 8 int32 lanes,
so sub-line writes are impossible. The kernel therefore does a
**line-grained read-modify-write**: an append of up to 16 rows touches at
most two 128-lane lines of the ring; those lines are DMA'd HBM->VMEM,
merged with the new rows by a one-hot matmul select (MXU-friendly gather),
and DMA'd back — while the ring itself stays in HBM and is aliased in
place. Total traffic per log per append: <= 2 lines in + 2 lines out
(2 KiB), independent of ring capacity.

Grid: one program per log (stacked [L, capacity, lanes] layout).
``interpret=True`` runs the same kernel on CPU (tests)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from clonos_tpu.causal.determinant import NUM_LANES

LINE = 128
ROWS_PER_LINE = LINE // NUM_LANES          # 16 determinant rows per line
MAX_APPEND_ROWS = ROWS_PER_LINE            # one line of new rows per call


@functools.partial(jax.jit, static_argnames=("interpret",))
def ring_append_stacked(storage: jnp.ndarray, heads: jnp.ndarray,
                        rows: jnp.ndarray, counts: jnp.ndarray,
                        interpret: bool = False):
    """Append ``counts[l]`` rows of ``rows[l]`` into ring ``storage[l]`` at
    absolute offset ``heads[l]``. Returns (new_storage, new_heads).

    storage: int32[L, capacity, NUM_LANES], capacity a power of two with
             at least 2 lines (32 rows)
    rows:    int32[L, max_batch, NUM_LANES], max_batch <= 16
    """
    L, capacity, lanes = storage.shape
    max_batch = rows.shape[1]
    if lanes != NUM_LANES or capacity & (capacity - 1):
        raise ValueError("bad storage shape")
    if max_batch > MAX_APPEND_ROWS:
        raise ValueError(f"max_batch {max_batch} > {MAX_APPEND_ROWS}; split "
                         f"the append")
    n_lines = capacity // ROWS_PER_LINE
    if n_lines < 2:
        raise ValueError("capacity must be at least 2 lines (32 rows)")

    flat = storage.reshape(L, n_lines, LINE)
    rows_flat = jnp.pad(
        rows, ((0, 0), (0, MAX_APPEND_ROWS - max_batch), (0, 0))
    ).reshape(L, 1, LINE)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                       # heads, counts
        grid=(L,),
        in_specs=[
            pl.BlockSpec((1, 1, LINE), lambda l, *_: (l, 0, 0),
                         memory_space=pltpu.VMEM),   # new rows, one line
            pl.BlockSpec(memory_space=pl.ANY),    # ring (HBM, aliased)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, LINE), jnp.int32),        # the touched lines
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )

    def kernel(heads_ref, counts_ref, rows_vmem, ring_hbm, out_hbm,
               scratch, sems):
        l = pl.program_id(0)
        head = heads_ref[l]
        count = counts_ref[l]
        head_mod = head & (capacity - 1)
        line_a = head_mod // ROWS_PER_LINE
        line_b = (line_a + 1) % n_lines

        # Pull the two candidate lines into VMEM.
        cp_a = pltpu.make_async_copy(
            out_hbm.at[l, pl.ds(line_a, 1), :], scratch.at[pl.ds(0, 1), :],
            sems.at[0])
        cp_b = pltpu.make_async_copy(
            out_hbm.at[l, pl.ds(line_b, 1), :], scratch.at[pl.ds(1, 1), :],
            sems.at[1])
        cp_a.start()
        cp_b.start()
        cp_a.wait()
        cp_b.wait()

        # Merge: scratch slot (j, c) is ring row line_j*16 + c//8, lane c%8.
        # rel = that row's offset past head; rows with rel < count take the
        # new value rows_flat[rel*8 + lane] — realized as a one-hot matmul
        # (the MXU-shaped gather).
        j_ids = jax.lax.broadcasted_iota(jnp.int32, (2, LINE), 0)
        c_ids = jax.lax.broadcasted_iota(jnp.int32, (2, LINE), 1)
        line_of = jnp.where(j_ids == 0, line_a, line_b)
        ring_row = line_of * ROWS_PER_LINE + c_ids // NUM_LANES
        rel = (ring_row - head_mod) & (capacity - 1)
        take_new = rel < count
        src_col = rel * NUM_LANES + c_ids % NUM_LANES      # [2, LINE]
        src_col = jnp.where(take_new, src_col, 0)
        onehot = (src_col[..., None]
                  == jax.lax.broadcasted_iota(jnp.int32, (1, 1, LINE), 2))
        new_line = rows_vmem[0, 0, :]                       # [LINE]
        gathered = jnp.sum(onehot * new_line[None, None, :],
                           axis=-1).astype(jnp.int32)       # [2, LINE]
        scratch[:, :] = jnp.where(take_new, gathered, scratch[:, :])

        # Write the lines back.
        wb_a = pltpu.make_async_copy(
            scratch.at[pl.ds(0, 1), :], out_hbm.at[l, pl.ds(line_a, 1), :],
            sems.at[0])
        wb_b = pltpu.make_async_copy(
            scratch.at[pl.ds(1, 1), :], out_hbm.at[l, pl.ds(line_b, 1), :],
            sems.at[1])
        wb_a.start()
        wb_b.start()
        wb_a.wait()
        wb_b.wait()

    new_flat = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        # Positional over all operands (prefetch first): heads=0, counts=1,
        # rows_flat=2, flat storage=3.
        input_output_aliases={3: 0},
        interpret=interpret,
    )(heads, counts, rows_flat, flat)
    return new_flat.reshape(L, capacity, NUM_LANES), heads + counts
