"""Whole-program static analysis: the lint grown into a cost model.

``clonos_tpu analyze [paths...]`` — four passes over one parsed file
set, sharing the lint's registry/waiver/CLI conventions
(clonos_tpu/lint/):

- ``callgraph``  — interprocedural call graph (attribute chains,
  import aliases, instance-attribute type inference).
- ``runner``     — nondet-escape propagation to step-function entry
  points (``nondet-reach``) + the census, with waivers and the
  ``--report json`` / exit-0/1 CI contract.
- ``lockorder``  — whole-repo lock acquisition-order graph; cycles are
  ERROR findings (``lock-order``).
- ``threads``    — thread-root inventory: every ``threading.Thread``
  spawn site resolved through the call graph to its entry function,
  daemon flag, and start/join sites; fingerprinted for the
  ``.clonos-threads`` pin.
- ``races``      — lockset ∩ happens-before race detection over the
  inventory (``thread-race``, ``join-discipline``), with pre-start /
  join / queue-handoff / publish discharge edges and a seeded-bug
  registry proving each rule bites.
- ``census``     — FT call-site census folded with serde encoding
  widths into a static bytes-per-epoch cost model; its blake2b
  fingerprint is recorded in BENCH/SOAK artifacts.
- ``ablate``     — the no-FT ablation twin ``bench.py --ablate`` runs
  head-to-head against the real executor to *measure* the ft-fraction
  the static model predicts.

Importing this package registers the analysis rules (``nondet-reach``,
``lock-order``, ``thread-race``, ``join-discipline``) in the shared
lint registry so waivers naming them validate.
"""

from clonos_tpu.analysis.ablate import (AblationRefused,
                                        AblationReport,
                                        ablated_executor,
                                        check_ablatable)
from clonos_tpu.analysis.callgraph import (CallGraph, FunctionInfo,
                                           STEP_ENTRY_NAMES)
from clonos_tpu.analysis.census import (build_census,
                                        census_fingerprint,
                                        fingerprint,
                                        static_cost_model)
from clonos_tpu.analysis.lockorder import (LOCK_BALANCE, LOCK_ORDER,
                                           LockOrderGraph)
from clonos_tpu.analysis.races import (JOIN_DISCIPLINE, SEEDED_BUGS,
                                       THREAD_RACE, RaceAnalysis,
                                       run_races, seeded_findings)
from clonos_tpu.analysis.runner import (ANALYSIS_RULES, NONDET_REACH,
                                        AnalysisResult, format_json,
                                        format_text, run_analysis)
from clonos_tpu.analysis.threads import (ThreadInventory, ThreadRoot,
                                         threads_fingerprint)

__all__ = [
    "AblationRefused", "AblationReport", "ablated_executor",
    "check_ablatable",
    "CallGraph", "FunctionInfo", "STEP_ENTRY_NAMES",
    "build_census", "census_fingerprint", "fingerprint",
    "static_cost_model",
    "LOCK_BALANCE", "LOCK_ORDER", "LockOrderGraph",
    "JOIN_DISCIPLINE", "SEEDED_BUGS", "THREAD_RACE", "RaceAnalysis",
    "run_races", "seeded_findings",
    "ANALYSIS_RULES", "NONDET_REACH", "AnalysisResult",
    "format_json", "format_text", "run_analysis",
    "ThreadInventory", "ThreadRoot", "threads_fingerprint",
]
