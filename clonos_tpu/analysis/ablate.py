"""Ablation generator: the semantics-preserving no-FT twin.

The census says what fault tolerance *should* cost; this module makes
the cost measurable. It rewrites the executor module's AST so every
fault-tolerance lane becomes the identity on its storage argument —
``clog.v_append_full(carry.logs, rows)`` -> ``carry.logs``,
``ifl.append_block(ring, out)`` -> ``ring``, and likewise the epoch
fence's start/truncate/replica-sync — then compiles the transformed
source as a twin module. The twin's ``LocalExecutor`` runs the same
block program minus FT: operators, routing, and the record data path
are untouched (XLA dead-code-eliminates the orphaned determinant-row
construction), so under ``logical_time=True`` with a fixed seed the
twin's sink outputs, record counts, and operator states are
bit-identical to the real executor's — only logs/rings/replicas stay
empty. ``bench.py --ablate`` times the two head-to-head; the wall
delta IS the measured ft-fraction.

Why the twin stays *semantics-preserving*: the causal inputs
(times/rng_bits) still flow to operators, they are just no longer
*logged*. That substitution is only sound when those inputs are pure
functions of (job, seed, step index) — the ``LogicalTimeSource`` +
seeded-RNG regime. A module whose record values depend on unlogged
process entropy (``examples/audit_nondet.py``'s SALT) has no no-FT
twin: replacing its FT would change its outputs, so
:func:`check_ablatable` *refuses* — the refusal is load-bearing and
tested, not a missing feature.
"""

from __future__ import annotations

import ast
import dataclasses
import types
from typing import Dict, List, Optional, Sequence, Tuple

from clonos_tpu.lint.core import FileContext

#: calls replaced by their first argument (identity on the storage
#: tree): the per-step append lanes and the epoch-fence log maintenance.
FT_IDENTITY_CALLS = {
    "clonos_tpu.causal.log.v_append_full",
    "clonos_tpu.causal.log.v_start_epoch",
    "clonos_tpu.causal.log.v_truncate",
    "clonos_tpu.inflight.log.append_block",
    "clonos_tpu.inflight.log.start_epoch",
    "clonos_tpu.inflight.log.truncate",
    "clonos_tpu.causal.replication.sync_replica_epochs",
}

#: rules whose unwaived findings make a module un-ablatable: its
#: outputs depend on values the determinant log was the only witness of.
NONDET_RULES = ("wallclock", "rng", "entropy")


class AblationRefused(RuntimeError):
    """The target's nondeterminism is load-bearing — a no-FT twin would
    not be semantics-preserving. Carries the findings that prove it."""

    def __init__(self, findings):
        self.findings = list(findings)
        locs = "; ".join(
            f"{f.location()} [{f.rule}] {f.message.split(chr(10))[0]}"
            for f in self.findings[:4])
        super().__init__(
            f"refusing to generate a no-FT ablation twin: "
            f"{len(self.findings)} unlogged-nondeterminism finding(s) "
            f"make its outputs depend on values only the determinant "
            f"log captures — stripping FT would change results, not "
            f"just cost. {locs}")


@dataclasses.dataclass
class AblationReport:
    """What the transform actually stripped (auditable, and asserted
    non-trivial by the tests: an ablation that strips nothing measures
    nothing)."""

    source_path: str
    stripped: List[Tuple[int, str]]     # (line, canonical callee)

    def to_dict(self) -> dict:
        return {
            "source_path": self.source_path,
            "stripped_sites": len(self.stripped),
            "stripped": [{"line": l, "callee": c}
                         for l, c in self.stripped],
        }


class _StripFT(ast.NodeTransformer):
    """Replace FT-lane calls with their first argument."""

    def __init__(self, ctx: FileContext):
        self._ctx = ctx
        self.stripped: List[Tuple[int, str]] = []

    def visit_Call(self, node: ast.Call):
        node = self.generic_visit(node)
        dotted = self._ctx.resolve(node.func)
        if dotted in FT_IDENTITY_CALLS and node.args:
            self.stripped.append((node.lineno, dotted))
            return node.args[0]
        return node


def check_ablatable(paths: Sequence[str],
                    use_waivers: bool = True) -> None:
    """Raise :class:`AblationRefused` if any target module has unwaived
    nondeterminism-escape findings (waived nondet is observability
    metadata by the waiver's own justification — it never feeds record
    values, so the twin stays equivalent)."""
    from clonos_tpu.lint.runner import run_lint
    result = run_lint(list(paths), use_waivers=use_waivers,
                      rules=list(NONDET_RULES))
    bad = [f for f in result.errors if f.rule in NONDET_RULES]
    if bad:
        raise AblationRefused(bad)


def transform_source(path: str, source: str
                     ) -> Tuple[ast.Module, AblationReport]:
    """Parse + strip one module's source; returns (tree, report)."""
    ctx = FileContext(path, source)
    stripper = _StripFT(ctx)
    tree = stripper.visit(ctx.tree)
    ast.fix_missing_locations(tree)
    return tree, AblationReport(source_path=path,
                                stripped=sorted(stripper.stripped))


_cached: Optional[Tuple[types.ModuleType, AblationReport]] = None


def ablated_executor(refresh: bool = False
                     ) -> Tuple[types.ModuleType, AblationReport]:
    """The no-FT twin of ``clonos_tpu.runtime.executor`` as a live
    module (compiled from the transformed AST; cached per process).

    Refuses first: the executor and the operator library must
    themselves be free of unwaived nondeterminism, or the twin's
    "bit-identical outputs" premise is void.
    """
    global _cached
    if _cached is not None and not refresh:
        return _cached
    import clonos_tpu.runtime.executor as _ex

    src_path = _ex.__file__
    if src_path.endswith((".pyc", ".pyo")):       # pragma: no cover
        src_path = src_path[:-1]
    check_ablatable([src_path,
                     _module_path("clonos_tpu.api.operators")])
    with open(src_path) as f:
        source = f.read()
    tree, report = transform_source(src_path, source)
    if not report.stripped:
        raise RuntimeError(
            "ablation transform stripped zero FT call sites in "
            f"{src_path} — the executor's FT lanes moved; update "
            "analysis/ablate.py FT_IDENTITY_CALLS")
    mod = types.ModuleType("clonos_tpu.runtime.executor_noft")
    mod.__file__ = src_path + "<no-ft twin>"
    mod.__dict__["__builtins__"] = __builtins__
    # dataclass/typing machinery resolves classes through
    # sys.modules[cls.__module__]; the twin must be importable by name.
    import sys
    sys.modules[mod.__name__] = mod
    exec(compile(tree, src_path, "exec"), mod.__dict__)
    _cached = (mod, report)
    return _cached


def _module_path(modname: str) -> str:
    import importlib
    m = importlib.import_module(modname)
    p = m.__file__
    return p[:-1] if p.endswith((".pyc", ".pyo")) else p
