"""FT census and the static cost model it feeds.

The paper's overhead claim is about *call sites*: every
nondeterministic decision inside a step function costs one determinant
row (causal/determinant.py: 8 int32 lanes = 32 bytes), every epoch
ships those rows in serde frames (causal/serde.py: 12-byte entry
header + rows + 4-byte CRC under a 9-byte frame header), and the block
program appends the sync-path rows for every subtask every superstep
(executor.py DETS_PER_STEP). All of that is statically visible, so the
census enumerates it from source:

- the executor's fixed sync-lane sequence, parsed out of
  ``CompiledJob._det_rows`` (the determinant tags it stamps, in order);
- per step function (operator ``process_block`` bodies and the block
  program itself), the causal-input references (``ctx.times`` /
  ``ctx.rng_bits``) that consume logged determinants;
- every host-side causal-service call site across the repo
  (``current_time_millis``, ``next_int``, ``serializable_service``,
  ``append_async_determinant``) with its determinant type.

``static_cost_model`` folds the census with a job shape into
bytes-per-epoch and calls-per-step, and predicts an ft-fraction as a
bytes-moved ratio: determinant + replica + in-flight-ring traffic over
total traffic (FT + record flow). It is a bandwidth model — on a
bandwidth-bound fused pipeline that is the first-order driver — and
``bench.py --ablate`` reports its relative error against the measured
ablation diff rather than pretending it is exact.

``census_fingerprint`` is the blake2b of the census JSON: BENCH/SOAK
artifacts record it so a perf number is traceable to the exact FT
call-site population that produced it.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence

from clonos_tpu.lint.core import FileContext

from clonos_tpu.analysis.callgraph import CallGraph, module_name

#: repo root (census paths are repo-relative regardless of cwd, so the
#: fingerprint is stable across where the caller ran from).
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: host-side causal-service entry points -> determinant type they log.
SERVICE_CALLS = {
    "current_time_millis": "TIMESTAMP",
    "next_int": "RNG",
    "serializable_service": "SERIALIZABLE",
    "timer_service": "TIMER_TRIGGER",
    "append_async_determinant": "ASYNC_ROW",
    "append_scale_determinant": "SCALE",
}

#: block-context attributes whose read consumes a logged determinant.
CAUSAL_INPUT_ATTRS = {
    "times": "TIMESTAMP", "time": "TIMESTAMP",
    "rng_bits": "RNG",
}

#: wire-format widths, kept in lockstep with causal/serde.py (asserted
#: against the real structs at import time below) and determinant.py.
ENCODING = {
    "row_bytes": 32,           # det.ROW_BYTES: 8 int32 lanes
    "lanes": 8,                # det.NUM_LANES
    "frame_header_bytes": 9,   # serde._HDR "<IBI"
    "flat_entry_bytes": 12,    # serde._FLAT_E "<iiI"
    "crc_bytes": 4,            # serde._CRC "<I"
}


def _check_encoding() -> None:
    from clonos_tpu.causal import determinant as det
    from clonos_tpu.causal import serde
    assert ENCODING["row_bytes"] == det.ROW_BYTES
    assert ENCODING["lanes"] == det.NUM_LANES
    assert ENCODING["frame_header_bytes"] == serde._HDR.size
    assert ENCODING["flat_entry_bytes"] == serde._FLAT_E.size
    assert ENCODING["crc_bytes"] == serde._CRC.size


_check_encoding()


def _sync_lanes(ctx: FileContext) -> List[str]:
    """The ordered determinant tags ``CompiledJob._det_rows`` stamps
    (the fixed sync-path rows every subtask pays every superstep)."""
    from clonos_tpu.causal.determinant import TAG_NAMES
    tag_names = set(TAG_NAMES)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_det_rows":
            hits = []
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr in tag_names:
                    hits.append((sub.lineno, sub.col_offset, sub.attr))
            return [t for _l, _c, t in sorted(hits)]
    return []


def build_census(contexts: Sequence[FileContext],
                 graph: Optional[CallGraph] = None) -> Dict:
    """Assemble the census over a parsed file set (AST only; jax-free)."""
    if graph is None:
        graph = CallGraph(contexts)

    sync_lanes: List[str] = []
    step_functions: List[Dict] = []
    service_sites: List[Dict] = []

    for ctx in contexts:
        if "runtime/executor.py" in ctx.path.replace(os.sep, "/"):
            lanes = _sync_lanes(ctx)
            if lanes:
                sync_lanes = lanes
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in SERVICE_CALLS:
                fi = graph.enclosing(ctx.path, node.lineno)
                service_sites.append({
                    "path": ctx.path, "line": node.lineno,
                    "callee": node.func.attr,
                    "determinant": SERVICE_CALLS[node.func.attr],
                    "function": fi.qname if fi is not None else None,
                })

    for fi in graph.step_entries():
        ctx = next((c for c in contexts if c.path == fi.path), None)
        if ctx is None:
            continue
        counts: Dict[str, int] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr in CAUSAL_INPUT_ATTRS \
                    and fi.covers(node.lineno):
                det_type = CAUSAL_INPUT_ATTRS[node.attr]
                counts[det_type] = counts.get(det_type, 0) + 1
        step_functions.append({
            "function": fi.qname, "path": fi.path, "line": fi.line,
            "causal_input_refs": dict(sorted(counts.items())),
        })

    return {
        "schema": 1,
        "encoding": ENCODING,
        "dets_per_step": len(sync_lanes) or None,
        "sync_lanes": sync_lanes,
        "step_functions": sorted(step_functions,
                                 key=lambda s: s["function"]),
        "service_call_sites": sorted(
            service_sites,
            key=lambda s: (s["path"], s["line"], s["callee"])),
    }


def census_json(census: Dict) -> str:
    return json.dumps(census, sort_keys=True, separators=(",", ":"))


def fingerprint(census: Dict) -> str:
    """blake2b over the canonical census JSON, 16 hex chars — the FT
    call-site population id recorded in BENCH/SOAK artifacts."""
    return hashlib.blake2b(census_json(census).encode(),
                           digest_size=8).hexdigest()


def _repo_contexts(paths: Sequence[str]) -> List[FileContext]:
    from clonos_tpu.lint.runner import build_waivers, collect_files
    cwd = os.getcwd()
    os.chdir(REPO_ROOT)     # paths repo-relative -> stable fingerprint
    try:
        files = collect_files(paths, build_waivers())
        out = []
        for p in files:
            try:
                with open(p) as f:
                    out.append(FileContext(p, f.read()))
            except (SyntaxError, UnicodeDecodeError, OSError):
                continue
        return out
    finally:
        os.chdir(cwd)


def census_fingerprint(paths: Sequence[str] = ("clonos_tpu",
                                               "examples")) -> str:
    """Fingerprint of the repo's current census (cwd-independent)."""
    return fingerprint(build_census(_repo_contexts(paths)))


def static_cost_model(census: Dict, *, steps_per_epoch: int,
                      subtasks: int, records_per_step: int,
                      replica_logs: int = 0, ring_vertices: int = 0,
                      record_touches: int = 4,
                      record_bytes: int = 16,
                      spill: bool = False) -> Dict:
    """Fold the census with a job shape into the FT cost ledger.

    ``record_touches`` is how many vertices each record flows through
    (topology depth); ``record_bytes`` is the RecordBatch footprint per
    record (4 int32 fields: key, value, timestamp, valid). The
    predicted ft-fraction is FT bytes moved / total bytes moved per
    epoch — a bandwidth model, cross-checked against the measured
    ablation diff by ``bench.py --ablate``.

    With ``spill=True`` the ledger grows the tiered-storage lanes
    (storage/tiered.py): every sealed epoch's ring slices AND
    determinant windows cross the d2h lane into the host tier, then the
    host→disk lane as checksummed segments — two extra moves of the
    same bytes, but on the writer thread, so they cost *bandwidth*
    (modeled here), not fence latency (measured by ``bench --spill``).
    """
    enc = census["encoding"]
    dets = census["dets_per_step"] or 0
    row = enc["row_bytes"]

    det_rows = steps_per_epoch * subtasks * dets
    det_bytes = det_rows * row
    replica_bytes = steps_per_epoch * replica_logs * dets * row
    # In-flight rings retain each producing vertex's raw output block.
    ring_bytes = (steps_per_epoch * ring_vertices
                  * records_per_step * record_bytes)
    # Shipping one epoch's determinants as serde FLAT frames: one frame,
    # one entry per log (owner + replica).
    n_logs = subtasks + replica_logs
    wire_bytes = (enc["frame_header_bytes"]
                  + n_logs * (enc["flat_entry_bytes"]
                              + enc["crc_bytes"])
                  + (det_rows + steps_per_epoch * replica_logs * dets)
                  * row)
    data_bytes = (steps_per_epoch * records_per_step
                  * record_touches * record_bytes)
    # Tiered-storage lanes: spilled epoch payload = ring slices + the
    # owner determinant windows (replicas stay device-only); it crosses
    # d2h once and host→disk once.
    spill_payload = (ring_bytes + det_bytes) if spill else 0
    spill_d2h = spill_payload
    spill_disk = spill_payload
    # Fence-tail lanes — the per-epoch bytes the pipelined fence
    # (runtime/cluster.py run_epoch overlap mode) moves off the
    # critical path, itemized so the predicted hidden tail is
    # attributable. Seal: the audit digest d2h's the epoch's causal
    # surface (owner determinant windows + ring slices). Ledger: one
    # JSON line with a fixed header plus one fingerprint per channel
    # (owner logs + rings), ~64 bytes each as serialized. Snapshot: the
    # lean fence offsets (per-log heads + per-ring heads + record
    # counts, int64-scale per lane) — operator state is job-dependent
    # and priced by the data lane, not here.
    fence_seal = det_bytes + ring_bytes
    fence_ledger = 64 * (1 + subtasks + ring_vertices)
    fence_snapshot = 8 * (2 * subtasks + ring_vertices)
    ft_bytes = (det_bytes + replica_bytes + ring_bytes
                + spill_d2h + spill_disk)
    total = ft_bytes + data_bytes
    return {
        "calls_per_step": dets * subtasks,
        "determinant_rows_per_epoch": det_rows,
        "determinant_bytes_per_epoch": det_bytes,
        "replica_bytes_per_epoch": replica_bytes,
        "ring_bytes_per_epoch": ring_bytes,
        "wire_bytes_per_epoch": wire_bytes,
        "data_bytes_per_epoch": data_bytes,
        "spill_d2h_bytes_per_epoch": spill_d2h,
        "spill_disk_bytes_per_epoch": spill_disk,
        "fence_seal_bytes_per_epoch": fence_seal,
        "fence_ledger_bytes_per_epoch": fence_ledger,
        "fence_snapshot_bytes_per_epoch": fence_snapshot,
        "ft_fraction_static": (round(ft_bytes / total, 6)
                               if total else 0.0),
    }
