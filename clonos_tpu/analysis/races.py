"""Whole-program static race detection over the thread-root inventory.

Clonos's correctness story is that every nondeterministic interleaving
is either logged as a determinant or structurally impossible. The
overlapped pipelines (fence tail, recovery finalize, tiered writer,
checkpoint async writers, serve loops, heartbeat/metrics loops) are the
"structurally impossible" half — argued until now by hand-placed joins
and per-class lock discipline. This pass checks the argument, in the
Eraser lockset tradition refined with happens-before edges (RacerD's
compositional spirit: syntactic, no execution, quiet on the repo's own
conventions):

1. **Access sets** — from every thread root (analysis/threads.py),
   interprocedural reachability over the PR 9 call graph to
   ``self.attr`` and one-hop collaborator (``self.obj.attr``) reads,
   writes, and mutating calls, each annotated with the lock set held
   at the site (lockorder.py's resolution, so the race pass and the
   lock-order pass agree on lock identities).
2. **Lockset ∩ happens-before** — an attribute touched by ≥2 roots
   with ≥1 write is a finding iff the roots' guard sets are disjoint
   AND no happens-before edge discharges the pair. Modeled edges:

   - ``pre-start``: writes in the spawning function before
     ``Thread.start()`` are published to the thread (this covers the
     dispatch-only overlap windows: everything inside the markers runs
     before the tail thread starts);
   - ``join``: accesses after ``t.join()`` in the same function — or
     after a call to a function that joins t (the repo's
     at-most-one-tail join points, e.g. ``run_epoch`` calling
     ``_join_fence_tail`` before touching tail state) — are ordered
     after the worker's writes;
   - ``handoff``: ``queue.Queue`` put/get and ``threading.Event``
     set/wait are synchronization objects; traffic through them is
     ordered (and the objects themselves are thread-safe);
   - ``publish``: a single writing root whose every write is a plain
     scalar attribute assignment, read by other roots — the repo's
     documented lock-free monotonic-publish convention (GIL-atomic
     pointer swap; the lint's "reads are not flagged" rule, made
     explicit and checkable).

3. **Rule split** — conflicting *writes* from two roots are a
   ``thread-race`` ERROR; a root's written product *read* by another
   root with no join/guard/handoff is a ``join-discipline`` ERROR (the
   invariant PR 12/13 enforce only by comment: never read a worker's
   product without joining it first).

Findings name the racing attribute, BOTH roots, the access sites, the
missing edge/guard, and the minimal call chain from the root's entry to
the access — the same addressable-counterexample convention as
``verify``'s traces. A seeded-bug registry (``SEEDED_BUGS``) proves
each rule bites: ``analyze --races --seed-bug drop-a-join`` must
exit 1.

Approximations (deliberate, in the lint's spirit — drop, never guess):
accesses through untyped locals/parameters are invisible; reach chains
use resolved call edges only; ``__init__``/teardown are exempt
(single-threaded by repo convention). A miss is possible; a report is
a real syntactic interleaving.
"""

from __future__ import annotations

import ast
import dataclasses
import textwrap
from typing import Dict, List, Optional, Sequence, Set, Tuple

from clonos_tpu.lint.core import (ERROR, FileContext, Finding, Rule,
                                  register_rule)
from clonos_tpu.lint.concurrency import (EXEMPT_METHODS,
                                         MUTATING_METHODS, _self_attr)

from clonos_tpu.analysis.callgraph import CallGraph, FunctionInfo
from clonos_tpu.analysis.lockorder import LockOrderGraph
from clonos_tpu.analysis.threads import (KIND_CLOSURE, MAIN_ROOT,
                                         ThreadInventory, ThreadRoot)

THREAD_RACE = "thread-race"
JOIN_DISCIPLINE = "join-discipline"

RACE_RULES = {THREAD_RACE, JOIN_DISCIPLINE}

#: attribute types that ARE synchronization/handoff objects — calls on
#: them are ordered by construction, never racy.
_HANDOFF_TYPES = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "threading.Event",
}
_LOCK_TYPES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}
_THREAD_TYPES = {"threading.Thread"}


@register_rule
class ThreadRaceRule(Rule):
    """Registry placeholder so waivers can reference ``thread-race``;
    the check is whole-program (it needs the thread-root inventory and
    call graph) and runs from ``clonos_tpu analyze``."""

    name = THREAD_RACE
    description = ("unguarded conflicting writes to one attribute from "
                   "two thread roots (whole-program: enforced by "
                   "`clonos_tpu analyze --races`)")

    def check(self, ctx: FileContext) -> List[Finding]:
        return []


@register_rule
class JoinDisciplineRule(Rule):
    """Registry placeholder for ``join-discipline`` (same arrangement
    as ``thread-race``)."""

    name = JOIN_DISCIPLINE
    description = ("worker thread's product read without a dominating "
                   "join/guard/handoff (whole-program: enforced by "
                   "`clonos_tpu analyze --races`)")

    def check(self, ctx: FileContext) -> List[Finding]:
        return []


READ, WRITE, MUTATE = "read", "write", "mutate"


@dataclasses.dataclass(frozen=True)
class Access:
    """One shared-state touch: ``cls.attr`` at ``path:line`` in ``fn``
    with ``held`` locks. ``plain`` marks a bare scalar attribute
    assignment (the publishable kind)."""

    cls: str
    attr: str
    kind: str                    # READ / WRITE / MUTATE
    plain: bool
    path: str
    line: int
    fn: str
    held: Tuple[str, ...]

    @property
    def writes(self) -> bool:
        return self.kind in (WRITE, MUTATE)


class _AttrTypes:
    """(class qname, attr) -> coarse type tag for lock/handoff/thread
    attrs, collected from constructor-call assignments anywhere in the
    class (``self._cv = threading.Condition()``)."""

    def __init__(self, contexts: Sequence[FileContext],
                 graph: CallGraph):
        self.tags: Dict[Tuple[str, str], str] = {}
        from clonos_tpu.analysis.callgraph import module_name
        for ctx in contexts:
            mod = module_name(ctx.path)
            for cls_node in ast.walk(ctx.tree):
                if not isinstance(cls_node, ast.ClassDef):
                    continue
                cq = f"{mod}.{cls_node.name}"
                for sub in ast.walk(cls_node):
                    if not (isinstance(sub, ast.Assign)
                            and isinstance(sub.value, ast.Call)):
                        continue
                    dotted = ctx.resolve(sub.value.func)
                    if dotted is None:
                        continue
                    tag = None
                    if dotted in _LOCK_TYPES:
                        tag = "lock"
                    elif dotted in _HANDOFF_TYPES:
                        tag = "handoff"
                    elif dotted in _THREAD_TYPES:
                        tag = "thread"
                    if tag is None:
                        continue
                    for t in sub.targets:
                        a = _self_attr(t)
                        if a is not None:
                            self.tags[(cq, a)] = tag

    def tag(self, cls: str, attr: str) -> Optional[str]:
        return self.tags.get((cls, attr))


class RaceAnalysis:
    """Access-set construction + lockset/happens-before checking."""

    def __init__(self, contexts: Sequence[FileContext],
                 graph: CallGraph, lockgraph: LockOrderGraph,
                 inventory: ThreadInventory):
        self.graph = graph
        self.lock = lockgraph
        self.inventory = inventory
        self._ctx_by_path = {c.path: c for c in contexts}
        self.attr_types = _AttrTypes(contexts, graph)
        #: fn qname -> accesses recorded in its body (closure-root
        #: bodies excluded — they belong to their root)
        self._fn_accesses: Dict[str, List[Access]] = {}
        #: closure root id -> its body's accesses
        self._closure_accesses: Dict[str, List[Access]] = {}
        #: closure root id -> resolved callee qnames from its body
        self._closure_calls: Dict[str, Set[str]] = {}
        self._closure_spans: Dict[str, Tuple[int, int]] = {
            r.path: (0, 0) for r in ()}  # filled in _scan_all
        self._closure_nodes = {
            id(r.closure_node): r.root_id
            for r in inventory.roots
            if r.kind == KIND_CLOSURE and r.closure_node is not None}
        self._scan_all()
        self.root_reach: Dict[str, Set[str]] = {}
        self.root_access: Dict[str, List[Access]] = {}
        self._build_roots()
        #: root id -> fn qname -> locks held on EVERY path from the
        #: root's entry to the fn (per-root: the same helper can be
        #: always-locked inside the callback root and lock-free on the
        #: main path)
        self._always_held: Dict[str, Dict[str, Tuple[str, ...]]] = {
            rid: self._always_held_fixpoint(rid)
            for rid in self.root_reach}

    # --- access scanning -----------------------------------------------------

    def _scan_all(self) -> None:
        for fi in self.graph.functions.values():
            ctx = self._ctx_by_path.get(fi.path)
            if ctx is None or fi.cls is None:
                continue          # only methods touch self state
            if fi.name in EXEMPT_METHODS:
                continue          # construction/teardown: single-threaded
            node = self.lock._def_index[ctx.path].get((fi.name, fi.line))
            if node is None:
                continue
            self.lock._params = self.lock._param_types(node)
            out: List[Access] = []
            self._walk(ctx, fi, node.body, (), out, skip_closures=True)
            self._fn_accesses[fi.qname] = out
        # Closure roots: scan the nested def in the spawner's scope.
        for root in self.inventory.roots:
            if root.kind != KIND_CLOSURE or root.closure_node is None:
                continue
            ctx = self._ctx_by_path.get(root.path)
            fi = self.graph.functions.get(root.spawner)
            if ctx is None or fi is None:
                continue
            node = self.lock._def_index[ctx.path].get(
                (fi.name, fi.line))
            self.lock._params = (self.lock._param_types(node)
                                 if node is not None else {})
            out: List[Access] = []
            calls: Set[str] = set()
            self._walk(ctx, fi, root.closure_node.body, (), out,
                       skip_closures=False, call_sink=calls)
            self._closure_accesses[root.root_id] = out
            self._closure_calls[root.root_id] = calls

    def _walk(self, ctx: FileContext, fi: FunctionInfo, stmts,
              held: Tuple[str, ...], out: List[Access],
              skip_closures: bool,
              call_sink: Optional[Set[str]] = None
              ) -> Tuple[str, ...]:
        for stmt in stmts:
            held = self._visit(ctx, fi, stmt, held, out,
                               skip_closures, call_sink)
        return held

    def _visit(self, ctx: FileContext, fi: FunctionInfo, node: ast.AST,
               held: Tuple[str, ...], out: List[Access],
               skip_closures: bool,
               call_sink: Optional[Set[str]]) -> Tuple[str, ...]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if skip_closures and id(node) in self._closure_nodes:
                return held       # a thread root's body, not this fn's
            # Other nested defs run later, possibly off-thread: analyze
            # lock-free (concurrency.py's rule), same fn attribution.
            self._walk(ctx, fi, node.body, (), out, skip_closures,
                       call_sink)
            return held
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                lock = self.lock._lock_id(ctx, fi, item.context_expr)
                if lock is not None and lock not in inner:
                    inner = inner + (lock,)
            self._walk(ctx, fi, node.body, inner, out, skip_closures,
                       call_sink)
            return held
        if isinstance(node, ast.Expr):
            lock, kind = self.lock._bare_lock_call(ctx, fi, node.value)
            if kind == "acquire":
                if lock not in held:
                    held = held + (lock,)
                return held
            if kind == "release":
                return tuple(h for h in held if h != lock)
        if call_sink is not None and isinstance(node, ast.Call):
            dotted = ctx.resolve(node.func)
            if dotted is not None:
                tgt = self.graph.resolve_call(fi, dotted)
                if tgt is not None:
                    call_sink.add(tgt)
        self._record(ctx, fi, node, held, out)
        for child in ast.iter_child_nodes(node):
            held = self._visit(ctx, fi, child, held, out,
                               skip_closures, call_sink)
        return held

    def _record(self, ctx: FileContext, fi: FunctionInfo,
                node: ast.AST, held: Tuple[str, ...],
                out: List[Access]) -> None:
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                self._record_store(fi, t, node.lineno, held, out)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    self._emit(fi, attr, WRITE, False, node.lineno,
                               held, out)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            if node.func.attr in MUTATING_METHODS:
                attr = _self_attr(node.func.value)
                if attr is not None:
                    self._emit(fi, attr, MUTATE, False, node.lineno,
                               held, out)
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            # A plain `self.X` read. Method references are calls, not
            # shared state; lock/handoff/thread objects are sync
            # primitives, not data.
            if fi.cls is not None \
                    and f"{fi.cls}.{node.attr}" in self.graph.functions:
                return
            self._emit(fi, node.attr, READ, True, node.lineno, held,
                       out)

    def _record_store(self, fi: FunctionInfo, target: ast.AST,
                      lineno: int, held: Tuple[str, ...],
                      out: List[Access]) -> None:
        plain = (isinstance(target, ast.Attribute)
                 and isinstance(target.value, ast.Name)
                 and target.value.id == "self")
        attr = _self_attr(target)
        if attr is not None:
            self._emit(fi, attr, WRITE, plain, lineno, held, out)

    def _emit(self, fi: FunctionInfo, attr: str, kind: str,
              plain: bool, lineno: int, held: Tuple[str, ...],
              out: List[Access]) -> None:
        if fi.cls is None:
            return
        tag = self.attr_types.tag(fi.cls, attr)
        if tag in ("lock", "handoff", "thread"):
            return                 # sync primitives, not shared data
        out.append(Access(cls=fi.cls, attr=attr, kind=kind,
                          plain=plain, path=fi.path, line=lineno,
                          fn=fi.qname, held=held))

    # --- guard closure -------------------------------------------------------

    def _root_entries(self, rid: str) -> Set[str]:
        """Functions where this root's execution begins (always-held
        is empty there)."""
        if rid == MAIN_ROOT:
            domain = self.root_reach[rid]
            called: Set[str] = set()
            for q in domain:
                facts = self.lock._fn_locks.get(q)
                if facts is None:
                    continue
                for callee, _line, _held in facts.calls:
                    if callee in domain:
                        called.add(callee)
            return domain - called or domain
        root = self.inventory.by_id(rid)
        if root is None:
            return set()
        if root.kind == KIND_CLOSURE:
            return set(self._closure_calls.get(rid, ()))
        return {root.entry} if root.entry else set()

    def _always_held_fixpoint(self, rid: str
                              ) -> Dict[str, Tuple[str, ...]]:
        """Locks held on every path from the root's entry to each
        reachable function — accesses in its body inherit them
        (``_compact_locked`` is only ever called under
        ``MetricsHistory._lock`` on the history root, so its mutations
        count as guarded there). Transitive meet-over-call-sites
        fixpoint scoped to the root's reach; the same helper gets a
        DIFFERENT answer per root, which is the whole point."""
        domain = self.root_reach[rid]
        entries = self._root_entries(rid)
        # callee -> [(caller, held-at-site)] restricted to the domain
        sites: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
        for q in domain:
            facts = self.lock._fn_locks.get(q)
            if facts is None:
                continue
            for callee, _line, held in facts.calls:
                if callee in domain:
                    sites.setdefault(callee, []).append((q, held))
        ah: Dict[str, Optional[Set[str]]] = {q: None for q in domain}
        for e in entries:
            if e in ah:
                ah[e] = set()
        changed = True
        while changed:
            changed = False
            for callee, callers in sites.items():
                if callee in entries:
                    continue
                new: Optional[Set[str]] = None
                for caller, held in callers:
                    inherited = ah.get(caller)
                    if inherited is None:
                        continue       # caller not yet resolved
                    at = set(held) | inherited
                    new = at if new is None else (new & at)
                if new is not None and new != ah.get(callee):
                    ah[callee] = new
                    changed = True
        return {q: tuple(sorted(v)) for q, v in ah.items()
                if v}                   # unresolved (None) -> no locks

    def _guards(self, a: Access, rid: str) -> Set[str]:
        return set(a.held) | set(
            self._always_held.get(rid, {}).get(a.fn, ()))

    # --- root access sets ----------------------------------------------------

    def _reach(self, seeds: Sequence[str]) -> Set[str]:
        seen: Set[str] = set()
        frontier = [s for s in seeds if s in self.graph.functions]
        seen.update(frontier)
        while frontier:
            nxt: List[str] = []
            for f in frontier:
                for g in self.graph.edges.get(f, ()):
                    if g not in seen:
                        seen.add(g)
                        nxt.append(g)
            frontier = nxt
        return seen

    def _build_roots(self) -> None:
        thread_fns: Set[str] = set()
        for root in self.inventory.roots:
            if root.kind == KIND_CLOSURE:
                reach = self._reach(
                    sorted(self._closure_calls.get(root.root_id, ())))
                accesses = list(
                    self._closure_accesses.get(root.root_id, ()))
            elif root.entry is not None:
                reach = self._reach([root.entry])
                accesses = []
            else:
                continue           # library target: nothing visible
            for fn in reach:
                accesses.extend(self._fn_accesses.get(fn, ()))
            self.root_reach[root.root_id] = reach
            self.root_access[root.root_id] = accesses
            thread_fns |= reach
        # Main root: every method NOT reachable from any thread entry.
        # Shared helpers are charged to the thread roots that reach
        # them (their main-side use follows the same discipline the
        # roots are checked against) — an under-approximation that
        # keeps reports real.
        main: List[Access] = []
        for fn, accesses in self._fn_accesses.items():
            if fn not in thread_fns:
                main.extend(accesses)
        self.root_reach[MAIN_ROOT] = set(self._fn_accesses) - thread_fns
        self.root_access[MAIN_ROOT] = main

    # --- happens-before ------------------------------------------------------

    def _discharged(self, a: Access, root: ThreadRoot) -> Optional[str]:
        """Is main-side access ``a`` ordered against ``root`` by a
        pre-start or join edge? Returns the edge name."""
        for path, line, fn in root.start_sites:
            if a.fn == fn and a.line < line:
                return "pre-start"
        join_fns = {fn for _p, _l, fn in root.join_sites}
        for path, line, fn in root.join_sites:
            if a.fn == fn and a.line > line:
                return "join"
        # Join-call dominance: an earlier call in a's function to a
        # function that joins the root (the at-most-one-tail join
        # points: run_epoch calls _join_fence_tail first).
        facts = self.lock._fn_locks.get(a.fn)
        if facts is not None:
            for callee, line, _held in facts.calls:
                if callee in join_fns and line < a.line:
                    return "join"
        return None

    # --- the check -----------------------------------------------------------

    def findings(self) -> List[Finding]:
        by_attr: Dict[Tuple[str, str], Dict[str, List[Access]]] = {}
        for rid, accesses in self.root_access.items():
            for a in accesses:
                by_attr.setdefault((a.cls, a.attr), {}) \
                    .setdefault(rid, []).append(a)

        out: List[Finding] = []
        for (cls, attr), parties in sorted(by_attr.items()):
            if len(parties) < 2:
                continue
            if not any(a.writes for acc in parties.values()
                       for a in acc):
                continue
            rids = sorted(parties)
            for i, r1 in enumerate(rids):
                for r2 in rids[i + 1:]:
                    f = self._check_pair(cls, attr, r1, parties[r1],
                                         r2, parties[r2])
                    if f is not None:
                        out.append(f)
        return sorted(out, key=lambda f: (f.path, f.line, f.rule))

    def _effective(self, rid_self: str, acc: List[Access],
                   rid_other: str) -> List[Access]:
        """Accesses of ``rid_self`` not ordered against ``rid_other``
        by pre-start/join edges (edges only order the side that does
        NOT run on the other root's thread)."""
        other = self.inventory.by_id(rid_other)
        if other is None or rid_self in (
                r.root_id for r in [other]):
            return acc
        # A root's own accesses are never pre-start/join discharged
        # against main; only the spawning/joining side is ordered.
        if rid_self == MAIN_ROOT or not self._runs_inside(
                rid_self, rid_other):
            return [a for a in acc
                    if self._discharged(a, other) is None]
        return acc

    def _runs_inside(self, rid: str, other_rid: str) -> bool:
        """Does root ``rid``'s code run on ``other_rid``'s thread?
        (Then start/join edges of other_rid cannot order it.)"""
        return rid == other_rid

    def _check_pair(self, cls: str, attr: str,
                    r1: str, acc1: List[Access],
                    r2: str, acc2: List[Access]) -> Optional[Finding]:
        eff1 = self._effective(r1, acc1, r2)
        eff2 = self._effective(r2, acc2, r1)
        if not eff1 or not eff2:
            return None            # fully ordered by pre-start/join
        if not any(a.writes for a in eff1 + eff2):
            return None
        w1 = [a for a in eff1 if a.writes]
        w2 = [a for a in eff2 if a.writes]
        gw1 = (set.intersection(*(self._guards(a, r1) for a in w1))
               if w1 else None)
        gw2 = (set.intersection(*(self._guards(a, r2) for a in w2))
               if w2 else None)
        short = f"{cls.rsplit('.', 1)[-1]}.{attr}"

        # Write/write: the two writers need a common guard.
        if w1 and w2 and not (gw1 & gw2):
            anchor = min(w1 + w2, key=lambda a: (a.path, a.line))
            return self._mk(THREAD_RACE, anchor, short, r1, r2,
                            gw1, gw2, eff1, eff2,
                            "no common guard orders the two writers "
                            "(write/write)")

        # Read/write: every read must share a guard with the other
        # side's writes, unless every write to the attribute is a
        # plain scalar assignment (the repo's lock-free monotonic
        # publish: a GIL-atomic reference swap is safe to read bare;
        # structural mutation is not).
        all_writes = w1 + w2
        publishable = all(a.kind == WRITE and a.plain
                          for a in all_writes)
        for reads, rid_r, wguard, rid_w in (
                ([a for a in eff1 if not a.writes], r1, gw2, r2),
                ([a for a in eff2 if not a.writes], r2, gw1, r1)):
            if wguard is None:
                continue           # other side never writes
            bare = [a for a in reads
                    if not (self._guards(a, rid_r) & wguard)]
            if not bare or publishable:
                continue
            anchor = min(bare, key=lambda a: (a.path, a.line))
            return self._mk(
                JOIN_DISCIPLINE, anchor, short, r1, r2, gw1, gw2,
                eff1, eff2,
                f"the read is not dominated by a join on "
                f"{self._root_name(rid_w)} and no shared "
                f"guard/handoff orders it")
        return None

    def _mk(self, rule: str, anchor: Access, short: str,
            r1: str, r2: str, gw1: Optional[Set[str]],
            gw2: Optional[Set[str]], eff1: List[Access],
            eff2: List[Access], missing: str) -> Finding:
        def _g(g: Optional[Set[str]]) -> str:
            return "no-writes" if g is None else (
                str(sorted(g)) if g else "unguarded")
        chains = "; ".join(filter(None, (
            self._chain_text(r1, eff1), self._chain_text(r2, eff2))))
        sites = ", ".join(sorted({
            f"{a.path}:{a.line} ({a.kind})" for a in eff1 + eff2}))
        return Finding(
            rule=rule, path=anchor.path, line=anchor.line,
            severity=ERROR,
            message=f"`{short}` is touched by thread roots "
                    f"{self._root_name(r1)} and {self._root_name(r2)} "
                    f"with at least one write and disjoint guard sets "
                    f"(write guards: {_g(gw1)} vs {_g(gw2)}) — "
                    f"{missing}; sites: {sites}; {chains}. Add a "
                    f"shared lock, hand the value through a "
                    f"queue/Event, join the worker first, or waive "
                    f"with a justification")

    def _root_name(self, rid: str) -> str:
        if rid == MAIN_ROOT:
            return "<main>"
        return rid

    def _chain_text(self, rid: str, acc: List[Access]) -> str:
        if rid == MAIN_ROOT or not acc:
            return ""
        root = self.inventory.by_id(rid)
        if root is None:
            return ""
        target = acc[0].fn
        if root.kind == KIND_CLOSURE:
            if target == root.spawner:      # closure body access
                return f"chain[{rid}]: {rid} (closure body)"
            seeds = sorted(self._closure_calls.get(rid, ()))
            for s in seeds:
                chain = self.graph.chain(s, {target})
                if chain is not None:
                    hops = " -> ".join([rid] + chain)
                    return f"chain[{rid}]: {hops}"
            return f"chain[{rid}]: {rid} -> ... -> {target}"
        if root.entry is None:
            return ""
        chain = self.graph.chain(root.entry, {target})
        if chain is None:
            return f"chain[{rid}]: {root.entry} -> ... -> {target}"
        return f"chain[{rid}]: {' -> '.join(chain)}"


def run_races(contexts: Sequence[FileContext], graph: CallGraph,
              lockgraph: LockOrderGraph,
              inventory: ThreadInventory) -> List[Finding]:
    """The race pass: lockset ∩ happens-before findings over the
    thread-root inventory."""
    return RaceAnalysis(contexts, graph, lockgraph,
                        inventory).findings()


# --- seeded-bug registry -----------------------------------------------------

#: Each entry is a minimal module that MUST produce exactly one finding
#: of the named rule on the named attribute — the proof the rule bites,
#: runnable as ``clonos_tpu analyze --races --seed-bug <name>`` (exit 1)
#: and pinned by tests/test_races.py. Each source also contains the
#: correct twin of the pattern (joined / guarded / through the queue),
#: which must stay quiet — the registry checks both directions.
SEEDED_BUGS: Dict[str, Dict[str, str]] = {
    "drop-a-join": {
        "rule": JOIN_DISCIPLINE,
        "attr": "Runner._product",
        "source": """\
            import threading

            class Runner:
                def __init__(self):
                    self._product = []
                    self._joined_product = []
                    self._t = threading.Thread(target=self._work)
                    self._t2 = threading.Thread(target=self._work2)

                def _work(self):
                    self._product.append(1)

                def _work2(self):
                    self._joined_product.append(1)

                def run(self):
                    self._t.start()
                    return list(self._product)      # BUG: no join

                def run_joined(self):
                    self._t2.start()
                    self._t2.join()
                    return list(self._joined_product)   # ordered
            """,
    },
    "unguarded-cross-thread-write": {
        "rule": THREAD_RACE,
        "attr": "Counter._totals",
        "source": """\
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._totals = {}
                    self._guarded = {}
                    self._t = threading.Thread(target=self._loop,
                                               daemon=True)
                    self._t.start()

                def _loop(self):
                    self._totals["beat"] = 1        # BUG: no lock
                    with self._lock:
                        self._guarded["beat"] = 1

                def bump(self, k):
                    with self._lock:
                        self._totals[k] = self._totals.get(k, 0) + 1
                        self._guarded[k] = 1
            """,
    },
    "queue-bypass": {
        "rule": THREAD_RACE,
        "attr": "Pipeline._latest",
        "source": """\
            import queue
            import threading

            class Pipeline:
                def __init__(self):
                    self._q = queue.Queue()
                    self._latest = {}
                    self._t = threading.Thread(target=self._produce,
                                               daemon=True)
                    self._t.start()

                def _produce(self):
                    item = object()
                    self._q.put(item)               # ordered handoff
                    self._latest["last"] = item     # BUG: bypasses it

                def drain(self):
                    out = self._q.get()             # ordered handoff
                    self._latest.clear()            # races the bypass
                    return out
            """,
    },
}


def seeded_findings(name: str) -> List[Finding]:
    """Run the full race pipeline over one seeded-bug module."""
    if name not in SEEDED_BUGS:
        raise ValueError(
            f"unknown seeded bug {name!r} — known: "
            f"{', '.join(sorted(SEEDED_BUGS))}")
    src = textwrap.dedent(SEEDED_BUGS[name]["source"])
    ctx = FileContext(f"<seed:{name}>.py", src)
    graph = CallGraph([ctx])
    lockgraph = LockOrderGraph([ctx], graph)
    inventory = ThreadInventory([ctx], graph)
    return run_races([ctx], graph, lockgraph, inventory)
