"""Analysis driver: whole-program passes + the lint's CI conventions.

``run_analysis`` shares the lint's building blocks — file collection,
waiver set, finding/severity model, one-line ``--report json``, exit
0/1 — but its rules are whole-program: they need the interprocedural
call graph, so they cannot run per-file from ``run_lint``:

- **nondet-reach** (ERROR): an *unwaived* nondeterminism-escape
  finding (wallclock/rng/entropy) whose function is reachable from a
  step-function entry point. The per-file lint already flags the
  source line; this names the step function it poisons and the call
  chain that gets it there — the difference between "style problem in
  a helper" and "this block program replays differently".
- **lock-order** (ERROR): acquisition-order cycles in the whole-repo
  lock graph (analysis/lockorder.py).
- **thread-race** / **join-discipline** (ERROR): lockset ∩
  happens-before race detection over the thread-root inventory
  (analysis/threads.py, analysis/races.py) — shared attributes touched
  by two roots with a write and disjoint guards, and reads of a
  worker's product not dominated by a join.

The thread-root census (analysis/threads.py) rides along next to the
FT-call-site census, fingerprinted, so CI can pin the concurrency
architecture (`.clonos-threads`) the same way it pins the call-site
population (`.clonos-census`).

The census (analysis/census.py) rides along in the result and the JSON
report, fingerprinted, so CI and the bench artifacts agree on exactly
which FT call-site population they describe.

Waiver semantics mirror the lint, with one addition: staleness is only
reported for waivers that name *analysis* rules — a waiver consumed by
the per-file lint is not this runner's to second-guess.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

from clonos_tpu.lint.core import (ERROR, WARNING, RULES, FileContext,
                                  Finding)
from clonos_tpu.lint.runner import (SYNTAX, build_waivers,
                                    collect_files)
from clonos_tpu.lint.waivers import STALE_WAIVER, collect_inline

from clonos_tpu.analysis import census as census_mod
from clonos_tpu.analysis.callgraph import CallGraph
from clonos_tpu.analysis.lockorder import (LOCK_BALANCE, LOCK_ORDER,
                                           LockOrderGraph)
from clonos_tpu.analysis import threads as threads_mod
from clonos_tpu.analysis.races import (JOIN_DISCIPLINE, THREAD_RACE,
                                       run_races)

NONDET_REACH = "nondet-reach"

#: rules this runner owns (waiver staleness is scoped to these).
ANALYSIS_RULES = {NONDET_REACH, LOCK_ORDER, LOCK_BALANCE,
                  THREAD_RACE, JOIN_DISCIPLINE}

#: per-file rules whose unwaived findings seed the reach propagation.
TAINT_RULES = ("wallclock", "rng", "entropy")


def _register_reach_rule() -> None:
    from clonos_tpu.lint.core import Rule, register_rule
    if NONDET_REACH in RULES:
        return

    @register_rule
    class _ReachRule(Rule):
        name = NONDET_REACH
        description = ("unlogged nondeterminism reachable from a step "
                       "function (whole-program: enforced by "
                       "`clonos_tpu analyze`)")

        def check(self, ctx: FileContext) -> List[Finding]:
            return []


_register_reach_rule()


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]
    files: List[str]
    census: Dict
    census_fingerprint: str
    threads: Dict = dataclasses.field(default_factory=dict)
    threads_fingerprint: str = ""

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings
                if f.severity == ERROR and not f.waived]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings
                if f.severity == WARNING and not f.waived]

    @property
    def waived(self) -> List[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def ok(self) -> bool:
        return not self.errors

    def exit_code(self) -> int:
        return 0 if self.ok else 1

    def to_dict(self, with_census: bool = True) -> dict:
        out = {
            "ok": self.ok,
            "files": len(self.files),
            "errors": len(self.errors),
            "warnings": len(self.warnings),
            "waived": len(self.waived),
            "census_fingerprint": self.census_fingerprint,
            "threads_fingerprint": self.threads_fingerprint,
            "findings": [f.to_dict() for f in self.findings],
        }
        if with_census:
            out["census"] = self.census
            out["threads"] = self.threads
        return out


def run_analysis(paths: Sequence[str] = ("clonos_tpu", "examples"),
                 waiver_file: Optional[str] = None,
                 use_waivers: bool = True) -> AnalysisResult:
    """Whole-program analysis over ``paths``; jax-free (AST only)."""
    ws = build_waivers(waiver_file, use_waivers)
    files = collect_files(paths, ws if use_waivers else None)

    contexts: List[FileContext] = []
    findings: List[Finding] = []
    for path in files:
        try:
            with open(path) as f:
                source = f.read()
            ctx = FileContext(path, source)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            findings.append(Finding(
                rule=SYNTAX, path=path,
                line=getattr(exc, "lineno", None) or 1,
                severity=ERROR,
                message=f"file does not parse: {exc}"))
            continue
        contexts.append(ctx)
        if use_waivers:
            inline, _problems = collect_inline(ctx)
            ws.inline.extend(inline)

    # Whole-program rules respect the lint's path scoping: test files
    # exercise clocks/threads legitimately and are not pipeline code.
    prog_ctxs = [c for c in contexts
                 if RULES[TAINT_RULES[0]].applies_to(c.path)]
    graph = CallGraph(prog_ctxs)

    findings.extend(_nondet_reach(prog_ctxs, graph, ws, use_waivers))
    lockgraph = LockOrderGraph(prog_ctxs, graph)
    findings.extend(lockgraph.findings())

    inventory = threads_mod.ThreadInventory(prog_ctxs, graph)
    findings.extend(run_races(prog_ctxs, graph, lockgraph, inventory))

    census = census_mod.build_census(prog_ctxs, graph)

    if use_waivers:
        for f in findings:
            if ws.waive(f):
                f.waived = True
        findings.extend(_stale_analysis_waivers(ws))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return AnalysisResult(findings=findings, files=files,
                          census=census,
                          census_fingerprint=census_mod.fingerprint(
                              census),
                          threads=inventory.to_dict(),
                          threads_fingerprint=threads_mod.fingerprint(
                              inventory))


def _nondet_reach(contexts: Sequence[FileContext], graph: CallGraph,
                  ws, use_waivers: bool) -> List[Finding]:
    """Escalate unwaived per-file nondet findings that a step function
    can reach. The base finding stays the lint's; this adds the
    interprocedural consequence with the proving call chain."""
    tainted: Dict[str, List[Finding]] = {}
    for ctx in contexts:
        for rule_name in TAINT_RULES:
            rule = RULES[rule_name]
            if not rule.applies_to(ctx.path):
                continue
            for f in rule.check(ctx):
                if use_waivers and ws.waive(f):
                    continue        # justified: never replayed data
                fi = graph.enclosing(f.path, f.line)
                if fi is not None:
                    tainted.setdefault(fi.qname, []).append(f)

    out: List[Finding] = []
    if not tainted:
        return out
    for entry in graph.step_entries():
        # One chain per tainted function (not just the nearest): every
        # provably-reachable escape is its own finding, so fixing one
        # does not hide the next.
        for fn_qname in sorted(tainted):
            chain = graph.chain(entry.qname, {fn_qname})
            if chain is None:
                continue
            hops = " -> ".join(q.split(".")[-1] if "<" not in q else q
                               for q in chain)
            for src in tainted[fn_qname]:
                out.append(Finding(
                    rule=NONDET_REACH, path=src.path, line=src.line,
                    severity=ERROR,
                    message=f"[{src.rule}] at {src.location()} is "
                            f"reachable from step function "
                            f"{entry.qname} ({entry.path}:{entry.line})"
                            f" via {hops} — the block program's replay "
                            f"diverges on this value; route it through "
                            f"a causal service or waive the base "
                            f"finding with a justification"))
    return out


def _stale_analysis_waivers(ws) -> List[Finding]:
    """Stale warnings scoped to analysis-owned rules (lint-owned
    waivers are the lint runner's to report)."""
    out: List[Finding] = []
    for w in ws.inline:
        if not w.used and w.rules & ANALYSIS_RULES:
            out.append(Finding(
                rule=STALE_WAIVER, path=w.path, line=w.line,
                severity=WARNING,
                message=f"stale analysis waiver allow("
                        f"{', '.join(sorted(w.rules & ANALYSIS_RULES))}"
                        f") — no analysis finding on the waived line; "
                        f"delete the comment"))
    for e in ws.entries:
        if not e.used and e.rule in ANALYSIS_RULES \
                and ws.waiver_path is not None:
            out.append(Finding(
                rule=STALE_WAIVER, path=ws.waiver_path, line=e.lineno,
                severity=WARNING,
                message=f"stale analysis waiver {e.rule} for "
                        f"{e.pattern!r} — matched no finding this run"))
    return out


def format_text(result: AnalysisResult, verbose: bool = False) -> str:
    lines: List[str] = []
    for f in result.findings:
        if f.waived and not verbose:
            continue
        tag = f"[{f.rule}]"
        if f.waived:
            tag += " (waived)"
        elif f.severity == WARNING:
            tag += " (warning)"
        lines.append(f"{f.location()}: {tag} {f.message}")
    c = result.census
    lines.append(
        f"analyze: {len(result.files)} file(s), "
        f"{len(result.errors)} error(s), "
        f"{len(result.warnings)} warning(s), "
        f"{len(result.waived)} waived; census "
        f"{result.census_fingerprint} "
        f"({len(c['step_functions'])} step fn(s), "
        f"{len(c['service_call_sites'])} service call site(s), "
        f"{c['dets_per_step']} sync lanes/step); threads "
        f"{result.threads_fingerprint} "
        f"({len(result.threads.get('roots', []))} root(s))")
    return "\n".join(lines)


def format_json(result: AnalysisResult,
                with_census: bool = True) -> str:
    """One machine-readable line (the lint/audit CI convention)."""
    return json.dumps(result.to_dict(with_census=with_census),
                      sort_keys=True)
