"""Interprocedural call graph over the repo's Python modules.

PR 5's lint rules are per-function: a ``time.time()`` buried two calls
deep in a helper escapes them because the rule never sees the step
function that (transitively) calls the helper. This module builds the
missing structure: every module-level function, class method, and
module body in the analyzed file set becomes a node; edges are resolved
through the same import-alias machinery the lint uses
(:class:`clonos_tpu.lint.core.FileContext`) plus a light intra-repo
type inference pass — ``self.coordinator = CheckpointCoordinator(...)``
in ``__init__`` lets ``self.coordinator.seal_epoch()`` resolve to
``CheckpointCoordinator.seal_epoch``.

Deliberately static and approximate (no execution, no dataflow): edges
the resolver cannot prove are dropped, never guessed, so a reported
reach chain is a real syntactic call path. The consumers are
``analysis/runner.py`` (nondet-escape propagation to step entry
points) and ``analysis/lockorder.py`` (lock acquisitions reached from
under a held lock).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from clonos_tpu.lint.core import FileContext

#: pseudo-function name for a module's top-level statements.
MODULE_BODY = "<module>"

#: method names that run inside the fused block program (operator
#: processing) or ARE the block program — the analysis's "step
#: function" entry points, where a nondet reach becomes a replay
#: divergence rather than a style problem.
STEP_ENTRY_NAMES = {
    "process", "process_block", "process_block_static_keys",
    "run_block",
}


def module_name(path: str) -> str:
    """``clonos_tpu/runtime/executor.py`` -> ``clonos_tpu.runtime.executor``."""
    p = path[:-3] if path.endswith(".py") else path
    p = p.replace("\\", "/").lstrip("./")
    return p.replace("/", ".")


@dataclasses.dataclass
class FunctionInfo:
    """One call-graph node: a function, method, or module body."""

    qname: str                    # canonical dotted id (mod[.Cls].fn)
    path: str
    name: str
    line: int
    end_line: int
    cls: Optional[str] = None     # canonical class qname for methods
    mod: str = ""
    #: (lineno, dotted-callee-as-written) — resolved lazily by the graph
    raw_calls: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list)

    def covers(self, line: int) -> bool:
        return self.line <= line <= self.end_line


class CallGraph:
    """Whole-program call graph over a set of parsed files."""

    def __init__(self, contexts: Sequence[FileContext]):
        #: qname -> node
        self.functions: Dict[str, FunctionInfo] = {}
        #: canonical class qname -> path
        self.classes: Dict[str, str] = {}
        #: (class qname, attr) -> class qname of the instance stored there
        self.attr_types: Dict[Tuple[str, str], str] = {}
        #: caller qname -> {callee qname}
        self.edges: Dict[str, Set[str]] = {}
        #: path -> nodes in that file, for line -> function lookup
        self._by_path: Dict[str, List[FunctionInfo]] = {}
        self._ctx_by_path: Dict[str, FileContext] = {}

        for ctx in contexts:
            self._index_file(ctx)
        for ctx in contexts:
            self._collect_attr_types(ctx)
        self._resolve_edges()

    # --- pass 1: index ------------------------------------------------------

    def _index_file(self, ctx: FileContext) -> None:
        mod = module_name(ctx.path)
        self._ctx_by_path[ctx.path] = ctx
        nodes: List[FunctionInfo] = []

        def add(fi: FunctionInfo) -> None:
            # Later definitions shadow earlier ones (redefinition), which
            # matches runtime binding order.
            self.functions[fi.qname] = fi
            nodes.append(fi)

        def collect_calls(fn_node: ast.AST, fi: FunctionInfo) -> None:
            for sub in ast.walk(fn_node):
                if isinstance(sub, ast.Call):
                    dotted = ctx.resolve(sub.func)
                    if dotted is not None:
                        fi.raw_calls.append((sub.lineno, dotted))

        for item in ctx.tree.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(
                    qname=f"{mod}.{item.name}", path=ctx.path,
                    name=item.name, line=item.lineno,
                    end_line=item.end_lineno or item.lineno, mod=mod)
                collect_calls(item, fi)
                add(fi)
            elif isinstance(item, ast.ClassDef):
                cq = f"{mod}.{item.name}"
                self.classes[cq] = ctx.path
                for m in item.body:
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        fi = FunctionInfo(
                            qname=f"{cq}.{m.name}", path=ctx.path,
                            name=m.name, line=m.lineno,
                            end_line=m.end_lineno or m.lineno,
                            cls=cq, mod=mod)
                        collect_calls(m, fi)
                        add(fi)
        # Module body: everything not inside a def/class def above.
        body_fi = FunctionInfo(
            qname=f"{mod}.{MODULE_BODY}", path=ctx.path,
            name=MODULE_BODY, line=1,
            end_line=len(ctx.lines) or 1, mod=mod)
        for item in ctx.tree.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for sub in ast.walk(item):
                if isinstance(sub, ast.Call):
                    dotted = ctx.resolve(sub.func)
                    if dotted is not None:
                        body_fi.raw_calls.append((sub.lineno, dotted))
        self.functions[body_fi.qname] = body_fi
        nodes.append(body_fi)
        # Innermost span first for line lookup (module body spans all).
        self._by_path[ctx.path] = sorted(
            nodes, key=lambda f: (f.end_line - f.line))

    # --- pass 2: instance-attribute types -----------------------------------

    def _collect_attr_types(self, ctx: FileContext) -> None:
        mod = module_name(ctx.path)
        for item in ctx.tree.body:
            if not isinstance(item, ast.ClassDef):
                continue
            cq = f"{mod}.{item.name}"
            for m in item.body:
                if not isinstance(m, ast.FunctionDef):
                    continue
                for sub in ast.walk(m):
                    if not isinstance(sub, ast.Assign) \
                            or not isinstance(sub.value, ast.Call):
                        continue
                    tgt_cls = self._class_of(ctx, mod, sub.value.func)
                    if tgt_cls is None:
                        continue
                    for t in sub.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            self.attr_types[(cq, t.attr)] = tgt_cls

    def _class_of(self, ctx: FileContext, mod: str,
                  func: ast.AST) -> Optional[str]:
        dotted = ctx.resolve(func)
        if dotted is None:
            return None
        if dotted in self.classes:
            return dotted
        cand = f"{mod}.{dotted}"
        if cand in self.classes:
            return cand
        return None

    # --- pass 3: edge resolution --------------------------------------------

    def resolve_call(self, fi: FunctionInfo,
                     dotted: str) -> Optional[str]:
        """Map a dotted callee as written in ``fi`` to a graph node."""
        parts = dotted.split(".")
        if parts[0] == "self" and fi.cls is not None:
            if len(parts) == 2:
                cand = f"{fi.cls}.{parts[1]}"
                return cand if cand in self.functions else None
            if len(parts) == 3:
                tgt = self.attr_types.get((fi.cls, parts[1]))
                if tgt is not None:
                    cand = f"{tgt}.{parts[2]}"
                    return cand if cand in self.functions else None
            return None
        for cand in (dotted, f"{fi.mod}.{dotted}"):
            if cand in self.functions:
                return cand
            if cand in self.classes:
                init = f"{cand}.__init__"
                return init if init in self.functions else None
        return None

    def _resolve_edges(self) -> None:
        for fi in self.functions.values():
            outs = self.edges.setdefault(fi.qname, set())
            for _line, dotted in fi.raw_calls:
                tgt = self.resolve_call(fi, dotted)
                if tgt is not None and tgt != fi.qname:
                    outs.add(tgt)

    # --- queries ------------------------------------------------------------

    def enclosing(self, path: str, line: int) -> Optional[FunctionInfo]:
        """Innermost function (or module body) containing ``path:line``."""
        for fi in self._by_path.get(path, ()):
            if fi.covers(line):
                return fi
        return None

    def step_entries(self) -> List[FunctionInfo]:
        return sorted(
            (fi for fi in self.functions.values()
             if fi.name in STEP_ENTRY_NAMES and fi.cls is not None),
            key=lambda f: f.qname)

    def chain(self, start: str, targets: Set[str]
              ) -> Optional[List[str]]:
        """Shortest call chain from ``start`` to any of ``targets``
        (BFS), as a qname list including both endpoints; None if
        unreachable."""
        if start in targets:
            return [start]
        parent: Dict[str, str] = {start: start}
        frontier = [start]
        while frontier:
            nxt: List[str] = []
            for f in frontier:
                for g in sorted(self.edges.get(f, ())):
                    if g in parent:
                        continue
                    parent[g] = f
                    if g in targets:
                        out = [g]
                        while out[-1] != start:
                            out.append(parent[out[-1]])
                        return list(reversed(out))
                    nxt.append(g)
            frontier = nxt
        return None
