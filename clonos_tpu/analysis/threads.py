"""Thread-root inventory: every place the repo leaves the main thread.

PRs 12-19 moved real work onto worker threads — the fence-tail drain,
the recovery-finalize overlap, the tiered-storage writer, checkpoint
async writers, serve/replica dispatch loops, heartbeat and metrics
loops, transport request handlers. Their safety is argued by joins and
per-class lock discipline; the race pass (analysis/races.py) checks
that argument, and this module builds the ground truth it needs: the
**roots** — every function that can run off the main thread — with
their spawn sites, daemon flags, stored thread identities, and every
``start()`` / ``join()`` site that orders them.

Three spawn idioms are resolved, through the PR 9 call graph
(callgraph.py: bound methods, collaborator attribute types):

- ``threading.Thread(target=self._loop)`` / ``target=module_fn`` —
  entry is the resolved method/function qname;
- ``threading.Thread(target=_closure)`` where ``_closure`` is a def
  nested in the spawning function — entry is a synthetic
  ``<spawner>.<closure>`` root whose body is analyzed in the spawner's
  ``self`` scope (the checkpoint async writer, the bootstrap overlap
  worker);
- callback servers (``ControlServer(self._handle, ...)``) — the
  handler runs on transport threads, so the handler method is a root
  even though no ``threading.Thread`` names it (the serve/replica
  endpoints, the JobMaster wire surface).

Thread identity is tracked so joins attach to the right root: a local
name (``th = Thread(...); th.join()``), a ``self.<attr>`` store
(``self._writer``), or the repo's tail-dict idiom
(``tail["thread"] = th`` joined as ``tail["thread"].join()``).

``fingerprint`` hashes the census (entries, kinds, daemon flags, join
discipline — NOT line numbers, so routine edits don't churn the pin);
``.clonos-threads`` pins it and ``analyze --expect-threads`` gates
drift: a new thread root appearing without review is exactly how the
next unchecked interleaving ships.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Sequence, Set, Tuple

from clonos_tpu.lint.core import FileContext

from clonos_tpu.analysis.callgraph import (CallGraph, FunctionInfo,
                                           MODULE_BODY)

#: constructors whose first argument is a handler called from threads
#: the constructor owns (callback-server idiom).
CALLBACK_SERVERS = {"ControlServer"}

#: entry kinds, ordered by how much the analysis can see of them.
KIND_METHOD = "method"        # resolved in-repo method/function
KIND_CLOSURE = "closure"      # def nested in the spawning function
KIND_CALLBACK = "callback"    # handler run on a server's threads
KIND_LIBRARY = "library"      # target is library code (serve_forever)

MAIN_ROOT = "<main>"


@dataclasses.dataclass
class ThreadRoot:
    """One way off the main thread: a spawn site plus its entry."""

    root_id: str                     # stable id (entry qname, unique)
    path: str
    line: int                        # spawn site
    kind: str                        # KIND_*
    target: str                      # target expression as written
    entry: Optional[str]             # entry qname (None for library)
    daemon: bool
    spawner: str                     # qname of the spawning function
    owner_cls: Optional[str]         # class qname owning the spawner
    #: identities the Thread object is bound to: ("local", name),
    #: ("attr", name) for self.<name>, ("key", k) for d[k] = th
    idents: List[Tuple[str, str]] = dataclasses.field(
        default_factory=list)
    start_sites: List[Tuple[str, int, str]] = dataclasses.field(
        default_factory=list)        # (path, line, fn qname)
    join_sites: List[Tuple[str, int, str]] = dataclasses.field(
        default_factory=list)
    #: closure def node for KIND_CLOSURE roots (not serialized)
    closure_node: Optional[ast.AST] = None

    @property
    def joined(self) -> bool:
        return bool(self.join_sites)

    def to_dict(self) -> dict:
        return {
            "root_id": self.root_id, "path": self.path,
            "line": self.line, "kind": self.kind,
            "target": self.target, "entry": self.entry,
            "daemon": self.daemon, "spawner": self.spawner,
            "idents": [list(i) for i in self.idents],
            "start_sites": [list(s) for s in self.start_sites],
            "join_sites": [list(s) for s in self.join_sites],
        }


def _const_true(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


class ThreadInventory:
    """All thread roots over a parsed file set."""

    def __init__(self, contexts: Sequence[FileContext],
                 graph: CallGraph):
        self.graph = graph
        self.roots: List[ThreadRoot] = []
        self._ctx_by_path = {c.path: c for c in contexts}
        for ctx in contexts:
            self._scan_file(ctx)
        self._collect_start_join(contexts)
        self.roots.sort(key=lambda r: (r.path, r.line))

    # --- spawn sites ---------------------------------------------------------

    def _scan_file(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = ctx.resolve(node.func)
            if dotted == "threading.Thread":
                self._add_thread_root(ctx, node)
            elif dotted is not None and node.args and \
                    dotted.rsplit(".", 1)[-1] in CALLBACK_SERVERS:
                self._add_callback_root(ctx, node, dotted)

    def _enclosing(self, ctx: FileContext,
                   line: int) -> Optional[FunctionInfo]:
        return self.graph.enclosing(ctx.path, line)

    def _add_thread_root(self, ctx: FileContext,
                         call: ast.Call) -> None:
        target_node = None
        daemon = False
        for kw in call.keywords:
            if kw.arg == "target":
                target_node = kw.value
            elif kw.arg == "daemon":
                daemon = _const_true(kw.value)
        if target_node is None and call.args:
            target_node = call.args[0]
        fi = self._enclosing(ctx, call.lineno)
        spawner = fi.qname if fi is not None else f"{ctx.path}:?"
        owner = fi.cls if fi is not None else None
        target_src = (ast.unparse(target_node)
                      if target_node is not None else "?")

        entry: Optional[str] = None
        kind = KIND_LIBRARY
        closure_node = None
        if target_node is not None and fi is not None:
            dotted = ctx.resolve(target_node)
            if dotted is not None:
                resolved = self.graph.resolve_call(fi, dotted)
                if resolved is not None:
                    entry, kind = resolved, KIND_METHOD
            if entry is None and isinstance(target_node, ast.Name):
                closure_node = self._find_closure(
                    ctx, fi, target_node.id)
                if closure_node is not None:
                    entry = f"{fi.qname}.<{target_node.id}>"
                    kind = KIND_CLOSURE
        root_id = entry if entry is not None else \
            f"{ctx.path}:{call.lineno}:{target_src}"
        # The same qname can be spawned from several sites (restarts of
        # the same worker); they are ONE root — merge spawn metadata.
        for r in self.roots:
            if r.root_id == root_id:
                r.daemon = r.daemon or daemon
                self._bind_idents(ctx, r, call)
                return
        root = ThreadRoot(
            root_id=root_id, path=ctx.path, line=call.lineno,
            kind=kind, target=target_src, entry=entry, daemon=daemon,
            spawner=spawner, owner_cls=owner,
            closure_node=closure_node)
        self._bind_idents(ctx, root, call)
        self.roots.append(root)

    def _add_callback_root(self, ctx: FileContext, call: ast.Call,
                           dotted: str) -> None:
        handler = call.args[0]
        fi = self._enclosing(ctx, call.lineno)
        if fi is None:
            return
        hdotted = ctx.resolve(handler)
        if hdotted is None:
            return
        entry = self.graph.resolve_call(fi, hdotted)
        if entry is None:
            return
        for r in self.roots:
            if r.root_id == entry:
                return
        self.roots.append(ThreadRoot(
            root_id=entry, path=ctx.path, line=call.lineno,
            kind=KIND_CALLBACK, target=ast.unparse(call.func),
            entry=entry, daemon=True, spawner=fi.qname,
            owner_cls=fi.cls))

    @staticmethod
    def _find_closure(ctx: FileContext, fi: FunctionInfo,
                      name: str) -> Optional[ast.AST]:
        """The def node of a function named ``name`` nested inside
        ``fi``'s body (the async-writer / overlap-worker idiom)."""
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == name \
                    and fi.line <= node.lineno <= fi.end_line \
                    and (node.lineno, node.name) != (fi.line, fi.name):
                return node
        return None

    def _bind_idents(self, ctx: FileContext, root: ThreadRoot,
                     call: ast.Call) -> None:
        """Walk the spawning function for stores of THIS Thread(...)
        call's result: a local name, a ``self.<attr>``, or a
        ``d[key] = th`` (possibly via the local name)."""
        fi = self._enclosing(ctx, call.lineno)
        if fi is None:
            return
        node = self._fn_node(ctx, fi)
        if node is None:
            return
        local: Optional[str] = None
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and sub.value is call:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        local = t.id
                        self._add_ident(root, ("local", t.id))
                    elif isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        self._add_ident(root, ("attr", t.attr))
        if local is None:
            return
        # Second-hop stores of the local: self.X = th / d["k"] = th.
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign) \
                    or not isinstance(sub.value, ast.Name) \
                    or sub.value.id != local:
                continue
            for t in sub.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    self._add_ident(root, ("attr", t.attr))
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.slice, ast.Constant):
                    self._add_ident(root, ("key", str(t.slice.value)))

    @staticmethod
    def _add_ident(root: ThreadRoot, ident: Tuple[str, str]) -> None:
        if ident not in root.idents:
            root.idents.append(ident)

    def _fn_node(self, ctx: FileContext,
                 fi: FunctionInfo) -> Optional[ast.AST]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == fi.name and node.lineno == fi.line:
                return node
        return None

    # --- start/join sites ----------------------------------------------------

    def _collect_start_join(self,
                            contexts: Sequence[FileContext]) -> None:
        """Attach every ``<ident>.start()`` / ``<ident>.join()`` to the
        root(s) the ident binds. Local names match inside the spawning
        function; ``self.<attr>`` and ``d[key]`` idents match anywhere
        in the owning class's file (the tail dict travels)."""
        for ctx in contexts:
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("start", "join")):
                    continue
                base = node.func.value
                fi = self._enclosing(ctx, node.lineno)
                fn = fi.qname if fi is not None else "?"
                site = (ctx.path, node.lineno, fn)
                for root in self.roots:
                    if self._matches(root, ctx, base, fi):
                        dest = (root.start_sites
                                if node.func.attr == "start"
                                else root.join_sites)
                        if site not in dest:
                            dest.append(site)

    @staticmethod
    def _matches(root: ThreadRoot, ctx: FileContext, base: ast.AST,
                 fi: Optional[FunctionInfo]) -> bool:
        if root.path != ctx.path:
            return False
        for kind, name in root.idents:
            if kind == "local" and isinstance(base, ast.Name) \
                    and base.id == name and fi is not None \
                    and fi.qname == root.spawner:
                return True
            if kind == "attr" and isinstance(base, ast.Attribute) \
                    and base.attr == name \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                return True
            if kind == "key" and isinstance(base, ast.Subscript) \
                    and isinstance(base.slice, ast.Constant) \
                    and str(base.slice.value) == name:
                return True
        return False

    # --- queries / census ----------------------------------------------------

    def by_id(self, root_id: str) -> Optional[ThreadRoot]:
        for r in self.roots:
            if r.root_id == root_id:
                return r
        return None

    def to_dict(self) -> dict:
        return {"schema": 1,
                "roots": [r.to_dict() for r in self.roots]}

    def census(self) -> List[dict]:
        """The pinned shape: stable across line-number churn — entries,
        kinds, daemon flags, stored idents, and whether joins exist."""
        return sorted(
            ({"entry": r.root_id, "kind": r.kind, "path": r.path,
              "daemon": r.daemon, "joined": r.joined,
              "idents": sorted(f"{k}:{n}" for k, n in r.idents)}
             for r in self.roots),
            key=lambda d: d["entry"])


def fingerprint(inventory: ThreadInventory) -> str:
    """blake2b over the canonical thread census, 16 hex chars — the
    value ``.clonos-threads`` pins."""
    payload = json.dumps(inventory.census(), sort_keys=True,
                         separators=(",", ":"))
    return hashlib.blake2b(payload.encode(),
                           digest_size=8).hexdigest()


#: package-level alias (``analysis.fingerprint`` is the census's).
threads_fingerprint = fingerprint
