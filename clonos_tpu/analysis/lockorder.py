"""Whole-repo lock-order graph: acquisition-order cycles are deadlocks.

``lint/concurrency.py`` checks each class alone: a guarded attribute
mutated without its lock. What it cannot see is the *cross*-class (and
cross-method) hazard the threaded runtime actually grew into:
``dispatcher.py`` holds its admission lock while calling into a
JobMaster whose checkpoint coordinator takes ``_lock``, while a
heartbeat thread entered from the other side holds that ``_lock`` and
calls back out. If two threads acquire the same pair of locks in
opposite orders, the runtime deadlocks — under load, rarely, in
production.

This pass builds the acquisition-order digraph over every analyzed
file: node = lock identity (``Class.attr``, resolved through the call
graph's instance-attribute types when the lock lives on a collaborator,
e.g. ``self.jm._lock``); edge ``A -> B`` = somewhere, B is acquired
while A is held — either directly (nested ``with``) or transitively (a
call made under A reaches a function whose closure acquires B). Any
cycle in that digraph is an ERROR finding naming both directions'
acquisition sites.

Approximations (same spirit as the lint's):

- Reentrant re-acquisition of an already-held lock is NOT an edge (the
  runtime uses ``RLock`` where it self-nests; flagging ``A -> A`` would
  punish that pattern).
- Nested function bodies are analyzed as part of their enclosing
  function: a callback defined under a lock usually runs later, but if
  it acquires locks the conservative edge is the one worth seeing.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from clonos_tpu.lint.core import ERROR, WARNING, FileContext, Finding, \
    Rule, register_rule
from clonos_tpu.lint.concurrency import _lock_attr, lock_attrs

from clonos_tpu.analysis.callgraph import CallGraph, FunctionInfo

LOCK_ORDER = "lock-order"
LOCK_BALANCE = "lock-balance"


@register_rule
class LockOrderRule(Rule):
    """Registry placeholder so waivers can reference ``lock-order`` and
    ``lint --list-rules`` documents it. The check itself is
    whole-program — it needs the call graph — so it runs from
    ``clonos_tpu analyze`` (analysis/runner.py), not the per-file lint
    pass."""

    name = LOCK_ORDER
    description = ("lock acquisition-order cycle across the runtime "
                   "(whole-program: enforced by `clonos_tpu analyze`)")

    def check(self, ctx: FileContext) -> List[Finding]:
        return []


@register_rule
class LockBalanceRule(Rule):
    """Registry placeholder for ``lock-balance`` (same arrangement as
    ``lock-order``: the check runs from ``clonos_tpu analyze``)."""

    name = LOCK_BALANCE
    description = ("bare .acquire() with no matching .release() in the "
                   "same function (whole-program: enforced by "
                   "`clonos_tpu analyze`)")

    def check(self, ctx: FileContext) -> List[Finding]:
        return []


@dataclasses.dataclass(frozen=True)
class AcqSite:
    path: str
    line: int
    fn: str                      # qname of the acquiring function


@dataclasses.dataclass
class _FnLocks:
    """Per-function lock facts from one ordered body walk."""

    #: (lock, line, locks held at that point)
    acquires: List[Tuple[str, int, Tuple[str, ...]]] = \
        dataclasses.field(default_factory=list)
    #: (resolved callee qname, line, locks held at the call)
    calls: List[Tuple[str, int, Tuple[str, ...]]] = \
        dataclasses.field(default_factory=list)
    #: bare ``lock.acquire()`` statements: (lock, line)
    bare_acquires: List[Tuple[str, int]] = \
        dataclasses.field(default_factory=list)
    #: locks a bare ``lock.release()`` statement releases somewhere in
    #: this function (any path balances the warning)
    releases: Set[str] = dataclasses.field(default_factory=set)


class LockOrderGraph:
    """Acquisition-order digraph over a parsed file set."""

    def __init__(self, contexts: Sequence[FileContext],
                 graph: CallGraph):
        self._graph = graph
        self._fn_locks: Dict[str, _FnLocks] = {}
        #: edge (a, b) -> first site where b was taken/reached under a
        self.edge_sites: Dict[Tuple[str, str], AcqSite] = {}
        by_path = {c.path: c for c in contexts}
        # One walk per file: (name, lineno) -> def node, so per-function
        # scans don't each re-walk the whole module AST.
        self._def_index: Dict[str, Dict[Tuple[str, int], ast.AST]] = {}
        for c in contexts:
            idx: Dict[Tuple[str, int], ast.AST] = {}
            for sub in ast.walk(c.tree):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    idx[(sub.name, sub.lineno)] = sub
            self._def_index[c.path] = idx
        self._class_shorts = {cq.rsplit(".", 1)[-1]
                              for cq in graph.classes}
        # lock attr -> class short names that acquire it via `with
        # self.<attr>:` — lets a lock reached through an untyped
        # parameter unify with its owner when the name is unambiguous.
        self._lock_owners: Dict[str, Set[str]] = {}
        # Type-proven lock attributes per file (`self._cv =
        # threading.Condition()`): extends the name hints so the lock
        # identity the race pass reuses matches the lint's guard set.
        self._known_locks: Dict[str, frozenset] = {
            c.path: lock_attrs(c) for c in contexts}
        for c in contexts:
            known = self._known_locks[c.path]
            for node in ast.walk(c.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for sub in ast.walk(node):
                    exprs = []
                    if isinstance(sub, ast.With):
                        exprs = [i.context_expr for i in sub.items]
                    elif isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "acquire":
                        exprs = [sub.func.value]
                    for e in exprs:
                        attr = _lock_attr(e, known)
                        if attr is not None \
                                and isinstance(e, ast.Attribute) \
                                and isinstance(e.value, ast.Name) \
                                and e.value.id == "self":
                            self._lock_owners.setdefault(
                                attr, set()).add(node.name)
        for fi in graph.functions.values():
            ctx = by_path.get(fi.path)
            if ctx is not None:
                self._fn_locks[fi.qname] = self._scan(ctx, fi)
        self._closure = self._acquire_closure()
        self._build_edges()

    # --- per-function ordered walk ------------------------------------------

    def _scan(self, ctx: FileContext, fi: FunctionInfo) -> _FnLocks:
        facts = _FnLocks()
        node = self._def_index[ctx.path].get((fi.name, fi.line))
        if node is None:
            if fi.name != "<module>":
                return facts
            node = ctx.tree
        self._params = self._param_types(node)
        body = node.body if isinstance(node.body, list) else [node.body]
        self._walk(ctx, fi, facts, body, held=())
        return facts

    def _param_types(self, node: ast.AST) -> Dict[str, str]:
        """Annotated parameters whose type is a repo class (short
        name): ``def heartbeat(self, d: Dispatcher)`` -> {"d":
        "Dispatcher"}. String annotations count too."""
        out: Dict[str, str] = {}
        args = getattr(node, "args", None)
        if args is None:
            return out
        for a in list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs):
            ann = a.annotation
            name = None
            if isinstance(ann, ast.Name):
                name = ann.id
            elif isinstance(ann, ast.Constant) \
                    and isinstance(ann.value, str):
                name = ann.value.strip('"').rsplit(".", 1)[-1]
            elif isinstance(ann, ast.Attribute):
                name = ann.attr
            if name in self._class_shorts:
                out[a.arg] = name
        return out

    def _lock_id(self, ctx: FileContext, fi: FunctionInfo,
                 expr: ast.AST) -> Optional[str]:
        """``with self._lock:`` -> ``Cls._lock``; ``with self.jm._lock:``
        -> ``JobMaster._lock`` when ``self.jm``'s class is known; a lock
        reached through a parameter resolves via its annotation, else
        via attribute-name uniqueness across the repo's classes."""
        attr = _lock_attr(
            expr, self._known_locks.get(ctx.path, frozenset()))
        if attr is None:
            return None
        owner = "?"
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and fi.cls is not None:
                owner = fi.cls.rsplit(".", 1)[-1]
            elif isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and fi.cls is not None:
                tgt = self._graph.attr_types.get((fi.cls, base.attr))
                owner = (tgt.rsplit(".", 1)[-1] if tgt is not None
                         else f"{fi.cls.rsplit('.', 1)[-1]}.{base.attr}")
            elif isinstance(base, ast.Name):
                if base.id in self._params:
                    owner = self._params[base.id]
                else:
                    owners = self._lock_owners.get(attr, set())
                    if len(owners) == 1:
                        owner = next(iter(owners))
                    else:
                        dotted = ctx.resolve(base)
                        owner = dotted if dotted is not None else base.id
        return f"{owner}.{attr}"

    def _walk(self, ctx: FileContext, fi: FunctionInfo,
              facts: _FnLocks, stmts,
              held: Tuple[str, ...]) -> Tuple[str, ...]:
        # Bare ``lock.acquire()`` / ``lock.release()`` statements change
        # the held set for SUBSEQUENT statements, so the walk threads
        # ``held`` through the body in source order (a straight-line
        # approximation: a branch's acquire stays held afterwards, which
        # conservatively over-orders rather than missing an edge).
        for stmt in stmts:
            held = self._visit(ctx, fi, facts, stmt, held)
        return held

    def _bare_lock_call(self, ctx: FileContext, fi: FunctionInfo,
                        expr: ast.AST
                        ) -> Tuple[Optional[str], Optional[str]]:
        """``self._lock.acquire()`` as a bare statement ->
        (lock id, "acquire"/"release")."""
        if isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in ("acquire", "release"):
            lock = self._lock_id(ctx, fi, expr.func.value)
            if lock is not None:
                return lock, expr.func.attr
        return None, None

    def _visit(self, ctx: FileContext, fi: FunctionInfo,
               facts: _FnLocks, node: ast.AST,
               held: Tuple[str, ...]) -> Tuple[str, ...]:
        if fi.name == "<module>" and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
            return held     # bodies belong to their own function scans
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                lock = self._lock_id(ctx, fi, item.context_expr)
                if lock is not None:
                    facts.acquires.append((lock, node.lineno, inner))
                    if lock not in inner:
                        inner = inner + (lock,)
            self._walk(ctx, fi, facts, node.body, inner)
            return held                 # with-scope restores on exit
        if isinstance(node, ast.Expr):
            lock, kind = self._bare_lock_call(ctx, fi, node.value)
            if kind == "acquire":
                facts.acquires.append((lock, node.lineno, held))
                facts.bare_acquires.append((lock, node.lineno))
                if lock not in held:
                    held = held + (lock,)
                return held
            if kind == "release":
                facts.releases.add(lock)
                return tuple(h for h in held if h != lock)
        if isinstance(node, ast.Call):
            dotted = ctx.resolve(node.func)
            if dotted is not None:
                tgt = self._graph.resolve_call(fi, dotted)
                if tgt is not None and tgt != fi.qname:
                    facts.calls.append((tgt, node.lineno, held))
        for child in ast.iter_child_nodes(node):
            held = self._visit(ctx, fi, facts, child, held)
        return held

    # --- interprocedural closure --------------------------------------------

    def _acquire_closure(self) -> Dict[str, Set[str]]:
        """acq*(f): every lock f can come to hold, directly or through
        any call it makes (fixed point over the call graph)."""
        acq = {q: {a for a, _l, _h in f.acquires}
               for q, f in self._fn_locks.items()}
        changed = True
        while changed:
            changed = False
            for q, facts in self._fn_locks.items():
                cur = acq[q]
                for callee, _line, _held in facts.calls:
                    extra = acq.get(callee, set()) - cur
                    if extra:
                        cur |= extra
                        changed = True
        return acq

    def _build_edges(self) -> None:
        for q, facts in self._fn_locks.items():
            fi = self._graph.functions[q]
            for lock, line, held in facts.acquires:
                for a in held:
                    if a != lock:
                        self.edge_sites.setdefault(
                            (a, lock), AcqSite(fi.path, line, q))
            for callee, line, held in facts.calls:
                if not held:
                    continue
                for b in self._closure.get(callee, ()):
                    for a in held:
                        if a != b and b not in held:
                            self.edge_sites.setdefault(
                                (a, b), AcqSite(fi.path, line, q))

    # --- cycles -------------------------------------------------------------

    def cycles(self) -> List[List[str]]:
        """Every elementary lock-order cycle, canonicalized (rotated to
        the lexicographically smallest head, deduplicated)."""
        adj: Dict[str, Set[str]] = {}
        for a, b in self.edge_sites:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        out: List[List[str]] = []
        seen: Set[Tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: List[str],
                on_path: Set[str]) -> None:
            for nxt in sorted(adj.get(node, ())):
                if nxt == start:
                    i = path.index(min(path))
                    canon = tuple(path[i:] + path[:i])
                    if canon not in seen:
                        seen.add(canon)
                        out.append(list(canon))
                elif nxt not in on_path and nxt > start:
                    # Only explore nodes > start: each cycle is found
                    # exactly once, from its smallest member.
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        for n in sorted(adj):
            dfs(n, n, [n], {n})
        return out

    def findings(self) -> List[Finding]:
        rule = LockOrderRule()
        out: List[Finding] = []
        for cyc in self.cycles():
            pairs = list(zip(cyc, cyc[1:] + cyc[:1]))
            sites = [self.edge_sites[p] for p in pairs]
            route = "; ".join(
                f"{a} -> {b} at {s.path}:{s.line} ({s.fn.rsplit('.', 2)[-1] if '.' in s.fn else s.fn})"
                for (a, b), s in zip(pairs, sites))
            anchor = min(sites, key=lambda s: (s.path, s.line))
            out.append(Finding(
                rule=LOCK_ORDER, path=anchor.path, line=anchor.line,
                severity=ERROR,
                message=f"lock acquisition-order cycle "
                        f"{' -> '.join(cyc + [cyc[0]])}: {route} — two "
                        f"threads taking these locks in opposite orders "
                        f"deadlock; pick one global order (or drop a "
                        f"lock scope) and add a waiver only if an "
                        f"external protocol serializes the paths"))
        out.extend(self.balance_findings())
        return sorted(out, key=lambda f: (f.path, f.line))

    def balance_findings(self) -> List[Finding]:
        """WARNING per bare ``.acquire()`` whose function never calls
        ``.release()`` on the same lock: on every path out of that
        function the lock stays held — either a leak (deadlock the next
        time anyone takes it) or a cross-function hand-off the analysis
        cannot see (which deserves the ``with`` form or a waiver)."""
        out: List[Finding] = []
        for q, facts in sorted(self._fn_locks.items()):
            if not facts.bare_acquires:
                continue
            fi = self._graph.functions[q]
            for lock, line in facts.bare_acquires:
                if lock in facts.releases:
                    continue
                out.append(Finding(
                    rule=LOCK_BALANCE, path=fi.path, line=line,
                    severity=WARNING,
                    message=f"{lock}.acquire() here but {q.rsplit('.', 1)[-1]}() "
                            f"never calls {lock}.release() — the lock "
                            f"stays held on every exit path; use `with "
                            f"{lock.rsplit('.', 1)[-1]}:` or release in "
                            f"a finally block"))
        return out
