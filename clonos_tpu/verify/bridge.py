"""Counterexample → chaos bridge: replay a model bug on the live
cluster.

A violating trace from the explorer is a sequence of protocol actions;
the subset with a live-fault analog (worker kills, lease expiry,
unlogged perturbations, storage stalls) carries a ``chaos`` hint naming
the PR 8 fault-DSL event it corresponds to. This module compiles those
hints into a :class:`~clonos_tpu.soak.chaos.ChaosSchedule`, so the
standard soak harness (``clonos_tpu soak --schedule``) re-injects the
model-level failure pattern against a real job — the audit ledger then
catches the same divergence the invariant caught in the model.

Two artifacts per counterexample, both replayable:

- ``.chaos`` — the schedule as DSL text (``parse_schedule`` input);
- ``.jsonl`` — one record per trace step (action label + the chaos
  event dict or null), tail-tolerant like every other append log, so
  ``soak.chaos.read_trace_schedule`` can import it directly.

Fire times are synthetic: hinted steps are spaced ``spacing_s`` apart
from ``start_s`` in trace order — the TEMPORAL shape of a model trace
is abstract, only the order matters, and the soak clock needs concrete
instants.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from clonos_tpu.soak.chaos import ChaosEvent, ChaosSchedule
from clonos_tpu.verify.explorer import Violation

#: ChaosEvent field defaults the hints may override
_EVENT_FIELDS = ("targets", "delay_s", "duration_s", "hold_s", "factor")


def event_for(action, at_s: float) -> Optional[ChaosEvent]:
    """The chaos event for one trace action, or None for pure protocol
    steps (acks, triggers, queue ops) with no live-fault analog."""
    if action.chaos is None:
        return None
    kind, overrides = action.chaos
    kw = {k: v for k, v in overrides if k in _EVENT_FIELDS}
    if "targets" in kw:
        kw["targets"] = tuple(int(t) for t in kw["targets"])
    return ChaosEvent(at_s=round(float(at_s), 3), kind=kind, **kw)


def compile_trace(violation: Violation, start_s: float = 0.5,
                  spacing_s: float = 1.0) -> ChaosSchedule:
    """Compile a violating trace's fault actions into a schedule."""
    events: List[ChaosEvent] = []
    at = start_s
    for action in violation.trace:
        ev = event_for(action, at)
        if ev is not None:
            events.append(ev)
            at += spacing_s
    return ChaosSchedule(events)


def trace_records(violation: Violation, start_s: float = 0.5,
                  spacing_s: float = 1.0) -> List[dict]:
    """One JSONL-able record per trace step, fault steps annotated
    with their compiled chaos event (the ``.jsonl`` artifact)."""
    out: List[dict] = []
    at = start_s
    for step, action in enumerate(violation.trace):
        ev = event_for(action, at)
        rec = {"model": violation.model, "step": step,
               "action": action.label(), "kind": action.kind,
               "args": list(action.args), "chaos": None}
        if ev is not None:
            rec["chaos"] = {"at_s": ev.at_s, "kind": ev.kind,
                            "targets": list(ev.targets),
                            "delay_s": ev.delay_s,
                            "duration_s": ev.duration_s,
                            "hold_s": ev.hold_s,
                            "factor": ev.factor}
            at += spacing_s
        out.append(rec)
    return out


def write_counterexample(dirpath: str, violation: Violation,
                         start_s: float = 0.5,
                         spacing_s: float = 1.0) -> dict:
    """Persist both artifacts; returns their paths and the schedule.

    File stem: ``counterexample-<model>-<invariant>`` (one pair per
    violated invariant — re-running overwrites, the trace is minimal
    and deterministic so that is idempotent)."""
    os.makedirs(dirpath, exist_ok=True)
    stem = os.path.join(
        dirpath,
        f"counterexample-{violation.model}-{violation.invariant}")
    schedule = compile_trace(violation, start_s, spacing_s)
    chaos_path = stem + ".chaos"
    with open(chaos_path, "w") as f:
        header = (f"# {violation.model}: {violation.invariant} — "
                  f"{len(violation.trace)}-step counterexample\n")
        f.write(header + schedule.to_text() + "\n")
    jsonl_path = stem + ".jsonl"
    with open(jsonl_path, "w") as f:
        for rec in trace_records(violation, start_s, spacing_s):
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    return {"chaos": chaos_path, "trace": jsonl_path,
            "schedule": schedule}
