"""Explicit-state explorer: exhaustive BFS over a protocol model.

TLA+-style bounded model checking, in-process: a :class:`Model` is a
transition system with hashable states; :func:`explore` enumerates
every state reachable within a depth/state budget, checks every safety
invariant on every reachable state, and checks bounded liveness on
every terminal (deadlock) state. Because the search is breadth-first,
the first trace reaching a violating state is a MINIMAL counterexample
— no shrinking pass needed.

Interleaving reduction is by state merging: two action orders that
land in the same (canonicalized) state are explored once. Models with
symmetric components (interchangeable workers, contenders) can
canonicalize harder via :meth:`Model.canon`.

Everything here is pure Python and deterministic — no wall clock, no
RNG, no jax — so the ``--quick`` sweep can gate the test session from
any CI box, like the lint.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

#: invariant check: state -> None (holds) or a violation detail string
Invariant = Tuple[str, Callable[[object], Optional[str]]]


@dataclasses.dataclass(frozen=True)
class Action:
    """One enabled protocol step. ``chaos`` optionally names the PR 8
    fault-DSL event this step corresponds to on a live cluster — the
    counterexample→chaos bridge compiles exactly those steps."""

    kind: str
    args: Tuple = ()
    #: chaos-DSL hint: (kind, field-overrides) or None for pure
    #: protocol steps with no live-fault analog
    chaos: Optional[Tuple[str, Tuple[Tuple[str, object], ...]]] = None

    def label(self) -> str:
        if not self.args:
            return self.kind
        return f"{self.kind}({', '.join(str(a) for a in self.args)})"


class Model:
    """A finite protocol transition system. States must be hashable
    and treated as immutable; ``apply`` returns a NEW state."""

    name = "?"

    def initial_state(self):
        raise NotImplementedError

    def enabled(self, state) -> List[Action]:
        """Every action enabled in ``state`` (deterministic order)."""
        raise NotImplementedError

    def apply(self, state, action: Action):
        raise NotImplementedError

    def invariants(self) -> List[Invariant]:
        """Safety: checked on every reachable state."""
        return []

    def canon(self, state):
        """Symmetry reduction hook: map a state to its equivalence-
        class representative before dedup (default: identity)."""
        return state

    def settled(self, state) -> Optional[str]:
        """Bounded liveness: called on every TERMINAL state (no
        enabled actions). None = acceptable final state; a string =
        the protocol wedged (e.g. a recovery that never caught up)."""
        return None


@dataclasses.dataclass
class Violation:
    model: str
    invariant: str               # invariant name, or "liveness"
    detail: str
    trace: List[Action]          # minimal: BFS discovery order
    state: object
    depth: int

    def to_dict(self) -> dict:
        return {"model": self.model, "invariant": self.invariant,
                "detail": self.detail, "depth": self.depth,
                "trace": [a.label() for a in self.trace]}


@dataclasses.dataclass
class ExploreResult:
    model: str
    states: int                  # distinct states reached
    transitions: int             # edges taken (post-dedup source count)
    depth: int                   # deepest layer fully expanded
    truncated: bool              # hit the depth or state budget
    violations: List[Violation]

    @property
    def ok(self) -> bool:
        return not self.violations


def explore(model: Model, depth: int = 48, max_states: int = 200_000,
            stop_at_first: bool = True) -> ExploreResult:
    """Exhaustive BFS from the model's initial state.

    Invariants are checked on every state at discovery; liveness
    (``settled``) on every terminal state. When the depth or state
    budget truncates the frontier, ``truncated`` is set and liveness
    is NOT judged on cut-off states — an unexpanded state is not a
    deadlock.
    """
    init = model.initial_state()
    init_key = model.canon(init)
    #: canon-key -> (parent key, action, concrete state, depth)
    seen: Dict[object, Tuple[Optional[object], Optional[Action],
                             object, int]] = {
        init_key: (None, None, init, 0)}
    queue = collections.deque([init_key])
    invs = model.invariants()
    violations: List[Violation] = []
    transitions = 0
    max_depth = 0
    truncated = False

    def trace_to(key: object) -> List[Action]:
        out: List[Action] = []
        while True:
            parent, action, _state, _d = seen[key]
            if action is None:
                return list(reversed(out))
            out.append(action)
            key = parent

    def check(key: object, state, d: int) -> bool:
        """True if a violation was recorded for this state."""
        bad = False
        for name, fn in invs:
            detail = fn(state)
            if detail is not None:
                violations.append(Violation(
                    model=model.name, invariant=name, detail=detail,
                    trace=trace_to(key), state=state, depth=d))
                bad = True
        return bad

    if check(init_key, init, 0) and stop_at_first:
        return ExploreResult(model.name, 1, 0, 0, False, violations)

    while queue:
        key = queue.popleft()
        _parent, _action, state, d = seen[key]
        max_depth = max(max_depth, d)
        actions = model.enabled(state)
        if not actions:
            wedged = model.settled(state)
            if wedged is not None:
                violations.append(Violation(
                    model=model.name, invariant="liveness",
                    detail=wedged, trace=trace_to(key), state=state,
                    depth=d))
                if stop_at_first:
                    break
            continue
        if d >= depth:
            truncated = True
            continue
        stop = False
        for action in actions:
            nxt = model.apply(state, action)
            nkey = model.canon(nxt)
            transitions += 1
            if nkey in seen:
                continue
            if len(seen) >= max_states:
                truncated = True
                continue
            seen[nkey] = (key, action, nxt, d + 1)
            queue.append(nkey)
            if check(nkey, nxt, d + 1) and stop_at_first:
                stop = True
                break
        if stop:
            break

    return ExploreResult(model=model.name, states=len(seen),
                         transitions=transitions, depth=max_depth,
                         truncated=truncated, violations=violations)


def traces(model: Model, n: int, depth: int = 48,
           max_states: int = 200_000,
           admissible: Optional[Callable[[List[Action]], bool]] = None
           ) -> List[List[Action]]:
    """Up to ``n`` distinct model-generated traces for conformance
    replay: the BFS paths to terminal states (preferred — they exercise
    the full protocol round) then to the deepest interior states,
    filtered by the adapter's ``admissible`` predicate. Deterministic:
    same model, same arguments, same traces."""
    init = model.initial_state()
    init_key = model.canon(init)
    seen: Dict[object, Tuple[Optional[object], Optional[Action],
                             object, int]] = {
        init_key: (None, None, init, 0)}
    queue = collections.deque([init_key])
    terminal: List[Tuple[object, int]] = []
    interior: List[Tuple[object, int]] = []
    while queue:
        key = queue.popleft()
        _p, _a, state, d = seen[key]
        actions = model.enabled(state)
        if not actions:
            terminal.append((key, d))
            continue
        interior.append((key, d))
        if d >= depth:
            continue
        for action in actions:
            nxt = model.apply(state, action)
            nkey = model.canon(nxt)
            if nkey in seen or len(seen) >= max_states:
                continue
            seen[nkey] = (key, action, nxt, d + 1)
            queue.append(nkey)

    def path(key: object) -> List[Action]:
        out: List[Action] = []
        while True:
            parent, action, _s, _d = seen[key]
            if action is None:
                return list(reversed(out))
            out.append(action)
            key = parent

    out: List[List[Action]] = []
    seen_traces = set()
    for key, _d in (sorted(terminal, key=lambda t: -t[1])
                    + sorted(interior, key=lambda t: -t[1])):
        t = path(key)
        if not t:
            continue
        if admissible is not None and not admissible(t):
            continue
        sig = tuple(a.label() for a in t)
        if sig in seen_traces:
            continue
        seen_traces.add(sig)
        out.append(t)
        if len(out) >= n:
            break
    return out
