"""Protocol model checker: exhaustive exploration of the
checkpoint–recovery–fencing–admission protocols at small bounds, with
chaos-replayable counterexamples.

- explorer.py — BFS state-space search, minimal counterexample traces
- models.py — the four formal transition models (+ seeded bugs)
- bridge.py — counterexample → chaos-DSL schedule compiler
- conformance.py — replay model traces against the real components
- runner.py — CLI/CI driver (``clonos_tpu verify``)
"""

from clonos_tpu.verify.explorer import (Action, ExploreResult, Model,
                                        Violation, explore, traces)
from clonos_tpu.verify.models import BUGS, MODELS
from clonos_tpu.verify.runner import (QUICK_BOUND, VerifyResult,
                                      format_json, format_text,
                                      run_verify)
from clonos_tpu.verify.bridge import (compile_trace, event_for,
                                      trace_records,
                                      write_counterexample)

__all__ = [
    "Action", "ExploreResult", "Model", "Violation", "explore",
    "traces", "BUGS", "MODELS", "QUICK_BOUND", "VerifyResult",
    "format_json", "format_text", "run_verify", "compile_trace",
    "event_for", "trace_records", "write_counterexample",
]
