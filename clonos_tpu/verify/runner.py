"""Verify driver: the model checker's CI conventions.

``run_verify`` explores every requested protocol model at one bound
(workers/epochs/faults × depth/state budget) and reports violations
with minimal counterexample traces, sharing the lint/analyze CI shape:
one-line ``--report json``, exit 0/1, a ``--quick`` bound cheap enough
to gate the test session from conftest.

Pure Python end to end (models and explorer import no jax) — the
conformance layer (verify/conformance.py), which drives the REAL
components and therefore needs the full runtime, is opt-in via
``conformance=True``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

from clonos_tpu.verify.explorer import ExploreResult, explore
from clonos_tpu.verify.models import BUGS, MODELS

#: the ``--quick`` session-gate bound: small enough to finish in well
#: under a second, big enough that every invariant is exercised on
#: thousands of states.
QUICK_BOUND = dict(workers=2, epochs=2, faults=1, depth=24,
                   max_states=20_000)


@dataclasses.dataclass
class VerifyResult:
    reports: List[ExploreResult]
    bound: Dict[str, int]
    quick: bool
    conformance: Optional[Dict] = None   # component -> report dict

    @property
    def violations(self) -> List:
        return [v for r in self.reports for v in r.violations]

    @property
    def ok(self) -> bool:
        if self.conformance is not None and any(
                not c["ok"] for c in self.conformance.values()):
            return False
        return all(r.ok for r in self.reports)

    def exit_code(self) -> int:
        return 0 if self.ok else 1

    def to_dict(self) -> dict:
        out = {
            "ok": self.ok,
            "quick": self.quick,
            "bound": dict(self.bound),
            "models": [{
                "model": r.model,
                "states": r.states,
                "transitions": r.transitions,
                "depth": r.depth,
                "truncated": r.truncated,
                "violations": [v.to_dict() for v in r.violations],
            } for r in self.reports],
        }
        if self.conformance is not None:
            out["conformance"] = self.conformance
        return out


def run_verify(models: Optional[Sequence[str]] = None,
               workers: int = 2, epochs: int = 2, faults: int = 1,
               depth: int = 48, max_states: int = 200_000,
               quick: bool = False,
               bugs: Optional[Dict[str, str]] = None,
               conformance: bool = False,
               conformance_traces: int = 3) -> VerifyResult:
    """Check the requested models (default: all six) at one bound.

    ``quick`` swaps in :data:`QUICK_BOUND` wholesale. ``bugs`` maps a
    model name to a seeded defect from :data:`models.BUGS` — the
    checker is then EXPECTED to find a counterexample, and the result's
    exit code says whether it did (nonzero = found, the
    prove-the-invariants-bite mode)."""
    if quick:
        workers, epochs, faults = (QUICK_BOUND["workers"],
                                   QUICK_BOUND["epochs"],
                                   QUICK_BOUND["faults"])
        depth = QUICK_BOUND["depth"]
        max_states = QUICK_BOUND["max_states"]
    names = list(models or MODELS)
    bugs = dict(bugs or {})
    for name in names:
        if name not in MODELS:
            raise ValueError(f"unknown model {name!r} "
                             f"(one of {', '.join(sorted(MODELS))})")
    for name, bug in bugs.items():
        if name not in BUGS or bug not in BUGS[name]:
            raise ValueError(f"unknown seeded bug {name}:{bug}")
    reports = [explore(MODELS[name](workers=workers, epochs=epochs,
                                    faults=faults,
                                    bug=bugs.get(name)),
                       depth=depth, max_states=max_states)
               for name in names]
    conf = None
    if conformance:
        from clonos_tpu.verify.conformance import run_conformance
        conf = {c: r.to_dict() for c, r in run_conformance(
            names, n_traces=conformance_traces, workers=workers,
            epochs=epochs, faults=faults).items()}
    return VerifyResult(reports=reports,
                        bound={"workers": workers, "epochs": epochs,
                               "faults": faults, "depth": depth,
                               "max_states": max_states},
                        quick=quick, conformance=conf)


def format_text(result: VerifyResult) -> str:
    lines: List[str] = []
    for r in result.reports:
        flag = " (truncated)" if r.truncated else ""
        lines.append(f"{r.model}: {r.states} state(s), "
                     f"{r.transitions} transition(s), depth {r.depth}"
                     f"{flag}, {len(r.violations)} violation(s)")
        for v in r.violations:
            lines.append(f"  {v.invariant} at depth {v.depth}: "
                         f"{v.detail}")
            for i, a in enumerate(v.trace):
                lines.append(f"    {i + 1}. {a.label()}")
    if result.conformance:
        for c, rep in sorted(result.conformance.items()):
            ok = "ok" if rep["ok"] else "DIVERGED"
            lines.append(f"conformance {c}: {rep['traces']} trace(s), "
                         f"{rep['steps']} step(s), {ok}")
            for d in rep["divergences"]:
                lines.append(
                    f"  trace {d['trace']} step {d['step']} "
                    f"({d['action']}): expected {d['expected']}, "
                    f"observed {d['observed']}")
    b = result.bound
    lines.append(
        f"verify: {len(result.reports)} model(s) at "
        f"workers={b['workers']} epochs={b['epochs']} "
        f"faults={b['faults']} depth={b['depth']}; "
        f"{sum(r.states for r in result.reports)} state(s), "
        f"{len(result.violations)} violation(s)"
        + ("" if result.ok else " — FAILED"))
    return "\n".join(lines)


def format_json(result: VerifyResult) -> str:
    """One machine-readable line (the lint/analyze CI convention)."""
    return json.dumps(result.to_dict(), sort_keys=True)
