"""Formal transition models of the six runtime protocols.

Each model mirrors ONE real component's protocol — the transitions the
implementation exposes to its driver — at the smallest state that
preserves the safety argument:

- :class:`CheckpointModel` — runtime/checkpoint.py's coordinator as
  driven by the cluster runner: trigger → (async) durable write →
  per-worker acks at the closing fence → completion → log truncation,
  with worker kills, failure detection (``ignore_unacked_for``) and
  the driver's ``discard_pending_through`` sweep of superseded fences.
- :class:`RecoveryModel` — causal/recovery.py's per-subtask FSM:
  STANDBY → WAITING_CONNECTIONS → WAITING_DETERMINANTS → REPLAYING →
  RUNNING, under every notification interleaving the driver permits.
- :class:`LeaseModel` — runtime/leader.py's claim-file election:
  epoch claims, lease expiry, rival takeover, and the receiver-side
  fencing check that makes a deposed leader's token worthless.
- :class:`AdmissionModel` — runtime/dispatcher.py's
  ``AdmissionController``: per-tenant quota charged on reservation
  (held + queued), strict-FIFO head-blocking queue, cancel/release.
- :class:`RepartitionModel` — the live re-cut protocol
  (runtime/cluster.py's ``rescale_live`` driven by the
  ``RescaleCoordinator``): at a completed checkpoint fence the old
  incarnation stops ingesting, drains each key group's in-flight edge
  records into its state, migrates the drained groups to the N±k
  incarnation, and only then redirects traffic — exactly once per
  record across the fence.
- :class:`ScalePolicyModel` — the autoscaler's decision protocol
  (autoscale/controller.py's ``AutoscaleController`` over the pure
  ``ScalePolicy``): per completed fence, fold the load signal into
  sustain streaks, decide under hysteresis + cooldown, LOG the
  decision as a SCALE determinant, then execute the re-cut only if
  the cluster is still healthy — under worker kills landing anywhere,
  including between decide and execute.

``bug=`` injects a named, intentional protocol defect (see ``BUGS``).
Each seeded bug reproduces a hazard the real protocol's discipline
exists to prevent; the checker must find a minimal counterexample for
every one of them (tests/test_verify.py), which is the evidence the
invariants are not vacuous.

States are nested tuples/frozensets (hashable, immutable); every
transition is pure. No wall clock, no RNG, no jax.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

from clonos_tpu.verify.explorer import Action, Model

#: model name -> {bug name: what protocol discipline it removes}
BUGS: Dict[str, Dict[str, str]] = {
    "checkpoint": {
        "late-ack": "acks accepted for superseded fences (drops the "
                    "executors' ack-at-the-closing-fence discipline) — "
                    "a late completion regresses the truncation fence",
        "unlogged-write": "a worker may perturb its output without "
                          "logging a determinant — replay diverges "
                          "(the audit-bait nondet fault)",
    },
    "recovery": {
        "early-response": "determinant responses delivered before the "
                          "manager reaches WAITING_DETERMINANTS — the "
                          "real manager raises RecoveryError",
    },
    "lease": {
        "no-fencing-check": "receivers skip the fencing_valid claim "
                            "check — a deposed leader's stale token "
                            "is accepted alongside the rival's",
    },
    "admission": {
        "cancel-leaks-quota": "cancelling a queued job forgets to "
                              "release its reservation charge — the "
                              "tenant's quota leaks",
    },
    "repartition": {
        "migrate-skips-drain": "a key group may migrate while its "
                               "in-flight edge records are still "
                               "buffered — the leftovers die with the "
                               "old incarnation at redirect (records "
                               "lost)",
        "redirect-before-migrate": "traffic redirects before every "
                                   "group has migrated — unmigrated "
                                   "groups restart empty on the new "
                                   "incarnation (state and in-flight "
                                   "records lost)",
        "stale-writer": "the old incarnation keeps applying to a "
                        "group it already handed off — the new owner "
                        "replays the same records (duplicates)",
    },
    "scalepolicy": {
        "no-cooldown": "decisions skip the cooldown gate — a sustained "
                       "spike followed by its own backpressure dip "
                       "thrashes the cluster up-then-down inside one "
                       "cooldown window",
        "unlogged-decision": "a scale action executes without its "
                             "SCALE determinant — a recovered "
                             "controller cannot replay it and would "
                             "re-decide (double re-cut)",
        "rescale-mid-recovery": "execute skips the health re-check — a "
                                "worker kill landing between decide "
                                "and execute lets a re-cut run over an "
                                "in-progress recovery",
    },
}


def _check_bug(model: str, bug: Optional[str]) -> Optional[str]:
    if bug is not None and bug not in BUGS[model]:
        raise ValueError(f"unknown {model} bug {bug!r} "
                         f"(one of {', '.join(sorted(BUGS[model]))})")
    return bug


# --- checkpoint coordination ---------------------------------------------

#: per-cid status markers (cids tuple entries; pending carries payload)
_UNBORN = ("unborn",)
_IGNORED = ("ignored",)
_COMPLETE = ("complete",)


class CheckpointModel(Model):
    """Checkpoint coordination for one coordinator (one job/group).

    State::

        (cids, alive, undetected, truncated, hi_truncated,
         faults_left, unlogged)

    ``cids[i]`` is checkpoint id ``i+1``: ``("unborn",)``,
    ``("pending", missing, written)``, ``("ignored",)`` or
    ``("complete",)``. Completion is NOT a scheduled choice — exactly
    like ``_maybe_complete`` it fires deterministically the moment a
    pending checkpoint is durable with an empty missing set, ignoring
    superseded lower fences (the driver's ``discard_pending_through``
    at the completion fence) and truncating logs to its fence.

    Invariants:

    - **truncate-monotone** — the truncation fence never regresses
      (a regression re-truncates rings below already-released state).
    - **truncate-sealed** — logs are only ever truncated at a fence
      backed by a durable COMPLETED checkpoint (exactly-once: records
      below the fence are re-derivable from that checkpoint alone).
    - **exactly-once-logged** — no worker holds an unlogged
      perturbation (every replayed value has a determinant).
    """

    name = "checkpoint"

    def __init__(self, workers: int = 2, epochs: int = 2,
                 faults: int = 1, bug: Optional[str] = None):
        self.workers = int(workers)
        self.epochs = int(epochs)
        self.faults = int(faults)
        self.bug = _check_bug("checkpoint", bug)

    def initial_state(self):
        return ((_UNBORN,) * self.epochs,
                frozenset(range(self.workers)), frozenset(),
                0, 0, self.faults, frozenset())

    # dense encoding helpers
    @staticmethod
    def _pending(missing, written):
        return ("pending", missing, written)

    def enabled(self, state) -> List[Action]:
        cids, alive, undetected, _tr, _hi, faults_left, unlogged = state
        out: List[Action] = []
        triggered = sum(1 for c in cids if c != _UNBORN)
        newest = triggered  # cid number of the newest triggered fence
        if triggered < self.epochs:
            out.append(Action("trigger", (triggered + 1,)))
        for i, c in enumerate(cids):
            if c[0] != "pending":
                continue
            cid = i + 1
            _tag, missing, written = c
            if not written:
                out.append(Action("write", (cid,)))
            ack_ok = (cid == newest or self.bug == "late-ack")
            if ack_ok:
                for w in sorted(missing & alive):
                    out.append(Action("ack", (cid, w)))
            if cid < newest:
                # The driver's discard_pending_through sweep: a fence
                # superseded by a newer trigger may be abandoned.
                out.append(Action("discard", (cid,)))
        for w in sorted(alive):
            if faults_left > 0:
                out.append(Action(
                    "kill", (w,),
                    chaos=("kill", (("targets", (w,)),))))
            if self.bug == "unlogged-write" and w not in unlogged:
                out.append(Action("perturb", (w,),
                                  chaos=("nondet", ())))
        for w in sorted(undetected):
            out.append(Action("detect", (w,)))
        return out

    def apply(self, state, action: Action):
        cids, alive, undetected, tr, hi, faults_left, unlogged = state
        cids = list(cids)
        k, args = action.kind, action.args
        if k == "trigger":
            cid = args[0]
            cids[cid - 1] = self._pending(
                frozenset(range(self.workers)), False)
        elif k == "write":
            cid = args[0]
            _t, missing, _w = cids[cid - 1]
            cids[cid - 1] = self._pending(missing, True)
            cids, tr, hi = self._maybe_complete(cids, cid, tr, hi)
        elif k == "ack":
            cid, w = args
            _t, missing, written = cids[cid - 1]
            cids[cid - 1] = self._pending(missing - {w}, written)
            cids, tr, hi = self._maybe_complete(cids, cid, tr, hi)
        elif k == "discard":
            cids[args[0] - 1] = _IGNORED
        elif k == "kill":
            w = args[0]
            alive = alive - {w}
            undetected = undetected | {w}
            faults_left -= 1
        elif k == "detect":
            # ignore_unacked_for({w}) + (abstracted) instant redeploy:
            # the detailed standby path is RecoveryModel's subject.
            w = args[0]
            for i, c in enumerate(cids):
                if c[0] == "pending" and w in c[1]:
                    cids[i] = _IGNORED
            undetected = undetected - {w}
            alive = alive | {w}
        elif k == "perturb":
            unlogged = unlogged | {args[0]}
        else:
            raise ValueError(f"bad action {action}")
        return (tuple(cids), alive, undetected, tr, hi, faults_left,
                unlogged)

    def _maybe_complete(self, cids, cid, tr, hi):
        tag, missing, written = cids[cid - 1]
        if tag != "pending" or missing or not written:
            return cids, tr, hi
        cids[cid - 1] = _COMPLETE
        # Completion fence: superseded pendings are swept (the driver's
        # discard_pending_through) and logs truncate to this fence.
        # Bug late-ack drops BOTH halves of the fence discipline — the
        # sweep and the ack gate — so a superseded checkpoint can
        # complete late and regress the truncation fence.
        if self.bug != "late-ack":
            for i in range(cid - 1):
                if cids[i][0] == "pending":
                    cids[i] = _IGNORED
        return cids, cid, max(hi, cid)

    def invariants(self):
        def monotone(state):
            _c, _a, _u, tr, hi, _f, _ul = state
            if tr != hi:
                return (f"truncation fence regressed to {tr} after "
                        f"reaching {hi} — rings below {hi} were "
                        f"already released")
            return None

        def sealed(state):
            cids, _a, _u, tr, _hi, _f, _ul = state
            if tr and cids[tr - 1] != _COMPLETE:
                return (f"logs truncated at fence {tr} but checkpoint "
                        f"{tr} is {cids[tr - 1][0]}, not durable — "
                        f"records below the fence are unrecoverable")
            return None

        def logged(state):
            unlogged = state[6]
            if unlogged:
                return (f"worker(s) {sorted(unlogged)} hold an "
                        f"unlogged perturbation — replay of their "
                        f"block diverges from the delivered output "
                        f"(exactly-once broken)")
            return None

        return [("truncate-monotone", monotone),
                ("truncate-sealed", sealed),
                ("exactly-once-logged", logged)]

    def canon(self, state):
        """Workers are symmetric: relabel to the lexicographically
        smallest image over all worker permutations."""
        if self.workers > 3:
            return state
        cids, alive, undetected, tr, hi, fl, unlogged = state

        def encode(s):
            # Fully-sorted injective encoding: min() over it picks one
            # well-defined representative per equivalence class.
            ecids, ea, eu, etr, ehi, efl, eul = s
            return (tuple(c if c[0] != "pending" else
                          ("pending", tuple(sorted(c[1])), c[2])
                          for c in ecids),
                    tuple(sorted(ea)), tuple(sorted(eu)),
                    etr, ehi, efl, tuple(sorted(eul)))

        best = None
        for perm in itertools.permutations(range(self.workers)):
            m = {w: perm[w] for w in range(self.workers)}
            cand = (tuple(c if c[0] != "pending" else
                          ("pending", frozenset(m[w] for w in c[1]),
                           c[2]) for c in cids),
                    frozenset(m[w] for w in alive),
                    frozenset(m[w] for w in undetected),
                    tr, hi, fl,
                    frozenset(m[w] for w in unlogged))
            enc = encode(cand)
            if best is None or enc < best[0]:
                best = (enc, cand)
        return best[1]

    def settled(self, state) -> Optional[str]:
        cids = state[0]
        stuck = [i + 1 for i, c in enumerate(cids)
                 if c[0] in ("unborn", "pending")]
        if stuck:
            return (f"checkpoint(s) {stuck} never resolved "
                    f"(complete or ignored) — the protocol wedged")
        return None


# --- recovery FSM ---------------------------------------------------------

#: RecoveryState mirror (ints keep the state tuple tiny; names match
#: causal/recovery.py's enum so conformance compares by name)
FSM_NAMES = ("STANDBY", "WAITING_CONNECTIONS", "WAITING_DETERMINANTS",
             "REPLAYING", "RUNNING")
_STANDBY, _WAIT_CONN, _WAIT_DET, _REPLAYING, _RUNNING = range(5)


class RecoveryModel(Model):
    """One recovering subtask's FSM under every notification
    interleaving the cluster driver permits: restoration completion,
    input/output channel establishment and the expected-response count
    arrive in ANY order after ``start``; determinant responses are
    delivered only once the manager is WAITING_DETERMINANTS (the real
    manager raises ``RecoveryError`` otherwise — bug
    ``early-response`` removes that gate to prove the model notices).

    State: ``(fsm, restored, ins, outs, expected_set, responses,
    errored)``; ``ins``/``outs`` are per-peer booleans.

    Liveness is the point here: every interleaving must reach RUNNING
    (recovery always catches up); a terminal state anywhere else is a
    lost-wakeup bug in the advance conditions.
    """

    name = "recovery"

    def __init__(self, workers: int = 2, epochs: int = 2,
                 faults: int = 1, bug: Optional[str] = None):
        del epochs, faults      # recovery explores one incarnation
        self.peers = max(1, int(workers) - 1)
        self.bug = _check_bug("recovery", bug)

    def initial_state(self):
        return (_STANDBY, False, (False,) * self.peers,
                (False,) * self.peers, False, 0, False)

    def enabled(self, state) -> List[Action]:
        fsm, restored, ins, outs, expected_set, resp, errored = state
        if errored:
            return []
        out: List[Action] = []
        if fsm == _STANDBY:
            out.append(Action(
                "start", (),
                chaos=("kill", (("targets", (1,)),))))
            return out
        if fsm == _WAIT_CONN and not restored:
            out.append(Action("restore_done"))
        if fsm == _WAIT_CONN:
            for i, up in enumerate(ins):
                if not up:
                    out.append(Action("chan_in", (i,)))
            for i, up in enumerate(outs):
                if not up:
                    out.append(Action("chan_out", (i,)))
        if not expected_set and fsm in (_WAIT_CONN, _WAIT_DET):
            out.append(Action("expect", (self.peers,)))
        if resp < self.peers and (
                fsm == _WAIT_DET
                or (self.bug == "early-response"
                    and fsm == _WAIT_CONN and expected_set)):
            out.append(Action("response", (resp,)))
        if fsm == _REPLAYING:
            out.append(Action("replay"))
        return out

    def apply(self, state, action: Action):
        fsm, restored, ins, outs, expected_set, resp, errored = state
        k = action.kind
        if k == "start":
            fsm = _WAIT_CONN
        elif k == "restore_done":
            restored = True
        elif k == "chan_in":
            ins = ins[:action.args[0]] + (True,) \
                + ins[action.args[0] + 1:]
        elif k == "chan_out":
            outs = outs[:action.args[0]] + (True,) \
                + outs[action.args[0] + 1:]
        elif k == "expect":
            expected_set = True
        elif k == "response":
            if fsm != _WAIT_DET:
                # the real notify_determinant_response raises here
                errored = True
            else:
                resp += 1
        elif k == "replay":
            fsm = _RUNNING
        else:
            raise ValueError(f"bad action {action}")
        # _maybe_advance_connections / _maybe_have_determinants mirrors
        if fsm == _WAIT_CONN and restored and all(ins) and all(outs):
            fsm = _WAIT_DET
        if fsm == _WAIT_DET and expected_set and resp >= self.peers:
            fsm = _REPLAYING
        return (fsm, restored, ins, outs, expected_set, resp, errored)

    def invariants(self):
        def no_error(state):
            if state[6]:
                return ("a notification arrived in a state the real "
                        "RecoveryManager raises RecoveryError for — "
                        "the driver's ordering guarantee is broken")
            return None

        def gated(state):
            fsm, restored, ins, outs, expected_set, resp, _e = state
            if fsm >= _REPLAYING and not (
                    restored and all(ins) and all(outs)
                    and expected_set and resp >= self.peers):
                return ("replay started before restoration, channels "
                        "and all determinant responses were in")
            return None

        return [("no-protocol-error", no_error),
                ("replay-gated", gated)]

    def settled(self, state) -> Optional[str]:
        if state[0] != _RUNNING:
            return (f"recovery wedged in {FSM_NAMES[state[0]]} — "
                    f"never reached RUNNING (caught-up)")
        return None


# --- leader-lease fencing -------------------------------------------------

class LeaseModel(Model):
    """Claim-file leader election with receiver-side fencing.

    State: ``(claims, believed, faults_left)`` — ``claims[e-1] =
    (owner, live)`` for epoch ``e`` (epochs are claimed in order, one
    owner each, exactly the O_CREAT|O_EXCL arbitration); ``believed[c]``
    is contender ``c``'s own fencing token (its ``election.epoch``),
    which goes stale silently when a rival claims a higher epoch —
    the split-brain window fencing exists to close.

    Each lease expiry consumes one injected fault (a leader pause long
    enough for the TTL to lapse — the chaos ``leader-loss`` event).

    Invariant **single-fenced-writer**: at most one contender holds a
    token the receiver-side check (``fencing_valid``: token == highest
    existing claim) would accept. Bug ``no-fencing-check`` makes
    receivers accept any token, and the checker must find the classic
    three-step counterexample: acquire(A) → expiry → acquire(B) leaves
    A and B both writing.
    """

    name = "lease"

    def __init__(self, workers: int = 2, epochs: int = 2,
                 faults: int = 1, bug: Optional[str] = None):
        del epochs              # epoch count is derived: faults + 1
        self.contenders = max(2, int(workers))
        self.faults = int(faults)
        self.bug = _check_bug("lease", bug)

    def initial_state(self):
        return ((), (None,) * self.contenders, self.faults)

    def enabled(self, state) -> List[Action]:
        claims, believed, faults_left = state
        out: List[Action] = []
        top_live = bool(claims) and claims[-1][1]
        if not top_live:
            for c in range(self.contenders):
                out.append(Action("acquire", (c,)))
        elif faults_left > 0:
            out.append(Action(
                "expire", (),
                chaos=("leader-loss", (("hold_s", 0.6),))))
        for c in range(self.contenders):
            e = believed[c]
            if e is None:
                continue
            if e == len(claims) and not claims[-1][1]:
                out.append(Action("renew", (c,)))   # revives own lease
            elif e != len(claims):
                out.append(Action("renew", (c,)))   # discovers deposed
        return out

    def apply(self, state, action: Action):
        claims, believed, faults_left = state
        k = action.kind
        believed = list(believed)
        if k == "acquire":
            c = action.args[0]
            claims = claims + ((c, True),)
            believed[c] = len(claims)
        elif k == "expire":
            claims = claims[:-1] + ((claims[-1][0], False),)
            faults_left -= 1
        elif k == "renew":
            c = action.args[0]
            if believed[c] == len(claims):
                claims = claims[:-1] + ((claims[-1][0], True),)
            else:
                believed[c] = None      # deposed: a higher claim exists
        else:
            raise ValueError(f"bad action {action}")
        return (claims, tuple(believed), faults_left)

    def _accepted(self, token: int, claims) -> bool:
        if self.bug == "no-fencing-check":
            return True
        return bool(claims) and token == len(claims)

    def invariants(self):
        def single_writer(state):
            claims, believed, _f = state
            writers = [c for c, e in enumerate(believed)
                       if e is not None and self._accepted(e, claims)]
            if len(writers) > 1:
                toks = {c: believed[c] for c in writers}
                return (f"contenders {writers} all hold accepted "
                        f"fencing tokens {toks} — two fenced writers "
                        f"for one job (split brain)")
            return None

        return [("single-fenced-writer", single_writer)]

    def settled(self, state) -> Optional[str]:
        return None     # a live, renewing leader is a fine place to end


# --- dispatcher admission -------------------------------------------------

class AdmissionModel(Model):
    """The AdmissionController's bookkeeping under one dispatcher lock:
    quota charged on RESERVATION (held + queued), strict-FIFO
    head-blocking drain, queued-cancel releasing the charge, release
    on finish/cancel of admitted jobs.

    Configuration scales with ``workers``: a pool of ``workers`` slots,
    two tenants with quota ``workers + 1``, and per tenant two jobs of
    1 and ``workers`` slots — small enough to exhaust, shaped to force
    queueing, head-blocking and cross-tenant contention.

    State: ``(status, queue, pending, held)`` — per-job status in
    {new, queued, held, done, cancelled, rejected}, the FIFO queue,
    the reservation-charge set, per-tenant held counts. ``held`` is
    EXPLICIT (not derived) precisely so accounting bugs are
    expressible; invariant **no-leak** re-derives it from statuses and
    must always agree.
    """

    name = "admission"

    NEW, QUEUED, HELD, DONE, CANCELLED, REJECTED = range(6)

    def __init__(self, workers: int = 2, epochs: int = 2,
                 faults: int = 1, bug: Optional[str] = None):
        del epochs, faults
        self.pool = max(2, int(workers))
        self.quota = self.pool + 1
        #: (tenant, slots) per job: two tenants, small + pool-sized
        self.jobs: Tuple[Tuple[int, int], ...] = (
            (0, 1), (0, self.pool), (1, 1), (1, self.pool))
        self.bug = _check_bug("admission", bug)

    def initial_state(self):
        return ((self.NEW,) * len(self.jobs), (), frozenset(), (0, 0))

    def _reserved(self, tenant, pending, held):
        return held[tenant] + sum(
            s for j, (t, s) in enumerate(self.jobs)
            if t == tenant and j in pending)

    def _free(self, held):
        return self.pool - sum(held)

    def enabled(self, state) -> List[Action]:
        status, queue, pending, held = state
        out: List[Action] = []
        for j, st in enumerate(status):
            if st == self.NEW:
                out.append(Action("submit", (j,)))
            elif st == self.QUEUED:
                out.append(Action("cancel_queued", (j,)))
            elif st == self.HELD:
                out.append(Action("finish", (j,)))
                out.append(Action("cancel_held", (j,)))
        if queue:
            _t, slots = self.jobs[queue[0]]
            if slots <= self._free(held):
                out.append(Action("admit"))
        return out

    def apply(self, state, action: Action):
        status, queue, pending, held = state
        status = list(status)
        held = list(held)
        k = action.kind
        if k == "submit":
            j = action.args[0]
            t, slots = self.jobs[j]
            if self._reserved(t, pending, tuple(held)) + slots \
                    > self.quota:
                status[j] = self.REJECTED
            elif queue or slots > self._free(held):
                status[j] = self.QUEUED
                queue = queue + (j,)
                pending = pending | {j}
            else:
                status[j] = self.HELD
                held[t] += slots
        elif k == "admit":
            # admit_queued: drain the head while slots last — strict
            # FIFO, a too-big head blocks the drain.
            free = self._free(held)
            while queue:
                t, slots = self.jobs[queue[0]]
                if slots > free:
                    break
                j = queue[0]
                queue = queue[1:]
                pending = pending - {j}
                status[j] = self.HELD
                held[t] += slots
                free -= slots
        elif k == "cancel_queued":
            j = action.args[0]
            status[j] = self.CANCELLED
            queue = tuple(q for q in queue if q != j)
            if self.bug != "cancel-leaks-quota":
                pending = pending - {j}
        elif k == "cancel_held" or k == "finish":
            j = action.args[0]
            t, slots = self.jobs[j]
            status[j] = (self.CANCELLED if k == "cancel_held"
                         else self.DONE)
            held[t] = max(0, held[t] - slots)   # release clamps at 0
        else:
            raise ValueError(f"bad action {action}")
        return (tuple(status), queue, pending, tuple(held))

    def invariants(self):
        def quota_ok(state):
            _s, _q, pending, held = state
            for t in (0, 1):
                r = self._reserved(t, pending, held)
                if r > self.quota:
                    return (f"tenant {t} reserved {r} > quota "
                            f"{self.quota}")
            return None

        def no_overcommit(state):
            held = state[3]
            if min(held) < 0:
                return f"negative held counts {held}"
            if sum(held) > self.pool:
                return (f"held {sum(held)} slots exceed the pool of "
                        f"{self.pool}")
            return None

        def no_leak(state):
            status, queue, pending, held = state
            for t in (0, 1):
                true_held = sum(
                    s for j, (jt, s) in enumerate(self.jobs)
                    if jt == t and status[j] == self.HELD)
                if held[t] != true_held:
                    return (f"tenant {t} accounting drift: held "
                            f"{held[t]} but {true_held} slots are "
                            f"actually admitted")
            if pending != frozenset(queue):
                ghost = sorted(pending - frozenset(queue))
                return (f"reservation charge leaked for job(s) "
                        f"{ghost} no longer queued — quota never "
                        f"recovers")
            return None

        return [("quota-never-exceeded", quota_ok),
                ("no-negative-or-overcommit", no_overcommit),
                ("no-leak", no_leak)]

    def settled(self, state) -> Optional[str]:
        status, queue, _p, _h = state
        if queue:
            return f"queue wedged with job(s) {list(queue)}"
        open_jobs = [j for j, st in enumerate(status)
                     if st in (self.NEW, self.QUEUED, self.HELD)]
        if open_jobs:
            return f"job(s) {open_jobs} never reached a terminal state"
        return None


# --- elastic repartition --------------------------------------------------

#: repartition phases (state[0])
_PRE, _FENCED, _REDIRECTED = range(3)
PHASE_NAMES = ("PRE", "FENCED", "REDIRECTED")


class RepartitionModel(Model):
    """The live re-cut handoff: fence → drain → migrate → redirect.

    One key group per old worker (``workers`` groups; groups are
    symmetric so one per worker preserves the argument). Records flow
    per group as ``ingest`` (old incarnation admits a record onto the
    group's in-flight edge) and ``process`` (the record is applied to
    the group's keyed state). The re-cut:

    - ``fence`` — a checkpoint fence completes; the old incarnation
      stops admitting new records (carries the ``rescale`` chaos hint:
      replaying a model counterexample on the live system re-cuts to
      ``workers + 1``).
    - ``drain(g)`` — a buffered in-flight record of group ``g`` is
      applied by the old incarnation (edge drain before handoff).
    - ``migrate(g)`` — group ``g``'s keyed state moves to the new
      incarnation; legal only once its edge buffer is empty.
    - ``redirect`` — traffic cuts over to the new incarnation; legal
      only once EVERY group has migrated. Whatever the old incarnation
      still buffers dies with it, and an unmigrated group restarts
      empty — the model charges both to ``lost`` so the seeded bugs
      that reach this state are caught by the invariant, not by fiat.

    After redirect the new incarnation ingests/processes fresh traffic;
    bug ``stale-writer`` lets the OLD incarnation re-apply a record of
    a group it already handed off (the duplicate hazard fencing-token
    discipline exists to prevent).

    State: ``(phase, groups)`` with per-group
    ``(produced, applied, buf, migrated, lost, stale)``.

    Invariants:

    - **no-record-lost** — no group ever loses a record across the
      re-cut fence (``lost == 0`` everywhere).
    - **no-record-duplicated** — no group applies more records than
      were produced for it (``applied + buf + lost <= produced``).
    """

    name = "repartition"

    def __init__(self, workers: int = 2, epochs: int = 2,
                 faults: int = 1, bug: Optional[str] = None):
        del faults              # the re-cut itself is the disturbance
        self.groups = max(2, int(workers))
        self.pre_cap = max(1, int(epochs))   # per-group records pre-fence
        self.post_cap = 1                    # per-group records post-cut
        self.bug = _check_bug("repartition", bug)

    def initial_state(self):
        return (_PRE, ((0, 0, 0, False, 0, False),) * self.groups)

    def enabled(self, state) -> List[Action]:
        phase, groups = state
        out: List[Action] = []
        if phase == _PRE:
            for g, (prod, _a, buf, _m, _l, _s) in enumerate(groups):
                if prod < self.pre_cap:
                    out.append(Action("ingest", (g,)))
                if buf > 0:
                    out.append(Action("process", (g,)))
            out.append(Action(
                "fence", (),
                chaos=("rescale", (("targets", (self.groups + 1,)),))))
        elif phase == _FENCED:
            all_migrated = all(m for _p, _a, _b, m, _l, _s in groups)
            for g, (_p, _a, buf, migrated, _l, _s) in enumerate(groups):
                if buf > 0:
                    out.append(Action("drain", (g,)))
                if not migrated and (
                        buf == 0 or self.bug == "migrate-skips-drain"):
                    out.append(Action("migrate", (g,)))
            if all_migrated or self.bug == "redirect-before-migrate":
                out.append(Action("redirect"))
        else:                   # _REDIRECTED
            for g, (prod, applied, buf, _m, _l, stale) in \
                    enumerate(groups):
                if prod < self.pre_cap + self.post_cap:
                    out.append(Action("ingest_new", (g,)))
                if buf > 0:
                    out.append(Action("process_new", (g,)))
                if (self.bug == "stale-writer" and applied > 0
                        and not stale):
                    out.append(Action("stale_write", (g,)))
        return out

    def apply(self, state, action: Action):
        phase, groups = state
        groups = list(groups)
        k = action.kind
        if k == "fence":
            phase = _FENCED
        elif k == "redirect":
            phase = _REDIRECTED
            # The old incarnation's leftovers die with it; an
            # unmigrated group's state never reached the new owner.
            for g, (prod, applied, buf, migrated, lost, stale) in \
                    enumerate(groups):
                if not migrated:
                    lost += applied
                    applied = 0
                lost += buf
                buf = 0
                groups[g] = (prod, applied, buf, True, lost, stale)
        else:
            g = action.args[0]
            prod, applied, buf, migrated, lost, stale = groups[g]
            if k in ("ingest", "ingest_new"):
                prod += 1
                buf += 1
            elif k in ("process", "process_new", "drain"):
                applied += 1
                buf -= 1
            elif k == "migrate":
                migrated = True
            elif k == "stale_write":
                applied += 1    # re-applies a record already handed off
                stale = True
            else:
                raise ValueError(f"bad action {action}")
            groups[g] = (prod, applied, buf, migrated, lost, stale)
        return (phase, tuple(groups))

    def invariants(self):
        def no_loss(state):
            _phase, groups = state
            lost = {g: gl for g, (_p, _a, _b, _m, gl, _s)
                    in enumerate(groups) if gl}
            if lost:
                return (f"group(s) {sorted(lost)} lost {lost} "
                        f"record(s) across the re-cut fence — "
                        f"in-flight or keyed state never reached the "
                        f"new incarnation")
            return None

        def no_dup(state):
            _phase, groups = state
            for g, (prod, applied, buf, _m, lost, _s) in \
                    enumerate(groups):
                if applied + buf + lost > prod:
                    return (f"group {g} accounts for "
                            f"{applied + buf + lost} records but only "
                            f"{prod} were produced — a record was "
                            f"applied twice across the handoff")
            return None

        return [("no-record-lost", no_loss),
                ("no-record-duplicated", no_dup)]

    def canon(self, state):
        """Key groups are symmetric: sort the per-group tuples."""
        phase, groups = state
        return (phase, tuple(sorted(groups)))

    def settled(self, state) -> Optional[str]:
        phase, groups = state
        if phase != _REDIRECTED:
            return (f"re-cut wedged in {PHASE_NAMES[phase]} — the old "
                    f"incarnation never handed off")
        undrained = [g for g, (_p, _a, b, _m, _l, _s)
                     in enumerate(groups) if b]
        if undrained:
            return (f"group(s) {undrained} finished with buffered "
                    f"records never applied")
        return None


# --- autoscale policy ------------------------------------------------------

# decision phases (the controller's observe → fence → decide cycle)
_AS_IDLE = 0       # awaiting this fence's signal snapshot
_AS_SIGNALED = 1   # snapshot taken, awaiting the fence completion
_AS_FENCED = 2     # fence completed+drained, awaiting the decision
_AS_PHASES = ("idle", "signaled", "fenced")


class ScalePolicyModel(Model):
    """The autoscaler's decision protocol (autoscale/policy.py +
    autoscale/controller.py), at abstract load levels.

    Per fence the controller observes one load level (0 low / 1 steady
    / 2 high), completes the fence, and decides: fold the level into
    sustain streaks, then — healthy and out of cooldown — scale up on
    a sustained high, down on a sustained low, bounded to ±1 within
    [min, max] workers. A scale decision is LOGGED as a SCALE
    determinant when made and sits pending until ``execute`` carries
    it out; worker kills land anywhere the controller is idle,
    INCLUDING between decide and execute — the window the execute-time
    health re-check exists for.

    State::

        (phase, fence, level, over, under, cooldown, workers,
         failed, faults_left, pending, last_dec, last_execs, n_dec)

    ``pending`` is ``(dir, fence_decided, logged)`` or None;
    ``last_dec`` records the newest decision as ``(action, over,
    under, cooldown_gate, healthy, room_up, room_down)`` — invariants
    judge each decision the moment it is made, so only the newest need
    be carried; ``last_execs`` keeps the two newest executed actions
    ``(fence, dir, healthy, logged)`` (oscillation is a property of
    consecutive pairs). ``n_dec`` counts decisions for the liveness
    check (every completed fence must have produced exactly one).

    Invariants:

    - **no-thrash** — consecutive executed actions in OPPOSITE
      directions are at least one full cooldown window apart.
    - **decision-logged** — nothing executes without its SCALE
      determinant (the replay-not-re-decide recovery contract).
    - **no-rescale-mid-recovery** — nothing executes while a subtask
      is failed (``rescale_live`` would be re-cutting a cluster that
      is mid-recovery).
    - **monotone-in-sustained-signals** — a healthy, out-of-cooldown
      controller facing a sustained high MUST scale up (and never
      down); facing a sustained low with headroom it MUST scale down.
      Sustained pressure cannot be ignored or inverted.
    """

    name = "scalepolicy"

    def __init__(self, workers: int = 2, epochs: int = 2,
                 faults: int = 1, bug: Optional[str] = None):
        self.min_w = 1
        self.max_w = int(workers) + 1     # headroom for one scale-up
        self.start_w = int(workers)
        self.fences = int(epochs) + 2     # decision rounds
        self.sustain = 1                  # fences a signal must hold
        self.cooldown = 2                 # fences between actions
        self.faults = int(faults)
        self.bug = _check_bug("scalepolicy", bug)

    def initial_state(self):
        return (_AS_IDLE, 0, -1, 0, 0, 0, self.start_w,
                0, self.faults, None, None, (), 0)

    def enabled(self, state) -> List[Action]:
        (phase, fence, _level, _over, _under, _cd, _w,
         failed, faults_left, pending, _ld, _le, _nd) = state
        acts: List[Action] = []
        if phase == _AS_IDLE and pending is None and fence < self.fences:
            # a 4x offered-rate spike is the live analog of sustained
            # high load — the bridge compiles exactly this hint
            acts.append(Action("signal", (2,),
                               chaos=("load-spike",
                                      (("factor", 4.0),
                                       ("duration_s", 2.0)))))
            acts.append(Action("signal", (1,)))
            acts.append(Action("signal", (0,)))
        if phase == _AS_SIGNALED:
            acts.append(Action("fence"))
        if phase == _AS_FENCED:
            acts.append(Action("decide"))
        if (phase == _AS_IDLE and pending is not None
                and (failed == 0 or self.bug == "rescale-mid-recovery")):
            acts.append(Action("execute"))
        # kills and recoveries land only while the controller is idle:
        # the signal→fence→decide triplet is atomic with respect to
        # health — the controller decides on the snapshot it OBSERVED,
        # so a health flip inside the triplet has no decision analog.
        # The decide→execute window stays open (that interleaving is
        # the rescale-mid-recovery hazard).
        if phase == _AS_IDLE and failed == 0 and faults_left > 0:
            acts.append(Action("kill",
                               chaos=("kill", (("targets", (1,)),))))
        if phase == _AS_IDLE and failed > 0:
            acts.append(Action("recover"))
        return acts

    def apply(self, state, action: Action):
        (phase, fence, level, over, under, cd, w,
         failed, faults_left, pending, last_dec, last_execs,
         n_dec) = state
        k = action.kind
        if k == "signal":
            return (_AS_SIGNALED, fence, action.args[0], over, under,
                    cd, w, failed, faults_left, pending, last_dec,
                    last_execs, n_dec)
        if k == "fence":
            return (_AS_FENCED, fence + 1, level, over, under, cd, w,
                    failed, faults_left, pending, last_dec, last_execs,
                    n_dec)
        if k == "decide":
            over2 = over + 1 if level == 2 else 0
            under2 = under + 1 if level == 0 else 0
            cd_gate = max(0, cd - 1)
            healthy = failed == 0
            room_up = w < self.max_w
            room_down = w > self.min_w
            dec = "hold"
            if healthy and (cd_gate == 0 or self.bug == "no-cooldown"):
                if over2 >= self.sustain and room_up:
                    dec = "up"
                elif under2 >= self.sustain and room_down:
                    dec = "down"
            last_dec = (dec, over2, under2, cd_gate, healthy,
                        room_up, room_down)
            cd2, pend = cd_gate, pending
            if dec != "hold":
                logged = self.bug != "unlogged-decision"
                pend = (1 if dec == "up" else -1, fence, logged)
                cd2 = self.cooldown        # restart the cooldown clock
                over2 = under2 = 0         # post-action: a new trend
            return (_AS_IDLE, fence, -1, over2, under2, cd2, w,
                    failed, faults_left, pend, last_dec, last_execs,
                    n_dec + 1)
        if k == "execute":
            direction, _fdec, logged = pending
            entry = (fence, direction, failed == 0, logged)
            return (phase, fence, level, over, under, cd,
                    w + direction, failed, faults_left, None, last_dec,
                    (last_execs + (entry,))[-2:], n_dec)
        if k == "kill":
            return (phase, fence, level, over, under, cd, w, 1,
                    faults_left - 1, pending, last_dec, last_execs,
                    n_dec)
        if k == "recover":
            return (phase, fence, level, over, under, cd, w, 0,
                    faults_left, pending, last_dec, last_execs, n_dec)
        raise ValueError(f"unknown action {action}")

    def invariants(self):
        def no_thrash(state):
            execs = state[11]
            if len(execs) == 2:
                (f1, d1, _h1, _l1), (f2, d2, _h2, _l2) = execs
                if d1 != d2 and f2 - f1 < self.cooldown:
                    return (f"opposite re-cuts {d1:+d} then {d2:+d} "
                            f"only {f2 - f1} fence(s) apart (cooldown "
                            f"window is {self.cooldown})")
            return None

        def logged(state):
            execs = state[11]
            for f, d, _h, lg in execs:
                if not lg:
                    return (f"re-cut {d:+d} at fence {f} executed "
                            f"without its SCALE determinant")
            return None

        def healthy_exec(state):
            execs = state[11]
            for f, d, h, _lg in execs:
                if not h:
                    return (f"re-cut {d:+d} at fence {f} executed "
                            f"over a failed subtask (mid-recovery)")
            return None

        def monotone(state):
            last_dec = state[10]
            if last_dec is None:
                return None
            dec, ov, un, cd_gate, healthy, room_up, room_down = last_dec
            if not healthy or cd_gate > 0:
                return None
            if ov >= self.sustain and dec == "down":
                return (f"sustained high load ({ov} fence(s)) answered "
                        f"with a scale-DOWN")
            if ov >= self.sustain and room_up and dec != "up":
                return (f"sustained high load ({ov} fence(s)), healthy "
                        f"and out of cooldown with headroom, but "
                        f"decision was {dec!r}")
            if (ov < self.sustain and un >= self.sustain and room_down
                    and dec != "down"):
                return (f"sustained low load ({un} fence(s)), healthy "
                        f"and out of cooldown with floor room, but "
                        f"decision was {dec!r}")
            return None

        return [("no-thrash", no_thrash),
                ("decision-logged", logged),
                ("no-rescale-mid-recovery", healthy_exec),
                ("monotone-in-sustained-signals", monotone)]

    def settled(self, state) -> Optional[str]:
        (_phase, fence, _level, _over, _under, _cd, _w,
         _failed, _faults_left, pending, _ld, _le, n_dec) = state
        if fence < self.fences:
            return (f"controller wedged after fence {fence} of "
                    f"{self.fences}")
        if n_dec != self.fences:
            return (f"{self.fences} fence(s) completed but only "
                    f"{n_dec} decision(s) made")
        if pending is not None:
            return "a logged scale decision was never executed"
        return None


#: registry: CLI/runner model names -> constructor
MODELS = {
    "checkpoint": CheckpointModel,
    "recovery": RecoveryModel,
    "lease": LeaseModel,
    "admission": AdmissionModel,
    "repartition": RepartitionModel,
    "scalepolicy": ScalePolicyModel,
}
