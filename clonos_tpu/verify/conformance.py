"""Conformance: replay model traces against the real components.

The models in verify/models.py claim to mirror the real protocols; this
module is what makes that claim checkable instead of aspirational. For
each component it takes model-generated traces (explorer.traces — the
deepest terminal paths, i.e. full protocol rounds), drives the REAL
implementation through the same steps, and asserts the implementation's
observable transitions (the ``transition_observers`` streams grown for
exactly this purpose) match the model's expected transitions bit for
bit. A divergence means the model and the code have drifted — the
checker's proofs no longer cover the shipping protocol — and fails CI.

The adapters drive the components exactly the way their real drivers
do (the cluster runner's ack-at-the-fence discipline, the dispatcher's
free-slot accounting, the soak driver's ``discard_pending_through``
sweep after a completion), so a conformance trace is a miniature of a
real run, minus the data plane.

Real components import jax; all component imports are lazy so the
model checker itself (verify/explorer.py, verify/models.py) stays
importable anywhere.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Tuple

from clonos_tpu.verify.explorer import Action, traces
from clonos_tpu.verify.models import (FSM_NAMES, PHASE_NAMES,
                                      AdmissionModel, CheckpointModel,
                                      LeaseModel, RecoveryModel,
                                      RepartitionModel,
                                      ScalePolicyModel)


@dataclasses.dataclass
class Divergence:
    """One step where the implementation's observable transitions did
    not match the model's."""

    component: str
    trace: int                   # trace index within the run
    step: int                    # action index within the trace
    action: str                  # Action.label()
    expected: List
    observed: List

    def to_dict(self) -> dict:
        return {"component": self.component, "trace": self.trace,
                "step": self.step, "action": self.action,
                "expected": [list(e) for e in self.expected],
                "observed": [list(o) for o in self.observed]}


@dataclasses.dataclass
class ConformanceReport:
    component: str
    traces: int
    steps: int
    divergences: List[Divergence]

    @property
    def ok(self) -> bool:
        return not self.divergences

    def to_dict(self) -> dict:
        return {"component": self.component, "traces": self.traces,
                "steps": self.steps, "ok": self.ok,
                "divergences": [d.to_dict() for d in self.divergences]}


def _replay(component: str, model, model_traces: List[List[Action]],
            make_adapter: Callable) -> ConformanceReport:
    """Drive one fresh adapter per trace; compare per-step expected vs
    observed transition lists, then the adapter's state projection.
    The first divergence aborts the trace (everything after it would
    diverge for the same reason)."""
    divergences: List[Divergence] = []
    steps = 0
    for ti, trace in enumerate(model_traces):
        adapter = make_adapter()
        state = model.initial_state()
        for si, action in enumerate(trace):
            expected = adapter.expected(state, action)
            observed = adapter.apply(state, action)
            state = model.apply(state, action)
            steps += 1
            if observed != expected:
                divergences.append(Divergence(
                    component=component, trace=ti, step=si,
                    action=action.label(), expected=expected,
                    observed=observed))
                break
            drift = adapter.projection_drift(state)
            if drift is not None:
                divergences.append(Divergence(
                    component=component, trace=ti, step=si,
                    action=action.label(),
                    expected=[("projection", drift[0])],
                    observed=[("projection", drift[1])]))
                break
    return ConformanceReport(component=component,
                             traces=len(model_traces), steps=steps,
                             divergences=divergences)


# --- checkpoint -----------------------------------------------------------

def _ckpt_expected(model: CheckpointModel, state, action: Action):
    """The observation stream CheckpointCoordinator must emit for this
    model step (plus the driver's post-completion discard sweep)."""
    cids = state[0]
    k, args = action.kind, action.args

    def discards_below(cid):
        return [("discard", i + 1) for i, c in enumerate(cids)
                if c[0] == "pending" and i + 1 < cid]

    if k == "trigger":
        return [("trigger", args[0])]
    if k in ("write", "ack"):
        cid = args[0]
        _t, missing, written = cids[cid - 1]
        out = []
        if k == "ack":
            missing = missing - {args[1]}
            written_after = written
            out.append(("ack", cid, args[1]))
        else:
            written_after = True
        if written_after and not missing:
            out.append(("complete", cid))
            out.extend(discards_below(cid))
        return out
    if k == "discard":
        return [("discard", args[0])]
    if k == "kill":
        return []                # the coordinator sees nothing yet
    if k == "detect":
        w = args[0]
        return [("ignore", i + 1) for i, c in enumerate(cids)
                if c[0] == "pending" and w in c[1]]
    raise ValueError(f"unmapped checkpoint action {action}")


def _ckpt_admissible(model: CheckpointModel):
    """The real driver abandons superseded fences with ONE
    ``discard_pending_through`` sweep; a model trace discarding cid
    while an older fence is still pending has no single-call impl
    analog — skip it (the sweep variant is covered by completion)."""
    def ok(trace: List[Action]) -> bool:
        state = model.initial_state()
        for a in trace:
            if a.kind == "discard":
                cid = a.args[0]
                if any(c[0] == "pending" and i + 1 < cid
                       for i, c in enumerate(state[0])):
                    return False
            state = model.apply(state, a)
        return True
    return ok


def conform_checkpoint(n_traces: int = 3, workers: int = 2,
                       epochs: int = 2, faults: int = 1,
                       depth: int = 48) -> ConformanceReport:
    from clonos_tpu.runtime.checkpoint import (CheckpointCoordinator,
                                               InMemoryCheckpointStorage)

    class GatedStorage(InMemoryCheckpointStorage):
        """Holds written snapshots non-durable until the model's
        ``write`` step lands — the model's handle on the async-write
        race (``_maybe_complete`` retries through the read gate)."""

        def __init__(self):
            super().__init__()
            self.durable: set = set()

        def read(self, checkpoint_id: int):
            if checkpoint_id not in self.durable:
                raise KeyError(checkpoint_id)
            return super().read(checkpoint_id)

    model = CheckpointModel(workers=workers, epochs=epochs,
                            faults=faults)

    class Adapter:
        def __init__(self):
            self.storage = GatedStorage()
            self.coord = CheckpointCoordinator(
                self.storage, num_subtasks=workers, max_retained=8)
            self.obs: List[Tuple] = []
            self.coord.transition_observers.append(self._on)

        def _on(self, kind, **fields):
            if kind == "ack":
                self.obs.append((kind, fields["cid"],
                                 fields["subtask"]))
            else:
                self.obs.append((kind, fields["cid"]))

        def expected(self, state, action):
            return _ckpt_expected(model, state, action)

        def apply(self, state, action):
            self.obs = []
            k, args = action.kind, action.args
            if k == "trigger":
                self.coord.trigger(args[0], {"x": args[0]},
                                   async_write=False, owned=True)
            elif k == "write":
                cid = args[0]
                self.storage.durable.add(cid)
                missing = state[0][cid - 1][1]
                # re-run _maybe_complete through the read gate without
                # acking anyone: everyone still missing stays excepted
                self.coord.ack_all(cid,
                                   except_subtasks=tuple(sorted(missing)))
                self._sweep_if_completed(cid)
            elif k == "ack":
                self.coord.ack(args[0], args[1])
                self._sweep_if_completed(args[0])
            elif k == "discard":
                self.coord.discard_pending_through(args[0])
            elif k == "kill":
                pass             # death is invisible until detection
            elif k == "detect":
                self.coord.ignore_unacked_for({args[0]})
            else:
                raise ValueError(f"unmapped checkpoint action {action}")
            return self.obs

        def _sweep_if_completed(self, cid):
            # The driver's fence discipline: a completion supersedes
            # every older pending fence (soak driver's pre-kill sweep).
            if ("complete", cid) in self.obs:
                self.coord.discard_pending_through(cid - 1)

        def projection_drift(self, state):
            want = sorted(i + 1 for i, c in enumerate(state[0])
                          if c == ("complete",))
            got = self.storage.completed_ids()
            if want != got:
                return (f"completed={want}", f"completed={got}")
            return None

    model_traces = traces(model, n_traces, depth=depth,
                          admissible=_ckpt_admissible(model))
    return _replay("checkpoint", model, model_traces, Adapter)


# --- recovery -------------------------------------------------------------

def conform_recovery(n_traces: int = 3, workers: int = 2,
                     depth: int = 48) -> ConformanceReport:
    import types

    import numpy as np

    from clonos_tpu.causal.recovery import RecoveryManager

    model = RecoveryModel(workers=workers)
    peers = model.peers

    class Adapter:
        def __init__(self):
            self.mgr = RecoveryManager(
                vertex_id=0, subtask=0, flat_subtask=0,
                replayer=types.SimpleNamespace())
            self.obs: List[Tuple] = []
            self.mgr.transition_observers.append(
                lambda kind, **f: self.obs.append(("goto", kind)))

        def expected(self, state, action):
            pre = state[0]
            post = model.apply(state, action)[0]
            return [("goto", FSM_NAMES[f])
                    for f in range(pre + 1, post + 1)]

        def apply(self, state, action):
            self.obs = []
            k = action.kind
            if k == "start":
                self.mgr.notify_start_recovery(
                    in_edges=range(peers), out_edges=range(peers))
            elif k == "restore_done":
                self.mgr.notify_state_restoration_complete()
            elif k == "chan_in":
                self.mgr.notify_new_input_channel(action.args[0])
            elif k == "chan_out":
                self.mgr.notify_new_output_channel(action.args[0])
            elif k == "expect":
                self.mgr.expect_determinant_responses(action.args[0])
            elif k == "response":
                self.mgr.notify_determinant_response(
                    np.zeros((0, 8), dtype=np.int64), 0)
            elif k == "replay":
                self.mgr.run_replay(
                    types.SimpleNamespace(verify_outputs=False))
            else:
                raise ValueError(f"unmapped recovery action {action}")
            return self.obs

        def projection_drift(self, state):
            want = FSM_NAMES[state[0]]
            got = self.mgr.state.name
            if want != got:
                return (want, got)
            return None

    # run_replay calls replayer.replay(...); stub it per adapter
    def make():
        a = Adapter()
        a.mgr.replayer = types.SimpleNamespace(
            replay=lambda plan, defer_sync=False:
                types.SimpleNamespace(deferred=True))
        return a

    model_traces = traces(model, n_traces, depth=depth)
    return _replay("recovery", model, model_traces, make)


# --- leader lease ---------------------------------------------------------

def conform_lease(workdir: str, n_traces: int = 3, workers: int = 2,
                  faults: int = 1, depth: int = 48) -> ConformanceReport:
    from clonos_tpu.runtime.leader import FileLeaderElection

    model = LeaseModel(workers=workers, faults=faults)
    ttl = 50.0
    counter = [0]

    class Adapter:
        def __init__(self):
            counter[0] += 1
            path = os.path.join(workdir, f"lease{counter[0]}")
            self.clock = [1000.0]
            self.obs: List[Tuple] = []
            self.elections = []
            for c in range(model.contenders):
                e = FileLeaderElection(path, f"c{c}", lease_ttl_s=ttl,
                                       clock=lambda: self.clock[0])
                e.transition_observers.append(
                    lambda kind, c=c, **f:
                        self.obs.append((kind, c, f.get("epoch"))))
                self.elections.append(e)
            self.observer = FileLeaderElection(path, "observer",
                                              lease_ttl_s=ttl,
                                              clock=lambda:
                                              self.clock[0])

        def expected(self, state, action):
            claims, believed, _f = state
            k, args = action.kind, action.args
            if k == "acquire":
                return [("claim", args[0], len(claims) + 1)]
            if k == "expire":
                return []
            if k == "renew":
                c = args[0]
                if believed[c] == len(claims):
                    return [("renew", c, believed[c])]
                return [("deposed", c, believed[c])]
            raise ValueError(f"unmapped lease action {action}")

        def apply(self, state, action):
            self.obs = []
            k, args = action.kind, action.args
            if k == "acquire":
                self.elections[args[0]].try_acquire()
            elif k == "expire":
                self.clock[0] += ttl + 1.0
            elif k == "renew":
                self.elections[args[0]].renew()
            else:
                raise ValueError(f"unmapped lease action {action}")
            return self.obs

        def projection_drift(self, state):
            claims, believed, _f = state
            for c in range(model.contenders):
                if self.elections[c].epoch != believed[c]:
                    return (f"c{c} epoch={believed[c]}",
                            f"c{c} epoch={self.elections[c].epoch}")
            # receiver-side fencing agrees with the model's acceptance
            for e in range(1, len(claims) + 1):
                want = model._accepted(e, claims)
                got = self.observer.fencing_valid(e)
                if want != got:
                    return (f"fencing_valid({e})={want}",
                            f"fencing_valid({e})={got}")
            return None

    model_traces = traces(model, n_traces, depth=depth)
    return _replay("lease", model, model_traces, Adapter)


# --- dispatcher admission -------------------------------------------------

def conform_admission(n_traces: int = 3, workers: int = 2,
                      depth: int = 48) -> ConformanceReport:
    from clonos_tpu.runtime.dispatcher import (AdmissionController,
                                               QuotaExceededError)

    model = AdmissionModel(workers=workers)

    class Adapter:
        def __init__(self):
            self.ac = AdmissionController(
                quotas={"t0": model.quota, "t1": model.quota})
            self.obs: List[Tuple] = []
            self.ac.transition_observers.append(self._on)

        def _on(self, kind, **fields):
            if kind == "release":
                self.obs.append((kind, fields["tenant"],
                                 fields["slots"]))
            else:
                self.obs.append((kind, fields["job_id"]))

        def _free(self):
            return model.pool - self.ac.total_held()

        def expected(self, state, action):
            status, queue, pending, held = state
            k, args = action.kind, action.args
            if k == "submit":
                j = args[0]
                post = model.apply(state, action)[0][j]
                kind = {model.REJECTED: "reject",
                        model.QUEUED: "queue",
                        model.HELD: "admit"}[post]
                return [(kind, f"j{j}")]
            if k == "admit":
                post_q = model.apply(state, action)[1]
                drained = [j for j in queue if j not in post_q]
                return [("admit", f"j{j}") for j in drained]
            if k == "cancel_queued":
                return [("cancel", f"j{args[0]}")]
            if k in ("cancel_held", "finish"):
                t, slots = model.jobs[args[0]]
                return [("release", f"t{t}", slots)]
            raise ValueError(f"unmapped admission action {action}")

        def apply(self, state, action):
            self.obs = []
            k, args = action.kind, action.args
            if k == "submit":
                j = args[0]
                t, slots = model.jobs[j]
                try:
                    self.ac.request(f"j{j}", f"t{t}", slots,
                                    self._free())
                except QuotaExceededError:
                    pass
            elif k == "admit":
                self.ac.admit_queued(self._free())
            elif k == "cancel_queued":
                self.ac.cancel_queued(f"j{args[0]}")
            elif k in ("cancel_held", "finish"):
                t, slots = model.jobs[args[0]]
                self.ac.release(f"t{t}", slots)
            else:
                raise ValueError(f"unmapped admission action {action}")
            return self.obs

        def projection_drift(self, state):
            _s, queue, _p, held = state
            for t in (0, 1):
                if self.ac.held(f"t{t}") != held[t]:
                    return (f"held[t{t}]={held[t]}",
                            f"held[t{t}]={self.ac.held(f't{t}')}")
                want_r = model._reserved(t, state[2], held)
                if self.ac.reserved(f"t{t}") != want_r:
                    return (f"reserved[t{t}]={want_r}",
                            f"reserved[t{t}]="
                            f"{self.ac.reserved(f't{t}')}")
            want_q = [f"j{j}" for j in queue]
            if self.ac.queued() != want_q:
                return (f"queue={want_q}",
                        f"queue={self.ac.queued()}")
            return None

    model_traces = traces(model, n_traces, depth=depth)
    return _replay("admission", model, model_traces, Adapter)


# --- elastic repartition --------------------------------------------------

def conform_repartition(n_traces: int = 3, workers: int = 2,
                        epochs: int = 2,
                        depth: int = 48) -> ConformanceReport:
    """Drive the real :class:`RescaleCoordinator` — the control plane
    ``ClusterRunner.rescale_live`` walks through a live re-cut —
    through model traces. Pre-fence ingest/process are data-plane
    bookkeeping (``note_inflight``; nothing observable), as is the new
    incarnation's post-redirect traffic; fence/drain/migrate/redirect
    must emit exactly the model's transition per step."""
    from clonos_tpu.runtime.scheduler import RescaleCoordinator

    model = RepartitionModel(workers=workers, epochs=epochs)

    class Adapter:
        def __init__(self):
            self.coord = RescaleCoordinator(model.groups)
            self.obs: List[Tuple] = []
            self.coord.transition_observers.append(self._on)

        def _on(self, kind, **fields):
            if kind in ("drain", "migrate"):
                self.obs.append((kind, fields["group"]))
            else:
                self.obs.append((kind,))

        def expected(self, state, action):
            k, args = action.kind, action.args
            if k in ("ingest", "process", "ingest_new", "process_new"):
                return []
            if k == "fence":
                return [("fence",)]
            if k in ("drain", "migrate"):
                return [(k, args[0])]
            if k == "redirect":
                return [("redirect",)]
            raise ValueError(f"unmapped repartition action {action}")

        def apply(self, state, action):
            self.obs = []
            k, args = action.kind, action.args
            if k == "ingest":
                self.coord.note_inflight(args[0], 1)
            elif k == "process":
                self.coord.note_inflight(args[0], -1)
            elif k == "fence":
                self.coord.fence(1)
            elif k == "drain":
                self.coord.drain(args[0])
            elif k == "migrate":
                self.coord.migrate(args[0])
            elif k == "redirect":
                self.coord.redirect()
            elif k in ("ingest_new", "process_new"):
                pass        # the NEW incarnation's traffic
            else:
                raise ValueError(f"unmapped repartition action {action}")
            return self.obs

        def projection_drift(self, state):
            phase, groups = state
            want_phase = PHASE_NAMES[phase]
            # model PRE/FENCED/REDIRECTED == coordinator phase names
            if self.coord.phase != want_phase:
                return (f"phase={want_phase}",
                        f"phase={self.coord.phase}")
            if phase == 2:      # redirected: new incarnation owns state
                return None
            for g, (_p, _a, buf, migrated, _l, _s) in enumerate(groups):
                if self.coord.inflight[g] != buf:
                    return (f"inflight[{g}]={buf}",
                            f"inflight[{g}]={self.coord.inflight[g]}")
                if self.coord.migrated[g] != migrated:
                    return (f"migrated[{g}]={migrated}",
                            f"migrated[{g}]={self.coord.migrated[g]}")
            return None

    model_traces = traces(model, n_traces, depth=depth)
    return _replay("repartition", model, model_traces, Adapter)


def conform_scalepolicy(n_traces: int = 3, workers: int = 2,
                        epochs: int = 2, faults: int = 1,
                        depth: int = 40) -> ConformanceReport:
    """Replay ScalePolicyModel traces through the REAL
    ``AutoscaleController`` (autoscale/controller.py) over the real
    ``ScalePolicy``, configured to the model's bounds (sustain 1,
    cooldown 2, one step of worker headroom, replica arms pinned
    shut). Model load levels become concrete snapshots via
    ``signals_for_level``; the controller's transition observers must
    emit exactly the model's observe/fence/decide/log/execute stream,
    and its PolicyState/decision-log projection must track the model
    state step for step."""
    from clonos_tpu.autoscale import (AutoscaleController, PolicyConfig,
                                      ScalePolicy, signals_for_level)

    model = ScalePolicyModel(workers=workers, epochs=epochs,
                             faults=faults)
    _LOAD = {0: 0.4, 1: 1.0, 2: 1.6}

    class Adapter:
        def __init__(self):
            cfg = PolicyConfig(sustain_fences=model.sustain,
                               cooldown_fences=model.cooldown,
                               min_workers=model.min_w,
                               max_workers=model.max_w,
                               min_replicas=1, max_replicas=1)
            self.workers = model.start_w
            self.failed = 0
            self.ac = AutoscaleController(
                ScalePolicy(cfg),
                execute_workers=self._exec_workers,
                healthy=lambda: self.failed == 0)
            self.ac.transition_observers.append(self._on)
            self.obs: List[Tuple] = []

        def _exec_workers(self, target):
            self.workers = target

        def _on(self, kind, **fields):
            if kind == "observe":
                self.obs.append((kind, fields["load"]))
            elif kind == "fence":
                self.obs.append((kind, fields["epoch"]))
            elif kind == "decide":
                self.obs.append((kind, fields["action"]))
            elif kind == "log":
                self.obs.append((kind, fields["seq"]))
            elif kind == "execute":
                self.obs.append((kind, fields["action"],
                                 fields["target"]))
            else:
                self.obs.append((kind,))

        def _model_decision(self, state):
            """The model's decide outcome, recomputed from its
            pre-decide state (mirrors ScalePolicyModel.apply)."""
            (_ph, _fence, level, over, under, cd, w,
             failed, _fl, _pend, _ld, _le, n_dec) = state
            over2 = over + 1 if level == 2 else 0
            under2 = under + 1 if level == 0 else 0
            cd_gate = max(0, cd - 1)
            dec = "hold"
            if failed == 0 and cd_gate == 0:
                if over2 >= model.sustain and w < model.max_w:
                    dec = "up"
                elif under2 >= model.sustain and w > model.min_w:
                    dec = "down"
            action = "hold" if dec == "hold" else "scale-workers"
            return dec, action, n_dec + 1

        def expected(self, state, action: Action):
            k = action.kind
            if k == "signal":
                return [("observe", _LOAD[action.args[0]])]
            if k == "fence":
                return [("fence", state[1] + 1)]
            if k == "decide":
                _dec, act, seq = self._model_decision(state)
                return [("decide", act), ("log", seq)]
            if k == "execute":
                direction, _fdec, _logged = state[9]
                return [("execute", "scale-workers",
                         state[6] + direction)]
            if k in ("kill", "recover"):
                return []        # the controller sees nothing yet
            raise ValueError(f"unmapped scalepolicy action {action}")

        def apply(self, state, action: Action):
            self.obs = []
            k = action.kind
            if k == "signal":
                # the snapshot carries the fence it will decide for
                # and the health the controller observed
                self.ac.observe(signals_for_level(
                    action.args[0], epoch=state[1],
                    workers=self.workers,
                    failed_subtasks=self.failed))
            elif k == "fence":
                self.ac.note_fence(state[1] + 1)
            elif k == "decide":
                self.ac.decide()
            elif k == "execute":
                self.ac.execute()
            elif k == "kill":
                self.failed = 1
            elif k == "recover":
                self.failed = 0
            return list(self.obs)

        def projection_drift(self, state):
            (_ph, _fence, _level, over, under, cd, w,
             _failed, _fl, pend, _ld, _le, n_dec) = state
            st = self.ac.state
            if st.cooldown != cd:
                return (f"cooldown={cd}", f"cooldown={st.cooldown}")
            if (st.over_streak, st.under_streak) != (over, under):
                return (f"streaks=({over},{under})",
                        f"streaks=({st.over_streak},"
                        f"{st.under_streak})")
            if st.seq != n_dec or len(self.ac.log) != n_dec:
                return (f"decisions={n_dec}",
                        f"seq={st.seq} log={len(self.ac.log)}")
            if self.workers != w:
                return (f"workers={w}", f"workers={self.workers}")
            if (self.ac.pending is not None) != (pend is not None):
                return (f"pending={pend is not None}",
                        f"pending={self.ac.pending is not None}")
            return None

    model_traces = traces(model, n_traces, depth=depth)
    return _replay("scalepolicy", model, model_traces, Adapter)


def run_conformance(components: Optional[List[str]] = None,
                    n_traces: int = 3, workers: int = 2,
                    epochs: int = 2, faults: int = 1,
                    workdir: Optional[str] = None
                    ) -> Dict[str, ConformanceReport]:
    """Conformance for the requested components (default: all six).
    ``workdir`` hosts the lease claim files (a temp dir is created
    when omitted)."""
    import tempfile
    components = list(components or ("checkpoint", "recovery", "lease",
                                     "admission", "repartition",
                                     "scalepolicy"))
    out: Dict[str, ConformanceReport] = {}
    for c in components:
        if c == "checkpoint":
            out[c] = conform_checkpoint(n_traces, workers=workers,
                                        epochs=epochs, faults=faults)
        elif c == "recovery":
            out[c] = conform_recovery(n_traces, workers=workers)
        elif c == "lease":
            wd = workdir or tempfile.mkdtemp(prefix="clonos-verify-")
            out[c] = conform_lease(wd, n_traces, workers=workers,
                                   faults=faults)
        elif c == "admission":
            out[c] = conform_admission(n_traces, workers=workers)
        elif c == "repartition":
            out[c] = conform_repartition(n_traces, workers=workers,
                                         epochs=epochs)
        elif c == "scalepolicy":
            out[c] = conform_scalepolicy(n_traces, workers=workers,
                                         epochs=epochs, faults=faults)
        else:
            raise ValueError(f"unknown component {c!r}")
    return out
