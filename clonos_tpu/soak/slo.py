"""Windowed SLO engine for open-loop soak runs.

Latency discipline: every sample is measured from *intended*-send time
— the instant the token bucket says the chunk was due — not from when
the driver actually got around to sending it. Under a fault the driver
stalls, the backlog grows, and actual-send timestamps would hide the
stall entirely (the classic coordinated-omission blind spot). The
corrected number is what a non-cooperating client would have seen.

:class:`SLOTracker` rolls fixed-width windows over the soak clock and
evaluates the :class:`SLOSpec` per window; each violation is emitted as
a ``soak.slo.breach`` trace instant under the run's trace id so the
flight recorder can correlate breach → injected fault.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def quantile(samples: Sequence[float], q: float) -> float:
    """Empirical quantile (0 for an empty sample set)."""
    if not len(samples):
        return 0.0
    return float(np.quantile(np.asarray(samples, dtype=np.float64), q))


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """Per-window service-level objectives. ``None`` disables a bound.

    ``exactly_once`` is not a latency bound: it asserts that the audit
    re-validation after every injected fault found zero ledger
    divergences (checked once, over the whole run).
    """

    max_p99_ms: Optional[float] = None
    min_throughput: Optional[float] = None   # records/sec per window
    max_recovery_ms: Optional[float] = None
    exactly_once: bool = True

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class Window:
    """One SLO evaluation window: corrected + actual latency samples,
    record/chunk counts, recoveries, and the breaches found at close."""

    def __init__(self, index: int, start_s: float, width_s: float):
        self.index = index
        self.start_s = start_s
        self.width_s = width_s
        self.corrected_ms: List[float] = []
        self.actual_ms: List[float] = []
        self.records = 0
        self.chunks = 0
        self.recoveries_ms: List[float] = []
        self.faults: List[str] = []
        self.breaches: List[str] = []

    def observe(self, corrected_ms: float, actual_ms: float,
                records: int) -> None:
        self.corrected_ms.append(corrected_ms)
        self.actual_ms.append(actual_ms)
        self.records += records
        self.chunks += 1

    def stats(self) -> Dict[str, Any]:
        thr = self.records / self.width_s if self.width_s > 0 else 0.0
        return {
            "window": self.index,
            "start_s": round(self.start_s, 3),
            "chunks": self.chunks,
            "records": self.records,
            "throughput": round(thr, 1),
            "p50_ms": round(quantile(self.corrected_ms, 0.50), 3),
            "p99_ms": round(quantile(self.corrected_ms, 0.99), 3),
            "p999_ms": round(quantile(self.corrected_ms, 0.999), 3),
            "actual_p99_ms": round(quantile(self.actual_ms, 0.99), 3),
            "recoveries_ms": [round(r, 1) for r in self.recoveries_ms],
            "faults": list(self.faults),
            "breaches": list(self.breaches),
        }

    def evaluate(self, spec: SLOSpec) -> List[str]:
        """Close the window against the spec; returns breach strings."""
        breaches = []
        p99 = quantile(self.corrected_ms, 0.99)
        if spec.max_p99_ms is not None and p99 > spec.max_p99_ms:
            breaches.append(f"p99 {p99:.1f}ms > {spec.max_p99_ms:g}ms")
        if spec.min_throughput is not None and self.chunks:
            thr = self.records / self.width_s
            if thr < spec.min_throughput:
                breaches.append(
                    f"throughput {thr:.0f}/s < {spec.min_throughput:g}/s")
        if spec.max_recovery_ms is not None:
            for r in self.recoveries_ms:
                if r > spec.max_recovery_ms:
                    breaches.append(
                        f"recovery {r:.0f}ms > {spec.max_recovery_ms:g}ms")
        self.breaches = breaches
        return breaches


class SLOTracker:
    """Rolls :class:`Window` objects over the soak clock and evaluates
    each against the spec as it closes.

    All times are seconds on the *soak clock* (0 = start of the paced
    phase), supplied by the driver — the tracker never reads wallclock
    itself, which keeps it replayable and lint-clean.
    """

    def __init__(self, spec: SLOSpec, window_s: float = 5.0,
                 tracer=None):
        self.spec = spec
        self.window_s = window_s
        self.tracer = tracer
        self.closed: List[Window] = []
        self.current = Window(0, 0.0, window_s)

    def _roll_to(self, now_s: float) -> None:
        while now_s >= self.current.start_s + self.window_s:
            self._close(self.current)
            nxt = self.current.index + 1
            self.current = Window(nxt, nxt * self.window_s,
                                  self.window_s)

    def _close(self, win: Window) -> None:
        breaches = win.evaluate(self.spec)
        if breaches:
            if self.tracer is not None:
                for b in breaches:
                    self.tracer.event("soak.slo.breach",
                                      window=win.index, breach=b)
            from clonos_tpu.obs import get_timeline
            tl = get_timeline()
            if tl.enabled:
                for b in breaches:
                    tl.record("slo.breach", window=win.index, breach=b)
            # One incident signal per breached window (not per breach
            # line — the window is the fault, the lines are symptoms);
            # no-op when the incident plane is disabled.
            from clonos_tpu.obs.incident import get_incidents
            get_incidents().signal("slo.breach", window=win.index,
                                   breaches=sorted(breaches))
        self.closed.append(win)

    def observe(self, now_s: float, corrected_ms: float,
                actual_ms: float, records: int) -> None:
        self._roll_to(now_s)
        self.current.observe(corrected_ms, actual_ms, records)

    def observe_recovery(self, now_s: float, recovery_ms: float) -> None:
        self._roll_to(now_s)
        self.current.recoveries_ms.append(recovery_ms)

    def observe_fault(self, now_s: float, kind: str) -> None:
        self._roll_to(now_s)
        self.current.faults.append(kind)

    def finish(self) -> List[Window]:
        """Close the in-progress window and return all windows."""
        if self.current.chunks or self.current.recoveries_ms \
                or self.current.faults:
            self._close(self.current)
        return self.closed

    # -- aggregates over all closed windows ---------------------------

    def all_corrected_ms(self) -> List[float]:
        return [s for w in self.closed for s in w.corrected_ms]

    def all_actual_ms(self) -> List[float]:
        return [s for w in self.closed for s in w.actual_ms]

    def breached_windows(self) -> List[Window]:
        return [w for w in self.closed if w.breaches]

    def worst_window(self) -> Optional[Window]:
        if not self.closed:
            return None
        return max(self.closed,
                   key=lambda w: quantile(w.corrected_ms, 0.99))


def corrected_closed_loop(samples: Sequence[Tuple[int, float]],
                          fences: Sequence[Tuple[int, float]],
                          steps_per_epoch: int,
                          records_per_step: int,
                          rate: Optional[float] = None,
                          ) -> Dict[str, float]:
    """Coordinated-omission correction for the closed-loop bench.

    The bench's latency markers measure record-tagged dwell *inside*
    the pipeline, but the bench pushes epochs back-to-back: when one
    fence runs long, every later record is also sent late, and the
    marker number never sees that queueing delay. Reconstruct it: from
    the fence walls ``(global_step, monotonic_s)`` derive the sustained
    step rate (or take ``rate`` in records/sec), lay down the intended
    wall for every fence on that fixed schedule, and charge each marker
    sample the queueing delay ``max(0, actual - intended)`` of the
    fence that closed its epoch.

    ``samples`` are ``(global_step, marker_ms)`` pairs from
    ``LatencyMarkers``; returns corrected p50/p99 plus the schedule
    parameters used, so the JSON output can show both numbers side by
    side.
    """
    if not samples or len(fences) < 2:
        return {"p50_ms": 0.0, "p99_ms": 0.0, "max_queue_ms": 0.0}
    fences = sorted(fences)
    steps0, t0 = fences[0]
    if rate is None:
        span_steps = fences[-1][0] - steps0
        span_s = fences[-1][1] - t0
        per_step_s = span_s / max(span_steps, 1)
    else:
        per_step_s = records_per_step / rate
    # queueing delay of each fence vs its intended wall on the fixed
    # schedule anchored at the first fence
    queue_ms = {}
    for step, t in fences:
        intended = t0 + (step - steps0) * per_step_s
        queue_ms[step] = max(0.0, (t - intended) * 1e3)
    fence_steps = sorted(queue_ms)
    corrected = []
    for step, marker_ms in samples:
        # the fence that closed this sample's epoch: first fence at or
        # after the sample's step
        idx = int(np.searchsorted(fence_steps, step))
        if idx >= len(fence_steps):
            idx = len(fence_steps) - 1
        corrected.append(marker_ms + queue_ms[fence_steps[idx]])
    return {
        "p50_ms": round(quantile(corrected, 0.50), 3),
        "p99_ms": round(quantile(corrected, 0.99), 3),
        "max_queue_ms": round(max(queue_ms.values()), 3),
        "per_step_us": round(per_step_s * 1e6, 3),
    }
