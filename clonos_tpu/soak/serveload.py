"""Read side of a mixed soak load: routed reads with their own SLO
windows.

The soak driver pumps a :class:`ServeLoad` once per ingest chunk, so
every read burst contends with live ingestion on the same clock — the
read p50/p99 reported here is measured UNDER write load, not against an
idle cluster (the honest-measurement half of the read-path tentpole).
Each pump samples the tier's staleness too, so a ``replica-kill``
mid-run is visible as the spike-then-recovery the acceptance criteria
demand, and the error counter is the zero-client-errors witness: the
router must degrade (re-route to the owner), never throw.
"""

from __future__ import annotations

import time as _time
from typing import Any, Dict, List, Optional

import numpy as np

from .slo import quantile


class ServeLoad:
    """Seeded random point-read load against a ServeTier's router."""

    def __init__(self, tier, vertex_id: int, num_keys: int,
                 reads_per_pump: int = 32, slo_ms: float = 250.0,
                 window_s: float = 5.0, seed: int = 7,
                 state: str = "acc"):
        self.tier = tier
        self.router = tier.router
        self.vertex_id = int(vertex_id)
        self.num_keys = int(num_keys)
        self.reads_per_pump = int(reads_per_pump)
        self.slo_ms = float(slo_ms)
        self.window_s = float(window_s)
        self.state = state
        self.rng = np.random.RandomState(seed)
        self.reads = 0
        self.pumps = 0
        #: client-visible failures — the replica-kill acceptance bar is
        #: that this stays 0 (degradation is reroutes, not errors)
        self.errors = 0
        self.last_error: Optional[str] = None
        self.latencies_ms: List[float] = []
        self.staleness_samples: List[int] = []
        self.staleness_peak = 0
        self.staleness_final = 0
        self.windows: List[Dict[str, Any]] = []
        self._win_start = 0.0
        self._win_lat: List[float] = []
        self._win_reads = 0
        self._win_reroutes0 = 0
        self._win_stal_max = 0
        self._t0: Optional[float] = None

    def pump(self, now_s: float, final: bool = False) -> None:
        """One read burst on the soak clock: a batched routed read of
        ``reads_per_pump`` random keys. ``final`` closes the last
        window and records the post-drain staleness (the recovery
        witness after a replica-kill)."""
        if self._t0 is None:
            self._t0 = now_s
            self._win_start = now_s
            self._win_reroutes0 = self.router.reroutes
        keys = self.rng.randint(0, self.num_keys,
                                size=self.reads_per_pump)
        t0 = _time.monotonic()
        try:
            out = self.router.query_batch(self.vertex_id, keys,
                                          state=self.state)
            stal = max((int(s) for s in out["staleness_epochs"]),
                       default=0)
        except Exception as e:      # noqa: BLE001 — ANY throw is a fail
            self.errors += 1
            self.last_error = repr(e)
            stal = 0
        lat_ms = (_time.monotonic() - t0) * 1e3
        self.pumps += 1
        self.reads += self.reads_per_pump
        self.tier.mark_reads(self.reads_per_pump)
        self.latencies_ms.append(lat_ms)
        self._win_lat.append(lat_ms)
        self._win_reads += self.reads_per_pump
        tier_stal = max([stal] + self.tier.staleness())
        self.staleness_samples.append(tier_stal)
        self.staleness_peak = max(self.staleness_peak, tier_stal)
        self.staleness_final = tier_stal
        self._win_stal_max = max(self._win_stal_max, tier_stal)
        if final or now_s - self._win_start >= self.window_s:
            self._close_window(now_s)

    def _close_window(self, now_s: float) -> None:
        lat = self._win_lat
        self.windows.append({
            "start_s": round(self._win_start, 3),
            "end_s": round(now_s, 3),
            "reads": self._win_reads,
            "p50_ms": round(quantile(lat, 0.50), 3),
            "p99_ms": round(quantile(lat, 0.99), 3),
            "reroutes": self.router.reroutes - self._win_reroutes0,
            "staleness_max": self._win_stal_max,
            "breached": bool(lat) and quantile(lat, 0.99) > self.slo_ms,
        })
        self._win_start = now_s
        self._win_lat = []
        self._win_reads = 0
        self._win_reroutes0 = self.router.reroutes
        self._win_stal_max = 0

    def summary(self) -> Dict[str, Any]:
        r = self.router
        wall = (self.latencies_ms and self._t0 is not None)
        span_s = max((self.windows[-1]["end_s"] - self._t0)
                     if self.windows and self._t0 is not None else 0.0,
                     1e-9)
        breached = [w for w in self.windows if w["breached"]]
        return {
            "reads": self.reads,
            "read_qps": round(self.reads / span_s, 1) if wall else 0.0,
            "errors": self.errors,
            "last_error": self.last_error,
            "p50_read_ms": round(quantile(self.latencies_ms, 0.50), 3),
            "p99_read_ms": round(quantile(self.latencies_ms, 0.99), 3),
            "reroutes": r.reroutes,
            "replica_reads": r.replica_reads,
            "owner_reads": r.owner_reads,
            "staleness_peak": self.staleness_peak,
            "staleness_final": self.staleness_final,
            "slo_ms": self.slo_ms,
            "windows": self.windows,
            "windows_breached": len(breached),
            # the read tier passed iff clients saw zero errors AND every
            # read window met its latency SLO
            "ok": self.errors == 0 and not breached,
        }
