"""Chaos schedule DSL: a seeded, replayable fault timetable.

The schedule is pure data — WHAT to break and WHEN, decoupled from HOW
(the :class:`soak.driver.SoakHarness` applies events to a live
cluster). That split is what makes runs replayable: the same schedule
text (or the same ``seed``) produces the identical fault sequence, so a
soak that tripped the audit can be re-run bit-for-bit.

Grammar (one event per line or ``;``-separated; ``#`` comments)::

    at 5s kill 1,9,17                 # cascading SIGKILL: flat subtasks
    at 12s gray 2 delay=50ms for 3s   # slow-worker gray failure
    at 20s leader-loss hold=1s        # rival claims the lease for 1s
    at 30s stall delay=200ms for 2s   # checkpoint-storage + spill-
                                      # segment write stall
    at 35s backlog for 4s             # suppress checkpoint completion:
                                      # replay backlog grows past the
                                      # device ring into the spill tiers
    at 40s nondet                     # unlogged value perturbation
                                      # (audit bait — MUST fail the run)
    at 45s replica-kill 1             # kill serve replica 1: reads must
                                      # re-route to the owner, no errors
    at 50s rescale 4                  # live re-cut: keyed vertices to
                                      # parallelism 4 at the next fence
    at 55s load-spike 4x for 3s       # offered-rate multiplier over a
                                      # window — the autoscaler's cue

Durations accept ``ms``/``s`` suffixes (bare numbers are seconds).
``ChaosSchedule.seeded`` generates a schedule from a seed via a seeded
``np.random.RandomState`` — deterministic by construction, covering
every requested fault kind at least once.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: every fault kind the harness knows how to apply. ``nondet`` is the
#: audit bait: an unlogged perturbation that every structural check
#: passes and only the epoch-digest diff catches. ``backlog`` starves
#: checkpoint completion so truncation stops and the replay backlog
#: spills past the device ring into the host/disk tiers
#: (storage/tiered.py) — the long-backlog disk-replay scenario.
#: ``replica-kill`` targets the READ tier, not the job: a serve replica
#: (runtime/serve.py) drops dead mid-run; the router must re-route its
#: key groups to the owner with zero client-visible errors, and the
#: replica revives (staleness spike, then recovery) at the next seal.
#: Optional target = replica index (defaults to replica 0).
#: ``rescale`` re-cuts the JOB under live traffic: at the next
#: completed checkpoint fence the keyed vertices re-partition to the
#: target parallelism (``ClusterRunner.rescale_live``) — exactly-once
#: must hold across the handoff and the read tier re-homes. Target =
#: the new keyed parallelism (exactly one positive integer).
#: ``load-spike`` is not a fault at all but a LOAD event: the offered
#: rate multiplies by ``factor`` for ``duration_s`` — the token bucket
#: paces chunks closer together while record CONTENTS stay identical
#: (logical time), so the fault-free control twin sees the exact same
#: spike and the byte-exact audit diff keeps gating. It is the cue the
#: autoscaler (clonos_tpu/autoscale/) is designed to answer.
FAULT_KINDS = ("kill", "gray", "leader-loss", "stall", "nondet",
               "backlog", "replica-kill", "rescale", "load-spike")


def _dur(tok: str) -> float:
    """Parse a duration token: ``200ms`` / ``1.5s`` / ``3`` (seconds)."""
    tok = tok.strip()
    try:
        if tok.endswith("ms"):
            return float(tok[:-2]) / 1e3
        if tok.endswith("s"):
            return float(tok[:-1])
        return float(tok)
    except ValueError:
        raise ValueError(f"bad duration {tok!r} (want e.g. 200ms, 1.5s)")


def _fmt_dur(s: float) -> str:
    if s < 1.0:
        return f"{s * 1e3:g}ms"
    return f"{s:g}s"


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One fault at one instant of the soak clock (seconds from the
    start of the paced phase)."""

    at_s: float
    kind: str
    #: flat subtask ids (kill: the cascade; gray: the slow worker)
    targets: Tuple[int, ...] = ()
    #: gray: injected heartbeat/transport delay; stall: per-write delay
    delay_s: float = 0.0
    #: gray/stall: how long the degradation stays active
    duration_s: float = 0.0
    #: leader-loss: how long the rival holds the stolen lease
    hold_s: float = 0.0
    #: load-spike: offered-rate multiplier over the window
    factor: float = 0.0

    def to_text(self) -> str:
        parts = [f"at {_fmt_dur(self.at_s)}", self.kind]
        if self.targets:
            parts.append(",".join(str(t) for t in self.targets))
        if self.kind == "load-spike":
            parts.append(f"{self.factor:g}x")
        if self.kind in ("gray", "stall"):
            parts.append(f"delay={_fmt_dur(self.delay_s)}")
            parts.append(f"for {_fmt_dur(self.duration_s)}")
        if self.kind in ("backlog", "load-spike"):
            parts.append(f"for {_fmt_dur(self.duration_s)}")
        if self.kind == "leader-loss" and self.hold_s:
            parts.append(f"hold={_fmt_dur(self.hold_s)}")
        return " ".join(parts)


def _parse_event(line: str) -> ChaosEvent:
    toks = line.split()
    if len(toks) < 3 or toks[0] != "at":
        raise ValueError(f"chaos event {line!r}: want 'at <time> <kind> "
                         f"[args]'")
    at_s = _dur(toks[1])
    kind = toks[2]
    if kind not in FAULT_KINDS:
        raise ValueError(f"chaos event {line!r}: unknown kind {kind!r} "
                         f"(one of {', '.join(FAULT_KINDS)})")
    targets: Tuple[int, ...] = ()
    delay_s = 0.0
    duration_s = 0.0
    hold_s = 0.0
    factor = 0.0
    i = 3
    if kind == "load-spike":
        if i >= len(toks):
            raise ValueError(f"chaos event {line!r}: load-spike needs "
                             f"a rate multiplier (e.g. 4x)")
        tok = toks[i]
        try:
            factor = float(tok[:-1] if tok.endswith("x") else tok)
        except ValueError:
            raise ValueError(f"chaos event {line!r}: bad multiplier "
                             f"{tok!r} (want e.g. 4x)")
        if factor <= 0:
            raise ValueError(f"chaos event {line!r}: multiplier must "
                             f"be positive")
        i += 1
    elif kind == "rescale":
        if i >= len(toks):
            raise ValueError(f"chaos event {line!r}: rescale needs the "
                             f"new keyed parallelism")
        try:
            targets = (int(toks[i]),)
        except ValueError:
            raise ValueError(f"chaos event {line!r}: bad parallelism "
                             f"{toks[i]!r}")
        if targets[0] < 1:
            raise ValueError(f"chaos event {line!r}: parallelism must "
                             f"be positive")
        i += 1
    elif kind in ("kill", "gray"):
        if i >= len(toks):
            raise ValueError(f"chaos event {line!r}: {kind} needs "
                             f"target subtask(s)")
        try:
            targets = tuple(int(t) for t in toks[i].split(",") if t)
        except ValueError:
            raise ValueError(f"chaos event {line!r}: bad targets "
                             f"{toks[i]!r}")
        if not targets:
            raise ValueError(f"chaos event {line!r}: empty target list")
        i += 1
    elif kind == "replica-kill" and i < len(toks) \
            and not toks[i].startswith(("delay=", "hold=")) \
            and toks[i] != "for":
        # optional replica index (defaults to replica 0 in the harness)
        try:
            targets = tuple(int(t) for t in toks[i].split(",") if t)
        except ValueError:
            raise ValueError(f"chaos event {line!r}: bad replica index "
                             f"{toks[i]!r}")
        i += 1
    while i < len(toks):
        tok = toks[i]
        if tok.startswith("delay="):
            delay_s = _dur(tok[len("delay="):])
        elif tok.startswith("hold="):
            hold_s = _dur(tok[len("hold="):])
        elif tok == "for":
            i += 1
            if i >= len(toks):
                raise ValueError(f"chaos event {line!r}: 'for' needs a "
                                 f"duration")
            duration_s = _dur(toks[i])
        else:
            raise ValueError(f"chaos event {line!r}: unexpected token "
                             f"{tok!r}")
        i += 1
    if kind in ("gray", "stall") and (delay_s <= 0 or duration_s <= 0):
        raise ValueError(f"chaos event {line!r}: {kind} needs "
                         f"delay=<d> for <d>")
    if kind in ("backlog", "load-spike") and duration_s <= 0:
        raise ValueError(f"chaos event {line!r}: {kind} needs "
                         f"for <duration>")
    if kind == "gray" and len(targets) != 1:
        raise ValueError(f"chaos event {line!r}: gray takes exactly one "
                         f"target")
    return ChaosEvent(at_s=at_s, kind=kind, targets=targets,
                      delay_s=delay_s, duration_s=duration_s,
                      hold_s=hold_s, factor=factor)


def event_from_dict(d: dict) -> ChaosEvent:
    """Build an event from its dict form (the verify counterexample
    trace records' ``chaos`` field; inverse of the bridge's record
    writer)."""
    kind = d.get("kind")
    if kind not in FAULT_KINDS:
        raise ValueError(f"chaos record {d!r}: unknown kind {kind!r}")
    return ChaosEvent(
        at_s=float(d.get("at_s", 0.0)), kind=kind,
        targets=tuple(int(t) for t in d.get("targets") or ()),
        delay_s=float(d.get("delay_s", 0.0)),
        duration_s=float(d.get("duration_s", 0.0)),
        hold_s=float(d.get("hold_s", 0.0)),
        factor=float(d.get("factor", 0.0)))


def read_trace_schedule(path: str) -> "ChaosSchedule":
    """Import a verify counterexample trace (JSONL, one record per
    model-trace step) as a schedule: the records whose ``chaos`` field
    is set are the steps with a live-fault analog. Tail-tolerant like
    every other append log (utils/jsonl.py)."""
    from clonos_tpu.utils.jsonl import read_jsonl
    events = []
    for rec in read_jsonl(path, label=path):
        ev = rec.get("chaos") if isinstance(rec, dict) else None
        if ev:
            events.append(event_from_dict(ev))
    return ChaosSchedule(events)


def parse_schedule(text: str) -> "ChaosSchedule":
    """Parse DSL text into a schedule (events sorted by fire time)."""
    events = []
    for raw in text.replace(";", "\n").splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        events.append(_parse_event(line))
    return ChaosSchedule(events)


class ChaosSchedule:
    """An ordered fault timetable. Immutable once built; the driver
    keeps its own cursor, so one schedule can drive many runs."""

    def __init__(self, events: Sequence[ChaosEvent]):
        self.events: List[ChaosEvent] = sorted(events,
                                               key=lambda e: e.at_s)

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    def __eq__(self, other) -> bool:
        return (isinstance(other, ChaosSchedule)
                and self.events == other.events)

    def kinds(self) -> List[str]:
        return [e.kind for e in self.events]

    def to_text(self) -> str:
        return "\n".join(e.to_text() for e in self.events)

    @classmethod
    def seeded(cls, seed: int, duration_s: float,
               targets: Sequence[int],
               kinds: Sequence[str] = ("kill", "gray", "leader-loss"),
               n_events: Optional[int] = None,
               cascade: int = 3) -> "ChaosSchedule":
        """Generate a replayable schedule: same ``seed`` (and the same
        other arguments) → the same fault sequence, byte for byte.

        Fire times land in the middle ``[0.2, 0.85] * duration_s`` band
        so the paced warm-in and the final seal/audit window stay
        fault-free. Every requested kind appears at least once
        (``n_events`` defaults to ``len(kinds)``); extra events draw
        kinds uniformly. Kill cascades pick ``cascade`` distinct flat
        subtasks from ``targets`` — the config4 "connected failures"
        pattern when the caller passes one subtask per vertex class.
        """
        for k in kinds:
            if k not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {k!r}")
        if not targets and any(k in ("kill", "gray") for k in kinds):
            raise ValueError("kill/gray faults need candidate targets")
        n = max(n_events or len(kinds), len(kinds))
        rng = np.random.RandomState(seed)
        times = np.sort(rng.uniform(0.2 * duration_s, 0.85 * duration_s,
                                    size=n))
        # Coverage first, then uniform draws — order shuffled so the
        # guaranteed instances are not always the earliest events.
        picked = list(kinds) + [kinds[int(rng.randint(len(kinds)))]
                                for _ in range(n - len(kinds))]
        rng.shuffle(picked)
        events = []
        for at_s, kind in zip(times, picked):
            # ms precision: to_text() must round-trip byte-for-byte
            at_s = round(float(at_s), 3)
            if kind == "kill":
                k = min(cascade, len(targets))
                tg = tuple(int(t) for t in sorted(
                    rng.choice(np.asarray(targets), size=k,
                               replace=False)))
                events.append(ChaosEvent(float(at_s), "kill", targets=tg))
            elif kind == "gray":
                tg = (int(np.asarray(targets)[
                    int(rng.randint(len(targets)))]),)
                events.append(ChaosEvent(
                    float(at_s), "gray", targets=tg,
                    delay_s=round(float(rng.uniform(0.02, 0.08)), 3),
                    duration_s=round(float(rng.uniform(2.0, 4.0)), 2)))
            elif kind == "leader-loss":
                events.append(ChaosEvent(
                    float(at_s), "leader-loss",
                    hold_s=round(float(rng.uniform(0.4, 0.9)), 2)))
            elif kind == "stall":
                events.append(ChaosEvent(
                    float(at_s), "stall",
                    delay_s=round(float(rng.uniform(0.1, 0.3)), 3),
                    duration_s=round(float(rng.uniform(1.0, 3.0)), 2)))
            elif kind == "backlog":
                events.append(ChaosEvent(
                    float(at_s), "backlog",
                    duration_s=round(float(rng.uniform(1.0, 3.0)), 2)))
            elif kind == "replica-kill":
                events.append(ChaosEvent(float(at_s), "replica-kill"))
            elif kind == "rescale":
                # N±k under live traffic: scale the keyed vertices up
                # or down; the harness picks the fence.
                events.append(ChaosEvent(
                    float(at_s), "rescale",
                    targets=(int((2, 4)[int(rng.randint(2))]),)))
            elif kind == "load-spike":
                events.append(ChaosEvent(
                    float(at_s), "load-spike",
                    factor=float((2.0, 4.0)[int(rng.randint(2))]),
                    duration_s=round(float(rng.uniform(1.0, 3.0)), 2)))
            else:                       # nondet
                events.append(ChaosEvent(float(at_s), "nondet"))
        return cls(events)
