"""Soak & chaos harness: open-loop SLO tracking with exactly-once
asserted under injected failure.

Every bench number elsewhere in this repo is a closed-loop burst; this
package is the open-loop counterpart — a fixed-rate load driver
(:mod:`soak.driver`) paced by a token bucket whose latency samples are
measured from *intended*-send time (coordinated-omission-corrected), a
windowed SLO engine (:mod:`soak.slo`), and a seeded, replayable chaos
schedule (:mod:`soak.chaos`) injecting cascading kills, slow-worker
gray failures, leader-lease loss, and checkpoint-storage write stalls —
with the epoch audit ledger re-validated against a fault-free control
chain after every injected event. The Clonos reference ships a
Jepsen-style harness for exactly this reason: exactly-once claims only
mean something under repeated, overlapping, adversarial failures.
"""

from .chaos import (ChaosEvent, ChaosSchedule,  # noqa: F401
                    parse_schedule)
from .slo import (SLOSpec, SLOTracker, Window,  # noqa: F401
                  corrected_closed_loop, quantile)
from .driver import (SoakConfig, SoakDriver, SoakHarness,  # noqa: F401
                     build_soak_fixture, default_kill_targets,
                     next_autoscale_artifact_path,
                     next_rescale_artifact_path,
                     next_serve_artifact_path, next_soak_artifact_path)
from .serveload import ServeLoad  # noqa: F401

__all__ = ["ChaosEvent", "ChaosSchedule", "parse_schedule",
           "SLOSpec", "SLOTracker", "Window", "quantile",
           "corrected_closed_loop",
           "SoakConfig", "SoakDriver", "SoakHarness",
           "build_soak_fixture", "default_kill_targets",
           "next_soak_artifact_path", "next_serve_artifact_path",
           "next_rescale_artifact_path",
           "next_autoscale_artifact_path", "ServeLoad"]
